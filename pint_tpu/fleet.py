"""Fleet fitting: N pulsars through a *bounded* set of compiled programs.

BASELINE.json's fifth config ("Batched many-pulsar WLS: vmap over the
full NANOGrav validation set") and ROADMAP item 1 describe the serving
shape this module implements: a pulsar-timing array is a few hundred
pulsars with *ragged* TOA counts and *heterogeneous* free-parameter
sets, and the bench trajectory says compile time — not steady state —
is the dominant wall-clock tax.  A naive per-pulsar jit pays one XLA
compile per pulsar; a naive single vmap pays one compile per distinct
shape, which for ragged data is the same thing.  The fleet answer
(Vela.jl's per-pulsar compiled kernels amortized across an array,
arXiv:2412.15858):

* **Bucketing** — pulsars are grouped by model *structure* (component
  set, params-pytree treedef, track mode), then their TOA counts are
  split into a small number of geometric classes
  (:func:`geometric_bucket_edges`; ``max_buckets`` per structure group,
  growth factor widened until the budget holds).  Every pulsar in a
  bucket is padded to the bucket's ``(n_toa, n_param)`` shape.  The
  bucket count IS the compile budget: one compiled program per bucket,
  enforced by the ``fleet_fit`` dispatch contract and
  ``tests/test_fleet.py``.
* **Mask-weighted padding** — padded TOA rows carry
  ``DOWNWEIGHT_ERROR_US`` *and* an explicit row mask that zeroes their
  residual and design-matrix rows, so they contribute exactly zero to
  chi2 and the normal equations; padded parameter slots carry a zero
  column, which the shared eigencutoff (`fit_wls_svd`/`fit_wls_eigh`)
  drops, so their step is exactly zero.
* **Heterogeneous free params in one program** — the fit vector maps to
  the params pytree through per-pulsar *data* (an integer slot array +
  mask) instead of trace-time names, so pulsars fitting different
  parameter subsets of the same model structure share one compiled
  program (`_build_bucket_fit`).
* **Vmapped in-bucket fits** — within a bucket the whole guarded
  Gauss-Newton fit (the `wls_solve` kernels and the PR 3 convergence
  sentinel via :func:`pint_tpu.fitter.sentinel_advance`) is vmapped over
  the pulsar axis; each pulsar carries its own in-graph
  :class:`~pint_tpu.fitter.FitStatus`, so one oscillating pulsar cannot
  mark its bucket-mates MAXITER.  An optional batch-axis
  ``NamedSharding`` (``mesh=``, see :func:`pint_tpu.parallel.
  make_batch_mesh`) spreads the pulsar axis across devices.
* **Preemption-tolerant execution** — chunks of the (bucket-ordered)
  pulsar list run through :func:`pint_tpu.runtime.run_checkpointed_scan`:
  CRC-verified checkpoints + a fleet sidecar (per-pulsar x/status), a
  SIGTERM mid-fleet flushes and raises ``ScanInterrupted``, resume is
  bit-identical, and a chunk whose dispatch raises or returns
  non-finite chi2 is retried then requeued onto the eager
  single-pulsar path.  Pulsars whose *in-graph* sentinel ends
  DIVERGED/NONFINITE are individually requeued onto the eager fitter
  (PR 3's fused->eager->LM chain), with rung provenance in the result —
  a fleet run returns a per-pulsar summary table, never an
  all-or-nothing crash.

Numerical honesty: correlated-noise (GLS) pulsars are routed to the
eager lane *by design* — their normal matrices carry physical structure
below the accelerator Gram noise (see ``GLSFitter._fused_ok``), so a
vmapped device solve there would be garbage.  They still ride the same
chunked/checkpointed scan and appear in the same result table.

The batched path reports values + per-pulsar chi2/status, not
per-parameter uncertainties (those need the host-exact final solve —
refit the pulsars you need covariances for with the single-pulsar
fitters, or call :meth:`FleetFitter.apply` and fit once more).
"""

from __future__ import annotations

import copy
import math
import warnings
import zlib
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import profiling, runtime, telemetry
from pint_tpu.exceptions import ConvergenceFailure, PintTpuWarning
from pint_tpu.fitter import (_RUNNING, FitStatus, FitSummary, GLSFitter,
                             WLSFitter, _default_wls_kernel,
                             sentinel_advance, wls_solve)
from pint_tpu.lint.contracts import dispatch_contract
from pint_tpu.logging import child as _logchild
from pint_tpu.models.timing_model import TimingModel, pv
from pint_tpu.residuals import Residuals, raw_phase_resids
from pint_tpu.toabatch import pad_batch_to

_log = _logchild("fleet")

__all__ = ["FleetFitter", "FleetEntry", "FleetResult",
           "FleetRequeueWarning", "geometric_bucket_edges"]


class FleetRequeueWarning(PintTpuWarning):
    """A pulsar's in-bucket fit ended DIVERGED/NONFINITE (or its chunk's
    dispatch failed) and it was requeued onto the eager single-pulsar
    path."""


#: rung codes stored in the (integer) fleet sidecar; reverse-mapped for
#: the result table.  "fleet" = the vmapped bucket program; everything
#: else is the eager lane / requeue path reporting the winning rung of
#: the PR 3 degradation machinery.
_RUNGS = ("fleet", "eager", "lm", "downhill", "fused", "powell", "failed")
_RUNG_CODE = {r: i for i, r in enumerate(_RUNGS)}


def _rung_code(rung: str) -> int:
    return _RUNG_CODE.get(rung, _RUNG_CODE["eager"])


# --- bucketing ----------------------------------------------------------------

def geometric_bucket_edges(sizes: Sequence[int], growth: float = 2.0,
                           max_buckets: int = 4) -> Dict[int, int]:
    """Map each size to a geometric class id such that at most
    ``max_buckets`` distinct classes exist.  Classes are
    ``ceil(log_g(size / min_size))``; the growth factor is widened (by
    1.5x steps) until the class count fits the budget, so the budget is
    a hard bound, not a hint.  The caller pads each class to its own
    maximum member size (tighter than the analytic edge)."""
    uniq = sorted(set(int(s) for s in sizes))
    if not uniq:
        return {}
    if max_buckets < 1:
        raise ValueError("max_buckets must be >= 1")
    base, g = uniq[0], float(growth)
    if g <= 1.0:
        raise ValueError("growth must be > 1")
    while True:
        classes = {s: (0 if s <= base else
                       int(math.ceil(math.log(s / base)
                                     / math.log(g) - 1e-9)))
                   for s in uniq}
        if len(set(classes.values())) <= max_buckets:
            return classes
        g *= 1.5


class _Bucket(NamedTuple):
    """One padded-shape group of pulsars (or the eager lane)."""

    skey_idx: int          #: structure-group index (-1 for eager lane)
    n_toa: int             #: padded TOA count (0 for eager lane)
    n_param: int           #: padded free-param count (0 for eager lane)
    members: tuple         #: pulsar indices, unpadded
    slots: tuple           #: pulsar index per slot (padded to cs multiple)
    eager: bool


class _Pulsar(NamedTuple):
    """Prepared per-pulsar record (host side)."""

    name: str
    index: int
    model: TimingModel
    toas: object
    resid: Residuals
    names: tuple           #: fleet-fittable free params, model order
    dof: int
    eager: bool


class FleetEntry(NamedTuple):
    """One pulsar's row of a :class:`FleetResult`."""

    name: str
    index: int
    chi2: float
    dof: int
    status: FitStatus
    rung: str              #: "fleet" or the eager-lane winning rung
    iterations: int
    x: np.ndarray          #: fitted offsets (device units), len(fit_names)
    fit_names: tuple

    @property
    def summary(self) -> FitSummary:
        return FitSummary(self.chi2, self.dof, self.iterations,
                          self.status in (FitStatus.CONVERGED,
                                          FitStatus.MAXITER),
                          status=self.status, rung=self.rung,
                          guard_trips=None)


class FleetResult(NamedTuple):
    """Per-pulsar summary table of one fleet fit — never an
    all-or-nothing result (the scan summary carries the chunk-level
    retry/reroute provenance; each entry its pulsar's terminal
    :class:`~pint_tpu.fitter.FitStatus` and winning rung)."""

    entries: tuple          #: tuple[FleetEntry, ...] in pulsar order
    scan: runtime.ScanSummary
    n_buckets: int
    n_programs: int

    @property
    def summaries(self) -> List[FitSummary]:
        return [e.summary for e in self.entries]

    @property
    def statuses(self) -> List[FitStatus]:
        return [e.status for e in self.entries]

    @property
    def chi2(self) -> np.ndarray:
        return np.asarray([e.chi2 for e in self.entries])

    @property
    def ok(self) -> bool:
        return self.scan.failures == 0 and all(
            e.status in (FitStatus.CONVERGED, FitStatus.MAXITER)
            for e in self.entries)

    def table(self) -> str:
        lines = [f"{'PSR':14s} {'NTOA-DOF':>9s} {'CHI2':>12s} "
                 f"{'STATUS':>10s} {'RUNG':>9s} {'ITER':>5s}"]
        for e in self.entries:
            lines.append(
                f"{e.name:14s} {e.dof:9d} {e.chi2:12.4f} "
                f"{e.status.name:>10s} {e.rung:>9s} {e.iterations:5d}")
        return "\n".join(lines)


# --- the in-bucket compiled program -------------------------------------------

def _build_bucket_fit(model: TimingModel, track_mode: str,
                      delta_keys: Tuple[str, ...], n_param: int,
                      include_offset: bool, maxiter: int, tol_chi2: float,
                      kernel, threshold, diverge_streak: int,
                      stall_iters: int):
    """ONE jitted, vmapped program fitting every pulsar of a bucket:
    ``prog(p, batch, slots, pmask, rowmask) -> (B, n_param + 5)`` rows of
    ``[x..., chi2, status, iterations, best_chi2, n_bad]``.

    The fit vector maps into the params pytree through *data*: ``slots``
    (int32, per-pulsar) names which scalar delta leaf each fit position
    moves, ``pmask`` zeroes padded positions (their column is exactly
    zero, so the shared eigencutoff drops the direction and their step
    is 0), and ``rowmask`` zeroes padded TOA rows out of the residual
    and design matrix (exact mask-weighted padding, not just
    downweighting).  Each pulsar runs ``maxiter`` Gauss-Newton steps in
    a fixed-trip-count ``lax.scan`` carrying the PR 3 convergence
    sentinel (:func:`pint_tpu.fitter.sentinel_advance`); finished
    pulsars freeze (their carry stops updating) so a stalling
    bucket-mate costs idle FLOPs, not correctness.  The fixed trip
    count is deliberate: like the proven vmapped grid-fit program
    (`gridutils.build_grid_fit_fn`) it avoids the XLA:CPU while_loop
    miscompilation documented on `Fitter._fused_ok`, and the scan (vs
    unrolling the steps) keeps ONE compiled step body — measured
    92 s -> 25 s compile on the two-bucket audit shapes, numerics
    bit-identical."""
    calc = model.calc
    keys = tuple(delta_keys)

    def apply_x(p, x, slots, pmask):
        # delta leaves are the *offsets* from the pytree's reference
        # values; scatter-add so positions masked off (pmask 0, slot 0)
        # contribute exactly nothing
        d = jnp.stack([jnp.asarray(p["delta"][k], jnp.float64)
                       for k in keys])
        d = d.at[slots].add(x * pmask)
        delta = dict(p["delta"])
        for j, k in enumerate(keys):
            delta[k] = d[j]
        out = dict(p)
        out["delta"] = delta
        return out

    def resid_sec(x, p, b, slots, pmask):
        p2 = apply_x(p, x, slots, pmask)
        r = raw_phase_resids(calc, p2, b, track_mode,
                             subtract_mean=False, use_weights=False)
        return r / pv(p2, "F0")

    def fit_one(p, b, slots, pmask, rowmask):
        sigma = model.scaled_toa_uncertainty(p, b) * 1e-6
        sigma = jnp.where(rowmask > 0, sigma, 1.0)
        offc = rowmask if include_offset else None

        def step(x):
            # primal + JVPs share one pass (same linearize idiom as the
            # split assembly's nonlinear block)
            r, jvp = jax.linearize(
                lambda xx: resid_sec(xx, p, b, slots, pmask), x)
            M = -jax.vmap(jvp, out_axes=1)(jnp.eye(n_param))
            r = r * rowmask
            M = M * rowmask[:, None]
            if offc is not None:
                M = jnp.concatenate([M, -offc[:, None]], axis=1)
            return wls_solve(jnp, r, M, sigma, offc, kernel, n_param,
                             threshold)

        def body(carry, _):
            x, prev, best_x, best_chi2, inc, stall, status, iters = carry
            out = step(x)
            chi2 = out["chi2"]
            run = status == _RUNNING
            bx, bc, ninc, nstall, nstatus = sentinel_advance(
                x, chi2, prev, best_x, best_chi2, inc, stall,
                tol_chi2, diverge_streak, stall_iters)
            # freeze finished pulsars: the scan runs the full trip count
            # for the whole bucket, so a converged carry must stop
            # moving (the vmapped analogue of the fused loop's early
            # exit — idle FLOPs, never corrupted state)
            best_x = jnp.where(run, bx, best_x)
            best_chi2 = jnp.where(run, bc, best_chi2)
            inc = jnp.where(run, ninc, inc)
            stall = jnp.where(run, nstall, stall)
            status = jnp.where(run, nstatus, status)
            x = jnp.where(run, x + out["dx"], x)
            prev = jnp.where(run, chi2, prev)
            iters = iters + run.astype(jnp.int32)
            return (x, prev, best_x, best_chi2, inc, stall, status,
                    iters), None

        carry = (jnp.zeros(n_param), jnp.float64(jnp.inf),
                 jnp.zeros(n_param), jnp.float64(jnp.inf), jnp.int32(0),
                 jnp.int32(0), jnp.int32(_RUNNING), jnp.int32(0))
        carry, _ = jax.lax.scan(body, carry, None, length=maxiter)
        x, _, best_x, best_chi2, _, _, status, iters = carry
        status = jnp.where(status == _RUNNING,
                           jnp.int32(FitStatus.MAXITER), status)
        # failed fits hand back the best finite iterate, like the fused
        # sentinel — x then feeds the eager requeue as a diagnostic
        ok = jnp.logical_or(status == FitStatus.CONVERGED,
                            status == FitStatus.MAXITER)
        x = jnp.where(ok, x, best_x)
        final = step(x)
        chi2 = jnp.where(ok, final["chi2"], best_chi2)
        tail = jnp.stack([chi2, status.astype(jnp.float64),
                          iters.astype(jnp.float64), best_chi2,
                          jnp.asarray(final["n_bad"], jnp.float64)])
        return jnp.concatenate([x, tail])

    return jax.jit(jax.vmap(fit_one))


def _build_bucket_resid(model: TimingModel, track_mode: str,
                        delta_keys: Tuple[str, ...], n_param: int,
                        include_offset: bool):
    """ONE jitted, vmapped residual evaluator for a bucket:
    ``prog(p, batch, x, slots, pmask, rowmask) -> (B, n_toa)`` seconds,
    padded rows exactly zero.  The PTA workload's correlation stage
    needs post-fit residuals for EVERY pulsar of a fleet — evaluating
    them through per-pulsar ``Residuals`` objects would pay one XLA
    compile per pulsar, which is exactly the tax the bucket machinery
    exists to avoid, so this shares the fit program's slot/pmask
    apply-x mapping and compiles once per bucket.  ``include_offset``
    mirrors the fit's implicit phase-offset column by subtracting the
    (mask-)weighted mean."""
    calc = model.calc
    keys = tuple(delta_keys)

    def apply_x(p, x, slots, pmask):
        d = jnp.stack([jnp.asarray(p["delta"][k], jnp.float64)
                       for k in keys])
        d = d.at[slots].add(x * pmask)
        delta = dict(p["delta"])
        for j, k in enumerate(keys):
            delta[k] = d[j]
        out = dict(p)
        out["delta"] = delta
        return out

    def resid_one(p, b, x, slots, pmask, rowmask):
        p2 = apply_x(p, x, slots, pmask)
        r = raw_phase_resids(calc, p2, b, track_mode,
                             subtract_mean=False, use_weights=False)
        r = r / pv(p2, "F0")
        if include_offset:
            sigma = model.scaled_toa_uncertainty(p2, b) * 1e-6
            w = rowmask / (sigma * sigma)
            r = r - jnp.sum(r * w) / jnp.maximum(jnp.sum(w), 1e-300)
        return r * rowmask

    return jax.jit(jax.vmap(resid_one))


#: columns appended after the x block in a bucket program's output row
_TAIL = 5
_COL_CHI2, _COL_STATUS, _COL_ITERS, _COL_BEST, _COL_NBAD = range(5)


def _pad_pdict(resid: Residuals, n_toa: int) -> dict:
    """Pad a pulsar's params-pytree per-TOA mask leaves to ``n_toa``
    rows (const/delta leaves are per-parameter, not per-TOA, and pass
    through).  Shared by the fleet chunk staging and the serve daemon's
    per-job staging — both stack these into bucket-program inputs."""
    p = resid.pdict
    npad = n_toa - resid.batch.ntoas
    mask = {k: (np.concatenate([np.asarray(v, np.float64),
                                np.zeros(npad)])
                if npad else np.asarray(v, np.float64))
            for k, v in p["mask"].items()}
    return {"const": p["const"], "delta": p["delta"], "mask": mask}


class _EagerOut(NamedTuple):
    chi2: float
    x: np.ndarray
    status: FitStatus
    iterations: int
    rung: str


# --- the fitter ---------------------------------------------------------------

class FleetFitter:
    """Fit N pulsars (ragged TOA counts, heterogeneous free-param sets)
    through a bounded number of compiled programs.

    ``pulsars``: sequence of ``(model, toas)`` or ``(name, model, toas)``
    tuples.  Pulsars sharing a model *structure* (same component set /
    params-pytree layout / track mode) share compiled programs; their
    ragged TOA counts are split into at most ``max_buckets`` geometric
    classes per structure group and padded (see module docstring for the
    exact-masking semantics).  Correlated-noise (GLS) models route to
    the eager single-pulsar lane — see the module docstring for why.

    ``chunk_size`` pulsars dispatch per compiled call (the vmap width —
    part of the program shape); ``mesh`` (a 1-D ``("batch",)`` mesh,
    e.g. :func:`pint_tpu.parallel.make_batch_mesh`) shards the pulsar
    axis of every chunk across devices with a ``NamedSharding``.

    ``fit()`` is side-effect free (models untouched) and idempotent:
    pulsar data is staged to device once and the compiled programs are
    cached, so a steady-state fleet fit is 1 dispatch + 1 fetch per
    chunk — the ``fleet_fit`` dispatch contract.  Use :meth:`apply` to
    write a result's offsets back into the models."""

    def __init__(self, pulsars, *, maxiter: int = 8,
                 tol_chi2: float = 1e-10,
                 threshold: Optional[float] = None, kernel=None,
                 chunk_size: int = 8, growth: float = 2.0,
                 max_buckets: int = 4, mesh=None,
                 track_mode: Optional[str] = None,
                 policy: Optional[str] = None,
                 diverge_streak: Optional[int] = None,
                 stall_iters: Optional[int] = None,
                 eager_maxiter: int = 16, requeue: bool = True):
        from pint_tpu.fitter import FUSED_DIVERGE_STREAK, FUSED_STALL_ITERS

        self.maxiter = int(maxiter)
        self.tol_chi2 = float(tol_chi2)
        self.threshold = threshold
        self.kernel = kernel
        self.chunk_size = int(chunk_size)
        self.growth = float(growth)
        self.max_buckets = int(max_buckets)
        self.policy = policy
        self.diverge_streak = FUSED_DIVERGE_STREAK \
            if diverge_streak is None else int(diverge_streak)
        self.stall_iters = FUSED_STALL_ITERS \
            if stall_iters is None else int(stall_iters)
        self.eager_maxiter = int(eager_maxiter)
        self.requeue = bool(requeue)
        if self.chunk_size < 1:
            raise ValueError("chunk_size must be >= 1")
        self._sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            nshard = int(np.prod(mesh.devices.shape))
            if self.chunk_size % nshard:
                raise ValueError(
                    f"chunk_size {self.chunk_size} does not split over "
                    f"the mesh's {nshard} device(s)")
            self._sharding = NamedSharding(
                mesh, PartitionSpec(mesh.axis_names[0]))

        self._pulsars: List[_Pulsar] = []
        for i, spec in enumerate(pulsars):
            if len(spec) == 3:
                name, model, toas = spec
            else:
                model, toas = spec
                name = getattr(getattr(model, "PSR", None), "value",
                               None) or f"PSR{i:04d}"
            resid = Residuals(toas, model, track_mode=track_mode,
                              policy=policy)
            names = self._fleet_fit_params(model, resid)
            self._pulsars.append(_Pulsar(
                str(name), i, model, toas, resid, tuple(names),
                resid.dof, model.has_correlated_errors))
        if not self._pulsars:
            raise ValueError("FleetFitter needs at least one pulsar")
        self._plan = None
        self._programs: dict = {}
        self._resid_programs: dict = {}
        self._args_cache: dict = {}

    # -- preparation -----------------------------------------------------------

    @staticmethod
    def _fleet_fit_params(model: TimingModel, resid: Residuals):
        """Free params the batched linear step can move: scalar-delta,
        non-noise parameters (same exclusion as ``Fitter.fit_params``;
        noise params need the ML path)."""
        noise = {type(c).__name__ for c in model.noise_components}
        delta = resid.pdict["delta"]
        out = []
        for n in model.free_params:
            if model.param_component(n) in noise:
                continue
            if n not in delta or np.ndim(delta[n]) != 0:
                continue
            out.append(n)
        return out

    @staticmethod
    def _structure_key(pu: _Pulsar) -> tuple:
        """Pulsars with equal keys share compiled programs: same pytree
        layout, component set, track mode, planet set and offset
        handling.  Per-TOA array shapes are NOT part of the key (they
        are padded per bucket); const-leaf shapes are (they must stack),
        so an exotic per-pulsar const leaf degrades to more buckets,
        never to a wrong stack."""
        p = pu.resid.pdict
        const_shapes = tuple(sorted(
            (k, tuple(np.shape(v))) for k, v in p["const"].items()))
        return (str(jax.tree_util.tree_structure(
                    {"const": p["const"], "delta": p["delta"],
                     "mask": p["mask"]})),
                tuple(pu.model.components.keys()),
                pu.resid.track_mode,
                tuple(sorted(pu.resid.batch.obs_planet_pos_ls)),
                "PhaseOffset" not in pu.model.components,
                const_shapes)

    def _ensure_plan(self):
        if self._plan is not None:
            return self._plan
        cs = self.chunk_size
        skeys: Dict[tuple, int] = {}
        groups: Dict[int, List[_Pulsar]] = {}
        rep: Dict[int, _Pulsar] = {}
        eager_members: List[int] = []
        for pu in self._pulsars:
            if pu.eager:
                eager_members.append(pu.index)
                continue
            k = self._structure_key(pu)
            si = skeys.setdefault(k, len(skeys))
            groups.setdefault(si, []).append(pu)
            rep.setdefault(si, pu)
        buckets: List[_Bucket] = []
        for si in sorted(groups):
            members = groups[si]
            classes = geometric_bucket_edges(
                [pu.resid.batch.ntoas for pu in members],
                self.growth, self.max_buckets)
            by_class: Dict[int, List[_Pulsar]] = {}
            for pu in members:
                by_class.setdefault(
                    classes[pu.resid.batch.ntoas], []).append(pu)
            for ci in sorted(by_class):
                mem = by_class[ci]
                idx = tuple(pu.index for pu in mem)
                pad = (-len(idx)) % cs
                slots = idx + (idx[-1],) * pad
                buckets.append(_Bucket(
                    si,
                    max(pu.resid.batch.ntoas for pu in mem),
                    max(len(pu.names) for pu in mem),
                    idx, slots, False))
        if eager_members:
            idx = tuple(eager_members)
            pad = (-len(idx)) % cs
            buckets.append(_Bucket(-1, 0, 0, idx,
                                   idx + (idx[-1],) * pad, True))

        slot_pulsar: List[int] = []
        chunk_map: List[Tuple[int, int]] = []
        for bi, b in enumerate(buckets):
            for lo in range(0, len(b.slots), cs):
                chunk_map.append((bi, lo))
            slot_pulsar.extend(b.slots)
        primary_slot = np.full(len(self._pulsars), -1, np.int64)
        for s, pi in enumerate(slot_pulsar):
            if primary_slot[pi] < 0:
                primary_slot[pi] = s
        p_max = max([b.n_param for b in buckets] +
                    [len(pu.names) for pu in self._pulsars])
        delta_keys = {
            si: tuple(sorted(
                k for k, v in rep[si].resid.pdict["delta"].items()
                if np.ndim(v) == 0))
            for si in rep}
        self._plan = {
            "buckets": buckets, "chunk_map": chunk_map,
            "slot_pulsar": np.asarray(slot_pulsar, np.int64),
            "primary_slot": primary_slot, "n_slots": len(slot_pulsar),
            "p_max": int(p_max), "rep": rep, "delta_keys": delta_keys,
            "skey_repr": {si: repr(k) for k, si in skeys.items()},
        }
        _log.info("fleet plan: %d pulsar(s) -> %d bucket(s), %d chunk(s) "
                  "of %d", len(self._pulsars), len(buckets),
                  len(chunk_map), cs)
        return self._plan

    @property
    def n_buckets(self) -> int:
        return len(self._ensure_plan()["buckets"])

    @property
    def program_count(self) -> int:
        """Compiled bucket programs built so far — after one fit this
        equals the number of non-eager buckets (the compile budget)."""
        return len(self._programs)

    def _bucket_program(self, bucket: _Bucket):
        plan = self._plan
        key = (bucket.skey_idx, bucket.n_toa, bucket.n_param)
        prog = self._programs.get(key)
        if prog is None:
            rep = plan["rep"][bucket.skey_idx]
            kern = self.kernel if self.kernel is not None else \
                _default_wls_kernel()
            profiling.count("fleet.program_build")
            prog = _build_bucket_fit(
                rep.model, rep.resid.track_mode,
                plan["delta_keys"][bucket.skey_idx], bucket.n_param,
                "PhaseOffset" not in rep.model.components,
                self.maxiter, self.tol_chi2, kern, self.threshold,
                self.diverge_streak, self.stall_iters)
            if self._sharding is None:
                # AOT store (ISSUE 7): bucket programs are the serving
                # hot set — all inputs (params, padded batch, slots,
                # masks) ride the call, so the key is pure structure:
                # the bucket's structure-group key + padded shape +
                # loop/solver configuration.  Deterministic bucket
                # edges make these prebuildable (python -m pint_tpu.aot
                # warm).  Explicitly-sharded programs are not served
                # (an exported module pins its input shardings).
                from pint_tpu import aot

                prog = aot.serve(
                    "fleet_bucket", prog,
                    f"{plan['skey_repr'][bucket.skey_idx]}"
                    f"|ntoa={bucket.n_toa}|nparam={bucket.n_param}"
                    f"|maxiter={self.maxiter}|tol={self.tol_chi2:g}"
                    f"|thr={self.threshold}"
                    f"|kern={getattr(kern, '__name__', str(kern))}"
                    f"|streak={self.diverge_streak}"
                    f"|stall={self.stall_iters}")
            self._programs[key] = prog
        return prog

    def _chunk_args(self, ci: int):
        """Device-resident stacked inputs for chunk ``ci`` — staged ONCE
        and cached, so steady-state fleet fits pay zero host->device
        traffic (the warm-program-cache serving property)."""
        args = self._args_cache.get(ci)
        if args is not None:
            return args
        plan = self._plan
        bi, lo = plan["chunk_map"][ci]
        b = plan["buckets"][bi]
        ps = [self._pulsars[pi] for pi in b.slots[lo:lo + self.chunk_size]]
        dkeys = plan["delta_keys"][b.skey_idx]
        kidx = {k: j for j, k in enumerate(dkeys)}

        pdicts = [_pad_pdict(pu.resid, b.n_toa) for pu in ps]
        stacked_p = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x, np.float64)
                                  for x in xs]), *pdicts)
        batches = [pad_batch_to(pu.resid.batch, b.n_toa) for pu in ps]
        stacked_b = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]), *batches)
        slots = np.zeros((len(ps), b.n_param), np.int32)
        pmask = np.zeros((len(ps), b.n_param), np.float64)
        rowmask = np.zeros((len(ps), b.n_toa), np.float64)
        for j, pu in enumerate(ps):
            for i, n in enumerate(pu.names):
                slots[j, i] = kidx[n]
                pmask[j, i] = 1.0
            rowmask[j, :pu.resid.batch.ntoas] = 1.0
        args = (stacked_p, stacked_b, jnp.asarray(slots),
                jnp.asarray(pmask), jnp.asarray(rowmask))
        if self._sharding is not None:
            args = jax.device_put(args, self._sharding)
        else:
            args = jax.device_put(args)
        self._args_cache[ci] = args
        return args

    # -- the eager lane --------------------------------------------------------

    def _eager_fit_one(self, pi: int, plan) -> _EagerOut:
        """One pulsar through the guarded single-pulsar engine (PR 3's
        degradation chain and provenance), on a deepcopy so ``fit()``
        stays side-effect free.  The fitted offsets are recovered from
        the copy's written-back parameter values."""
        pu = self._pulsars[pi]
        model = copy.deepcopy(pu.model)
        cls = GLSFitter if model.has_correlated_errors else WLSFitter
        f = cls(pu.toas, model, track_mode=pu.resid.track_mode,
                policy=self.policy)
        try:
            chi2 = float(f.fit_toas(maxiter=self.eager_maxiter,
                                    tol_chi2=self.tol_chi2,
                                    threshold=self.threshold))
            fr = f.fitresult
            status, rung, iters = fr.status, fr.rung or "eager", \
                fr.iterations
        except ConvergenceFailure as e:
            chi2 = float("nan")
            status = e.status if e.status is not None else \
                FitStatus.NONFINITE
            rung, iters = "failed", 0
        x = np.zeros(plan["p_max"])
        for i, n in enumerate(pu.names):
            old = np.asarray(pu.model[n].device_value, np.float64)  # ddlint: disable=TRACE002 host parameter metadata, bounded by nfit
            new = np.asarray(model[n].device_value, np.float64)     # ddlint: disable=TRACE002 host parameter metadata, bounded by nfit
            x[i] = np.sum(new - old)
        return _EagerOut(chi2, x, status, int(iters), rung)

    def _run_eager_chunk(self, lo: int, hi: int, plan, side,
                         why: str) -> np.ndarray:
        """The eager lane / requeue path for slots [lo, hi): one guarded
        single-pulsar fit per UNIQUE pulsar (pad duplicates copy their
        original's row)."""
        chi2 = np.empty(hi - lo, np.float64)
        done: Dict[int, _EagerOut] = {}
        for j, pi in enumerate(plan["slot_pulsar"][lo:hi]):
            pi = int(pi)
            if pi not in done:
                profiling.count(f"fleet.eager_{why}")
                done[pi] = self._eager_fit_one(pi, plan)
            eo = done[pi]
            chi2[j] = eo.chi2
            s = lo + j
            side["x"][s] = eo.x
            side["status"][s] = int(eo.status)
            side["iters"][s] = eo.iterations
            side["best_chi2"][s] = eo.chi2
            side["rung"][s] = _rung_code(eo.rung)
        return chi2

    # -- the fit ---------------------------------------------------------------

    # warmup budget: one XLA program per bucket (2 on the audit fixture,
    # measured exactly when the persistent compile cache is cold) plus
    # the one-time tiny staging-op executables (pad/stack/device_put);
    # steady state on the audit fixture is 2 chunk dispatches + 2
    # result fetches, compiles == retraces == 0
    @dispatch_contract("fleet_fit", max_compiles=24, max_dispatches=4,
                       max_transfers=8, warm_from_store=True,
                       # compiled-HLO comm contract (ISSUE 10), measured
                       # with the bucket program lowered on batch-mesh
                       # NamedSharding avals: XLA replicates the
                       # unconstrained vmap outputs via exactly two
                       # all-gathers — a SANCTIONED replication (every
                       # host reads the full result); anything else
                       # (e.g. an input all-gather undoing the batch
                       # sharding) is unbudgeted and always-fail
                       max_collectives={"all-gather": 2},
                       max_comm_bytes=8192,
                       max_device_peak_bytes=1 << 20)
    def fit(self, *, checkpoint: Optional[str] = None,
            resume: bool = False, max_retries: int = 1,
            checkpoint_every: int = 1) -> FleetResult:
        """Fit the whole fleet; returns a :class:`FleetResult` (models
        are NOT mutated — see :meth:`apply`).

        Dispatch contract ``fleet_fit``: the first call compiles one
        program per bucket (the bucket count is the compile budget);
        a steady-state call is 1 dispatch + 1 result fetch per chunk,
        zero compiles, zero retraces — audited in tier-1 with the other
        hot entrypoints.

        ``checkpoint``/``resume`` ride
        :func:`pint_tpu.runtime.run_checkpointed_scan` (plus a fleet
        sidecar at ``<checkpoint>.fleet`` holding per-pulsar x/status),
        so a SIGTERM mid-fleet flushes state and raises
        ``ScanInterrupted``, and a resume restores completed chunks
        bit-identically.  A chunk whose dispatch raises or returns
        non-finite chi2 is retried ``max_retries`` times then requeued
        onto the eager single-pulsar path; pulsars whose in-graph
        sentinel ends DIVERGED/NONFINITE are requeued individually."""
        plan = self._ensure_plan()
        cs = self.chunk_size
        n_slots = plan["n_slots"]
        p_max = plan["p_max"]
        side = {
            "x": np.full((n_slots, p_max), np.nan, np.float64),
            "status": np.full(n_slots, -1, np.int16),
            "iters": np.zeros(n_slots, np.int32),
            "best_chi2": np.full(n_slots, np.nan, np.float64),
            "rung": np.zeros(n_slots, np.int16),
        }
        sig = self._signature(plan)
        sidecar = (checkpoint + ".fleet") if checkpoint else None
        if resume and sidecar:
            import os as _os

            if _os.path.exists(sidecar):
                data = runtime.load_checkpoint(sidecar)
                stored = bytes(np.asarray(
                    data.get("signature", np.zeros(0, np.uint8)),
                    np.uint8)).decode(errors="replace")
                if stored != sig or data["x"].shape != (n_slots, p_max):
                    raise ValueError(
                        f"fleet sidecar {sidecar!r} does not match this "
                        f"fleet (stored signature {stored!r})")
                for k in side:
                    # checkpoint payloads are host npz arrays; no
                    # device sync hides in this conversion
                    side[k] = np.asarray(data[k], side[k].dtype).copy()  # ddlint: disable=TRACE002 host checkpoint data
            elif _os.path.exists(checkpoint):
                raise ValueError(
                    f"scan checkpoint {checkpoint!r} exists but its "
                    f"fleet sidecar {sidecar!r} is missing; cannot "
                    "resume per-pulsar state")

        def flush_side():
            if sidecar:
                payload = dict(side)
                payload["signature"] = np.frombuffer(sig.encode(),
                                                     np.uint8)
                runtime.write_checkpoint(sidecar, payload)

        def run_chunk(ci, lo, hi):
            bi, blo = plan["chunk_map"][ci]
            b = plan["buckets"][bi]
            if b.eager:
                vals = self._run_eager_chunk(lo, hi, plan, side, "lane")
                flush_side()
                return vals
            prog = self._bucket_program(b)
            args = self._chunk_args(ci)
            profiling.count("fleet.chunk_dispatch")
            with telemetry.span("fleet.chunk", chunk=ci, lo=lo, hi=hi,
                                n_toa=b.n_toa, n_param=b.n_param):
                out = np.asarray(prog(*args))
            P = b.n_param
            side["x"][lo:hi, :P] = out[:, :P]
            side["x"][lo:hi, P:] = 0.0
            side["status"][lo:hi] = out[:, P + _COL_STATUS].astype(
                np.int16)
            side["iters"][lo:hi] = out[:, P + _COL_ITERS].astype(np.int32)
            side["best_chi2"][lo:hi] = out[:, P + _COL_BEST]
            side["rung"][lo:hi] = _RUNG_CODE["fleet"]
            flush_side()
            # the returned chi2 is what the scan engine judges: a chunk
            # whose dispatch poisons every value (vs one sentinel-failed
            # pulsar, which returns its best finite chi2) drives the
            # retry/requeue machinery
            return out[:, P + _COL_CHI2]

        def fallback(ci, lo, hi):
            vals = self._run_eager_chunk(lo, hi, plan, side, "requeue")
            flush_side()
            return vals

        results, summary = runtime.run_checkpointed_scan(
            n_slots, run_chunk, chunk_size=cs, fallback=fallback,
            checkpoint=checkpoint, resume=resume,
            max_retries=max_retries, checkpoint_every=checkpoint_every,
            signature=sig)

        # per-pulsar requeue: an in-graph sentinel failure (DIVERGED /
        # NONFINITE) lands that one pulsar — not its bucket — on the
        # guarded eager path, with the winning rung in the result
        if self.requeue:
            for pu in self._pulsars:
                s = int(plan["primary_slot"][pu.index])
                st = int(side["status"][s])
                if side["rung"][s] != _RUNG_CODE["fleet"] or st in (
                        int(FitStatus.CONVERGED), int(FitStatus.MAXITER)):
                    continue
                warnings.warn(
                    f"fleet pulsar {pu.name} ended "
                    f"{FitStatus(st).name} in its bucket; requeueing "
                    "onto the eager single-pulsar path",
                    FleetRequeueWarning)
                profiling.count("fleet.pulsar_requeue")
                eo = self._eager_fit_one(pu.index, plan)
                results[s] = eo.chi2
                side["x"][s] = eo.x
                side["status"][s] = int(eo.status)
                side["iters"][s] = eo.iterations
                side["rung"][s] = _rung_code(eo.rung)

        entries = []
        # results/side are host np arrays by here (fetched once per
        # chunk boundary inside the scan) — this loop never syncs
        for pu in self._pulsars:
            s = int(plan["primary_slot"][pu.index])
            st = int(side["status"][s])  # ddlint: disable=TRACE002 host result table
            entries.append(FleetEntry(
                pu.name, pu.index, float(results[s]), pu.dof,  # ddlint: disable=TRACE002 host result table
                FitStatus(st) if 0 <= st <= 3 else FitStatus.NONFINITE,
                _RUNGS[int(side["rung"][s])], int(side["iters"][s]),
                side["x"][s, :len(pu.names)].copy(), pu.names))
        return FleetResult(tuple(entries), summary,
                           len(plan["buckets"]), self.program_count)

    def _signature(self, plan) -> str:
        crc = 0
        for pu in self._pulsars:
            rec = f"{pu.name}:{pu.resid.batch.ntoas}:" \
                  f"{','.join(pu.names)};"
            crc = zlib.crc32(rec.encode(), crc)
        return (f"fleet|cs={self.chunk_size}|maxiter={self.maxiter}"
                f"|tol={self.tol_chi2:g}|nb={len(plan['buckets'])}"
                f"|crc={crc & 0xFFFFFFFF:#010x}")

    def apply(self, result: FleetResult) -> None:
        """Write a result's fitted offsets back into each pulsar's model
        (parameter VALUES only; the batched path computes no
        uncertainties).  Non-finite entries are skipped.  Invalidates
        the staged device data (models changed => pdicts stale)."""
        for e in result.entries:
            pu = self._pulsars[e.index]
            if not np.all(np.isfinite(e.x)):
                continue
            p2 = pu.model.with_x(pu.resid.pdict, np.asarray(e.x),
                                 list(e.fit_names))
            pu.model.apply_deltas(p2)
            pu.resid.update()
        self._args_cache.clear()
        self._plan = None

    # -- bucketed residual evaluation ------------------------------------------

    def _resid_program(self, bucket: _Bucket):
        plan = self._plan
        key = (bucket.skey_idx, bucket.n_toa, bucket.n_param)
        prog = self._resid_programs.get(key)
        if prog is None:
            rep = plan["rep"][bucket.skey_idx]
            profiling.count("fleet.resid_program_build")
            prog = _build_bucket_resid(
                rep.model, rep.resid.track_mode,
                plan["delta_keys"][bucket.skey_idx], bucket.n_param,
                "PhaseOffset" not in rep.model.components)
            if self._sharding is None:
                from pint_tpu import aot

                prog = aot.serve(
                    "fleet_resid", prog,
                    f"{plan['skey_repr'][bucket.skey_idx]}"
                    f"|ntoa={bucket.n_toa}|nparam={bucket.n_param}")
            self._resid_programs[key] = prog
        return prog

    def residuals(self, result: Optional[FleetResult] = None
                  ) -> Dict[str, np.ndarray]:
        """Whitened-mean-subtracted residual SECONDS for every pulsar,
        evaluated through ONE compiled program per bucket (the PTA
        correlation stage's entrypoint — per-pulsar ``Residuals``
        evaluation would pay a compile per pulsar).

        With ``result`` the residuals are evaluated at that fit's
        offsets WITHOUT mutating any model (the side-effect-free
        companion of :meth:`apply`); without it, at the models' current
        values.  Eager-lane pulsars (correlated-noise models) evaluate
        through a deep-copied single-pulsar path.  Returns
        ``{name: (ntoas,) float64}``; steady state is 1 dispatch + 1
        fetch per chunk, like the fit."""
        plan = self._ensure_plan()
        cs = self.chunk_size
        xs: Dict[int, np.ndarray] = {}
        if result is not None:
            xs = {e.index: np.asarray(e.x, np.float64)
                  for e in result.entries}
        out: Dict[str, np.ndarray] = {}
        for ci, (bi, blo) in enumerate(plan["chunk_map"]):
            b = plan["buckets"][bi]
            sl = b.slots[blo:blo + cs]
            if b.eager:
                for pi in dict.fromkeys(sl):
                    pu = self._pulsars[pi]
                    if pu.name in out:
                        continue
                    model = pu.model
                    if xs.get(pi) is not None and \
                            np.all(np.isfinite(xs[pi])) and np.any(xs[pi]):
                        model = copy.deepcopy(pu.model)
                        p2 = model.with_x(pu.resid.pdict,
                                          xs[pi][:len(pu.names)],
                                          list(pu.names))
                        model.apply_deltas(p2)
                    r = Residuals(pu.toas, model,
                                  track_mode=pu.resid.track_mode,
                                  policy=self.policy)
                    rs = np.asarray(r.time_resids, np.float64)
                    w = 1.0 / np.asarray(r.get_data_error(),
                                         np.float64) ** 2
                    out[pu.name] = rs - np.sum(rs * w) / np.sum(w)
                continue
            prog = self._resid_program(b)
            stacked_p, stacked_b, slots, pmask, rowmask = \
                self._chunk_args(ci)
            X = np.zeros((len(sl), b.n_param), np.float64)
            for j, pi in enumerate(sl):
                x = xs.get(pi)
                # a failed fit's x is NaN; evaluate that pulsar at its
                # current model values rather than poisoning its row
                if x is not None and np.all(np.isfinite(x)):
                    X[j, :x.shape[0]] = x
            with telemetry.span("fleet.resid_chunk", chunk=ci,
                                n_toa=b.n_toa, n_param=b.n_param):
                r = np.asarray(prog(stacked_p, stacked_b,
                                    jnp.asarray(X), slots, pmask,
                                    rowmask))
            for j, pi in enumerate(sl):
                pu = self._pulsars[pi]
                if pu.name not in out:
                    out[pu.name] = r[j, :pu.resid.batch.ntoas].copy()
        return out
