"""Pulse-profile templates and photon-phase statistics.

Reference: `pint.templates` (`/root/reference/src/pint/templates/`,
~4.8k LoC: lcprimitives/lcnorm/lctemplate/lcfitters) and
`pint.eventstats`.  The TPU redesign collapses this to one module:

* :class:`LCGaussian` / :class:`LCLorentzian` — wrapped peak primitives
  evaluated with jnp (a few explicit wraps; widths << 1 make that exact
  to f64);
* :class:`LCTemplate` — normalized sum of primitives + uniform
  background, a pure function of a flat parameter vector so the unbinned
  log-likelihood is jit/grad/vmap-able;
* :func:`fit_template` — maximum-likelihood template fitting by L-BFGS
  over the jax gradient (the reference's lcfitters uses scipy fmin
  without gradients);
* :func:`hm` / :func:`z2m` — (weighted) H-test and Z^2_m periodicity
  statistics (de Jager et al. 1989, 2010), vectorized.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LCGaussian", "LCLorentzian", "LCTemplate", "fit_template",
           "hm", "z2m", "sf_hm"]

TWOPI = 2.0 * math.pi
_NWRAP = 3  # peaks wrapped over [-3, 3] cover sigma <~ 0.5 exactly in f64


class _Primitive:
    """A localized peak on the phase circle with (loc, width) params."""

    def __init__(self, loc: float, width: float):
        self.loc = float(loc)
        self.width = float(width)

    nparams = 2

    @staticmethod
    def density(dphi, width):
        raise NotImplementedError

    def __call__(self, phases):
        return type(self).eval(jnp.asarray(phases), self.loc, self.width)

    @classmethod
    def eval(cls, phases, loc, width):
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            out = out + cls.density(phases - loc + k, width)
        return out


class LCGaussian(_Primitive):
    """Wrapped Gaussian peak (reference `LCGaussian`,
    `templates/lcprimitives.py:431`)."""

    @staticmethod
    def density(dphi, width):
        return jnp.exp(-0.5 * (dphi / width) ** 2) / \
            (width * jnp.sqrt(TWOPI))


class LCLorentzian(_Primitive):
    """Wrapped Lorentzian peak (reference `LCLorentzian`,
    `templates/lcprimitives.py:540`): the wrapped-Cauchy closed form —
    exactly normalized, no truncated 1/x^2 tails."""

    @classmethod
    def eval(cls, phases, loc, width):
        rho = jnp.exp(-TWOPI * width)
        return (1.0 - rho**2) / \
            (1.0 + rho**2 - 2.0 * rho * jnp.cos(TWOPI * (phases - loc)))


class LCTemplate:
    """f(phi) = sum_k n_k P_k(phi; loc_k, w_k) + (1 - sum n_k).

    Parameter vector layout (for the jit path): per peak
    ``[norm_k, loc_k, log_width_k]`` — widths enter through log so
    unconstrained optimization keeps them positive (reference keeps a
    separate constraint machinery, `lcnorm.py`).
    """

    def __init__(self, primitives: Sequence[_Primitive],
                 norms: Sequence[float]):
        if len(primitives) != len(norms):
            raise ValueError("one norm per primitive")
        if sum(norms) > 1.0 + 1e-9:
            raise ValueError("peak norms must sum to <= 1")
        self.primitives = list(primitives)
        self.norms = [float(n) for n in norms]

    # -- parameter vector <-> structure ------------------------------------
    def get_parameters(self) -> np.ndarray:
        out = []
        for n, p in zip(self.norms, self.primitives):
            out += [n, p.loc, math.log(p.width)]
        return np.array(out)

    def set_parameters(self, x):
        x = np.asarray(x, np.float64)
        nsum = float(sum(x[3 * k] for k in range(len(self.primitives))))
        scale = 1.0 / nsum if nsum > 1.0 else 1.0
        for k, p in enumerate(self.primitives):
            self.norms[k] = float(x[3 * k]) * scale
            p.loc = float(x[3 * k + 1]) % 1.0
            p.width = math.exp(float(x[3 * k + 2]))

    def _eval_fn(self):
        classes = [type(p) for p in self.primitives]

        def f(phases, x):
            total = jnp.zeros_like(phases)
            nsum = 0.0
            for k, cls in enumerate(classes):
                n, loc, logw = x[3 * k], x[3 * k + 1], x[3 * k + 2]
                total = total + n * cls.eval(phases, loc, jnp.exp(logw))
                nsum = nsum + n
            return total + (1.0 - nsum)

        return f

    def __call__(self, phases) -> np.ndarray:
        f = self._eval_fn()
        return np.asarray(f(jnp.asarray(phases, jnp.float64),
                            jnp.asarray(self.get_parameters())))

    def integrate(self, n: int = 4096) -> float:
        """Sanity integral over one cycle (should be 1)."""
        grid = (np.arange(n) + 0.5) / n
        return float(np.mean(self(grid)))


def log_likelihood_fn(template: LCTemplate):
    """``(phases, weights, x) -> lnL`` — the weighted unbinned photon
    log-likelihood sum_i ln(w_i f(phi_i) + 1 - w_i) (reference
    `lcfitters.py:99`), jit-pure in the template parameter vector."""
    f = template._eval_fn()

    def lnlike(phases, weights, x):
        vals = f(phases, x)
        # floor guards optimizer excursions where sum(norms) > 1 briefly
        # makes the background (and f) negative
        return jnp.sum(jnp.log(jnp.maximum(
            weights * vals + (1.0 - weights), 1e-300)))

    return lnlike


def fit_template(template: LCTemplate, phases, weights=None,
                 maxiter: int = 200) -> Tuple[LCTemplate, float]:
    """Maximum-likelihood template fit; returns (template, lnL).  The
    template is updated in place and returned for convenience."""
    from scipy.optimize import minimize

    phases = jnp.asarray(np.asarray(phases, np.float64))
    weights = jnp.ones_like(phases) if weights is None else \
        jnp.asarray(np.asarray(weights, np.float64))
    lnlike = log_likelihood_fn(template)

    nk = len(template.primitives)

    @jax.jit
    def negll(x):
        # smooth barrier keeps sum(norms) <= 1 (the per-norm bounds alone
        # cannot: two peaks at 0.8 + 0.7 would drive the background
        # negative and the likelihood to NaN)
        nsum = sum(x[3 * k] for k in range(nk))
        barrier = 1e4 * jnp.maximum(nsum - 0.999, 0.0) ** 2
        return -lnlike(phases, weights, x) + barrier

    grad = jax.jit(jax.grad(negll))
    x0 = template.get_parameters()
    # keep norms in (0,1) via bounds; loc free (wrapped); log-width free
    bounds = []
    for _ in range(nk):
        bounds += [(1e-4, 1.0), (None, None), (math.log(5e-4),
                                               math.log(0.5))]
    res = minimize(lambda x: float(negll(jnp.asarray(x))),
                   x0, jac=lambda x: np.asarray(grad(jnp.asarray(x))),
                   method="L-BFGS-B", bounds=bounds,
                   options={"maxiter": maxiter})
    template.set_parameters(res.x)
    return template, -float(res.fun)


# -- periodicity statistics ------------------------------------------------
def z2m(phases, m: int = 2, weights=None) -> np.ndarray:
    """Z^2_m statistics for harmonics 1..m (Buccheri et al. 1983;
    reference `eventstats.z2m`).  Returns the cumulative array."""
    phases = np.asarray(phases, np.float64)
    w = np.ones_like(phases) if weights is None else \
        np.asarray(weights, np.float64)
    k = np.arange(1, m + 1)[:, None]
    arg = TWOPI * k * phases[None, :]
    c = np.sum(w[None, :] * np.cos(arg), axis=1)
    s = np.sum(w[None, :] * np.sin(arg), axis=1)
    return np.cumsum((2.0 / np.sum(w**2)) * (c**2 + s**2))


def hm(phases, m: int = 20, weights=None) -> float:
    """(Weighted) H-test statistic (de Jager et al. 1989, 2010;
    reference `eventstats.hm`/`hmw`): max_m (Z^2_m - 4m + 4)."""
    z = z2m(phases, m=m, weights=weights)
    return float(np.max(z - 4.0 * np.arange(1, m + 1) + 4.0))


def sf_hm(h: float) -> float:
    """H-test survival function ~ exp(-0.4 h) (de Jager & Busching
    2010)."""
    return math.exp(-0.4 * h)
