"""Pulse-profile templates and photon-phase statistics.

Reference: `pint.templates` (`/root/reference/src/pint/templates/`,
~4.8k LoC: lcprimitives/lcnorm/lctemplate/lcfitters) and
`pint.eventstats`.  The TPU redesign collapses this to one module:

* :class:`LCGaussian` / :class:`LCLorentzian` — wrapped peak primitives
  evaluated with jnp (a few explicit wraps; widths << 1 make that exact
  to f64);
* :class:`LCTemplate` — normalized sum of primitives + uniform
  background, a pure function of a flat parameter vector so the unbinned
  log-likelihood is jit/grad/vmap-able;
* :func:`fit_template` — maximum-likelihood template fitting by L-BFGS
  over the jax gradient (the reference's lcfitters uses scipy fmin
  without gradients);
* :func:`hm` / :func:`z2m` — (weighted) H-test and Z^2_m periodicity
  statistics (de Jager et al. 1989, 2010), vectorized.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LCGaussian", "LCGaussian2", "LCSkewGaussian", "LCLorentzian",
           "LCLorentzian2", "LCVonMises", "LCKing", "LCTopHat",
           "LCEGaussian", "LCTemplate", "NormAngles", "fit_template",
           "fit_template_binned", "hm", "z2m", "sf_hm"]

TWOPI = 2.0 * math.pi
_NWRAP = 3  # peaks wrapped over [-3, 3] cover sigma <~ 0.5 exactly in f64


class _Primitive:
    """A localized peak on the phase circle: a location plus one or more
    shape parameters.

    ``shape_names`` / ``log_shape`` declare the shape parameters and
    whether each is packed in log (widths: yes; skews/slopes: no) for
    unconstrained optimization (the reference keeps a separate bounds
    machinery instead, `lcprimitives.py:208`)."""

    shape_names = ("width",)
    log_shape = (True,)

    def __init__(self, loc: float, *shape, **kw):
        self.loc = float(loc)
        defaults = list(self.shape_defaults())
        if len(shape) > len(self.shape_names):
            raise TypeError(
                f"{type(self).__name__} takes at most "
                f"{len(self.shape_names)} shape parameters "
                f"{self.shape_names}, got {len(shape)}")
        shape = list(shape)
        for i, nm in enumerate(self.shape_names):
            if nm in kw:
                val = kw.pop(nm)
            elif i < len(shape):
                val = shape[i]
            else:
                val = defaults[i]
            defaults[i] = float(val)
        if kw:
            raise TypeError(f"unknown shape parameters {sorted(kw)}")
        self.shape = defaults

    @classmethod
    def shape_defaults(cls):
        return [0.03] * len(cls.shape_names)

    @classmethod
    def shape_fit_bounds(cls):
        """L-BFGS-B bounds per (packed) shape parameter: log-widths get
        the standard range, others unbounded; primitives with hard
        domain edges (King's gamma > 1) override."""
        import math as _m

        return [(_m.log(5e-4), _m.log(0.5)) if is_log else (None, None)
                for is_log in cls.log_shape]

    # back-compat convenience for single-width primitives
    @property
    def width(self):
        return self.shape[0]

    @width.setter
    def width(self, v):
        self.shape[0] = float(v)

    @staticmethod
    def density(dphi, *shape):
        raise NotImplementedError

    def __call__(self, phases, log10_ens=None):
        f = type(self).eval_e if log10_ens is not None else None
        if f is not None:
            return f(jnp.asarray(phases), jnp.asarray(log10_ens),
                     self.loc, *self.shape)
        return type(self).eval(jnp.asarray(phases), self.loc, *self.shape)

    #: wrap count; heavy-tailed primitives override (Cauchy-class tails
    #: decay only as 1/x^2)
    _nwrap = _NWRAP

    @classmethod
    def eval(cls, phases, loc, *shape):
        out = 0.0
        for k in range(-cls._nwrap, cls._nwrap + 1):
            out = out + cls.density(phases - loc + k, *shape)
        return out

    @classmethod
    def eval_e(cls, phases, log10_ens, loc, *shape):
        """Energy-dependent evaluation; energy-independent primitives
        ignore the energies."""
        return cls.eval(phases, loc, *shape)


class LCGaussian(_Primitive):
    """Wrapped Gaussian peak (reference `LCGaussian`,
    `templates/lcprimitives.py:724`)."""

    @staticmethod
    def density(dphi, width):
        return jnp.exp(-0.5 * (dphi / width) ** 2) / \
            (width * jnp.sqrt(TWOPI))


class LCGaussian2(_Primitive):
    """Two-sided (asymmetric) wrapped Gaussian (reference `LCGaussian2`,
    `lcprimitives.py:797`): width1 on the leading (dphi < 0) side, width2
    trailing, continuous at the peak, exactly normalized."""

    shape_names = ("width1", "width2")
    log_shape = (True, True)

    @staticmethod
    def density(dphi, width1, width2):
        w = jnp.where(dphi < 0.0, width1, width2)
        return jnp.exp(-0.5 * (dphi / w) ** 2) * \
            (2.0 / ((width1 + width2) * jnp.sqrt(TWOPI)))


class LCSkewGaussian(_Primitive):
    """Wrapped skew-normal peak (reference `LCSkewGaussian`,
    `lcprimitives.py:861`): 2/w phi(z) Phi(skew z), z = dphi/w."""

    shape_names = ("width", "skew")
    log_shape = (True, False)

    @classmethod
    def shape_defaults(cls):
        return [0.03, 0.0]

    @staticmethod
    def density(dphi, width, skew):
        from jax.scipy.stats import norm

        z = dphi / width
        return 2.0 / width * norm.pdf(z) * norm.cdf(skew * z)


class LCLorentzian(_Primitive):
    """Wrapped Lorentzian peak (reference `LCLorentzian`,
    `templates/lcprimitives.py:1004`): the wrapped-Cauchy closed form —
    exactly normalized, no truncated 1/x^2 tails."""

    @classmethod
    def eval(cls, phases, loc, width):
        rho = jnp.exp(-TWOPI * width)
        return (1.0 - rho**2) / \
            (1.0 + rho**2 - 2.0 * rho * jnp.cos(TWOPI * (phases - loc)))


class LCLorentzian2(_Primitive):
    """Two-sided (asymmetric) wrapped Lorentzian (reference
    `LCLorentzian2`, `lcprimitives.py:1089`)."""

    shape_names = ("width1", "width2")
    log_shape = (True, True)
    _nwrap = 50  # 1/x^2 tails: 50 wraps leave ~3e-4 of the mass

    @staticmethod
    def density(dphi, width1, width2):
        w = jnp.where(dphi < 0.0, width1, width2)
        return (2.0 / (math.pi * (width1 + width2))) * \
            w**2 / (dphi**2 + w**2)


class LCVonMises(_Primitive):
    """Von Mises peak (reference `LCVonMises`, `lcprimitives.py:1178`):
    exp(kappa cos(2 pi dphi)) / I0(kappa), kappa = 1/(2 pi width)^2 —
    periodic by construction, no wrapping needed."""

    @classmethod
    def eval(cls, phases, loc, width):
        from jax.scipy.special import i0e

        kappa = (TWOPI * width) ** -2
        dphi = TWOPI * (phases - loc)
        # i0e = exp(-|k|) I0(k): form the ratio without overflow
        return jnp.exp(kappa * (jnp.cos(dphi) - 1.0)) / i0e(kappa)


class LCKing(_Primitive):
    """Wrapped King-profile peak (reference `LCKing`,
    `lcprimitives.py:1253`): the radial King PSF treated as a 1D pulse
    shape, density d/dz [1 - (1 + z^2/(2 sigma^2 gamma))^(1-gamma)]/2
    matching the reference's closed-form integral."""

    shape_names = ("sigma", "gamma")
    log_shape = (True, False)
    _nwrap = 50  # x^(1-2 gamma) tails: power-law, like Lorentzian2

    @classmethod
    def shape_defaults(cls):
        return [0.03, 1.5]

    @classmethod
    def shape_fit_bounds(cls):
        b = super().shape_fit_bounds()
        b[1] = (1.05, 50.0)   # density is negative/singular at gamma <= 1
        return b

    @staticmethod
    def density(dphi, sigma, gamma):
        u = 0.5 * (dphi / sigma) ** 2
        return 0.5 * (gamma - 1.0) / (gamma * sigma**2) * \
            jnp.abs(dphi) * (1.0 + u / gamma) ** -gamma


class LCTopHat(_Primitive):
    """Top-hat (boxcar) peak (reference `LCTopHat`,
    `lcprimitives.py:1311`); piecewise-constant, so fit it with the
    derivative-free path only."""

    @classmethod
    def eval(cls, phases, loc, width):
        dphi = (phases - loc + 0.5) % 1.0 - 0.5
        return jnp.where(jnp.abs(dphi) <= width / 2.0, 1.0 / width, 0.0)


class LCEGaussian(LCGaussian):
    """Energy-dependent wrapped Gaussian (reference `LCEGaussian`,
    `lceprimitives.py:180`): location and width vary linearly in
    log10(E), referenced to 1 GeV (log10_ens = 3)."""

    shape_names = ("width", "loc_slope", "width_slope")
    log_shape = (True, False, False)

    @classmethod
    def shape_defaults(cls):
        return [0.03, 0.0, 0.0]

    @classmethod
    def eval(cls, phases, loc, width, loc_slope=0.0, width_slope=0.0):
        return LCGaussian.eval(phases, loc, width)

    @classmethod
    def eval_e(cls, phases, log10_ens, loc, width, loc_slope=0.0,
               width_slope=0.0):
        de = log10_ens - 3.0
        loc_e = loc + loc_slope * de
        width_e = jnp.maximum(width + width_slope * de, 1e-4)
        out = 0.0
        for k in range(-_NWRAP, _NWRAP + 1):
            out = out + LCGaussian.density(phases - loc_e + k, width_e)
        return out


class LCTemplate:
    """f(phi) = sum_k n_k P_k(phi; loc_k, shape_k) + (1 - sum n_k).

    Parameter vector layout (for the jit path): per peak
    ``[norm_k, loc_k, shape_k...]`` with log-declared shape parameters
    (widths) packed through log, so unconstrained optimization keeps
    them positive (reference keeps a separate constraint machinery,
    `lcnorm.py`; :class:`NormAngles` is provided for parity)."""

    def __init__(self, primitives: Sequence[_Primitive],
                 norms: Sequence[float]):
        if len(primitives) != len(norms):
            raise ValueError("one norm per primitive")
        if sum(norms) > 1.0 + 1e-9:
            raise ValueError("peak norms must sum to <= 1")
        self.primitives = list(primitives)
        self.norms = [float(n) for n in norms]

    # -- parameter vector <-> structure ------------------------------------
    def _offsets(self):
        """Start index of each peak's [norm, loc, shapes...] block."""
        out = [0]
        for p in self.primitives:
            out.append(out[-1] + 2 + len(p.shape_names))
        return out

    def norm_indices(self):
        return [o for o in self._offsets()[:-1]]

    def get_parameters(self) -> np.ndarray:
        out = []
        for n, p in zip(self.norms, self.primitives):
            out += [n, p.loc]
            for v, is_log in zip(p.shape, type(p).log_shape):
                out.append(math.log(v) if is_log else v)
        return np.array(out)

    def set_parameters(self, x):
        x = np.asarray(x, np.float64)
        offs = self._offsets()
        nsum = float(sum(x[o] for o in offs[:-1]))
        scale = 1.0 / nsum if nsum > 1.0 else 1.0
        for k, p in enumerate(self.primitives):
            o = offs[k]
            self.norms[k] = float(x[o]) * scale
            p.loc = float(x[o + 1]) % 1.0
            for i, is_log in enumerate(type(p).log_shape):
                v = float(x[o + 2 + i])
                p.shape[i] = math.exp(v) if is_log else v

    def _eval_fn(self, energy_dependent: bool = False):
        classes = [type(p) for p in self.primitives]
        offs = self._offsets()

        def shapes_from(x, k):
            cls = classes[k]
            o = offs[k]
            vals = []
            for i, is_log in enumerate(cls.log_shape):
                v = x[o + 2 + i]
                vals.append(jnp.exp(v) if is_log else v)
            return vals

        if energy_dependent:
            def f(phases, log10_ens, x):
                total = jnp.zeros_like(phases)
                nsum = 0.0
                for k, cls in enumerate(classes):
                    o = offs[k]
                    total = total + x[o] * cls.eval_e(
                        phases, log10_ens, x[o + 1], *shapes_from(x, k))
                    nsum = nsum + x[o]
                return total + (1.0 - nsum)
        else:
            def f(phases, x):
                total = jnp.zeros_like(phases)
                nsum = 0.0
                for k, cls in enumerate(classes):
                    o = offs[k]
                    total = total + x[o] * cls.eval(
                        phases, x[o + 1], *shapes_from(x, k))
                    nsum = nsum + x[o]
                return total + (1.0 - nsum)

        return f

    def __call__(self, phases, log10_ens=None) -> np.ndarray:
        if log10_ens is not None:
            f = self._eval_fn(energy_dependent=True)
            return np.asarray(f(jnp.asarray(phases, jnp.float64),
                                jnp.asarray(log10_ens, jnp.float64),
                                jnp.asarray(self.get_parameters())))
        f = self._eval_fn()
        return np.asarray(f(jnp.asarray(phases, jnp.float64),
                            jnp.asarray(self.get_parameters())))

    def integrate(self, n: int = 4096) -> float:
        """Sanity integral over one cycle (should be 1)."""
        grid = (np.arange(n) + 0.5) / n
        return float(np.mean(self(grid)))


def log_likelihood_fn(template: LCTemplate):
    """``(phases, weights, x) -> lnL`` — the weighted unbinned photon
    log-likelihood sum_i ln(w_i f(phi_i) + 1 - w_i) (reference
    `lcfitters.py:99`), jit-pure in the template parameter vector."""
    f = template._eval_fn()

    def lnlike(phases, weights, x):
        vals = f(phases, x)
        # floor guards optimizer excursions where sum(norms) > 1 briefly
        # makes the background (and f) negative
        return jnp.sum(jnp.log(jnp.maximum(
            weights * vals + (1.0 - weights), 1e-300)))

    return lnlike


def fit_template(template: LCTemplate, phases, weights=None,
                 maxiter: int = 200) -> Tuple[LCTemplate, float]:
    """Maximum-likelihood template fit; returns (template, lnL).  The
    template is updated in place and returned for convenience."""
    from scipy.optimize import minimize

    phases = jnp.asarray(np.asarray(phases, np.float64))
    weights = jnp.ones_like(phases) if weights is None else \
        jnp.asarray(np.asarray(weights, np.float64))
    lnlike = log_likelihood_fn(template)

    norm_idx = template.norm_indices()

    @jax.jit
    def negll(x):
        # smooth barrier keeps sum(norms) <= 1 (the per-norm bounds alone
        # cannot: two peaks at 0.8 + 0.7 would drive the background
        # negative and the likelihood to NaN)
        nsum = sum(x[i] for i in norm_idx)
        barrier = 1e4 * jnp.maximum(nsum - 0.999, 0.0) ** 2
        return -lnlike(phases, weights, x) + barrier

    grad = jax.jit(jax.grad(negll))
    x0 = template.get_parameters()
    res = minimize(lambda x: float(negll(jnp.asarray(x))),
                   x0, jac=lambda x: np.asarray(grad(jnp.asarray(x))),
                   method="L-BFGS-B", bounds=_fit_bounds(template),
                   options={"maxiter": maxiter})
    template.set_parameters(res.x)
    return template, -float(res.fun)


def _fit_bounds(template: LCTemplate):
    """Per-parameter L-BFGS-B bounds: norms in (0,1), locations free
    (wrapped), shape bounds from each primitive class."""
    bounds = []
    for p in template.primitives:
        bounds += [(1e-4, 1.0), (None, None)]
        bounds += type(p).shape_fit_bounds()
    return bounds


def fit_template_binned(template: LCTemplate, phases, weights=None,
                        nbins: int = 64,
                        maxiter: int = 200) -> Tuple[LCTemplate, float]:
    """Binned Poisson maximum-likelihood template fit (reference
    `lcfitters.py` binned path): histogram the (weighted) phases and
    maximize sum_b [c_b ln mu_b - mu_b] with mu_b the template integral
    per bin x total counts.  Much cheaper than the unbinned likelihood
    for very large photon sets; agrees with it as nbins -> inf."""
    from scipy.optimize import minimize

    phases = np.asarray(phases, np.float64) % 1.0
    w = np.ones_like(phases) if weights is None else         np.asarray(weights, np.float64)
    counts, edges = np.histogram(phases, bins=nbins, range=(0.0, 1.0),
                                 weights=w)
    centers = jnp.asarray(0.5 * (edges[:-1] + edges[1:]))
    counts_j = jnp.asarray(counts)
    total = float(np.sum(w))
    f = template._eval_fn()
    norm_idx = template.norm_indices()

    @jax.jit
    def negll(x):
        mu = jnp.maximum(f(centers, x) / nbins * total, 1e-300)
        nsum = sum(x[i] for i in norm_idx)
        barrier = 1e4 * jnp.maximum(nsum - 0.999, 0.0) ** 2
        return -jnp.sum(counts_j * jnp.log(mu) - mu) + barrier

    grad = jax.jit(jax.grad(negll))
    res = minimize(lambda x: float(negll(jnp.asarray(x))),
                   template.get_parameters(),
                   jac=lambda x: np.asarray(grad(jnp.asarray(x))),
                   method="L-BFGS-B", bounds=_fit_bounds(template),
                   options={"maxiter": maxiter})
    template.set_parameters(res.x)
    return template, -float(res.fun)


class NormAngles:
    """Simplex parameterization of the peak norms (reference
    `lcnorm.NormAngles`, `templates/lcnorm.py:20`): n norms with
    sum <= 1 mapped to n unconstrained angles through nested
    spherical sines, so constrained optimizers are unnecessary."""

    def __init__(self, norms: Sequence[float]):
        self.n = len(norms)
        self.set_norms(norms)

    def set_norms(self, norms):
        norms = np.asarray(norms, np.float64)
        if np.any(norms < 0) or norms.sum() > 1.0 + 1e-9:
            raise ValueError("norms must be >= 0 with sum <= 1")
        self.angles = np.zeros(self.n)
        remainder = 1.0
        for i, v in enumerate(norms):
            frac = np.clip(v / remainder if remainder > 0 else 0.0,
                           0.0, 1.0)
            self.angles[i] = math.asin(math.sqrt(frac))
            remainder -= v

    def get_norms(self) -> np.ndarray:
        out = np.zeros(self.n)
        remainder = 1.0
        for i, a in enumerate(self.angles):
            out[i] = remainder * math.sin(a) ** 2
            remainder -= out[i]
        return out


# -- periodicity statistics ------------------------------------------------
def z2m(phases, m: int = 2, weights=None) -> np.ndarray:
    """Z^2_m statistics for harmonics 1..m (Buccheri et al. 1983;
    reference `eventstats.z2m`).  Returns the cumulative array."""
    phases = np.asarray(phases, np.float64)
    w = np.ones_like(phases) if weights is None else \
        np.asarray(weights, np.float64)
    k = np.arange(1, m + 1)[:, None]
    arg = TWOPI * k * phases[None, :]
    c = np.sum(w[None, :] * np.cos(arg), axis=1)
    s = np.sum(w[None, :] * np.sin(arg), axis=1)
    return np.cumsum((2.0 / np.sum(w**2)) * (c**2 + s**2))


def hm(phases, m: int = 20, weights=None) -> float:
    """(Weighted) H-test statistic (de Jager et al. 1989, 2010;
    reference `eventstats.hm`/`hmw`): max_m (Z^2_m - 4m + 4)."""
    z = z2m(phases, m=m, weights=weights)
    return float(np.max(z - 4.0 * np.arange(1, m + 1) + 4.0))


def sf_hm(h: float) -> float:
    """H-test survival function ~ exp(-0.4 h) (de Jager & Busching
    2010)."""
    return math.exp(-0.4 * h)
