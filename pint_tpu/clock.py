"""Observatory clock-correction files: parsing, interpolation, registry.

Equivalent of the reference's `src/pint/observatory/clock_file.py` (906 LoC)
and `global_clock_corrections.py`.  Differences forced by this environment:

* **No network.**  The reference auto-downloads the IPTA clock-correction
  repository; here corrections are resolved from local directories only
  (``$PINT_TPU_CLOCK_DIR``, ``$TEMPO/clock``, ``$TEMPO2/clock``, CWD).  When a
  file is absent the correction is zero and a single warning is emitted per
  site (policy ``limits='warn'``) or :class:`~pint_tpu.exceptions.
  ClockCorrectionError` is raised (``limits='error'``).

Formats supported (format behavior matched to the reference parsers,
`clock_file.py:441` tempo2 / `clock_file.py:566` tempo):

* **tempo2**: ``# FROM TO`` header line, then ``mjd  offset_seconds`` rows.
* **tempo**: fixed columns — MJD in cols 0:9, two corrections (µs) in cols
  9:21 / 21:33, site code in col 34; correction = clkcorr2 - clkcorr1; the
  hard-coded tempo quirk ``clkcorr1 -= 818.8 if clkcorr1 > 800`` applies;
  ``INCLUDE`` lines are followed.
"""

from __future__ import annotations

import os
import warnings
from pathlib import Path
from typing import List, Optional

import numpy as np

from pint_tpu.exceptions import (ClockCorrectionError,
                                 ClockCorrectionOutOfRange,
                                 ClockCorrectionWarning)


class ClockFile:
    """MJD -> clock offset [s], linearly interpolated.

    mjd values must be non-decreasing; evaluation outside the span follows
    ``limits``: 'warn' (clamp to end values with a warning) or 'error'.
    """

    def __init__(self, mjd, offset_s, friendly_name="", valid_beyond_ends=False,
                 leading_comment=""):
        self.mjd = np.asarray(mjd, np.float64)
        self.offset = np.asarray(offset_s, np.float64)
        order = np.argsort(self.mjd, kind="stable")
        if not np.array_equal(order, np.arange(len(order))):
            self.mjd, self.offset = self.mjd[order], self.offset[order]
        self.friendly_name = friendly_name
        self.valid_beyond_ends = valid_beyond_ends
        self.leading_comment = leading_comment

    def evaluate(self, mjd, limits="warn"):
        mjd = np.asarray(mjd, np.float64)
        if len(self.mjd) == 0:
            return np.zeros_like(mjd)
        if not self.valid_beyond_ends:
            bad = (mjd < self.mjd[0]) | (mjd > self.mjd[-1])
            if np.any(bad):
                msg = (
                    f"{np.sum(bad)} MJD(s) outside clock file "
                    f"{self.friendly_name} span [{self.mjd[0]}, {self.mjd[-1]}] "
                    f"(last correction at MJD {self.last_correction_mjd:.2f}"
                    " — the clock file may simply be stale; see "
                    "pint_tpu.clockcorr.update_clock_files)"
                )
                if limits == "error":
                    raise ClockCorrectionOutOfRange(msg)
                warnings.warn(msg, ClockCorrectionWarning)
        return np.interp(mjd, self.mjd, self.offset)

    @property
    def last_correction_mjd(self):
        return self.mjd[-1] if len(self.mjd) else -np.inf

    # -- parsers ---------------------------------------------------------------

    @classmethod
    def read(cls, filename, fmt="tempo", **kw):
        if fmt == "tempo":
            return cls.read_tempo(filename, **kw)
        elif fmt == "tempo2":
            return cls.read_tempo2(filename, **kw)
        raise ValueError(f"unknown clock file format {fmt!r}")

    @classmethod
    def read_tempo2(cls, filename, bogus_last_correction=False, valid_beyond_ends=False):
        mjd: List[float] = []
        clk: List[float] = []
        leading = []
        with open(filename) as f:
            header = f.readline()
            if not header.startswith("#"):
                raise ValueError(f"{filename}: tempo2 clock file must start with '# FROM TO' header")
            for line in f:
                if line.startswith("#"):
                    leading.append(line.rstrip())
                    continue
                parts = line.split()
                if len(parts) < 2:
                    continue
                try:
                    m = float(parts[0].replace("D", "E").replace("d", "e"))
                    c = float(parts[1].replace("D", "E").replace("d", "e"))
                except ValueError:
                    continue
                mjd.append(m)
                clk.append(c)
        mjd, clk = _trim(mjd, clk, bogus_last_correction)
        return cls(mjd, clk, friendly_name=str(filename),
                   valid_beyond_ends=valid_beyond_ends,
                   leading_comment="\n".join(leading))

    @classmethod
    def read_tempo(cls, filename, obscode=None, bogus_last_correction=False,
                   process_includes=True, valid_beyond_ends=False):
        mjds: List[float] = []
        clkcorrs: List[float] = []
        with open(filename) as f:
            for line in f:
                if line.startswith("#"):
                    continue
                ls = line.split()
                if ls and (ls[0].upper().startswith("MJD") or ls[0].startswith("=====")):
                    continue  # header furniture
                if ls and ls[0].upper() == "INCLUDE" and process_includes and obscode is not None:
                    inc = cls.read_tempo(Path(filename).parent / ls[1], obscode=obscode)
                    mjds.extend(inc.mjd.tolist())
                    clkcorrs.extend(inc.offset.tolist())
                    continue
                try:
                    mjd = float(line[:9])
                    if (mjd < 39000 and mjd != 0) or mjd > 100000:
                        mjd = None
                except (ValueError, IndexError):
                    mjd = None
                try:
                    c1 = float(line[9:21])
                except (ValueError, IndexError):
                    c1 = None
                try:
                    c2 = float(line[21:33])
                except (ValueError, IndexError):
                    c2 = None
                try:
                    csite = line[34].lower()
                except IndexError:
                    csite = None
                if obscode is not None and csite != obscode.lower():
                    continue
                if mjd is None or (c1 is None and c2 is None):
                    continue
                c1 = c1 or 0.0
                c2 = c2 or 0.0
                if c1 > 800.0:  # hard-coded tempo convention
                    c1 -= 818.8
                mjds.append(mjd)
                clkcorrs.append((c2 - c1) * 1e-6)  # µs -> s
        mjds, clkcorrs = _trim(mjds, clkcorrs, bogus_last_correction)
        return cls(mjds, clkcorrs, friendly_name=str(filename),
                   valid_beyond_ends=valid_beyond_ends)

    # -- writers (round-trip support, cf. reference `ClockFile.write_tempo2_clock_file`) --

    def write_tempo2(self, filename, hdrline="# UTC(obs) UTC"):
        with open(filename, "w") as f:
            print(hdrline, file=f)
            for m, c in zip(self.mjd, self.offset):
                print(f"{m:.5f} {c:.12e}", file=f)

    def write_tempo(self, filename, obscode="1"):
        with open(filename, "w") as f:
            print("   MJD       EECO-REF    NIST-REF NS      DATE    COMMENTS", file=f)
            print("=========    ========    ======== ==    ========  ========", file=f)
            for m, c in zip(self.mjd, self.offset):
                f.write(f"{m:9.2f}{0.0:12.3f}{c * 1e6:12.3f} {obscode}\n")

    def merge(self, other: "ClockFile") -> "ClockFile":
        mjd = np.concatenate([self.mjd, other.mjd])
        off = np.concatenate([self.offset, other.offset])
        return ClockFile(mjd, off, friendly_name=f"{self.friendly_name}+{other.friendly_name}")


def _trim(mjd, clk, bogus_last):
    if bogus_last and len(mjd):
        mjd, clk = mjd[:-1], clk[:-1]
    while len(mjd) and mjd[0] == 0:
        mjd, clk = mjd[1:], clk[1:]
    return mjd, clk


# --- registry / search --------------------------------------------------------

_warned: set = set()
_cache: dict = {}


def clock_search_dirs() -> List[str]:
    from pint_tpu.clockcorr import clock_cache_dir

    dirs = []
    for env, sub in (("PINT_TPU_CLOCK_DIR", ""),
                     ("PINT_CLOCK_OVERRIDE", "")):
        v = os.environ.get(env)
        if v:
            dirs.append(v)
    # the global-repository download cache (pint_tpu.clockcorr) comes
    # BEFORE any TEMPO/TEMPO2 install dirs: freshly downloaded IPTA
    # corrections must not be shadowed by a stale env installation
    # (explicit PINT_TPU_CLOCK_DIR/PINT_CLOCK_OVERRIDE still win above)
    cache = clock_cache_dir()
    if cache not in dirs:
        dirs.append(cache)
    for env, sub in (("TEMPO2", "clock"), ("TEMPO", "clock")):
        v = os.environ.get(env)
        if v:
            dirs.append(os.path.join(v, sub))
    dirs.append(os.path.join(os.path.dirname(__file__), "data", "clock"))
    dirs.append(os.getcwd())
    return dirs


def reset_cache() -> None:
    """Forget cached clock-file lookups (including cached MISSES) and
    one-time warnings — called by `pint_tpu.clockcorr.update_clock_files`
    so fresh downloads are picked up within the same process."""
    _cache.clear()
    _warned.clear()


def find_clock_file(name: str, fmt="tempo", obscode=None, limits="warn",
                    bogus_last_correction=False) -> Optional[ClockFile]:
    """Locate and parse a clock file by bare name (e.g. ``time_gbt.dat``).

    Returns None (with a one-time warning) when unavailable and
    ``limits='warn'``; raises ClockCorrectionError when ``limits='error'``.
    A cached miss is re-judged against the *current* call's ``limits`` so a
    strict caller still gets the exception.
    """
    key = (name, fmt, obscode, bogus_last_correction)
    if key not in _cache:
        cf = None
        for d in clock_search_dirs():
            p = os.path.join(d, name)
            if os.path.isfile(p):
                if fmt == "tempo":
                    cf = ClockFile.read(p, fmt=fmt, obscode=obscode,
                                        bogus_last_correction=bogus_last_correction)
                else:
                    cf = ClockFile.read(p, fmt=fmt,
                                        bogus_last_correction=bogus_last_correction)
                break
        _cache[key] = cf
    cf = _cache[key]
    if cf is None:
        msg = (f"Clock file {name!r} not found in {clock_search_dirs()} — "
               f"run pint_tpu.clockcorr.update_clock_files() where the "
               f"IPTA repository is reachable (this environment has no "
               f"network); corrections treated as 0.")
        if limits == "error":
            raise ClockCorrectionError(msg)
        if name not in _warned:
            warnings.warn(msg, ClockCorrectionWarning)
            _warned.add(name)
    return cf


def gps_to_utc_correction(mjd_utc, limits="warn"):
    """GPS->UTC clock correction [s] (reference applies ``gps2utc.clk``).

    GPS time = TAI - 19 s by construction; UTC(GPS) realization differs from
    UTC by <10 ns (the downloaded file contains those residuals).  Without the
    file the correction is ~0 and we return zeros.
    """
    cf = find_clock_file("gps2utc.clk", fmt="tempo2", limits="warn")
    if cf is None:
        return np.zeros_like(np.asarray(mjd_utc, np.float64))
    return cf.evaluate(mjd_utc, limits=limits)


def bipm_correction(mjd_utc, version="BIPM2021", limits="warn"):
    """TT(BIPMxxxx) - TT(TAI) correction [s] from a tai2tt_bipmXXXX.clk file."""
    cf = find_clock_file(f"tai2tt_{version.lower()}.clk", fmt="tempo2", limits="warn")
    if cf is None:
        return np.zeros_like(np.asarray(mjd_utc, np.float64))
    return cf.evaluate(mjd_utc, limits=limits)
