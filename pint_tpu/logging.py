"""Logging setup with repeated-message suppression.

Reference: `pint.logging` (`/root/reference/src/pint/logging.py`, 372 LoC
of loguru configuration): its load-bearing behaviors are (a) one-line
opt-in setup with a level, (b) de-duplication of repeated warnings, and
(c) rerouting python ``warnings`` through the logger.  This module
provides the same three on the standard library logger — no third-party
logging dependency.
"""

from __future__ import annotations

import logging as _logging
import warnings as _warnings
from typing import Optional

__all__ = ["setup", "log", "child", "DedupFilter"]

log = _logging.getLogger("pint_tpu")


def child(name: str) -> _logging.Logger:
    """A namespaced child of the package logger (``pint_tpu.<name>``):
    subsystem modules (``runtime``, ``multihost``) log through it so
    records carry their origin while riding the one configured handler
    and its :class:`DedupFilter`."""
    return log.getChild(name)


class DedupFilter(_logging.Filter):
    """Drop messages already emitted (reference `LogFilter`,
    `/root/reference/src/pint/logging.py:192`): each distinct message
    text is shown at most ``max_repeats`` times."""

    def __init__(self, max_repeats: int = 1):
        super().__init__()
        self.max_repeats = max_repeats
        self._seen: dict = {}

    def filter(self, record: _logging.LogRecord) -> bool:
        key = (record.levelno, record.getMessage())
        n = self._seen.get(key, 0)
        self._seen[key] = n + 1
        return n < self.max_repeats

    def reset(self):
        self._seen.clear()


_state = {"handler": None, "filter": None, "showwarning": None}


def setup(level: str = "INFO", dedup: bool = True,
          capture_warnings: bool = True,
          stream=None) -> Optional[DedupFilter]:
    """Configure the ``pint_tpu`` logger (reference `pint.logging.setup`,
    `/root/reference/src/pint/logging.py:247`): attach one stream
    handler at ``level``, optionally de-duplicate repeats and reroute
    ``warnings.warn`` through the logger.  Idempotent."""
    if _state["handler"] is not None:
        log.removeHandler(_state["handler"])
    handler = _logging.StreamHandler(stream)
    handler.setFormatter(_logging.Formatter(
        "%(levelname)s (%(name)s): %(message)s"))
    filt = None
    if dedup:
        filt = DedupFilter()
        handler.addFilter(filt)
    log.addHandler(handler)
    log.setLevel(level.upper())
    _state["handler"], _state["filter"] = handler, filt

    if capture_warnings:
        if _state["showwarning"] is None:
            _state["showwarning"] = _warnings.showwarning

        def showwarning(message, category, filename, lineno, file=None,
                        line=None):
            log.warning("%s: %s", category.__name__, message)

        _warnings.showwarning = showwarning
    elif _state["showwarning"] is not None:
        _warnings.showwarning = _state["showwarning"]
        _state["showwarning"] = None
    return filt
