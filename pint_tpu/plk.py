"""Interactive plk-style residual workbench on bare matplotlib.

Reference: `pintk`'s plk panel (`/root/reference/src/pint/pintk/plk.py`,
a 1.8k-LoC Tkinter embedding).  This re-architecture drops the Tk layer
entirely and drives a plain matplotlib Figure with event handlers, which
buys two things the reference design cannot offer here:

* it runs on ANY matplotlib backend — an interactive desktop backend
  gives the click-select/fit/undo workflow of tempo2's plk, while the
  Agg backend gives the same object headlessly (plots to files); and
* it is fully TESTABLE without a display: the test suite synthesizes
  matplotlib button/key events against an Agg canvas and asserts the
  state machine (`tests/test_plk.py`) — the reference's GUI logic has
  no headless coverage at all.

Workflow (keys mirror plk's):

=========  ========================================================
click      select nearest TOA (shift-click adds to the selection)
drag       rubber-band a time range into the selection
``f``      fit the current (non-deleted) TOAs, replot post-fit
``u``      undo the last fit/delete (full model + state restore)
``d``      delete the selected TOAs (excluded from later fits)
``c``      clear the selection
``r``      reset everything (model, deletions, selection)
``w``      write ``plk.par`` (post-fit model)
``m``      cycle the color mode (default/freq/obs/name/jump)
=========  ========================================================

The surrounding pintk workbench (reference `pintk/paredit.py`,
`timedit.py`, `colormodes.py`) maps to:

* :class:`ParEditor` / :class:`TimEditor` — text-level par/tim editing
  bound to the panel: edit ``.text``, ``apply()`` rebuilds the model /
  TOAs in place (undoable), ``reset()`` discards edits, ``write()``
  saves.  No Tk text widget — any editor (or test) manipulates the
  ``text`` attribute directly.
* ``set_color_mode(mode)`` — color residuals by frequency band,
  observatory, ``-name`` flag group, or JUMP assignment, with a legend
  (reference `colormodes.py`'s Default/Freq/Obs/Name/Jump modes).

The scripted entry point is ``tpintk --gui``; library use::

    from pint_tpu.plk import PlkPanel
    panel = PlkPanel(parfile, timfile)
    panel.show()        # interactive backends; omit under Agg
"""

from __future__ import annotations

import warnings
from typing import List, Optional

import numpy as np

__all__ = ["PlkPanel", "ParEditor", "TimEditor", "run_auto_fit"]

#: categorical palette for the non-default color modes (distinct at
#: small marker sizes on white)
_PALETTE = ("#46769c", "#c25b4e", "#5d9e63", "#8d6cab", "#c2903e",
            "#4ea5b5", "#a84f79", "#7a7a32", "#5565c2", "#b0553a")


def run_auto_fit(toas, model, maxiter=None):
    """Auto-fitter run + the standard status line — the ONE fit path
    shared by the plk panel and the tpintk REPL."""
    from pint_tpu.fitter import Fitter

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fitter = Fitter.auto(toas, model)
        kw = {"maxiter": maxiter} if maxiter else {}
        chi2 = fitter.fit_toas(**kw)
    r = fitter.resids
    msg = (f"{type(fitter).__name__}: chi2={chi2:.2f} dof={r.dof} "
           f"rms={r.rms_weighted() * 1e6:.3f} us")
    return fitter, msg


class PlkPanel:
    """plk state machine bound to a matplotlib figure."""

    def __init__(self, parfile: str, timfile: str, fig=None):
        import matplotlib.pyplot as plt

        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals
        from pint_tpu.toa import get_TOAs

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            self.model = get_model(parfile)
            self.toas = get_TOAs(timfile, model=self.model)
        self.parfile = parfile
        n = self.toas.ntoas
        self.selected = np.zeros(n, bool)
        self.deleted = np.zeros(n, bool)
        self.fitter = None
        self.postfit: Optional[np.ndarray] = None
        #: undo stack of (par-values snapshot, deleted mask, postfit)
        self._undo: List[tuple] = []
        self.message = ""
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            self.prefit = Residuals(self.toas, self.model)
        self.mjds = np.asarray(self.prefit.batch.tdbld)
        self.errs_us = np.asarray(self.prefit.get_data_error())
        self.fig = fig if fig is not None else plt.figure(figsize=(10, 6))
        self.ax = self.fig.add_subplot(111)
        self._press_px = None
        # our key bindings ('f','r','c',...) collide with matplotlib's
        # default navigation keymap (fullscreen/home/back) on
        # interactive backends; detach the default handler
        try:
            mgr = self.fig.canvas.manager
            if mgr is not None and getattr(mgr, "key_press_handler_id",
                                           None) is not None:
                self.fig.canvas.mpl_disconnect(mgr.key_press_handler_id)
        except Exception:
            pass
        self.color_mode = "default"
        self.fig.canvas.mpl_connect("button_press_event", self._on_press)
        self.fig.canvas.mpl_connect("button_release_event",
                                    self._on_release)
        self.fig.canvas.mpl_connect("key_press_event", self._on_key)
        self.replot()

    # -- workbench editors -------------------------------------------------
    @property
    def paredit(self) -> "ParEditor":
        """The par editor bound to this panel (created on first use)."""
        if getattr(self, "_paredit", None) is None:
            self._paredit = ParEditor(self)
        return self._paredit

    @property
    def timedit(self) -> "TimEditor":
        """The tim editor bound to this panel (created on first use)."""
        if getattr(self, "_timedit", None) is None:
            self._timedit = TimEditor(self)
        return self._timedit

    def set_model(self, model):
        """Replace the timing model (ParEditor.apply): recompute the
        pre-fit residuals, keep deletions/selection, drop post-fit
        state.  Undoable — but via the EDITOR's revert (the undo stack
        snapshots parameter VALUES of the live model object, which a
        model swap replaces wholesale)."""
        from pint_tpu.residuals import Residuals

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            self.prefit = Residuals(self.toas, model)
        self.model = model
        # error bars are MODEL-scaled (EFAC/EQUAD); refresh with the
        # new model or the plot shows stale uncertainties
        self.errs_us = np.asarray(self.prefit.get_data_error())
        self._undo.clear()
        self.postfit = None
        self.fitter = None
        self.message = "model replaced (par edit)"
        self.replot()

    def set_toas(self, toas):
        """Replace the TOAs (TimEditor.apply): per-TOA state resets.
        Residuals are computed BEFORE any panel state is touched, so a
        failure leaves the panel fully consistent."""
        from pint_tpu.residuals import Residuals

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            prefit = Residuals(toas, self.model)
        self.toas = toas
        self.prefit = prefit
        n = toas.ntoas
        self.selected = np.zeros(n, bool)
        self.deleted = np.zeros(n, bool)
        self.mjds = np.asarray(self.prefit.batch.tdbld)
        self.errs_us = np.asarray(self.prefit.get_data_error())
        self._undo.clear()
        self.postfit = None
        self.fitter = None
        self.message = "TOAs replaced (tim edit)"
        self.replot()

    # -- color modes -------------------------------------------------------
    #: cycle order for the 'm' key (reference colormodes.py mode set)
    COLOR_MODES = ("default", "freq", "obs", "name", "jump")

    def set_color_mode(self, mode: str):
        if mode not in self.COLOR_MODES:
            raise ValueError(f"unknown color mode {mode!r}; pick from "
                             f"{self.COLOR_MODES}")
        self.color_mode = mode
        self.message = f"color mode: {mode}"
        self.replot()

    def _color_groups(self):
        """``(labels_per_toa, {label: color})`` for the current mode;
        None in default mode."""
        n = self.toas.ntoas
        mode = self.color_mode
        if mode == "default":
            return None, None
        if mode == "freq":
            # the reference's fixed bands (colormodes.py FreqMode)
            f = np.asarray(self.prefit.batch.freq_mhz)
            edges = [(0.0, 300.0, "<300 MHz"), (300.0, 400.0, "300-400"),
                     (400.0, 500.0, "400-500"), (500.0, 700.0, "500-700"),
                     (700.0, 1000.0, "700-1000"),
                     (1000.0, 1800.0, "1000-1800"),
                     (1800.0, 3000.0, "1800-3000"),
                     (3000.0, np.inf, ">3000")]
            labels = np.empty(n, object)
            for lo, hi, lab in edges:
                labels[(f >= lo) & (f < hi)] = lab
            labels[~np.isfinite(f)] = "inf"
            order = [lab for _, _, lab in edges] + ["inf"]
            uniq = [lab for lab in order if (labels == lab).any()]
            cmap = {lab: _PALETTE[i % len(_PALETTE)]
                    for i, lab in enumerate(uniq)}
            return labels, cmap
        elif mode == "obs":
            labels = np.asarray([str(o) for o in self.toas.obs],
                                object)
        elif mode == "name":
            labels = np.asarray(
                [fl.get("name", fl.get("f", "?"))
                 for fl in self.toas.flags], object)
        elif mode == "jump":
            labels = np.full(n, "no jump", object)
            from pint_tpu.models.parameter import MaskParam

            for nm in self.model.params:
                par = self.model[nm]
                if isinstance(par, MaskParam) and nm.startswith("JUMP"):
                    m = par.select_mask(self.toas)
                    labels[np.asarray(m)] = nm
        uniq = sorted(set(labels))
        cmap = {lab: _PALETTE[i % len(_PALETTE)]
                for i, lab in enumerate(uniq)}
        return labels, cmap

    # -- state snapshots ---------------------------------------------------
    def _snapshot(self):
        vals = {n: (self.model[n].value, self.model[n].uncertainty)
                for n in self.model.params
                if self.model[n].value is not None}
        self._undo.append((vals, self.deleted.copy(),
                           None if self.postfit is None
                           else self.postfit.copy()))

    def _restore(self, vals):
        for n, (v, u) in vals.items():
            try:
                self.model[n].value = v
                self.model[n].uncertainty = u
            except Exception:
                pass

    def undo(self):
        """Restore the state before the last fit/delete (plk 'u')."""
        if not self._undo:
            self.message = "nothing to undo"
            return
        vals, deleted, postfit = self._undo.pop()
        self._restore(vals)
        self.deleted = deleted
        self.postfit = postfit
        self.fitter = None
        self.message = "undone"
        self.replot()

    # -- actions -----------------------------------------------------------
    def fit(self, maxiter: Optional[int] = None):
        """Fit the non-deleted TOAs (plk 'f')."""
        keep = ~self.deleted
        if not keep.any():
            self.message = "no TOAs left to fit"
            self.replot()
            return
        self._snapshot()
        toas = self.toas.select(keep) if self.deleted.any() else self.toas
        try:
            self.fitter, self.message = run_auto_fit(toas, self.model,
                                                     maxiter)
        except Exception as e:
            self._undo.pop()   # a failed fit must not leave an entry
            self.message = f"fit failed: {type(e).__name__}: {e}"
            self.replot()
            return
        full = np.full(self.toas.ntoas, np.nan)
        full[keep] = np.asarray(self.fitter.resids.time_resids)
        self.postfit = full
        self.replot()

    def delete_selected(self):
        """Remove the selected TOAs from subsequent fits (plk 'd')."""
        if not self.selected.any():
            self.message = "nothing selected"
            return
        self._snapshot()
        self.deleted |= self.selected
        self.selected[:] = False
        self.message = f"{int(self.deleted.sum())} TOA(s) deleted"
        self.replot()

    def clear_selection(self):
        self.selected[:] = False
        self.message = "selection cleared"
        self.replot()

    def reset(self):
        """Back to the loaded par/tim (plk 'r')."""
        if self._undo:
            vals, _, _ = self._undo[0]  # oldest snapshot = loaded state
            self._undo.clear()
            self._restore(vals)
        self.deleted[:] = False
        self.selected[:] = False
        self.postfit = None
        self.fitter = None
        self.message = "reset"
        self.replot()

    def write_par(self, path: str = "plk.par") -> str:
        self.model.write_parfile(path)
        self.message = f"wrote {path}"
        return path

    # -- event handlers ----------------------------------------------------
    def _nav_active(self):
        """True while a toolbar tool (pan/zoom) owns the mouse."""
        tb = getattr(self.fig.canvas, "toolbar", None)
        return bool(tb is not None and getattr(tb, "mode", ""))

    def _on_press(self, event):
        from matplotlib.backend_bases import MouseButton

        if (event.inaxes is not self.ax or event.xdata is None
                or self._nav_active()
                or event.button != MouseButton.LEFT):
            return
        self._press_px = (event.x, event.xdata)

    def _on_release(self, event):
        if self._press_px is None or event.xdata is None \
                or self._nav_active():
            self._press_px = None
            return
        px0, x0 = self._press_px
        x1 = event.xdata
        self._press_px = None
        add = bool(getattr(event, "key", None) == "shift")
        if abs(event.x - px0) > 5:  # drag beyond click jitter [pixels]
            lo, hi = sorted((x0, x1))
            sel = (self.mjds >= lo) & (self.mjds <= hi) & ~self.deleted
            self.selected = (self.selected | sel) if add else sel
            self.message = f"{int(self.selected.sum())} TOA(s) selected"
        else:  # click: nearest TOA in DISPLAY space (co-epoch TOAs at
            # different residuals must be individually pickable)
            alive = ~self.deleted
            if not alive.any():
                return
            r_us, _ = self._current_resids_us()
            pts = self.ax.transData.transform(
                np.column_stack([self.mjds[alive],
                                 np.nan_to_num(r_us[alive])]))
            d2 = (pts[:, 0] - event.x) ** 2 + (pts[:, 1] - event.y) ** 2
            i = int(np.flatnonzero(alive)[np.argmin(d2)])
            if not add:
                self.selected[:] = False
            self.selected[i] = ~self.selected[i] if add else True
            self.message = f"TOA {i} @ MJD {self.mjds[i]:.4f}"
        self.replot()

    def _on_key(self, event):
        key = (event.key or "").lower()
        if key == "f":
            self.fit()
        elif key == "u":
            self.undo()
        elif key == "d":
            self.delete_selected()
        elif key == "c":
            self.clear_selection()
        elif key == "r":
            self.reset()
        elif key == "w":
            self.write_par()
            self.replot()
        elif key == "m":
            i = self.COLOR_MODES.index(self.color_mode)
            self.set_color_mode(
                self.COLOR_MODES[(i + 1) % len(self.COLOR_MODES)])

    # -- drawing -----------------------------------------------------------
    def _current_resids_us(self):
        if self.postfit is not None:
            return self.postfit * 1e6, "post-fit"
        return np.asarray(self.prefit.time_resids) * 1e6, "pre-fit"

    def replot(self):
        r_us, label = self._current_resids_us()
        ax = self.ax
        ax.clear()
        alive = ~self.deleted
        labels, cmap = self._color_groups()
        if labels is None:
            ax.errorbar(self.mjds[alive], r_us[alive],
                        yerr=self.errs_us[alive], fmt=".", ms=4, lw=0.7,
                        color="#46769c", ecolor="#b8c8d8", zorder=2)
        else:
            for lab, color in cmap.items():
                s = alive & (labels == lab)
                if not s.any():
                    continue
                ax.errorbar(self.mjds[s], r_us[s],
                            yerr=self.errs_us[s], fmt=".", ms=4,
                            lw=0.7, color=color, ecolor="#c8c8c8",
                            zorder=2, label=str(lab))
            ax.legend(loc="best", fontsize=7, ncol=2,
                      title=self.color_mode, title_fontsize=7)
        if self.selected.any():
            s = self.selected & alive
            ax.plot(self.mjds[s], r_us[s], "o", ms=7, mfc="none",
                    mec="#c25b4e", mew=1.5, zorder=3)
        ax.axhline(0.0, color="0.75", lw=0.8, zorder=1)
        ax.set_xlabel("MJD (TDB)")
        ax.set_ylabel(f"{label} residual [us]")
        psr = getattr(self.model, "PSR", None)
        name = psr.value if psr is not None and psr.value else "pulsar"
        ax.set_title(f"{name} — {label}   {self.message}", fontsize=10)
        self.fig.canvas.draw_idle()

    def show(self):  # pragma: no cover - needs an interactive backend
        import matplotlib.pyplot as plt

        plt.show()


class ParEditor:
    """Text-level par editing bound to a :class:`PlkPanel` (reference
    `pintk/paredit.py`'s ParWidget, minus the Tk text box: ``text`` IS
    the editor buffer).

    Workflow: read/modify ``.text`` -> :meth:`apply` (rebuild the model
    and the panel's pre-fit residuals; a bad par is rejected with the
    error in ``panel.message`` — the edited text stays in the buffer
    for fixing) -> fit/undo on the panel as usual -> :meth:`write`.  :meth:`reset` re-serializes the panel's CURRENT
    model (discarding unapplied edits); :meth:`reload` goes back to the
    par file loaded on disk."""

    def __init__(self, panel: PlkPanel):
        self.panel = panel
        self.text = panel.model.as_parfile()

    def reset(self):
        """Discard unapplied edits (reference ParActionsWidget
        'remove changes')."""
        self.text = self.panel.model.as_parfile()

    def reload(self):
        """Back to the on-disk par file (reference 'reset par file')."""
        with open(self.panel.parfile) as fh:
            self.text = fh.read()

    def apply(self) -> bool:
        """Build a model from ``text`` and install it in the panel;
        returns False (panel message set, text kept) when the par does
        not parse."""
        from pint_tpu.models import get_model

        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                model = get_model(self.text.splitlines())
            # get_model is lenient (unknown lines warn-and-drop), so
            # a garbage buffer can yield a componentless model; the
            # panel needs at least a spin model to compute phases
            if "Spindown" not in model.components:
                raise ValueError(
                    "parsed model has no spin component (F0 missing "
                    "or unparseable par text)")
        except Exception as e:
            self.panel.message = (f"par edit rejected: "
                                  f"{type(e).__name__}: {e}")
            self.panel.replot()
            return False
        self.panel.set_model(model)
        return True

    def write(self, path: str = "edited.par") -> str:
        with open(path, "w") as fh:
            fh.write(self.text)
        self.panel.message = f"wrote {path}"
        return path


class TimEditor:
    """Text-level tim editing bound to a :class:`PlkPanel` (reference
    `pintk/timedit.py`'s TimWidget).  ``apply()`` re-runs the full TOA
    preparation pipeline on the edited text."""

    def __init__(self, panel: PlkPanel):
        self.panel = panel
        self.text = self._read_tim()

    def _read_tim(self) -> str:
        fn = self.panel.toas.filename
        if not isinstance(fn, str):
            raise ValueError(
                "these TOAs carry no tim-file path (built from arrays "
                "or a non-string source); TimEditor needs a loaded tim")
        with open(fn) as fh:
            return fh.read()

    def reset(self):
        """Discard unapplied edits: re-read the panel's loaded tim."""
        self.text = self._read_tim()

    def apply(self) -> bool:
        """Parse ``text`` as a tim file and install the TOAs; returns
        False (message set, panel untouched) on a parse/prepare
        error."""
        import os
        import tempfile

        from pint_tpu.toa import get_TOAs

        fd, tmp = tempfile.mkstemp(suffix=".tim")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(self.text)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                toas = get_TOAs(tmp, model=self.panel.model)
            toas.filename = self.panel.toas.filename
            # set_toas computes residuals before touching panel state,
            # so a model/TOA mismatch rejects cleanly too
            self.panel.set_toas(toas)
        except Exception as e:
            self.panel.message = (f"tim edit rejected: "
                                  f"{type(e).__name__}: {e}")
            self.panel.replot()
            return False
        finally:
            os.unlink(tmp)
        return True

    def write(self, path: str = "edited.tim") -> str:
        with open(path, "w") as fh:
            fh.write(self.text)
        self.panel.message = f"wrote {path}"
        return path
