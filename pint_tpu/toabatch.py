"""Device-resident TOA data: a frozen struct-of-arrays pytree.

The reference keeps TOAs in an ``astropy.table.Table`` with per-row object
columns (`/root/reference/src/pint/toa.py:1184,1228-1283`).  That layout is
hostile to XLA: ragged flags, python objects, longdouble columns.  Here the
TOA data that the *compute core* needs is a frozen pytree of dense f64/i64
arrays, staged to HBM once per dataset and closed over by jitted residual /
design-matrix / fit kernels.

Everything host-side (flags, observatory names, selection, merging, clock
bookkeeping) lives in :mod:`pint_tpu.toa`; this module is the device contract.

Unit conventions (documented once, used everywhere):

* times: TDB MJD as ``(day:int64, frac:float64)`` two-part values with
  ``|frac| <= 0.5`` — the double-double expansion of the absolute MJD.
* positions: light-seconds; velocities: dimensionless (v/c).
* frequencies: MHz (inf = infinite frequency / barycentered data).
* uncertainties: microseconds.
"""

from __future__ import annotations

import os
import warnings
from typing import Dict, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from pint_tpu import precision
from pint_tpu.exceptions import InvalidTOAs, PintTpuWarning


class ValidationWarning(PintTpuWarning):
    """TOA batch validation found invalid rows and the active policy
    ("mask"/"warn") handled them without raising."""


#: the three user-facing input-validation policies (see
#: :func:`make_batch`); "off" additionally exists for INTERNAL trusted
#: reference batches (the 1-row TZR batch carries a deliberate zero
#: uncertainty — it is a phase reference, not a measurement)
VALIDATE_POLICIES = ("raise", "mask", "warn", "off")

#: explicit downweight sentinel [us] for invalid rows under
#: policy="warn": weight ratio (1e12/1us)^2 = 1e24 makes the row
#: chi2/fit-neutral while staying inside TPU's emulated-f64 exponent
#: range (inf is NOT used — 0*inf from mask arithmetic would turn a
#: downweight into a NaN); same sentinel as `parallel.pad_batch`
DOWNWEIGHT_ERROR_US = 1e12


def resolve_validate_policy(policy: Optional[str]) -> str:
    """``policy`` if given, else $PINT_TPU_VALIDATE, else "raise" —
    invalid inputs fail loudly by default (see MIGRATION.md)."""
    if policy is None:
        policy = os.environ.get("PINT_TPU_VALIDATE", "raise")
    if policy not in VALIDATE_POLICIES:
        raise ValueError(
            f"validation policy must be one of {VALIDATE_POLICIES}, "
            f"got {policy!r}")
    return policy


def split_f64_words(x: np.ndarray, nwords: int = 3) -> np.ndarray:
    """Exact host-side split of true-IEEE float64 values into ``nwords``
    non-overlapping float32 words (last axis).  sum(words) == x to 24*nwords
    bits."""
    x = np.asarray(x, np.float64)
    out = np.zeros(x.shape + (nwords,), np.float32)
    r = x.copy()
    for k in range(nwords):
        w = r.astype(np.float32)
        out[..., k] = w
        r = r - w.astype(np.float64)
    return out


class TOABatch(NamedTuple):
    """Struct-of-arrays TOA data for the jitted compute core.

    Replaces the table columns built by the reference's
    ``TOAs.compute_TDBs`` / ``compute_posvels``
    (`/root/reference/src/pint/toa.py:2262,2334`).
    """

    #: TDB epoch at the observatory, integer MJD part, shape (N,)
    tdb_day: jnp.ndarray
    #: TDB epoch fractional MJD part (|frac| <= 0.5), shape (N,)
    tdb_frac: jnp.ndarray
    #: exact 3-word float32 decomposition of tdb_frac (w0+w1+w2 == frac to
    #: 2^-72), shape (N, 3).  Host-precomputed because on-device f64→f32
    #: splitting cannot be trusted under TPU's emulated (~48-bit) float64;
    #: the quad-single phase kernels (pint_tpu.qs) consume these words.
    tdb_frac_w: jnp.ndarray
    #: TOA uncertainty [us], shape (N,)
    error_us: jnp.ndarray
    #: observing frequency [MHz] (inf for barycentric/infinite), shape (N,)
    freq_mhz: jnp.ndarray
    #: observatory position wrt SSB [light-s], shape (N, 3)
    ssb_obs_pos_ls: jnp.ndarray
    #: observatory velocity wrt SSB [v/c, dimensionless], shape (N, 3)
    ssb_obs_vel_c: jnp.ndarray
    #: Sun position wrt observatory [light-s], shape (N, 3)
    obs_sun_pos_ls: jnp.ndarray
    #: tracked absolute pulse numbers (nan where absent), shape (N,)
    pulse_number: jnp.ndarray
    #: planet positions wrt observatory [light-s], each shape (N, 3);
    #: keys among {"jupiter","saturn","venus","uranus","neptune","mercury","mars","moon"}
    obs_planet_pos_ls: Dict[str, jnp.ndarray]

    # NOTE: no __len__ override — TOABatch is a NamedTuple and len() must
    # keep tuple semantics (10 fields): _replace()/_make() and pytree
    # machinery check it.  Row count is .ntoas.
    @property
    def ntoas(self) -> int:
        return self.tdb_day.shape[0]

    @property
    def tdbld(self) -> jnp.ndarray:
        """Lossy float64 TDB MJD (for quantities insensitive to ns)."""
        return self.tdb_day + self.tdb_frac

    def select(self, mask) -> "TOABatch":
        """Row-subset along the TOA axis (host-side convenience)."""
        mask = np.asarray(mask)
        return TOABatch(
            tdb_day=self.tdb_day[mask],
            tdb_frac=self.tdb_frac[mask],
            tdb_frac_w=self.tdb_frac_w[mask],
            error_us=self.error_us[mask],
            freq_mhz=self.freq_mhz[mask],
            ssb_obs_pos_ls=self.ssb_obs_pos_ls[mask],
            ssb_obs_vel_c=self.ssb_obs_vel_c[mask],
            obs_sun_pos_ls=self.obs_sun_pos_ls[mask],
            pulse_number=self.pulse_number[mask],
            obs_planet_pos_ls={k: v[mask] for k, v in self.obs_planet_pos_ls.items()},
        )


def _validate_rows(day_f, frac64, error, policy):
    """The input-validation policy (ISSUE 3 leg 4): non-finite/zero/
    negative uncertainties and non-finite MJDs, judged BEFORE anything
    reaches the device — inside a jitted program a NaN sigma is
    unobservable until it has already poisoned chi2.  Returns
    ``(keep_mask_or_None, error, day_f, frac64)``: under "mask" the
    caller drops ``~keep`` rows; under "warn" the bad rows come back
    neutralized (finite MJD, DOWNWEIGHT_ERROR_US) with a warning —
    the explicit replacement for the silent ``np.where(..., inf)``
    downweighting this policy supersedes."""
    bad_sigma = ~np.isfinite(error) | (error <= 0.0)
    bad_mjd = ~(np.isfinite(day_f) & np.isfinite(frac64))
    bad = bad_sigma | bad_mjd
    if not bad.any():
        return None, error, day_f, frac64
    msg = (f"invalid TOA rows: {int(bad_sigma.sum())} non-finite/"
           f"nonpositive uncertainties, {int(bad_mjd.sum())} non-finite "
           f"MJDs (of {len(bad)} TOAs)")
    if policy == "raise":
        raise InvalidTOAs(
            msg + '; use policy="mask" to drop them or policy="warn" '
            "to downweight them")
    if policy == "mask":
        warnings.warn(msg + f"; masking {int(bad.sum())} TOA(s)",
                      ValidationWarning)
        return ~bad, error, day_f, frac64
    warnings.warn(
        msg + f"; downweighting {int(bad.sum())} TOA(s) to "
        f"error={DOWNWEIGHT_ERROR_US:g} us", ValidationWarning)
    error = np.where(bad, DOWNWEIGHT_ERROR_US, error)
    good_day = day_f[~bad_mjd]
    fill_day = float(good_day[0]) if good_day.size else 50000.0
    day_f = np.where(bad_mjd, fill_day, day_f)
    frac64 = np.where(bad_mjd, 0.0, frac64)
    return None, error, day_f, frac64


def make_batch(
    tdb_day,
    tdb_frac,
    error_us,
    freq_mhz,
    ssb_obs_pos_ls=None,
    ssb_obs_vel_c=None,
    obs_sun_pos_ls=None,
    pulse_number=None,
    obs_planet_pos_ls: Optional[Dict[str, np.ndarray]] = None,
    policy: Optional[str] = None,
) -> TOABatch:
    """Build a TOABatch, filling absent geometry with zeros.

    Zero geometry corresponds to data already at the solar-system barycenter
    (the reference's ``@``/``bat`` observatory,
    `/root/reference/src/pint/observatory/special_locations.py:71`).

    ``policy`` ("raise" | "mask" | "warn"; default $PINT_TPU_VALIDATE ->
    "raise") governs invalid inputs — non-finite/zero/negative
    uncertainties, non-finite MJDs, empty selections: raise
    :class:`~pint_tpu.exceptions.InvalidTOAs`, drop the offending rows,
    or warn and neutralize them (``DOWNWEIGHT_ERROR_US``).  An empty
    selection always raises except under "warn".
    """
    policy = resolve_validate_policy(policy)
    frac64 = np.asarray(tdb_frac, np.float64)
    day_f = np.asarray(tdb_day, np.float64)
    error = np.broadcast_to(
        np.asarray(error_us, np.float64), frac64.shape).copy()
    keep = None
    if policy != "off":
        if frac64.shape[0] == 0:
            if policy == "warn":
                warnings.warn("empty TOA selection (0 rows)",
                              ValidationWarning)
            else:
                raise InvalidTOAs(
                    "empty TOA selection: cannot build a 0-row TOABatch "
                    '(policy="warn" to permit)')
        keep, error, day_f, frac64 = _validate_rows(day_f, frac64, error,
                                                    policy)
    if keep is not None:
        if not keep.any():
            raise InvalidTOAs(
                "every TOA row is invalid; nothing left after masking")
        frac64, day_f, error = frac64[keep], day_f[keep], error[keep]
        freq_mhz = np.asarray(freq_mhz, np.float64)[keep]
        ssb_obs_pos_ls = None if ssb_obs_pos_ls is None else \
            np.asarray(ssb_obs_pos_ls)[keep]
        ssb_obs_vel_c = None if ssb_obs_vel_c is None else \
            np.asarray(ssb_obs_vel_c)[keep]
        obs_sun_pos_ls = None if obs_sun_pos_ls is None else \
            np.asarray(obs_sun_pos_ls)[keep]
        pulse_number = None if pulse_number is None else \
            np.asarray(pulse_number)[keep]
        if obs_planet_pos_ls is not None:
            obs_planet_pos_ls = {k: np.asarray(v)[keep]
                                 for k, v in obs_planet_pos_ls.items()}
    error_us = error
    # staging dtypes follow the active precision policy: f64 by default,
    # f32 under "dd32" where the phase-critical precision rides the
    # exact tdb_frac_w word splits instead of a wide scalar column
    # (requesting f64 with x64 disabled would stage f32 anyway, with a
    # warning per column — dd32 makes the narrow staging explicit)
    fdt = precision.float_dtype()
    idt = jnp.int64 if fdt == jnp.float64 else jnp.int32
    tdb_day = jnp.asarray(np.asarray(day_f, np.int64), dtype=idt)
    tdb_frac = jnp.asarray(frac64, dtype=fdt)
    n = tdb_day.shape[0]
    z3 = jnp.zeros((n, 3), dtype=fdt)

    def _arr(x, default):
        return default if x is None else jnp.asarray(x, dtype=fdt)

    return TOABatch(
        tdb_day=tdb_day,
        tdb_frac=tdb_frac,
        tdb_frac_w=jnp.asarray(split_f64_words(frac64), dtype=jnp.float32),
        error_us=jnp.asarray(error_us, dtype=fdt),
        freq_mhz=jnp.asarray(freq_mhz, dtype=fdt),
        ssb_obs_pos_ls=_arr(ssb_obs_pos_ls, z3),
        ssb_obs_vel_c=_arr(ssb_obs_vel_c, z3),
        obs_sun_pos_ls=_arr(obs_sun_pos_ls, z3),
        pulse_number=_arr(pulse_number, jnp.full((n,), jnp.nan, dtype=fdt)),
        obs_planet_pos_ls=(
            {}
            if obs_planet_pos_ls is None
            else {k: jnp.asarray(v, dtype=fdt)
                  for k, v in obs_planet_pos_ls.items()}
        ),
    )


def pad_batch_to(batch: TOABatch, n: int) -> TOABatch:
    """Pad the TOA axis to EXACTLY ``n`` rows by repeating the last row
    with ``DOWNWEIGHT_ERROR_US`` uncertainty (chi2/fit-neutral, same
    sentinel as the validation policy's downweight and
    ``parallel.pad_batch``'s mesh padding).  The fleet bucket programs
    (:mod:`pint_tpu.fleet`) additionally carry an explicit row mask that
    zeroes padded rows out of the residuals and normal equations, so
    padding there is exact, not just strongly downweighted."""
    if batch.ntoas > n:
        raise ValueError(
            f"cannot pad a {batch.ntoas}-row batch down to {n} rows")
    if batch.ntoas == n:
        return batch
    idx = np.concatenate([np.arange(batch.ntoas),
                          np.full(n - batch.ntoas, batch.ntoas - 1)])
    out = batch.select(idx)
    err = np.asarray(out.error_us).copy()
    err[batch.ntoas:] = DOWNWEIGHT_ERROR_US
    return out._replace(error_us=jnp.asarray(err))


def concatenate(batches) -> TOABatch:
    """Concatenate batches along the TOA axis (planet dicts must agree)."""
    batches = list(batches)
    keys = set(batches[0].obs_planet_pos_ls)
    for b in batches[1:]:
        if set(b.obs_planet_pos_ls) != keys:
            raise ValueError("cannot concatenate TOABatches with differing planet sets")
    cat = jnp.concatenate
    return TOABatch(
        tdb_day=cat([b.tdb_day for b in batches]),
        tdb_frac=cat([b.tdb_frac for b in batches]),
        tdb_frac_w=cat([b.tdb_frac_w for b in batches]),
        error_us=cat([b.error_us for b in batches]),
        freq_mhz=cat([b.freq_mhz for b in batches]),
        ssb_obs_pos_ls=cat([b.ssb_obs_pos_ls for b in batches]),
        ssb_obs_vel_c=cat([b.ssb_obs_vel_c for b in batches]),
        obs_sun_pos_ls=cat([b.obs_sun_pos_ls for b in batches]),
        pulse_number=cat([b.pulse_number for b in batches]),
        obs_planet_pos_ls={k: cat([b.obs_planet_pos_ls[k] for b in batches]) for k in keys},
    )
