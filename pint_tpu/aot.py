"""AOT serving-program store: exported, disk-resident entrypoint programs.

The bench trajectory says compilation — not steady state — is the
wall-clock tax (r03: 0.90 s of fitting behind ~193 s of compile; r04:
0.47 s behind ~33 s).  The persistent XLA compilation cache (PR 6)
removes the *XLA compile* half of that tax, but a fresh process still
pays the full Python trace+lower cost of every entrypoint program —
tens of seconds at NANOGrav width — before the cache can even be
consulted.  A serving process answering requests for many users'
models (PINT's workload per arXiv:2012.00074, and the always-on
Bayesian pipelines Vela.jl targets per arXiv:2412.15858) cannot pay
30–190 s per process.

This module closes the remaining half: hot entrypoint programs are
``jax.export``-serialized to a disk **store** keyed by a
:class:`ProgramKey` fingerprint, and a warm process *deserializes*
instead of tracing.  The two layers compose into a zero-compile warm
start:

* the **AOT store** (here) skips tracing + lowering — a store hit
  rebuilds the program from serialized StableHLO in milliseconds;
* the **persistent compilation cache** (``runtime.
  configure_compile_cache``) skips the XLA compile of the thin
  exported-call wrapper — so a warm process makes **zero**
  ``backend_compile`` calls, asserted via
  :mod:`pint_tpu.lint.tracehooks` and enforced by the contract
  auditor's CONTRACT003 cold-start axis.

**Keying.**  A :class:`ProgramKey` fingerprints everything that
determines program identity: entrypoint name, the abstract in-avals
(shapes/dtypes/pytree structure of the call arguments — i.e. the fleet
bucket shape or the TOA-batch shape), a caller-supplied structural
fingerprint (component set, free-param slots, track mode, and — for
programs that close over TOA data — a CRC of that data, since closure
constants are baked into the exported module), and the backend +
topology.  The jax/XLA version rides the blob *header*, not the key
digest, so a version bump is a detectable *stale* blob (warned,
fallen back from, and overwritten) rather than a silent dead file.

**Loud-but-safe invalidation.**  A stale, corrupt, or
version-mismatched blob NEVER crashes a fit: the load path warns
(:class:`AotStoreWarning`), deletes the bad blob, counts the
invalidation, and falls back to live tracing — which then overwrites
the slot with a fresh, round-trip-verified blob.  Writes are atomic
(write-tmp + ``os.replace``) and CRC32-checksummed, the same
checkpoint discipline as :mod:`pint_tpu.runtime`; a blob is only
written after its deserialized program reproduced the live program's
output.  The store is LRU-bounded (``PINT_TPU_AOT_MAX_ENTRIES`` /
``PINT_TPU_AOT_MAX_MB``).

**Integration.**  Entrypoints wrap their jitted programs with
:func:`serve` (``residuals.build_resid_fn``, the
``fitter.build_whitened_assembly`` internal programs,
``fitter.build_wls_step``, ``fitter.build_fused_fit``, and the
FleetFitter bucket programs); with no store enabled the wrapper is a
two-attribute-lookup passthrough.  Enable the store with
``runtime.acquire_backend(warm_start=True)``, the
``PINT_TPU_WARM_START=1`` / ``PINT_TPU_AOT_STORE=<dir>`` env vars, or
:func:`configure_store`.  Prebuild a deployment's store with::

    python -m pint_tpu.aot warm            # trace, compile, export
    python -m pint_tpu.aot check           # prove 0 compiles, warm
    python -m pint_tpu.aot stats           # list the store

The fleet bucket edges are deterministic
(:func:`pint_tpu.fleet.geometric_bucket_edges`), so bucket programs
are prebuildable: the ``warm`` fixtures include a 4-pulsar ragged
fleet whose two bucket programs serve any same-structure fleet.

Failpoints (:mod:`pint_tpu.faultinject`): ``corrupt_aot_blob``
(truncate|flip) and ``stale_aot_version`` prove the
fallback-to-trace-and-overwrite path fires with a warning.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time
import warnings
import zlib
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from pint_tpu import faultinject, profiling, telemetry
from pint_tpu.exceptions import PintTpuWarning
from pint_tpu.logging import child as _logchild

_log = _logchild("aot")

__all__ = ["AotStoreWarning", "ProgramKey", "ProgramMiss", "ProgramStore",
           "program_key", "args_signature", "serve", "get_store",
           "configure_store", "disable_store", "temporary_store",
           "suspend_writes", "counters", "counters_since", "miss_mark",
           "misses_since", "data_crc", "model_fingerprint",
           "default_store_dir", "warm_fixtures", "run_warm", "run_check",
           "main", "AOT_FORMAT_VERSION"]


class AotStoreWarning(PintTpuWarning):
    """A store blob was stale/corrupt/unusable and the entrypoint fell
    back to live tracing (the store self-heals by overwriting)."""


#: bumped whenever the blob layout (NOT jax's serialization) changes
AOT_FORMAT_VERSION = 1

_MAGIC = b"PTAOT1\n"


# --- keys ---------------------------------------------------------------------

def data_crc(*trees) -> str:
    """CRC32 fingerprint (8 hex) over dtype/shape/bytes of every array
    leaf — the *data* half of a ProgramKey, needed because programs
    that close over a TOABatch bake that data into the exported module
    as constants (same shapes + different TOAs must not share a
    blob)."""
    crc = 0
    import jax

    for leaf in jax.tree_util.tree_leaves(trees):
        a = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(str(a.dtype).encode(), crc)
        crc = zlib.crc32(np.asarray(a.shape, np.int64).tobytes(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def model_fingerprint(model, batch=None, *extra) -> str:
    """Structural fingerprint of a (model, batch) pair for
    :func:`serve`: component set, free-param slots, track/frozen
    structure — plus the batch row count and data CRC when the program
    closes over the batch.  ``extra`` items (maxiter, tolerances,
    kernel names...) are appended verbatim."""
    parts = [
        "comps=" + ",".join(sorted(model.components.keys())),
        "free=" + ",".join(model.free_params),
    ]
    if batch is not None:
        parts.append(f"ntoa={batch.ntoas}")
        parts.append("data=" + data_crc(batch))
    parts.extend(str(e) for e in extra)
    return "|".join(parts)


def args_signature(args) -> str:
    """Abstract in-shapes/dtypes + pytree structure of one positional
    call — the per-call component of a ProgramKey."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            sig.append(f"{leaf.dtype}[{','.join(map(str, leaf.shape))}]")
        else:  # python scalar: weak-typed, value-independent
            sig.append(f"py{type(leaf).__name__}")
    return ";".join(sig) + "|" + str(treedef)


def _platform() -> str:
    import jax

    return jax.default_backend()


def _topology() -> str:
    import jax

    devs = jax.devices()
    return f"{devs[0].platform}x{len(devs)}"


def _versions() -> str:
    import jax

    xla = getattr(jax.lib, "xla_extension_version", "?")
    return f"jax={jax.__version__}|xla_ext={xla}|fmt={AOT_FORMAT_VERSION}"


class ProgramKey(NamedTuple):
    """Identity of one exported entrypoint program.

    ``entry``/``fingerprint``/``avals``/``platform``/``topology`` feed
    the filename digest; ``versions`` rides the blob header and is
    validated at load (a mismatch is a *stale* blob: warned, fallen
    back from, overwritten — never a silent dead file)."""

    entry: str         #: entrypoint name ("fused_fit", "fleet_bucket"...)
    fingerprint: str   #: structural+data fingerprint from the builder
    avals: str         #: abstract in-shapes/dtypes + treedef
    platform: str      #: backend the program was lowered for
    topology: str      #: device kind x count
    versions: str      #: jax/XLA/format versions (header-checked)

    @property
    def digest(self) -> str:
        h = hashlib.sha1("\x1f".join(
            (self.entry, self.fingerprint, self.avals, self.platform,
             self.topology)).encode())
        return h.hexdigest()[:16]

    @property
    def filename(self) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in self.entry)[:40]
        return f"{safe}-{self.digest}.aotx"


def program_key(entry: str, fingerprint: str, args) -> ProgramKey:
    return ProgramKey(entry, fingerprint, args_signature(args),
                      _platform(), _topology(), _versions())


class ProgramMiss(NamedTuple):
    """One store miss, for CONTRACT003 / bench attribution."""

    entry: str
    digest: str
    reason: str     #: "absent" | "stale ..." | "corrupt ..." | ...


_SERIALIZATION_REGISTERED = False


def _ensure_serialization_registered() -> None:
    """Register the package's custom pytree containers with
    ``jax.export`` (needed on BOTH sides: serializing a program whose
    arguments carry a TOABatch, and rebuilding its treedef at
    deserialize time)."""
    global _SERIALIZATION_REGISTERED

    if _SERIALIZATION_REGISTERED:
        return
    from jax import export as jexport

    from pint_tpu.toabatch import TOABatch

    try:
        jexport.register_namedtuple_serialization(
            TOABatch, serialized_name="pint_tpu.toabatch.TOABatch")
    except ValueError:   # already registered (reload/second instance)
        pass
    # LAPACK custom-call targets register LAZILY, at the first lowering
    # of a linalg op — a warm process that never traces one would hand
    # the deserialized module's `lapack_*` custom calls an uninitialized
    # handler and SEGFAULT (observed on this jaxlib: eigh/svd/qr all
    # crash cross-process without this).  Importing the shim module
    # registers the targets and `initialize()` binds the scipy BLAS/
    # LAPACK symbols — no compile, so the zero-compile start holds.
    try:
        import jaxlib.lapack  # noqa: F401  (registers the targets)
        from jaxlib.cpu import _lapack

        if hasattr(_lapack, "initialize"):
            _lapack.initialize()
    except Exception as e:  # pragma: no cover - jaxlib layout drift
        _log.warning("could not pre-register LAPACK custom-call "
                     "handlers (%s: %s); deserialized linalg programs "
                     "may need a priming trace", type(e).__name__, e)
    _SERIALIZATION_REGISTERED = True


# --- counters -----------------------------------------------------------------

_LOCK = threading.RLock()
_COUNTERS = {"hits": 0, "misses": 0, "writes": 0, "invalidations": 0,
             "evictions": 0, "verify_failures": 0, "call_fallbacks": 0}
_MISSES: List[ProgramMiss] = []


def counters() -> Dict[str, int]:
    with _LOCK:
        return dict(_COUNTERS)


def counters_since(mark: Dict[str, int]) -> Dict[str, int]:
    now = counters()
    return {k: now[k] - mark.get(k, 0) for k in now}


def miss_mark() -> int:
    with _LOCK:
        return len(_MISSES)


def misses_since(mark: int) -> Tuple[ProgramMiss, ...]:
    with _LOCK:
        return tuple(_MISSES[mark:])


def _count(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n
    profiling.count(f"aot.{name}", n)


def _record_miss(key: ProgramKey, reason: str) -> None:
    with _LOCK:
        _COUNTERS["misses"] += 1
        _MISSES.append(ProgramMiss(key.entry, key.digest, reason))
    profiling.count("aot.misses")
    telemetry.event("aot.miss", entry=key.entry, reason=reason)


# --- the disk store -----------------------------------------------------------

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _version_problem(header: dict) -> Optional[str]:
    """None when the blob header's versions match this process, else a
    description of the staleness.  Routed through the
    ``stale_aot_version`` failpoint so the fallback path is drivable."""
    want = _versions()
    got = header.get("versions", "<missing>")
    if got != want:
        return f"versions {got!r} != current {want!r}"
    if int(header.get("format", -1)) != AOT_FORMAT_VERSION:
        return (f"blob format {header.get('format')} != "
                f"{AOT_FORMAT_VERSION}")
    return None


class ProgramStore:
    """Disk-resident store of exported entrypoint programs.

    One blob per :class:`ProgramKey` digest
    (``<entry>-<digest>.aotx``): ``PTAOT1\\n`` magic, a JSON header
    line (key fields, versions, payload CRC32/length), then the
    ``jax.export`` payload.  An advisory ``manifest.json`` carries LRU
    metadata (sizes, last-used); the blob headers stay authoritative,
    so a lost/corrupt manifest is rebuilt from the directory, never
    trusted over it."""

    MANIFEST = "manifest.json"

    def __init__(self, path: str, max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        self.path = os.path.abspath(os.path.expanduser(path))
        self.max_entries = max_entries if max_entries is not None else \
            _env_int("PINT_TPU_AOT_MAX_ENTRIES", 256)
        self.max_bytes = max_bytes if max_bytes is not None else \
            _env_int("PINT_TPU_AOT_MAX_MB", 512) * (1 << 20)
        os.makedirs(self.path, exist_ok=True)
        self._manifest = self._load_manifest()

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.path, self.MANIFEST)

    def _load_manifest(self) -> dict:
        try:
            with open(self._manifest_path(), encoding="utf-8") as fh:
                m = json.load(fh)
            if not isinstance(m.get("files"), dict):
                raise ValueError("manifest has no files table")
        except (OSError, ValueError):
            m = {"version": 1, "files": {}}
        # reconcile with the directory: blobs are authoritative
        on_disk = {f for f in os.listdir(self.path)
                   if f.endswith(".aotx")}
        files = {f: meta for f, meta in m["files"].items()
                 if f in on_disk}
        for f in on_disk - set(files):
            try:
                st = os.stat(os.path.join(self.path, f))
                files[f] = {"size": st.st_size, "last_used": st.st_mtime,
                            "entry": f.rsplit("-", 1)[0]}
            except OSError:
                pass
        m["files"] = files
        return m

    def _save_manifest(self) -> None:
        tmp = self._manifest_path() + f".tmp{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self._manifest, fh, indent=1, sort_keys=True)
            os.replace(tmp, self._manifest_path())
        except OSError:  # advisory: never fail a fit over LRU metadata
            with contextlib.suppress(OSError):
                os.unlink(tmp)

    def entries(self) -> Dict[str, dict]:
        return dict(self._manifest["files"])

    def stats(self) -> dict:
        files = self._manifest["files"]
        return {"path": self.path, "entries": len(files),
                "bytes": sum(int(m.get("size", 0))
                             for m in files.values())}

    # -- invalidation ------------------------------------------------------
    def _invalidate(self, key: ProgramKey, fname: str, why: str) -> None:
        """Loud-but-safe: warn, count, delete — the subsequent live
        trace overwrites the slot with a fresh blob."""
        msg = (f"AOT store blob {fname} for entrypoint "
               f"{key.entry!r} is unusable ({why}); falling back to "
               "live tracing and overwriting")
        warnings.warn(msg, AotStoreWarning)
        _log.warning(msg)
        _count("invalidations")
        telemetry.warn("aot.invalidated", entry=key.entry, why=why)
        with contextlib.suppress(OSError):
            os.unlink(os.path.join(self.path, fname))
        self._manifest["files"].pop(fname, None)
        self._save_manifest()

    # -- load --------------------------------------------------------------
    def load(self, key: ProgramKey):
        """The deserialized ``jax.export.Exported`` for ``key``, or
        None (with a recorded miss + loud invalidation when a blob
        existed but was stale/corrupt)."""
        fname = key.filename
        fpath = os.path.join(self.path, fname)
        if not os.path.exists(fpath):
            _record_miss(key, "absent")
            return None
        try:
            with open(fpath, "rb") as fh:
                raw = fh.read()
            if not raw.startswith(_MAGIC):
                raise ValueError("bad magic")
            nl = raw.index(b"\n", len(_MAGIC))
            header = json.loads(raw[len(_MAGIC):nl].decode())
            payload = raw[nl + 1:]
        except (OSError, ValueError, KeyError) as e:
            self._invalidate(key, fname, f"corrupt header: {e}")
            _record_miss(key, "corrupt header")
            return None
        ver_check = faultinject.wrap("stale_aot_version",
                                     _version_problem)
        stale = ver_check(header)
        if stale:
            self._invalidate(key, fname, f"stale: {stale}")
            _record_miss(key, f"stale: {stale}")
            return None
        if header.get("digest") != key.digest:
            self._invalidate(key, fname, "key digest mismatch")
            _record_miss(key, "digest mismatch")
            return None
        if len(payload) != int(header.get("payload_len", -1)) or \
                (zlib.crc32(payload) & 0xFFFFFFFF) != \
                int(header.get("payload_crc32", -1)):
            self._invalidate(
                key, fname, "payload failed its CRC32 integrity check "
                "(truncated or bit-flipped after write)")
            _record_miss(key, "corrupt payload (CRC)")
            return None
        try:
            from jax import export as jexport

            _ensure_serialization_registered()
            exported = jexport.deserialize(payload)
        except Exception as e:  # jax-internal format drift
            self._invalidate(key, fname,
                             f"undeserializable: {type(e).__name__}: {e}")
            _record_miss(key, f"undeserializable: {type(e).__name__}")
            return None
        _count("hits")
        telemetry.event("aot.hit", entry=key.entry)
        from pint_tpu.lint import tracehooks

        tracehooks.note_aot_hit()
        meta = self._manifest["files"].setdefault(
            fname, {"size": len(raw), "entry": key.entry})
        meta["last_used"] = time.time()
        self._save_manifest()
        _log.info("aot store hit: %s (%s, %.1f kB)", key.entry, fname,
                  len(raw) / 1024.0)
        return exported

    # -- put ---------------------------------------------------------------
    def put(self, key: ProgramKey, payload: bytes) -> str:
        """Atomically write one serialized program; returns the blob
        path.  CRC32-checksummed header + write-tmp + ``os.replace``
        (the :mod:`pint_tpu.runtime` checkpoint discipline), then LRU
        eviction down to the configured bounds."""
        fname = key.filename
        header = {
            "format": AOT_FORMAT_VERSION, "entry": key.entry,
            "digest": key.digest, "fingerprint": key.fingerprint,
            "avals": key.avals, "platform": key.platform,
            "topology": key.topology, "versions": key.versions,
            "created": time.time(),
            "payload_len": len(payload),
            "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        }
        raw = _MAGIC + json.dumps(header, sort_keys=True).encode() + \
            b"\n" + payload
        fpath = os.path.join(self.path, fname)
        tmp = fpath + f".tmp{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(raw)
        os.replace(tmp, fpath)
        _count("writes")
        self._manifest["files"][fname] = {
            "size": len(raw), "entry": key.entry,
            "last_used": time.time()}
        self._evict(keep=fname)
        self._save_manifest()
        _log.info("aot store write: %s -> %s (%.1f kB)", key.entry,
                  fname, len(raw) / 1024.0)
        return fpath

    def _evict(self, keep: str) -> None:
        files = self._manifest["files"]

        def total() -> int:
            return sum(int(m.get("size", 0)) for m in files.values())

        while len(files) > self.max_entries or total() > self.max_bytes:
            victims = sorted(
                (f for f in files if f != keep),
                key=lambda f: files[f].get("last_used", 0.0))
            if not victims:
                break
            v = victims[0]
            _count("evictions")
            _log.info("aot store LRU eviction: %s", v)
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(self.path, v))
            files.pop(v, None)


# --- global store wiring ------------------------------------------------------

_STORE: Optional[ProgramStore] = None
_SUSPENDED = 0
_SAVED_CACHE_MIN: Optional[float] = None


def get_store() -> Optional[ProgramStore]:
    return _STORE


def default_store_dir() -> str:
    return os.path.expanduser("~/.cache/pint_tpu/aot")


def _set_store(store: Optional[ProgramStore]) -> None:
    """Swap the active store; entering warm mode also drops the
    persistent-cache compile-time floor to 0 so the thin exported-call
    wrappers (which compile in milliseconds) are persisted — the other
    half of the zero-compile warm start."""
    global _STORE, _SAVED_CACHE_MIN

    import jax

    if store is not None and _STORE is None:
        _SAVED_CACHE_MIN = \
            jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0)
    elif store is None and _STORE is not None and \
            _SAVED_CACHE_MIN is not None:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          _SAVED_CACHE_MIN)
        _SAVED_CACHE_MIN = None
    _STORE = store


def configure_store(path: Optional[str] = None,
                    enable: Optional[bool] = None) -> Optional[str]:
    """Wire the process-global AOT program store and return its
    directory (None = disabled).

    Resolution order: explicit ``path``, then ``PINT_TPU_AOT_STORE``
    (a directory; ``0`` disables, ``1`` means the default location),
    then — only when ``enable=True`` (e.g.
    ``runtime.acquire_backend(warm_start=True)``) — the default
    ``~/.cache/pint_tpu/aot``.  With no path and no enable request the
    store stays disabled: :func:`serve` wrappers are passthroughs and
    steady-state counters are untouched."""
    if enable is False:
        disable_store()
        return None
    target = path
    if target is None:
        env = os.environ.get("PINT_TPU_AOT_STORE", "")
        if env == "0":
            return None
        if env not in ("", "1"):
            target = env
        elif env == "1" or enable:
            target = default_store_dir()
    if target is None:
        return None
    _set_store(ProgramStore(target))
    _log.info("aot store enabled at %s (%d entr(y/ies))", _STORE.path,
              len(_STORE.entries()))
    return _STORE.path


def disable_store() -> None:
    _set_store(None)


@contextlib.contextmanager
def temporary_store(path: str, max_entries: Optional[int] = None,
                    max_bytes: Optional[int] = None):
    """Scoped store for tests and the contract auditor's warm leg;
    restores the previous store (or disabled state) on exit."""
    prev = _STORE
    _set_store(ProgramStore(path, max_entries=max_entries,
                            max_bytes=max_bytes))
    try:
        yield _STORE
    finally:
        _set_store(prev)


@contextlib.contextmanager
def suspend_writes():
    """Suspend store WRITES (reads still served) — entered by
    ``tracehooks.instrument`` so measurement cannot mutate the store it
    observes (the same discipline as the persistent-compilation-cache
    write suspension; without it a marginal-mode base run could write
    a blob the extended run then loads, skewing the delta negative)."""
    global _SUSPENDED

    with _LOCK:
        _SUSPENDED += 1
    try:
        yield
    finally:
        with _LOCK:
            _SUSPENDED -= 1


def _writes_suspended() -> bool:
    return _SUSPENDED > 0


# --- the serve wrapper --------------------------------------------------------

_RESOLVE_MISS = object()


class _ServedProgram:
    """Store-consulting wrapper around one jitted entrypoint program.

    With no store enabled, ``__call__`` is a passthrough.  With a
    store: the first call per argument signature resolves through the
    store (hit -> deserialized exported program; miss -> live call,
    then export + round-trip verify + atomic write), and every later
    call dispatches the resolved program directly.  A deserialized
    program whose call raises falls back to the live program
    permanently (loud, counted) — the store can degrade a process to
    exactly what it would have done without a store, never worse."""

    def __init__(self, entry: str, fn: Callable, fingerprint: str):
        self.entry = entry
        self.fn = fn
        self.fingerprint = fingerprint
        self._resolved: Dict[str, Callable] = {}

    def __call__(self, *args):
        if _STORE is None:
            return self.fn(*args)
        import jax

        if any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves(args)):
            # traced context (an outer jit/vmap is inlining this
            # program): the store serves the OUTER program; store
            # consultation/export is host-side and not trace-safe
            return self.fn(*args)
        sig = args_signature(args)
        call = self._resolved.get(sig)
        if call is None:
            call, out = self._resolve(sig, args)
            self._resolved[sig] = call
            if out is not _RESOLVE_MISS:
                return out
            return call(*args)
        return call(*args)

    # -- resolution --------------------------------------------------------
    def _guard(self, sig: str, exported, ecall=None) -> Callable:
        """Wrap the exported call so a runtime failure (platform drift,
        jax-internal incompatibility) degrades to the live program.
        The exported call is jitted ONCE: ``Exported.call`` builds a
        fresh wrapper per invocation, which would churn the tracing
        cache; a single jitted wrapper keeps steady state on the C++
        fastpath with a stable cache key (0 retraces)."""
        import jax

        live = self.fn
        if ecall is None:
            ecall = jax.jit(exported.call)

        def guarded(*args):
            try:
                return ecall(*args)
            except Exception as e:
                _count("call_fallbacks")
                msg = (f"deserialized AOT program for {self.entry!r} "
                       f"failed at call time ({type(e).__name__}: {e}); "
                       "falling back to live tracing for this process")
                warnings.warn(msg, AotStoreWarning)
                _log.warning(msg)
                self._resolved[sig] = live
                return live(*args)

        return guarded

    def _harvest_cost_card(self, ecall, args, key) -> None:
        """Best-effort cost card at resolution time (ISSUE 13).
        Counter-neutral by construction: ``ecall.lower(*args)`` traces
        the already-jitted wrapper and ``Lowered.cost_analysis()`` is a
        host-side estimate — no ``backend_compile``, no retrace event —
        so the aot zero-compile contract survives the harvest.  The
        full memory card (device peak) is filled in by the audit/bench
        legs, which own a real ``Compiled``."""
        try:
            from pint_tpu import metrics

            if not metrics.enabled():
                return
            metrics.harvest_lowered(self.entry, ecall.lower(*args),
                                    digest=key.digest,
                                    source="aot_resolve")
        except Exception:
            pass

    def _resolve(self, sig: str, args):
        store = _STORE
        key = program_key(self.entry, self.fingerprint, args)
        exported = store.load(key)
        if exported is not None:
            import jax

            ecall = jax.jit(exported.call)
            self._harvest_cost_card(ecall, args, key)
            return self._guard(sig, exported, ecall), _RESOLVE_MISS
        # miss: run the live program (the caller's result), then —
        # unless measurement suspended writes — export, ROUND-TRIP
        # VERIFY, and write, leaving the process dispatching the same
        # exported program a warm process will (which also lands the
        # thin wrapper executable in the persistent compilation cache)
        out = self.fn(*args)
        if _writes_suspended():
            return self.fn, out
        try:
            from jax import export as jexport

            _ensure_serialization_registered()
            exported = jexport.export(self.fn)(*args)
            payload = exported.serialize()
            restored = jexport.deserialize(payload)
        except Exception as e:
            _count("verify_failures")
            _log.warning(
                "AOT export of %r failed (%s: %s); serving live",
                self.entry, type(e).__name__, e)
            return self.fn, out
        # verify OUTSIDE the guard: a call-time failure here must mean
        # "blob not written, serve live", never a silent live-vs-live
        # comparison through the guard's fallback
        import jax

        ecall = jax.jit(restored.call)
        try:
            verify = ecall(*args)
        except Exception as e:
            _count("verify_failures")
            _log.warning(
                "AOT round-trip call of %r raised (%s: %s); blob NOT "
                "written, serving live", self.entry,
                type(e).__name__, e)
            return self.fn, out
        if not _outputs_match(out, verify):
            _count("verify_failures")
            msg = (f"AOT round-trip of {self.entry!r} did not reproduce "
                   "the live program's output; blob NOT written, "
                   "serving live")
            warnings.warn(msg, AotStoreWarning)
            _log.warning(msg)
            return self.fn, out
        store.put(key, payload)
        self._harvest_cost_card(ecall, args, key)
        return self._guard(sig, restored, ecall), out


def _outputs_match(a, b, rtol: float = 1e-12, atol: float = 1e-12) -> bool:
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb or len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x = np.asarray(x)
        y = np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if x.dtype.kind in "fc":
            ok = np.isclose(x, y, rtol=rtol, atol=atol) | \
                (np.isnan(x) & np.isnan(y))
            if not bool(np.all(ok)):
                return False
        elif not bool(np.array_equal(x, y)):
            return False
    return True


def serve(entry: str, fn: Callable, fingerprint: str = "") -> Callable:
    """Wrap a jitted entrypoint program so it consults the AOT store.

    Zero-cost when no store is enabled (one global + one attribute
    lookup per call).  ``fingerprint`` must capture everything the
    call-time avals cannot: closed-over data (use
    :func:`model_fingerprint` / :func:`data_crc`), static build
    options (maxiter, tolerances, kernel choice), and structural
    identity (component set, free-param slots)."""
    return _ServedProgram(entry, fn, fingerprint)


# --- warm fixtures + CLI ------------------------------------------------------

#: B1855+09-class synthetic serving fixture: ELL1 binary + FD block,
#: well-posed on a 60-day span (the PR 6 lesson: freeze the
#: near-degenerate astrometry/DM directions so plain in-graph GN
#: converges; error_us=300 keeps 1e-10 chi2 parity meaningful)
_B1855_PAR = """
PSR B1855+09SIM
RAJ 18:57:36.39
DECJ 09:43:17.2
F0 186.49408124 1
F1 -6.2e-16 1
PEPOCH 55000
POSEPOCH 55000
DM 13.3
FD1 1e-5 1
FD2 -2e-6 1
BINARY ELL1
PB 12.32717
A1 9.230780 1
TASC 55000.1 1
EPS1 2.2e-5
EPS2 -2.0e-6
M2 0.25
SINI 0.96
TZRMJD 55000.2
TZRFRQ 1400
TZRSITE gbt
EPHEM DE421
"""


#: isolated-pulsar quick fixture (no binary): the cheap serving shape
#: the bench cold/warm legs time — compiles in seconds on one core
_QUICK_PAR = """
PSR QUICKSERVE
RAJ 05:00:00.0
DECJ 20:00:00.0
F0 300.0 1
F1 -1.0e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 15.0
FD1 1e-5 1
FD2 -2e-6 1
TZRMJD 55000.1
TZRFRQ 1400
TZRSITE gbt
EPHEM DE421
"""


def _single_pulsar_fixture(tag: str, par: str, ntoas: int, span: float,
                           seed: int, with_gls: bool = False):
    """Two-phase single-pulsar serving fixture: the returned builder
    does everything EXCEPT entrypoint calls (data simulation, model
    build, program construction), so the check harness can instrument
    the calls alone; it returns ``(cold, steady)`` thunks — ``cold``
    makes every first call (where store resolution happens), ``steady``
    repeats them on the already-resolved programs.

    ``with_gls`` adds the host-solve serving shapes ROADMAP item 2 left
    open: one GLS step (the served ``gls_solve`` program on the CPU
    backend) and one wideband GLS step (same solve program at the
    stacked TOA+DM row count, through the served wideband assembly)."""
    import warnings as _w

    from pint_tpu.fitter import (build_fused_fit, build_gls_step,
                                 build_wideband_assembly, build_wls_step)
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.simulation import make_fake_toas_uniform

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        model = get_model(par.strip().splitlines())
        toas = make_fake_toas_uniform(
            55000.0, 55000.0 + span, ntoas, model, obs="gbt",
            error_us=300.0,
            freq_mhz=np.tile([1400.0, 800.0],
                             (ntoas + 1) // 2)[:ntoas],
            add_noise=True, seed=seed)
        resid = Residuals(toas, model)
        names = list(model.free_params)
        step = build_wls_step(model, resid.batch, names,
                              resid.track_mode)
        fit = build_fused_fit(model, resid.batch, names,
                              resid.track_mode, maxiter=3,
                              exact_floor=0.0)
        gls = wb = None
        if with_gls:
            gls = build_gls_step(model, resid.batch, names,
                                 resid.track_mode)
            # deterministic wideband DM rows: the model's DM value plus
            # a seeded perturbation, one measurement per TOA
            rng = np.random.default_rng(seed)
            dm0 = float(np.asarray(model.DM.value))
            dm_data = dm0 + rng.normal(0.0, 1e-4, toas.ntoas)
            wb_assemble = build_wideband_assembly(
                model, resid.batch, np.arange(toas.ntoas),
                dm_data, np.full(toas.ntoas, 1e-3), names,
                resid.track_mode, True)
            wb = build_gls_step(model, resid.batch, names,
                                resid.track_mode, assemble=wb_assemble)
    x0 = np.zeros(len(names))
    p = resid.pdict

    def run(out: dict) -> None:
        r = np.asarray(resid._fn(p))
        s = step(x0, p)
        x, info = fit(p, p)
        out[tag] = {"ntoa": int(toas.ntoas), "nfit": len(names),
                    "chi2": float(info["chi2"]),
                    "status": info["status"].name,
                    "rms_cycles": float(np.std(r)),
                    "step_chi2": float(s["chi2"])}
        if gls is not None:
            out[tag]["gls_chi2"] = float(gls(x0, p)["chi2"])
            out[tag]["wb_chi2"] = float(wb(x0, p)["chi2"])

    return run, run


def _quick_fixture():
    """Isolated 32-TOA pulsar (no binary): the cheap serving shape the
    bench cold/warm legs time — compiles in seconds on one core."""
    return _single_pulsar_fixture("quick", _QUICK_PAR, 32, 30.0, 42)


def _b1855_fixture():
    """B1855-class (ELL1 binary + FD block) serving fixture, including
    the GLS and wideband host-solve serving shapes (ROADMAP item 2's
    leftover — the ``gls_solve`` program at both the narrowband and the
    stacked TOA+DM row counts)."""
    return _single_pulsar_fixture("b1855", _B1855_PAR, 64, 60.0, 1855,
                                  with_gls=True)


def _fleet4_fixture():
    """The 4-pulsar ragged fleet (sizes 8/8/16/16 -> 2 buckets, chunk
    width 2), heterogeneous free-param sets (half freeze the FD
    block) — the PR 6 pmask case, deterministic so two processes
    produce identical bucket ProgramKeys."""
    import warnings as _w

    from pint_tpu.fitter import FitStatus
    from pint_tpu.fleet import FleetFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    pulsars = []
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        for i, n in enumerate((8, 8, 16, 16)):
            par = _B1855_PAR.replace("B1855+09SIM", f"FLEET{i}")
            model = get_model(par.strip().splitlines())
            model.A1.frozen = True
            model.TASC.frozen = True
            if i % 2:   # heterogeneous slots: half freeze the FD block
                model.FD1.frozen = True
                model.FD2.frozen = True
            toas = make_fake_toas_uniform(
                55000.0, 55060.0, n, model, obs="gbt", error_us=300.0,
                freq_mhz=np.tile([1400.0, 800.0],
                                 (n + 1) // 2)[:n],
                add_noise=True, seed=100 + i)
            pulsars.append((f"FLEET{i}", model, toas))
        ff = FleetFitter(pulsars, maxiter=3, chunk_size=2)
        ff._ensure_plan()

    def run(out: dict) -> None:
        res = ff.fit()
        out["fleet4"] = {
            "n_pulsars": len(res.entries),
            "n_buckets": res.n_buckets,
            "n_ok": sum(e.status in (FitStatus.CONVERGED,
                                     FitStatus.MAXITER)
                        for e in res.entries),
            "chi2": [round(float(e.chi2), 6) for e in res.entries]}

    return run, run


def _serve_fixture():
    """The timing daemon's bucket programs (PR 9): two serve buckets
    (8- and 16-TOA shape classes over the same structure key) driven
    through the inline submit/flush path.  Serve quantizes pad shapes
    as a pure function of each job (power-of-two, not max-member), so
    a warm process reproduces the ProgramKeys exactly."""
    from pint_tpu.fitter import FitStatus
    from pint_tpu.serve import _demo_service

    svc, jobs = _demo_service(batch_size=2, maxiter=3)

    def run(out: dict) -> None:
        futs = [svc.submit_prepared(j) for j in jobs]
        svc.flush()
        res = [f.result(timeout=600.0) for f in futs]
        out["serve"] = {
            "n_jobs": len(res),
            "n_buckets": svc.stats()["n_buckets"],
            "n_ok": sum(r.status in (FitStatus.CONVERGED,
                                     FitStatus.MAXITER) for r in res),
            "chi2": [round(float(r.chi2), 6) for r in res]}

    return run, run


def _pta_fixture():
    """The PTA scenario factory's noise-synthesis program plus the
    fleet bucket programs its simulated array routes into (ISSUE 15):
    a tiny 4-pulsar scenario, deterministic by seeding, so a warm
    serving process prebuilds the exact pta_noise/fleet_bucket
    ProgramKeys an N=1024 campaign's shape classes start from."""
    from pint_tpu import pta
    from pint_tpu.fitter import FitStatus

    sc = pta.Scenario(n_pulsars=4, seed=0, chunk_size=2,
                      cadence=pta.Cadence(span_days=360.0,
                                          cadence_days=15.0))
    run_ = pta.build(sc)

    def run(out: dict) -> None:
        sim = run_.simulate(realization=0)
        ff = sim.fleet(maxiter=3)
        res = ff.fit()
        out["pta"] = {
            "n_pulsars": len(res.entries),
            "n_buckets": res.n_buckets,
            "n_chunks": sim.scan.n_chunks,
            "scan": sim.scan.counts(),
            "n_ok": sum(e.status in (FitStatus.CONVERGED,
                                     FitStatus.MAXITER)
                        for e in res.entries),
            "rms_us": [round(float(r) * 1e6, 6) for r in sim.rms_sec]}

    return run, run


def warm_fixtures() -> Dict[str, Callable]:
    """The deterministic serving fixtures the ``warm``/``check`` CLI
    legs drive — the entrypoint programs a fresh serving process needs
    on its floor: the B1855-class fused fit / WLS step / residuals,
    the 4-pulsar ragged fleet's two bucket programs, and the cheap
    isolated-pulsar "quick" shape the bench legs time.

    Each value is a BUILDER: calling it does everything except the
    entrypoint calls and returns ``(cold, steady)`` thunks, so the
    check harness instruments the calls alone (fixture construction is
    thousands of tiny eager dispatches that would otherwise drown the
    measurement in instrumentation overhead)."""
    return {"quick": _quick_fixture, "b1855": _b1855_fixture,
            "fleet4": _fleet4_fixture, "serve": _serve_fixture,
            "pta": _pta_fixture}


def _resolve_fixtures(fixtures: Optional[List[str]]) -> List[str]:
    fix = warm_fixtures()
    names = list(fixtures) if fixtures else sorted(fix)
    unknown = [n for n in names if n not in fix]
    if unknown:
        raise KeyError(f"unknown warm fixture(s) {unknown}; "
                       f"available: {sorted(fix)}")
    return names


def run_warm(fixtures: Optional[List[str]] = None,
             store_path: Optional[str] = None) -> dict:
    """Prebuild the store: trace, compile, export and write every
    fixture's entrypoint programs (store misses self-populate)."""
    path = configure_store(store_path, enable=True)
    fix = warm_fixtures()
    names = _resolve_fixtures(fixtures)
    mark = counters()
    t0 = time.time()
    results: dict = {}
    for n in names:
        cold, _ = fix[n]()
        cold(results)
    store = get_store()
    return {"mode": "warm", "store": path,
            "fixtures": names, "elapsed_s": round(time.time() - t0, 2),
            "counters": counters_since(mark),
            "store_stats": store.stats() if store else None,
            "results": results}


def run_check(fixtures: Optional[List[str]] = None,
              store_path: Optional[str] = None) -> dict:
    """The zero-compile warm-start proof: drive the same fixtures with
    the store enabled UNDER :mod:`pint_tpu.lint.tracehooks`
    instrumentation and report compiles/retraces/hits.  A warm store
    must yield ``compiles == 0`` (exit 1 from the CLI otherwise)."""
    from pint_tpu.lint.tracehooks import instrument

    path = configure_store(store_path, enable=True)
    fix = warm_fixtures()
    names = _resolve_fixtures(fixtures)
    t0 = time.time()
    # fixture CONSTRUCTION stays uninstrumented (thousands of tiny
    # eager dispatches that would drown the measurement); entrypoint
    # programs resolve at first CALL, inside the instrumented region
    built = [(n, fix[n]()) for n in names]
    mark = counters()
    mmark = miss_mark()
    results: dict = {}
    results2: dict = {}
    with instrument() as th:
        m0 = th.mark()
        # cold leg: every first call — store loads + wrapper first-
        # traces (logged as "never seen function" but initial traces,
        # not re-traces); ZERO compiles demanded
        for n, (cold, _) in built:
            cold(results)
        m1 = th.mark()
        # steady leg: same resolved programs again — zero compiles AND
        # zero retraces
        for n, (_, steady) in built:
            steady(results2)
        m2 = th.mark()
    first = m1 - m0
    steady_d = m2 - m1
    return {"mode": "check", "store": path, "fixtures": names,
            "elapsed_s": round(time.time() - t0, 2),
            "compiles": first.compiles + steady_d.compiles,
            "initial_traces": len(first.retraces),
            "retraces": len(steady_d.retraces),
            "dispatches": first.dispatches,
            "cache_hits": first.cache_hits,
            "aot_hits": first.aot_hits,
            "counters": counters_since(mark),
            "misses": [m._asdict() for m in misses_since(mmark)],
            "results": results}


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m pint_tpu.aot {warm,check,stats}``."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m pint_tpu.aot",
        description="AOT serving-program store: prebuild (warm), prove "
                    "the zero-compile warm start (check), or list the "
                    "store (stats).")
    ap.add_argument("command", choices=("warm", "check", "stats"))
    ap.add_argument("--store", default=None,
                    help="store directory (default: PINT_TPU_AOT_STORE "
                         "or ~/.cache/pint_tpu/aot)")
    ap.add_argument("--fixtures", default=None,
                    help="comma-separated fixture subset "
                         "(default: all; see aot.warm_fixtures)")
    args = ap.parse_args(argv)
    fixtures = [f.strip() for f in args.fixtures.split(",")
                if f.strip()] if args.fixtures else None
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        _w.simplefilter("always", AotStoreWarning)
        if args.command == "warm":
            doc = run_warm(fixtures, args.store)
        elif args.command == "check":
            doc = run_check(fixtures, args.store)
        else:
            path = configure_store(args.store, enable=True)
            store = get_store()
            doc = {"mode": "stats", "store": path,
                   **(store.stats() if store else {}),
                   "entries": store.entries() if store else {}}
    print(json.dumps(doc))
    if args.command == "check" and \
            (doc["compiles"] > 0 or doc["retraces"] > 0
             or doc["misses"]):
        return 1
    return 0


if __name__ == "__main__":
    import sys

    # ``python -m pint_tpu.aot`` executes this file as ``__main__`` — a
    # SECOND module instance whose globals (the active store, counters)
    # the package-imported ``pint_tpu.aot`` never sees.  Delegate to the
    # canonical instance so the CLI and the serve() wrappers share state.
    from pint_tpu.aot import main as _main

    sys.exit(_main())
