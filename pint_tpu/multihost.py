"""Multi-host (multi-process) execution: the DCN axis of the scale-out.

`pint_tpu.parallel` shards one jitted fit over a single-process
("batch", "toa") device mesh — the ICI story.  This module adds the
outer, multi-host layer the same way real TPU pods are driven: one
python process per host, `jax.distributed` for the runtime, a mesh
spanning every process's devices, host-local shards assembled into
global `jax.Array`s, and the SAME shard_map program as the
single-process path (its psums ride ICI within a host and DCN across
hosts; on this CPU-only box, Gloo collectives over localhost stand in
for DCN).

The reference's only scale-out is a single-host process pool that
deep-copies the fitter per chi2-grid point
(`/root/reference/src/pint/gridutils.py:322`); it has no multi-host
story at all (SURVEY §2.8).  Here a grid/ensemble scales across hosts by
sharding the batch axis over the process dimension of the mesh while
each host's local devices split the TOA axis.

Usage (every process runs the same program, SPMD):

    from pint_tpu import multihost
    multihost.init(coordinator="10.0.0.1:8476", num_processes=4,
                   process_id=i, local_devices=2)   # before any jax use
    mesh = multihost.global_mesh()
    chi2 = multihost.multihost_grid_chisq(fitter, grid, mesh=mesh)

`tests/test_multihost.py` spawns real OS processes and checks the
multi-process result against the single-process path (1e-9 relative;
observed bit-identical on the test problem).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional

import numpy as np

from pint_tpu import telemetry
from pint_tpu.lint.contracts import dispatch_contract

__all__ = ["init", "global_mesh", "barrier", "multihost_grid_chisq"]


def init(coordinator: str, num_processes: int, process_id: int,
         local_devices: Optional[int] = None, platform: str = "cpu",
         timeout_s: Optional[float] = None):
    """Initialize the distributed runtime for this process.  MUST run
    before anything touches a jax backend (same constraint as
    `__graft_entry__.dryrun_multichip`).

    ``local_devices``: on CPU, the number of virtual devices this process
    exposes (the "ICI island" size per host); on real TPU hosts the
    hardware decides and this is ignored.

    ``timeout_s`` bounds the coordinator rendezvous (default
    ``PINT_TPU_MH_INIT_TIMEOUT_S`` or 300 s): a peer that died before
    joining, or an unreachable coordinator, raises an actionable
    :class:`~pint_tpu.exceptions.MultihostTimeoutError` instead of
    hanging this process forever (ISSUE 4 multihost hardening).
    """
    from pint_tpu.exceptions import MultihostTimeoutError

    if timeout_s is None:
        timeout_s = float(os.environ.get("PINT_TPU_MH_INIT_TIMEOUT_S",
                                         300.0))
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        if local_devices:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{local_devices}").strip()

    import jax

    if platform == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        try:
            # without a CPU collectives implementation the CPU client is
            # built single-node and every cross-process dispatch dies
            # with "Multiprocess computations aren't implemented on the
            # CPU backend" — Gloo over TCP is the localhost DCN stand-in
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # pragma: no cover - jax without the flag
            pass
    from pint_tpu import runtime

    def _initialize():
        # the C++ deadline is kept LONGER than ours: on expiry this
        # jax's coordination client LOG(FATAL)s the whole process
        # (client.h "Terminating process ... DEADLINE_EXCEEDED", a
        # SIGABRT) instead of raising — so the catchable Python-level
        # deadline below must win the race, raise our typed error, and
        # let the caller exit before the C++ fatal ever fires
        kw = ({"initialization_timeout": max(1, int(2 * timeout_s))}
              if timeout_s else {})
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes, process_id=process_id,
                **kw)
        except TypeError:  # pragma: no cover - jax without the kwarg
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=num_processes,
                                       process_id=process_id)

    try:
        runtime.call_with_deadline(
            _initialize, timeout_s,
            f"distributed init of process {process_id}/{num_processes} "
            f"(coordinator {coordinator})")
    except MultihostTimeoutError:
        raise
    except Exception as e:
        msg = str(e).lower()
        if ("deadline" in msg or "timeout" in msg or "timed out" in msg
                or "unavailable" in msg):
            raise MultihostTimeoutError(
                f"distributed init of process {process_id}/"
                f"{num_processes} did not complete within "
                f"{timeout_s:.0f} s (coordinator {coordinator}): {e} — "
                "a peer process likely died before the rendezvous or "
                "the coordinator address is unreachable; check every "
                "worker's logs and restart the ensemble") from e
        raise


def global_mesh(timeout_s: Optional[float] = None):
    """("batch", "toa") mesh over every device of every process: the
    batch axis spans processes (DCN), the toa axis each process's local
    devices (ICI).  ``timeout_s`` bounds the global device-list
    formation (which blocks on every process having initialized)."""
    import jax
    from jax.sharding import Mesh

    from pint_tpu import runtime

    devs = runtime.call_with_deadline(
        jax.devices, timeout_s, "multihost global device enumeration")
    nproc = jax.process_count()
    nlocal = jax.local_device_count()
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    arr = np.array(devs).reshape(nproc, nlocal)
    return Mesh(arr, ("batch", "toa"))


def barrier(name: str = "pint_tpu_mh_barrier",
            timeout_s: Optional[float] = None) -> None:
    """A cross-process barrier with a deadline: every process must call
    this with the same ``name``.  A dead peer raises an actionable
    :class:`~pint_tpu.exceptions.MultihostTimeoutError` after
    ``timeout_s`` (default ``PINT_TPU_MH_BARRIER_TIMEOUT_S``, unset =
    no deadline) instead of blocking this process indefinitely."""
    from jax.experimental import multihost_utils

    from pint_tpu import runtime

    if timeout_s is None:
        env = os.environ.get("PINT_TPU_MH_BARRIER_TIMEOUT_S")
        timeout_s = float(env) if env else None
    runtime.call_with_deadline(
        lambda: multihost_utils.sync_global_devices(name), timeout_s,
        f"multihost barrier {name!r}")


def _multihost_dispatch(fitter, grid_values: Dict[str, np.ndarray],
                        mesh, maxiter: int) -> np.ndarray:
    """One whole-grid multihost dispatch: the shard_map fit over the
    global mesh, host-local slices in, allgathered chi2 out."""
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from pint_tpu.parallel import prep_sharded_grid

    nproc = mesh.devices.shape[0]
    fit, stacked, batch, g = prep_sharded_grid(
        fitter, grid_values, mesh, nproc, maxiter, "multihost")

    # host-local view: this process's slice of the batch axis; full
    # copies of everything else (replicated or toa-sharded locally)
    pid = jax.process_index()
    lo, hi = pid * (g // nproc), (pid + 1) * (g // nproc)
    gnames = set(grid_values)
    local = {
        "const": stacked["const"],
        "delta": {k: (np.asarray(v)[lo:hi] if k in gnames else v)
                  for k, v in stacked["delta"].items()},
        "mask": stacked["mask"],
    }
    gspec = {
        "const": {k: P() for k in stacked["const"]},
        "delta": {k: (P("batch") if k in gnames else P())
                  for k in stacked["delta"]},
        "mask": {k: P("toa") for k in stacked["mask"]},
    }
    bspec = jax.tree_util.tree_map(lambda leaf: P("toa"), batch)

    p_g = multihost_utils.host_local_array_to_global_array(
        local, mesh, gspec)
    b_g = multihost_utils.host_local_array_to_global_array(
        jax.tree_util.tree_map(np.asarray, batch), mesh, bspec)

    chi2_g, _ = fit(p_g, b_g)
    chi2_local = multihost_utils.global_array_to_host_local_array(
        chi2_g, mesh, P("batch"))
    full = multihost_utils.process_allgather(np.asarray(chi2_local))
    return np.asarray(full).reshape(g)


@dispatch_contract("multihost_chunk", max_compiles=40, max_dispatches=80,
                   max_transfers=16,
                   # compiled-HLO comm contract (ISSUE 10), measured on
                   # the per-process (1, 8) virtual CPU mesh: the same 6
                   # "toa"-axis all-reduces as the single-process
                   # program (the batch axis is host-level here), and
                   # nothing else — an implicit all-gather would be
                   # unbudgeted and therefore always-fail
                   max_collectives={"all-reduce": 6},
                   max_comm_bytes=8192, max_device_peak_bytes=1 << 20)
def multihost_grid_chisq(fitter, grid_values: Dict[str, np.ndarray],
                         mesh=None, maxiter: int = 2, *,
                         timeout_s: Optional[float] = None,
                         chunk_size: Optional[int] = None,
                         checkpoint: Optional[str] = None,
                         resume: bool = False, max_retries: int = 2,
                         checkpoint_every: int = 1,
                         return_summary: bool = False) -> np.ndarray:
    """chi2 over a flat grid, grid points sharded across PROCESSES and
    TOAs across each process's local devices — the multi-host analogue of
    `pint_tpu.parallel.sharded_grid_chisq` (same inner shard_map program,
    same psum'd thresholded-eigh normal equations).  Every process passes
    the SAME full ``grid_values``; the full chi2 vector is returned on
    every process (allgathered over DCN).

    Hardening (ISSUE 4): ``timeout_s`` bounds the entry barrier, so a
    dead peer raises ``MultihostTimeoutError`` instead of hanging the
    collective.  ``chunk_size``/``checkpoint``/``resume`` execute the
    grid in chunks through ``runtime.run_checkpointed_scan`` — every
    process runs the identical chunk sequence in SPMD lockstep, process
    0 alone writes the CRC32-verified checkpoints, every process reads
    them on resume (the checkpoint path must be on a filesystem all
    hosts share).  The fallback requeue path is the eager single-device
    fit, computed REPLICATED on every process (no collectives, so a
    poisoned mesh cannot poison the requeue)."""
    import jax

    mesh = mesh or global_mesh(timeout_s=timeout_s)
    if timeout_s:
        barrier("multihost_grid_chisq_entry", timeout_s=timeout_s)
    if chunk_size is None and checkpoint is None and not return_summary:
        # chunked runs get their spans from runtime.run_checkpointed_scan
        with telemetry.span("multihost.grid_chisq"):
            return _multihost_dispatch(fitter, grid_values, mesh, maxiter)

    from pint_tpu import runtime
    from pint_tpu.gridutils import _eager_grid_chisq
    from pint_tpu.parallel import _chunk_values

    nproc = mesh.devices.shape[0]
    if not grid_values:
        raise ValueError("grid_values is empty")
    gvals = {k: np.asarray(v, np.float64) for k, v in grid_values.items()}
    sizes = {n: len(v) for n, v in gvals.items()}
    if len(set(sizes.values())) != 1:
        raise ValueError(f"grid arrays differ in length: {sizes}")
    g = next(iter(sizes.values()))
    cs = int(chunk_size) if chunk_size else g
    if cs % nproc:
        raise ValueError(f"chunk_size {cs} does not split over {nproc} "
                         "processes")

    def run_chunk(ci, lo, hi):
        vals = _chunk_values(gvals, lo, hi, cs)
        return _multihost_dispatch(fitter, vals, mesh, maxiter)[: hi - lo]

    def fallback(ci, lo, hi):
        # replicated on every process: identical inputs -> identical
        # results, keeping the SPMD chunk sequence in lockstep
        return _eager_grid_chisq(
            fitter, {k: v[lo:hi] for k, v in gvals.items()},
            maxiter=maxiter)

    names = [n for n in fitter.fit_params if n not in gvals]
    sig = runtime.scan_signature("multihost", gvals, names, maxiter, cs)
    chi2, summary = runtime.run_checkpointed_scan(
        g, run_chunk, chunk_size=cs, fallback=fallback,
        checkpoint=checkpoint, resume=resume, max_retries=max_retries,
        checkpoint_every=checkpoint_every, signature=sig,
        write_checkpoints=jax.process_index() == 0)
    return (chi2, summary) if return_summary else chi2
