"""Multi-host (multi-process) execution: the DCN axis of the scale-out.

`pint_tpu.parallel` shards one jitted fit over a single-process
("batch", "toa") device mesh — the ICI story.  This module adds the
outer, multi-host layer the same way real TPU pods are driven: one
python process per host, `jax.distributed` for the runtime, a mesh
spanning every process's devices, host-local shards assembled into
global `jax.Array`s, and the SAME shard_map program as the
single-process path (its psums ride ICI within a host and DCN across
hosts; on this CPU-only box, Gloo collectives over localhost stand in
for DCN).

The reference's only scale-out is a single-host process pool that
deep-copies the fitter per chi2-grid point
(`/root/reference/src/pint/gridutils.py:322`); it has no multi-host
story at all (SURVEY §2.8).  Here a grid/ensemble scales across hosts by
sharding the batch axis over the process dimension of the mesh while
each host's local devices split the TOA axis.

Usage (every process runs the same program, SPMD):

    from pint_tpu import multihost
    multihost.init(coordinator="10.0.0.1:8476", num_processes=4,
                   process_id=i, local_devices=2)   # before any jax use
    mesh = multihost.global_mesh()
    chi2 = multihost.multihost_grid_chisq(fitter, grid, mesh=mesh)

`tests/test_multihost.py` spawns real OS processes and checks the
multi-process result against the single-process path (1e-9 relative;
observed bit-identical on the test problem).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional

import numpy as np

__all__ = ["init", "global_mesh", "multihost_grid_chisq"]


def init(coordinator: str, num_processes: int, process_id: int,
         local_devices: Optional[int] = None, platform: str = "cpu"):
    """Initialize the distributed runtime for this process.  MUST run
    before anything touches a jax backend (same constraint as
    `__graft_entry__.dryrun_multichip`).

    ``local_devices``: on CPU, the number of virtual devices this process
    exposes (the "ICI island" size per host); on real TPU hosts the
    hardware decides and this is ignored.
    """
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        if local_devices:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""))
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{local_devices}").strip()

    import jax

    if platform == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh():
    """("batch", "toa") mesh over every device of every process: the
    batch axis spans processes (DCN), the toa axis each process's local
    devices (ICI)."""
    import jax
    from jax.sharding import Mesh

    nproc = jax.process_count()
    nlocal = jax.local_device_count()
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    arr = np.array(devs).reshape(nproc, nlocal)
    return Mesh(arr, ("batch", "toa"))


def multihost_grid_chisq(fitter, grid_values: Dict[str, np.ndarray],
                         mesh=None, maxiter: int = 2) -> np.ndarray:
    """chi2 over a flat grid, grid points sharded across PROCESSES and
    TOAs across each process's local devices — the multi-host analogue of
    `pint_tpu.parallel.sharded_grid_chisq` (same inner shard_map program,
    same psum'd thresholded-eigh normal equations).  Every process passes
    the SAME full ``grid_values``; the full chi2 vector is returned on
    every process (allgathered over DCN)."""
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from pint_tpu.parallel import prep_sharded_grid

    mesh = mesh or global_mesh()
    nproc = mesh.devices.shape[0]
    fit, stacked, batch, g = prep_sharded_grid(
        fitter, grid_values, mesh, nproc, maxiter, "multihost")

    # host-local view: this process's slice of the batch axis; full
    # copies of everything else (replicated or toa-sharded locally)
    pid = jax.process_index()
    lo, hi = pid * (g // nproc), (pid + 1) * (g // nproc)
    gnames = set(grid_values)
    local = {
        "const": stacked["const"],
        "delta": {k: (np.asarray(v)[lo:hi] if k in gnames else v)
                  for k, v in stacked["delta"].items()},
        "mask": stacked["mask"],
    }
    gspec = {
        "const": {k: P() for k in stacked["const"]},
        "delta": {k: (P("batch") if k in gnames else P())
                  for k in stacked["delta"]},
        "mask": {k: P("toa") for k in stacked["mask"]},
    }
    bspec = jax.tree_util.tree_map(lambda leaf: P("toa"), batch)

    p_g = multihost_utils.host_local_array_to_global_array(
        local, mesh, gspec)
    b_g = multihost_utils.host_local_array_to_global_array(
        jax.tree_util.tree_map(np.asarray, batch), mesh, bspec)

    chi2_g, _ = fit(p_g, b_g)
    chi2_local = multihost_utils.global_array_to_host_local_array(
        chi2_g, mesh, P("batch"))
    full = multihost_utils.process_allgather(np.asarray(chi2_local))
    return np.asarray(full).reshape(g)
