"""Publication-quality timing-solution table (LaTeX).

Reference: `pintpublish` (`/root/reference/src/pint/scripts/pintpublish.py`
+ `output/publish.py:31`): generate a LaTeX table of the fitted model —
measured parameters with uncertainties, set parameters, and fit summary
statistics when a tim file is given.
"""

import argparse
import sys
import warnings

__all__ = ["main", "publish_table"]


def _fmt_unc(value, unc):
    """value(err) notation with the uncertainty on the last two digits."""
    import math

    if unc is None or not (unc > 0):
        return f"{value:.12g}", ""
    digits = max(0, -int(math.floor(math.log10(unc))) + 1)
    scaled = round(unc * 10**digits)
    return f"{value:.{digits}f}", f"({scaled})"


def publish_table(model, toas=None, include_dmx: bool = False) -> str:
    rows_fit = []
    rows_set = []
    for name in model.params:
        par = model[name]
        if par.value is None or name in ("PSR", "EPHEM", "CLK", "UNITS"):
            continue
        if not include_dmx and name.startswith(("DMX", "SWX")):
            continue
        kind = getattr(par, "kind", "float")
        if kind in ("str", "bool", "pair"):
            continue
        try:
            v = float(par.value) if kind != "mjd" \
                else float(par.value.mjd_float)
        except (TypeError, ValueError):
            continue
        if not par.frozen:
            val, err = _fmt_unc(v, par.uncertainty)
            rows_fit.append((name, par.units or "", f"{val}{err}"))
        else:
            rows_set.append((name, par.units or "", f"{v:.12g}"))
    lines = [
        r"\begin{table}",
        rf"\caption{{Timing solution for {model.PSR.value}}}",
        r"\begin{tabular}{lll}",
        r"\hline",
        r"Parameter & Units & Value \\",
        r"\hline",
        r"\multicolumn{3}{c}{Measured parameters} \\",
        r"\hline",
    ]
    esc = lambda s: s.replace("_", r"\_").replace("^", r"\^{}")
    for n, u, v in rows_fit:
        lines.append(rf"{esc(n)} & {esc(u)} & {v} \\")
    lines += [r"\hline", r"\multicolumn{3}{c}{Set parameters} \\",
              r"\hline"]
    for n, u, v in rows_set:
        lines.append(rf"{esc(n)} & {esc(u)} & {v} \\")
    if toas is not None:
        from pint_tpu.residuals import Residuals

        r = Residuals(toas, model)
        lines += [
            r"\hline",
            r"\multicolumn{3}{c}{Fit summary} \\",
            r"\hline",
            rf"Number of TOAs & & {toas.ntoas} \\",
            rf"$\chi^2$ & & {r.calc_chi2():.2f} \\",
            rf"Reduced $\chi^2$ & & {r.reduced_chi2:.3f} \\",
            rf"Weighted RMS & $\mu$s & {r.rms_weighted() * 1e6:.3f} \\",
        ]
    lines += [r"\hline", r"\end{tabular}", r"\end{table}"]
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu LaTeX timing-solution table (cf. "
                    "pintpublish)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("parfile")
    parser.add_argument("timfile", nargs="?", default=None,
                        help="optional tim file for fit statistics")
    parser.add_argument("-o", "--out", default=None)
    parser.add_argument("--include-dmx", action="store_true",
                        help="include the DMX/SWX forest in the table")
    args = parser.parse_args(argv)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.models import get_model

        model = get_model(args.parfile)
        toas = None
        if args.timfile:
            from pint_tpu.toa import get_TOAs

            toas = get_TOAs(args.timfile, model=model)
        table = publish_table(model, toas, include_dmx=args.include_dmx)
    if args.out:
        with open(args.out, "w") as f:
            f.write(table + "\n")
        print(f"Wrote {args.out}")
    else:
        print(table)
    return 0


if __name__ == "__main__":
    sys.exit(main())
