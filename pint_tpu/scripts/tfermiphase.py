"""Compute pulse phases for Fermi-LAT photons.

Reference: `fermiphase` (`/root/reference/src/pint/scripts/fermiphase.py`):
load a Fermi FT1 event file + par file, compute each photon's phase,
report the (weighted) H-test, optionally write the phases out.  The
weight column may be 'CALC' to compute SearchPulsation PSF weights from
photon ENERGY + angular separation to the model's sky position
(`pint_tpu.event_toas.calc_lat_weights`, validated against the
reference's H-test golden in tests/test_real_events.py).  Writing a
PULSE_PHASE column back into the FITS file is not supported (no FITS
writer in this zero-dependency stack); phases go to a text file instead.
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu Fermi photon phases (cf. fermiphase)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("eventfile", help="Fermi FT1 event FITS file "
                                          "(barycentered or geocentric)")
    parser.add_argument("parfile", help="par file to construct the model")
    parser.add_argument("weightcol", nargs="?", default=None,
                        help="photon-weight column name (e.g. from "
                             "gtsrcprob), or CALC to compute PSF "
                             "weights from ENERGY + target separation")
    parser.add_argument("--ephem", default="DE421")
    parser.add_argument("--planets", action="store_true")
    parser.add_argument("--minMJD", type=float, default=None)
    parser.add_argument("--maxMJD", type=float, default=None)
    parser.add_argument("--outfile", default=None,
                        help="write 'MJD phase [weight]' rows here")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    import numpy as np

    from pint_tpu import qs
    from pint_tpu.event_toas import get_Fermi_TOAs
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.templates import hm, sf_hm

    model = get_model(args.parfile)
    kw = {}
    if args.weightcol:
        kw["weightcolumn"] = args.weightcol
        if args.weightcol.upper() == "CALC":
            # target = the model's sky position (reference fermiphase
            # builds the SkyCoord from modelin, fermiphase.py:77)
            astro = [c for c in model.components.values()
                     if hasattr(c, "psr_dir")][0]
            kw["targetcoord"] = astro.radec_deg()
    if args.minMJD is not None:
        kw["minmjd"] = args.minMJD
    if args.maxMJD is not None:
        kw["maxmjd"] = args.maxMJD
    toas = get_Fermi_TOAs(args.eventfile, ephem=args.ephem,
                          planets=args.planets, **kw)
    print(f"Read {toas.ntoas} Fermi photons from {args.eventfile}")
    r = Residuals(toas, model, subtract_mean=False)
    ph = model.calc.phase(r.pdict, r.batch)
    _, frac = qs.round_nearest(ph)
    phases = np.asarray(qs.to_f64(frac)) % 1.0
    weights = getattr(toas, "weights", None)
    h = hm(phases, weights=weights)
    wtag = "weighted " if weights is not None else ""
    print(f"{wtag}Htest: {h:.2f} (sig ~ {sf_hm(h):.3g})")
    if args.outfile:
        mjds = np.asarray(toas.utc.mjd_float)
        with open(args.outfile, "w") as f:
            if weights is None:
                f.write("# MJD phase\n")
                for m, p in zip(mjds, phases):
                    f.write(f"{m:.12f} {p:.9f}\n")
            else:
                f.write("# MJD phase weight\n")
                for m, p, w in zip(mjds, phases, weights):
                    f.write(f"{m:.12f} {p:.9f} {w:.6f}\n")
        print(f"Wrote phases to {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
