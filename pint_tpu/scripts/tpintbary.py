"""Command-line barycentering of a single time.

Reference: `pintbary` (`/root/reference/src/pint/scripts/pintbary.py`):
given a UTC MJD, an observatory, and a source position (par file or
RA/DEC), print the barycentric arrival time (TDB at the SSB, with Roemer,
Shapiro, and dispersion removed).
"""

import argparse
import sys
import warnings

__all__ = ["main"]

_MINIMAL_PAR = """PSR BARY
RAJ {ra}
DECJ {dec}
F0 1.0
PEPOCH {mjd}
DM {dm}
"""


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu command-line barycentering (cf. pintbary)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("time", help="UTC MJD, e.g. 57000.123456789")
    parser.add_argument("--obs", default="geocenter", help="observatory")
    parser.add_argument("--freq", type=float, default=float("inf"),
                        help="observing frequency [MHz]")
    parser.add_argument("--parfile", default=None)
    parser.add_argument("--ra", default=None,
                        help="RAJ (H:M:S) if no par file")
    parser.add_argument("--dec", default=None,
                        help="DECJ (D:M:S) if no par file")
    parser.add_argument("--dm", type=float, default=0.0)
    parser.add_argument("--ephem", default="DE421")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    import numpy as np

    from pint_tpu import mjd as mjdmod
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import TOA, TOAs

    if args.parfile:
        model = get_model(args.parfile)
    else:
        if not (args.ra and args.dec):
            parser.error("either --parfile or both --ra and --dec required")
        mjd0 = args.time.split(".")[0]
        model = get_model(_MINIMAL_PAR.format(
            ra=args.ra, dec=args.dec, mjd=mjd0,
            dm=args.dm).splitlines())

    t = TOA(mjd=mjdmod.from_string(args.time), error_us=1.0,
            freq_mhz=args.freq, obs=args.obs)
    toas = TOAs([t])
    toas.apply_clock_corrections()
    toas.compute_TDBs(ephem=args.ephem)
    toas.compute_posvels(ephem=args.ephem)
    r = Residuals(toas, model, subtract_mean=False)
    # barycentric time = TDB at the observatory minus all delays
    delay_sec = float(np.asarray(model.delay(r.pdict, r.batch))[0])
    bat = mjdmod.add_sec(toas.tdb, -delay_sec)
    day, frac = int(bat.day[0]), float(bat.frac[0])
    if frac < 0.0:
        day, frac = day - 1, frac + 1.0
    print(f"Barycentric MJD (TDB): {day}{f'{frac:.15f}'[1:]}")
    print(f"Total delay removed: {delay_sec:.9f} s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
