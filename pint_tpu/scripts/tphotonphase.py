"""Compute model phases for photon events.

Reference: `photonphase` (`/root/reference/src/pint/scripts/photonphase.py`):
load an event file + par file, compute each photon's pulse phase, report
the H-test, optionally write phases out.
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu photon phases (cf. photonphase)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("eventfile", help="FITS event file (barycentered "
                                          "or geocentric)")
    parser.add_argument("parfile")
    parser.add_argument("--ephem", default="DE421")
    parser.add_argument("--planets", action="store_true")
    parser.add_argument("--minMJD", type=float, default=None)
    parser.add_argument("--maxMJD", type=float, default=None)
    parser.add_argument("--outfile", default=None,
                        help="write 'MJD phase' rows to this file")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    import numpy as np

    from pint_tpu import qs
    from pint_tpu.event_toas import get_event_TOAs
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.templates import hm, sf_hm

    model = get_model(args.parfile)
    kw = {}
    if args.minMJD is not None:
        kw["minmjd"] = args.minMJD
    if args.maxMJD is not None:
        kw["maxmjd"] = args.maxMJD
    toas = get_event_TOAs(args.eventfile, ephem=args.ephem,
                          planets=args.planets, **kw)
    print(f"Read {toas.ntoas} photons from {args.eventfile}")
    r = Residuals(toas, model, subtract_mean=False)
    ph = model.calc.phase(r.pdict, r.batch)
    _, frac = qs.round_nearest(ph)
    phases = np.asarray(qs.to_f64(frac)) % 1.0
    h = hm(phases)
    print(f"Htest: {h:.2f} (sig ~ {sf_hm(h):.3g})")
    if args.outfile:
        mjds = np.asarray(toas.utc.mjd_float)
        with open(args.outfile, "w") as f:
            f.write("# MJD phase\n")
            for m, p in zip(mjds, phases):
                f.write(f"{m:.12f} {p:.9f}\n")
        print(f"Wrote phases to {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
