"""Compute model phases for photon events.

Reference: `photonphase` (`/root/reference/src/pint/scripts/photonphase.py`):
load an event file + par file, compute each photon's pulse phase, report
the H-test, optionally write phases out.
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu photon phases (cf. photonphase)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("eventfile", help="FITS event file (barycentered "
                                          "or geocentric)")
    parser.add_argument("parfile")
    parser.add_argument("--ephem", default="DE421")
    parser.add_argument("--planets", action="store_true")
    parser.add_argument("--minMJD", type=float, default=None)
    parser.add_argument("--maxMJD", type=float, default=None)
    parser.add_argument("--orbfile", default=None,
                        help="FPorbit/FT2 orbit file for topocentric "
                             "(spacecraft-frame) events")
    parser.add_argument("--addorbphase", action="store_true",
                        help="also compute each photon's fractional "
                             "ORBIT_PHASE (binary models only)")
    parser.add_argument("--outfile", default=None,
                        help="write 'MJD phase [orbphase]' rows to this "
                             "file")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    import numpy as np

    from pint_tpu import qs
    from pint_tpu.event_toas import (get_event_TOAs,
                                     get_satellite_observatory)
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.templates import hm, sf_hm

    model = get_model(args.parfile)
    kw = {}
    if args.minMJD is not None:
        kw["minmjd"] = args.minMJD
    if args.maxMJD is not None:
        kw["maxmjd"] = args.maxMJD
    if args.orbfile:
        # reference: get_satellite_observatory(mission, orbfile) then
        # events load in the spacecraft frame (photonphase.py:230-246)
        get_satellite_observatory("satellite", args.orbfile)
        kw["obs"] = "satellite"
    # reference: planets follow the model's PLANET_SHAPIRO
    # (photonphase.py:167)
    planets = args.planets or model.planets_flag
    toas = get_event_TOAs(args.eventfile, ephem=args.ephem,
                          planets=planets, **kw)
    print(f"Read {toas.ntoas} photons from {args.eventfile}")
    r = Residuals(toas, model, subtract_mean=False)
    ph = model.calc.phase(r.pdict, r.batch)
    _, frac = qs.round_nearest(ph)
    phases = np.asarray(qs.to_f64(frac)) % 1.0
    h = hm(phases)
    print(f"Htest: {h:.2f} (sig ~ {sf_hm(h):.3g})")
    orbphases = None
    if args.addorbphase:
        orbphases = np.asarray(model.orbital_phase(r.pdict, r.batch))
        print(f"Orbit phases: {orbphases[0]:.4f} .. {orbphases[-1]:.4f}")
    if args.outfile:
        mjds = np.asarray(toas.utc.mjd_float)
        with open(args.outfile, "w") as f:
            f.write("# MJD phase" +
                    (" orbphase\n" if orbphases is not None else "\n"))
            for i, (m, p) in enumerate(zip(mjds, phases)):
                row = f"{m:.12f} {p:.9f}"
                if orbphases is not None:
                    row += f" {orbphases[i]:.9f}"
                f.write(row + "\n")
        print(f"Wrote phases to {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
