"""Command-line tools (reference `/root/reference/src/pint/scripts/`).

Each module exposes ``main(argv=None)`` and is wired to a console script in
``pyproject.toml``: ``tpintempo`` (fit), ``tzima`` (simulate),
``tpintbary`` (barycenter), ``ttcb2tdb`` (unit conversion),
``tcompare_parfiles`` (model diff).  The ``t`` prefix keeps them
side-by-side-installable with the reference's tools.
"""
