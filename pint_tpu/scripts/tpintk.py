"""Interactive timing-model workbench (pintk equivalent).

Reference: `pintk` (`/root/reference/src/pint/pintk/`, a tkinter GUI).
This environment has no display, so the same workflow runs as a command
REPL with matplotlib (Agg) plot output:

    fit [maxiter]        run the auto-selected fitter
    plot [file.png]      pre/post-fit residual plot
    setpar PAR VALUE     edit one parameter value (par-file syntax)
    freeze PAR / thaw PAR
    select MJD1 MJD2     keep only TOAs in the range
    reset                restore the full TOA set
    summary              fit summary
    write file.par       save the current model
    quit

Commands can also be piped or given with ``--command`` for scripted use.
"""

import argparse
import shlex
import sys
import warnings

__all__ = ["main", "PintkSession"]


class PintkSession:
    """The model/TOA state behind the REPL (reference `pintk.plk`
    widget state)."""

    def __init__(self, parfile: str, timfile: str):
        import numpy as np

        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals
        from pint_tpu.toa import get_TOAs

        self.model = get_model(parfile)
        self.all_toas = get_TOAs(timfile, model=self.model)
        self.toas = self.all_toas
        self.fitter = None
        self.prefit = Residuals(self.toas, self.model)
        self.postfit = None
        self._np = np

    # -- commands ----------------------------------------------------------
    def cmd_fit(self, maxiter: str = "") -> str:
        from pint_tpu.plk import run_auto_fit

        self.fitter, msg = run_auto_fit(
            self.toas, self.model, int(maxiter) if maxiter else None)
        self.postfit = self.fitter.resids
        return msg

    def cmd_plot(self, outfile: str = "tpintk.png") -> str:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        np = self._np
        mjd = np.asarray(self.prefit.batch.tdbld)
        err = np.asarray(self.prefit.get_data_error())
        fig, ax = plt.subplots(figsize=(9, 5))
        ax.errorbar(mjd, self.prefit.time_resids * 1e6, yerr=err,
                    fmt=".", ms=4, alpha=0.6, label="pre-fit")
        if self.postfit is not None:
            post = self.postfit.toa if hasattr(self.postfit, "toa") \
                else self.postfit
            ax.errorbar(np.asarray(post.batch.tdbld),
                        post.time_resids * 1e6,
                        yerr=np.asarray(post.get_data_error()),
                        fmt=".", ms=4, alpha=0.8, label="post-fit")
        ax.set_xlabel("MJD (TDB)")
        ax.set_ylabel("residual [us]")
        ax.axhline(0.0, color="k", lw=0.5)
        ax.legend()
        psr = self.model.PSR.value or "PSR"
        ax.set_title(psr)
        fig.tight_layout()
        fig.savefig(outfile, dpi=120)
        plt.close(fig)
        return f"wrote {outfile}"

    #: parameters baked into the TOAs at load time (get_TOAs(model=),
    #: toa.py) — changing them here would silently leave stale TOA
    #: preparation; they need a session reload
    _LOAD_TIME_PARAMS = ("EPHEM", "CLOCK", "PLANET_SHAPIRO")

    def cmd_setpar(self, name: str, value: str) -> str:
        """Edit one parameter value (the REPL's slice of the pintk
        paredit workflow; full text-level editing is
        `pint_tpu.plk.ParEditor` on the GUI panel)."""
        from pint_tpu.residuals import Residuals

        uname = name.upper()
        if uname in self._LOAD_TIME_PARAMS:
            return (f"{uname} is baked into the loaded TOAs (clock/"
                    "ephemeris preparation); edit the par file and "
                    "restart the session instead")
        par = self.model[uname]
        old = par.value
        par.set_from_string(value)   # the par-file value parser
        try:
            self.prefit = Residuals(self.toas, self.model)
        except Exception:
            # a value the pipeline cannot evaluate must not leave the
            # session half-updated (new value, old residuals)
            par.value = old
            raise
        self.postfit = None
        self.fitter = None
        return f"{uname} = {par.value} (was {old})"

    def cmd_freeze(self, name: str) -> str:
        self.model[name.upper()].frozen = True
        return f"{name.upper()} frozen"

    def cmd_thaw(self, name: str) -> str:
        self.model[name.upper()].frozen = False
        return f"{name.upper()} free"

    def cmd_select(self, mjd1: str, mjd2: str) -> str:
        from pint_tpu.residuals import Residuals

        lo, hi = sorted((float(mjd1), float(mjd2)))
        m = self.all_toas.utc.mjd_float
        self.toas = self.all_toas.select((m >= lo) & (m <= hi))
        self.prefit = Residuals(self.toas, self.model)
        self.postfit = None
        self.fitter = None      # stale fit stats must not survive
        return f"selected {self.toas.ntoas} of {self.all_toas.ntoas} TOAs"

    def cmd_reset(self) -> str:
        from pint_tpu.residuals import Residuals

        self.toas = self.all_toas
        self.prefit = Residuals(self.toas, self.model)
        self.postfit = None
        self.fitter = None
        return f"restored {self.toas.ntoas} TOAs"

    def cmd_summary(self) -> str:
        if self.fitter is None:
            free = ", ".join(self.model.free_params)
            return (f"{self.toas.ntoas} TOAs, pre-fit rms "
                    f"{self.prefit.rms_weighted()*1e6:.3f} us; "
                    f"free: {free}")
        return self.fitter.get_summary()

    def cmd_write(self, outfile: str) -> str:
        self.model.write_parfile(outfile)
        return f"wrote {outfile}"

    def run_command(self, line: str) -> str:
        parts = shlex.split(line)
        if not parts:
            return ""
        cmd, args = parts[0].lower(), parts[1:]
        if cmd in ("quit", "exit", "q"):
            raise EOFError
        handler = getattr(self, f"cmd_{cmd}", None)
        if handler is None:
            return (f"unknown command {cmd!r} (fit/plot/setpar/freeze/"
                    "thaw/select/reset/summary/write/quit)")
        return handler(*args)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu interactive timing workbench (cf. pintk; "
                    "REPL + Agg plots instead of a GUI)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("parfile")
    parser.add_argument("timfile")
    parser.add_argument("--command", "-c", action="append", default=None,
                        help="run this command and exit (repeatable)")
    parser.add_argument("--gui", action="store_true",
                        help="open the interactive plk panel "
                             "(matplotlib; needs an interactive "
                             "backend/display)")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    if args.gui:
        from pint_tpu.plk import PlkPanel

        panel = PlkPanel(args.parfile, args.timfile)
        panel.show()
        return 0

    sess = PintkSession(args.parfile, args.timfile)
    print(f"Loaded {sess.toas.ntoas} TOAs; free params: "
          f"{', '.join(sess.model.free_params)}")

    failed = [False]

    def run(line):
        try:
            out = sess.run_command(line)
            if out:
                print(out)
            return True
        except EOFError:
            return False
        except Exception as e:  # keep the session alive on bad input
            print(f"error: {e}")
            failed[0] = True
            return True

    if args.command:
        for line in args.command:
            if not run(line):
                break
        # scripted mode: automation must see failures in the exit code
        return 1 if failed[0] else 0
    while True:
        try:
            line = input("tpintk> ")
        except EOFError:
            break
        if not run(line):
            break
    return 0


if __name__ == "__main__":
    sys.exit(main())
