"""Convert a TCB par file to TDB.

Reference: `tcb2tdb` (`/root/reference/src/pint/scripts/tcb2tdb.py`).
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Approximately convert a TCB par file to TDB "
                    "(cf. tcb2tdb); re-fit the output",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("input_par", help="TCB par file")
    parser.add_argument("output_par", help="output TDB par file")
    args = parser.parse_args(argv)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.models import get_model

        model = get_model(args.input_par, allow_tcb=True)
    model.write_parfile(args.output_par,
                        comment="converted TCB -> TDB by ttcb2tdb "
                                "(approximate; re-fit)")
    print(f"Wrote TDB model to {args.output_par}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
