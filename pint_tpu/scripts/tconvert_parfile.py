"""Par-file conversions: binary model, format, output location.

Reference: `convert_parfile`
(`/root/reference/src/pint/scripts/convert_parfile.py`).
"""

import argparse
import os
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    from pint_tpu.binaryconvert import _SUPPORTED

    parser = argparse.ArgumentParser(
        description="pint_tpu par-file conversions (cf. convert_parfile)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("input", help="input par file")
    parser.add_argument("-b", "--binary", default=None,
                        choices=sorted(_SUPPORTED),
                        help="convert the binary model")
    parser.add_argument("-o", "--out", default=None,
                        help="output file (default: stdout)")
    parser.add_argument("--kom", type=float, default=0.0,
                        help="KOM [deg] when converting to DDK")
    parser.add_argument("--allow_tcb", action="store_true",
                        help="convert TCB par files to TDB automatically")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    if not os.path.exists(args.input):
        print(f"cannot open {args.input!r}", file=sys.stderr)
        return 1
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.binaryconvert import convert_binary
        from pint_tpu.models import get_model

        model = get_model(args.input, allow_tcb=args.allow_tcb)
        if args.binary is not None:
            if "BINARY" not in model or not model.BINARY.value:
                print(f"{args.input!r} has no binary model; cannot "
                      f"convert to {args.binary}", file=sys.stderr)
                return 1
            kw = {"KOM": args.kom} if args.binary.upper() == "DDK" else {}
            model = convert_binary(model, args.binary, **kw)
    out = model.as_parfile()
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)
        print(f"Wrote {args.out}")
    else:
        print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
