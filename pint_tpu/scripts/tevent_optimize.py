"""Optimize timing parameters against photon events with a template.

Reference: `event_optimize`
(`/root/reference/src/pint/scripts/event_optimize.py`): sample the
posterior of the timing parameters where the likelihood is the photon
template density at each event's phase, via emcee.  Here the
photon-phase log-likelihood is a single jitted function of the parameter
vector — template lookup included — and the device ensemble sampler
(`pint_tpu.mcmc`) replaces emcee.
"""

import argparse
import sys
import warnings

__all__ = ["main", "build_photon_lnpost"]


def build_photon_lnpost(model, toas, template, weights=None):
    """Jit-pure ``lnpost(dx) -> float`` over free-parameter offsets (par
    units): sum_i ln( w f(phi_i) + 1-w ) with phi from the full timing
    model, plus the priors from `default_prior_info`."""
    import jax.numpy as jnp
    import numpy as np

    from pint_tpu import qs
    from pint_tpu.bayesian import default_prior_info, BayesianTiming
    from pint_tpu.residuals import Residuals

    bt_info = default_prior_info(model)
    bt = BayesianTiming(model, toas, prior_info=bt_info)
    r = bt.resids
    calc = model.calc
    names = bt.param_labels
    units = jnp.asarray(bt._units)
    p0 = r.pdict
    batch = r.batch
    if weights is None:
        weights = getattr(toas, "weights", None)
    w = jnp.ones(batch.ntoas) if weights is None else \
        jnp.asarray(np.asarray(weights, np.float64))
    tmpl_fn = template._eval_fn()
    x_tmpl = jnp.asarray(template.get_parameters())
    lnprior = bt.lnprior_fn
    refs = jnp.asarray(bt.start_point())

    def lnpost(dx):
        p = model.with_x(p0, dx * units, names)
        ph = calc.phase(p, batch)
        _, frac = qs.round_nearest(ph)
        phases = qs.to_f64(frac) % 1.0
        vals = tmpl_fn(phases, x_tmpl)
        ll = jnp.sum(jnp.log(w * vals + (1.0 - w)))
        lp = lnprior(refs + dx)
        return jnp.where(jnp.isfinite(lp), ll + lp, -jnp.inf)

    return lnpost, bt


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu photon-event timing sampler "
                    "(cf. event_optimize)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("eventfile")
    parser.add_argument("parfile")
    parser.add_argument("gaussfile", nargs="?", default=None,
                        help="optional: fit a 1-Gaussian template if "
                             "absent")
    parser.add_argument("--nwalkers", type=int, default=16)
    parser.add_argument("--nsteps", type=int, default=500)
    parser.add_argument("--burn", type=int, default=250)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--outfile", default=None,
                        help="write the post-fit par here")
    parser.add_argument("--backend-file", default=None,
                        help="chain checkpoint .npz (reference "
                             "event_optimize --backend analogue)")
    parser.add_argument("--checkpoint-every", type=int, default=100,
                        help="steps between checkpoint writes")
    parser.add_argument("--resume", action="store_true",
                        help="continue from --backend-file; reproduces "
                             "the uninterrupted chain exactly")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    import numpy as np

    from pint_tpu import qs
    from pint_tpu.event_toas import get_event_TOAs
    from pint_tpu.mcmc import ensemble_sample
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.templates import LCGaussian, LCTemplate, fit_template

    model = get_model(args.parfile)
    toas = get_event_TOAs(args.eventfile)
    print(f"Read {toas.ntoas} photons")

    r = Residuals(toas, model, subtract_mean=False)
    ph = model.calc.phase(r.pdict, r.batch)
    _, frac = qs.round_nearest(ph)
    phases = np.asarray(qs.to_f64(frac)) % 1.0
    template = LCTemplate([LCGaussian(float(np.median(phases)), 0.05)],
                          [0.5])
    template, lnl = fit_template(template, phases)
    print(f"Template: peak at {template.primitives[0].loc:.4f}, width "
          f"{template.primitives[0].width:.4f}, lnL={lnl:.1f}")

    lnpost, bt = build_photon_lnpost(model, toas, template)
    rng = np.random.default_rng(args.seed)
    nw = args.nwalkers + (args.nwalkers % 2)
    dx0 = rng.standard_normal((nw, bt.nparams)) * \
        bt.scales()[None, :] * 0.1
    res = ensemble_sample(lnpost, dx0, args.nsteps, seed=args.seed,
                          checkpoint=args.backend_file,
                          checkpoint_every=args.checkpoint_every,
                          resume=args.resume)
    flat = res.chain[args.burn:].reshape(-1, bt.nparams)
    refs = bt.start_point()
    print(f"acceptance {res.acceptance:.2f}")
    for i, n in enumerate(bt.param_labels):
        mean = refs[i] + flat[:, i].mean()
        std = flat[:, i].std()
        par = model[n]
        if hasattr(par, "set_value"):
            par.set_value(float(mean))
        else:
            par.value = float(mean)
        par.uncertainty = float(std)
        print(f"  {n:12s} {mean:.12g} +/- {std:.3g}")
    if args.outfile:
        model.write_parfile(args.outfile)
        print(f"Wrote {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
