"""Compare two par files parameter by parameter.

Reference: `compare_parfiles`
(`/root/reference/src/pint/scripts/compare_parfiles.py`).
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Compare two par files (cf. compare_parfiles)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("par1")
    parser.add_argument("par2")
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    from pint_tpu.models import get_model

    m1 = get_model(args.par1)
    m2 = get_model(args.par2)
    diff = m1.compare(m2)
    print(f"# THIS = {args.par1}")
    print(f"# OTHER = {args.par2}")
    print(diff)
    return 0


if __name__ == "__main__":
    sys.exit(main())
