"""Convert a tempo2 'T2' binary par file to a model this framework (and
the reference) implements.

Reference: `t2binary2pint`
(`/root/reference/src/pint/scripts/t2binary2pint.py`): the tempo2 T2
model is a universal superset; the concrete model is guessed from which
parameters are present (KOM/KIN -> DDK, EPS1/EPS2 or TASC -> ELL1,
otherwise DD/BT).
"""

import argparse
import sys
import warnings

__all__ = ["main", "guess_binary_model"]


def guess_binary_model(params) -> str:
    """Map a T2 parameter set to a concrete binary model (reference
    `pint.models.binary_dd` guessing in `t2binary2pint`/model_builder)."""
    has = lambda *names: any(n in params for n in names)
    if has("KOM", "KIN"):
        return "DDK"
    if has("EPS1", "EPS2", "TASC"):
        return "ELL1H" if has("H3") else "ELL1"
    if has("H3", "STIGMA"):
        return "DDH"
    if has("SHAPMAX"):
        return "DDS"
    if has("M2", "SINI", "OMDOT", "GAMMA"):
        return "DD"
    return "BT"


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu T2-binary par conversion (cf. "
                    "t2binary2pint)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("input_par", help="input par file (BINARY T2)")
    parser.add_argument("output_par", help="output par file")
    parser.add_argument("--allow_tcb", action="store_true")
    args = parser.parse_args(argv)

    lines = open(args.input_par).read().splitlines()
    params = {ln.split()[0].upper() for ln in lines if ln.split()}
    out_lines = []
    binary = None
    for ln in lines:
        fields = ln.split()
        if fields and fields[0].upper() == "BINARY":
            binary = fields[1].upper()
            if binary == "T2":
                binary = guess_binary_model(params)
                print(f"BINARY T2 -> {binary}")
            out_lines.append(f"BINARY {binary}")
        else:
            out_lines.append(ln)
    if binary is None:
        print("no BINARY line in input", file=sys.stderr)
        return 1
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.models import get_model

        model = get_model(out_lines, allow_tcb=args.allow_tcb)
    model.write_parfile(args.output_par,
                        comment=f"converted from T2 to {binary} by "
                                "tt2binary2pint")
    print(f"Wrote {args.output_par}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
