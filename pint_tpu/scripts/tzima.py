"""Simulate fake TOAs from a timing model ("zima").

Reference: `zima` (`/root/reference/src/pint/scripts/zima.py`): generate
uniformly spaced TOAs that the model predicts perfectly, optionally add
white measurement noise and wideband DM data, write a tim file.
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu fake-TOA simulator (cf. zima)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("parfile", help="model par file")
    parser.add_argument("timfile", help="output tim file")
    parser.add_argument("--ntoa", type=int, default=100)
    parser.add_argument("--startMJD", type=float, default=56000.0)
    parser.add_argument("--duration", type=float, default=400.0,
                        help="span [days]")
    parser.add_argument("--obs", default="gbt")
    parser.add_argument("--freq", type=float, nargs="+", default=[1400.0],
                        help="observing frequencies [MHz], cycled over TOAs")
    parser.add_argument("--error", type=float, default=1.0,
                        help="TOA uncertainty [us]")
    parser.add_argument("--fuzzdays", type=float, default=0.0)
    parser.add_argument("--addnoise", action="store_true")
    parser.add_argument("--wideband", action="store_true",
                        help="attach -pp_dm/-pp_dme wideband DM data")
    parser.add_argument("--dmerror", type=float, default=1e-4,
                        help="wideband DM uncertainty [pc cm^-3]")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import (
        add_wideband_dm_data,
        make_fake_toas_uniform,
    )
    from pint_tpu.toa import write_tim

    model = get_model(args.parfile)
    freqs = np.resize(np.asarray(args.freq, float), args.ntoa)
    toas = make_fake_toas_uniform(
        args.startMJD, args.startMJD + args.duration, args.ntoa, model,
        obs=args.obs, error_us=args.error, freq_mhz=freqs,
        fuzz_days=args.fuzzdays, add_noise=args.addnoise, seed=args.seed)
    if args.wideband:
        dm_seed = None if args.seed is None else args.seed + 1
        toas = add_wideband_dm_data(toas, model, dm_error=args.dmerror,
                                    add_noise=args.addnoise, seed=dm_seed)
    write_tim(args.timfile, toas)
    print(f"Wrote {toas.ntoas} simulated TOAs to {args.timfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
