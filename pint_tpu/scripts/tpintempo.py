"""Fit a timing model to TOAs from the command line.

Reference: `pintempo` (`/root/reference/src/pint/scripts/pintempo.py`):
load par + tim, compute pre-fit residuals, fit, print the summary, and
optionally write the post-fit par file and residuals.
"""

import argparse
import sys
import warnings

__all__ = ["main"]


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="pint_tpu command-line timing fit (cf. pintempo)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("parfile", help="model par file")
    parser.add_argument("timfile", help="TOA tim file")
    parser.add_argument("--fitter", default="auto",
                        choices=["auto", "wls", "gls", "downhill",
                                 "downhill_gls", "wideband",
                                 "wideband_downhill", "powell", "lm"],
                        help="fitter to use; auto picks GLS/wideband from "
                             "the model and data")
    parser.add_argument("--maxiter", type=int, default=None,
                        help="fit iterations (default: the chosen "
                             "fitter's own default)")
    parser.add_argument("--outfile", default=None,
                        help="write the post-fit model to this par file")
    parser.add_argument("--plotfile", default=None,
                        help="write pre/post-fit residuals (MJD, us, err) "
                             "to this text file")
    parser.add_argument("--ephem", default=None, help="ephemeris override")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress warnings")
    args = parser.parse_args(argv)
    if args.quiet:
        warnings.filterwarnings("ignore")

    from pint_tpu import fitter as F
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs

    model = get_model(args.parfile)
    kw = {"model": model}
    if args.ephem:
        kw["ephem"] = args.ephem
    toas = get_TOAs(args.timfile, **kw)
    print(f"Read {toas.ntoas} TOAs from {args.timfile}")

    prefit = Residuals(toas, model)
    print(f"Pre-fit weighted RMS: {prefit.rms_weighted()*1e6:.4f} us")
    if args.fitter == "auto":
        f = F.Fitter.auto(toas, model)
    else:
        cls = {"wls": F.WLSFitter, "gls": F.GLSFitter,
               "downhill": F.DownhillWLSFitter,
               "downhill_gls": F.DownhillGLSFitter,
               "wideband": F.WidebandTOAFitter,
               "wideband_downhill": F.WidebandDownhillFitter,
               "powell": F.PowellFitter, "lm": F.LMFitter}[args.fitter]
        f = cls(toas, model)
    f.fit_toas(**({} if args.maxiter is None
                  else {"maxiter": args.maxiter}))
    print(f"Fitted with {type(f).__name__}")
    print(f.get_summary())

    if args.plotfile:
        import numpy as np

        r = f.resids
        toa_r = r.toa if hasattr(r, "toa") else r
        mjd = np.asarray(toa_r.batch.tdbld)
        with open(args.plotfile, "w") as fh:
            fh.write("# MJD prefit_us postfit_us err_us\n")
            for row in zip(mjd, prefit.time_resids * 1e6,
                           toa_r.time_resids * 1e6, toa_r.get_data_error()):
                fh.write(" ".join(f"{v:.6f}" for v in row) + "\n")
        print(f"Wrote residuals to {args.plotfile}")
    if args.outfile:
        model.write_parfile(args.outfile,
                            comment="post-fit model written by tpintempo")
        print(f"Wrote post-fit model to {args.outfile}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
