"""pint_tpu — a TPU-native pulsar timing framework.

A ground-up JAX/XLA re-architecture with the capabilities of PINT (the
NANOGrav pulsar-timing package; reference layout surveyed in /root/repo/SURVEY.md):
TOA loading and clock correction, solar-system barycentering, a composable
physical timing model, phase residuals, and WLS/GLS/downhill/wideband fitting.

Design stance (vs the reference, see SURVEY.md §7):

* Times live on device as two-float ``(day:int, frac:float64)`` pairs
  (:mod:`pint_tpu.timescales`), and absolute pulse phase is accumulated in
  double-double arithmetic (:mod:`pint_tpu.dd`) — replacing the reference's
  ``np.longdouble`` (80-bit) dependency, which XLA/TPU does not have.
* Model components are pure jittable functions of ``(params, TOABatch)``;
  design matrices come from autodiff (jacfwd) rather than thousands of lines
  of hand-written derivatives (reference `src/pint/models/timing_model.py:2157`).
* Fits are jitted linear-algebra kernels (QR/Cholesky/eigh — chosen for
  float64 support on TPU) vmapped over grid points and pulsar ensembles, and
  shard_mapped over a `jax.sharding.Mesh` for multi-chip scale-out
  (replacing the reference's ProcessPoolExecutor, `src/pint/gridutils.py:322`).

Physical constants follow the reference's choices
(`src/pint/__init__.py:56-106`): IAU/tempo conventions.
"""

import os as _os

import jax

# Pulsar timing is meaningless in float32: absolute phase needs ~21 significant
# digits (handled by double-double on top of f64). Enable x64 before anything
# else in the package builds jitted functions.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the heavyweight fit programs (a wideband
# GLS step compiles for ~3 min cold) are identical across processes, so every
# pytest run / CLI invocation / bench subprocess should pay the compile once
# per machine, not once per process.  PINT_TPU_XLA_CACHE=0 disables; =1 (or
# unset) uses the default ~/.cache location; any other value is the cache
# BASE directory — entries land in <base>/<host-fingerprint> (see below).
# An explicit JAX_COMPILATION_CACHE_DIR (or a prior programmatic setting)
# wins and is used verbatim.
#
# MEASURED (2026-08, tunneled v5e): a cache HIT loads a big executable in
# ~10 s (trace + deserialize + upload over the ~10-20 MB/s tunnel) vs
# 120-160 s compiling cold — so a warm bench's "compile_s" is load cost,
# not a recompile.  The cache directory carries a HOST FINGERPRINT
# segment: XLA:CPU entries are AOT-compiled against the build host's CPU
# features, and loading them on a different machine generation logs
# "machine feature mismatch ... could lead to SIGILL" — a shared cache
# dir across hosts risks exactly that.


def _host_key() -> str:
    """8-hex fingerprint of the host CPU generation (the features XLA:CPU
    AOT results are specialized to)."""
    import hashlib
    import platform

    src = platform.machine() + platform.processor()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    src += line
                    break
    except OSError:
        pass
    return hashlib.sha1(src.encode()).hexdigest()[:8]


_cache_flag = _os.environ.get("PINT_TPU_XLA_CACHE", "1")
if _cache_flag != "0":
    if jax.config.jax_compilation_cache_dir is None:
        _base = _os.path.expanduser(
            _cache_flag if _cache_flag not in ("", "1") else
            "~/.cache/pint_tpu/xla")
        _dir = _os.path.join(_base, _host_key())
        # migrate pre-fingerprint flat entries once — ONLY for the
        # package-owned default location (a user-supplied base may be a
        # shared directory like ~/.cache whose files must not be
        # linked); foreign-host entries whose program keys never match
        # are simply dead files
        if _cache_flag in ("", "1") and _os.path.isdir(_base) \
                and not _os.path.isdir(_dir):
            try:
                _os.makedirs(_dir, exist_ok=True)
                for _f in _os.listdir(_base):
                    _src = _os.path.join(_base, _f)
                    if _os.path.isfile(_src):
                        try:
                            _os.link(_src, _os.path.join(_dir, _f))
                        except OSError:
                            pass
            except OSError:
                pass
        jax.config.update("jax_compilation_cache_dir", _dir)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in _os.environ:
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)

__version__ = "0.1.0"

# --- fundamental constants (SI) ----------------------------------------------
#: speed of light [m/s] (exact, SI definition)
c = 299792458.0
#: astronomical unit [m] (IAU 2012 exact)
AU = 149597870700.0
#: light-second [m]
ls = c
#: Julian year [s]
JULIAN_YEAR = 365.25 * 86400.0
#: seconds per day
SECS_PER_DAY = 86400.0
#: days per Julian century / millennium
DAYS_PER_CENTURY = 36525.0
#: MJD of the J2000.0 epoch (TT): 2000 Jan 1.5 TT
MJD_J2000 = 51544.5

# --- tempo/pulsar conventions -------------------------------------------------
#: Dispersion constant, tempo convention (reference `src/pint/__init__.py:90`):
#: delay[s] = DM[pc/cm^3] / (2.41e-4 * freq[MHz]^2).  This is *defined* as
#: 1/2.41e-4 exactly, not the more precise physical e^2/(2 pi m_e c) value,
#: for compatibility with tempo/tempo2.
DMconst = 1.0 / 2.41e-4  # s MHz^2 cm^3 / pc

#: GM_sun / c^3 [s] — solar mass in time units (IAU 2009 GM_sun)
GMsun = 1.32712440018e20  # m^3/s^2
Tsun = GMsun / c**3  # 4.92549094765e-06 s

#: planet masses in time units GM/c^3 [s], from the IAU 2009 system mass
#: ratios (same convention as reference `src/pint/__init__.py:81-88`);
#: Tearth includes the Moon.
Tmercury = Tsun / 6023600.0
Tvenus = Tsun / 408523.71
Tearth = Tsun / 328900.56
Tmars = Tsun / 3098708.0
Tjupiter = Tsun / 1047.3486
Tsaturn = Tsun / 3497.898
Turanus = Tsun / 22902.98
Tneptune = Tsun / 19412.24

# Planetary GM values [m^3/s^2] (IAU/DE421-era values, as used for Shapiro
# delays; reference `src/pint/__init__.py:92-106` uses the same bodies).
GM_BODY = {
    "sun": GMsun,
    "mercury": 2.2032e13,
    "venus": 3.24858592e14,
    "earth": 3.986004418e14,
    "moon": 4.9028e12,
    "mars": 4.282837e13,
    "jupiter": 1.26686534e17,
    "saturn": 3.7931187e16,
    "uranus": 5.793939e15,
    "neptune": 6.836529e15,
    "pluto": 8.71e11,
}
#: T_body = GM/c^3 [s] for Shapiro delay per body
T_BODY = {k: v / c**3 for k, v in GM_BODY.items()}

#: parsec [m] (exact from au and arcsec definition)
PARSEC = AU * 3600.0 * 180.0 / 3.141592653589793
#: kilometer per second in AU/day, etc. left to pint_tpu.units

#: mean obliquity of the ecliptic at J2000, IERS 2010 [arcsec]
OBLIQUITY_J2000_ARCSEC = 84381.406

# Re-exports of the most-used API surface (kept lazy-ish: these modules only
# depend on jax/numpy).
from pint_tpu.dd import DD  # noqa: E402,F401
from pint_tpu.phase import Phase  # noqa: E402,F401

__all__ = [
    "c",
    "AU",
    "ls",
    "DMconst",
    "Tsun",
    "GM_BODY",
    "T_BODY",
    "PARSEC",
    "SECS_PER_DAY",
    "MJD_J2000",
    "DD",
    "Phase",
    "__version__",
]
