"""The metrics plane: live Prometheus exposition, per-program cost
cards, and the bench-history regression gate (ISSUE 13).

The repo already *measures* almost everything that matters — dispatch
counters at the XLA boundary (:mod:`pint_tpu.profiling`), span timings
in the telemetry ring (:mod:`pint_tpu.telemetry`), collective bytes in
the compiled HLO (:mod:`pint_tpu.lint.hlo_audit`), and a bench JSON
trajectory (``BENCH_r0*.json``).  What it lacked was a *plane*: nothing
exposed those numbers live, tied them to what each compiled program
costs, or failed a PR when the trajectory regressed.  This module is
that plane, in three parts, stdlib-only like telemetry.py so a broken
jax install cannot take observability down with it:

* **registry** — lock-guarded counters / gauges / log2-bucketed latency
  histograms, fed with ZERO per-site edits: every ``profiling.count``
  site arrives through :func:`profiling.add_count_hook`, and every
  ``telemetry.span`` feeds a duration histogram keyed by span name
  through :func:`telemetry.add_span_end_hook`.  ``PINT_TPU_METRICS=0``
  is the master off-switch (the hooks stay registered but become
  no-ops, mirroring ``PINT_TPU_TELEMETRY=0``).

* **cost cards** — at ``aot.serve`` resolution (counter-neutral:
  ``lowered.cost_analysis()`` only, no extra ``backend_compile``) and
  at contract-audit / bench time (full: ``compiled.cost_analysis()``
  FLOPs/bytes plus the :func:`hlo_audit.memory_profile` per-device
  peak), a per-``(entry, digest)`` card records what each entrypoint
  program costs, so bench reports achieved-vs-peak FLOP/s per
  entrypoint instead of a bare wall.

* **exposure** — (1) an opt-in stdlib ``http.server`` thread
  (``PINT_TPU_METRICS_PORT``; port 0 picks an ephemeral port for
  tests) serving Prometheus text exposition at ``/metrics`` and the
  serve daemon's ``stats()`` JSON at ``/healthz``, wired into
  ``serve.TimingService`` and exercised by ``bench_serve``; (2) the
  regression gate — ``python -m pint_tpu.metrics compare OLD NEW``
  (also ``bench.py --compare``) diffs headline wall (tolerance),
  steady-state compiles/retraces (must stay ZERO), comm / all-gather
  bytes and serve p99 against a prior bench artifact and exits 1 with
  per-metric attribution, turning the ``BENCH_r0*.json`` pile into a
  CI-gateable series.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from pint_tpu import profiling, telemetry

__all__ = ["enable", "disable", "enabled", "inc", "set_gauge",
           "observe", "reset", "snapshot", "record_cost_card",
           "cost_cards", "harvest_lowered", "harvest_compiled",
           "render_prometheus", "parse_prometheus", "start_exporter",
           "Exporter", "load_bench_line", "check_schema", "compare",
           "main", "HIST_BUCKETS_MS"]

# --- master switch -----------------------------------------------------------

_enabled = os.environ.get("PINT_TPU_METRICS", "1") != "0"


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


# --- the registry ------------------------------------------------------------

#: guards every table below: count hooks arrive from serve worker
#: threads and scan drivers concurrently with an exporter scrape
_lock = threading.Lock()
_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}

#: log2 latency buckets in milliseconds, 2^-4 .. 2^14 (62 us .. 16 s):
#: wide enough for a timer flush at the bottom and a cold compile at
#: the top, cheap enough (19 floats) to render on every scrape
HIST_BUCKETS_MS: Tuple[float, ...] = tuple(
    float(2.0 ** e) for e in range(-4, 15))


class _Hist:
    """One cumulative-on-render histogram: per-bucket counts are stored
    non-cumulative (one increment per observe) and summed at render
    time, which keeps observe O(log n_buckets) lock-held work."""

    __slots__ = ("counts", "total", "n")

    def __init__(self) -> None:
        self.counts = [0] * (len(HIST_BUCKETS_MS) + 1)  # +1: +Inf
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        lo, hi = 0, len(HIST_BUCKETS_MS)
        while lo < hi:                      # first bucket with le >= v
            mid = (lo + hi) // 2
            if HIST_BUCKETS_MS[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1
        self.total += v
        self.n += 1


_hists: Dict[str, _Hist] = {}

#: (entry, digest) -> cost card dict
_cost_cards: Dict[Tuple[str, str], Dict[str, Any]] = {}


def inc(name: str, n: float = 1) -> None:
    """Increment counter ``name`` (no-op when disabled)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def set_gauge(name: str, value: float) -> None:
    if not _enabled:
        return
    with _lock:
        _gauges[name] = float(value)


def observe(name: str, value_ms: float) -> None:
    """Record one latency sample (milliseconds) in histogram ``name``."""
    if not _enabled:
        return
    if not isinstance(value_ms, (int, float)) or not math.isfinite(
            value_ms):
        return
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = _Hist()
        h.observe(float(value_ms))


def reset() -> None:
    """Clear every table (tests; the bench legs snapshot-delta via
    profiling, but the metrics registry is process-cumulative)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _cost_cards.clear()


def snapshot() -> Dict[str, Any]:
    """Plain-data copy of the registry (tests and ``/healthz``)."""
    with _lock:
        hists = {}
        for name, h in _hists.items():
            hists[name] = {"n": h.n, "sum_ms": h.total,
                           "counts": list(h.counts)}
        return {"counters": dict(_counters), "gauges": dict(_gauges),
                "histograms": hists,
                "cost_cards": [dict(c) for c in _cost_cards.values()]}


# --- zero-per-site-edit feeds ------------------------------------------------

def _on_count(name: str, n: int) -> None:
    """``profiling.add_count_hook`` target — every existing
    ``profiling.count`` site becomes a Prometheus counter."""
    inc(name, n)


def _on_span_end(name: str, dur_ms: float, err: Optional[str]) -> None:
    """``telemetry.add_span_end_hook`` target — every span duration
    lands in the histogram keyed by span name; errored spans also bump
    a counter so a failing path is visible without log archaeology."""
    observe(name, dur_ms)
    if err is not None:
        inc(f"span_errors.{name}")


profiling.add_count_hook(_on_count)
telemetry.add_span_end_hook(_on_span_end)


# --- cost cards --------------------------------------------------------------

def record_cost_card(entry: str, card: Dict[str, Any]) -> None:
    """Merge a card for ``(entry, digest)``.  Numeric zeros never
    overwrite a known nonzero (the counter-neutral aot harvest carries
    flops but no memory peak; the audit harvest fills the peak in
    later without erasing the flops)."""
    digest = str(card.get("digest", ""))
    key = (entry, digest)
    with _lock:
        cur = _cost_cards.setdefault(
            key, {"entry": entry, "digest": digest})
        for k, v in card.items():
            if k in ("entry", "digest"):
                continue
            if (isinstance(v, (int, float)) and not v
                    and cur.get(k)):
                continue
            cur[k] = v


def cost_cards() -> List[Dict[str, Any]]:
    """Every recorded card, ``(entry, digest)``-sorted copies."""
    with _lock:
        cards = [dict(c) for c in _cost_cards.values()]
    return sorted(cards, key=lambda c: (c["entry"], c["digest"]))


def _cost_analysis(obj) -> Dict[str, Any]:
    """``.cost_analysis()`` across jax versions: some return a dict,
    some a one-element list of dicts; anything else counts as empty."""
    try:
        ca = obj.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def harvest_lowered(entry: str, lowered, digest: str = "",
                    source: str = "") -> Optional[Dict[str, Any]]:
    """Counter-neutral harvest from a ``jax.stages.Lowered`` — the
    ``aot.serve`` resolution path rides this: ``lowered.
    cost_analysis()`` is a host-side estimate that triggers no
    ``backend_compile`` and no retrace, so the aot zero-compile
    contract survives the harvest.  Best-effort: returns the card or
    None, never raises."""
    if not _enabled:
        return None
    try:
        ca = _cost_analysis(lowered)
        card = {"entry": entry, "digest": digest, "source": source,
                "flops": float(ca.get("flops", 0.0) or 0.0),
                "bytes_accessed": float(
                    ca.get("bytes accessed", 0.0) or 0.0)}
        record_cost_card(entry, card)
        return card
    except Exception:
        return None


def harvest_compiled(entry: str, compiled, digest: str = "",
                     source: str = "") -> Optional[Dict[str, Any]]:
    """Full harvest from a ``Compiled``: cost_analysis FLOPs/bytes plus
    the :func:`hlo_audit.memory_profile` per-device sizes.  Only called
    where a compile already happened (contract audit, bench cost-card
    leg) — never on the aot hot path.  Best-effort, never raises."""
    if not _enabled:
        return None
    try:
        from pint_tpu.lint import hlo_audit

        ca = _cost_analysis(compiled)
        mem = hlo_audit.memory_profile(compiled)
        card = {"entry": entry, "digest": digest, "source": source,
                "flops": float(ca.get("flops", 0.0) or 0.0),
                "bytes_accessed": float(
                    ca.get("bytes accessed", 0.0) or 0.0)}
        card.update(mem)
        record_cost_card(entry, card)
        return card
    except Exception:
        return None


# --- Prometheus text exposition ----------------------------------------------

def _esc_label(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(extra_stats: Optional[Dict[str, Any]] = None
                      ) -> str:
    """The registry as Prometheus text exposition (format 0.0.4).

    Families: ``pint_tpu_counter_total{name=}``,
    ``pint_tpu_gauge{name=}``, ``pint_tpu_span_ms`` histograms
    (cumulative ``_bucket{le=}`` + ``_sum`` + ``_count``),
    ``pint_tpu_cost_card_{flops,bytes_accessed,peak_bytes}{entry=,
    digest=}``, and — when ``extra_stats`` (the serve daemon's
    ``stats()``) is given — ``pint_tpu_serve_stat{name=}`` gauges for
    every scalar numeric stat."""
    snap = snapshot()
    out: List[str] = []

    def fam(name: str, typ: str, help_: str) -> None:
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {typ}")

    # gateway front-door families (ISSUE 19): the gateway feeds plain
    # profiling.count names ("gateway.request.<tenant>.<code>",
    # "gateway.queue_depth.<priority>" as +-1 deltas) with zero
    # per-site metrics edits; exposition re-labels them here so
    # dashboards get real tenant/code/priority label axes instead of
    # one flat name string
    gw_req: Dict[str, float] = {}
    gw_depth: Dict[str, float] = {}
    generic: Dict[str, float] = {}
    for name, v in snap["counters"].items():
        if name.startswith("gateway.request."):
            gw_req[name[len("gateway.request."):]] = v
        elif name.startswith("gateway.queue_depth."):
            gw_depth[name[len("gateway.queue_depth."):]] = v
        else:
            generic[name] = v
    fam("pint_tpu_counter_total", "counter",
        "pint_tpu.profiling dispatch/runtime counters")
    for name in sorted(generic):
        out.append('pint_tpu_counter_total{name="%s"} %s'
                   % (_esc_label(name), _fmt(generic[name])))
    if gw_req:
        fam("pint_tpu_gateway_requests_total", "counter",
            "gateway HTTP responses by tenant and status code")
        for key in sorted(gw_req):
            tenant, _, code = key.rpartition(".")
            out.append(
                'pint_tpu_gateway_requests_total{tenant="%s",'
                'code="%s"} %s'
                % (_esc_label(tenant), _esc_label(code),
                   _fmt(gw_req[key])))
    if gw_depth:
        fam("pint_tpu_gateway_queue_depth", "gauge",
            "gateway jobs admitted and not yet resolved, by priority "
            "class")
        for prio in sorted(gw_depth):
            out.append('pint_tpu_gateway_queue_depth{priority="%s"} %s'
                       % (_esc_label(prio), _fmt(gw_depth[prio])))
    fam("pint_tpu_gauge", "gauge", "pint_tpu point-in-time gauges")
    for name in sorted(snap["gauges"]):
        out.append('pint_tpu_gauge{name="%s"} %s'
                   % (_esc_label(name), _fmt(snap["gauges"][name])))
    fam("pint_tpu_span_ms", "histogram",
        "telemetry span durations (ms) by span name")
    for name in sorted(snap["histograms"]):
        h = snap["histograms"][name]
        lab = _esc_label(name)
        cum = 0
        for le, c in zip(HIST_BUCKETS_MS, h["counts"]):
            cum += c
            out.append('pint_tpu_span_ms_bucket{name="%s",le="%s"} %d'
                       % (lab, _fmt(le), cum))
        cum += h["counts"][-1]
        out.append('pint_tpu_span_ms_bucket{name="%s",le="+Inf"} %d'
                   % (lab, cum))
        out.append('pint_tpu_span_ms_sum{name="%s"} %s'
                   % (lab, _fmt(h["sum_ms"])))
        out.append('pint_tpu_span_ms_count{name="%s"} %d'
                   % (lab, h["n"]))
    for field, help_ in (
            ("flops", "estimated FLOPs per execution"),
            ("bytes_accessed", "estimated bytes accessed per execution"),
            ("peak_bytes", "per-device peak memory bound")):
        mname = f"pint_tpu_cost_card_{field}"
        fam(mname, "gauge", f"program cost card: {help_}")
        for card in snap["cost_cards"]:
            v = card.get(field)
            if not isinstance(v, (int, float)):
                continue
            out.append('%s{entry="%s",digest="%s"} %s'
                       % (mname, _esc_label(card["entry"]),
                          _esc_label(card["digest"]), _fmt(v)))
    if extra_stats is not None:
        fam("pint_tpu_serve_stat", "gauge",
            "TimingService.stats() scalar snapshot")
        for key in sorted(extra_stats):
            v = extra_stats[key]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            out.append('pint_tpu_serve_stat{name="%s"} %s'
                       % (_esc_label(key), _fmt(v)))
        # the per-bucket circuit breaker (ISSUE 18): the stats() map
        # {bucket repr: "closed"|"open"|"half_open"} is not a scalar,
        # so it renders as its own labelled gauge (0/1/2) — what a
        # dashboard alerts on when a bucket is thrown onto eager
        breaker = extra_stats.get("breaker_state")
        if isinstance(breaker, dict) and breaker:
            fam("pint_tpu_serve_breaker", "gauge",
                "per-bucket circuit breaker (0=closed, 1=half_open, "
                "2=open)")
            code = {"closed": 0, "half_open": 1, "open": 2}
            for bucket in sorted(breaker):
                v = code.get(str(breaker[bucket]))
                if v is None:
                    continue
                out.append('pint_tpu_serve_breaker{bucket="%s"} %d'
                           % (_esc_label(str(bucket)), v))
    return "\n".join(out) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+"
    r"(?P<value>[+-]?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|Inf|NaN))$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                         ...]], float]:
    """Strict parser for the exposition format: every non-comment,
    non-blank line must be a valid sample.  Returns
    ``{(metric_name, ((label, value), ...)): float}`` with labels
    sorted and unescaped.  Raises ``ValueError`` on any malformed
    line — the bench scrape check rides this."""
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for ln in text.splitlines():
        if not ln.strip() or ln.startswith("#"):
            continue
        m = _SAMPLE_RE.match(ln)
        if m is None:
            raise ValueError(f"malformed exposition line: {ln!r}")
        labels: List[Tuple[str, str]] = []
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL_RE.finditer(raw):
                # single-pass unescape: sequential .replace would turn
                # an escaped backslash followed by 'n' into a newline
                val = re.sub(
                    r"\\(.)",
                    lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
                    lm.group(2))
                labels.append((lm.group(1), val))
                consumed = lm.end()
            leftover = raw[consumed:].strip().strip(",")
            if leftover:
                raise ValueError(f"malformed labels in: {ln!r}")
        samples[(m.group("name"), tuple(sorted(labels)))] = float(
            m.group("value"))
    return samples


# --- the /metrics endpoint ---------------------------------------------------

class Exporter:
    """An opt-in stdlib HTTP thread serving ``/metrics`` (Prometheus
    text) and ``/healthz`` (``stats_fn()`` JSON).  Daemon thread: it
    can never hold a drained process alive; :meth:`stop` shuts it down
    explicitly (serve exposes that as ``stop_metrics``)."""

    def __init__(self, server, thread) -> None:
        self._server = server
        self._thread = thread
        self.port: int = server.server_address[1]
        self.url: str = f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
            self._thread.join(timeout=5.0)
        except Exception:
            pass


def start_exporter(port: Optional[int] = None,
                   stats_fn: Optional[Callable[[], Dict[str, Any]]]
                   = None) -> Optional[Exporter]:
    """Start the metrics endpoint.  ``port`` defaults to
    ``PINT_TPU_METRICS_PORT`` (unset/empty -> no exporter, the normal
    library posture); 0 binds an ephemeral port (tests read
    ``exporter.port``).  Returns None when opted out, disabled, or the
    bind fails (a second daemon on the same port must not crash the
    first's host process — the failure is a telemetry warning)."""
    if port is None:
        raw = os.environ.get("PINT_TPU_METRICS_PORT", "").strip()
        if not raw:
            return None
        try:
            port = int(raw)
        except ValueError:
            telemetry.warn("metrics.bad_port", value=raw)
            return None
    if not _enabled:
        return None
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *args):  # noqa: D102 — silence stderr
            pass

        def do_GET(self):
            try:
                if self.path.split("?")[0] == "/metrics":
                    stats = None
                    if stats_fn is not None:
                        try:
                            stats = stats_fn()
                        except Exception:
                            stats = None
                    body = render_prometheus(stats).encode("utf-8")
                    ctype = ("text/plain; version=0.0.4; "
                             "charset=utf-8")
                elif self.path.split("?")[0] == "/healthz":
                    doc: Dict[str, Any] = {"ok": True}
                    if stats_fn is not None:
                        try:
                            doc["stats"] = stats_fn()
                        except Exception as e:
                            doc = {"ok": False, "error": str(e)}
                    body = json.dumps(
                        doc, sort_keys=True).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except Exception:
                pass  # a broken scrape must never hurt the daemon

    try:
        server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", int(port)), _Handler)
    except OSError as e:
        telemetry.warn("metrics.bind_failed", port=int(port),
                       error=str(e))
        return None
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name="pint-tpu-metrics",
                              kwargs={"poll_interval": 0.2},
                              daemon=True)
    thread.start()
    exp = Exporter(server, thread)
    telemetry.event("metrics.exporter_started", port=exp.port)
    return exp


# --- bench-history regression gate -------------------------------------------

def load_bench_line(path: str) -> Optional[Dict[str, Any]]:
    """Load one bench artifact: either a raw bench JSON line or the
    ``BENCH_r0*.json`` wrapper ``{"n","cmd","rc","tail","parsed"}``
    (the ``parsed`` payload is the line).  Returns None for an *empty
    round* (wrapper whose ``parsed`` is null with a clean rc — rounds
    before bench.py existed); raises ``ValueError`` for anything
    malformed."""
    with open(path, "r", encoding="utf-8") as fh:
        try:
            doc = json.load(fh)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not JSON ({e})") from None
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: bench artifact must be a JSON object")
    if "parsed" in doc and "cmd" in doc:        # the wrapper shape
        parsed = doc["parsed"]
        if parsed is None:
            if doc.get("rc", 0) == 0 and not str(
                    doc.get("tail", "")).strip():
                return None                     # empty round, skip
            raise ValueError(
                f"{path}: wrapper has no parsed payload but a "
                f"non-clean rc/tail — truncated or hand-edited")
        if not isinstance(parsed, dict):
            raise ValueError(f"{path}: wrapper 'parsed' is not an "
                             f"object")
        return parsed
    return doc


def check_schema(doc: Dict[str, Any]) -> List[str]:
    """Problems with one bench line (empty list = valid).  The rule set
    is the value-or-error contract every round since r02 satisfies:
    a ``metric`` string, a ``unit`` string, and EITHER a numeric
    ``value`` OR an ``error`` string (the r05 wedged-tunnel shape);
    when the newer axes are present they must be well-typed."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["bench line is not a JSON object"]
    if not isinstance(doc.get("metric"), str):
        problems.append("missing/non-string 'metric'")
    if not isinstance(doc.get("unit"), str):
        problems.append("missing/non-string 'unit'")
    val = doc.get("value")
    if not isinstance(val, (int, float)) or isinstance(val, bool):
        if not isinstance(doc.get("error"), str):
            problems.append(
                "neither a numeric 'value' nor an 'error' string")
    dc = doc.get("dispatch_counters")
    if dc is not None:
        if not isinstance(dc, dict):
            problems.append("'dispatch_counters' is not an object")
        else:
            for key in ("compiles", "retraces", "dispatches"):
                if not isinstance(dc.get(key), int):
                    problems.append(
                        f"dispatch_counters.{key} missing/non-int")
    for key in ("comm_bytes", "all_gather_bytes"):
        if key in doc and not isinstance(doc[key], int):
            problems.append(f"'{key}' is not an int")
    if "submetrics" in doc and not isinstance(doc["submetrics"], dict):
        problems.append("'submetrics' is not an object")
    for key in ("precflow_clean", "concurrency_clean"):
        if key in doc and doc[key] is not None \
                and not isinstance(doc[key], bool):
            problems.append(f"'{key}' is not a bool/null")
    cc = doc.get("cost_cards")
    if cc is not None:
        if not isinstance(cc, dict):
            problems.append("'cost_cards' is not an object")
        else:
            for entry, card in cc.items():
                if not isinstance(card, dict):
                    problems.append(f"cost_cards.{entry} not an object")
                    continue
                for field in ("flops", "bytes_accessed", "peak_bytes"):
                    if not isinstance(card.get(field), (int, float)):
                        problems.append(
                            f"cost_cards.{entry}.{field} "
                            f"missing/non-numeric")
    return problems


def _num(doc: Dict[str, Any], *path) -> Optional[float]:
    cur: Any = doc
    for p in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(p)
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def compare(old: Dict[str, Any], new: Dict[str, Any],
            tolerance: float = 0.25, p99_tolerance: float = 0.5
            ) -> List[Dict[str, Any]]:
    """The regression gate: failures (empty = pass) comparing a new
    bench line against a prior one.  Axes:

    * headline wall ``value`` — may grow at most ``tolerance``
      (fractional; walls are noisy, so the default is generous);
    * steady-state ``compiles`` / ``retraces`` — must be ZERO in the
      new line whenever it carries dispatch counters (absolute, not
      relative: the whole point of the warm contract);
    * ``comm_bytes`` — bounded growth by ``tolerance``;
    * ``all_gather_bytes`` — must not exceed the old value at all (the
      no-implicit-gather invariant as a gate);
    * ``serve_p99_ms`` — bounded growth by ``p99_tolerance``;
    * ``sim_toas_per_sec`` / ``pta_fleet_fits_per_sec`` — PTA-scale
      throughput may shrink at most ``tolerance``;
    * ``serve_quarantined`` / ``serve_deadline_miss_fraction`` — must
      be ZERO whenever the new line carries them (absolute, like the
      compile axes): the healthy-path bench has no poison jobs and no
      expiring deadlines, so any nonzero value means containment fired
      on clean traffic — a regression, not noise;
    * ``gateway_p99_ms`` — bounded growth by ``p99_tolerance``;
      ``gateway_dedup_hits`` must be ZERO and ``gateway_retries`` may
      not exceed the prior round (clean traffic never retries or
      replays).

    An axis absent from either line is skipped — early rounds carry
    only the headline, and a gate that fails on *missing history* would
    make the series un-adoptable."""
    failures: List[Dict[str, Any]] = []

    def fail(metric: str, old_v, new_v, why: str) -> None:
        failures.append({"metric": metric, "old": old_v, "new": new_v,
                         "why": why})

    ov, nv = _num(old, "value"), _num(new, "value")
    if ov is not None and nv is not None and ov > 0:
        if nv > ov * (1.0 + tolerance):
            fail("value", ov, nv,
                 f"headline wall grew {nv / ov - 1.0:+.1%} "
                 f"(> +{tolerance:.0%} tolerance)")
    for counter in ("compiles", "retraces"):
        nc = _num(new, "dispatch_counters", counter)
        if nc is not None and nc != 0:
            fail(f"dispatch_counters.{counter}",
                 _num(old, "dispatch_counters", counter), nc,
                 f"steady-state {counter} must stay 0 "
                 f"(got {int(nc)})")
    ob, nb = _num(old, "comm_bytes"), _num(new, "comm_bytes")
    if ob is not None and nb is not None and ob > 0:
        if nb > ob * (1.0 + tolerance):
            fail("comm_bytes", ob, nb,
                 f"collective bytes grew {nb / ob - 1.0:+.1%} "
                 f"(> +{tolerance:.0%} tolerance)")
    og = _num(old, "all_gather_bytes")
    ng = _num(new, "all_gather_bytes")
    if og is not None and ng is not None and ng > og:
        fail("all_gather_bytes", og, ng,
             "all-gather bytes exceeded the prior round "
             "(no-implicit-gather invariant)")
    # latency axes: in-process serving (ISSUE 18) and the network
    # front door (ISSUE 19) share the p99 growth bound
    for axis in ("serve_p99_ms", "gateway_p99_ms"):
        op, np_ = _num(old, axis), _num(new, axis)
        if op is not None and np_ is not None and op > 0:
            if np_ > op * (1.0 + p99_tolerance):
                fail(axis, op, np_,
                     f"{axis.split('_')[0]} p99 grew "
                     f"{np_ / op - 1.0:+.1%} "
                     f"(> +{p99_tolerance:.0%} tolerance)")
    # PTA-scale throughput axes (ISSUE 15): simulation and whole-array
    # fit rates may not drop below (1 - tolerance) of the prior round;
    # rounds predating the pta leg skip via the absent-axis rule
    for axis in ("sim_toas_per_sec", "pta_fleet_fits_per_sec"):
        oa, na = _num(old, axis), _num(new, axis)
        if oa is not None and na is not None and oa > 0:
            if na < oa * (1.0 - tolerance):
                fail(axis, oa, na,
                     f"throughput dropped {na / oa - 1.0:+.1%} "
                     f"(> -{tolerance:.0%} tolerance)")
    # serve containment axes (ISSUE 18): the healthy-path bench must
    # never quarantine a job or miss a deadline — nonzero means the
    # blast-radius machinery fired on clean traffic.  Absolute (like
    # the compile axes); absent on pre-containment rounds -> skipped
    for axis in ("serve_quarantined", "serve_deadline_miss_fraction"):
        na = _num(new, axis)
        if na is not None and na != 0:
            fail(axis, _num(old, axis), na,
                 f"healthy-path {axis} must stay 0 (got {na:g})")
    # gateway exactly-once axis (ISSUE 19): on clean bench traffic
    # with distinct idempotency keys, dedup replays mean the harness
    # retried something it should not have — absolute zero, and a
    # bounded retry budget on the front door
    na = _num(new, "gateway_dedup_hits")
    if na is not None and na != 0:
        fail("gateway_dedup_hits", _num(old, "gateway_dedup_hits"),
             na, f"healthy-path gateway_dedup_hits must stay 0 "
                 f"(got {na:g})")
    og, ng = _num(old, "gateway_retries"), _num(new, "gateway_retries")
    if og is not None and ng is not None and ng > og:
        fail("gateway_retries", og, ng,
             "healthy-path gateway retries exceeded the prior round")
    # concurrency audit verdict (ISSUE 20): like the steady-compile
    # axes, absolute — an explicit False means the lock-guard/lock-
    # order/signal/hook rules found something, regardless of the prior
    # round.  Bools are invisible to _num, so read the dict directly;
    # null/absent (skipped or pre-audit round) passes
    if new.get("concurrency_clean") is False:
        fail("concurrency_clean", old.get("concurrency_clean"), False,
             "concurrency audit reported findings "
             "(must stay clean, like steady compiles)")
    return failures


# --- CLI ---------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    """``python -m pint_tpu.metrics compare OLD.json NEW.json`` — the
    bench-history regression gate.  Exit 0 on pass, 1 on regression
    (one attribution line per failed metric), 2 on unusable input.
    ``--schema-only`` validates any number of bench artifacts
    (including the ``BENCH_r0*.json`` wrappers) without comparing."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m pint_tpu.metrics",
        description="pint_tpu metrics plane CLI.")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_cmp = sub.add_parser(
        "compare", help="gate a new bench line against a prior one")
    p_cmp.add_argument("files", nargs="+",
                       help="OLD.json NEW.json (or any number of "
                            "files with --schema-only)")
    p_cmp.add_argument("--tolerance", type=float, default=0.25,
                       help="allowed fractional wall/bytes growth "
                            "(default 0.25)")
    p_cmp.add_argument("--p99-tolerance", type=float, default=0.5,
                       help="allowed fractional serve-p99 growth "
                            "(default 0.5)")
    p_cmp.add_argument("--schema-only", action="store_true",
                       help="validate artifact schemas, no diff")
    ns = parser.parse_args(argv)

    if ns.schema_only:
        rc = 0
        for path in ns.files:
            try:
                doc = load_bench_line(path)
            except (OSError, ValueError) as e:
                print(json.dumps({"file": path, "ok": False,
                                  "problems": [str(e)]}))
                rc = 2
                continue
            if doc is None:
                print(json.dumps({"file": path, "ok": True,
                                  "empty_round": True}))
                continue
            problems = check_schema(doc)
            print(json.dumps({"file": path, "ok": not problems,
                              "problems": problems}))
            if problems:
                rc = 2
        return rc

    if len(ns.files) != 2:
        print("compare takes exactly 2 files: OLD.json NEW.json",
              file=__import__("sys").stderr)
        return 2
    docs = []
    for path in ns.files:
        try:
            doc = load_bench_line(path)
        except (OSError, ValueError) as e:
            print(json.dumps({"ok": False, "error": str(e)}))
            return 2
        if doc is None:
            print(json.dumps({"ok": False,
                              "error": f"{path}: empty round has no "
                                       f"comparable payload"}))
            return 2
        problems = check_schema(doc)
        if problems:
            print(json.dumps({"ok": False, "file": path,
                              "problems": problems}))
            return 2
        docs.append(doc)
    failures = compare(docs[0], docs[1], tolerance=ns.tolerance,
                       p99_tolerance=ns.p99_tolerance)
    print(json.dumps({"ok": not failures, "old": ns.files[0],
                      "new": ns.files[1], "failures": failures},
                     sort_keys=True))
    return 1 if failures else 0


if __name__ == "__main__":  # pragma: no cover — exercised by CLI tests
    import sys

    sys.exit(main())
