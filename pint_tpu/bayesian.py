"""Bayesian timing interface: lnprior / prior_transform / lnlikelihood /
lnposterior.

Reference: `BayesianTiming` (`/root/reference/src/pint/bayesian.py:12`),
which exposes the same four functions for use with external samplers, with
params given in par-file ("fitting") units.  Two TPU-native upgrades over
the reference:

* every function here is **jit-compiled, vmappable and differentiable**
  (the reference's are pure-python loops, and its MCMC cannot use
  gradients), enabling the HMC sampler in :mod:`pint_tpu.mcmc` and
  device-resident ensemble sampling;
* the **GLS likelihood for correlated noise is implemented** (Woodbury
  form with log-determinant, Lentati+ 2013) — the reference raises
  NotImplementedError for that case (`bayesian.py:113-121`).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals, raw_phase_resids
from pint_tpu.utils import woodbury_dot

__all__ = ["UniformPrior", "NormalPrior", "BayesianTiming",
           "default_prior_info"]

LOG2PI = float(np.log(2.0 * np.pi))


class UniformPrior:
    """Uniform prior on [pmin, pmax]."""

    def __init__(self, pmin: float, pmax: float):
        if not pmax > pmin:
            raise ValueError(f"need pmax > pmin, got [{pmin}, {pmax}]")
        self.pmin, self.pmax = float(pmin), float(pmax)

    def logpdf(self, x):
        inb = (x >= self.pmin) & (x <= self.pmax)
        return jnp.where(inb, -jnp.log(self.pmax - self.pmin), -jnp.inf)

    def ppf(self, q):
        return self.pmin + q * (self.pmax - self.pmin)


class NormalPrior:
    """Normal prior with mean mu and width sigma."""

    def __init__(self, mu: float, sigma: float):
        if not sigma > 0:
            raise ValueError("sigma must be positive")
        self.mu, self.sigma = float(mu), float(sigma)

    def logpdf(self, x):
        z = (x - self.mu) / self.sigma
        return -0.5 * (z * z + LOG2PI) - jnp.log(self.sigma)

    def ppf(self, q):
        from jax.scipy.special import ndtri

        return self.mu + self.sigma * ndtri(q)


def _make_prior(spec: dict):
    distr = spec.get("distr", "uniform")
    if distr == "uniform":
        return UniformPrior(spec["pmin"], spec["pmax"])
    if distr == "normal":
        return NormalPrior(spec["mu"], spec["sigma"])
    raise NotImplementedError(
        f"only uniform and normal priors are supported, not {distr!r} "
        "(reference bayesian.py:45-49 has the same restriction)")


def default_prior_info(model: TimingModel, nsigma: float = 20.0
                       ) -> Dict[str, dict]:
    """Uniform priors of half-width ``nsigma * uncertainty`` about each
    free parameter's current value — a convenience the reference leaves to
    the user; parameters without uncertainties must still be given priors
    explicitly."""
    out = {}
    for name in model.free_params:
        par = model[name]
        if par.uncertainty:
            v = float(par.value if np.isscalar(par.value) or
                      isinstance(par.value, float) else par.mjd_float)
            w = nsigma * float(par.uncertainty)
            out[name] = {"distr": "uniform", "pmin": v - w, "pmax": v + w}
    return out


class BayesianTiming:
    """Jit-pure Bayesian timing posterior (reference `BayesianTiming`,
    `/root/reference/src/pint/bayesian.py:12`).

    ``params`` arrays are in par-file units, ordered as
    ``param_labels`` (= the model's free parameters).  All four methods
    accept 1-D arrays; the underscored ``_fn`` attributes are the raw
    jitted closures for samplers (`lnposterior_fn` composes with
    `jax.vmap` / `jax.grad`).
    """

    def __init__(self, model: TimingModel, toas,
                 use_pulse_numbers: bool = False,
                 prior_info: Optional[Dict[str, dict]] = None):
        self.model = model
        self.toas = toas
        self.track_mode = "use_pulse_numbers" if use_pulse_numbers \
            else "nearest"
        self.is_wideband = toas.is_wideband
        self.param_labels: List[str] = list(model.free_params)
        self.nparams = len(self.param_labels)
        if self.nparams == 0:
            raise ValueError("model has no free parameters")

        info = dict(prior_info or {})
        missing = [n for n in self.param_labels if n not in info]
        if missing:
            raise AttributeError(
                f"prior is not set for free parameter(s) {missing}; pass "
                "prior_info entries for them, or fit the model first so "
                "default_prior_info can derive widths from uncertainties")
        self.priors = [_make_prior(info[n]) for n in self.param_labels]

        self._build()

    # -- jit closures ------------------------------------------------------
    def _build(self):
        model, names = self.model, self.param_labels
        resids = Residuals(self.toas, model, track_mode=self.track_mode)
        self.resids = resids
        batch, p0 = resids.batch, resids.pdict
        calc = model.calc
        track = resids.track_mode
        # par-file value of each free parameter at the pytree reference
        # point, and d(device)/d(par-unit)
        self._ref = np.array([self._par_value(n) for n in names])
        self._units = np.array(model.fit_units(names))
        refs = jnp.asarray(self._ref)
        units = jnp.asarray(self._units)
        correlated = model.has_correlated_errors
        wideband = self.is_wideband
        if wideband:
            dm_index, dm_data, dm_error = self.toas.get_dm_data()
            idx = jnp.asarray(dm_index)
            dmv = jnp.asarray(dm_data)
            dme = jnp.asarray(dm_error)

        def lnlike_off(dx):
            # dx: offsets from the reference values, par units.  Working in
            # offsets avoids the catastrophic quantization of e.g.
            # F0 = 346.53... +- 2e-11 (a ~350-ulp posterior) that sampling
            # raw par values would suffer.
            p = model.with_x(p0, dx * units, names)
            r_cyc = raw_phase_resids(calc, p, batch, track,
                                     subtract_mean=False, use_weights=False)
            from pint_tpu.models.timing_model import pv

            r = r_cyc / pv(p, "F0")
            sigma = model.scaled_toa_uncertainty(p, batch) * 1e-6
            w = 1.0 / sigma**2
            # the phase offset is profiled out analytically (the reference
            # subtracts the weighted mean the same way, residuals.py:442)
            off = jnp.sum(r * w) / jnp.sum(w)
            r = r - off
            if correlated:
                U = model.noise_basis(p)
                phi = model.noise_weights(p)
                phi = jnp.where(phi > 0.0, phi, 1e-30)
                dot, logdet = woodbury_dot(sigma**2, U, phi, r, r)
                ll = -0.5 * (dot + logdet + r.shape[0] * LOG2PI)
            else:
                chi2 = jnp.sum((r / sigma) ** 2)
                logdet = 2.0 * jnp.sum(jnp.log(sigma))
                ll = -0.5 * (chi2 + logdet + r.shape[0] * LOG2PI)
            if wideband:
                r_dm = dmv - model.total_dm(p, batch)[idx]
                sdm = model.scaled_dm_uncertainty(
                    p, batch, jnp.zeros(batch.ntoas).at[idx].set(dme))[idx]
                ll = ll - 0.5 * (jnp.sum((r_dm / sdm) ** 2)
                                 + 2.0 * jnp.sum(jnp.log(sdm))
                                 + r_dm.shape[0] * LOG2PI)
            return ll

        priors = list(self.priors)

        def lnprior(params):
            terms = [pr.logpdf(params[i]) for i, pr in enumerate(priors)]
            return jnp.sum(jnp.stack(terms))

        def lnpost_off(dx):
            lp = lnprior(refs + dx)
            # evaluate the likelihood only where the prior is finite
            # (jit-safe: compute and mask)
            ll = lnlike_off(dx)
            return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

        #: offset-space closures — the preferred sampler interface
        self.lnlikelihood_offset_fn = jax.jit(lnlike_off)
        self.lnposterior_offset_fn = jax.jit(lnpost_off)
        #: reference-parity closures over raw par-unit values (these
        #: re-derive the offset by subtraction, so they inherit the par
        #: value's ulp quantization — fine for evaluation, poor for
        #: sampling tightly-determined parameters)
        self.lnlikelihood_fn = jax.jit(lambda params: lnlike_off(params - refs))
        self.lnprior_fn = jax.jit(lnprior)
        self.lnposterior_fn = jax.jit(lambda params: lnpost_off(params - refs))

    def _par_value(self, name: str) -> float:
        par = self.model[name]
        try:
            return float(par.value)
        except (TypeError, ValueError):
            return float(par.mjd_float)

    # -- reference-parity methods -----------------------------------------
    def lnprior(self, params) -> float:
        return float(self.lnprior_fn(jnp.asarray(params, jnp.float64)))

    def lnlikelihood(self, params) -> float:
        return float(self.lnlikelihood_fn(jnp.asarray(params, jnp.float64)))

    def lnposterior(self, params) -> float:
        return float(self.lnposterior_fn(jnp.asarray(params, jnp.float64)))

    def prior_transform(self, cube):
        cube = np.asarray(cube)
        return np.array([np.asarray(pr.ppf(c))
                         for pr, c in zip(self.priors, cube)])

    def scales(self) -> np.ndarray:
        """Per-parameter scale guesses (par units) for sampler seeding:
        prior sigma, or 1/100 of a uniform prior's width."""
        out = []
        for pr in self.priors:
            if isinstance(pr, NormalPrior):
                out.append(pr.sigma)
            else:
                out.append((pr.pmax - pr.pmin) / 100.0)
        return np.array(out)

    def start_point(self) -> np.ndarray:
        """Current model values (prior centers for ppf=0.5 fallback)."""
        return self._ref.copy()
