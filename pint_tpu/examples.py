"""Shared example configurations (the reference ships example par files
via `pint.config`/`src/pint/data/examples`; here the flagship bench/test
configuration lives in one place so bench.py, __graft_entry__.py and the
test suites cannot drift apart)."""

from __future__ import annotations

import warnings

import numpy as np

#: J0740+6620-class millisecond pulsar with an ELL1 binary — the flagship
#: configuration used by bench.py (the reference's grid benchmark dataset
#: is NANOGrav J0740+6620, `profiling/bench_chisq_grid_WLSFitter.py:10-24`)
J0740_CLASS_PAR = """
PSR J0740-BENCH
RAJ 07:40:45.79 1
DECJ 66:20:33.5 1
F0 346.53199992 1
F1 -1.46e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 14.96 1
BINARY ELL1
PB 4.76694461 1
A1 3.9775561 1
TASC 55000.3 1
EPS1 -5.7e-6 1
EPS2 -1.89e-5 1
M2 0.25
SINI 0.99
TZRMJD 55000.1
TZRFRQ 1400
TZRSITE gbt
EPHEM DE421
"""


def j0740_class_model():
    from pint_tpu.models import get_model

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return get_model(J0740_CLASS_PAR.strip().splitlines())


def simulate_j0740_class(ntoas: int = 40, span_days: float = 600.0,
                         center_mjd: float = 55000.0, error_us: float = 1.0,
                         seed: int = 7):
    """(model, noisy dual-frequency TOAs) for the flagship configuration."""
    from pint_tpu.simulation import make_fake_toas_uniform

    model = j0740_class_model()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        toas = make_fake_toas_uniform(
            center_mjd - span_days / 2, center_mjd + span_days / 2, ntoas,
            model, obs="gbt", error_us=error_us,
            freq_mhz=np.tile([1400.0, 800.0], (ntoas + 1) // 2)[:ntoas],
            add_noise=True, seed=seed)
    return model, toas


def j0740_realistic_par(dmx_bins: int = 70, span_days: float = 4550.0,
                        center_mjd: float = 54975.0) -> str:
    """The flagship par grown to the real NANOGrav J0740+6620 column
    count (the reference's 176 s benchmark fit carries ~dozens of
    DMX/FD/JUMP columns, `profiling/bench_chisq_grid_WLSFitter.py:10-24`;
    VERDICT r2 asked for the honest-width comparison): ~`dmx_bins` DMX
    windows + FD1-4 + two receiver JUMPs on top of spin/astrometry/
    binary."""
    lines = [J0740_CLASS_PAR.strip()]
    lines += ["FD1 1e-5 1", "FD2 -4e-6 1", "FD3 2e-6 1", "FD4 -1e-6 1",
              "JUMP -fe RCVR800 1e-5 1", "JUMP -fe RCVR1400L 5e-6 1"]
    lo = center_mjd - span_days / 2
    width = span_days / dmx_bins
    for i in range(1, dmx_bins + 1):
        r1 = lo + (i - 1) * width
        r2 = lo + i * width
        lines += [f"DMX_{i:04d} 0 1",
                  f"DMXR1_{i:04d} {r1:.4f}", f"DMXR2_{i:04d} {r2:.4f}"]
    return "\n".join(lines)


def simulate_j0740_realistic(ntoas: int = 12500, span_days: float = 4550.0,
                             center_mjd: float = 54975.0, seed: int = 0):
    """(model, TOAs) at the honest NANOGrav-like width: ~95 free
    parameters, three receiver/frequency groups carrying -fe flags for
    the JUMPs."""
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = get_model(j0740_realistic_par(
            span_days=span_days, center_mjd=center_mjd).splitlines())
        freqs = np.tile([1400.0, 800.0, 1420.0], (ntoas + 2) // 3)[:ntoas]
        toas = make_fake_toas_uniform(
            center_mjd - span_days / 2, center_mjd + span_days / 2, ntoas,
            model, obs="gbt", error_us=1.0, freq_mhz=freqs,
            add_noise=True, seed=seed)
    fe = {800.0: "RCVR800", 1400.0: "RCVR1400", 1420.0: "RCVR1400L"}
    for f_mhz, fl in zip(freqs, toas.flags):
        fl["fe"] = fe[float(f_mhz)]
    return model, toas
