"""Ephemeris calibration: a data-driven Earth-position correction field
fit to the reference's published DE-ephemeris truth.

The builtin integrated ephemeris (:mod:`pint_tpu.ephemeris`) seeds its
N-body initial conditions from analytic theory; its Earth-SSB error
(~1400 km, dominated by the giant planets' Sun-vs-SSB term plus VSOP87
truncation) is the ~200 us absolute-residual gap against the reference's
tempo2 goldens.  Round 4 tried to absorb that error into 9 *physical*
giant-planet mean-element corrections — under-determined by the
available truth, it overfit per-dataset nuisances and degraded the
holdout (see git history).  This module replaces that with a direct
**3-axis smooth correction spline** ``delta(t)`` on the geocenter's
barycentric position, fit jointly to every piece of DE-derived truth the
reference ships:

* the DE405 daily table (``pint_tpu/data/de_anchor.py``: 730 3-D
  geocenter positions, MJD 52544-53274),
* the ``testtimes.par.tempo2_test`` golden (8 sparse 3-D Earth
  positions + velocities, MJD 52616-55656),
* the J1744-1134 golden per-TOA ``roemer`` column (line-of-sight
  projections over ~7 yr, one sky direction),
* per-TOA residual-difference curves of the other tempo2 goldens
  (B1855+09 x2, B1953+29, J0613-0200, J0023+0923, J1853+1303 — six
  more sky directions that jointly triangulate the 3-D error).

A scalar **common-mode spline** ``cm(t)`` (shared by all pulsars,
direction-independent) is available to separate clock-chain/TDB
differences from geometry — but it ships DISABLED
(``cm_amp_m=None``): measured, the RA-clustering of the pulsars (4 of
7 within 19h +/- 1h) lets even an amplitude-ridged cm absorb real
geometry along the mean sky direction, which the served 3-axis table
would then lack (holdout: prediction unchanged, served accuracy up to
10x worse).  The sub-us physical clock/TDB differences leak into the
per-dataset constants instead, which is harmless at this grade.
Per-dataset constants absorb the arbitrary phase reference of each
golden; anything MORE per dataset eats real geometry — measured:
per-dataset LINEAR nuisances (pre-detrending every gap curve)
degrade the B1855 holdout 13.7 -> 113 us, because each curve's
secular trend IS line-of-sight Sun-SSB drift.

The correction is fit against the CANONICAL window build
(`IntegratedEphemeris._CANONICAL`) — one fixed integration every
in-era dataset is served from — and baked into
``pint_tpu/data/ephem_correction.py``, which the ephemeris then applies
by default (`IntegratedEphemeris._correction_spline`).  Data-free edges
taper to zero (i.e. back to the uncorrected integration) so the
correction can only help where truth constrained it.

Pipeline (offline; run ``python -m pint_tpu.ephemcal``)::

    collect   -> per-dataset npz caches of (mjd, truth-minus-ours, n)
    holdout   -> fit without B1855-9y, report its gap before/after
    fit+bake  -> final fit on everything, write the data module

Reference counterpart: none — the reference downloads real JPL kernels
(`solar_system_ephemerides.py`).  This is the zero-download route to
approach its ~10 ns tempo2 identity (README.rst:44-48) from published
test artifacts alone.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["collect_all", "load_obs", "fit_correction", "eval_dataset",
           "bake", "main"]

REFDATA = os.environ.get("PINT_TPU_REFDATA",
                         "/root/reference/tests/datafile")
C = 299792458.0

#: residual-gap datasets: name -> (par, tim, golden)
GAP_SETS = {
    "b1855_9y": ("B1855+09_NANOGrav_9yv1.gls.par",
                 "B1855+09_NANOGrav_9yv1.tim",
                 "B1855+09_NANOGrav_9yv1.gls.par.tempo2_test"),
    "b1855_fb90": ("B1855+09_NANOGrav_dfg+12_TAI_FB90.par",
                   "B1855+09_NANOGrav_dfg+12.tim",
                   "B1855+09_NANOGrav_dfg+12_TAI_FB90.par.tempo2_test"),
    "b1953": ("B1953+29_NANOGrav_dfg+12_TAI_FB90.par",
              "B1953+29_NANOGrav_dfg+12.tim",
              "B1953+29_NANOGrav_dfg+12_TAI_FB90.par.tempo2_test"),
    "j0613": ("J0613-0200_NANOGrav_dfg+12_TAI_FB90.par",
              "J0613-0200_NANOGrav_dfg+12.tim",
              "J0613-0200_NANOGrav_dfg+12_TAI_FB90.par.tempo2_test"),
    "j0023": ("J0023+0923_NANOGrav_11yv0.gls.par",
              "J0023+0923_NANOGrav_11yv0.tim",
              "J0023+0923_NANOGrav_11yv0.gls.par.tempo2_test"),
    "j1853": ("J1853+1303_NANOGrav_11yv0.gls.par",
              "J1853+1303_NANOGrav_11yv0.tim",
              "J1853+1303_NANOGrav_11yv0.gls.par.tempo2_test"),
}

#: the golden with a tempo2 `roemer` column (cleaner than residual gaps:
#: no binary/DM/track differences enter) — (par, tim, golden, column)
ROEMER_SET = ("j1744", "J1744-1134.basic.par",
              "J1744-1134.Rcvr1_2.GASP.8y.x.tim",
              "J1744-1134.basic.par.tempo2_test", 3)

#: Fermi-LAT photon "truth": the J0030 GEO FT1 file carries a
#: tempo2-Fermi-plugin PULSE_PHASE column (DE405) along RA ~0h over
#: 2008-2016 — a direction/era the radio goldens barely constrain.
#: MEASURED (2026-08) TO BE UNUSABLE as calibration input: the column's
#: producing par is unknown, so the phase difference mixes
#: timing-model differences (F0/astrometry offsets are smooth annual/
#: secular curves, exactly degenerate with line-of-sight ephemeris
#: error in a single direction) with the geometry — adding it degraded
#: the B1855 holdout 11 -> 78 us (right sign; 245 us wrong sign).
#: `collect_fermi_gap` remains as the harness for the day a
#: same-par photon dataset exists; it is NOT in `collect_all`.
FERMI_GAP_SET = ("j0030_fermi", "PSRJ0030+0451_psrcat.par",
                 "J0030+0451_P8_15.0deg_239557517_458611204_"
                 "ft1weights_GEO_wt.gt.0.4.fits")


def _los_names():
    """The line-of-sight dataset names, in fit/report order (single
    source for fit_correction and main — a dataset present in the
    observables but missing here would be silently unfit)."""
    return list(GAP_SETS) + ["j1744", FERMI_GAP_SET[0]]

#: per-TOA "sigma" [m] — not measurement noise (identical TOAs cancel in
#: the difference) but the size of NON-ephemeris model differences vs
#: tempo2 (TDB series ~100 ns, clock interpolation, binary integration)
SIGMA_LOS_M = 60.0
SIGMA_ROEMER_M = 60.0
SIGMA_ANCHOR_M = 15.0
SIGMA_TESTTIMES_M = 400.0


def _cache_dir():
    d = os.environ.get("PINT_TPU_CAL_CACHE")
    if not d:
        d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         os.pardir, "bench_cache", "calib")
    os.makedirs(d, exist_ok=True)
    return d


def _force_cpu_base():
    """Calibration measures the CPU-exact base pipeline with any baked
    correction disabled (so a re-run measures raw gaps, not residual
    ones)."""
    os.environ["PINT_TPU_NO_EPH_CORR"] = "1"
    # the correction is served on the UNANCHORED canonical build; an
    # inherited opt-in anchor flag would make the calibration measure
    # against a different base than the one it is applied to
    os.environ.pop("PINT_TPU_DE_ANCHOR", None)
    import jax

    jax.config.update("jax_enable_x64", True)
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _read_golden(path):
    """Numeric rows of a tempo2 golden file (comment/header tolerant)."""
    rows = []
    with open(os.path.join(REFDATA, path)) as fh:
        for line in fh:
            s = line.split()
            if not s or line.lstrip().startswith("#"):
                continue
            try:
                rows.append([float(v) for v in s])
            except ValueError:
                continue  # the column-name header line
    return np.asarray(rows, np.float64)


def _load_pipeline(par, tim):
    from pint_tpu.models import get_model
    from pint_tpu.toa import get_TOAs

    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = get_model(os.path.join(REFDATA, par))
        t = get_TOAs(os.path.join(REFDATA, tim), model=m)
    return m, t


def _psr_dirs(m, batch, p):
    from pint_tpu.utils import host_eager

    astro = [c for c in m.components.values() if hasattr(c, "psr_dir")][0]
    with host_eager():
        n = np.asarray(astro.psr_dir(p, batch))
        pos_ls = np.asarray(batch.ssb_obs_pos_ls)
    return n, pos_ls


def _unwrap_gap(d, P, mjd, nbin_days=60.0):
    """Per-TOA continuity unwrapping of a residual difference that is
    only defined mod the pulse period: remove the circular mean, build a
    binned continuity-unwrapped curve, then snap each TOA to the branch
    nearest its bin's value."""
    z = np.exp(2j * np.pi * d / P)
    mu = np.angle(z.mean()) * P / (2 * np.pi)
    dw = (d - mu + P / 2) % P - P / 2
    edges = np.arange(mjd.min(), mjd.max() + nbin_days, nbin_days)
    bm, bg = [], []
    prev = None
    for lo, hi in zip(edges[:-1], edges[1:]):
        sel = (mjd >= lo) & (mjd < hi)
        if sel.sum() < 3:
            continue
        zb = np.exp(2j * np.pi * dw[sel] / P)
        gb = np.angle(zb.mean()) * P / (2 * np.pi)
        if prev is not None:
            gb += P * np.round((prev - gb) / P)
        prev = gb
        bm.append(mjd[sel].mean())
        bg.append(gb)
    if len(bm) < 2:
        return dw
    ref = np.interp(mjd, np.asarray(bm), np.asarray(bg))
    return dw - P * np.round((dw - ref) / P)


def collect_gap(name, par, tim, golden):
    """Per-TOA ``(mjd_tdb, y_sec, n)`` for one residual-gap dataset;
    ``y_sec`` is *truth minus ours* (tempo2's residual minus ours,
    continuity-unwrapped; the sign measured against the J1744 roemer
    column: corr -0.9997, see round-4 notes)."""
    from pint_tpu.residuals import Residuals

    m, t = _load_pipeline(par, tim)
    gold = _read_golden(golden)
    r = Residuals(t, m)
    ours = np.asarray(r.time_resids)
    assert gold.shape[0] == len(ours), (name, gold.shape, len(ours))
    P = 1.0 / float(m.F0.value)
    n, _ = _psr_dirs(m, r.batch, r.pdict)
    mjd = np.asarray(r.batch.tdbld)
    d_u = _unwrap_gap(ours - gold[:, 0], P, mjd)
    return {"mjd": mjd, "y": -d_u, "n": n}


def collect_roemer():
    """Per-TOA ``(mjd_tdb, y_sec, n)`` from the J1744 golden roemer
    column (y = gold_roemer - ours, directly ``n . delta / c``).  The
    golden's tt2tb column rides along in the cache as truth input for
    the TDB-chain (tdbseries) calibration."""
    name, par, tim, golden, col = ROEMER_SET
    m, t = _load_pipeline(par, tim)
    gold = _read_golden(golden)
    batch = t.to_batch()
    p = m.build_pdict(t)
    n, pos_ls = _psr_dirs(m, batch, p)
    ours = np.einsum("ij,ij->i", pos_ls, n)
    assert gold.shape[0] == len(ours), (gold.shape, len(ours))
    return {"mjd": np.asarray(batch.tdbld), "y": gold[:, col] - ours,
            "n": n, "tt2tb": gold[:, 2]}


def collect_fermi_gap():
    """Per-photon ``(mjd_tdb, y_sec, n)`` from the J0030 GEO FT1 file's
    tempo2-plugin PULSE_PHASE column.  NOT used by `collect_all` — see
    the FERMI_GAP_SET note: without the producing par, timing-model
    differences contaminate the curve inseparably.

    Sign: our model phase minus the plugin's is ``F0 * (delay_gold -
    delay_ours)``; with the barycentric correction entering the delay
    as ``-n.r/c``, that is ``-n.delta/c`` — so y (= truth-minus-ours
    light time, like every other row here) is MINUS the wrapped phase
    difference over F0 (confirmed: this sign fits the photon curve to
    12 us where the opposite leaves 55)."""
    from pint_tpu import qs
    from pint_tpu.event_toas import get_event_TOAs
    from pint_tpu.residuals import Residuals

    # gaps must be measured against the RAW base — the other
    # collectors get this from _force_cpu_base, but this one is
    # documented for standalone use too
    os.environ["PINT_TPU_NO_EPH_CORR"] = "1"
    name, par, ft1 = FERMI_GAP_SET
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("ignore")
        from pint_tpu.models import get_model

        m = get_model(os.path.join(REFDATA, par))
        toas = get_event_TOAs(os.path.join(REFDATA, ft1),
                              ephem="DE421", planets=False,
                              extra_columns=("PULSE_PHASE",))
        pp = toas.extra["PULSE_PHASE"]
        r = Residuals(toas, m, subtract_mean=False)
        ph = m.calc.phase(r.pdict, r.batch)
    _, frac = qs.round_nearest(ph)
    ours = np.asarray(qs.to_f64(frac)) % 1.0
    P = 1.0 / float(m.F0.value)
    d = ((ours - pp + 0.5) % 1.0 - 0.5) * P
    n, _ = _psr_dirs(m, r.batch, r.pdict)
    return {"mjd": np.asarray(r.batch.tdbld), "y": -d, "n": n}


def anchor_rows():
    """3-D rows from the DE405 daily table: ``delta = truth - base`` at
    730 epochs (geocenter, metres, vs the canonical unanchored build)."""
    from pint_tpu.data import de_anchor
    from pint_tpu.ephemeris import IntegratedEphemeris

    eph = IntegratedEphemeris(warn=False)
    mjd = np.asarray(de_anchor.MJD_TDB, np.float64)
    base = eph.posvel("earth", mjd).pos
    return {"mjd": mjd,
            "d3": np.asarray(de_anchor.EARTH_POS_M, np.float64) - base}


def testtimes_rows():
    """3-D rows from the ``testtimes`` golden: 8 sparse Earth-SSB
    positions (lt-sec -> m, asserted < 2 m by the reference's own
    `tests/test_times.py`) spanning MJD 52616-55656 — six of them
    BEYOND the DE405 daily table, the only 3-D truth out there.

    Epochs: the ``Ttt`` column is the TOA's TT; evaluation time is
    TT + (tt2tb - ttcorr) = the TOA's TDB.  Cross-checked against the
    DE405 daily table at the two in-window epochs: agreement ~1.5 km
    (an along-track ~50 ms epoch-bookkeeping inconsistency between the
    two goldens' derivations — the floor of this row set's accuracy,
    hence SIGMA_TESTTIMES_M ~ 400 m, still 3 orders below the ~1400 km
    base error being fit)."""
    from pint_tpu.ephemeris import IntegratedEphemeris

    g = _read_golden("testtimes.par.tempo2_test")
    # columns: oclk ut1_utc tai_utc tt_tai ttcorr tt2tb ep0 ep1 ep2
    #          ev0 ev1 ev2 tp0 tp1 tp2 tv0 tv1 tv2 Ttt
    ttcorr, tt2tb = g[:, 4], g[:, 5]
    ep = g[:, 6:9] * C
    mjd = g[:, 18] + (tt2tb - ttcorr) / 86400.0
    eph = IntegratedEphemeris(warn=False)
    d3 = ep - eph.posvel("earth", mjd).pos
    err = float(np.median(np.linalg.norm(d3, axis=1)))
    # the base error is ~1400-2000 km; a wrong epoch/frame would add
    # its own ~1900 km (64 s x 30 km/s) on top
    assert err < 5000e3, f"testtimes frame mismatch: {err/1e3:.0f} km"
    return {"mjd": mjd, "d3": d3, "median_err_m": err}


def _base_stamp():
    """Version stamp of the base ephemeris the gaps are measured
    against; a cache collected against a different base is invalid
    (this very module's history: a cubic->quintic serve change moved
    the base by ~9 km)."""
    from pint_tpu import ephemeris as E

    return np.array([float(E._NBODY_VERSION), 2.0])  # 2.0: quintic serve


def collect_all(refresh=False, verbose=True):
    """Collect every observable into per-dataset npz caches (stamped
    with the base-ephemeris version — a stale cache re-collects
    automatically); returns the dict of loaded arrays.

    REFUSES to run with the baked correction live: gaps measured
    against the corrected base are near zero, and a later refit from
    such caches would bake a corrupted (near-zero) table.  Call
    :func:`_force_cpu_base` first, or set ``PINT_TPU_NO_EPH_CORR=1``
    (scoped, e.g. monkeypatch) yourself."""
    if os.environ.get("PINT_TPU_NO_EPH_CORR") != "1":
        raise RuntimeError(
            "collect_all measures gaps against the RAW base; set "
            "PINT_TPU_NO_EPH_CORR=1 (or call "
            "ephemcal._force_cpu_base()) before collecting — with the "
            "baked correction live the caches would be poisoned with "
            "near-zero gaps")
    cache = _cache_dir()
    stamp = _base_stamp()
    out = {}
    jobs = [("anchor", anchor_rows), ("testtimes", testtimes_rows),
            ("j1744", collect_roemer)]
    jobs += [(nm, (lambda nm=nm, s=s: collect_gap(nm, *s)))
             for nm, s in GAP_SETS.items()]
    for nm, fn in jobs:
        path = os.path.join(cache, f"{nm}.npz")
        if os.path.isfile(path) and not refresh:
            d = dict(np.load(path, allow_pickle=False))
            if np.array_equal(d.pop("base_stamp", None), stamp):
                out[nm] = d
                continue
            if verbose:
                print(f"{nm}: cache from older base, re-collecting",
                      flush=True)
        if verbose:
            print(f"collecting {nm}...", flush=True)
        d = {k: v for k, v in fn().items()
             if isinstance(v, np.ndarray)}
        np.savez(path, base_stamp=stamp, **d)
        out[nm] = d
        if verbose:
            span = (d["mjd"].min(), d["mjd"].max())
            print(f"  {nm}: {len(d['mjd'])} rows, MJD "
                  f"{span[0]:.0f}-{span[1]:.0f}", flush=True)
    return out


# --- the fit -----------------------------------------------------------------

def _knot_grid(knots_lo, knots_hi, spacing, dense=None):
    """Uniform ~``spacing``-day knots over [knots_lo, knots_hi]; when
    ``dense=(lo, hi, spacing)`` is given, that interval is re-gridded
    at the finer spacing (daily 3-D truth there supports it — the
    DE405 anchor window resolves lunar-period structure a 60-day grid
    cannot)."""
    nseg = max(int(np.ceil((knots_hi - knots_lo) / spacing)), 2)
    grid = np.linspace(knots_lo, knots_hi, nseg + 1)
    if dense is not None:
        dlo, dhi, dsp = dense
        dlo, dhi = max(dlo, knots_lo), min(dhi, knots_hi)
        if dhi > dlo:
            fine = np.arange(dlo, dhi + dsp / 2, dsp)
            grid = np.unique(np.concatenate(
                [grid[(grid < dlo - dsp) | (grid > dhi + dsp)], fine]))
    return grid


def _bspline_design(t, grid):
    """(csr design matrix, full knot vector) of a cubic B-spline on
    interior knot grid ``grid``."""
    from scipy.interpolate import BSpline

    kn = np.r_[[grid[0]] * 3, grid, [grid[-1]] * 3]
    t = np.clip(t, grid[0], grid[-1])
    return BSpline.design_matrix(t, kn, 3), kn


def _second_diff(n):
    D = np.zeros((n - 2, n))
    for i in range(n - 2):
        D[i, i:i + 3] = (1.0, -2.0, 1.0)
    return D


def fit_correction(obs, exclude=(), knot_days=60.0, cm_knot_days=180.0,
                   lam_smooth=20.0, lam_cm=200.0, cm_amp_m=None,
                   dense_days=15.0, verbose=True):
    """Solve the joint correction fit.

    Parameters: 3 x Nk B-spline coefficients of ``delta`` [m], Ncm
    coefficients of the scalar common mode [m], one constant per
    line-of-sight dataset [m].  Regularization: second-difference
    smoothness on each spline (``lam_smooth``/``lam_cm`` in metres per
    knot-curvature unit) + a mean-zero tie for the common mode (its
    constant is degenerate with the per-dataset constants).

    Returns a dict with the fitted evaluators and diagnostics.
    """
    from scipy.interpolate import BSpline

    los_names = [nm for nm in _los_names()
                 if nm in obs and nm not in exclude]
    t_all = [obs[nm]["mjd"] for nm in ("anchor", "testtimes")
             if nm in obs and nm not in exclude]
    t_all += [obs[nm]["mjd"] for nm in los_names]
    tmin = min(float(t.min()) for t in t_all) - 20.0
    tmax = max(float(t.max()) for t in t_all) + 20.0

    rows_A, rows_b, rows_w = [], [], []

    # dense knots inside the DE405 daily-truth window (it resolves
    # sub-monthly structure the sparse line-of-sight curves cannot)
    dense = None
    if "anchor" in obs and "anchor" not in exclude and dense_days:
        am = obs["anchor"]["mjd"]
        dense = (float(am.min()) - 5.0, float(am.max()) + 5.0,
                 dense_days)
    grid = _knot_grid(tmin, tmax, knot_days, dense)

    def design(t):
        A, kn = _bspline_design(t, grid)
        return A.toarray(), kn

    _, kn = design(np.array([tmin]))
    nk = len(kn) - 4
    # cm columns exist only when the amplitude ridge is enabled (see
    # the ridge comment below for why cm ships disabled)
    if cm_amp_m:
        grid_cm = _knot_grid(tmin, tmax, cm_knot_days)
        _, kn_cm = _bspline_design(np.array([tmin]), grid_cm)
        ncm = len(kn_cm) - 4
    else:
        ncm = 0
    nset = len(los_names)
    ncol = 3 * nk + ncm + nset

    def blank(nrow):
        return np.zeros((nrow, ncol))

    # 3-D rows
    for nm, sig in (("anchor", SIGMA_ANCHOR_M),
                    ("testtimes", SIGMA_TESTTIMES_M)):
        if nm not in obs or nm in exclude:
            continue
        t, d3 = obs[nm]["mjd"], obs[nm]["d3"]
        B, _ = design(t)
        for ax in range(3):
            blk = blank(len(t))
            blk[:, ax * nk:(ax + 1) * nk] = B
            rows_A.append(blk)
            rows_b.append(d3[:, ax])
            rows_w.append(np.full(len(t), 1.0 / sig))

    # line-of-sight rows
    for k, nm in enumerate(los_names):
        t, y, n = obs[nm]["mjd"], obs[nm]["y"], obs[nm]["n"]
        sig = SIGMA_ROEMER_M if nm == "j1744" else SIGMA_LOS_M
        B, _ = design(t)
        blk = blank(len(t))
        for ax in range(3):
            blk[:, ax * nk:(ax + 1) * nk] = n[:, ax:ax + 1] * B
        if ncm:
            blk[:, 3 * nk:3 * nk + ncm] = \
                _bspline_design(t, grid_cm)[0].toarray()
        blk[:, 3 * nk + ncm + k] = 1.0
        rows_A.append(blk)
        rows_b.append(y * C)
        rows_w.append(np.full(len(t), 1.0 / sig))

    # regularization: plain (1,-2,1) coefficient second differences
    # with one lam for all knots.  On the non-uniform grid this gives
    # the 15-day dense anchor-window knots a ~16x WEAKER curvature
    # penalty than the 60-day region — deliberately: the daily 3-D
    # truth there supports sub-monthly structure, and rescaling the
    # rows to constant-curvature units was MEASURED to erase exactly
    # that benefit (anchor residual 72 m -> 3.4 km).
    D = _second_diff(nk)
    for ax in range(3):
        blk = blank(D.shape[0])
        blk[:, ax * nk:(ax + 1) * nk] = D
        rows_A.append(blk)
        rows_b.append(np.zeros(D.shape[0]))
        rows_w.append(np.full(D.shape[0], 1.0 / lam_smooth))
    # Common-mode AMPLITUDE ridge: cm models clock-chain/TDB-series
    # differences vs tempo2 — physically <= a few hundred ns (~100 m).
    # Without this ridge, the RA-clustering of the pulsars (4 of 7
    # within 19h +/- 1h) lets cm absorb REAL geometry along the mean
    # sky direction (measured: +/-1000 km of cm, i.e. +/-3 ms —
    # geometry that the served 3-axis correction would then LACK).
    # Curvature smoothing alone cannot prevent that (a smooth huge cm
    # is curvature-free); pinning every coefficient to 0 at ~cm_amp_m
    # keeps cm to its physical job.  Even ridged, cm was measured to
    # degrade the SERVED accuracy, so the default is cm_amp_m=None —
    # no cm columns at all.
    if ncm:
        Dc = _second_diff(ncm)
        blk = blank(Dc.shape[0])
        blk[:, 3 * nk:3 * nk + ncm] = Dc
        rows_A.append(blk)
        rows_b.append(np.zeros(Dc.shape[0]))
        rows_w.append(np.full(Dc.shape[0], 1.0 / lam_cm))
        blk = blank(ncm)
        blk[:, 3 * nk:3 * nk + ncm] = np.eye(ncm)
        rows_A.append(blk)
        rows_b.append(np.zeros(ncm))
        rows_w.append(np.full(ncm, 1.0 / cm_amp_m))

    A = np.vstack(rows_A)
    b = np.concatenate(rows_b)
    w = np.concatenate(rows_w)
    x, *_ = np.linalg.lstsq(A * w[:, None], b * w, rcond=None)

    cx = [BSpline(kn, x[ax * nk:(ax + 1) * nk], 3) for ax in range(3)]
    cm = (BSpline(kn_cm, x[3 * nk:3 * nk + ncm], 3) if ncm
          else (lambda t: np.zeros(np.shape(t))))
    consts = dict(zip(los_names, x[3 * nk + ncm:]))

    def delta(t):
        t = np.clip(np.asarray(t, np.float64), tmin, tmax)
        return np.stack([c(t) for c in cx], axis=-1)

    res = (A @ x - b)
    nobs = sum(len(obs[nm]["mjd"]) for nm in los_names)
    rep = {"wrms_m": float(np.sqrt(np.mean((res * w) ** 2))),
           "span": (tmin, tmax), "nk": nk, "ncm": ncm,
           "consts_m": {k: float(v) for k, v in consts.items()},
           "nrows": len(b), "nlos": nobs}
    if verbose:
        print(f"fit: {rep['nrows']} rows, {ncol} params, whitened rms "
              f"{rep['wrms_m']:.2f}", flush=True)
    return {"delta": delta, "cm": cm, "consts": consts, "span":
            (tmin, tmax), "report": rep}


def eval_dataset(obs, nm, fit=None):
    """Median |gap| [us] of dataset ``nm`` before and (when ``fit`` is
    given) after the correction, with the per-dataset constant profiled
    out (medians; the golden's phase reference is arbitrary)."""
    t, y, n = obs[nm]["mjd"], obs[nm]["y"], obs[nm]["n"]
    y_m = y * C
    before = np.median(np.abs(y_m - np.median(y_m))) / C * 1e6
    out = {"before_us": float(before)}
    if fit is not None:
        pred = np.einsum("ij,ij->i", n, fit["delta"](t)) + fit["cm"](
            np.clip(t, *fit["span"]))
        r = y_m - pred
        out["after_us"] = float(
            np.median(np.abs(r - np.median(r))) / C * 1e6)
    return out


# --- baking ------------------------------------------------------------------

def bake(fit, path=None, grid_days=4.0, taper_days=600.0):
    """Write ``pint_tpu/data/ephem_correction.py``: the fitted
    correction sampled on a uniform grid over the FULL canonical window,
    tapered to zero outside the constrained span (cosine ramp over
    ``taper_days``), so the served spline never extrapolates."""
    from pint_tpu.ephemeris import IntegratedEphemeris

    clo, chi = IntegratedEphemeris._CANONICAL
    tmin, tmax = fit["span"]
    grid = np.arange(clo, chi + grid_days / 2, grid_days)
    vals = fit["delta"](grid)

    def taper_w(t):
        w = np.ones_like(t)
        lo_edge = t < tmin
        w[lo_edge] = np.clip(1.0 - (tmin - t[lo_edge]) / taper_days,
                             0.0, 1.0)
        hi_edge = t > tmax
        w[hi_edge] = np.clip(1.0 - (t[hi_edge] - tmax) / taper_days,
                             0.0, 1.0)
        return 0.5 - 0.5 * np.cos(np.pi * w)

    vals = vals * taper_w(grid)[:, None]
    path = path or os.path.join(os.path.dirname(__file__), "data",
                                "ephem_correction.py")
    lines = [
        '"""Earth-SSB position correction table (published-data'
        ' derived).',
        "",
        "Fit by :mod:`pint_tpu.ephemcal` against the reference's",
        "DE-ephemeris truth (DE405 daily table, testtimes 3-D golden",
        "rows, J1744-1134 golden Roemer column, multi-pulsar tempo2",
        "residual-gap curves), relative to the CANONICAL unanchored",
        "integrated-ephemeris build.  Applied by",
        "`IntegratedEphemeris._correction_spline`; regenerate with",
        "``python -m pint_tpu.ephemcal``.  Data, not logic.",
        '"""',
        "",
        "import numpy as np",
        "",
        f"#: fitted span MJD {tmin:.1f}-{tmax:.1f}; zero-tapered "
        f"({taper_days:.0f} d) outside",
        "KNOT_MJD = np.array([",
    ]
    lines += [f"    {v!r}," for v in grid.tolist()]
    lines += ["])", "", "#: geocenter correction [m], ICRS axes",
              "CORR_M = np.array(["]
    lines += [f"    ({x!r}, {y!r}, {z!r}),"
              for x, y, z in (r.tolist() for r in vals)]
    lines += ["])", ""]
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--refresh", action="store_true",
                    help="recollect observables (ignore npz caches)")
    ap.add_argument("--holdout", default="b1855_9y",
                    help="dataset to hold out for validation "
                         "(empty string: none)")
    ap.add_argument("--no-bake", action="store_true")
    ap.add_argument("--knot-days", type=float, default=60.0)
    ap.add_argument("--lam-smooth", type=float, default=20.0)
    args = ap.parse_args(argv)

    _force_cpu_base()
    obs = collect_all(refresh=args.refresh)

    if args.holdout:
        fit_h = fit_correction(obs, exclude=(args.holdout,),
                               knot_days=args.knot_days,
                               lam_smooth=args.lam_smooth)
        ev = eval_dataset(obs, args.holdout, fit_h)
        print(f"HOLDOUT {args.holdout}: {ev['before_us']:.1f} -> "
              f"{ev['after_us']:.1f} us median", flush=True)

    fit = fit_correction(obs, knot_days=args.knot_days,
                         lam_smooth=args.lam_smooth)
    for nm in _los_names():
        if nm in obs:
            ev = eval_dataset(obs, nm, fit)
            print(f"  {nm}: {ev['before_us']:.1f} -> "
                  f"{ev['after_us']:.1f} us", flush=True)
    if not args.no_bake:
        p = bake(fit)
        print("wrote", p, flush=True)


if __name__ == "__main__":
    main()
