"""Ephemeris calibration against published JPL-derived truth.

The builtin integrated ephemeris (:mod:`pint_tpu.ephemeris`) seeds its
N-body initial conditions from analytic theory; its dominant error is
the Sun-vs-SSB term contributed by the giant planets' Keplerian
mean-element errors (measured ~1400 km of Earth-SSB error, i.e. several
light-milliseconds, quasi-static on multi-year timescales).  A 2-year
3-D anchor (the DE405 table in ``pint_tpu/data/de_anchor.py``) cannot
constrain those slow terms in extrapolation — but SKY-PROJECTED truth
over longer spans can: the reference's tempo2 golden outputs include a
per-TOA ``roemer`` column for J1744-1134 (tempo2's DE-kernel projected
site position over ~7 years), and residual-difference curves of other
pulsars at other sky positions carry the same information.  This module
triangulates those observables into giant-planet mean-element
corrections — the same physics as pulsar-timing-array ephemeris
refinement (BayesEphem-style), done here against the reference's own
published test data.

Pipeline (offline; run ``python -m pint_tpu.ephemcal``):

1. Observables: the DE405 anchor table (730 daily 3-D EMB positions,
   MJD 52544-53274) + the J1744-1134 golden Roemer gaps (1-D
   projections, MJD ~53200-55900).
2. Forward model: full anchored window builds of the integrated
   ephemeris with giant corrections applied and the EMB state RE-FIT to
   the anchor per build (so each sensitivity column reflects what the
   served ephemeris would actually do).
3. Ridge least squares for the corrections, with per-dataset nuisance
   terms (constant/trend/annual — absorbing proper-motion-convention
   and analytic-series annual differences that are not giant-planet
   signal).
4. Bake the result into ``pint_tpu/data/ephem_calibration.py``; the
   integrated ephemeris then applies the corrections as FIXED in every
   window build (`IntegratedEphemeris._stored_gcorr`).

Holdout: the B1855+09 9-yr golden residuals are never used here — they
remain the independent accuracy gauge (tests/test_tempo2_parity.py).

STATUS (2026-08, measured): the calibration fits its inputs (weighted
rms 6031 -> 1051 m) but does NOT generalize — the B1855 holdout
DEGRADED from the 187 us analytic-anchored baseline (575 us with priors,
1053 us without), with the weakly-sensed parameters (Uranus dL walked
7 sigma past its prior) absorbing dataset nuisances.  The available
truth (one 2-year 3-D table + one sky direction of multi-year Roemer
projections + four noisy residual-difference curves) under-determines
the 9-parameter giant-correction space.  No calibration file ships;
this module remains the harness for the day longer-span JPL truth (a
real .bsp, or more golden Roemer columns) is available — rerun
``python -m pint_tpu.ephemcal`` then and the integrated ephemeris picks
the corrections up automatically (`IntegratedEphemeris._stored_gcorr`).
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np

__all__ = ["GIANT_FIT_PARAMS", "roemer_gap", "build_design",
           "calibrate", "main"]

REFDATA = os.environ.get("PINT_TPU_REFDATA",
                         "/root/reference/tests/datafile")

#: (planet, element) corrections solved for; element "dL" is a mean
#: longitude offset [rad], "da" a fractional semi-major-axis change
GIANT_FIT_PARAMS: Tuple[Tuple[str, str], ...] = (
    ("jupiter", "dL"), ("jupiter", "da"),
    ("saturn", "dL"), ("saturn", "da"),
    ("uranus", "dL"),
)

#: datasets whose golden files carry a per-TOA tempo2 `roemer` column
ROEMER_SETS = [
    ("J1744-1134.basic.par", "J1744-1134.Rcvr1_2.GASP.8y.x.tim",
     "J1744-1134.basic.par.tempo2_test", 3),  # roemer = column index 3
]

#: datasets contributing binned residual-difference curves (column 0 of
#: the golden file); sky positions triangulate the Sun-SSB error.  The
#: B1855+09 9-yr set is deliberately ABSENT (the holdout).
GAP_SETS = [
    ("J0613-0200_NANOGrav_dfg+12_TAI_FB90.par",
     "J0613-0200_NANOGrav_dfg+12.tim",
     "J0613-0200_NANOGrav_dfg+12_TAI_FB90.par.tempo2_test"),
    ("B1953+29_NANOGrav_dfg+12_TAI_FB90.par",
     "B1953+29_NANOGrav_dfg+12.tim",
     "B1953+29_NANOGrav_dfg+12_TAI_FB90.par.tempo2_test"),
    ("J0023+0923_NANOGrav_11yv0.gls.par",
     "J0023+0923_NANOGrav_11yv0.tim",
     "J0023+0923_NANOGrav_11yv0.gls.par.tempo2_test"),
    ("J1853+1303_NANOGrav_11yv0.gls.par",
     "J1853+1303_NANOGrav_11yv0.tim",
     "J1853+1303_NANOGrav_11yv0.gls.par.tempo2_test"),
]

#: Gaussian priors (1-sigma) on the fit parameters — the plausible
#: accuracy of the JPL mean elements over 1800-2050 (Standish's table:
#: tens-to-hundreds of arcsec in longitude).  Without these a
#: single-direction fit parks implausible corrections on the weakly
#: sensed planets and extrapolates badly (measured: the B1855 holdout
#: DEGRADED 188->1099 us when Saturn walked to 0.7 deg).
PARAM_PRIORS = {
    ("jupiter", "dL"): 1e-3, ("jupiter", "da"): 3e-5,
    ("saturn", "dL"): 2e-3, ("saturn", "da"): 1e-4,
    ("uranus", "dL"): 3e-3,
}


def gap_curve(par: str, tim: str, golden: str, nbin_days: float = 60.0):
    """Binned, unwrapped residual-difference curve of one dataset:
    ``(mjd_bin, gap_sec_bin, psr_dir_bin)``.

    Residual differences are only defined mod the pulse period; binned
    medians are unwrapped by continuity (nearest-branch relative to the
    previous bin), which is safe because the underlying Sun-SSB error
    moves slowly compared to 60 days."""
    import jax

    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toa import get_TOAs
    from pint_tpu.utils import host_eager

    m = get_model(os.path.join(REFDATA, par))
    t = get_TOAs(os.path.join(REFDATA, tim), model=m)
    gold = np.genfromtxt(os.path.join(REFDATA, golden), skip_header=1)
    if gold.ndim > 1:
        gold = gold[:, 0]
    r = Residuals(t, m)
    ours = np.asarray(r.time_resids)
    assert len(gold) == len(ours), (len(gold), len(ours))
    P = 1.0 / float(m.F0.value)
    d = ours - gold
    z = np.exp(2j * np.pi * d / P)
    mu = np.angle(z.mean()) * P / (2 * np.pi)
    dw = (d - mu + P / 2) % P - P / 2
    mjd = np.asarray(r.batch.tdbld)
    batch = r.batch
    p = r.pdict
    astro = [c for c in m.components.values() if hasattr(c, "psr_dir")][0]
    with host_eager():
        n = np.asarray(astro.psr_dir(p, batch))
    order = np.argsort(mjd)
    mjd, dw, n = mjd[order], dw[order], n[order]
    edges = np.arange(mjd.min(), mjd.max() + nbin_days, nbin_days)
    bm, bg, bn = [], [], []
    prev = None
    for lo, hi in zip(edges[:-1], edges[1:]):
        sel = (mjd >= lo) & (mjd < hi)
        if sel.sum() < 3:
            continue
        # circular median within the bin, then continuity unwrapping
        zb = np.exp(2j * np.pi * dw[sel] / P)
        gb = np.angle(zb.mean()) * P / (2 * np.pi)
        if prev is not None:
            gb += P * np.round((prev - gb) / P)
        prev = gb
        bm.append(mjd[sel].mean())
        bg.append(gb)
        bn.append(n[sel].mean(axis=0))
    bn = np.array(bn) if bn else np.zeros((0, 3))
    if len(bn):
        bn = bn / np.linalg.norm(bn, axis=1, keepdims=True)
    # SIGN: residual difference (ours - gold) = -(gold_roemer -
    # our_roemer) — measured on J1744-1134, which publishes both
    # columns: corr -0.9997, slope -0.999.  Negating here makes every
    # observable in this module mean "truth minus ours", so one set of
    # sensitivity columns (d ours / d theta) serves all rows.
    return np.array(bm), -np.array(bg), bn

#: the full calibration window [MJD] (covers anchor + golden spans)
CAL_WINDOW = (51712.0, 58368.0)


def roemer_gap(par: str, tim: str, golden: str, col: int):
    """(mjd_tdb, gap_sec, psr_dir): tempo2's golden Roemer delay minus
    ours, per TOA.  Ours is the same convention: the SSB->site vector
    projected on the (proper-motion-corrected) pulsar direction."""
    import jax

    from pint_tpu.models import get_model
    from pint_tpu.toa import get_TOAs
    from pint_tpu.utils import host_eager

    m = get_model(os.path.join(REFDATA, par))
    t = get_TOAs(os.path.join(REFDATA, tim), model=m)
    batch = t.to_batch()
    p = m.build_pdict(t)
    astro = [c for c in m.components.values()
             if hasattr(c, "psr_dir")][0]
    with host_eager():
        n = np.asarray(astro.psr_dir(p, batch))
        pos_ls = np.asarray(batch.ssb_obs_pos_ls)
    ours = np.einsum("ij,ij->i", pos_ls, n)
    gold = np.genfromtxt(os.path.join(REFDATA, golden), skip_header=1)
    assert gold.shape[0] == len(ours), (gold.shape, len(ours))
    gap = gold[:, col] - ours
    return np.asarray(batch.tdbld), gap, n


def _window_builder():
    """A fresh IntegratedEphemeris with NO stored calibration (the fit
    solves for corrections relative to the uncalibrated base)."""
    from pint_tpu.ephemeris import IntegratedEphemeris

    eph = IntegratedEphemeris(warn=False)
    return eph


def build_design(datasets=None, verbose=True):
    """Assemble (rows, columns) of the calibration least squares.

    Returns ``(A, b, w, meta)``: design matrix over
    [giant params | per-dataset nuisance], residual vector (metres),
    weights, and bookkeeping.  The forward sensitivities are full
    window rebuilds — EMB re-anchored per column."""
    from scipy.interpolate import CubicSpline

    from pint_tpu import ephemeris as E

    eph = _window_builder()
    wlo, whi = CAL_WINDOW

    def emb_spline(gcorr):
        grid, states = eph._integrate_window(
            wlo, whi, gcorr_base=gcorr, free_giants=())
        return CubicSpline(grid, states[:, 9:12])

    if verbose:
        print("building base window...", flush=True)
    base = emb_spline({})

    # observables --------------------------------------------------------
    amjd, aemb = eph._anchor_emb_bary()
    sets = []   # (name, mjd, gap_sec, n, sigma_m)
    for par, tim, golden, col in ROEMER_SETS:
        if verbose:
            print(f"loading roemer {par}...", flush=True)
        mjd, gap, n = roemer_gap(par, tim, golden, col)
        sets.append((par, mjd, gap, n, 150.0))
    for par, tim, golden in GAP_SETS:
        if verbose:
            print(f"loading gaps {par}...", flush=True)
        mjd, gap, n = gap_curve(par, tim, golden)
        sets.append((par, mjd, gap, n, 100.0))

    # residuals (metres) -------------------------------------------------
    C = 299792458.0
    b_anchor = (aemb - base(amjd)).ravel()

    # sensitivity columns ------------------------------------------------
    steps = {"dL": 1e-5, "da": 1e-7}
    cols_anchor = []
    cols_sets: List[List[np.ndarray]] = [[] for _ in sets]
    for nm, which in GIANT_FIT_PARAMS:
        if verbose:
            print(f"sensitivity {nm}.{which}...", flush=True)
        s = steps[which]
        g = {nm: (s, 0.0) if which == "dL" else (0.0, s)}
        sp = emb_spline(g)
        cols_anchor.append(((sp(amjd) - base(amjd)) / s).ravel())
        for k, (_, mjd, _, n, _) in enumerate(sets):
            d = (sp(mjd) - base(mjd)) / s
            cols_sets[k].append(np.einsum("ij,ij->i", d, n))

    # assemble -----------------------------------------------------------
    ngp = len(GIANT_FIT_PARAMS)
    yr = 365.25
    nuis_per_set = 6
    ncol = ngp + nuis_per_set * len(sets)
    rows = [np.column_stack(cols_anchor + [np.zeros_like(b_anchor)] *
                            (ncol - ngp))]
    b = [b_anchor]
    w = [np.full(b_anchor.size, 1.0 / 10.0)]       # anchor sigma ~10 m
    for k, (_, mjd, gap, n, sig) in enumerate(sets):
        t0 = mjd.mean()
        nuis = np.column_stack([
            np.ones_like(mjd), (mjd - t0) / 1000.0,
            np.cos(2 * np.pi * mjd / yr), np.sin(2 * np.pi * mjd / yr),
            np.cos(4 * np.pi * mjd / yr), np.sin(4 * np.pi * mjd / yr)])
        blk = np.zeros((mjd.size, ncol))
        blk[:, :ngp] = np.column_stack(cols_sets[k])
        blk[:, ngp + k * nuis_per_set:ngp + (k + 1) * nuis_per_set] = nuis
        rows.append(blk)
        b.append(gap * C)
        w.append(np.full(mjd.size, 1.0 / sig))
    A = np.vstack(rows)
    b = np.concatenate(b)
    w = np.concatenate(w)
    return A, b, w, {"ngp": ngp, "sets": [s[0] for s in sets]}


def calibrate(verbose=True):
    """Solve the prior-regularized calibration; returns
    ``{planet: (dL_rad, da_frac)}``."""
    A, b, w, meta = build_design(verbose=verbose)
    ngp = meta["ngp"]
    # Gaussian priors as pseudo-observations pulling each parameter to 0
    prior_rows = np.zeros((ngp, A.shape[1]))
    for j, key in enumerate(GIANT_FIT_PARAMS):
        prior_rows[j, j] = 1.0 / PARAM_PRIORS[key]
    Aw = np.vstack([A * w[:, None], prior_rows])
    bw = np.concatenate([b * w, np.zeros(ngp)])
    x, *_ = np.linalg.lstsq(Aw, bw, rcond=None)
    res = bw - Aw @ x
    if verbose:
        print("weighted rms before/after:",
              float(np.sqrt(np.mean((b * w)**2))),
              float(np.sqrt(np.mean(res[:len(b)]**2))))
        for (nm, which), v in zip(GIANT_FIT_PARAMS, x[:ngp]):
            print(f"  {nm}.{which} = {v:.6e} "
                  f"(prior {PARAM_PRIORS[(nm, which)]:.0e})")
    out: Dict[str, list] = {}
    for (nm, which), v in zip(GIANT_FIT_PARAMS, x[:ngp]):
        cur = out.setdefault(nm, [0.0, 0.0])
        cur[0 if which == "dL" else 1] += float(v)
    return {k: tuple(v) for k, v in out.items()}


def write_calibration(gcorr: Dict[str, tuple], path=None):
    path = path or os.path.join(os.path.dirname(__file__), "data",
                                "ephem_calibration.py")
    lines = [
        '"""Giant-planet mean-element corrections from the multi-dataset',
        "ephemeris calibration (:mod:`pint_tpu.ephemcal`; DE405 anchor",
        "table + tempo2 golden Roemer projections).  Regenerate with",
        "``python -m pint_tpu.ephemcal``.  This file is data, not",
        'logic."""',
        "",
        "#: {planet: (dL_rad, da_frac)} applied by",
        "#: IntegratedEphemeris._stored_gcorr",
        "GIANT_CORRECTIONS = {",
    ]
    for nm, (dl, da) in sorted(gcorr.items()):
        lines.append(f"    {nm!r}: ({dl:.12e}, {da:.12e}),")
    lines += ["}", ""]
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def main():
    os.environ["PINT_TPU_NO_EPHEMCAL"] = "1"   # fit relative to base
    os.environ["PINT_TPU_DE_ANCHOR"] = "1"     # anchored forward model
    gcorr = calibrate()
    del os.environ["PINT_TPU_NO_EPHEMCAL"]
    p = write_calibration(gcorr)
    print("wrote", p)


if __name__ == "__main__":
    main()
