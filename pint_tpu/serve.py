"""Always-on timing service: continuous batching over the fleet bucket
programs and the AOT store.

ROADMAP item 1's front door.  Every fit so far is a library call; at
PTA scale the workload is thousands of independent (model, TOAs)
requests arriving asynchronously, and the serving answer (the Vela.jl /
VI-flow ecosystem's per-pulsar workloads, arXiv:2412.15858 /
arXiv:2405.08857, at array scale) is *continuous batching*: coalesce
concurrent requests into the already-compiled padded bucket programs so
per-request cost is amortized dispatch, never a compile.

* **Admission** — :meth:`TimingService.submit` (or :meth:`prepare` +
  :meth:`submit_prepared`) stages a job host-side and appends it to its
  bucket's queue, returning a :class:`ServeFuture`.  The queue is
  bounded (``max_pending``); overflow is typed backpressure
  (:class:`~pint_tpu.exceptions.ServeSaturated`), routed through the
  ``request_flood`` failpoint so the rejection path is testable.
* **Routing** — jobs are grouped by the fleet's structure key
  (:meth:`FleetFitter._structure_key`) plus a power-of-two-quantized
  ``(n_toa, n_param)`` pad shape.  Unlike the fleet's pad-to-largest-
  member policy, the pad shape is a pure function of the job itself, so
  a restarted daemon reproduces identical program shapes (=> identical
  AOT ProgramKeys) without ever seeing the same job mix — that is what
  makes the two-process zero-compile warm start (CONTRACT003) hold.
* **Coalescing** — a full bucket (``batch_size`` jobs) dispatches
  immediately; a partial bucket dispatches when its oldest job has
  waited ``max_wait_ms`` (``PINT_TPU_SERVE_MAX_WAIT_MS``) — the
  max-latency timer, routed through the ``stalled_bucket`` failpoint so
  the timer path is provable, not incidental.  The steady-state request
  path is the ``serve_request`` dispatch contract: 1 dispatch + 1 result
  fetch per coalesced batch, zero compiles, zero retraces
  (CONTRACT001/002) — per-request recompilation is structurally
  impossible.
* **Buffer donation** — jit-level ``donate_argnums`` would invalidate
  the cached device inputs (and is a no-op on the CPU backend anyway),
  so input residency is bounded instead: stacked batch inputs live in a
  small LRU keyed by the job composition, and evicting an entry between
  dispatches releases its device buffers back to the allocator before
  the next batch stages new ones.  Re-dispatching an identical batch
  pays zero host->device bytes.
* **Graceful drain** — :meth:`flush` runs under the PR 4 signal
  machinery (:class:`pint_tpu.runtime.SignalFlush`): on SIGTERM/SIGINT
  the in-flight batch finishes (its futures resolve), every still-
  queued job is spooled through
  :func:`pint_tpu.runtime.write_checkpoint` (CRC-verified, atomic), and
  :class:`~pint_tpu.exceptions.ServeDrained` is raised;
  :meth:`resume_spool` on a restarted daemon readmits the spool after
  verifying each resubmitted job is BIT-identical to what was queued.

``python -m pint_tpu.serve check`` runs the deterministic demo service
through the daemon path and prints one JSON line of stats — the
subprocess surface the tooling tests drive under the failpoints.
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import threading
import time
import warnings
import zlib
from collections import OrderedDict, deque
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import (aot, faultinject, metrics, profiling, runtime,
                      telemetry)
from pint_tpu.exceptions import (CheckpointCorruptError, CorrelatedErrors,
                                 ServeCancelled, ServeDeadlineExceeded,
                                 ServeDrained, ServeOverCapacity,
                                 ServePoisoned, ServeSaturated)
from pint_tpu.fitter import FitStatus, WLSFitter, _default_wls_kernel
from pint_tpu.fleet import (_COL_CHI2, _COL_ITERS, _COL_STATUS,
                            FleetFitter, _build_bucket_fit, _pad_pdict,
                            _Pulsar)
from pint_tpu.lint.contracts import dispatch_contract
from pint_tpu.logging import child as _logchild
from pint_tpu.residuals import Residuals
from pint_tpu.toabatch import pad_batch_to

_log = _logchild("serve")

__all__ = ["TimingService", "PreparedJob", "ServeFuture", "ServeResult",
           "DEFAULT_MAX_WAIT_MS", "main"]

#: partial-bucket max-latency deadline (ms) when neither the ctor arg
#: nor PINT_TPU_SERVE_MAX_WAIT_MS is given
DEFAULT_MAX_WAIT_MS = 50.0

#: pad-shape floors: a job's (n_toa, n_param) rounds up to a power of
#: two at least this large, so the program set stays bounded and the
#: shapes are reproducible across daemon restarts (the AOT warm-start
#: property — see the module docstring)
_MIN_TOA, _MIN_PARAM = 8, 4

_SPOOL_SIG = "pint_tpu.serve spool v1"

_UID = itertools.count()


def _pow2_at_least(n: int, floor: int) -> int:
    v = max(int(floor), 1)
    while v < n:
        v *= 2
    return v


def _bucket_label(key: tuple) -> str:
    """Compact, restart-stable bucket id for stats keys / Prometheus
    labels / incident attrs: the pad shape plus a CRC32 of the full
    structure key (whose repr is unbounded)."""
    return (f"ntoa{key[1]}xnp{key[2]}-"
            f"{zlib.crc32(repr(key[0]).encode()) & 0xffffffff:08x}")


class ServeResult(NamedTuple):
    """One resolved timing request (the fleet entry shape minus requeue
    provenance — the daemon path is the vmapped bucket program only)."""

    name: str
    chi2: float
    dof: int
    status: FitStatus
    iterations: int
    x: np.ndarray          #: fitted offsets (device units), len(fit_names)
    fit_names: tuple
    #: which lane produced the numbers: "bucket" (the compiled
    #: coalesced program — the steady-state path) or "eager" (solo
    #: host-driven recovery after quarantine/bisection/breaker — a
    #: LOUD degradation, never a silent one)
    rung: str = "bucket"

    @property
    def ok(self) -> bool:
        return self.status in (FitStatus.CONVERGED, FitStatus.MAXITER)


class ServeFuture:
    """Handle for one submitted job; resolves when its coalesced batch
    dispatch completes (or rejects with ``ServeDrained`` if the job was
    spooled instead of fitted)."""

    __slots__ = ("name", "trace_id", "submitted_at", "resolved_at",
                 "deadline_at", "_ev", "_result", "_exc", "_service")

    def __init__(self, name: str, service=None,
                 deadline_s: Optional[float] = None):
        self.name = name
        #: per-request telemetry id, threaded from admission through the
        #: bucket dispatch span (ISSUE 12) — what a flight-recorder dump
        #: is grepped by
        self.trace_id = telemetry.new_trace_id()
        self.submitted_at = time.monotonic()
        self.resolved_at: Optional[float] = None
        #: monotonic instant past which the queued job expires with
        #: ``ServeDeadlineExceeded`` (checked strictly BEFORE staging —
        #: an in-flight batch is never interrupted); None = no deadline
        self.deadline_at = None if deadline_s is None \
            else self.submitted_at + float(deadline_s)
        self._ev = threading.Event()
        self._result: Optional[ServeResult] = None
        self._exc: Optional[BaseException] = None
        self._service = service

    def done(self) -> bool:
        return self._ev.is_set()

    def cancel(self) -> bool:
        """Withdraw the job if it is still queued (not yet staged into
        a dispatch): the future rejects with ``ServeCancelled`` and
        True is returned.  Returns False when the job already resolved,
        was already taken for dispatch, or has no owning service."""
        if self._service is None or self.done():
            return False
        return self._service._cancel_future(self)

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if not self._ev.wait(timeout):
            raise TimeoutError(f"timing job {self.name!r} not resolved "
                               f"within {timeout} s")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError(f"timing job {self.name!r} not resolved "
                               f"within {timeout} s")
        return self._exc

    def _resolve(self, res: ServeResult) -> None:
        self._result = res
        self.resolved_at = time.monotonic()
        self._ev.set()

    def _reject(self, exc: BaseException) -> None:
        self._exc = exc
        self.resolved_at = time.monotonic()
        self._ev.set()


class PreparedJob(NamedTuple):
    """Host-side staged request: everything admission needs, computed
    once (Residuals build, structure key, padded single-row program
    inputs, data CRC).  Resubmitting the same PreparedJob in the same
    batch composition hits the device-args cache — zero host->device
    bytes on the steady-state path."""

    name: str
    uid: int
    model: object
    resid: Residuals
    names: tuple
    skey: tuple
    n_toa: int
    n_param: int
    dof: int
    staged_p: dict
    staged_b: object
    slot_row: np.ndarray
    pmask_row: np.ndarray
    rowmask_row: np.ndarray
    crc: str               #: CRC32 (8 hex) over the staged arrays


class _ServeBucket:
    """One (structure key, pad shape) queue + its compiled program."""

    __slots__ = ("key", "skey", "n_toa", "n_param", "rep", "dkeys",
                 "include_offset", "pending", "fails", "state",
                 "opened_at")

    def __init__(self, key: tuple, job: PreparedJob):
        self.key = key
        self.skey = job.skey
        self.n_toa, self.n_param = key[1], key[2]
        self.rep = job
        self.dkeys = tuple(sorted(
            k for k, v in job.resid.pdict["delta"].items()
            if np.ndim(v) == 0))
        self.include_offset = "PhaseOffset" not in job.model.components
        self.pending: deque = deque()   # (PreparedJob, ServeFuture)
        # per-bucket circuit breaker (ISSUE 18): consecutive dispatch
        # failures open the bucket onto the eager lane; a half-open
        # probe after the cooldown restores the compiled path
        self.fails = 0
        self.state = "closed"           # closed | open | half_open
        self.opened_at = 0.0


class TimingService:
    """Continuous-batching timing daemon over the fleet bucket programs.

    Two modes share one dispatch path:

    * **inline** — ``submit*`` then :meth:`flush`: deterministic batch
      composition, the contract-audited request path.
    * **daemon** — :meth:`start` spawns the dispatcher thread: full
      buckets dispatch immediately, partial buckets when their oldest
      job has waited ``max_wait_ms``; :meth:`drain` closes admission,
      flushes everything and joins the thread.

    ``batch_size`` is the vmap width of every bucket program (part of
    the compiled shape, so one program per bucket regardless of
    occupancy — partial batches pad by repeating the last job's rows
    and only real rows resolve futures).  ``program_cache`` lets a
    restarted in-process service reuse compiled programs; across OS
    processes the same role is played by the AOT store
    (``runtime.acquire_backend(warm_start=True)``).

    Correlated-noise (GLS) models are rejected at :meth:`prepare` —
    their solves are host-exact by design (see the fleet module
    docstring); a serving lane for them would be dishonest."""

    def __init__(self, *, batch_size: int = 4, maxiter: int = 8,
                 tol_chi2: float = 1e-10,
                 threshold: Optional[float] = None, kernel=None,
                 track_mode: Optional[str] = None,
                 policy: Optional[str] = None,
                 diverge_streak: Optional[int] = None,
                 stall_iters: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 max_pending: int = 64,
                 spool: Optional[str] = None,
                 max_device_bytes: Optional[int] = None,
                 args_cache_size: int = 8,
                 program_cache: Optional[dict] = None,
                 stats_path: Optional[str] = None):
        from pint_tpu.fitter import FUSED_DIVERGE_STREAK, FUSED_STALL_ITERS

        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self.batch_size = int(batch_size)
        self.maxiter = int(maxiter)
        self.tol_chi2 = float(tol_chi2)
        self.threshold = threshold
        self.kernel = kernel
        self.track_mode = track_mode
        self.policy = policy
        self.diverge_streak = FUSED_DIVERGE_STREAK \
            if diverge_streak is None else int(diverge_streak)
        self.stall_iters = FUSED_STALL_ITERS \
            if stall_iters is None else int(stall_iters)
        if max_wait_ms is None:
            max_wait_ms = float(os.environ.get(
                "PINT_TPU_SERVE_MAX_WAIT_MS", DEFAULT_MAX_WAIT_MS))
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.max_pending = int(max_pending)
        self.spool = spool
        # blast-radius containment knobs (ISSUE 18).  The admission
        # guard is OFF unless a byte limit is configured — the healthy
        # steady-state path is untouched by default.
        if max_device_bytes is None:
            max_device_bytes = int(float(os.environ.get(
                "PINT_TPU_SERVE_MAX_DEVICE_BYTES", "0")))
        self.max_device_bytes = int(max_device_bytes) or None
        self._breaker_n = max(int(os.environ.get(
            "PINT_TPU_SERVE_BREAKER_N", "3")), 1)
        self._breaker_cooldown_s = float(os.environ.get(
            "PINT_TPU_SERVE_BREAKER_COOLDOWN_S", "5.0"))
        self._inflight_bytes = 0
        self._bucket_bytes: dict = {}
        self.args_cache_size = max(int(args_cache_size), 1)
        # live metrics (ISSUE 12): daemon mode writes stats() to this
        # atomic file every stats-interval so an operator (or the
        # telemetry CLI) can watch a running service without attaching
        if stats_path is None:
            stats_path = os.environ.get("PINT_TPU_SERVE_STATS_FILE") \
                or None
        self.stats_path = stats_path
        self._stats_interval_s = max(float(os.environ.get(
            "PINT_TPU_TELEMETRY_STATS_S", "1.0")), 0.05)
        self._last_stats_write = 0.0
        self._stats_file_writes = 0

        self._buckets: "OrderedDict[tuple, _ServeBucket]" = OrderedDict()
        self._programs: dict = {} if program_cache is None else program_cache
        self._args_lru: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._cond = threading.Condition()
        self._n_pending = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._draining = False
        self._latencies: deque = deque(maxlen=4096)
        self._stats = self._zero_stats()
        # metrics plane (ISSUE 13): opt-in /metrics + /healthz endpoint
        # (PINT_TPU_METRICS_PORT; port 0 -> ephemeral).  None when the
        # env knob is unset — the normal library posture
        self._metrics_exporter = metrics.start_exporter(
            stats_fn=self.stats)

    @staticmethod
    def _zero_stats() -> dict:
        return {"submitted": 0, "completed": 0, "rejected": 0,
                "spooled": 0, "dispatches": 0, "full_flushes": 0,
                "timer_flushes": 0, "drain_flushes": 0,
                "flush_flushes": 0, "occupancy_jobs": 0,
                # blast-radius containment counters (ISSUE 18)
                "deadline_misses": 0, "cancelled": 0,
                "over_capacity": 0, "quarantined": 0,
                "eager_served": 0, "breaker_opens": 0,
                "spool_skipped": 0}

    def reset_stats(self) -> None:
        """Zero the counters + latency samples (e.g. after a warmup
        pass, so a measurement window starts clean)."""
        with self._cond:
            self._stats = self._zero_stats()
            self._latencies.clear()

    # -- admission -------------------------------------------------------------

    def prepare(self, model, toas, name: Optional[str] = None) -> PreparedJob:
        """Host-side staging: builds the Residuals, derives the
        structure/shape bucket key and the padded single-row program
        inputs.  Everything expensive happens here, once — the request
        path (:meth:`submit_prepared` + :meth:`flush`) is queue ops and
        the coalesced dispatch."""
        if model.has_correlated_errors:
            raise CorrelatedErrors(model)
        resid = Residuals(toas, model, track_mode=self.track_mode,
                          policy=self.policy)
        names = tuple(FleetFitter._fleet_fit_params(model, resid))
        if not names:
            raise ValueError("model has no fleet-fittable free "
                             "parameters; nothing to serve")
        if name is None:
            name = getattr(getattr(model, "PSR", None), "value",
                           None) or f"JOB{next(_UID):06d}"
        pu = _Pulsar(str(name), 0, model, toas, resid, names,
                     resid.dof, False)
        skey = FleetFitter._structure_key(pu)
        n_toa = _pow2_at_least(resid.batch.ntoas, _MIN_TOA)
        n_param = _pow2_at_least(len(names), _MIN_PARAM)
        dkeys = tuple(sorted(k for k, v in resid.pdict["delta"].items()
                             if np.ndim(v) == 0))
        kidx = {k: j for j, k in enumerate(dkeys)}
        staged_p = _pad_pdict(resid, n_toa)
        staged_b = pad_batch_to(resid.batch, n_toa)
        slot_row = np.zeros(n_param, np.int32)
        pmask_row = np.zeros(n_param, np.float64)
        rowmask_row = np.zeros(n_toa, np.float64)
        for i, n in enumerate(names):
            slot_row[i] = kidx[n]
            pmask_row[i] = 1.0
        rowmask_row[:resid.batch.ntoas] = 1.0
        crc = aot.data_crc(
            jax.tree_util.tree_map(
                lambda v: np.asarray(v, np.float64), staged_p),
            staged_b, slot_row, pmask_row, rowmask_row)
        return PreparedJob(str(name), next(_UID), model, resid, names,
                           skey, n_toa, n_param, resid.dof, staged_p,
                           staged_b, slot_row, pmask_row, rowmask_row,
                           crc)

    def _has_capacity(self) -> bool:
        return self._n_pending < self.max_pending

    def _bucket_for(self, job: PreparedJob) -> _ServeBucket:
        key = (job.skey, job.n_toa, job.n_param)
        b = self._buckets.get(key)
        if b is None:
            b = _ServeBucket(key, job)
            self._buckets[key] = b
        return b

    def submit_prepared(self, job: PreparedJob,
                        deadline_s: Optional[float] = None) -> ServeFuture:
        """Admit a prepared job into its bucket's queue (bounded:
        overflow raises :class:`ServeSaturated`, the backpressure path
        driven by the ``request_flood`` failpoint).  ``deadline_s``
        (optional) expires the job with typed
        :class:`ServeDeadlineExceeded` if it is still queued — never
        mid-dispatch — that long after submission.  With a device-byte
        limit configured, admission also rides the cost-card guard
        (:meth:`_admit_capacity_locked`, typed
        :class:`ServeOverCapacity`)."""
        admit = faultinject.wrap("request_flood", self._has_capacity)
        with self._cond:
            if self._draining or self._stop:
                raise ServeDrained("service is draining; admission "
                                   "closed", spool=self.spool)
            if not admit():
                profiling.count("serve.rejected")
                self._stats["rejected"] += 1
                raise ServeSaturated(
                    f"request queue is full "
                    f"({self._n_pending}/{self.max_pending} pending); "
                    f"retry after in-flight batches drain")
            if deadline_s is not None and float(deadline_s) <= 0.0:
                self._stats["deadline_misses"] += 1
                profiling.count("serve.deadline_miss")
                raise ServeDeadlineExceeded(
                    f"job {job.name!r} deadline {deadline_s} s was "
                    f"already expired at admission",
                    deadline_s=float(deadline_s), waited_s=0.0)
            self._admit_capacity_locked(job)
            fut = ServeFuture(job.name, service=self,
                              deadline_s=deadline_s)
            self._bucket_for(job).pending.append((job, fut))
            self._n_pending += 1
            self._stats["submitted"] += 1
            profiling.count("serve.submit")
            self._cond.notify_all()
        # positional-only event() (ISSUE 13 satellite): an attr named
        # ``name`` no longer collides with the event's own name
        telemetry.event("serve.admit", name=job.name,
                        trace_id=fut.trace_id)
        return fut

    def submit(self, model, toas, name: Optional[str] = None,
               deadline_s: Optional[float] = None) -> ServeFuture:
        return self.submit_prepared(self.prepare(model, toas, name=name),
                                    deadline_s=deadline_s)

    # -- programs + staged device inputs ---------------------------------------

    def _bucket_program(self, bucket: _ServeBucket):
        prog = self._programs.get(bucket.key)
        if prog is None:
            kern = self.kernel if self.kernel is not None else \
                _default_wls_kernel()
            profiling.count("serve.program_build")
            prog = _build_bucket_fit(
                bucket.rep.model, bucket.rep.resid.track_mode,
                bucket.dkeys, bucket.n_param, bucket.include_offset,
                self.maxiter, self.tol_chi2, kern, self.threshold,
                self.diverge_streak, self.stall_iters)
            # the pad shape is a pure function of the job (pow2
            # quantization, not fleet's max-member padding), so this
            # fingerprint — and the call avals — are reproducible across
            # daemon restarts: a warm process resolves every program
            # from the store with zero compiles (CONTRACT003)
            prog = aot.serve(
                "serve_bucket", prog,
                f"{bucket.skey!r}"
                f"|ntoa={bucket.n_toa}|nparam={bucket.n_param}"
                f"|bs={self.batch_size}"
                f"|maxiter={self.maxiter}|tol={self.tol_chi2:g}"
                f"|thr={self.threshold}"
                f"|kern={getattr(kern, '__name__', str(kern))}"
                f"|streak={self.diverge_streak}"
                f"|stall={self.stall_iters}")
            self._programs[bucket.key] = prog
        return prog

    def _batch_args(self, bucket: _ServeBucket, jobs: List[PreparedJob]):
        akey = (bucket.key, tuple(j.uid for j in jobs))
        # the LRU is shared between the dispatcher daemon and any
        # caller-thread flush() — every touch happens under the lock
        # (lint v5 LOCK001: the unlocked get/move_to_end/popitem here
        # was a real OrderedDict race); the expensive staging below
        # stays outside it
        with self._cond:
            args = self._args_lru.get(akey)
            if args is not None:
                self._args_lru.move_to_end(akey)
        if args is not None:
            profiling.count("serve.args_reuse")
            return args
        stacked_p = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x, np.float64)
                                  for x in xs]),
            *[j.staged_p for j in jobs])
        stacked_b = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[j.staged_b for j in jobs])
        args = jax.device_put((
            stacked_p, stacked_b,
            jnp.asarray(np.stack([j.slot_row for j in jobs])),
            jnp.asarray(np.stack([j.pmask_row for j in jobs])),
            jnp.asarray(np.stack([j.rowmask_row for j in jobs]))))
        # donation between dispatches: jit donate_argnums would
        # invalidate these cached inputs (and is a no-op on CPU), so
        # residency is bounded here instead — evicting the LRU tail
        # releases its device buffers back to the allocator before the
        # next dispatch stages new ones.  Counting happens after the
        # lock is released: profiling.count fans out to hooks, and
        # hooks are never called with a service lock held
        evicted = 0
        with self._cond:
            self._args_lru[akey] = args
            while len(self._args_lru) > self.args_cache_size:
                self._args_lru.popitem(last=False)
                evicted += 1
        if evicted:
            profiling.count("serve.args_donate", evicted)
        return args

    # -- blast-radius containment (ISSUE 18) -----------------------------------

    def _estimate_bytes(self, job: PreparedJob) -> int:
        """Shape-based floor for one bucket dispatch's device footprint:
        staged inputs across the vmap width, with 3x headroom for the
        output row and solver transients."""
        n = 0
        for leaf in (jax.tree_util.tree_leaves(job.staged_p)
                     + jax.tree_util.tree_leaves(job.staged_b)):
            n += np.asarray(leaf).nbytes  # ddlint: disable=TRACE002 admission-time size probe, runs once per bucket key (cached in _bucket_bytes), never per dispatch
        n += (job.slot_row.nbytes + job.pmask_row.nbytes
              + job.rowmask_row.nbytes)
        return 3 * self.batch_size * n

    def _predict_job_bytes(self, job: PreparedJob) -> int:
        """Predicted per-dispatch device peak for the job's bucket: the
        harvested ``serve_bucket`` cost cards (the PR 11 metrics plane)
        when any exist, floored by the shape-based estimate.  Cached per
        bucket key — admission stays queue-ops cheap."""
        key = (job.skey, job.n_toa, job.n_param)
        got = self._bucket_bytes.get(key)
        if got is None:
            got = self._estimate_bytes(job)
            for card in metrics.cost_cards():
                if card.get("entry") != "serve_bucket":
                    continue
                peak = card.get("peak_bytes") or card.get("bytes_accessed")
                if peak:
                    got = max(got, int(peak))
            self._bucket_bytes[key] = got
        return got

    def _admit_capacity_locked(self, job: PreparedJob) -> None:
        """Cost-card admission guard (called under ``self._cond``):
        predict the job's bucket footprint and either briefly wait for
        in-flight bytes to drain or reject with typed
        ``ServeOverCapacity`` — the daemon refuses work instead of
        OOMing the device.  No-op unless ``max_device_bytes`` (or
        ``PINT_TPU_SERVE_MAX_DEVICE_BYTES``) is configured."""
        if self.max_device_bytes is None:
            return
        need = self._predict_job_bytes(job)
        if need > self.max_device_bytes:
            self._stats["over_capacity"] += 1
            profiling.count("serve.over_capacity")
            raise ServeOverCapacity(
                f"job {job.name!r} bucket is predicted to need {need} "
                f"device bytes > limit {self.max_device_bytes}; "
                f"refusing admission (would OOM)",
                predicted_bytes=need, limit_bytes=self.max_device_bytes)
        deadline = time.monotonic() + max(self.max_wait_s, 1e-3)
        while self._inflight_bytes + need > self.max_device_bytes:
            left = deadline - time.monotonic()
            if left <= 0:
                self._stats["over_capacity"] += 1
                profiling.count("serve.over_capacity")
                raise ServeOverCapacity(
                    f"job {job.name!r} needs {need} device bytes but "
                    f"{self._inflight_bytes} are in flight (limit "
                    f"{self.max_device_bytes}); not admitted within "
                    f"{self.max_wait_s:.3f} s",
                    predicted_bytes=need,
                    limit_bytes=self.max_device_bytes)
            self._cond.wait(left)

    def _cancel_future(self, fut: ServeFuture) -> bool:
        removed = False
        with self._cond:
            if fut.done():
                return False
            for bucket in self._buckets.values():
                kept = deque(p for p in bucket.pending
                             if p[1] is not fut)
                if len(kept) != len(bucket.pending):
                    bucket.pending = kept
                    removed = True
            if removed:
                self._n_pending -= 1
                self._stats["cancelled"] += 1
        if removed:
            profiling.count("serve.cancelled")
            fut._reject(ServeCancelled(
                f"job {fut.name!r} cancelled before staging"))
        return removed

    def _expire_locked(self, now: float) -> None:
        """Expire queued jobs past their deadline (called under
        ``self._cond``, strictly BEFORE batch selection — an in-flight
        batch is never interrupted, and an expired job costs zero
        device work)."""
        expired = []
        for bucket in self._buckets.values():
            if not bucket.pending:
                continue
            keep: deque = deque()
            for job, fut in bucket.pending:
                if fut.deadline_at is not None \
                        and now >= fut.deadline_at:
                    expired.append((job, fut))
                else:
                    keep.append((job, fut))
            if len(keep) != len(bucket.pending):
                bucket.pending = keep
        if not expired:
            return
        self._n_pending -= len(expired)
        self._stats["deadline_misses"] += len(expired)
        for job, fut in expired:
            waited = now - fut.submitted_at
            limit = fut.deadline_at - fut.submitted_at
            profiling.count("serve.deadline_miss")
            telemetry.warn("serve.deadline_miss", job=job.name,
                           trace_id=fut.trace_id, waited_s=waited)
            fut._reject(ServeDeadlineExceeded(
                f"job {job.name!r} expired in queue after "
                f"{waited:.3f} s (deadline {limit:.3f} s); never "
                f"staged", deadline_s=limit, waited_s=waited))

    def _shed_expired_pairs(self, pairs) -> list:
        """Pre-staging deadline re-check over pairs already TAKEN for a
        batch: any whose deadline passed between batch selection and
        staging rejects with ``ServeDeadlineExceeded`` (counted as a
        deadline miss, exactly like an in-queue expiry) and the
        survivors dispatch without it.  Closes the ISSUE 19 edge where
        a propagated deadline expired behind a slow scheduler gap but
        the job still rode the batch onto the device."""
        now = time.monotonic()
        expired = [(j, f) for j, f in pairs
                   if f.deadline_at is not None and now >= f.deadline_at]
        if not expired:
            return pairs
        with self._cond:
            self._stats["deadline_misses"] += len(expired)
        for job, fut in expired:
            waited = now - fut.submitted_at
            limit = fut.deadline_at - fut.submitted_at
            profiling.count("serve.deadline_miss")
            telemetry.warn("serve.deadline_miss", job=job.name,
                           trace_id=fut.trace_id, waited_s=waited,
                           stage="pre_staging")
            fut._reject(ServeDeadlineExceeded(
                f"job {job.name!r} expired after {waited:.3f} s "
                f"(deadline {limit:.3f} s), between batch selection "
                f"and staging; shed pre-staging",
                deadline_s=limit, waited_s=waited))
        gone = {id(f) for _, f in expired}
        return [(j, f) for j, f in pairs if id(f) not in gone]

    def _breaker_admit(self, bucket: _ServeBucket) -> bool:
        """True when the bucket's compiled program may be tried: breaker
        closed, or open past its cooldown (=> half-open probe)."""
        with self._cond:
            if bucket.state != "open":
                return True
            if time.monotonic() - bucket.opened_at \
                    >= self._breaker_cooldown_s:
                bucket.state = "half_open"
                telemetry.event("serve.breaker_probe",
                                bucket=_bucket_label(bucket.key))
                return True
            return False

    def _breaker_ok(self, bucket: _ServeBucket) -> None:
        closed = False
        with self._cond:
            if bucket.state != "closed":
                closed = True
            bucket.fails = 0
            bucket.state = "closed"
        if closed:
            telemetry.event("serve.breaker_close",
                            bucket=_bucket_label(bucket.key))

    def _breaker_fail(self, bucket: _ServeBucket) -> None:
        opened = False
        with self._cond:
            bucket.fails += 1
            # snapshot under the lock: another thread's _breaker_ok can
            # zero bucket.fails between release and the incident below
            # (lint v5: stale-read race — the incident/log would claim
            # 0 consecutive failures for a breaker that just opened)
            fails = bucket.fails
            if fails >= self._breaker_n \
                    and bucket.state != "open":
                bucket.state = "open"
                bucket.opened_at = time.monotonic()
                self._stats["breaker_opens"] += 1
                opened = True
        if opened:
            profiling.count("serve.breaker_open")
            telemetry.incident("serve.breaker_open",
                               bucket=_bucket_label(bucket.key),
                               fails=fails)
            _log.warning("bucket %s breaker OPEN after %d consecutive "
                         "dispatch failures; serving on the eager lane "
                         "until a half-open probe succeeds",
                         _bucket_label(bucket.key), fails)

    def _eager_fit(self, job: PreparedJob) -> ServeResult:
        """Solo host-driven fit on the PR 3 guarded engine — the lane
        quarantine/bisection/breaker recovery resolves through.  The
        job's model is deep-copied so the staged request is never
        mutated; raises ``ConvergenceFailure`` upward."""
        model = copy.deepcopy(job.model)
        f = WLSFitter(job.resid.toas, model,
                      track_mode=job.resid.track_mode,
                      policy=self.policy)
        chi2 = float(f.fit_toas(maxiter=self.maxiter,
                                tol_chi2=self.tol_chi2,
                                threshold=self.threshold))
        fr = f.fitresult
        x = np.asarray([
            float(np.sum(np.asarray(model[n].device_value, np.float64)
                         - np.asarray(job.model[n].device_value,
                                      np.float64)))
            for n in job.names], np.float64)
        status = getattr(fr, "status", FitStatus.CONVERGED)
        iters = int(getattr(fr, "iterations", 0) or 0)
        return ServeResult(job.name, chi2, job.dof, status, iters, x,
                           job.names, rung="eager")

    def _eager_confirm(self, bucket: _ServeBucket, pair,
                       cause=None) -> None:
        """Serve one suspect/orphaned job solo on the eager lane.  A
        job that still comes back non-finite is quarantined: typed
        ``ServePoisoned`` + a flight-recorder incident — never a
        silently wrong number, and never a batch-mate's problem."""
        job, fut = pair
        poisoned = faultinject.wrap(
            "poison_batch_member", lambda n: False)(job.name)
        res = None
        err = None
        if not poisoned:
            try:
                res = self._eager_fit(job)
            except Exception as e:
                err = e
        if (res is not None and np.isfinite(res.chi2)
                and np.all(np.isfinite(res.x))
                and res.status != FitStatus.NONFINITE):
            with self._cond:
                self._stats["eager_served"] += 1
            profiling.count("serve.eager_served")
            telemetry.warn("serve.quarantine_recovered", job=job.name,
                           trace_id=fut.trace_id,
                           bucket=_bucket_label(bucket.key))
            fut._resolve(res)
            return
        with self._cond:
            self._stats["quarantined"] += 1
        profiling.count("serve.quarantined")
        why = type(cause or err).__name__ if (cause or err) \
            else "non-finite result"
        telemetry.incident("ServePoisoned", job=job.name,
                           trace_id=fut.trace_id,
                           bucket=_bucket_label(bucket.key), cause=why)
        fut._reject(ServePoisoned(
            f"job {job.name!r} poisoned bucket {bucket.key!r}: "
            f"quarantined after eager-lane confirmation ({why})",
            job=job.name, bucket=_bucket_label(bucket.key), cause=cause or err))

    def _bisect(self, bucket: _ServeBucket, pairs, cause) -> None:
        """Isolate poison members after a failed dispatch by re-running
        the batch halves through the SAME compiled program.  vmap rows
        are independent, so a healthy mate's sub-batch row is
        bit-identical to its full-batch row — healthy jobs lose nothing
        to a poisoned neighbour."""
        if len(pairs) == 1:
            self._eager_confirm(bucket, pairs[0], cause=cause)
            return
        profiling.count("serve.bisect")
        mid = len(pairs) // 2
        for half in (pairs[:mid], pairs[mid:]):
            try:
                out = self._run_bucket(bucket, half)
            except Exception as exc:
                self._bisect(bucket, half, exc)
                continue
            _, suspects = self._resolve_rows(bucket, half, out)
            for pair in suspects:
                self._eager_confirm(bucket, pair, cause=cause)

    # -- dispatch --------------------------------------------------------------

    def _dispatch(self, bucket: _ServeBucket, pairs, reason: str) -> None:
        with telemetry.span(
                "serve.dispatch_bucket", reason=reason,
                n_toa=bucket.n_toa, n_param=bucket.n_param,
                jobs=[j.name for j, _ in pairs],
                traces=[f.trace_id for _, f in pairs]):
            self._dispatch_inner(bucket, pairs, reason)

    def _dispatch_inner(self, bucket: _ServeBucket, pairs,
                        reason: str) -> None:
        """One contained batch: try the compiled program; a dispatch
        failure bisects onto the eager lane (never crashes the flush),
        a non-finite row quarantines its job only.  The healthy path is
        byte-for-byte the pre-containment one: 0 compiles, 0 retraces,
        1 dispatch + 1 result fetch per coalesced batch."""
        # scheduler latency on the device path (drives deadline misses)
        faultinject.wrap("slow_dispatch", lambda: None)()
        # deadline re-check at pre-staging (ISSUE 19): a job taken into
        # this batch whose deadline expired during the scheduler gap
        # above is shed HERE, before it costs any device work — batch
        # selection already expired the queue, but the window between
        # take and stage was unguarded
        pairs = self._shed_expired_pairs(pairs)
        if not pairs:
            self._finish_batch(bucket, pairs, reason, dispatched=False)
            return
        if not self._breaker_admit(bucket):
            # breaker open: the bucket's program is suspect — every job
            # goes solo on the eager lane (rung "eager" or typed
            # ServePoisoned; loud either way) until the half-open probe
            for pair in pairs:
                self._eager_confirm(bucket, pair)
            self._finish_batch(bucket, pairs, reason, dispatched=False)
            return
        try:
            out = self._run_bucket(bucket, pairs)
        except Exception as exc:
            # containment, not propagation: one breaker failure count
            # per top-level dispatch, an incident dump with the failing
            # bucket's span + trace ids, then bisection isolates the
            # poison member(s) while healthy mates are re-served
            # bit-identically through the same program
            self._breaker_fail(bucket)
            telemetry.incident(
                "serve_bucket_failure", err=type(exc).__name__,
                bucket=_bucket_label(bucket.key),
                jobs=[j.name for j, _ in pairs],
                traces=[f.trace_id for _, f in pairs])
            _log.warning(
                "bucket %s dispatch failed (%s: %s); bisecting %d "
                "job(s) onto the eager lane",
                _bucket_label(bucket.key),
                type(exc).__name__, exc, len(pairs))
            self._bisect(bucket, pairs, exc)
            self._finish_batch(bucket, pairs, reason, dispatched=False)
            return
        self._breaker_ok(bucket)
        _, suspects = self._resolve_rows(bucket, pairs, out)
        for pair in suspects:
            self._eager_confirm(bucket, pair)
        self._finish_batch(bucket, pairs, reason, dispatched=True)

    def _run_bucket(self, bucket: _ServeBucket, pairs) -> np.ndarray:
        """The raw compiled-program primitive: pad, stage, 1 dispatch +
        1 result fetch.  Raises on dispatch failure (contained by
        :meth:`_dispatch_inner`); a poisoned member's row comes back
        non-finite."""
        # the recorder_crash failpoint fires HERE — inside the open
        # bucket span, after the admit events — so the flight recorder's
        # incident dump provably carries the failing bucket's span and
        # the admitting requests' trace ids (ISSUE 12's black-box proof)
        faultinject.wrap("recorder_crash", lambda: None)()
        # a dispatch-time allocator failure (RESOURCE_EXHAUSTED)
        faultinject.wrap("oom_dispatch", lambda: None)()
        jobs = [j for j, _ in pairs]
        padded = jobs + [jobs[-1]] * (self.batch_size - len(jobs))
        prog = self._bucket_program(bucket)
        args = self._batch_args(bucket, padded)
        need = self._predict_job_bytes(jobs[0]) \
            if self.max_device_bytes is not None else 0
        with self._cond:
            self._inflight_bytes += need
        try:
            profiling.count("serve.dispatch")
            out = np.asarray(prog(*args))   # 1 dispatch + 1 result fetch
        finally:
            with self._cond:
                self._inflight_bytes -= need
                self._cond.notify_all()
        # the chaos sweep's negative control: a seeded silent
        # corruption the sweep judge MUST catch (never in the default
        # fault set — tier-1 proves both directions)
        out = faultinject.wrap("silent_result_bias", lambda o: o)(out)
        pois = faultinject.wrap("poison_batch_member", lambda n: False)
        if any(pois(j.name) for j in jobs[:len(pairs)]):
            out = out.copy()
            for row in range(len(pairs)):
                if pois(jobs[row].name):
                    out[row, :] = np.nan
        return out

    def _resolve_rows(self, bucket: _ServeBucket, pairs, out) -> tuple:
        """Resolve each real row of a dispatch output; returns
        ``(resolved_pairs, suspect_pairs)``.  A suspect row (non-finite
        chi2/step, or NONFINITE status) is NOT resolved — it goes to
        the eager lane for confirmation instead of surfacing a bad
        number as if it were a fit."""
        P = bucket.n_param
        resolved, suspects = [], []
        for row, (job, fut) in enumerate(pairs):
            chi2 = float(out[row, P + _COL_CHI2])  # ddlint: disable=TRACE002 `out` is the host array fetched once above — no per-row device sync
            sval = float(out[row, P + _COL_STATUS])
            status = FitStatus(int(sval)) \
                if np.isfinite(sval) and 0 <= sval <= 3 \
                else FitStatus.NONFINITE
            x = out[row, :len(job.names)].copy()
            if (not np.isfinite(chi2) or not np.all(np.isfinite(x))
                    or status == FitStatus.NONFINITE):
                suspects.append((job, fut))
                continue
            fut._resolve(ServeResult(
                job.name, chi2, job.dof, status,
                int(out[row, P + _COL_ITERS]), x, job.names))
            resolved.append((job, fut))
        return resolved, suspects

    def _finish_batch(self, bucket: _ServeBucket, pairs, reason: str,
                      dispatched: bool) -> None:
        """Batch bookkeeping: the healthy path's numbers are unchanged
        (dispatches/occupancy count only real program dispatches;
        completed counts resolved futures)."""
        with self._cond:
            if dispatched:
                self._stats["dispatches"] += 1
                self._stats["occupancy_jobs"] += len(pairs)
            self._stats[f"{reason}_flushes"] += 1
            done = 0
            for _, fut in pairs:
                if fut.done() and fut._exc is None:
                    done += 1
                    self._latencies.append(
                        fut.resolved_at - fut.submitted_at)
            self._stats["completed"] += done
        profiling.count("serve.jobs_done", done)

    def _take_batch_locked(self, bucket: _ServeBucket) -> list:
        pairs = []
        while bucket.pending and len(pairs) < self.batch_size:
            pairs.append(bucket.pending.popleft())
        self._n_pending -= len(pairs)
        return pairs

    def _next_batch_locked(self):
        for bucket in self._buckets.values():
            if bucket.pending:
                return bucket, self._take_batch_locked(bucket)
        return None

    # warmup budget: one XLA program per bucket plus the one-time tiny
    # staging executables (stack/device_put) — same shape economics as
    # fleet_fit; steady state on the audit fixture is 1 coalesced batch
    # = 1 dispatch + 1 result fetch, compiles == retraces == 0
    @dispatch_contract("serve_request", max_compiles=24,
                       max_dispatches=4, max_transfers=8,
                       warm_from_store=True)
    def flush(self, reason: str = "flush") -> int:
        """Dispatch every pending batch now (the inline request path and
        the drain path); returns the number of jobs resolved.

        Dispatch contract ``serve_request``: the first flush of a bucket
        compiles its one program (or resolves it from the AOT store —
        zero compiles in a warm process, CONTRACT003); a steady-state
        flush is 1 dispatch + 1 result fetch per coalesced batch, zero
        compiles, zero retraces (CONTRACT001/002) — per-request
        recompilation is structurally impossible.

        SIGTERM/SIGINT mid-flush rides the PR 4 machinery
        (:class:`pint_tpu.runtime.SignalFlush` + the
        ``sigterm_midscan`` failpoint): the in-flight batch finishes and
        its futures resolve; when a ``spool`` path is configured, every
        still-queued job is flushed there via
        :func:`pint_tpu.runtime.write_checkpoint`, its future rejects
        with :class:`ServeDrained`, and ``ServeDrained`` is raised —
        :meth:`resume_spool` readmits the spool bit-identically."""
        after_batch = faultinject.wrap("sigterm_midscan", lambda ci: None)
        done = 0
        bi = 0
        with telemetry.span("serve.flush", reason=reason), \
                runtime.SignalFlush() as sigs:
            while True:
                with self._cond:
                    self._expire_locked(time.monotonic())
                    nxt = self._next_batch_locked()
                if nxt is None:
                    break
                bucket, pairs = nxt
                self._dispatch(bucket, pairs, reason)
                done += len(pairs)
                after_batch(bi)
                bi += 1
                if sigs.fired is not None and self.spool is not None:
                    self._spool_pending(sigs.fired)
        return done

    # -- drain / spool / resume ------------------------------------------------

    def _spool_pending(self, signum: int) -> None:
        """Flush every queued (not-yet-dispatched) job to the spool and
        raise ``ServeDrained`` — the SIGTERM half of graceful drain."""
        with self._cond:
            self._draining = True
            pairs = []
            for bucket in self._buckets.values():
                while bucket.pending:
                    pairs.append(bucket.pending.popleft())
            self._n_pending = 0
            self._stats["spooled"] += len(pairs)
        payload = {
            "signature": np.frombuffer(_SPOOL_SIG.encode(), np.uint8),
            "count": np.asarray(len(pairs), np.int64)}
        for i, (job, _) in enumerate(pairs):
            payload[f"job{i}_name"] = np.frombuffer(job.name.encode(),
                                                    np.uint8)
            payload[f"job{i}_crc"] = np.frombuffer(job.crc.encode(),
                                                   np.uint8)
            payload[f"job{i}_params"] = np.frombuffer(
                ",".join(job.names).encode(), np.uint8)
            payload[f"job{i}_ntoa"] = np.asarray(  # ddlint: disable=TRACE002 ntoas is host metadata (a Python int), not a device value
                job.resid.batch.ntoas, np.int64)
        with telemetry.span("serve.spool", signum=signum,
                            n_spooled=len(pairs),
                            traces=[f.trace_id for _, f in pairs]):
            runtime.write_checkpoint(self.spool, payload)
            profiling.count("serve.spool_write")
        _log.info("serve drained on signal %s: %d job(s) spooled to %s",
                  signum, len(pairs), self.spool)
        err = ServeDrained(
            f"serve drained on signal {signum}: {len(pairs)} queued "
            f"job(s) spooled to {self.spool!r}", spool=self.spool,
            n_spooled=len(pairs), signum=signum)
        for _, fut in pairs:
            fut._reject(err)
        telemetry.warn("serve.drained", signum=signum,
                       n_spooled=len(pairs), spool=self.spool)
        telemetry.dump_on_failure("ServeDrained")
        raise err

    def _spool_skip(self, name: str, reason: str, detail: str) -> None:
        with self._cond:
            self._stats["spool_skipped"] += 1
        profiling.count("serve.spool_skip")
        telemetry.warn("serve.spool_skip", job=name, reason=reason,
                       spool=self.spool)
        warnings.warn(f"serve spool {self.spool!r}: skipping job "
                      f"{name!r} ({detail})", RuntimeWarning,
                      stacklevel=3)

    def resume_spool(self, jobs) -> List[ServeFuture]:
        """Readmit the jobs a drained service spooled.  The spool stores
        identity + a CRC32 of each job's staged arrays, not the (model,
        TOAs) objects, so the caller supplies re-:meth:`prepare`-d jobs
        covering the spooled names; each is verified BIT-identical to
        what was queued (same staged params/batch/mask bytes) before
        admission.

        A blemished spool no longer takes the whole resume down (ISSUE
        18): a CRC-mismatched resubmission or a spooled name with no
        matching prepared job is SKIPPED — with a ``RuntimeWarning`` +
        a ``serve.spool_skip`` telemetry event, never silently refit
        from different data — and the remainder is readmitted.  A
        corrupt spool container (the ``runtime.load_checkpoint`` CRC)
        likewise warns and resumes nothing.  A file that is not a serve
        spool at all is still a hard ``ValueError`` (caller error, not
        rot)."""
        if self.spool is None:
            raise ValueError("this service has no spool path configured")
        try:
            data = runtime.load_checkpoint(self.spool)   # CRC-verified
        except CheckpointCorruptError as exc:
            self._spool_skip("*", "corrupt_container",
                             f"corrupt spool container, resuming "
                             f"nothing: {exc}")
            return []
        sig = bytes(np.asarray(data["signature"], np.uint8)).decode(
            errors="replace")
        if sig != _SPOOL_SIG:
            raise ValueError(f"{self.spool!r} is not a serve spool "
                             f"(signature {sig!r})")
        by_name: Dict[str, PreparedJob] = {}
        for j in jobs:
            by_name.setdefault(j.name, j)
        futs = []
        for i in range(int(data["count"])):
            name = bytes(np.asarray(data[f"job{i}_name"],
                                    np.uint8)).decode()
            crc = bytes(np.asarray(data[f"job{i}_crc"],
                                   np.uint8)).decode()
            job = by_name.get(name)
            if job is None:
                self._spool_skip(name, "no_matching_prepared",
                                 "no matching prepared job supplied")
                continue
            if job.crc != crc:
                self._spool_skip(
                    name, "crc_mismatch",
                    f"resubmitted crc {job.crc} != spooled {crc}; "
                    f"refusing to resume a different fit")
                continue
            futs.append(self.submit_prepared(job))
        profiling.count("serve.spool_resume", len(futs))
        return futs

    # -- daemon mode -----------------------------------------------------------

    def start(self) -> "TimingService":
        """Start the dispatcher thread (daemon mode): full buckets
        dispatch immediately; partial buckets when their oldest job has
        waited ``max_wait_ms``."""
        with self._cond:
            if self._thread is None:
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, name="pint-tpu-serve", daemon=True)
                self._thread.start()
        return self

    def _ready_batch_locked(self):
        """Next (bucket, pairs, reason) under the continuous-batching
        policy, or None.  The bucket-full check routes through the
        ``stalled_bucket`` failpoint: with it active only the
        max-latency timer (or drain) can flush, which is how the timer
        path is proven rather than assumed."""
        full = faultinject.wrap(
            "stalled_bucket",
            lambda b: len(b.pending) >= self.batch_size)
        now = time.monotonic()
        self._expire_locked(now)
        for bucket in self._buckets.values():
            if not bucket.pending:
                continue
            if self._stop or self._draining:
                return bucket, self._take_batch_locked(bucket), "drain"
            if full(bucket):
                return bucket, self._take_batch_locked(bucket), "full"
            if now - bucket.pending[0][1].submitted_at >= self.max_wait_s:
                profiling.count("serve.timer_fire")
                return bucket, self._take_batch_locked(bucket), "timer"
        return None

    def _wait_s_locked(self) -> Optional[float]:
        if self._n_pending == 0:
            return None
        deadline = min(b.pending[0][1].submitted_at + self.max_wait_s
                       for b in self._buckets.values() if b.pending)
        # wake for request deadlines too, so expiry is prompt even when
        # the max-latency timer is far out
        for b in self._buckets.values():
            for _, fut in b.pending:
                if fut.deadline_at is not None:
                    deadline = min(deadline, fut.deadline_at)
        return max(deadline - time.monotonic(), 0.0) + 1e-3

    def _loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    if self._stop and self._n_pending == 0:
                        return
                    got = self._ready_batch_locked()
                    if got is not None:
                        break
                    self._cond.wait(self._wait_s_locked())
                bucket, pairs, reason = got
            try:
                self._dispatch(bucket, pairs, reason)
            except Exception as e:   # futures must always resolve
                for _, fut in pairs:
                    if not fut.done():
                        fut._reject(e)
            # supervised-restart failpoint: a one-shot SIGTERM between
            # dispatches (the crash window `serve supervise` recovers
            # from with a backoff restart + spool resume)
            faultinject.wrap("kill_daemon", lambda: None)()
            self._maybe_write_stats()

    def _maybe_write_stats(self, force: bool = False) -> None:
        """Refresh the atomic live-stats file (daemon mode), rate-limited
        to the ``PINT_TPU_TELEMETRY_STATS_S`` interval.  Best-effort: a
        full disk must not take the dispatcher down."""
        if self.stats_path is None:
            return
        now = time.monotonic()
        # the rate-limit check-and-set is atomic under the lock (lint
        # v5 LOCK001: the daemon's _loop and a caller-thread drain()
        # could both pass the unlocked check and double-write); the
        # file write itself happens after release — stats() retakes
        # the same non-reentrant lock
        with self._cond:
            if not force and \
                    now - self._last_stats_write < self._stats_interval_s:
                return
            self._last_stats_write = now
        try:
            telemetry.write_stats(self.stats_path, self.stats())
            with self._cond:
                self._stats_file_writes += 1
        except OSError:
            pass

    def drain(self, timeout: Optional[float] = 600.0) -> dict:
        """Graceful shutdown: admission closes, every pending job
        dispatches (partial buckets included — the drain path), the
        dispatcher thread exits.  Inline-mode services just flush.
        Returns :meth:`stats`."""
        with self._cond:
            self._draining = True
            self._stop = True
            thread = self._thread
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout)
            with self._cond:
                self._thread = None
        else:
            self.flush(reason="drain")
        self._maybe_write_stats(force=True)
        # the exporter deliberately lives past drain: a supervisor's
        # last scrape sees the final snapshot.  stop_metrics() (or
        # process exit — daemon thread) closes it.
        return self.stats()

    # -- observability ---------------------------------------------------------

    def stop_metrics(self) -> None:
        """Shut the /metrics endpoint down (a no-op when the exporter
        was never started)."""
        exp = self._metrics_exporter
        self._metrics_exporter = None
        if exp is not None:
            exp.stop()

    @property
    def metrics_port(self) -> Optional[int]:
        """The bound /metrics port, or None when the exporter is off
        (tests bind port 0 and read the ephemeral port back here)."""
        exp = self._metrics_exporter
        return exp.port if exp is not None else None

    def stats(self) -> dict:
        """Thread-safe snapshot: counters, latency percentiles and the
        derived occupancy/timer fractions (the ``bench_serve``
        fields)."""
        with self._cond:
            s = dict(self._stats)
            lat = list(self._latencies)
            s["pending"] = self._n_pending
            s["n_buckets"] = len(self._buckets)
            s["n_programs"] = len(self._programs)
            s["stats_file_writes"] = self._stats_file_writes
            # per-bucket breaker map (ISSUE 18): rides /healthz (this
            # dict IS the healthz body) and the labelled
            # pint_tpu_serve_breaker Prometheus gauge
            s["breaker_state"] = {_bucket_label(b.key): b.state
                                  for b in self._buckets.values()}
        s.update(profiling.latency_stats(lat))
        d = s["dispatches"]
        s["batch_occupancy"] = \
            (s["occupancy_jobs"] / (d * self.batch_size)) if d else 0.0
        s["timer_flush_fraction"] = (s["timer_flushes"] / d) if d else 0.0
        s["deadline_miss_fraction"] = \
            s["deadline_misses"] / max(s["submitted"], 1)
        return s


# --- demo service + CLI -------------------------------------------------------

def _demo_service(*, batch_size: int = 2, maxiter: int = 3,
                  max_wait_ms: Optional[float] = None,
                  spool: Optional[str] = None,
                  program_cache: Optional[dict] = None,
                  stats_path: Optional[str] = None):
    """Deterministic 4-pulsar / 2-bucket service + prepared jobs, shared
    by the AOT warm fixture (``--fixtures serve``), the serve CLI
    self-check, and the bench leg.  Mirrors ``aot._fleet4_fixture``'s
    pulsars (sizes 8/8/16/16, heterogeneous FD-block freezing) under
    distinct PSR names so its ``serve_bucket`` ProgramKeys are its
    own."""
    import warnings as _w

    from pint_tpu.aot import _B1855_PAR
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    svc = TimingService(batch_size=batch_size, maxiter=maxiter,
                        max_wait_ms=max_wait_ms, spool=spool,
                        program_cache=program_cache,
                        stats_path=stats_path)
    jobs = []
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        for i, n in enumerate((8, 8, 16, 16)):
            par = _B1855_PAR.replace("B1855+09SIM", f"SERVE{i}")
            model = get_model(par.strip().splitlines())
            model.A1.frozen = True
            model.TASC.frozen = True
            if i % 2:   # heterogeneous slots: half freeze the FD block
                model.FD1.frozen = True
                model.FD2.frozen = True
            toas = make_fake_toas_uniform(
                55000.0, 55060.0, n, model, obs="gbt", error_us=300.0,
                freq_mhz=np.tile([1400.0, 800.0], (n + 1) // 2)[:n],
                add_noise=True, seed=200 + i)
            jobs.append(svc.prepare(model, toas, name=f"SERVE{i}"))
    return svc, jobs


def _check(args) -> int:
    """The ``check`` subcommand: :func:`_check_body` under the dynamic
    lock audit (``lint.lockhooks.maybe_instrument`` — a null context
    unless ``PINT_TPU_LOCKAUDIT=1`` or a concurrency failpoint is
    active).  CONTRACT005 findings go to STDERR (stdout must stay a
    single JSON line — the chaos sweep parses it) and force rc 1."""
    import sys

    from pint_tpu.lint import lockhooks

    with lockhooks.maybe_instrument() as audit:
        rc = _check_body(args)
    if audit is not None:
        findings = audit.judge()
        for f in findings:
            print(f.format(), file=sys.stderr)
        if findings:
            return 1
    return rc


def _check_body(args) -> int:
    """The ``check`` subcommand body: demo/pta corpus through the
    daemon path -> one JSON line with per-job results (chi2 as
    ``float.hex`` for bit-exact comparison — the chaos-sweep judge's
    ground truth)."""
    from pint_tpu.exceptions import ServeError

    # a crashed check leaves a flight recording when
    # PINT_TPU_TELEMETRY_DUMP is set — the black-box subprocess surface
    telemetry.install_excepthook()
    st = runtime.acquire_backend()
    deadline_s = (args.deadline_ms / 1e3) if args.deadline_ms else None
    if args.corpus == "pta":
        # the factory's first realistic heavy-traffic corpus: a
        # simulated fleet whose power-of-two shape classes land in the
        # daemon's bounded bucket set by construction (ISSUE 15)
        from pint_tpu import pta

        run = pta.build(pta.Scenario(
            n_pulsars=args.pta_n, seed=0,
            chunk_size=min(8, args.pta_n),
            cadence=pta.Cadence(span_days=360.0, cadence_days=15.0)))
        sim = run.simulate()
        svc = TimingService(batch_size=args.batch_size, maxiter=3,
                            max_wait_ms=args.wait_ms, spool=args.spool)
        jobs = sim.serve_jobs(svc)
    else:
        svc, jobs = _demo_service(batch_size=args.batch_size,
                                  maxiter=3,
                                  max_wait_ms=args.wait_ms,
                                  spool=args.spool)
    # warm the bucket programs inline so the daemon-phase stats measure
    # the serving policy, not first-call compiles; under request_flood
    # the warmup is rejected too — then nothing dispatches and no
    # program is needed.  Containment applies here too: a warm future
    # may reject typed (e.g. a poisoned member) without aborting the run
    warmed = True
    try:
        wf = [svc.submit_prepared(j) for j in jobs]
        svc.flush()
        for f in wf:
            try:
                f.result(timeout=600.0)
            except ServeError:
                pass
    except ServeSaturated:
        warmed = False
    svc.reset_stats()

    svc.start()
    t0 = time.monotonic()
    futs = []
    rejected = 0
    interrupted = None
    spooled = 0
    resumed = None
    sigs = runtime.SignalFlush() if args.spool else None
    try:
        if sigs is not None:
            sigs.__enter__()
        if args.resume:
            # restarted-daemon half of `supervise`: NO fresh
            # submissions — readmit exactly what the killed daemon
            # spooled, so no job is lost and none is fit twice
            futs = svc.resume_spool(jobs)
            resumed = len(futs)
        else:
            for i in range(args.jobs):
                if sigs is not None and sigs.fired is not None:
                    break
                try:
                    futs.append(svc.submit_prepared(
                        jobs[i % len(jobs)], deadline_s=deadline_s))
                except (ServeSaturated, ServeOverCapacity,
                        ServeDeadlineExceeded):
                    rejected += 1
                time.sleep(args.stagger_ms / 1e3)
        # let partial buckets hit their max-latency deadline (the timer
        # path) before drain would flush them
        if sigs is None or sigs.fired is None:
            time.sleep(3.0 * svc.max_wait_s)
        if sigs is not None and sigs.fired is not None:
            try:
                svc._spool_pending(sigs.fired)
            except ServeDrained as e:
                interrupted = sigs.fired
                spooled = e.n_spooled
    finally:
        if sigs is not None:
            sigs.__exit__(None, None, None)
    s = svc.drain(timeout=600.0)
    statuses: Dict[str, int] = {}
    errors: Dict[str, int] = {}
    results: Dict[str, dict] = {}
    ok = 0
    completed = 0
    for i, f in enumerate(futs):
        key = f"{i}:{f.name}"
        try:
            r = f.result(timeout=600.0)
        except Exception as e:
            errors[type(e).__name__] = \
                errors.get(type(e).__name__, 0) + 1
            results[key] = {"error": type(e).__name__, "flagged": True}
            continue
        completed += 1
        statuses[r.status.name] = statuses.get(r.status.name, 0) + 1
        ok += bool(r.ok)
        # chi2 as float.hex(): the sweep judge compares un-flagged
        # results bit-exactly against the clean baseline — "flagged"
        # (typed error or a non-bucket rung) is the loud-degradation
        # exemption
        results[key] = {"chi2_hex": float(r.chi2).hex(),
                        "status": r.status.name, "rung": r.rung,
                        "iterations": int(r.iterations),
                        "flagged": r.rung != "bucket"}
    wall = time.monotonic() - t0
    line = {"mode": "check", "backend": st.rung, "warmed": warmed,
            "jobs": args.jobs, "submitted": len(futs),
            "completed": completed, "rejected": rejected,
            "converged_or_maxiter": ok, "statuses": statuses,
            "errors": errors, "results": results,
            "interrupted": interrupted, "spooled": spooled,
            "jobs_resumed": resumed, "wall_s": round(wall, 3),
            "fits_per_sec": round(completed / wall, 3) if wall > 0
            else 0.0}
    for k in ("dispatches", "full_flushes", "timer_flushes",
              "drain_flushes", "batch_occupancy",
              "timer_flush_fraction", "p50_ms", "p99_ms",
              "quarantined", "eager_served", "deadline_misses",
              "deadline_miss_fraction", "cancelled", "over_capacity",
              "breaker_opens", "breaker_state", "spool_skipped"):
        v = s[k]
        line[k] = round(v, 3) if isinstance(v, float) else v
    print(json.dumps(line))
    if interrupted is not None:
        # graceful drain-under-signal: distinct rc so a supervisor can
        # tell "killed with a spool to resume" from clean/broken
        return 3
    if args.resume:
        return 0 if completed == len(futs) else 1
    return 0 if len(futs) + rejected == args.jobs else 1


def _supervise(args) -> int:
    """``supervise``: run the check daemon under
    :func:`runtime.run_supervised` — a crashed/killed daemon restarts
    with exponential backoff and resumes its spool, so no admitted job
    is lost and none is fit twice."""
    import sys

    def argv(attempt: int) -> list:
        cmd = [sys.executable, "-m", "pint_tpu.serve", "check",
               "--jobs", str(args.jobs),
               "--wait-ms", str(args.wait_ms),
               "--batch-size", str(args.batch_size),
               "--stagger-ms", str(args.stagger_ms),
               "--spool", args.spool]
        if attempt > 0 and os.path.exists(args.spool):
            cmd.append("--resume")
        return cmd

    attempts = runtime.run_supervised(
        argv, max_restarts=args.max_restarts, backoff_s=args.backoff_s,
        clean_rcs=(0,), timeout_s=args.timeout_s)
    parsed = []
    for rc, stdout, stderr in attempts:
        doc = {}
        for ln in reversed([x for x in stdout.splitlines()
                            if x.strip()]):
            try:
                doc = json.loads(ln)
                break
            except ValueError:
                continue
        parsed.append({"rc": rc,
                       "submitted": doc.get("submitted"),
                       "completed": doc.get("completed"),
                       "spooled": doc.get("spooled"),
                       "jobs_resumed": doc.get("jobs_resumed"),
                       "interrupted": doc.get("interrupted")})
        if rc not in (0, 3):
            print(stderr[-800:], file=sys.stderr)
    completed_total = sum(p["completed"] or 0 for p in parsed)
    okflag = bool(attempts) and attempts[-1][0] == 0
    print(json.dumps({"mode": "supervise", "attempts": parsed,
                      "restarts": max(len(parsed) - 1, 0),
                      "completed_total": completed_total,
                      "ok": okflag}))
    return 0 if okflag else 1


def main(argv=None) -> int:
    """``python -m pint_tpu.serve check|supervise``: drive the demo
    service through the daemon path and print one JSON line — the
    subprocess surface the tooling tests and the chaos sweep exercise
    under the serve failpoints."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m pint_tpu.serve",
        description="continuous-batching timing daemon")
    sub = ap.add_subparsers(dest="cmd", required=True)
    chk = sub.add_parser(
        "check", help="daemon self-exercise -> one JSON line of stats")
    chk.add_argument("--jobs", type=int, default=12)
    chk.add_argument("--wait-ms", type=float, default=40.0)
    chk.add_argument("--batch-size", type=int, default=2)
    chk.add_argument("--stagger-ms", type=float, default=2.0)
    chk.add_argument("--deadline-ms", type=float, default=0.0,
                     help="per-request deadline (0 = none): queued "
                     "jobs past it expire with ServeDeadlineExceeded, "
                     "never mid-dispatch")
    chk.add_argument("--spool", default=None,
                     help="drain spool path; also arms the SIGTERM "
                     "record-don't-kill window (exit 3 = interrupted "
                     "with a spool to resume)")
    chk.add_argument("--resume", action="store_true",
                     help="readmit the spool instead of submitting "
                     "fresh jobs (the restarted-daemon half of "
                     "supervise)")
    chk.add_argument("--corpus", choices=("demo", "pta"),
                     default="demo",
                     help="traffic corpus: the 4-pulsar demo set, or "
                     "a simulated PTA fleet (pint_tpu.pta factory)")
    chk.add_argument("--pta-n", type=int, default=8,
                     help="pulsar count for --corpus pta")
    sup = sub.add_parser(
        "supervise", help="run the check daemon under a restarting "
        "supervisor (crash -> backoff restart -> spool resume)")
    sup.add_argument("--spool", required=True)
    sup.add_argument("--jobs", type=int, default=12)
    sup.add_argument("--wait-ms", type=float, default=40.0)
    sup.add_argument("--batch-size", type=int, default=2)
    sup.add_argument("--stagger-ms", type=float, default=2.0)
    sup.add_argument("--max-restarts", type=int, default=3)
    sup.add_argument("--backoff-s", type=float, default=0.25)
    sup.add_argument("--timeout-s", type=float, default=600.0)
    args = ap.parse_args(argv)
    if args.cmd == "supervise":
        return _supervise(args)
    return _check(args)


if __name__ == "__main__":   # pragma: no cover
    # delegate to the canonical module instance so failpoints/counters
    # registered at import time are shared (the aot CLI idiom)
    import sys as _sys

    from pint_tpu.serve import main as _main

    _sys.exit(_main())
