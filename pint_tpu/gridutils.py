"""Chi-squared grids over frozen parameters — the vmap showcase.

Reference: `grid_chisq` (`/root/reference/src/pint/gridutils.py:169`), which
deep-copies the whole fitter per grid point and farms points out to a
`ProcessPoolExecutor` (`gridutils.py:36-116,322-331`) — the reference's only
scale-out mechanism, at ~20 s/point on CPU.

Here a grid point is just a different value of some ``p["delta"]`` leaves in
the params pytree, so the WHOLE grid is one `jax.vmap` of the jitted
Gauss-Newton fit over a stacked pytree: one XLA program, all points resident
on the accelerator, no copies, no processes.  Sharding the same stacked
axis over a device mesh is `pint_tpu.parallel`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitter import (Fitter, _default_wls_kernel,
                             build_whitened_assembly, wls_solve)
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals

__all__ = ["grid_chisq", "grid_chisq_flat", "grid_chisq_derived",
           "build_grid_fit_fn",
           "stack_grid_pdict", "grid_in_axes"]


def _grid_deltas(model: TimingModel, p: dict,
                 grid_values: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Device-unit delta arrays (G,) that realize the requested par-unit
    grid values for each (frozen) grid parameter."""
    out = {}
    for name, vals in grid_values.items():
        par = model[name]
        vals = np.asarray(vals, np.float64)
        base = np.asarray(par.device_value, np.float64)
        if par.kind == "mjd":
            out[name] = vals - (base[0] + base[1])  # grid given in MJD
        else:
            out[name] = vals * par.par2dev - base
    return out


def stack_grid_pdict(model: TimingModel, p: dict,
                     grid_values: Dict[str, np.ndarray]) -> dict:
    """A params pytree whose ``delta`` leaves for the grid parameters carry
    a leading grid axis; everything else is shared."""
    deltas = _grid_deltas(model, p, grid_values)
    delta = dict(p["delta"])
    for name, d in deltas.items():
        delta[name] = jnp.asarray(d)
    out = dict(p)
    out["delta"] = delta
    return out


def grid_in_axes(p: dict, grid_names: Sequence[str]) -> dict:
    """The matching `jax.vmap` in_axes pytree: 0 on the grid deltas."""
    names = set(grid_names)
    return {
        "const": {k: None for k in p["const"]},
        "delta": {k: (0 if k in names else None) for k in p["delta"]},
        "mask": {k: None for k in p["mask"]},
    }


def build_grid_fit_fn(model: TimingModel, batch, fit_params: Sequence[str],
                      track_mode: str, maxiter: int = 2,
                      threshold: Optional[float] = None, kernel=None,
                      design_matrix: Optional[str] = None):
    """``fit_one(p, cols=None) -> (chi2, x)``: a full (fixed-iteration)
    WLS fit of one pytree — vmap/shard_map this over stacked grid
    pytrees.  ``kernel`` forces a specific WLS solve kernel (default:
    backend-matched).

    With the split design-matrix path (the default), the linear-block
    columns are computed ONCE per fit point — hoisted out of the
    Gauss-Newton iteration loop in-graph — cutting the per-point JVP
    fan-out from maxiter*P to P_lin + maxiter*P_nl tangents.  Columns
    are deliberately NOT shared across grid points: the sharded path
    (`pint_tpu.parallel`) computes them per point, and the two paths
    must track each other to rounding even on ill-conditioned systems
    where the Gauss-Newton iteration has not fully settled (the bench
    asserts 1e-6 agreement).  ``cols`` lets a caller override the
    columns explicitly; ``fit_one.assemble`` exposes the underlying
    assembly (``.split``/``.lin_cols``)."""
    names = list(fit_params)
    # all-device solve: the grid is one vmapped XLA program; the
    # eigh kernel is right for chi2 maps (see build_wls_step)
    assemble = build_whitened_assembly(model, batch, names, track_mode,
                                       include_offset=True,
                                       design_matrix=design_matrix)
    kern = _default_wls_kernel() if kernel is None else kernel

    def step(x, p, cols):
        if assemble.split:
            c = assemble.lin_cols(x, p) if cols is None else cols
            r, M, sigma, offc = assemble.inline_with_cols(x, p, c)
        else:
            r, M, sigma, offc = assemble.inline(x, p)
        return wls_solve(jnp, r, M, sigma, offc, kern, len(names),
                         threshold)

    def fit_one(p, cols=None):
        if assemble.split and cols is None:
            # per-point hoist: one column computation shared by every
            # iteration of this fit
            cols = assemble.lin_cols(jnp.zeros(len(names)), p)
        x = jnp.zeros(len(names))
        for _ in range(maxiter):
            x = x + step(x, p, cols)["dx"]
        out = step(x, p, cols)
        return out["chi2"], x

    fit_one.assemble = assemble
    return fit_one


def grid_chisq_flat(fitter: Fitter, grid_values: Dict[str, np.ndarray],
                    maxiter: int = 2, kernel=None) -> np.ndarray:
    """chi2 at each of G grid points (all grid arrays shape (G,)); the
    non-grid free parameters are re-fit at every point.  ``kernel``
    forces a specific WLS solve kernel (default: backend-matched)."""
    model = fitter.model
    r = fitter.resids
    names = [n for n in fitter.fit_params if n not in grid_values]
    for n in grid_values:
        if not model[n].frozen:
            raise ValueError(f"grid parameter {n} must be frozen")
    p = r.pdict
    # cache the compiled vmapped fit on the fitter: a fresh jit wrapper
    # per call would retrace the whole grid program every time
    key = (tuple(sorted(grid_values)), tuple(names), maxiter, kernel,
           getattr(fitter, "design_matrix", None))
    cache = getattr(fitter, "_grid_fit_cache", None)
    if cache is None:
        cache = fitter._grid_fit_cache = {}
    vfit = cache.get(key)
    if vfit is None:
        fit_one = build_grid_fit_fn(
            model, r.batch, names, fitter.track_mode, maxiter=maxiter,
            kernel=kernel,
            design_matrix=getattr(fitter, "design_matrix", None))
        axes = grid_in_axes(p, list(grid_values))
        # per-point cached columns (computed inside fit_one, hoisted out
        # of its iteration loop) — see build_grid_fit_fn for why they
        # are not shared across points
        vfit = cache[key] = jax.jit(
            jax.vmap(lambda pp: fit_one(pp), in_axes=(axes,)))
    stacked = stack_grid_pdict(model, p, grid_values)
    chi2, _ = vfit(stacked)
    return _check_grid_chi2(np.asarray(chi2))


def _check_grid_chi2(chi2: np.ndarray) -> np.ndarray:
    """Non-finite guard for vmapped/sharded grid fits: inside the one
    compiled program a poisoned grid point is invisible, so the host
    boundary is where a NaN chi2 must be called out (the values are
    still returned — a partial grid is useful — but never silently)."""
    bad = int(np.sum(~np.isfinite(chi2)))
    if bad:
        import warnings

        from pint_tpu import profiling
        from pint_tpu.exceptions import PintTpuWarning

        profiling.count("guard.grid_nonfinite", bad)
        warnings.warn(
            f"{bad}/{chi2.size} grid points returned non-finite chi2 "
            "(degenerate or diverging fits at those parameter values)",
            PintTpuWarning)
    return chi2


def grid_chisq(fitter: Fitter, parnames: Sequence[str],
               parvalues: Sequence[np.ndarray],
               maxiter: int = 2) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Full outer-product chi2 grid (reference `grid_chisq`,
    `/root/reference/src/pint/gridutils.py:169`): returns
    ``(chi2[shape G1 x G2 x ...], meshgrids)``."""
    grids = np.meshgrid(*[np.asarray(v) for v in parvalues], indexing="ij")
    flat = {n: g.ravel() for n, g in zip(parnames, grids)}
    chi2 = grid_chisq_flat(fitter, flat, maxiter=maxiter)
    return chi2.reshape(grids[0].shape), grids


def grid_chisq_derived(fitter: Fitter, parnames: Sequence[str],
                       parfuncs: Sequence, gridvalues: Sequence[np.ndarray],
                       maxiter: int = 2):
    """chi2 over a grid of DERIVED quantities (reference
    `grid_chisq_derived`, `/root/reference/src/pint/gridutils.py:395`):
    each model parameter ``parnames[i]`` is set to
    ``parfuncs[i](*gridpoint)`` — e.g. grid over (Mp, Mc) while fitting
    models parameterized by (M2, SINI).  Returns ``(chi2, parvalues)``
    with shapes matching the outer product of ``gridvalues``."""
    grids = np.meshgrid(*[np.asarray(v) for v in gridvalues],
                        indexing="ij")
    flatpts = [g.ravel() for g in grids]
    out = {}
    for name, func in zip(parnames, parfuncs):
        out[name] = np.asarray([func(*vals) for vals in zip(*flatpts)],
                               np.float64)
    chi2 = grid_chisq_flat(fitter, out, maxiter=maxiter)
    parvalues = [out[n].reshape(grids[0].shape) for n in parnames]
    return chi2.reshape(grids[0].shape), parvalues


def tuple_chisq(fitter: Fitter, parnames: Sequence[str], parvalues,
                maxiter: int = 2):
    """chi2 at an arbitrary LIST of parameter tuples (reference
    `tuple_chisq`, `/root/reference/src/pint/gridutils.py:593`, there a
    process pool over points; here the whole list is one vmapped XLA
    program).  ``parvalues``: sequence of tuples, one value per name in
    ``parnames``.  Returns ``(chi2[G], dof)``."""
    vals = np.asarray([[float(v) for v in tup] for tup in parvalues],
                      np.float64)
    flat = {n: vals[:, i] for i, n in enumerate(parnames)}
    chi2 = grid_chisq_flat(fitter, flat, maxiter=maxiter)
    return chi2, fitter.resids.dof
