"""Chi-squared grids over frozen parameters — the vmap showcase.

Reference: `grid_chisq` (`/root/reference/src/pint/gridutils.py:169`), which
deep-copies the whole fitter per grid point and farms points out to a
`ProcessPoolExecutor` (`gridutils.py:36-116,322-331`) — the reference's only
scale-out mechanism, at ~20 s/point on CPU.

Here a grid point is just a different value of some ``p["delta"]`` leaves in
the params pytree, so the WHOLE grid is one `jax.vmap` of the jitted
Gauss-Newton fit over a stacked pytree: one XLA program, all points resident
on the accelerator, no copies, no processes.  Sharding the same stacked
axis over a device mesh is `pint_tpu.parallel`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import telemetry
from pint_tpu.fitter import (Fitter, _default_wls_kernel,
                             build_whitened_assembly, wls_solve)
from pint_tpu.lint.contracts import dispatch_contract
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals

__all__ = ["grid_chisq", "grid_chisq_flat", "grid_chisq_derived",
           "build_grid_fit_fn",
           "stack_grid_pdict", "grid_in_axes"]


def _grid_deltas(model: TimingModel, p: dict,
                 grid_values: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Device-unit delta arrays (G,) that realize the requested par-unit
    grid values for each (frozen) grid parameter."""
    out = {}
    for name, vals in grid_values.items():
        par = model[name]
        # host parameter metadata, never device values: no sync here
        vals = np.asarray(vals, np.float64)    # ddlint: disable=TRACE002
        base = np.asarray(par.device_value,
                          np.float64)          # ddlint: disable=TRACE002
        if par.kind == "mjd":
            out[name] = vals - (base[0] + base[1])  # grid given in MJD
        else:
            out[name] = vals * par.par2dev - base
    return out


def stack_grid_pdict(model: TimingModel, p: dict,
                     grid_values: Dict[str, np.ndarray]) -> dict:
    """A params pytree whose ``delta`` leaves for the grid parameters carry
    a leading grid axis; everything else is shared."""
    deltas = _grid_deltas(model, p, grid_values)
    delta = dict(p["delta"])
    for name, d in deltas.items():
        delta[name] = jnp.asarray(d)
    out = dict(p)
    out["delta"] = delta
    return out


def grid_in_axes(p: dict, grid_names: Sequence[str]) -> dict:
    """The matching `jax.vmap` in_axes pytree: 0 on the grid deltas."""
    names = set(grid_names)
    return {
        "const": {k: None for k in p["const"]},
        "delta": {k: (0 if k in names else None) for k in p["delta"]},
        "mask": {k: None for k in p["mask"]},
    }


def build_grid_fit_fn(model: TimingModel, batch, fit_params: Sequence[str],
                      track_mode: str, maxiter: int = 2,
                      threshold: Optional[float] = None, kernel=None,
                      design_matrix: Optional[str] = None):
    """``fit_one(p, cols=None) -> (chi2, x)``: a full (fixed-iteration)
    WLS fit of one pytree — vmap/shard_map this over stacked grid
    pytrees.  ``kernel`` forces a specific WLS solve kernel (default:
    backend-matched).

    With the split design-matrix path (the default), the linear-block
    columns are computed ONCE per fit point — hoisted out of the
    Gauss-Newton iteration loop in-graph — cutting the per-point JVP
    fan-out from maxiter*P to P_lin + maxiter*P_nl tangents.  Columns
    are deliberately NOT shared across grid points: the sharded path
    (`pint_tpu.parallel`) computes them per point, and the two paths
    must track each other to rounding even on ill-conditioned systems
    where the Gauss-Newton iteration has not fully settled (the bench
    asserts 1e-6 agreement).  ``cols`` lets a caller override the
    columns explicitly; ``fit_one.assemble`` exposes the underlying
    assembly (``.split``/``.lin_cols``)."""
    names = list(fit_params)
    # all-device solve: the grid is one vmapped XLA program; the
    # eigh kernel is right for chi2 maps (see build_wls_step)
    assemble = build_whitened_assembly(model, batch, names, track_mode,
                                       include_offset=True,
                                       design_matrix=design_matrix)
    kern = _default_wls_kernel() if kernel is None else kernel

    def step(x, p, cols):
        if assemble.split:
            c = assemble.lin_cols(x, p) if cols is None else cols
            r, M, sigma, offc = assemble.inline_with_cols(x, p, c)
        else:
            r, M, sigma, offc = assemble.inline(x, p)
        return wls_solve(jnp, r, M, sigma, offc, kern, len(names),
                         threshold)

    def fit_one(p, cols=None):
        if assemble.split and cols is None:
            # per-point hoist: one column computation shared by every
            # iteration of this fit
            cols = assemble.lin_cols(jnp.zeros(len(names)), p)
        x = jnp.zeros(len(names))
        for _ in range(maxiter):
            x = x + step(x, p, cols)["dx"]
        out = step(x, p, cols)
        return out["chi2"], x

    fit_one.assemble = assemble
    return fit_one


def _grid_fit_program(fitter: Fitter, grid_values: Dict[str, np.ndarray],
                      names, maxiter: int, kernel, form: str):
    """Fetch/compile the cached grid fit program on the fitter: a fresh
    jit wrapper per call would retrace the whole grid program every
    time.  ``form="vmap"`` is the one-program whole-grid path;
    ``form="point"`` the unvmapped single-point fit (the eager requeue
    path of checkpointed scans)."""
    model = fitter.model
    r = fitter.resids
    key = (form, tuple(sorted(grid_values)), tuple(names), maxiter,
           kernel, getattr(fitter, "design_matrix", None))
    cache = getattr(fitter, "_grid_fit_cache", None)
    if cache is None:
        cache = fitter._grid_fit_cache = {}
    fit = cache.get(key)
    if fit is None:
        fit_one = build_grid_fit_fn(
            model, r.batch, names, fitter.track_mode, maxiter=maxiter,
            kernel=kernel,
            design_matrix=getattr(fitter, "design_matrix", None))
        if form == "point":
            fit = cache[key] = jax.jit(lambda pp: fit_one(pp))
        else:
            axes = grid_in_axes(r.pdict, list(grid_values))
            # per-point cached columns (computed inside fit_one, hoisted
            # out of its iteration loop) — see build_grid_fit_fn for why
            # they are not shared across points
            fit = cache[key] = jax.jit(
                jax.vmap(lambda pp: fit_one(pp), in_axes=(axes,)))
    return fit


def _slice_stacked(stacked: dict, grid_names: Sequence[str], lo: int,
                   hi: int, width: Optional[int]) -> dict:
    """The [lo:hi) slice of a stacked grid pytree, padded to ``width``
    points by repeating the last row (pad results are computed and
    discarded, so every chunk dispatch reuses ONE compiled shape).
    ``width=None`` with ``hi == lo + 1`` yields scalar grid leaves —
    the unvmapped point form."""
    gset = set(grid_names)
    delta = {}
    for k, v in stacked["delta"].items():
        if k not in gset:
            delta[k] = v
            continue
        arr = jnp.asarray(v)
        if width is None:
            delta[k] = arr[lo]
            continue
        sl = arr[lo:hi]
        if hi - lo < width:
            sl = jnp.concatenate(
                [sl, jnp.repeat(sl[-1:], width - (hi - lo), axis=0)])
        delta[k] = sl
    return {"const": stacked["const"], "delta": delta,
            "mask": stacked["mask"]}


def _eager_grid_chisq(fitter: Fitter, grid_values: Dict[str, np.ndarray],
                      maxiter: int = 2, kernel=None) -> np.ndarray:
    """The requeue path of checkpointed scans: chi2 of each grid point
    from the EAGER single-device fit — one unvmapped jitted fit per
    point, no vmap, no sharding — slower but independent of whatever
    poisoned the batched dispatch."""
    names = [n for n in fitter.fit_params if n not in grid_values]
    pfit = _grid_fit_program(fitter, grid_values, names, maxiter, kernel,
                             "point")
    stacked = stack_grid_pdict(fitter.model, fitter.resids.pdict,
                               grid_values)
    gnames = list(grid_values)
    g = len(np.asarray(next(iter(grid_values.values()))))
    out = np.empty(g, np.float64)
    for i in range(g):
        chi2, _ = pfit(_slice_stacked(stacked, gnames, i, i + 1, None))
        # per-point fetch is the REQUEUE path's design: one eager
        # single-device fit per poisoned point, isolation over speed
        out[i] = float(chi2)                   # ddlint: disable=TRACE002
    return out


@dispatch_contract("grid_chunk", max_compiles=40, max_dispatches=6,
                   max_transfers=3)
def grid_chisq_flat(fitter: Fitter, grid_values: Dict[str, np.ndarray],
                    maxiter: int = 2, kernel=None, *,
                    chunk_size: Optional[int] = None,
                    checkpoint: Optional[str] = None,
                    resume: bool = False, max_retries: int = 2,
                    checkpoint_every: int = 1,
                    return_summary: bool = False) -> np.ndarray:
    """chi2 at each of G grid points (all grid arrays shape (G,)); the
    non-grid free parameters are re-fit at every point.  ``kernel``
    forces a specific WLS solve kernel (default: backend-matched).

    Preemption tolerance (ISSUE 4): with ``chunk_size``/``checkpoint``
    set, the grid executes in chunks through
    :func:`pint_tpu.runtime.run_checkpointed_scan` — CRC32-verified
    atomic shard checkpoints after every ``checkpoint_every`` chunks, a
    SIGTERM/SIGINT mid-scan flushes a final checkpoint and raises
    ``ScanInterrupted``, and ``resume=True`` skips completed chunks
    bit-identically.  A chunk that raises or returns non-finite chi2 is
    retried ``max_retries`` times, then requeued onto the eager
    single-device path.  ``return_summary=True`` returns
    ``(chi2, ScanSummary)``."""
    model = fitter.model
    r = fitter.resids
    names = [n for n in fitter.fit_params if n not in grid_values]
    for n in grid_values:
        if not model[n].frozen:
            raise ValueError(f"grid parameter {n} must be frozen")
    vfit = _grid_fit_program(fitter, grid_values, names, maxiter, kernel,
                             "vmap")
    stacked = stack_grid_pdict(model, r.pdict, grid_values)
    if chunk_size is None and checkpoint is None and not return_summary:
        # the historical one-program whole-grid fast path (the chunked
        # path below gets its spans from runtime.run_checkpointed_scan)
        with telemetry.span("grid.chisq_flat"):
            chi2, _ = vfit(stacked)
        return _check_grid_chi2(np.asarray(chi2))

    from pint_tpu import runtime

    sizes = {n: len(np.asarray(v)) for n, v in grid_values.items()}
    if len(set(sizes.values())) != 1:
        raise ValueError(f"grid arrays differ in length: {sizes}")
    g = next(iter(sizes.values()))
    cs = int(chunk_size) if chunk_size else g
    gnames = list(grid_values)

    def run_chunk(ci, lo, hi):
        chi2, _ = vfit(_slice_stacked(stacked, gnames, lo, hi, cs))
        return np.asarray(chi2)[: hi - lo]

    def fallback(ci, lo, hi):
        return _eager_grid_chisq(
            fitter, {k: np.asarray(v)[lo:hi]
                     for k, v in grid_values.items()},
            maxiter=maxiter, kernel=kernel)

    sig = runtime.scan_signature("grid", grid_values, names, maxiter, cs)
    chi2, summary = runtime.run_checkpointed_scan(
        g, run_chunk, chunk_size=cs, fallback=fallback,
        checkpoint=checkpoint, resume=resume, max_retries=max_retries,
        checkpoint_every=checkpoint_every, signature=sig)
    chi2 = _check_grid_chi2(chi2)
    return (chi2, summary) if return_summary else chi2


def _check_grid_chi2(chi2: np.ndarray) -> np.ndarray:
    """Non-finite guard for vmapped/sharded grid fits: inside the one
    compiled program a poisoned grid point is invisible, so the host
    boundary is where a NaN chi2 must be called out (the values are
    still returned — a partial grid is useful — but never silently)."""
    bad = int(np.sum(~np.isfinite(chi2)))
    if bad:
        import warnings

        from pint_tpu import profiling
        from pint_tpu.exceptions import PintTpuWarning

        profiling.count("guard.grid_nonfinite", bad)
        warnings.warn(
            f"{bad}/{chi2.size} grid points returned non-finite chi2 "
            "(degenerate or diverging fits at those parameter values)",
            PintTpuWarning)
    return chi2


def grid_chisq(fitter: Fitter, parnames: Sequence[str],
               parvalues: Sequence[np.ndarray],
               maxiter: int = 2) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Full outer-product chi2 grid (reference `grid_chisq`,
    `/root/reference/src/pint/gridutils.py:169`): returns
    ``(chi2[shape G1 x G2 x ...], meshgrids)``."""
    grids = np.meshgrid(*[np.asarray(v) for v in parvalues], indexing="ij")
    flat = {n: g.ravel() for n, g in zip(parnames, grids)}
    chi2 = grid_chisq_flat(fitter, flat, maxiter=maxiter)
    return chi2.reshape(grids[0].shape), grids


def grid_chisq_derived(fitter: Fitter, parnames: Sequence[str],
                       parfuncs: Sequence, gridvalues: Sequence[np.ndarray],
                       maxiter: int = 2):
    """chi2 over a grid of DERIVED quantities (reference
    `grid_chisq_derived`, `/root/reference/src/pint/gridutils.py:395`):
    each model parameter ``parnames[i]`` is set to
    ``parfuncs[i](*gridpoint)`` — e.g. grid over (Mp, Mc) while fitting
    models parameterized by (M2, SINI).  Returns ``(chi2, parvalues)``
    with shapes matching the outer product of ``gridvalues``."""
    grids = np.meshgrid(*[np.asarray(v) for v in gridvalues],
                        indexing="ij")
    flatpts = [g.ravel() for g in grids]
    out = {}
    for name, func in zip(parnames, parfuncs):
        out[name] = np.asarray([func(*vals) for vals in zip(*flatpts)],
                               np.float64)
    chi2 = grid_chisq_flat(fitter, out, maxiter=maxiter)
    parvalues = [out[n].reshape(grids[0].shape) for n in parnames]
    return chi2.reshape(grids[0].shape), parvalues


def tuple_chisq(fitter: Fitter, parnames: Sequence[str], parvalues,
                maxiter: int = 2):
    """chi2 at an arbitrary LIST of parameter tuples (reference
    `tuple_chisq`, `/root/reference/src/pint/gridutils.py:593`, there a
    process pool over points; here the whole list is one vmapped XLA
    program).  ``parvalues``: sequence of tuples, one value per name in
    ``parnames``.  Returns ``(chi2[G], dof)``."""
    vals = np.asarray([[float(v) for v in tup] for tup in parvalues],
                      np.float64)
    flat = {n: vals[:, i] for i, n in enumerate(parnames)}
    chi2 = grid_chisq_flat(fitter, flat, maxiter=maxiter)
    return chi2, fitter.resids.dof
