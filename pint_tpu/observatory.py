"""Observatory registry: ground sites, special locations, satellite hooks.

Equivalent of the reference's `src/pint/observatory/` package
(`__init__.py:135` Observatory/get_observatory, `topo_obs.py:65` TopoObs,
`special_locations.py:71,117` barycenter/geocenter).  Site facts (ITRF
coordinates, codes, aliases) live in `pint_tpu/data/observatories_data.py`.

An Observatory provides:

* ``clock_corrections(mjd_utc)`` — site clock chain -> UTC [s]
* ``posvel_gcrs(tt_mjd, ut1_mjd)`` — geocentric ICRS position/velocity
* identity (name, aliases, tempo/itoa codes)

Time-scale work (UTC->TT->TDB) and SSB barycentering live in the TOA loader
(`pint_tpu.toa`) so they can be vectorized over the whole TOA table at once.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from pint_tpu import clock as clockmod
from pint_tpu.earth import EOPProvider, itrf_to_gcrs_posvel, null_eop
from pint_tpu.exceptions import ObservatoryError
from pint_tpu.utils import PosVel


class Observatory:
    """Base observatory; subclasses define location/clock behavior."""

    def __init__(self, name: str, aliases: Optional[List[str]] = None, fullname: str = ""):
        self.name = name.lower()
        self.aliases = [a.lower() for a in (aliases or [])]
        self.fullname = fullname or name

    # identity ------------------------------------------------------------
    @property
    def tempo_code(self) -> str:
        return ""

    @property
    def itoa_code(self) -> str:
        return ""

    # physics -------------------------------------------------------------
    def clock_corrections(self, mjd_utc, include_gps=True, limits="warn"):
        """Clock corrections [s] to add to the site TOA to reach UTC."""
        return np.zeros_like(np.asarray(mjd_utc, np.float64))

    def posvel_gcrs(self, tt_mjd, ut1_mjd=None, eop: EOPProvider = null_eop) -> PosVel:
        """Geocentric ICRS (GCRS) position [m] / velocity [m/s]."""
        raise NotImplementedError

    @property
    def is_barycenter(self) -> bool:
        return False

    @property
    def is_geocenter(self) -> bool:
        return False


class TopoObs(Observatory):
    """A ground-based observatory at fixed ITRF coordinates.

    cf. reference `src/pint/observatory/topo_obs.py:65`.
    """

    def __init__(self, name, itrf_xyz, tempo_code="", itoa_code="", aliases=None,
                 clock_file="", apply_gps2utc=True, bogus_last_correction=False,
                 fullname=""):
        super().__init__(name, aliases, fullname)
        self.itrf_xyz = np.asarray(itrf_xyz, np.float64)
        self._tempo_code = tempo_code
        self._itoa_code = itoa_code
        self.clock_file = clock_file
        self.apply_gps2utc = apply_gps2utc
        self.bogus_last_correction = bogus_last_correction

    @property
    def tempo_code(self):
        return self._tempo_code

    @property
    def itoa_code(self):
        return self._itoa_code

    def clock_corrections(self, mjd_utc, include_gps=True, limits="warn"):
        mjd_utc = np.asarray(mjd_utc, np.float64)
        corr = np.zeros_like(mjd_utc)
        # some sites (jbroach, jbdfb, ncyobs) chain several clock files
        files = self.clock_file if isinstance(self.clock_file, (list, tuple)) else (
            [self.clock_file] if self.clock_file else []
        )
        for entry in files:
            # chain entries may be {'name': ..., 'valid_beyond_ends': True}
            fname = entry["name"] if isinstance(entry, dict) else entry
            fmt = "tempo2" if fname.endswith(".clk") else "tempo"
            cf = clockmod.find_clock_file(
                fname,
                fmt=fmt,
                obscode=self._tempo_code or None,
                limits=limits,
                bogus_last_correction=self.bogus_last_correction,
            )
            if cf is not None:
                corr = corr + cf.evaluate(mjd_utc, limits=limits)
        if include_gps and self.apply_gps2utc:
            corr = corr + clockmod.gps_to_utc_correction(mjd_utc, limits=limits)
        return corr

    def posvel_gcrs(self, tt_mjd, ut1_mjd=None, eop: EOPProvider = null_eop) -> PosVel:
        from pint_tpu.mjd import tai_minus_utc

        tt_mjd = np.asarray(tt_mjd, np.float64)
        if ut1_mjd is None:
            e = eop(tt_mjd)
            # tai_minus_utc wants a UTC day; shift TT by the ~64-69 s offset
            # first so epochs just before a leap-second boundary resolve to
            # the correct table row
            utc_guess = tt_mjd - (32.184 + 37.0) / 86400.0
            ut1_mjd = tt_mjd - (32.184 + tai_minus_utc(utc_guess) - e.ut1_minus_utc) / 86400.0
            return itrf_to_gcrs_posvel(self.itrf_xyz, tt_mjd, ut1_mjd, e.xp, e.yp)
        return itrf_to_gcrs_posvel(self.itrf_xyz, tt_mjd, ut1_mjd)


class BarycenterObs(Observatory):
    """TOAs already referred to the solar-system barycenter ('@'/'bat').

    cf. reference `special_locations.py:71`.  No clock corrections, no
    geometry; TDB times are taken as given.
    """

    @property
    def is_barycenter(self):
        return True

    @property
    def tempo_code(self):
        return "@"

    def posvel_gcrs(self, tt_mjd, ut1_mjd=None, eop=null_eop):
        z = np.zeros(np.shape(np.asarray(tt_mjd)) + (3,))
        return PosVel(z, z.copy())


class GeocenterObs(Observatory):
    """TOAs referred to the geocenter (cf. `special_locations.py:117`)."""

    @property
    def is_geocenter(self):
        return True

    @property
    def tempo_code(self):
        return "0"

    @property
    def itoa_code(self):
        return "GC"

    def posvel_gcrs(self, tt_mjd, ut1_mjd=None, eop=null_eop):
        z = np.zeros(np.shape(np.asarray(tt_mjd)) + (3,))
        return PosVel(z, z.copy())


class T2SpacecraftObs(Observatory):
    """Spacecraft whose GCRS position rides in per-TOA tim flags
    (tempo2 convention: ``-telx -tely -telz`` [km], optionally
    ``-vx -vy -vz`` [km/s]); reference `T2SpacecraftObs`,
    `/root/reference/src/pint/observatory/special_locations.py:161`."""

    #: compute_posvels must source the geometry from the TOA flags
    needs_flag_positions = True

    def posvel_gcrs_from_flags(self, flags_list) -> PosVel:
        try:
            pos = np.array([[float(f["telx"]), float(f["tely"]),
                             float(f["telz"])] for f in flags_list]) * 1e3
        except KeyError as e:
            raise ObservatoryError(
                "spacecraft TOAs need -telx/-tely/-telz flags (GCRS "
                f"position in km); missing {e}")
        have_v = [all(k in f for k in ("vx", "vy", "vz"))
                  for f in flags_list]
        some_v = ["vx" in f or "vy" in f or "vz" in f for f in flags_list]
        if all(have_v):
            vel = np.array([[float(f["vx"]), float(f["vy"]),
                             float(f["vz"])] for f in flags_list]) * 1e3
        elif any(some_v):
            raise ObservatoryError(
                "spacecraft TOA velocity flags are incomplete: supply all "
                "of -vx/-vy/-vz on every TOA, or none at all")
        else:
            import warnings as _w

            _w.warn("spacecraft TOAs have no -vx/-vy/-vz flags; GCRS "
                    "velocities set to zero (Doppler terms omitted)")
            vel = np.zeros_like(pos)
        return PosVel(pos, vel)

    def posvel_gcrs(self, tt_mjd, ut1_mjd=None, eop=null_eop):
        raise ObservatoryError(
            "spacecraft positions come from TOA flags; use "
            "posvel_gcrs_from_flags")


class SatelliteObs(Observatory):
    """An orbiting observatory whose GCRS posvel comes from an orbit table.

    The reference builds these from FT2/FPorbit files
    (`satellite_obs.py:283`); here the table is injected (see
    `pint_tpu.event_toas` for the FT2/FPorbit loaders).
    """

    def __init__(self, name, mjd_tt, pos_gcrs_m, vel_gcrs_ms, aliases=None):
        super().__init__(name, aliases)
        self.mjd_tt = np.asarray(mjd_tt, np.float64)
        self.pos = np.asarray(pos_gcrs_m, np.float64)
        self.vel = np.asarray(vel_gcrs_ms, np.float64)

    def posvel_gcrs(self, tt_mjd, ut1_mjd=None, eop=null_eop):
        t = np.asarray(tt_mjd, np.float64)
        # np.interp clamps silently; an event outside the orbit table
        # would get the frozen endpoint position (km-scale error, ms of
        # barycentering) — refuse instead (the reference errors too).
        # Slack: ~2 table sample intervals (clamp error within slack
        # stays at the interpolation-error scale), not a fixed minute.
        step = np.median(np.diff(self.mjd_tt)) if len(self.mjd_tt) > 1 \
            else 1.0 / 86400.0
        slack = 2.0 * float(step)
        if t.size and (t.min() < self.mjd_tt[0] - slack
                       or t.max() > self.mjd_tt[-1] + slack):
            raise ValueError(
                f"TOAs (MJD {t.min():.3f}-{t.max():.3f}) fall outside "
                f"the orbit table of observatory {self.name!r} "
                f"(MJD {self.mjd_tt[0]:.3f}-{self.mjd_tt[-1]:.3f})")
        pos = np.stack([np.interp(t, self.mjd_tt, self.pos[:, i]) for i in range(3)], -1)
        vel = np.stack([np.interp(t, self.mjd_tt, self.vel[:, i]) for i in range(3)], -1)
        return PosVel(pos, vel)


# --- registry -----------------------------------------------------------------

_registry: Dict[str, Observatory] = {}
_alias_map: Dict[str, str] = {}


def register(obs: Observatory, overwrite=False):
    # a user registration into a fresh process must not pre-empt the
    # built-in site table (_load_defaults only fills an EMPTY registry,
    # so registering first would silently hide every default site)
    if not _loading:
        _load_defaults()
    if obs.name in _registry and not overwrite:
        raise ObservatoryError(f"observatory {obs.name!r} already registered")
    _registry[obs.name] = obs
    for a in obs.aliases:
        _alias_map[a] = obs.name
    if obs.tempo_code:
        _alias_map[obs.tempo_code.lower()] = obs.name
    if obs.itoa_code:
        _alias_map[obs.itoa_code.lower()] = obs.name


_loading = False


def _load_defaults():
    global _loading
    if _registry or _loading:
        return
    _loading = True
    from pint_tpu.data.observatories_data import SITES

    for (name, xyz, tcode, icode, aliases, clock_file, gps, bogus) in SITES:
        register(
            TopoObs(name, xyz, tempo_code=tcode, itoa_code=icode,
                    aliases=list(aliases), clock_file=clock_file,
                    apply_gps2utc=gps, bogus_last_correction=bogus)
        )
    register(BarycenterObs("barycenter", aliases=["bat", "ssb", "bary", "@"]))
    register(GeocenterObs("geocenter", aliases=["coe", "geo"]))
    register(T2SpacecraftObs("stl_geo", aliases=["spacecraft"]))
    _loading = False


def get_observatory(name: str) -> Observatory:
    """Look up by name, alias, tempo code, or ITOA code (case-insensitive).

    cf. reference `get_observatory` (`observatory/__init__.py:519`).
    """
    _load_defaults()
    key = str(name).lower().strip()
    if key in _registry:
        return _registry[key]
    if key in _alias_map:
        return _registry[_alias_map[key]]
    raise ObservatoryError(f"unknown observatory {name!r}")


def list_observatories() -> List[str]:
    _load_defaults()
    return sorted(_registry)
