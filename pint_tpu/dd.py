"""Double-word arithmetic as backend-generic error-free transforms.

This module is part of the precision foundation of pint_tpu.  The reference
package leans on ``np.longdouble`` (x87 80-bit) everywhere absolute pulse
phase is computed (reference `src/pint/pulsar_mjd.py:529-637` implements the
same error-free transforms for its two-float day/fraction arithmetic, and
`src/pint/phase.py:7` splits phase into integer+fraction for the same reason).
XLA/TPU has no float128, so extended precision is built from unevaluated
multi-word float sums using the classic error-free transforms (Dekker 1971;
Knuth TAOCP v2; Hida, Li & Bailey's QD algorithms).

Hardware reality (measured, see ``tests/test_dd.py``):

* **float32 is correctly-rounded IEEE on TPU** (subnormals flush to zero) —
  error-free transforms hold exactly.
* **float64 on TPU is software-emulated and NOT correctly rounded** (~48-bit
  double-f32 emulation), so DD-over-f64 must not be used in on-device
  precision-critical paths.  It *is* valid on CPU (host precompute, tests),
  where f64 is true IEEE.

Consequently this module is deliberately backend- and dtype-generic: the
algorithms use only ``+ - *`` plus a dtype-aware Dekker split constant, so
they run unchanged on numpy float64 arrays (host, ~106-bit DD), jax float64
on the CPU backend, and jax float32 on TPU (~48-bit DD; quadruple-word f32 in
:mod:`pint_tpu.qs` provides the ~90-bit path used for absolute phase
on device).

Everything is branch-free and shape-polymorphic: a ``DD`` is a NamedTuple of
two equal-shaped arrays, so it is automatically a JAX pytree and flows
through ``jit``/``vmap``/``grad``/``scan`` untouched.

Verified against mpmath in ``tests/test_dd.py`` (hypothesis fuzzing),
mirroring the reference's precision tests (`tests/test_precision.py`).
"""

from __future__ import annotations

from typing import NamedTuple, Union

import numpy as np

Arrayish = Union[float, np.ndarray]

# Dekker splitting constants: 2^ceil(p/2) + 1 for p-bit significands.
_SPLIT_F64 = 134217729.0  # 2^27 + 1
_SPLIT_F32 = 4097.0  # 2^12 + 1

#: Precision-flow kernel registry (read by pint_tpu/lint/precflow.py).
#: PAIR_KERNELS: public functions in THIS module whose emitted
#: equations are pair-preserving transfer functions — their f32 word
#: arithmetic is error-free (or error-captured) by construction, so a
#: compensated value passing through them stays compensated.
#: COLLAPSE_KERNELS: functions whose result genuinely discards the
#: compensation words; a collapse to a narrow dtype of a value tainted
#: by phase-critical inputs is exactly what rule PREC002 reports when
#: it happens at the sanctioned-module boundary.  A new public kernel
#: MUST be added to one of the two sets: the auditor treats unknown
#: public dd/qs functions as collapses (conservative-by-default).
PAIR_KERNELS = frozenset({
    "two_sum", "quick_two_sum", "split", "two_prod", "from_float",
    "from_two", "normalize", "add", "add_f", "sub", "mul", "mul_f",
    "prod_ff", "sum_ff", "div", "neg", "sq", "scale_pow2",
    "round_nearest", "floor", "horner", "horner_plain", "where",
    "weighted_mean", "mean", "from_string", "self_check",
})
COLLAPSE_KERNELS = frozenset({"to_float", "astype_float"})


def _split_const(a):
    dt = getattr(a, "dtype", None)
    if dt is not None and dt == np.float32:
        # dtype-matched to the f32 input word — not a demotion
        return np.float32(_SPLIT_F32)  # ddlint: disable=PREC001
    # np.float64, not a bare Python float: a weak-typed scalar would let
    # JAX demote the split to the other operand's (possibly narrower)
    # dtype instead of anchoring it at f64
    return np.float64(_SPLIT_F64)


_guard_p = None


def _make_guard_primitive():
    """The EFT guard as a first-class primitive with its own autodiff and
    batching rules.  ``jax.lax.optimization_barrier`` alone is the right
    LOWERING, but on the pinned jax (0.4.x) its primitive has neither a
    JVP nor a batching rule — so every ``jacfwd`` of the phase pipeline
    (the entire design-matrix path) died with ``NotImplementedError``.
    The guard is semantically the identity, so the rules are trivial:
    tangents/cotangents pass through a guard of their own (the tangent
    EFT chains are built from the same cancellation-sensitive arithmetic
    and need the same simplifier protection), and batching maps
    elementwise.  Only the lowering inserts the real barrier."""
    import jax
    from jax.interpreters import ad, batching, mlir

    try:
        from jax.extend import core as jcore
    except ImportError:  # older layouts
        from jax import core as jcore

    p = jcore.Primitive("pint_tpu_eft_guard")
    p.multiple_results = True
    p.def_impl(lambda *ws: list(ws))
    p.def_abstract_eval(lambda *avals: list(avals))

    def jvp(primals, tangents):
        out = p.bind(*primals)
        nz = [(i, t) for i, t in enumerate(tangents)
              if type(t) is not ad.Zero]
        if nz:
            guarded = iter(p.bind(*[t for _, t in nz]))
            tangents = [t if type(t) is ad.Zero else next(guarded)
                        for t in tangents]
        else:
            tangents = list(tangents)
        return out, tangents

    ad.primitive_jvps[p] = jvp
    # linear (identity): cotangents pass straight through
    ad.primitive_transposes[p] = lambda cts, *_: list(cts)
    batching.primitive_batchers[p] = \
        lambda args, dims, **kw: (p.bind(*args), list(dims))
    mlir.register_lowering(p, mlir.lower_fun(
        lambda *ws: jax.lax.optimization_barrier(tuple(ws)),
        multiple_results=True))
    return p


def _guard(*words):
    """Pin EFT result words against value-changing compiler rewrites.

    XLA's HLO simplification pipeline rewrites floating-point graphs under
    the assumption that 1-ulp rounding differences don't matter (e.g. it
    sinks broadcasts through elementwise chains and re-derives scalar
    clones).  Error-free transforms are exactly the code for which that
    assumption is false: a 1-ulp change in the primary word without the
    matching compensation word corrupts the low-order words entirely —
    observed as ~1e-7-relative phase errors on the CPU backend (jit vs
    eager).  An ``optimization_barrier`` on every EFT output pair makes the
    transform opaque to the simplifier while remaining transparent to
    autodiff and batching (via the guard primitive above).  Host numpy
    paths need no guard.
    """
    if isinstance(words[0], np.ndarray) or np.isscalar(words[0]):
        return words if len(words) > 1 else words[0]
    global _guard_p
    if _guard_p is None:
        _guard_p = _make_guard_primitive()
    out = _guard_p.bind(*words)
    return out if len(words) > 1 else out[0]


def two_sum(a, b):
    """Error-free sum: returns (s, e) with s = fl(a+b) and a+b = s+e exactly."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return _guard(s, e)


def quick_two_sum(a, b):
    """Error-free sum assuming |a| >= |b|: (s, e) with a+b = s+e exactly."""
    s = a + b
    e = b - (s - a)
    return _guard(s, e)


def split(a):
    """Dekker split into high/low half-width parts (exact)."""
    t = _split_const(a) * a
    hi = t - (t - a)
    lo = a - hi
    return _guard(hi, lo)


def two_prod(a, b):
    """Error-free product: (p, e) with p = fl(a*b) and a*b = p+e exactly."""
    p = a * b
    ahi, alo = split(a)
    bhi, blo = split(b)
    e = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo
    return _guard(p, e)


class DD(NamedTuple):
    """A double-word number: value = hi + lo, |lo| <= ulp(hi)/2.

    NamedTuple => automatically a JAX pytree; broadcastable like its leaves.
    """

    hi: Arrayish
    lo: Arrayish

    def __add__(self, other):
        return add(self, _coerce(other, self))

    def __radd__(self, other):
        return add(_coerce(other, self), self)

    def __sub__(self, other):
        return sub(self, _coerce(other, self))

    def __rsub__(self, other):
        return sub(_coerce(other, self), self)

    def __mul__(self, other):
        return mul(self, _coerce(other, self))

    def __rmul__(self, other):
        return mul(_coerce(other, self), self)

    def __truediv__(self, other):
        return div(self, _coerce(other, self))

    def __neg__(self):
        return DD(-self.hi, -self.lo)

    @property
    def shape(self):
        return np.shape(self.hi)

    def astype_float(self):
        return self.hi + self.lo


def _coerce(x, like: DD) -> DD:
    if isinstance(x, DD):
        return x
    z = like.hi * 0
    return DD(z + x, z)


def from_float(x) -> DD:
    """Promote a float array/scalar to DD exactly (lo = 0)."""
    return DD(x, x * 0)


def from_two(hi, lo) -> DD:
    """Build a normalized DD from an unnormalized two-float sum hi+lo."""
    s, e = two_sum(hi, lo)
    return DD(s, e)


def from_string(s: str):
    """Host-side: parse a decimal string to an exact (hi, lo) float64 pair."""
    from decimal import Decimal, getcontext

    getcontext().prec = 50
    d = Decimal(s)
    hi = float(d)
    lo = float(d - Decimal(hi))
    return DD(np.float64(hi), np.float64(lo))


def to_float(x: DD):
    return x.hi + x.lo


def normalize(x: DD) -> DD:
    s, e = quick_two_sum(x.hi, x.lo)
    return DD(s, e)


def add(x: DD, y: DD) -> DD:
    """DD + DD (QD 'ieee_add' accurate variant)."""
    s1, s2 = two_sum(x.hi, y.hi)
    t1, t2 = two_sum(x.lo, y.lo)
    s2 = s2 + t1
    s1, s2 = quick_two_sum(s1, s2)
    s2 = s2 + t2
    s1, s2 = quick_two_sum(s1, s2)
    return DD(s1, s2)


def add_f(x: DD, f) -> DD:
    """DD + float."""
    s1, s2 = two_sum(x.hi, f)
    s2 = s2 + x.lo
    s1, s2 = quick_two_sum(s1, s2)
    return DD(s1, s2)


def sub(x: DD, y: DD) -> DD:
    return add(x, DD(-y.hi, -y.lo))


def mul(x: DD, y: DD) -> DD:
    """DD * DD."""
    p1, p2 = two_prod(x.hi, y.hi)
    p2 = p2 + (x.hi * y.lo + x.lo * y.hi)
    p1, p2 = quick_two_sum(p1, p2)
    return DD(p1, p2)


def mul_f(x: DD, f) -> DD:
    """DD * float."""
    p1, p2 = two_prod(x.hi, f)
    p2 = p2 + x.lo * f
    p1, p2 = quick_two_sum(p1, p2)
    return DD(p1, p2)


def prod_ff(a, b) -> DD:
    """float * float -> exact DD."""
    p, e = two_prod(a, b)
    return DD(p, e)


def sum_ff(a, b) -> DD:
    """float + float -> exact DD."""
    s, e = two_sum(a, b)
    return DD(s, e)


def div(x: DD, y: DD) -> DD:
    """DD / DD via Newton-corrected long division (QD algorithm)."""
    q1 = x.hi / y.hi
    r = add(x, -mul_f(y, q1))
    q2 = r.hi / y.hi
    r = add(r, -mul_f(y, q2))
    q3 = r.hi / y.hi
    q1_, q2_ = quick_two_sum(q1, q2)
    return add_f(DD(q1_, q2_), q3)


def neg(x: DD) -> DD:
    return DD(-x.hi, -x.lo)


def sq(x: DD) -> DD:
    return mul(x, x)


def scale_pow2(x: DD, k) -> DD:
    """Exact multiply by a power of two."""
    return DD(x.hi * k, x.lo * k)


def _xp(x):
    """numpy-or-jax dispatch for the few non-arithmetic ops (round/floor).

    Same rule as :func:`pint_tpu.utils.get_xp` (kept inline: utils imports
    would be circular for this foundation module).
    """
    if isinstance(x, np.ndarray) or np.isscalar(x):
        return np
    import jax.numpy as jnp

    return jnp


def round_nearest(x: DD):
    """Round-to-nearest-integer of a DD; returns (n: exact-int float, frac: DD).

    n is the nearest integer to hi+lo and frac = x - n (|frac| <= 0.5).
    This is the pulse-number split: the reference keeps (int, frac) Phase
    pairs for exactly this reason (`src/pint/phase.py:7`).
    """
    xp = _xp(x.hi)
    n = xp.round(x.hi)
    r = add_f(x, -n)
    adj = xp.round(r.hi + r.lo)
    n = n + adj
    r = add_f(r, -adj)
    return n, r


def floor(x: DD):
    """Floor of a DD; returns (n: exact-int float, frac: DD in [0,1))."""
    xp = _xp(x.hi)
    n = xp.floor(x.hi)
    r = add_f(x, -n)
    adj = xp.floor(r.hi + r.lo)
    n = n + adj
    r = add_f(r, -adj)
    return n, r


def horner(dt: DD, coeffs) -> DD:
    """Evaluate sum_k coeffs[k] * dt^k / k!  in DD (Taylor-Horner).

    Equivalent of the reference's `taylor_horner` (`src/pint/utils.py:415`),
    which it evaluates in longdouble.  ``coeffs`` is a sequence of scalars /
    arrays (float or DD), lowest order first, WITHOUT factorial division —
    i.e. this computes c0 + c1 dt + c2 dt^2/2! + ...
    """
    n = len(coeffs)
    if n == 0:
        return from_float(dt.hi * 0)
    fact = 1.0
    facts = []
    for k in range(n):
        facts.append(fact)
        fact *= k + 1
    acc = _as_dd(coeffs[-1], dt)
    if facts[n - 1] != 1.0:
        acc = mul_f(acc, 1.0 / facts[n - 1])
    for k in range(n - 2, -1, -1):
        ck = _as_dd(coeffs[k], dt)
        if facts[k] != 1.0:
            ck = mul_f(ck, 1.0 / facts[k])
        acc = add(mul(acc, dt), ck)
    return acc


def horner_plain(dt: DD, coeffs) -> DD:
    """Plain Horner: c0 + c1 dt + c2 dt^2 + ... in DD."""
    n = len(coeffs)
    if n == 0:
        return from_float(dt.hi * 0)
    acc = _as_dd(coeffs[-1], dt)
    for k in range(n - 2, -1, -1):
        acc = add(mul(acc, dt), _as_dd(coeffs[k], dt))
    return acc


def _as_dd(x, like: DD) -> DD:
    return x if isinstance(x, DD) else _coerce(x, like)


def where(cond, x: DD, y: DD) -> DD:
    xp = _xp(x.hi)
    return DD(xp.where(cond, x.hi, y.hi), xp.where(cond, x.lo, y.lo))


def weighted_mean(x: DD, w) -> DD:
    """Weighted mean of a DD vector, as a DD (compensated reduction).

    The hi and lo words are reduced separately and renormalized into a
    pair: the mean's error is bounded by the f32 summation error of
    each word stream (~N*eps relative), far below the pair's own
    resolution at residual scales.  Lives here — not at the call site —
    so the word arithmetic stays inside the sanctioned kernel modules
    (the precision-flow auditor treats dd.py/qs.py reductions as
    pair-preserving; see pint_tpu/lint/precflow.py)."""
    xp = _xp(x.hi)
    sw = xp.sum(w)
    return from_two(xp.sum(x.hi * w) / sw, xp.sum(x.lo * w) / sw)


def mean(x: DD) -> DD:
    """Unweighted mean of a DD vector, as a DD (compensated reduction)."""
    xp = _xp(x.hi)
    n = x.hi * 0 + 1
    return from_two(xp.sum(x.hi) / xp.sum(n), xp.sum(x.lo) / xp.sum(n))


def self_check() -> bool:
    """Verify error-free transforms hold on host numpy (true IEEE f64)."""
    a = np.float64(999999999999999.0)
    b = np.float64(-878345505234691.4)
    s, e = two_sum(a, b)
    ok = float(s) + float(e) == float(a) + float(b) and float(s) == float(a + b)
    p, ep = two_prod(np.float64(1.0 + 2.0**-30), np.float64(1.0 + 2.0**-31))
    ok &= ep != 0.0 or p == (1.0 + 2.0**-30) * (1.0 + 2.0**-31)
    return bool(ok)
