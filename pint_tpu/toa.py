"""Host-side TOA loading: ``.tim`` parsing, clock corrections, TDB, posvels.

The pipeline mirrors the reference's `get_TOAs`
(`/root/reference/src/pint/toa.py:110`):

    parse .tim  →  apply clock corrections  →  compute TDBs  →  compute posvels

but the product is a :class:`pint_tpu.toabatch.TOABatch` — dense f64 arrays
for the jitted compute core — instead of an astropy Table.  Everything in this
module is deliberately plain numpy on the host: it is one-time O(N) load work
(the reference spends ~16 s of pure-python per 10k TOAs here; see
`/root/reference/profiling/README.txt:40-50`), vectorized here over TOAs.

Supported ``.tim`` formats: Tempo2, Princeton, Parkes (reference
`_toa_format`, `/root/reference/src/pint/toa.py:442`). Inline commands:
FORMAT, MODE, INFO, TIME, EFAC, EQUAD, EMIN/EMAX, FMIN/FMAX, SKIP/NOSKIP,
END, PHASE, JUMP, INCLUDE, TRACK (reference `/root/reference/src/pint/toa.py:69,760-860`).
"""

from __future__ import annotations

import os
import re
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from pint_tpu import c as C_LIGHT
from pint_tpu import mjd as mjdmod
from pint_tpu import tdbseries
from pint_tpu.exceptions import TimFileError
from pint_tpu.mjd import MJD
from pint_tpu.observatory import get_observatory
from pint_tpu.toabatch import TOABatch, make_batch
from pint_tpu.utils import PosVel

__all__ = ["TOA", "TOAs", "get_TOAs", "read_tim", "write_tim", "merge_TOAs",
           "get_TOAs_array"]

_COMMANDS = (
    "DITHER", "EFAC", "EMAX", "EMAP", "EMIN", "EQUAD", "FMAX", "FMIN",
    "INCLUDE", "INFO", "JUMP", "MODE", "NOSKIP", "PHA1", "PHA2", "PHASE",
    "SEARCH", "SIGMA", "SIM", "SKIP", "TIME", "TRACK", "ZAWGT", "FORMAT",
    "END",
)

#: planets whose positions `compute_posvels(planets=True)` attaches
PLANETS = ("jupiter", "saturn", "venus", "uranus", "neptune")


@dataclass
class TOA:
    """One time-of-arrival: site-UTC epoch + metadata (host record)."""

    mjd: MJD                      # UTC at the observatory (two-part)
    error_us: float = 0.0
    freq_mhz: float = np.inf
    obs: str = "barycenter"
    flags: Dict[str, str] = field(default_factory=dict)

    def __str__(self):  # pragma: no cover - debugging aid
        return (f"{self.mjd.day}{str(float(self.mjd.frac))[1:]}:"
                f" {self.error_us} us at '{self.obs}' at {self.freq_mhz} MHz")


def _classify(line: str, fmt: str) -> str:
    """Line-type classification, matching the reference's precedence
    (`/root/reference/src/pint/toa.py:442`)."""
    if re.match(r"[0-9a-z@] ", line):
        return "Princeton"
    if line.startswith(("C ", "c ", "#", "CC ")):
        return "Comment"
    if line.upper().lstrip().startswith(_COMMANDS):
        return "Command"
    if re.match(r"^\s*$", line):
        return "Blank"
    if re.match(r"^ ", line) and len(line) > 41 and line[41] == ".":
        return "Parkes"
    if len(line) > 80 or fmt == "Tempo2":
        return "Tempo2"
    if re.match(r"\S\S", line) and len(line) > 14 and line[14] == ".":
        return "ITOA"
    return "Unknown"


def _parse_line(line: str, fmt: str) -> Tuple[str, Optional[TOA], List[str]]:
    """Parse one tim line → (kind, TOA-or-None, command-fields)."""
    kind = _classify(line, fmt)
    if kind == "Command":
        return kind, None, line.split()
    if kind in ("Comment", "Blank"):
        return kind, None, []
    if kind == "Unknown":
        raise TimFileError(f"unable to identify TOA format of line {line!r} "
                           "(missing FORMAT 1 header?)")
    if kind == "Tempo2":
        fields = line.split()
        if len(fields) < 5:
            raise TimFileError(f"short Tempo2 TOA line: {line!r}")
        name, freq, epoch, err, obs = fields[:5]
        flags = {"name": name}
        rest = fields[5:]
        if len(rest) % 2:
            raise TimFileError(f"flags must come in -key value pairs: {line!r}")
        for i in range(0, len(rest), 2):
            k = rest[i].lstrip("-")
            if not k or not rest[i].startswith("-"):
                raise TimFileError(f"bad flag {rest[i]!r} in {line!r}")
            if k in ("error", "freq", "scale", "MJD", "flags", "obs", "name"):
                raise TimFileError(f"TOA flag {k!r} would overwrite a TOA "
                                   f"column: {line!r}")
            flags[k] = rest[i + 1]
        return kind, TOA(mjd=mjdmod.from_string(epoch), error_us=float(err),
                         freq_mhz=_freq(float(freq)), obs=get_observatory(obs).name,
                         flags=flags), []
    if kind == "Princeton":
        obs = get_observatory(line[0]).name
        freq = float(line[15:24])
        ii, ff = line[24:44].split(".")
        day = int(ii)
        if day < 40000:   # two-digit-year era TOAs (tempo convention)
            day += 39126
        t = mjdmod.from_string(f"{day}.{ff.strip()}")
        err = float(line[44:53])
        flags = {}
        try:
            flags["ddm"] = str(float(line[68:78]))
        except (ValueError, IndexError):
            pass
        return kind, TOA(mjd=t, error_us=err, freq_mhz=_freq(freq), obs=obs,
                         flags=flags), []
    if kind == "Parkes":
        name = line[1:25].strip()
        freq = float(line[25:34])
        ii = int(line[34:41])
        ff = line[42:55].strip()
        if float(line[55:62] or 0.0) != 0.0:
            raise TimFileError("Parkes phase-offset column is not supported")
        err = float(line[63:71])
        obs = get_observatory(line[79]).name
        return kind, TOA(mjd=mjdmod.from_string(f"{ii}.{ff}"), error_us=err,
                         freq_mhz=_freq(freq), obs=obs,
                         flags={"name": name} if name else {}), []
    raise TimFileError(f"TOA format {kind!r} not supported: {line!r}")


def _freq(f: float) -> float:
    return np.inf if f == 0.0 else f


def read_tim(path_or_lines: Union[str, Sequence[str]], fmt: str = "Unknown"
             ) -> Tuple[List[TOA], List[str]]:
    """Read a tim file (or iterable of lines) → (toas, commands-seen).

    Applies inline commands exactly as the reference does
    (`/root/reference/src/pint/toa.py:760-860`): EFAC/EQUAD scale the
    uncertainty, EMIN/EMAX/FMIN/FMAX filter, TIME accumulates an offset
    recorded in the ``to`` flag, PHASE in the ``phase`` flag, JUMP brackets
    mark TOAs with ``tim_jump`` flags, INCLUDE recurses.
    """
    if isinstance(path_or_lines, str):
        basedir = os.path.dirname(os.path.abspath(path_or_lines))
        with open(path_or_lines) as f:
            lines = f.readlines()
    else:
        basedir, lines = ".", list(path_or_lines)

    toas: List[TOA] = []
    commands: List[str] = []
    # one shared command state across INCLUDEd files, so e.g. an END inside
    # an include terminates the whole read (reference shares its cdict,
    # `/root/reference/src/pint/toa.py:760-832`)
    st = {"FORMAT": fmt, "EFAC": 1.0, "EQUAD": 0.0, "EMIN": 0.0,
          "EMAX": np.inf, "FMIN": 0.0, "FMAX": np.inf, "TIME": 0.0,
          "PHASE": 0, "SKIP": False, "END": False, "INFO": None,
          "JUMP_ACTIVE": False, "JUMP_N": 0}

    def handle_command(fields, basedir):
        cmd = fields[0].upper()
        commands.append(" ".join(fields))
        if cmd == "SKIP":
            st["SKIP"] = True
        elif cmd == "NOSKIP":
            st["SKIP"] = False
        elif cmd == "END":
            st["END"] = True
        elif cmd == "FORMAT":
            st["FORMAT"] = "Tempo2" if fields[1] == "1" else "Unknown"
        elif cmd == "TIME":
            st["TIME"] += float(fields[1])
        elif cmd == "PHASE":
            st["PHASE"] += int(float(fields[1]))
        elif cmd in ("EFAC", "EQUAD", "EMIN", "EMAX", "FMIN", "FMAX"):
            st[cmd] = float(fields[1])
        elif cmd == "INFO":
            st["INFO"] = fields[1] if len(fields) > 1 else None
        elif cmd == "JUMP":
            if st["JUMP_ACTIVE"]:
                st["JUMP_ACTIVE"] = False
            else:
                st["JUMP_ACTIVE"] = True
                st["JUMP_N"] += 1
        elif cmd == "INCLUDE":
            path = os.path.join(basedir, fields[1])
            # the included file declares its own FORMAT; restore the parent's
            # afterwards (reference `/root/reference/src/pint/toa.py:806-816`)
            saved_fmt, st["FORMAT"] = st["FORMAT"], "Unknown"
            try:
                with open(path) as f:
                    process(f.readlines(),
                            os.path.dirname(os.path.abspath(path)))
            finally:
                st["FORMAT"] = saved_fmt
        elif cmd == "MODE":
            if fields[1:] and fields[1] != "1":
                warnings.warn(f"MODE {fields[1]} is ignored (only MODE 1, "
                              "fit-with-errors, is meaningful)")
        # DITHER/EMAP/PHA1/PHA2/SEARCH/SIGMA/SIM/TRACK/ZAWGT: recorded, ignored

    def process(lines, basedir):
        for raw in lines:
            if st["END"]:
                break
            # commands stay live inside SKIP blocks (reference handles
            # Command lines before its SKIP check,
            # `/root/reference/src/pint/toa.py:771-830`); only TOA lines
            # are suppressed.
            if st["SKIP"] and _classify(raw, st["FORMAT"]) != "Command":
                continue
            kind, toa, fields = _parse_line(raw, st["FORMAT"])
            if kind == "Command":
                handle_command(fields, basedir)
                if st["END"]:
                    break
                continue
            if toa is None:
                continue
            # EMIN/EMAX filter on the *raw* uncertainty, then EFAC/EQUAD
            # rescale (reference order, `/root/reference/src/pint/toa.py:836-845`)
            if not (st["EMIN"] <= toa.error_us <= st["EMAX"]) or \
                    not (st["FMIN"] <= toa.freq_mhz <= st["FMAX"]):
                continue
            toa.error_us = float(np.hypot(toa.error_us * st["EFAC"],
                                          st["EQUAD"]))
            if st["INFO"]:
                toa.flags.setdefault("info", st["INFO"])
            if st["JUMP_ACTIVE"]:
                toa.flags["jump"] = str(st["JUMP_N"])
                toa.flags["tim_jump"] = str(st["JUMP_N"])
            if st["PHASE"]:
                toa.flags["phase"] = str(st["PHASE"])
            if st["TIME"]:
                # recorded only; applied with the clock corrections, like the
                # reference's handling of "-to" flags (toa.py:2238)
                toa.flags["to"] = str(st["TIME"])
            toas.append(toa)

    process(lines, basedir)
    return toas, commands


def format_toa_line(toa: TOA) -> str:
    """One Tempo2-format output line (cf. reference `format_toa_line`,
    `/root/reference/src/pint/toa.py:567`)."""
    name = toa.flags.get("name", "unk")
    freq = 0.0 if np.isinf(toa.freq_mhz) else toa.freq_mhz
    obs = get_observatory(toa.obs)
    code = obs.tempo_code or toa.obs
    flagstr = " ".join(
        f"-{k} {v}" for k, v in sorted(toa.flags.items()) if k != "name"
    )
    day, frac = int(toa.mjd.day), float(toa.mjd.frac)
    fracstr = f"{frac:.16f}"
    if fracstr.startswith("1"):  # frac within 10 ps of midnight rounded up
        day, fracstr = day + 1, f"{0.0:.16f}"
    return (f"{name} {freq:.6f} {day}{fracstr[1:]} "
            f"{toa.error_us:.3f} {code} {flagstr}").rstrip()


def write_tim(path, toas: "TOAs", commentflag: Optional[str] = None):
    """Write a Tempo2-format tim file."""
    with open(path, "w") as f:
        f.write("FORMAT 1\n")
        for t in toas.to_list():
            prefix = ""
            if commentflag and commentflag in t.flags:
                prefix = "C "
            f.write(prefix + format_toa_line(t) + "\n")


class TOAs:
    """Host container of TOAs: numpy columns + per-TOA flag dicts.

    The analogue of the reference's ``TOAs``
    (`/root/reference/src/pint/toa.py:1184`), with the astropy Table replaced
    by plain arrays and the device-facing data exported via :meth:`to_batch`.
    """

    def __init__(self, toalist: Sequence[TOA], commands: Optional[List[str]] = None,
                 filename: Optional[str] = None):
        if len(toalist) == 0:
            raise TimFileError("no TOAs")
        self.filename = filename
        self.commands = commands or []
        self.ephem: Optional[str] = None
        self.planets = False
        self.clock_corr_info: Dict[str, object] = {}
        n = len(toalist)
        self.utc = MJD(np.array([int(t.mjd.day) for t in toalist], np.int64),
                       np.array([float(t.mjd.frac) for t in toalist], np.float64))
        self.error_us = np.array([t.error_us for t in toalist], np.float64)
        self.freq_mhz = np.array([t.freq_mhz for t in toalist], np.float64)
        self.obs = np.array([t.obs for t in toalist])
        self.flags: List[Dict[str, str]] = [dict(t.flags) for t in toalist]
        self.tdb: Optional[MJD] = None
        self.ssb_obs_pos: Optional[np.ndarray] = None   # m
        self.ssb_obs_vel: Optional[np.ndarray] = None   # m/s
        self.obs_sun_pos: Optional[np.ndarray] = None   # m
        self.obs_planet_pos: Dict[str, np.ndarray] = {}
        # index into original file ordering (survives select())
        self.index = np.arange(n)

    # -- basic introspection ------------------------------------------------
    @property
    def ntoas(self) -> int:
        return len(self.flags)

    def __len__(self):
        return self.ntoas

    @property
    def observatories(self):
        return set(self.obs.tolist())

    @property
    def first_MJD(self) -> float:
        return float(np.min(self.utc.mjd_float))

    @property
    def last_MJD(self) -> float:
        return float(np.max(self.utc.mjd_float))

    def get_mjds(self, high_precision=False):
        """UTC MJDs as float64 (or the exact two-part MJD)."""
        return self.utc if high_precision else self.utc.mjd_float

    def get_errors(self):
        return self.error_us

    def get_freqs(self):
        return self.freq_mhz

    def get_obss(self):
        return self.obs

    def get_pulse_numbers(self) -> Optional[np.ndarray]:
        if all("pn" not in f for f in self.flags):
            return None
        return np.array([float(f.get("pn", np.nan)) for f in self.flags])

    @classmethod
    def from_columns(cls, utc: MJD, error_us, freq_mhz, obs,
                     flags: Optional[List[Dict[str, str]]] = None,
                     filename: Optional[str] = None) -> "TOAs":
        """Column-wise construction, bypassing per-row TOA objects —
        photon-event files carry 1e6-1e7 rows where the per-row path
        costs minutes of pure python."""
        self = cls.__new__(cls)
        self.filename = filename
        self.commands = []
        self.ephem = None
        self.planets = False
        self.clock_corr_info = {}
        n = len(utc.day)
        self.utc = MJD(np.asarray(utc.day, np.int64),
                       np.asarray(utc.frac, np.float64))
        self.error_us = np.broadcast_to(
            np.asarray(error_us, np.float64), (n,)).copy()
        self.freq_mhz = np.broadcast_to(
            np.asarray(freq_mhz, np.float64), (n,)).copy()
        self.obs = (np.full(n, obs) if isinstance(obs, str)
                    else np.asarray(obs))
        self.flags = flags if flags is not None else [{} for _ in range(n)]
        if len(self.flags) != n:
            raise ValueError("flags list length mismatch")
        self.tdb = None
        self.ssb_obs_pos = None
        self.ssb_obs_vel = None
        self.obs_sun_pos = None
        self.obs_planet_pos = {}
        self.index = np.arange(n)
        return self

    @property
    def is_wideband(self) -> bool:
        """True when any TOA carries a ``-pp_dm`` wideband DM measurement
        (reference `TOAs.is_wideband`,
        `/root/reference/src/pint/toa.py:1659`)."""
        return any("pp_dm" in f for f in self.flags)

    def get_dm_data(self):
        """Wideband DM measurements: ``(index, dm, dm_error)`` — the TOA
        row indices carrying ``-pp_dm``/``-pp_dme`` flags and their values
        [pc cm^-3] — or None if no TOA has DM data (reference
        `WidebandDMResiduals.get_dm_data`,
        `/root/reference/src/pint/residuals.py:1114`)."""
        idx = [i for i, f in enumerate(self.flags) if "pp_dm" in f]
        if not idx:
            return None
        dm = np.array([float(self.flags[i]["pp_dm"]) for i in idx])
        dme = np.array([float(self.flags[i].get("pp_dme", 0.0))
                        for i in idx])
        if np.any(dme <= 0.0):
            raise ValueError(
                "wideband TOAs need positive -pp_dme DM uncertainties")
        return np.array(idx), dm, dme

    def get_flag_value(self, flag, fill_value=None, as_type=None):
        vals = []
        idx = []
        for i, f in enumerate(self.flags):
            v = f.get(flag, fill_value)
            if v is not fill_value and as_type is not None:
                v = as_type(v)
            vals.append(v)
            if f.get(flag) is not None:
                idx.append(i)
        return vals, idx

    def to_list(self, undo_clkcorr=True) -> List[TOA]:
        """Back to per-TOA records; by default un-applies clock corrections
        (and drops the ``clkcorr`` flag) so written tim files are raw site
        arrival times, as the reference does
        (`/root/reference/src/pint/toa.py:1624`)."""
        out = []
        for i in range(self.ntoas):
            t = MJD(self.utc.day[i], self.utc.frac[i])
            flags = dict(self.flags[i])
            if undo_clkcorr and "clkcorr" in flags:
                t = mjdmod.add_sec(t, -float(flags.pop("clkcorr")))
            out.append(TOA(mjd=MJD(np.int64(t.day), np.float64(t.frac)),
                           error_us=float(self.error_us[i]),
                           freq_mhz=float(self.freq_mhz[i]),
                           obs=str(self.obs[i]), flags=flags))
        return out

    def select(self, mask) -> "TOAs":
        """Boolean/index subset (new object; host-side)."""
        mask = np.asarray(mask)
        out = object.__new__(TOAs)
        out.filename = self.filename
        out.commands = self.commands
        out.ephem = self.ephem
        out.planets = self.planets
        out.clock_corr_info = dict(self.clock_corr_info)
        out.utc = MJD(self.utc.day[mask], self.utc.frac[mask])
        out.error_us = self.error_us[mask]
        out.freq_mhz = self.freq_mhz[mask]
        out.obs = self.obs[mask]
        idx = np.arange(self.ntoas)[mask] if mask.dtype == bool else mask
        out.flags = [dict(self.flags[i]) for i in idx]
        # optional photon-event columns (see event_toas.load_fits_TOAs)
        for attr in ("energies", "weights"):
            col = getattr(self, attr, None)
            if col is not None:
                setattr(out, attr, np.asarray(col)[idx])
        out.is_photon_events = getattr(self, "is_photon_events", False)
        extra = getattr(self, "extra", None)
        if extra is not None:
            out.extra = {k: np.asarray(v)[idx] for k, v in extra.items()}
        out.index = self.index[mask]
        out.tdb = None if self.tdb is None else MJD(self.tdb.day[mask],
                                                    self.tdb.frac[mask])
        out._tdb_topo_applied = getattr(self, "_tdb_topo_applied", False)
        for col in ("ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos"):
            v = getattr(self, col)
            setattr(out, col, None if v is None else v[mask])
        out.obs_planet_pos = {k: v[mask] for k, v in self.obs_planet_pos.items()}
        return out

    # -- pipeline stages ----------------------------------------------------
    def apply_clock_corrections(self, include_bipm=False, bipm_version="BIPM2021",
                                limits="warn"):
        """Shift site TOAs to (BIPM-realized) UTC, per observatory group.

        cf. reference `/root/reference/src/pint/toa.py:2195`.  Idempotent via
        the ``clkcorr`` flag.
        """
        if any("clkcorr" in f for f in self.flags):
            return
        from pint_tpu import clock as clockmod

        # "-to" flags are TIME offsets applied together with the clock
        # corrections (reference `/root/reference/src/pint/toa.py:2238`)
        corr = np.array([float(f.get("to", 0.0)) for f in self.flags])
        for obsname in self.observatories:
            sel = self.obs == obsname
            site = get_observatory(obsname)
            csel = site.clock_corrections(self.utc.mjd_float[sel], limits=limits)
            if include_bipm and not site.is_barycenter:
                csel = csel + clockmod.bipm_correction(
                    self.utc.mjd_float[sel], version=bipm_version, limits=limits)
            corr[sel] += csel
        self.utc = mjdmod.add_sec(self.utc, corr)
        for i, f in enumerate(self.flags):
            if corr[i] != 0.0:
                f["clkcorr"] = str(corr[i])
        self.clock_corr_info.update(
            include_bipm=include_bipm, bipm_version=bipm_version)

    def compute_TDBs(self, ephem: Optional[str] = "DE421", method="default"):
        """UTC → TDB at each TOA (geocentric FB90 series; the topocentric
        term, ~2 us diurnal amplitude, is applied in :meth:`compute_posvels`
        once the observatory geometry is available — cf. reference
        `/root/reference/src/pint/toa.py:2262`).

        Barycentric ('@'/'bat') TOAs are *already* TDB by convention
        (reference `special_locations.py:71` sets timescale tdb) and pass
        through unchanged.
        """
        tdb = mjdmod.utc_to_tdb(self.utc)
        bary = np.array([get_observatory(o).is_barycenter for o in self.obs])
        self.tdb = MJD(np.where(bary, self.utc.day, tdb.day),
                       np.where(bary, self.utc.frac, tdb.frac))
        self._tdb_topo_applied = False
        self.ephem = self.ephem or ephem

    def compute_posvels(self, ephem: Optional[str] = "DE421", planets=False):
        """Attach SSB-relative observatory/Sun/planet geometry.

        cf. reference `/root/reference/src/pint/toa.py:2334`.
        """
        from pint_tpu.ephemeris import load_ephemeris

        if self.tdb is None:
            self.compute_TDBs(ephem=ephem)
        eph = load_ephemeris(ephem)
        self.ephem = ephem
        self.planets = planets
        tdb_f = self.tdb.mjd_float
        if hasattr(eph, "pinned_to") and len(tdb_f):
            # serve every per-observatory group below from the ONE window
            # quantized from the full dataset span (integrated-ephemeris
            # consistency; see IntegratedEphemeris.pinned_to)
            eph = eph.pinned_to(tdb_f)
        tt = mjdmod.utc_to_tt(self.utc)

        n = self.ntoas
        self.ssb_obs_pos = np.zeros((n, 3))
        self.ssb_obs_vel = np.zeros((n, 3))
        self.obs_sun_pos = np.zeros((n, 3))
        wanted = PLANETS if planets else ()
        self.obs_planet_pos = {p: np.zeros((n, 3)) for p in wanted}
        tdb_topo = np.zeros(n)

        for obsname in self.observatories:
            sel = np.flatnonzero(self.obs == obsname)
            site = get_observatory(obsname)
            t_sel = tdb_f[sel]
            if site.is_barycenter:
                ssb_obs = PosVel(np.zeros((len(sel), 3)), np.zeros((len(sel), 3)))
            else:
                earth = eph.posvel("earth", t_sel)
                if site.is_geocenter:
                    ssb_obs = earth
                else:
                    if getattr(site, "needs_flag_positions", False):
                        geo = site.posvel_gcrs_from_flags(
                            [self.flags[i] for i in sel])
                    else:
                        geo = site.posvel_gcrs(tt.mjd_float[sel])
                    ssb_obs = PosVel(earth.pos + geo.pos, earth.vel + geo.vel)
                    # topocentric TDB-TT term (v_earth·r_obs)/c², ~2 us
                    # diurnal (tdbseries.py:180); the FB90 series applied in
                    # compute_TDBs is geocentric only
                    tdb_topo[sel] = tdbseries.tdb_minus_tt_topo(
                        geo.pos, earth.vel)
            self.ssb_obs_pos[sel] = ssb_obs.pos
            self.ssb_obs_vel[sel] = ssb_obs.vel
            sun = eph.posvel("sun", t_sel)
            self.obs_sun_pos[sel] = sun.pos - ssb_obs.pos
            for p in wanted:
                body = eph.posvel(p, t_sel)
                self.obs_planet_pos[p][sel] = body.pos - ssb_obs.pos

        if not getattr(self, "_tdb_topo_applied", False) and np.any(tdb_topo):
            self.tdb = mjdmod.add_sec(self.tdb, tdb_topo)
            self._tdb_topo_applied = True

    # -- export -------------------------------------------------------------
    def to_batch(self, policy: Optional[str] = None) -> TOABatch:
        """Export the device-facing struct-of-arrays pytree.

        ``policy`` ("raise" | "mask" | "warn") is the input-validation
        policy applied by :func:`pint_tpu.toabatch.make_batch` to
        non-finite/nonpositive uncertainties, non-finite MJDs and empty
        selections; default $PINT_TPU_VALIDATE -> "raise".  Photon-event
        TOAs (``is_photon_events``) default to "off": their zero
        uncertainties are by construction (unbinned likelihoods), not
        data corruption."""
        if policy is None and getattr(self, "is_photon_events", False):
            policy = "off"
        if self.tdb is None:
            raise ValueError("run compute_TDBs/compute_posvels before to_batch")
        if self.ssb_obs_pos is None and any(
                not get_observatory(o).is_barycenter for o in self.observatories):
            raise ValueError(
                "topocentric/geocentric TOAs need compute_posvels() before "
                "to_batch(); zero geometry is only valid for barycentric data")
        # center the fraction at |frac|<=0.5 for best dd cancellation
        frac = np.asarray(self.tdb.frac, np.float64)
        day = np.asarray(self.tdb.day, np.int64).copy()
        hi = frac > 0.5
        day[hi] += 1
        frac = np.where(hi, frac - 1.0, frac)
        pn = self.get_pulse_numbers()
        return make_batch(
            tdb_day=day, tdb_frac=frac, error_us=self.error_us,
            freq_mhz=self.freq_mhz,
            ssb_obs_pos_ls=None if self.ssb_obs_pos is None
            else self.ssb_obs_pos / C_LIGHT,
            ssb_obs_vel_c=None if self.ssb_obs_vel is None
            else self.ssb_obs_vel / C_LIGHT,
            obs_sun_pos_ls=None if self.obs_sun_pos is None
            else self.obs_sun_pos / C_LIGHT,
            pulse_number=pn,
            obs_planet_pos_ls={k: v / C_LIGHT
                               for k, v in self.obs_planet_pos.items()},
            policy=policy,
        )


def _toa_cache_key(timfile: str, ephem, planets, include_bipm,
                   bipm_version, limits) -> str:
    """Content hash of the tim file + preparation settings (reference
    caches on file hashes the same way, `toa.py:334-404`)."""
    import hashlib

    h = hashlib.sha256()

    def feed(path):
        with open(path, "rb") as f:
            data = f.read()
        h.update(data)
        # INCLUDEd tim files are part of the content (read_tim recurses)
        basedir = os.path.dirname(os.path.abspath(path))
        for line in data.decode("ascii", "replace").splitlines():
            fields = line.split()
            if fields and fields[0].upper() == "INCLUDE" and len(fields) > 1:
                sub = fields[1]
                if not os.path.isabs(sub):
                    sub = os.path.join(basedir, sub)
                if os.path.exists(sub):
                    feed(sub)

    feed(timfile)
    h.update(repr((ephem, planets, include_bipm, bipm_version, limits,
                   3)).encode())        # trailing int = cache format rev
    return h.hexdigest()


def get_TOAs(timfile, ephem="DE421", planets=False, include_bipm=False,
             bipm_version="BIPM2021", model=None, limits="warn",
             usepickle=False, picklefilename=None) -> TOAs:
    """Load, clock-correct, and barycenter-prepare TOAs from a tim file.

    Equivalent of the reference's `get_TOAs`
    (`/root/reference/src/pint/toa.py:110`).  If ``model`` is given, EPHEM /
    CLOCK / PLANET_SHAPIRO defaults are taken from it.  ``usepickle``
    caches the fully-prepared TOAs next to the tim file, keyed on a
    content hash of the file + preparation settings (reference
    `load_pickle`/`save_pickle`, `toa.py:334-404`); a stale or
    incompatible cache is silently rebuilt.
    """
    if model is not None:
        if getattr(model, "EPHEM", None) and model.EPHEM.value:
            ephem = model.EPHEM.value
        if getattr(model, "PLANET_SHAPIRO", None) and model.PLANET_SHAPIRO.value:
            planets = True
        clk = getattr(model, "CLOCK", None)
        if clk is not None and clk.value and clk.value.upper().startswith("TT(BIPM"):
            include_bipm = True
            v = clk.value.upper().replace("TT(", "").replace(")", "")
            if v != "BIPM":
                bipm_version = v
    cachefile = None
    if usepickle and isinstance(timfile, str):
        import gzip
        import pickle

        cachefile = picklefilename or timfile + ".pint_tpu_pickle.gz"
        key = _toa_cache_key(timfile, ephem, planets, include_bipm,
                             bipm_version, limits)
        if os.path.exists(cachefile):
            try:
                with gzip.open(cachefile, "rb") as f:
                    stored_key, t = pickle.load(f)
                if stored_key == key:
                    return t
            except Exception:
                pass  # unreadable/incompatible cache: rebuild below
    toalist, commands = read_tim(timfile)
    t = TOAs(toalist, commands=commands,
             filename=timfile if isinstance(timfile, str) else None)
    t.apply_clock_corrections(include_bipm=include_bipm,
                              bipm_version=bipm_version, limits=limits)
    t.compute_TDBs(ephem=ephem)
    t.compute_posvels(ephem=ephem, planets=planets)
    if cachefile is not None:
        import gzip
        import pickle

        with gzip.open(cachefile, "wb") as f:
            pickle.dump((key, t), f)
    return t


def get_TOAs_array(times, obs="bary", errors_us=1.0, freqs_mhz=np.inf,
                   flags=None, ephem="DE421", planets=False,
                   include_bipm=False, **kw) -> TOAs:
    """Build prepared TOAs from arrays (reference `get_TOAs_array`,
    `/root/reference/src/pint/toa.py:2787`).

    ``times`` may be an :class:`MJD` pair or float64 MJDs (UTC at site).
    """
    if not isinstance(times, MJD):
        times = mjdmod.from_mjd_float(np.atleast_1d(np.asarray(times, np.float64)))
    else:
        times = MJD(np.atleast_1d(times.day), np.atleast_1d(times.frac))
    n = times.day.shape[0]
    errors_us = np.broadcast_to(np.asarray(errors_us, np.float64), (n,))
    freqs_mhz = np.broadcast_to(np.asarray(freqs_mhz, np.float64), (n,))
    if np.ndim(obs):
        obs_arr = [get_observatory(o).name for o in np.asarray(obs)]
    else:
        obs_arr = [get_observatory(obs).name] * n
    toalist = [TOA(mjd=MJD(times.day[i], times.frac[i]),
                   error_us=float(errors_us[i]), freq_mhz=float(freqs_mhz[i]),
                   obs=str(obs_arr[i]),
                   flags=dict(flags[i]) if flags is not None else {})
               for i in range(n)]
    t = TOAs(toalist)
    t.apply_clock_corrections(include_bipm=include_bipm, **kw)
    t.compute_TDBs(ephem=ephem)
    t.compute_posvels(ephem=ephem, planets=planets)
    return t


def merge_TOAs(toas_list: Sequence[TOAs]) -> TOAs:
    """Concatenate prepared TOAs objects (reference `merge_TOAs`,
    `/root/reference/src/pint/toa.py:2757`)."""
    toas_list = list(toas_list)
    ephems = {t.ephem for t in toas_list}
    if len(ephems) > 1:
        raise ValueError(f"cannot merge TOAs with different ephemerides: {ephems}")
    # clock-correction state must agree, or the merged object's idempotency
    # guard would leave the uncorrected subset permanently uncorrected
    corrected = {any("clkcorr" in f for f in t.flags) for t in toas_list}
    if len(corrected) > 1:
        raise ValueError("cannot merge clock-corrected with uncorrected TOAs")
    infos = {tuple(sorted(t.clock_corr_info.items())) for t in toas_list}
    if len(infos) > 1:
        raise ValueError(
            f"cannot merge TOAs with different clock settings: {infos}")
    alltoas = [x for t in toas_list for x in t.to_list(undo_clkcorr=False)]
    out = TOAs(alltoas, commands=[c for t in toas_list for c in t.commands])
    out.ephem = toas_list[0].ephem
    out.planets = all(t.planets for t in toas_list)
    out.clock_corr_info = dict(toas_list[0].clock_corr_info)
    # photon-event columns: propagate when every input carries them
    for attr in ("energies", "weights"):
        cols = [getattr(t, attr, None) for t in toas_list]
        if all(c is not None for c in cols):
            setattr(out, attr, np.concatenate([np.asarray(c) for c in cols]))
        elif any(c is not None for c in cols):
            warnings.warn(f"merge_TOAs: only some inputs carry {attr}; "
                          "the merged TOAs drops the column")
    # re-deriving the prepared columns keeps merge simple and exact
    if all(t.tdb is not None for t in toas_list):
        out.compute_TDBs(ephem=out.ephem)
    if all(t.ssb_obs_pos is not None for t in toas_list):
        out.compute_posvels(ephem=out.ephem, planets=out.planets)
    return out
