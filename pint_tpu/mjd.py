"""Two-float MJD times and time-scale conversions (UTC/TAI/TT/TDB).

Replaces the reference's astropy-Time + ``np.longdouble`` time handling
(`src/pint/pulsar_mjd.py`): a time is an ``MJD`` pytree of
``(day: int64, frac: float64 in [0,1))``.  The fraction resolution is
86400 s × 2⁻⁵² ≈ 19 ps, far below the ~ns timing requirement, and epoch
*differences* are returned as exact double-double seconds, so no precision is
lost forming ``t - PEPOCH`` over decades-long baselines.

Scale conventions follow tempo/tempo2 ("pulsar_mjd", reference
`src/pint/pulsar_mjd.py:36-114`): a UTC day is always 86400 fractional-day
units long; on a day with a leap second the extra second is absorbed at the
UTC→TAI step via the leap-second table, never smeared into the day length.

This module is deliberately **pure numpy**: time-scale conversion is
host-side loader work (reference: `TOAs.compute_TDBs`, `src/pint/toa.py:2262`),
and on this image every jax op lands on the TPU backend whose emulated f64 is
not IEEE-correct — host precompute must stay on true-IEEE CPU floats.
Device-side code only ever sees exact (day, frac) pairs or DD/QS seconds
produced here.

The TT→TDB conversion is the Fairhead & Bretagnon (1990) analytic series in
:mod:`pint_tpu.tdbseries`.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from pint_tpu import dd as ddm
from pint_tpu.dd import DD

SECS_PER_DAY = 86400.0
TT_MINUS_TAI = 32.184  # s, exact by definition
MJD_J2000 = 51544.5  # TT


class MJD(NamedTuple):
    """A (vector of) time(s) as integer MJD day + float64 day fraction.

    The time *scale* is contextual (functions below are explicit about what
    scale they expect); the pytree carries no metadata so it can flow through
    jit/vmap.
    """

    day: np.ndarray  # integer-valued (int64)
    frac: np.ndarray  # float64 in [0, 1)

    @property
    def mjd_float(self):
        """Lossy float64 view (for plotting / rough work only)."""
        return self.day + self.frac

    def to_dd_day(self) -> DD:
        """MJD as a double-double number of days (exact)."""
        return ddm.add_f(ddm.from_float(np.asarray(self.frac, np.float64)),
                         np.asarray(self.day, np.float64))


def normalize(day, frac) -> MJD:
    """Carry the fraction into [0,1) adjusting the day."""
    day = np.asarray(day)
    frac = np.asarray(frac, np.float64)
    carry = np.floor(frac)
    return MJD((day + carry.astype(day.dtype)), frac - carry)


def from_mjd_float(x) -> MJD:
    """Build from a plain float64 MJD (≈19 ps resolution near MJD 5e4)."""
    x = np.asarray(x, np.float64)
    d = np.floor(x)
    return MJD(d.astype(np.int64), x - d)


def from_day_frac(day, frac) -> MJD:
    return normalize(np.asarray(day, np.int64), frac)


def from_string(s: str) -> MJD:
    """Host-side exact parse of a decimal MJD string (tim-file precision)."""
    s = s.strip()
    neg = s.startswith("-")
    body = s.lstrip("+-")
    if "." in body:
        ip, fp = body.split(".")
    else:
        ip, fp = body, ""
    day = int(ip) if ip else 0
    # the decimal module gives a correctly-rounded fraction
    from decimal import Decimal

    frac = float(Decimal("0." + fp)) if fp else 0.0
    if neg:
        day, frac = (-day, 0.0) if frac == 0.0 else (-day - 1, 1.0 - frac)
    if frac >= 1.0:  # rounding of 0.999... can land exactly on 1.0
        day, frac = day + 1, 0.0
    return MJD(np.int64(day), np.float64(frac))


def add_sec(t: MJD, sec) -> MJD:
    """t + seconds (f64).  Rounding ≤ ~19 ps per call."""
    return normalize(t.day, t.frac + np.asarray(sec, np.float64) / SECS_PER_DAY)


def diff_sec(a: MJD, b: MJD) -> DD:
    """(a - b) in seconds, exact to double-double precision."""
    ddays = (np.asarray(a.day, np.int64) - np.asarray(b.day, np.int64)).astype(
        np.float64
    )
    dfrac = ddm.sum_ff(a.frac, -np.asarray(b.frac, np.float64))
    # ddays * 86400 is exact in f64 for |ddays| < 1e11; dfrac*86400 via DD mul
    out = ddm.add(ddm.prod_ff(ddays, SECS_PER_DAY), ddm.mul_f(dfrac, SECS_PER_DAY))
    return out


def diff_day_dd(a: MJD, b: MJD) -> DD:
    """(a - b) in days, exact."""
    ddays = (np.asarray(a.day, np.int64) - np.asarray(b.day, np.int64)).astype(
        np.float64
    )
    dfrac = ddm.sum_ff(a.frac, -np.asarray(b.frac, np.float64))
    return ddm.add_f(dfrac, ddays)


# --- leap seconds -------------------------------------------------------------
# (MJD of UTC day on which TAI-UTC changed, TAI-UTC in seconds from that day).
# Public IERS facts; the modern (post-1972) integer-leap-second era. The table
# is closed: no leap second has been scheduled since 2017-01-01, and none is
# before the framework's data horizon. Pre-1972 "rubber seconds" are not
# supported (the reference's pulsar timing data never predates 1972).
_LEAP_TABLE = np.array(
    [
        (41317, 10.0),  # 1972-01-01
        (41499, 11.0),  # 1972-07-01
        (41683, 12.0),  # 1973-01-01
        (42048, 13.0),  # 1974-01-01
        (42413, 14.0),  # 1975-01-01
        (42778, 15.0),  # 1976-01-01
        (43144, 16.0),  # 1977-01-01
        (43509, 17.0),  # 1978-01-01
        (43874, 18.0),  # 1979-01-01
        (44239, 19.0),  # 1980-01-01
        (44786, 20.0),  # 1981-07-01
        (45151, 21.0),  # 1982-07-01
        (45516, 22.0),  # 1983-07-01
        (46247, 23.0),  # 1985-07-01
        (47161, 24.0),  # 1988-01-01
        (47892, 25.0),  # 1990-01-01
        (48257, 26.0),  # 1991-01-01
        (48804, 27.0),  # 1992-07-01
        (49169, 28.0),  # 1993-07-01
        (49534, 29.0),  # 1994-07-01
        (50083, 30.0),  # 1996-01-01
        (50630, 31.0),  # 1997-07-01
        (51179, 32.0),  # 1999-01-01
        (53736, 33.0),  # 2006-01-01
        (54832, 34.0),  # 2009-01-01
        (56109, 35.0),  # 2012-07-01
        (57204, 36.0),  # 2015-07-01
        (57754, 37.0),  # 2017-01-01
    ],
    dtype=np.float64,
)

_LEAP_MJD = np.asarray(_LEAP_TABLE[:, 0])
_LEAP_OFF = np.asarray(_LEAP_TABLE[:, 1])


def tai_minus_utc(utc_day) -> np.ndarray:
    """TAI-UTC [s] for the given UTC MJD day number(s)."""
    idx = np.searchsorted(_LEAP_MJD, np.asarray(utc_day, np.float64), side="right")
    idx = np.clip(idx - 1, 0, _LEAP_OFF.shape[0] - 1)
    return _LEAP_OFF[idx]


def utc_to_tai(t: MJD) -> MJD:
    return add_sec(t, tai_minus_utc(t.day))


def tai_to_utc(t: MJD) -> MJD:
    # offset is a step function of the *UTC* day; one fixed-point pass is exact
    # except within a second of a boundary, where a second pass settles it.
    guess = add_sec(t, -tai_minus_utc(t.day))
    return add_sec(t, -tai_minus_utc(guess.day))


def tai_to_tt(t: MJD) -> MJD:
    return add_sec(t, TT_MINUS_TAI)


def tt_to_tai(t: MJD) -> MJD:
    return add_sec(t, -TT_MINUS_TAI)


def utc_to_tt(t: MJD) -> MJD:
    return tai_to_tt(utc_to_tai(t))


def tt_to_tdb(t: MJD) -> MJD:
    """Geocentric TT→TDB via the FB90 series (see pint_tpu.tdbseries)."""
    from pint_tpu import tdbseries

    return add_sec(t, tdbseries.tdb_minus_tt(_tt_julian_millennia(t)))


def tdb_to_tt(t: MJD) -> MJD:
    from pint_tpu import tdbseries

    # series argument in TDB instead of TT differs at the 1e-12 s level
    return add_sec(t, -tdbseries.tdb_minus_tt(_tt_julian_millennia(t)))


def _tt_julian_millennia(t: MJD):
    """Julian millennia since J2000.0 for series arguments (f64 is plenty)."""
    return ((np.asarray(t.day, np.float64) - 51544.0) + (t.frac - 0.5)) / 365250.0


def utc_to_tdb(t: MJD) -> MJD:
    return tt_to_tdb(utc_to_tt(t))
