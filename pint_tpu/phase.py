"""Exact pulse phase as (integer, fractional) pairs.

Equivalent of the reference's ``Phase`` namedtuple (`src/pint/phase.py:7`),
re-done for JAX: the integer part is stored as an *exact-integer-valued*
float64 (exact up to 2^53 ≈ 9e15 cycles — pulsar phases are ≲1e12) and the
fractional part is float64 in [-0.5, 0.5).  Arithmetic re-normalizes so the
fraction never loses precision to the large integer part.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from pint_tpu import dd as ddm
from pint_tpu.dd import DD


class Phase(NamedTuple):
    """Pulse phase split as int + frac, frac in [-0.5, 0.5)."""

    int: jnp.ndarray
    frac: jnp.ndarray

    def __add__(self, other):
        other = _as_phase(other)
        return _normalize(self.int + other.int, self.frac + other.frac)

    def __sub__(self, other):
        other = _as_phase(other)
        return _normalize(self.int - other.int, self.frac - other.frac)

    def __neg__(self):
        return Phase(-self.int, -self.frac)

    @property
    def quantity(self):
        return self.int + self.frac

    def to_dd(self) -> DD:
        return ddm.sum_ff(self.int, self.frac)


def _as_phase(x) -> "Phase":
    if isinstance(x, Phase):
        return x
    return from_float(x)


def _normalize(i, f):
    i = jnp.asarray(i, jnp.float64)
    f = jnp.asarray(f, jnp.float64)
    k = jnp.round(f)
    return Phase(i + k, f - k)


def from_float(x) -> Phase:
    """Split a float64 phase into (int, frac)."""
    x = jnp.asarray(x, jnp.float64)
    i = jnp.round(x)
    return Phase(i, x - i)


def from_dd(x: DD) -> Phase:
    """Split a double-double phase into (int, frac) with frac error ~1e-32."""
    n, r = ddm.round_nearest(x)
    return Phase(n, ddm.to_float(r))


def zeros(shape=()) -> Phase:
    z = jnp.zeros(shape, jnp.float64)
    return Phase(z, z)
