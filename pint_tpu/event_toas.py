"""Photon-event TOAs from mission FITS event files.

Reference: `event_toas.py` (`/root/reference/src/pint/event_toas.py:245-560`),
which reads NICER/NuSTAR/XMM/Fermi/... event lists through astropy.  Here
the from-scratch FITS reader (:mod:`pint_tpu.fitsio`) supplies the EVENTS
binary table, and event epochs become ordinary :class:`~pint_tpu.toa.TOAs`:

* event time [s] -> MJD via MJDREF(I/F) + TIMEZERO, exactly in two-part
  arithmetic (the second-scale TIME column keeps ns precision that a
  single f64 MJD would lose);
* TIMESYS/TIMEREF decide the observatory: barycentered (TDB/SOLARSYSTEM)
  events map to the ``@`` pseudo-site and pass through time scales
  untouched; geocentric TT events map to the geocenter with TT->UTC
  undone host-side.  Spacecraft-frame (LOCAL) events need orbit files
  and are rejected with guidance, matching the reference's
  barycenter-first workflow for non-orbit-aware use.

Photon TOAs carry zero uncertainty and optional ``-energy`` / template
``-weight`` flags (reference `get_fits_TOAs`, ibid:315-454).
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import numpy as np

from pint_tpu import mjd as mjdmod
from pint_tpu.fitsio import read_fits
from pint_tpu.toa import TOAs

__all__ = ["load_event_TOAs", "load_fits_TOAs", "get_event_TOAs",
           "get_Fermi_TOAs", "calc_lat_weights", "load_FPorbit",
           "get_satellite_observatory"]

#: missions whose event files this loader understands (reference keeps a
#: HEASOFT-derived mission db, `event_toas.py:75-168`)
KNOWN_MISSIONS = ("NICER", "NUSTAR", "XMM", "RXTE", "SWIFT", "IXPE",
                  "CHANDRA", "AXAF", "GLAST", "FERMI")


def _mjdref(header) -> tuple:
    if "MJDREFI" in header:
        return int(header["MJDREFI"]), float(header.get("MJDREFF", 0.0))
    if "MJDREF" in header:
        v = header["MJDREF"]
        if isinstance(v, str):  # some missions write it as a string
            v = float(v)
        return int(np.floor(v)), float(v - np.floor(v))
    raise ValueError("event file has no MJDREF/MJDREFI keyword")


def load_fits_TOAs(eventfile: str, extname: str = "EVENTS",
                   timecolumn: str = "TIME",
                   weightcolumn: Optional[str] = None,
                   minmjd: float = -np.inf,
                   maxmjd: float = np.inf,
                   obs: Optional[str] = None,
                   extra_columns: Sequence[str] = ()) -> TOAs:
    """Load photon TOAs from a FITS event file (reference
    `load_fits_TOAs`, `/root/reference/src/pint/event_toas.py:245`).

    ``obs``: a registered observatory name for spacecraft-frame
    (TIMEREF=LOCAL) events — typically a :class:`SatelliteObs` created
    by :func:`get_satellite_observatory` from the mission orbit file
    (reference `photonphase --orbfile`)."""
    hdus = read_fits(eventfile)
    ev = None
    for h in hdus:
        if h.name.upper() == extname.upper() and timecolumn in h:
            ev = h
            break
    if ev is None and extname == "EVENTS":
        # mission-specific extension names (XTE_SE, SC_DATA, ...): with
        # the DEFAULT extname, fall back to the first binary table with
        # a time column, as the reference does (get_fits_TOAs
        # extension=1, `/root/reference/src/pint/event_toas.py:300`).
        # An explicitly requested extname still errors when absent.
        for h in hdus:
            if timecolumn in h and h.name.upper() != "GTI":
                ev = h
                break
    if ev is None:
        raise ValueError(f"no {extname} binary table with a {timecolumn} "
                         f"column in {eventfile}")
    hdr = ev.header
    telescope = str(hdr.get("TELESCOP", "unknown")).strip().upper()
    if telescope not in KNOWN_MISSIONS:
        warnings.warn(f"unrecognized TELESCOP {telescope!r}; proceeding "
                      "with generic FITS timing keywords")
    timesys = str(hdr.get("TIMESYS", "TT")).strip().upper()
    timeref = str(hdr.get("TIMEREF", "LOCAL")).strip().upper()
    day0, frac0 = _mjdref(hdr)
    tz = float(hdr.get("TIMEZERO", 0.0))

    t_sec = np.asarray(ev[timecolumn], np.float64) + tz
    # min/max MJD select on the file's OWN time scale, BEFORE any
    # scale conversion (reference read_fits_event_mjds + mask,
    # `/root/reference/src/pint/event_toas.py:414`): a TT->UTC shift
    # would otherwise move the window by ~67 s
    mjd_raw = day0 + frac0 + t_sec / 86400.0
    keep = (mjd_raw >= minmjd) & (mjd_raw <= maxmjd)
    if not keep.any():
        raise ValueError("no events inside [minmjd, maxmjd]")
    t_sec = t_sec[keep]
    # two-part epoch: integer days from the seconds column, fraction exact
    day = day0 + np.floor(t_sec / 86400.0).astype(np.int64)
    frac = frac0 + (t_sec - np.floor(t_sec / 86400.0) * 86400.0) / 86400.0
    times = mjdmod.normalize(day, frac)

    if timesys == "TDB" or timeref in ("SOLARSYSTEM", "BARYCENTER"):
        obs = "barycenter"
        if timesys != "TDB":
            raise ValueError(
                f"barycentered events must be TIMESYS=TDB, got {timesys}")
    elif timeref == "GEOCENTRIC":
        obs = "geocenter"
        if timesys == "TT":
            # our TOA epochs are site UTC; undo TT host-side (exact)
            times = mjdmod.tai_to_utc(mjdmod.tt_to_tai(times))
        elif timesys != "UTC":
            raise ValueError(f"unsupported TIMESYS {timesys} for "
                             "geocentric events")
    elif obs is not None:
        # spacecraft-frame events with an orbit-backed observatory:
        # event TIME is mission elapsed TT at the spacecraft; our TOA
        # epochs are site UTC, so undo TT host-side (exact), as for
        # the geocenter (the satellite has no ground clock chain)
        if timesys == "TT":
            times = mjdmod.tai_to_utc(mjdmod.tt_to_tai(times))
        elif timesys != "UTC":
            raise ValueError(f"unsupported TIMESYS {timesys} for "
                             "spacecraft-frame events")
    else:
        raise ValueError(
            f"events are in the spacecraft frame (TIMEREF={timeref}); "
            "pass obs=<satellite observatory> (see "
            "get_satellite_observatory) or barycenter them first "
            "(e.g. barycorr)")

    weights = None
    if weightcolumn is not None:
        weights = np.asarray(ev[weightcolumn], np.float64)[keep]
    energies = np.asarray(ev["PI"], np.float64)[keep] if "PI" in ev \
        else None

    out = TOAs.from_columns(times, 0.0, np.inf, obs, filename=eventfile)
    # per-photon columns stay vectorized (a dict-of-strings per photon
    # would cost minutes + GBs at 1e7 events); TOAs.select carries them
    out.energies = energies
    out.weights = weights
    # photon events carry NO per-TOA uncertainty by construction (the
    # zero error above feeds unbinned template likelihoods, never a
    # whitened solve) — exempt them from the TOABatch validation
    # policy, which would otherwise reject the zeros
    out.is_photon_events = True
    out.extra = {c: np.asarray(ev[c], np.float64)[keep]
                 for c in extra_columns if c in ev}
    return out


def load_event_TOAs(eventfile: str, mission: str = "",
                    **kw) -> TOAs:
    """Mission-flavored entry point (reference `load_event_TOAs`,
    ibid:455); the mission name is informational here — all supported
    missions share the generic FITS timing keywords."""
    return load_fits_TOAs(eventfile, **kw)


def get_event_TOAs(eventfile: str, ephem: str = "DE421",
                   planets: bool = False, **kw) -> TOAs:
    """Load + run the TOA preparation pipeline (reference
    `get_event_TOAs`, ibid:519)."""
    toas = load_event_TOAs(eventfile, **kw)
    toas.apply_clock_corrections()
    toas.compute_TDBs(ephem=ephem)
    toas.compute_posvels(ephem=ephem, planets=planets)
    return toas


def calc_lat_weights(energies_mev, angsep_deg, logeref: float = 4.1,
                     logesig: float = 0.5):
    """Fermi-LAT photon weights from the energy-dependent PSF alone
    (no spectral model) — the physics of Philippe Bruel's
    SearchPulsation weighting (reference `calc_lat_weights`,
    `/root/reference/src/pint/fermi_toas.py:20-67`): a King-like PSF
    footprint ``(1 + th^2 / (2 g s(E)^2))^-g`` times a log-normal
    energy prior centred on ``logeref``.

    Parameters: photon energies [MeV], angular separations from the
    target [deg]; returns per-photon target probabilities in [0, 1].
    """
    energies = np.asarray(energies_mev, np.float64)
    th = np.asarray(angsep_deg, np.float64)
    # PSF shape constants from the SearchPulsation optimization
    psfpar0, psfpar1, psfpar2 = 5.445, 0.848, 0.084
    gam, scalepsf = 2.0, 3.0
    logE = np.log10(energies)
    sigma = np.sqrt(psfpar0**2 * (100.0 / energies) ** (2.0 * psfpar1)
                    + psfpar2**2) / scalepsf
    fgeom = (1.0 + th * th / (2.0 * gam * sigma * sigma)) ** -gam
    return fgeom * np.exp(-(((logE - logeref) / np.sqrt(2.0) / logesig)
                            ** 2))


def _angsep_deg(ra1, dec1, ra2, dec2):
    """Great-circle separation [deg] (Vincenty form, stable at all
    separations)."""
    r1, d1, r2, d2 = map(np.deg2rad, (ra1, dec1, ra2, dec2))
    dl = r2 - r1
    num = np.hypot(np.cos(d2) * np.sin(dl),
                   np.cos(d1) * np.sin(d2)
                   - np.sin(d1) * np.cos(d2) * np.cos(dl))
    den = np.sin(d1) * np.sin(d2) + np.cos(d1) * np.cos(d2) * np.cos(dl)
    return np.rad2deg(np.arctan2(num, den))


def get_Fermi_TOAs(ft1name: str, weightcolumn: Optional[str] = None,
                   targetcoord=None, logeref: float = 4.1,
                   logesig: float = 0.5, minweight: float = 0.0,
                   minmjd: float = -np.inf, maxmjd: float = np.inf,
                   ephem: str = "DE421", planets: bool = False,
                   obs: Optional[str] = None) -> TOAs:
    """Load Fermi FT1 photons, with optional PSF-computed weights
    (reference `get_Fermi_TOAs`,
    `/root/reference/src/pint/fermi_toas.py:113`: weightcolumn='CALC'
    computes SearchPulsation weights from ENERGY + angular separation
    to ``targetcoord`` = (ra_deg, dec_deg))."""
    calc = weightcolumn is not None and weightcolumn.upper() == "CALC"
    toas = load_fits_TOAs(
        ft1name, weightcolumn=None if calc else weightcolumn,
        minmjd=minmjd, maxmjd=maxmjd, obs=obs,
        # the photon columns are only needed for CALC weights; at 1e7
        # photons they are ~240 MB of dead arrays otherwise
        extra_columns=("ENERGY", "RA", "DEC") if calc else ())
    if calc:
        if targetcoord is None:
            raise ValueError("weightcolumn='CALC' needs targetcoord="
                             "(ra_deg, dec_deg)")
        ex = toas.extra
        if any(c not in ex for c in ("ENERGY", "RA", "DEC")):
            raise ValueError("FT1 file lacks ENERGY/RA/DEC columns "
                             "needed for CALC weights")
        sep = _angsep_deg(ex["RA"], ex["DEC"], targetcoord[0],
                          targetcoord[1])
        toas.weights = calc_lat_weights(ex["ENERGY"], sep,
                                        logeref=logeref,
                                        logesig=logesig)
    if toas.weights is not None and minweight > 0.0:
        # select carries the photon columns (weights/energies/extra)
        toas = toas.select(toas.weights >= minweight)
    toas.apply_clock_corrections()
    toas.compute_TDBs(ephem=ephem)
    toas.compute_posvels(ephem=ephem, planets=planets)
    return toas


def load_FPorbit(orbit_filename: str):
    """Parse a satellite orbit FITS file into ``(mjd_tt, pos_m,
    vel_ms)`` arrays.  Handles both FPorbit-style tables
    (NICER/RXTE: TIME + X/Y/Z [+VX/VY/VZ] columns; reference
    `load_FPorbit`, `/root/reference/src/pint/observatory/
    satellite_obs.py:87`) and Fermi FT2 spacecraft files (START +
    SC_POSITION 3-vector [m] ECI; reference `load_FT2`, ibid:25-85)."""
    hdus = read_fits(orbit_filename)
    orb = kind = None
    for h in hdus:
        if "X" in h and "TIME" in h:
            orb, kind = h, "fporbit"
            break
        if "SC_POSITION" in h and "START" in h:
            orb, kind = h, "ft2"
            break
    if orb is None:
        raise ValueError(f"no orbit table (TIME/X/Y/Z or "
                         f"START/SC_POSITION) in {orbit_filename}")
    hdr = orb.header
    timesys = str(hdr.get("TIMESYS", "TT")).strip().upper()
    if timesys != "TT":
        warnings.warn(f"orbit file TIMESYS={timesys}; treating as TT")
    day0, frac0 = _mjdref(hdr)
    tz = float(hdr.get("TIMEZERO", 0.0))
    tcol = "TIME" if kind == "fporbit" else "START"
    t_sec = np.asarray(orb[tcol], np.float64) + tz
    mjd_tt = day0 + frac0 + t_sec / 86400.0
    if kind == "fporbit":
        pos = np.stack([np.asarray(orb[c], np.float64)
                        for c in ("X", "Y", "Z")], axis=-1)
    else:
        pos = np.asarray(orb["SC_POSITION"], np.float64).reshape(-1, 3)
    # sort FIRST: differentiation needs monotonic time
    order = np.argsort(mjd_tt)
    mjd_tt, t_sec, pos = mjd_tt[order], t_sec[order], pos[order]
    if kind == "fporbit" and "VX" in orb:
        vel = np.stack([np.asarray(orb[c], np.float64)
                        for c in ("VX", "VY", "VZ")], axis=-1)[order]
    elif kind == "ft2" and "SC_VELOCITY" in orb:
        vel = np.asarray(orb["SC_VELOCITY"],
                         np.float64).reshape(-1, 3)[order]
    else:
        # central differences; matches the reference fallback for FT2
        # files without velocity columns (satellite_obs.py:60-70)
        vel = np.gradient(pos, t_sec, axis=0)
    return mjd_tt, pos, vel


def get_satellite_observatory(name: str, orbit_filename: str,
                              overwrite: bool = True):
    """Create + register a SatelliteObs from an orbit file (reference
    `get_satellite_observatory`, `satellite_obs.py:500`)."""
    from pint_tpu.observatory import SatelliteObs, register

    mjd_tt, pos, vel = load_FPorbit(orbit_filename)
    obs = SatelliteObs(name, mjd_tt, pos, vel)
    register(obs, overwrite=overwrite)
    return obs
