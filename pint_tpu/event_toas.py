"""Photon-event TOAs from mission FITS event files.

Reference: `event_toas.py` (`/root/reference/src/pint/event_toas.py:245-560`),
which reads NICER/NuSTAR/XMM/Fermi/... event lists through astropy.  Here
the from-scratch FITS reader (:mod:`pint_tpu.fitsio`) supplies the EVENTS
binary table, and event epochs become ordinary :class:`~pint_tpu.toa.TOAs`:

* event time [s] -> MJD via MJDREF(I/F) + TIMEZERO, exactly in two-part
  arithmetic (the second-scale TIME column keeps ns precision that a
  single f64 MJD would lose);
* TIMESYS/TIMEREF decide the observatory: barycentered (TDB/SOLARSYSTEM)
  events map to the ``@`` pseudo-site and pass through time scales
  untouched; geocentric TT events map to the geocenter with TT->UTC
  undone host-side.  Spacecraft-frame (LOCAL) events need orbit files
  and are rejected with guidance, matching the reference's
  barycenter-first workflow for non-orbit-aware use.

Photon TOAs carry zero uncertainty and optional ``-energy`` / template
``-weight`` flags (reference `get_fits_TOAs`, ibid:315-454).
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import numpy as np

from pint_tpu import mjd as mjdmod
from pint_tpu.fitsio import read_fits
from pint_tpu.toa import TOAs

__all__ = ["load_event_TOAs", "load_fits_TOAs", "get_event_TOAs",
           "load_FPorbit", "get_satellite_observatory"]

#: missions whose event files this loader understands (reference keeps a
#: HEASOFT-derived mission db, `event_toas.py:75-168`)
KNOWN_MISSIONS = ("NICER", "NUSTAR", "XMM", "RXTE", "SWIFT", "IXPE",
                  "CHANDRA", "AXAF", "GLAST", "FERMI")


def _mjdref(header) -> tuple:
    if "MJDREFI" in header:
        return int(header["MJDREFI"]), float(header.get("MJDREFF", 0.0))
    if "MJDREF" in header:
        v = header["MJDREF"]
        if isinstance(v, str):  # some missions write it as a string
            v = float(v)
        return int(np.floor(v)), float(v - np.floor(v))
    raise ValueError("event file has no MJDREF/MJDREFI keyword")


def load_fits_TOAs(eventfile: str, extname: str = "EVENTS",
                   timecolumn: str = "TIME",
                   weightcolumn: Optional[str] = None,
                   minmjd: float = -np.inf,
                   maxmjd: float = np.inf) -> TOAs:
    """Load photon TOAs from a FITS event file (reference
    `load_fits_TOAs`, `/root/reference/src/pint/event_toas.py:245`)."""
    hdus = read_fits(eventfile)
    ev = None
    for h in hdus:
        if h.name.upper() == extname.upper() and timecolumn in h:
            ev = h
            break
    if ev is None:
        raise ValueError(f"no {extname} binary table with a {timecolumn} "
                         f"column in {eventfile}")
    hdr = ev.header
    telescope = str(hdr.get("TELESCOP", "unknown")).strip().upper()
    if telescope not in KNOWN_MISSIONS:
        warnings.warn(f"unrecognized TELESCOP {telescope!r}; proceeding "
                      "with generic FITS timing keywords")
    timesys = str(hdr.get("TIMESYS", "TT")).strip().upper()
    timeref = str(hdr.get("TIMEREF", "LOCAL")).strip().upper()
    day0, frac0 = _mjdref(hdr)
    tz = float(hdr.get("TIMEZERO", 0.0))

    t_sec = np.asarray(ev[timecolumn], np.float64) + tz
    # two-part epoch: integer days from the seconds column, fraction exact
    day = day0 + np.floor(t_sec / 86400.0).astype(np.int64)
    frac = frac0 + (t_sec - np.floor(t_sec / 86400.0) * 86400.0) / 86400.0
    times = mjdmod.normalize(day, frac)

    if timesys == "TDB" or timeref in ("SOLARSYSTEM", "BARYCENTER"):
        obs = "barycenter"
        if timesys != "TDB":
            raise ValueError(
                f"barycentered events must be TIMESYS=TDB, got {timesys}")
    elif timeref == "GEOCENTRIC":
        obs = "geocenter"
        if timesys == "TT":
            # our TOA epochs are site UTC; undo TT host-side (exact)
            times = mjdmod.tai_to_utc(mjdmod.tt_to_tai(times))
        elif timesys != "UTC":
            raise ValueError(f"unsupported TIMESYS {timesys} for "
                             "geocentric events")
    else:
        raise ValueError(
            f"events are in the spacecraft frame (TIMEREF={timeref}); "
            "barycenter them first (e.g. barycorr) — orbit-file support "
            "needs a mission orbit reader")

    weights = None
    if weightcolumn is not None:
        weights = np.asarray(ev[weightcolumn], np.float64)
    energies = np.asarray(ev["PI"], np.float64) if "PI" in ev else None

    mask = (times.mjd_float >= minmjd) & (times.mjd_float <= maxmjd)
    idx = np.flatnonzero(mask)
    if len(idx) == 0:
        raise ValueError("no events inside [minmjd, maxmjd]")
    sel = mjdmod.MJD(np.asarray(times.day)[idx], np.asarray(times.frac)[idx])
    out = TOAs.from_columns(sel, 0.0, np.inf, obs, filename=eventfile)
    # per-photon columns stay vectorized (a dict-of-strings per photon
    # would cost minutes + GBs at 1e7 events); TOAs.select carries them
    out.energies = None if energies is None else energies[idx]
    out.weights = None if weights is None else weights[idx]
    return out


def load_event_TOAs(eventfile: str, mission: str = "",
                    **kw) -> TOAs:
    """Mission-flavored entry point (reference `load_event_TOAs`,
    ibid:455); the mission name is informational here — all supported
    missions share the generic FITS timing keywords."""
    return load_fits_TOAs(eventfile, **kw)


def get_event_TOAs(eventfile: str, ephem: str = "DE421",
                   planets: bool = False, **kw) -> TOAs:
    """Load + run the TOA preparation pipeline (reference
    `get_event_TOAs`, ibid:519)."""
    toas = load_event_TOAs(eventfile, **kw)
    toas.apply_clock_corrections()
    toas.compute_TDBs(ephem=ephem)
    toas.compute_posvels(ephem=ephem, planets=planets)
    return toas


def load_FPorbit(orbit_filename: str):
    """Parse an FPorbit-style FITS orbit file (NICER/RXTE) into
    ``(mjd_tt, pos_m, vel_ms)`` arrays (reference `load_FPorbit`,
    `/root/reference/src/pint/observatory/satellite_obs.py:87`)."""
    hdus = read_fits(orbit_filename)
    orb = None
    for h in hdus:
        if "X" in h and "TIME" in h:
            orb = h
            break
    if orb is None:
        raise ValueError(f"no orbit table (TIME/X/Y/Z) in {orbit_filename}")
    hdr = orb.header
    timesys = str(hdr.get("TIMESYS", "TT")).strip().upper()
    if timesys != "TT":
        warnings.warn(f"orbit file TIMESYS={timesys}; treating as TT")
    day0, frac0 = _mjdref(hdr)
    tz = float(hdr.get("TIMEZERO", 0.0))
    t_sec = np.asarray(orb["TIME"], np.float64) + tz
    mjd_tt = day0 + frac0 + t_sec / 86400.0
    pos = np.stack([np.asarray(orb[c], np.float64)
                    for c in ("X", "Y", "Z")], axis=-1)
    # sort FIRST: differentiation needs monotonic time
    order = np.argsort(mjd_tt)
    mjd_tt, t_sec, pos = mjd_tt[order], t_sec[order], pos[order]
    if "VX" in orb:
        vel = np.stack([np.asarray(orb[c], np.float64)
                        for c in ("VX", "VY", "VZ")], axis=-1)[order]
    else:
        # central differences; matches the reference fallback for FT2
        # files without velocity columns (satellite_obs.py:60-70)
        vel = np.gradient(pos, t_sec, axis=0)
    return mjd_tt, pos, vel


def get_satellite_observatory(name: str, orbit_filename: str,
                              overwrite: bool = True):
    """Create + register a SatelliteObs from an orbit file (reference
    `get_satellite_observatory`, `satellite_obs.py:500`)."""
    from pint_tpu.observatory import SatelliteObs, register

    mjd_tt, pos, vel = load_FPorbit(orbit_filename)
    obs = SatelliteObs(name, mjd_tt, pos, vel)
    register(obs, overwrite=overwrite)
    return obs
