"""Simulation of fake TOAs ("zima"): the framework's no-hardware test
backbone, as in the reference (`/root/reference/src/pint/simulation.py`).

`make_fake_toas_uniform` synthesizes arrival times from a model by the
reference's `zero_residuals` iteration (`simulation.py:30`): start from a
uniform grid, evaluate model residuals with "nearest" tracking and no mean
subtraction, shift the TOAs by -residual, repeat until |residual| < tol —
the resulting arrival times are exactly on integer model phases.  Optional
white measurement noise is then added.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu import mjd as mjdmod
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import build_resid_fn
from pint_tpu.toa import TOAs, get_TOAs_array

__all__ = ["zero_residuals", "make_fake_toas_uniform", "make_fake_toas_fromtim",
           "update_fake_toa_errors", "add_wideband_dm_data",
           "add_correlated_noise", "calculate_random_models"]


def zero_residuals(toas: TOAs, model: TimingModel, maxiter: int = 10,
                   tol_us: float = 1e-4) -> TOAs:
    """Iteratively shift TOAs onto integer model phases (reference
    `zero_residuals`, `/root/reference/src/pint/simulation.py:30`)."""
    f0 = float(model.F0.value)
    if model.tzr_batch is None and "AbsPhase" in model.components:
        model.attach_tzr(toas)
    for it in range(maxiter):
        batch = toas.to_batch()
        fn = build_resid_fn(model, batch, "nearest", False, False)
        p = model.build_pdict(toas, tzr_toas=model.make_tzr_toas_or_none())
        r_sec = np.asarray(fn(p)) / f0
        if np.max(np.abs(r_sec)) < tol_us * 1e-6:
            return toas
        toas.utc = mjdmod.add_sec(toas.utc, -r_sec)
        toas.compute_TDBs(ephem=toas.ephem)
        toas.compute_posvels(ephem=toas.ephem, planets=toas.planets)
    raise RuntimeError(
        f"zero_residuals did not converge below {tol_us} us in {maxiter} "
        f"iterations (last max {np.max(np.abs(r_sec))*1e6:.3g} us)")


def make_fake_toas_uniform(startMJD: float, endMJD: float, ntoas: int,
                           model: TimingModel, obs: str = "gbt",
                           error_us: float = 1.0, freq_mhz=1400.0,
                           fuzz_days: float = 0.0,
                           add_noise: bool = False,
                           ephem: Optional[str] = None,
                           planets: Optional[bool] = None,
                           seed: Optional[int] = None) -> TOAs:
    """Uniformly spaced synthetic TOAs that the model predicts perfectly
    (reference `make_fake_toas_uniform`,
    `/root/reference/src/pint/simulation.py:208`)."""
    rng = np.random.default_rng(seed)
    times = np.linspace(startMJD, endMJD, ntoas)
    if fuzz_days:
        times = times + rng.uniform(-fuzz_days, fuzz_days, ntoas)
    ephem = ephem or (model.EPHEM.value or "DE421")
    if planets is None:
        planets = bool(model.PLANET_SHAPIRO.value) \
            if "PLANET_SHAPIRO" in model else False
    freqs = np.broadcast_to(np.asarray(freq_mhz, np.float64), (ntoas,))
    toas = get_TOAs_array(times, obs=obs, errors_us=error_us,
                          freqs_mhz=freqs, ephem=ephem, planets=planets)
    toas = zero_residuals(toas, model)
    if add_noise:
        sigma_us = np.asarray(toas.error_us)
        if model.noise_components:
            # EFAC/EQUAD-scaled white noise, as the reference simulates
            # (`simulation.py:126` uses scaled_toa_uncertainty)
            from pint_tpu.residuals import Residuals

            sigma_us = Residuals(toas, model).get_data_error()
        noise = rng.standard_normal(ntoas) * sigma_us * 1e-6
        toas.utc = mjdmod.add_sec(toas.utc, noise)
        toas.compute_TDBs(ephem=ephem)
        toas.compute_posvels(ephem=ephem, planets=planets)
    for f in toas.flags:
        f.setdefault("simulated", "1")
    return toas


def make_fake_toas_fromtim(timfile, model: TimingModel,
                           add_noise: bool = False,
                           seed: Optional[int] = None) -> TOAs:
    """Replace the TOAs of an existing tim file with model-perfect ones
    (reference `make_fake_toas_fromtim`, `simulation.py:477`)."""
    from pint_tpu.toa import get_TOAs

    rng = np.random.default_rng(seed)
    toas = get_TOAs(timfile, model=model)
    toas = zero_residuals(toas, model)
    if add_noise:
        noise = rng.standard_normal(toas.ntoas) * toas.error_us * 1e-6
        toas.utc = mjdmod.add_sec(toas.utc, noise)
        toas.compute_TDBs(ephem=toas.ephem)
        toas.compute_posvels(ephem=toas.ephem, planets=toas.planets)
    return toas


def add_correlated_noise(toas: TOAs, model: TimingModel,
                         seed: Optional[int] = None) -> TOAs:
    """Shift TOAs by one realization of the model's correlated noise
    (ECORR epochs, red-noise Fourier modes): delay = U @ (sqrt(phi) * z)
    with z ~ N(0, I) (reference `make_fake_toas(..., add_correlated_noise=
    True)`, `/root/reference/src/pint/simulation.py:126-170`)."""
    import numpy as _np

    from pint_tpu.residuals import Residuals

    if not model.has_correlated_errors:
        raise ValueError("model has no correlated noise components")
    rng = np.random.default_rng(seed)
    r = Residuals(toas, model)
    U = _np.asarray(model.noise_basis(r.pdict))
    phi = _np.asarray(model.noise_weights(r.pdict))
    z = rng.standard_normal(U.shape[1])
    delay_sec = U @ (np.sqrt(np.maximum(phi, 0.0)) * z)
    toas.utc = mjdmod.add_sec(toas.utc, delay_sec)
    toas.compute_TDBs(ephem=toas.ephem)
    toas.compute_posvels(ephem=toas.ephem, planets=toas.planets)
    return toas


def calculate_random_models(fitter, toas: TOAs, Nmodels: int = 100,
                            seed: Optional[int] = None,
                            return_time: bool = False):
    """Phase (or time) deviations of ``Nmodels`` parameter vectors drawn
    from the fit covariance, evaluated at ``toas`` (reference
    `calculate_random_models`, `/root/reference/src/pint/simulation.py:524`,
    there a python loop over deep-copied models; here ONE `jax.vmap` of
    the jitted residual function over the draw matrix).

    Returns ``(dphase, draws)``: dphase shape (Nmodels, ntoas) in cycles
    (seconds if ``return_time``); draws shape (Nmodels, nfree) are the
    sampled parameter offsets in device units.
    """
    import jax
    import jax.numpy as jnp

    from pint_tpu.fitter import build_resid_sec_fn
    from pint_tpu.residuals import Residuals

    model = fitter.model
    names = fitter.covariance_params or fitter.fit_params
    C = np.asarray(fitter.parameter_covariance_matrix)[
        :len(names), :len(names)]
    # range-safe draw: factor the correlation on the (IEEE f64) host,
    # scale columns afterwards
    s = np.sqrt(np.diag(C))
    L = np.linalg.cholesky(C / np.outer(s, s) +
                           1e-12 * np.eye(len(names)))
    rng = np.random.default_rng(seed)
    draws = (rng.standard_normal((Nmodels, len(names))) @ L.T) * s[None, :]

    r = Residuals(toas, model, track_mode=fitter.track_mode)
    resid_sec = build_resid_sec_fn(model, r.batch, names, r.track_mode)
    p = r.pdict

    w = 1.0 / jnp.asarray(toas.error_us) ** 2

    @jax.jit
    def dev(xs):
        base = resid_sec(jnp.zeros(len(names)), p)

        def one(x):
            d = resid_sec(x, p) - base
            # profile out the constant phase offset, as the fit does —
            # the covariance describes offset-marginalized scatter
            return d - jnp.sum(d * w) / jnp.sum(w)

        return jax.vmap(one)(xs)

    dt_sec = np.asarray(dev(jnp.asarray(draws)))
    if return_time:
        return dt_sec, draws
    return dt_sec * float(model.F0.value), draws


def add_wideband_dm_data(toas: TOAs, model: TimingModel,
                         dm_error: float = 1e-4,
                         add_noise: bool = False,
                         seed: Optional[int] = None) -> TOAs:
    """Attach simulated wideband DM measurements (``-pp_dm``/``-pp_dme``
    flags) drawn from the model's ``total_dm`` (reference
    `update_fake_dms`, `/root/reference/src/pint/simulation.py:125`)."""
    rng = np.random.default_rng(seed)
    p = model.build_pdict(toas, tzr_toas=model.make_tzr_toas_or_none())
    dm = np.asarray(model.total_dm(p, toas.to_batch()))
    if add_noise:
        dm = dm + rng.standard_normal(toas.ntoas) * dm_error
    for i, f in enumerate(toas.flags):
        f["pp_dm"] = repr(float(dm[i]))
        f["pp_dme"] = repr(float(dm_error))
    return toas


def update_fake_toa_errors(toas: TOAs, error_us) -> TOAs:
    toas.error_us = np.broadcast_to(np.asarray(error_us, np.float64),
                                    (toas.ntoas,)).copy()
    return toas
