"""Fitters: weighted least squares on device, Gauss-Newton with autodiff.

Reference: `WLSFitter` / `DownhillWLSFitter` and the `fit_wls_svd` kernel
(`/root/reference/src/pint/fitter.py:1703,1268,2551`), where >80% of wall
clock is hand-written design-matrix assembly in Python longdouble
(`profiling/README.txt:62-71`).  Here the whole Gauss-Newton iteration is a
single jitted XLA program:

* residuals come from the jit-pure phase pipeline
  (:func:`pint_tpu.residuals.raw_phase_resids`);
* the design matrix is **forward-mode autodiff** (`jax.jacfwd`) of the
  residual function over the free-parameter offset vector — replacing the
  reference's `d_phase_d_param` registry
  (`/root/reference/src/pint/models/timing_model.py:2157-2326`);
* the solve is whiten → column-normalize → factorize → threshold, the
  reference's numerical recipe (`fit_wls_svd`, `fitter.py:2551`;
  `normalize_designmatrix`, `utils.py:2900`), in f64 on device.  On the
  CPU backend the factorization is the reference's SVD; on TPU it is the
  MXU-friendly normal-equations/eigh kernel (:func:`fit_wls_eigh`) with
  identical thresholding semantics — the tall-matrix SVD does not map to
  the systolic array and costs ~5x the rest of the step combined.

Because the step function is pure in the params pytree, grids and ensembles
batch with `jax.vmap` and shard with `shard_map` — the TPU replacement for
the reference's per-point process pool (`gridutils.py:322`).
"""

from __future__ import annotations

import enum
import warnings
from typing import Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import faultinject, profiling, telemetry
from pint_tpu.exceptions import (ConvergenceFailure, DegeneracyWarning,
                                 PintTpuWarning)
from pint_tpu.lint.contracts import dispatch_contract
from pint_tpu.models.timing_model import TimingModel, pv
from pint_tpu.residuals import Residuals, raw_phase_resids
from pint_tpu.toabatch import TOABatch
from pint_tpu.utils import (get_xp, normalize_designmatrix,
                            woodbury_dot, woodbury_dot_split)


def _machine_eps(xp=None) -> float:
    """Effective f64 epsilon of wherever the SOLVE runs: TPU's emulated
    f64 carries ~48 mantissa bits, so degeneracy thresholds tuned to true
    IEEE eps (2^-52) under-cut it and let near-singular directions leak
    huge, chi2-flat parameter steps through the solve.  Host-finished
    solves (xp is numpy) are true-IEEE regardless of the backend — using
    the device eps there would DROP legitimately deep directions (e.g.
    B1855's OM-T0 pair) and collapse their uncertainties."""
    import jax as _jax

    if xp is np:
        return float(np.finfo(np.float64).eps)
    return 2.0 ** -48 if _jax.default_backend() != "cpu" else \
        float(jnp.finfo(jnp.float64).eps)

__all__ = ["Fitter", "WLSFitter", "GLSFitter", "DownhillWLSFitter",
           "DownhillGLSFitter", "PowellFitter", "LMFitter",
           "WidebandTOAFitter", "WidebandDownhillFitter", "WidebandLMFitter",
           "fit_wls_svd", "fit_wls_eigh", "wls_solve", "gls_solve",
           "build_wls_step", "build_gls_step", "build_gls_fullcov_step",
           "build_fused_fit", "FitStatus", "FitSummary",
           "FitDegradedWarning", "sentinel_advance"]


class FitStatus(enum.IntEnum):
    """Terminal state of one fit attempt — computed IN-GRAPH by the
    fused while_loop's convergence sentinel (integer codes survive the
    flat device->host transfer) and mirrored by the eager/LM loops.

    * CONVERGED — consecutive-chi2 tolerance met.
    * MAXITER — iteration budget exhausted with finite, non-diverging
      chi2 (the historical silent outcome, now labeled).
    * DIVERGED — chi2 rose for ``diverge_streak`` consecutive
      iterations, OR produced no new best for ``stall_iters``
      iterations (the period-2 oscillation a consecutive-increase test
      alone misses — e.g. the degenerate 3-frequency/free-DM FD block),
      OR the step/line-search machinery found no acceptable step.
    * NONFINITE — chi2 (or the solver output feeding it) went NaN/inf.

    DIVERGED and NONFINITE trigger the degradation chain in
    ``Fitter._fit_fused`` (fused -> eager stepwise -> damped LM)."""

    CONVERGED = 0
    MAXITER = 1
    DIVERGED = 2
    NONFINITE = 3


#: in-graph sentinel code for "still iterating" (never escapes the loop)
_RUNNING = -1


class FitDegradedWarning(PintTpuWarning):
    """A fit rung failed (DIVERGED/NONFINITE) and the engine is falling
    back to the next rung of the degradation chain."""


def sentinel_advance(x, chi2, prev, best_x, best_chi2, inc_streak,
                     stall_streak, tol_chi2, diverge_streak, stall_iters):
    """One iteration of the in-graph convergence sentinel (ISSUE 3): the
    best-so-far / streak / :class:`FitStatus` bookkeeping shared by the
    fused while_loop body and the fleet bucket programs
    (:mod:`pint_tpu.fleet`), so the two sentinels cannot drift.  ``chi2``
    is the objective at ``x`` BEFORE the step is applied; NaN compares
    False everywhere below, so a non-finite chi2 can neither extend a
    streak nor claim the best slot.  Returns ``(best_x, best_chi2,
    inc_streak, stall_streak, status)`` with ``status`` one of the
    FitStatus codes or ``_RUNNING``."""
    nonfinite = jnp.logical_not(jnp.isfinite(chi2))
    converged = jnp.abs(prev - chi2) < tol_chi2
    inc_streak = jnp.where(chi2 > prev + tol_chi2,
                           inc_streak + 1, jnp.int32(0))
    stall_streak = jnp.where(chi2 < best_chi2 - tol_chi2,
                             jnp.int32(0), stall_streak + 1)
    better = chi2 < best_chi2
    best_x = jnp.where(better, x, best_x)
    best_chi2 = jnp.where(better, chi2, best_chi2)
    diverged = jnp.logical_or(inc_streak >= diverge_streak,
                              stall_streak >= stall_iters)
    status = jnp.where(
        nonfinite, jnp.int32(FitStatus.NONFINITE),
        jnp.where(converged, jnp.int32(FitStatus.CONVERGED),
                  jnp.where(diverged, jnp.int32(FitStatus.DIVERGED),
                            jnp.int32(_RUNNING))))
    return best_x, best_chi2, inc_streak, stall_streak, status


def _whiten_normalize(M, r_sec, sigma_sec):
    """Whiten by sigma and column-normalize in two range-safe stages
    (max-abs first, then the norm of an O(1) matrix): TPU's emulated f64
    carries only the f32 exponent range (~1e±38), and a one-shot
    sum-of-squares norm overflows for stiff columns like F1.  Shared by
    the SVD and eigh kernels so the contract cannot drift between them.
    Works on numpy and jax arrays.  Returns ``(Mn, rw, norms)``."""
    xp = get_xp(M)
    Mw = M / sigma_sec[:, None]
    rw = r_sec / sigma_sec
    cmax = xp.max(xp.abs(Mw), axis=0)
    cmax = xp.where(cmax == 0.0, 1.0, cmax)
    Mc = Mw / cmax
    Mn, nc = normalize_designmatrix(Mc)
    return Mn, rw, cmax * nc


def fit_wls_svd(M, r_sec, sigma_sec, threshold: Optional[float] = None):
    """One linear WLS solve (reference `fit_wls_svd`,
    `/root/reference/src/pint/fitter.py:2551`): whiten → column-normalize →
    SVD → threshold.  Jit-pure.

    M: (N, P) design matrix = -d(resid_sec)/d(param); r_sec: (N,) residuals
    [s]; sigma_sec: (N,) uncertainties [s].  Returns
    ``(dpars, Sigma_n, norms, n_bad)``: the parameter step, the covariance
    of the *normalized* parameters, the column norms, and the number of
    singular values dropped by the degeneracy threshold.  The true
    covariance is ``Sigma_n / outer(norms, norms)`` — deliberately left to
    the (true-IEEE f64) host: TPU's emulated f64 carries only the f32
    exponent range (~1e±38), and both ``norms**2`` for stiff columns like
    F1 (~1e43) and the resulting variances (~1e-42) fall outside it.  For
    the same reason column scaling happens in two range-safe stages
    (max-abs, then the norm of an O(1) matrix) instead of one
    sum-of-squares.
    """
    xp = get_xp(M)
    Mn, rw, norms = _whiten_normalize(M, r_sec, sigma_sec)
    U, S, Vt = xp.linalg.svd(Mn, full_matrices=False)
    if threshold is None:
        threshold = _machine_eps(xp) * max(M.shape)
    bad = S <= threshold * S[0]
    Sinv = xp.where(bad, 0.0, 1.0 / xp.where(bad, 1.0, S))
    dpars = (Vt.T @ (Sinv * (U.T @ rw))) / norms
    Sigma_n = (Vt.T * Sinv**2) @ Vt
    return dpars, Sigma_n, norms, xp.sum(bad)


def fit_wls_eigh(M, r_sec, sigma_sec, threshold: Optional[float] = None):
    """Same contract and thresholding semantics as :func:`fit_wls_svd`,
    solved through the normal equations: ``eigh(Mn^T Mn)`` instead of
    ``svd(Mn)``.

    Rationale: on TPU the tall-matrix SVD runs as a sequential
    one-sided-Jacobi program and costs ~200 ms for a NANOGrav-width
    (12500x87) system — 85% of a whole Gauss-Newton step — while the
    (N,P)x(P,) normal-matrix product rides the MXU and the eigh touches
    only a PxP matrix (~45 ms total measured).  The eigenvalues of
    ``Mn^T Mn`` are the squared singular values of ``Mn``, so the
    degeneracy cutoff below (on ``sqrt(e)`` relative to the largest)
    drops the directions the SVD path drops, in the regime the normal
    equations can resolve.  The one *documented divergence*: eigenvalues
    of ``G`` are only computed to ~eps·||G|| absolute accuracy, so a
    direction whose true relative singular value is below ~sqrt(eps·P)
    comes back as pure rounding noise — keeping it would inject a 1/e ~
    1e14 garbage step with no warning.  The cutoff is therefore
    additionally floored at the eigh noise floor ``eps_eff·e_max·P``;
    equivalently, this kernel treats directions deeper than ~1e-7
    (CPU) / ~6e-7 (TPU) in relative singular value as degenerate where
    the SVD kernel resolves down to ~eps·N.  After the two-stage column
    normalization, real deep degeneracies on NANOGrav-class sets (e.g.
    the OM–T0 correlation on B1855+09) sit at ~1e-5 — two orders above
    the floor (`test_fitter.py::TestEighKernel` pins both sides).  The
    conditioning price of squaring is bounded the same way, and any
    residual solve error only perturbs the Gauss-Newton *step*, which
    the next nonlinear re-evaluation corrects — the converged fit and
    covariance agree with the SVD path to well inside quoted
    uncertainties.
    """
    Mn, rw, norms = _whiten_normalize(M, r_sec, sigma_sec)
    V, einv, n_bad = masked_eigh_inverse(Mn.T @ Mn, threshold, M.shape[0])
    y = Mn.T @ rw
    dpars = (V @ (einv * (V.T @ y))) / norms
    Sigma_n = (V * einv) @ V.T
    return dpars, Sigma_n, norms, n_bad


def masked_eigh_inverse(G, threshold, n_rows):
    """Thresholded eigendecomposition of a unit-normalized normal matrix
    ``G = Mn^T Mn``: the single source of the eigh kernel's degeneracy
    semantics (relative singular-value cutoff + the normal-equations
    noise floor — see :func:`fit_wls_eigh`), shared with the sharded
    psum path (`pint_tpu.parallel`) so the two can never drift.  Returns
    ``(V, einv, n_bad)`` with ``pinv(G) = (V * einv) @ V.T``."""
    xp = get_xp(G)
    e, V = xp.linalg.eigh(G)
    S = xp.sqrt(xp.maximum(e, 0.0))
    if threshold is None:
        threshold = _machine_eps(xp) * max(n_rows, G.shape[0])
    # noise floor of the eigendecomposition itself: below this, e is
    # rounding garbage and 1/e would poison the step
    efloor = _machine_eps(xp) * G.shape[0] * xp.maximum(e[-1], 0.0)
    bad = (S <= threshold * S[-1]) | (e <= efloor)
    einv = xp.where(bad, 0.0, 1.0 / xp.where(bad, 1.0, e))
    return V, einv, xp.sum(bad)


def _eigh_xp(xp, A):
    """eigh for the GLS solve.  For the host (numpy) path this calls the
    XLA:CPU eigendecomposition EAGERLY rather than numpy's LAPACK: the
    B1855-class GLS spectrum is knife-edge at the absolute degeneracy
    cutoff (several physical eigenvalues within implementation-noise of
    eps*P), and using a different eigh implementation than the
    CPU-backend jitted path makes n_bad — and therefore the reported
    deep-direction uncertainties — process-dependent.  Same kernel on
    both paths = same knife-edge decisions."""
    if xp is not np:
        return xp.linalg.eigh(A)
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # JAX_PLATFORMS excludes cpu
        return np.linalg.eigh(np.asarray(A, np.float64))
    with jax.default_device(cpu):
        e, V = jnp.linalg.eigh(jax.device_put(np.asarray(A), cpu))
    return np.asarray(e), np.asarray(V)


def _default_wls_kernel():
    """Backend-matched WLS solve kernel: the true-IEEE CPU backend keeps
    the reference's SVD recipe bit-for-bit; accelerators get the
    MXU-friendly normal-equations/eigh kernel (~4.5x faster per step at
    NANOGrav width, identical thresholding semantics)."""
    return fit_wls_svd if jax.default_backend() == "cpu" else fit_wls_eigh


def build_resid_sec_fn(model: TimingModel, batch: TOABatch,
                       fit_params: Sequence[str], track_mode: str):
    """``(x, p) -> time residuals [s]`` (jit-pure, not mean-subtracted):
    the function whose jacobian is the design matrix."""
    calc = model.calc
    names = list(fit_params)

    def resid_sec(x, p):
        p2 = model.with_x(p, x, names)
        r_cyc = raw_phase_resids(calc, p2, batch, track_mode,
                                 subtract_mean=False, use_weights=False)
        return r_cyc / pv(p2, "F0")

    return resid_sec


#: default source of the ``design_matrix`` knob: "split" caches the
#: linear-parameter design-matrix columns (DMX/JUMP/FD/WaveX...) across
#: Gauss-Newton iterations and differentiates only the nonlinear core;
#: "full" is the original one-jacfwd-over-everything path.
def _resolve_design_matrix(design_matrix: Optional[str]) -> str:
    import os

    if design_matrix is None:
        design_matrix = os.environ.get("PINT_TPU_DESIGN_MATRIX", "split")
    if design_matrix not in ("split", "full"):
        raise ValueError(
            f"design_matrix must be 'split' or 'full', got "
            f"{design_matrix!r}")
    return design_matrix


#: Cached linear-block columns are refreshed when the nonlinear offsets
#: have moved, since the last refresh, by more than this much predicted
#: residual-model drift in SECONDS (sum_k colmax_k * |dx_k| over the
#: nonlinear block — a 1-norm bound on the model change).  Columns drift
#: only at second order through the nonlinear parameters, bounded by
#: ~3e-5 fractional per second of delay drift (the orbital Romer
#: curvature is the largest cross term), so 0.05 s keeps cached columns
#: within ~2e-6 fractional of exact — orders below solve/quoted
#: precision (the Gauss-Newton fixed point shifts only by
#: ~(dJ/J) * sigma ~ 1e-6 sigma).
SPLIT_REFRESH_DRIFT_SEC = 0.05


def _make_assembly(model: TimingModel, names: Sequence[str], combined,
                   sigma_fn, offc_np, design_matrix: Optional[str],
                   aot_fingerprint: str = ""):
    """Shared two-block construction of an ``(x, p) -> (r, M, sigma,
    offc)`` assembly from a residual-rows function ``combined(x, p)``, a
    row-uncertainty function ``sigma_fn(p)`` and a host offset-regressor
    column ``offc_np`` (or None).

    ``design_matrix="split"`` (the default) partitions the free
    parameters via the components' linearity declarations
    (:meth:`TimingModel.partition_linear_params`): the linear block's
    columns (DMX bins, JUMPs, FD terms, WaveX amplitudes...) are
    computed ONCE per (model, batch) by a jacfwd restricted to that
    block, staged to device, and reused across Gauss-Newton iterations
    (and, via ``.lin_cols``/``.inline_with_cols``, across grid points,
    ensemble members and fused-fit loop iterations); only the nonlinear
    core (spin, astrometry, DM polynomial, binary) is re-differentiated
    per step — through ``jax.linearize``, so the primal residual pass is
    shared with the JVPs instead of a separate jit(resid) +
    jit(jacfwd(resid)) pair.  This is the structure the reference
    exploits through its ``d_phase_d_delay * d_delay_d_param`` registry
    (`/root/reference/src/pint/models/timing_model.py:2157`) and that
    Vela.jl's kernels lean on (arxiv 2412.15858), recovered here on top
    of autodiff.  Cached columns are refreshed automatically when the
    nonlinear offsets move enough to matter (``SPLIT_REFRESH_DRIFT_SEC``).

    ``design_matrix="full"``: the original one-jacfwd path.  Also used
    whenever the model declares no linear parameters.

    XLA:CPU pathology note (preserved from the original builder): the
    primal and jacobian chains are compiled as SEPARATE modules when
    called eagerly on the CPU backend with a small (<= 2 column)
    jacobian — a single module holding both chains trips a pathological
    XLA:CPU optimization pass (minutes-to-hours compile) when those
    columns all flow through the quad-single spindown arithmetic.  With
    a >2-column nonlinear block (or on accelerators) the split path
    fuses primal+JVPs into one module via ``jax.linearize``.  Under an
    outer jit/vmap (grids, fused fits) everything inlines into one
    module either way, which has never shown the pathology.

    The returned callable carries attributes: ``.inline`` (trace-safe,
    no caching), ``.lin_cols(x, p)`` (the linear-block columns, exact at
    ``x``; trace-safe), ``.inline_with_cols(x, p, cols)`` (trace-safe
    assembly from pre-computed columns), ``.split`` (bool),
    ``.lin_names``/``.nl_names``, and ``.design_matrix``.
    """
    from pint_tpu import aot
    from pint_tpu.utils import effective_platform

    names = list(names)
    P = len(names)
    design_matrix = _resolve_design_matrix(design_matrix)
    lin_names, nl_names = model.partition_linear_params(names)
    offc_j = None if offc_np is None else jnp.asarray(offc_np)
    # AOT store key for the jitted assembly programs: the caller's
    # model/batch fingerprint (the batch rides these closures as baked
    # constants) + the free-param slots and design-matrix mode
    aot_fp = (f"{aot_fingerprint}|names={','.join(names)}"
              f"|dm={design_matrix}|offc={offc_np is not None}")

    def _append_offset(M):
        if offc_j is None:
            return M, None
        return jnp.concatenate([M, -offc_j[:, None]], axis=1), offc_j

    if design_matrix == "full" or not lin_names:
        def primal(x, p):
            return combined(x, p), sigma_fn(p)

        primal_j = aot.serve("assembly_full_primal", jax.jit(primal),
                             aot_fp)
        jac_j = aot.serve("assembly_full_jac",
                          jax.jit(jax.jacfwd(combined)), aot_fp)

        def assemble_inline(x, p):
            r, sigma = primal_j(x, p)
            M, offc = _append_offset(-jac_j(x, p))
            return r, M, sigma, offc

        def assemble(x, p):
            with profiling.stage("assemble_device"):
                profiling.count("jit_call", 2)
                out = assemble_inline(x, p)
                if profiling.enabled():
                    jax.block_until_ready(
                        [a for a in out if a is not None])
            return out

        assemble.inline = assemble_inline
        assemble.lin_cols = None
        assemble.inline_with_cols = None
        assemble.split = False
        assemble.lin_names, assemble.nl_names = [], names
        assemble.design_matrix = "full"
        return assemble

    # ---------------- split path ----------------
    lin_set = set(lin_names)
    lin_idx = np.asarray([i for i, n in enumerate(names) if n in lin_set],
                         np.int64)
    nl_idx = np.asarray([i for i, n in enumerate(names)
                         if n not in lin_set], np.int64)
    n_nl, n_lin = len(nl_idx), len(lin_idx)

    def resid_parts(x_nl, x_lin, p):
        x = jnp.zeros(P).at[nl_idx].set(x_nl).at[lin_idx].set(x_lin)
        return combined(x, p)

    def lin_cols(x, p):
        """(N, n_lin) linear-block jacobian d(resid)/d(x_lin), EXACT at
        ``x`` (jit/vmap-safe) — the cacheable columns."""
        return jax.jacfwd(resid_parts, argnums=1)(x[nl_idx], x[lin_idx], p)

    # the XLA:CPU small-jacobian compile pathology (see docstring):
    # fuse primal+JVPs only when safe
    share = n_nl > 2 or effective_platform() != "cpu"

    if n_nl and share:
        def nl_block(x, p):
            x_lin = x[lin_idx]
            r, jvp = jax.linearize(
                lambda xn: resid_parts(xn, x_lin, p), x[nl_idx])
            Jnl = jax.vmap(jvp, out_axes=1)(jnp.eye(n_nl))
            return r, Jnl, sigma_fn(p)

        def refresh_fn(x, p):
            cols = lin_cols(x, p)
            Jnl = jax.jacfwd(resid_parts, argnums=0)(
                x[nl_idx], x[lin_idx], p)
            return cols, jnp.max(jnp.abs(Jnl), axis=0)

        refresh_j = aot.serve("assembly_refresh", jax.jit(refresh_fn),
                              aot_fp)
        nl_jit_calls = 1
    else:
        def prim(x, p):
            return combined(x, p), sigma_fn(p)

        prim_j = aot.serve("assembly_primal", jax.jit(prim), aot_fp)
        nl_jac_j = aot.serve(
            "assembly_nljac",
            jax.jit(jax.jacfwd(resid_parts, argnums=0)), aot_fp) \
            if n_nl else None

        def nl_block(x, p):
            r, sigma = prim_j(x, p)
            Jnl = nl_jac_j(x[nl_idx], x[lin_idx], p) if n_nl else \
                jnp.zeros((r.shape[0], 0))
            return r, Jnl, sigma

        lin_cols_j = aot.serve("assembly_lincols", jax.jit(lin_cols),
                               aot_fp)

        def refresh_j(x, p):
            cols = lin_cols_j(x, p)
            s = jnp.max(jnp.abs(nl_jac_j(x[nl_idx], x[lin_idx], p)),
                        axis=0) if n_nl else jnp.zeros(0)
            return cols, s

        nl_jit_calls = 2 if n_nl else 1

    def inline_with_cols(x, p, cols):
        r, Jnl, sigma = nl_block(x, p)
        M = jnp.zeros((r.shape[0], P)) \
            .at[:, nl_idx].set(-Jnl).at[:, lin_idx].set(-cols)
        M, offc = _append_offset(M)
        return r, M, sigma, offc

    def assemble_inline(x, p):
        return inline_with_cols(x, p, lin_cols(x, p))

    # eager path: one jitted program per call (primal + nonlinear JVPs +
    # column scatter) when fused, plus a column refresh only when needed
    asm_cols_j = aot.serve("assembly_cols", jax.jit(inline_with_cols),
                           aot_fp) if share else inline_with_cols

    state: dict = {}

    def _has_tracer(x, p):
        if isinstance(x, jax.core.Tracer):
            return True
        return any(isinstance(leaf, jax.core.Tracer)
                   for leaf in jax.tree_util.tree_leaves(p))

    def assemble(x, p):
        if _has_tracer(x, p):
            # traced context (outer jit/vmap): pure-functional variant;
            # callers that want cross-iteration reuse hoist the columns
            # themselves via .lin_cols/.inline_with_cols
            return assemble_inline(x, p)
        with profiling.stage("assemble_device"):
            x_h = x if isinstance(x, np.ndarray) else np.asarray(x)
            x_nl_h = x_h[nl_idx]
            # columns valid while (a) the params pytree is the same
            # OBJECT (strong ref below — ids cannot recycle) and (b)
            # the nonlinear offsets' predicted model drift stays under
            # the refresh tolerance
            hit = state.get("p") is p
            if hit and n_nl:
                drift = float(np.sum(state["nl_scale"]
                                     * np.abs(x_nl_h - state["x_nl"])))
                hit = drift <= SPLIT_REFRESH_DRIFT_SEC
            if not hit:
                with profiling.stage("assemble.linear_refresh"):
                    profiling.count("assemble.linear_refresh")
                    profiling.count("jit_call")
                    cols, nl_scale = refresh_j(x, p)
                    if profiling.enabled():
                        jax.block_until_ready(cols)
                state.update(p=p, cols=cols, x_nl=x_nl_h.copy(),
                             nl_scale=np.asarray(nl_scale))
            else:
                profiling.count("assemble.linear_cached")
            with profiling.stage("assemble.jacfwd_nonlinear"):
                profiling.count("jit_call", nl_jit_calls)
                out = asm_cols_j(x, p, state["cols"])
                if profiling.enabled():
                    jax.block_until_ready(
                        [a for a in out if a is not None])
        return out

    assemble.inline = assemble_inline
    assemble.lin_cols = lin_cols
    assemble.inline_with_cols = inline_with_cols
    assemble.split = True
    assemble.lin_names, assemble.nl_names = lin_names, nl_names
    assemble.design_matrix = "split"
    return assemble


@dispatch_contract("split_assembly", max_compiles=30, max_dispatches=2,
                   max_transfers=2)
# ddlint: disable=OBS001 returns bare jitted closures consumed per step by the fused/eager drivers — the span lives in their callers (fitter.fused_fit / fitter.degrade)
def build_whitened_assembly(model: TimingModel, batch: TOABatch,
                            fit_params: Sequence[str], track_mode: str,
                            include_offset: bool,
                            design_matrix: Optional[str] = None):
    """``(x, p) -> (r, M, sigma, offc)``: residuals [s], design matrix
    (offset column appended unless the model carries PHOFF), scaled per-TOA
    uncertainties [s], and the offset regressor column (None when the
    offset is not profiled) — the assembly shared by the WLS and GLS
    steps.  ``design_matrix``: "split" (default; cached linear-block
    columns + nonlinear-core jacfwd) or "full" — see
    :func:`_make_assembly` for the split-path design."""
    from pint_tpu import aot

    resid_sec = build_resid_sec_fn(model, batch, list(fit_params),
                                   track_mode)

    def sigma_fn(p):
        return model.scaled_toa_uncertainty(p, batch) * 1e-6

    offc_np = np.ones(batch.ntoas) if include_offset else None
    return _make_assembly(model, list(fit_params), resid_sec, sigma_fn,
                          offc_np, design_matrix,
                          aot_fingerprint=aot.model_fingerprint(
                              model, batch, track_mode, "nb"))


def build_chi2_fn(model: TimingModel, batch: TOABatch,
                  fit_params: Sequence[str], track_mode: str,
                  include_offset: bool):
    """Jitted chi2-only evaluation ``(x, p) -> float`` — no jacobian, no
    factorization; the cheap trial-point metric for Powell/LM."""
    resid_sec = build_resid_sec_fn(model, batch, list(fit_params),
                                   track_mode)

    @jax.jit
    def chi2(x, p):
        r = resid_sec(x, p)
        sigma = model.scaled_toa_uncertainty(p, batch) * 1e-6
        if include_offset:
            w = 1.0 / sigma**2
            r = r - jnp.sum(r * w) / jnp.sum(w)
        return jnp.sum((r / sigma) ** 2)

    return chi2


def build_wideband_chi2_fn(model: TimingModel, batch: TOABatch,
                           dm_index, dm_data, dm_error,
                           fit_params: Sequence[str], track_mode: str,
                           include_offset: bool):
    """Jitted combined TOA+DM chi2 ``(x, p) -> float`` — the wideband
    trial-point metric for Powell/LM (no jacobian)."""
    from pint_tpu.residuals import scaled_dm_sigma_rows

    names = list(fit_params)
    resid_sec = build_resid_sec_fn(model, batch, names, track_mode)
    idx = jnp.asarray(np.asarray(dm_index), dtype=jnp.int64)
    dmv = jnp.asarray(np.asarray(dm_data, np.float64))
    dme = jnp.asarray(np.asarray(dm_error, np.float64))

    @jax.jit
    def chi2(x, p):
        p2 = model.with_x(p, x, names)
        r_t = resid_sec(x, p)
        sigma_t = model.scaled_toa_uncertainty(p, batch) * 1e-6
        if include_offset:
            w = 1.0 / sigma_t**2
            r_t = r_t - jnp.sum(r_t * w) / jnp.sum(w)
        r_dm = dmv - model.total_dm(p2, batch)[idx]
        sigma_dm = scaled_dm_sigma_rows(model, p, batch, idx, dme)
        return jnp.sum((r_t / sigma_t) ** 2) + \
            jnp.sum((r_dm / sigma_dm) ** 2)

    return chi2


@dispatch_contract("wideband_step", max_compiles=40, max_dispatches=3,
                   max_transfers=3)
# ddlint: disable=OBS001 returns bare jitted closures for the per-step hot path — a host span wrapper here would be per-iteration overhead; spanned by the fitter drivers
def build_wideband_assembly(model: TimingModel, batch: TOABatch,
                            dm_index, dm_data, dm_error,
                            fit_params: Sequence[str], track_mode: str,
                            include_offset: bool,
                            design_matrix: Optional[str] = None):
    """The wideband ``(x, p) -> (r, M, sigma, offc)`` assembly (reference
    `WidebandTOAFitter.get_designmatrix` / `pint_matrix.combine_design_matrices_by_quantity`,
    `/root/reference/src/pint/fitter.py:1975`, `pint_matrix.py:532`).

    Rows are ``[TOA residuals [s] ; DM residuals [pc cm^-3]]``; the design
    matrix is forward-mode autodiff of the stacked residual function, so
    the DM block automatically picks up every parameter with a
    ``dm_value`` dependence (DM/DMX/DMJUMP) and the TOA block every
    delay/phase dependence.  The mixed units cancel in the whitened
    solve.  The phase offset regressor covers only the TOA rows.  The
    split design-matrix path (see :func:`_make_assembly`) caches the
    stacked linear-block columns — a DMX bin's cached column carries
    both its TOA-delay and its DM-block rows."""
    from pint_tpu.residuals import scaled_dm_sigma_rows

    names = list(fit_params)
    resid_sec = build_resid_sec_fn(model, batch, names, track_mode)
    idx = jnp.asarray(np.asarray(dm_index), dtype=jnp.int64)
    dmv = jnp.asarray(np.asarray(dm_data, np.float64))
    dme = jnp.asarray(np.asarray(dm_error, np.float64))
    nt = batch.ntoas

    def combined(x, p):
        p2 = model.with_x(p, x, names)
        r_t = resid_sec(x, p)
        # measured - model (reference residuals.py:1077)
        r_dm = dmv - model.total_dm(p2, batch)[idx]
        return jnp.concatenate([r_t, r_dm])

    def sigma_fn(p):
        sigma_t = model.scaled_toa_uncertainty(p, batch) * 1e-6
        sigma_dm = scaled_dm_sigma_rows(model, p, batch, idx, dme)
        return jnp.concatenate([sigma_t, sigma_dm])

    offc_np = np.concatenate(
        [np.ones(nt), np.zeros(int(idx.shape[0]))]) if include_offset \
        else None
    from pint_tpu import aot

    return _make_assembly(model, names, combined, sigma_fn, offc_np,
                          design_matrix,
                          aot_fingerprint=aot.model_fingerprint(
                              model, batch, track_mode, "wb",
                              "dm=" + aot.data_crc(dmv, dme, idx)))


@dispatch_contract("gls_step", max_compiles=40, max_dispatches=3,
                   max_transfers=3)
# ddlint: disable=OBS001 returns bare jitted closures for the per-step hot path — a host span wrapper here would be per-iteration overhead; spanned by the fitter drivers
def build_gls_step(model: TimingModel, batch: TOABatch,
                   fit_params: Sequence[str], track_mode: str,
                   threshold: Optional[float] = None,
                   include_offset: bool = True, assemble=None,
                   assemble_builder=None,
                   design_matrix: Optional[str] = None):
    """The jitted GLS Gauss-Newton step ``(x, p) -> dict`` (reference
    `GLSFitter.fit_toas` basis path + `get_gls_mtcm_mtcy`,
    `/root/reference/src/pint/fitter.py:1841,2618`).

    The normal matrix is assembled over the augmented design matrix
    ``[M | noise basis]`` with the diagonal prior ``phiinv = 1/weights``
    on the basis columns (zero — an improper flat prior — on timing
    columns, where the reference uses enterprise's 1e40 constant), then
    solved by a thresholded eigendecomposition in diagonally
    preconditioned coordinates (the eigencutoff plays the reference's
    SVD-fallback/degeneracy-warning role, `fitter.py:2639`).  NOTE:
    ``threshold`` here is an ABSOLUTE eigenvalue cutoff in the
    unit-column-normalized coordinates (data eigenvalues are O(ncols));
    this differs from :func:`fit_wls_svd`, whose threshold is relative to
    the largest singular value — a noise prior can inflate the largest
    GLS eigenvalue by many orders, so a relative cutoff there would
    swallow legitimately small timing eigenvalues (see the inline
    comment at the cutoff).  Returned
    covariance and noise-realization amplitudes are in normalized
    coordinates + norms, denormalized on host (TPU f64 range; see
    `fit_wls_svd`).  chi2 is the Woodbury form r^T C^-1 r with the
    offset profiled out in the SAME C^-1 metric (reference
    `residuals.py:646`, `utils.py:3097`).
    """
    names = list(fit_params)
    npar = len(names)
    if assemble is None:
        assemble = build_whitened_assembly(model, batch, names, track_mode,
                                           include_offset,
                                           design_matrix=design_matrix)

    def _impl(xp, r, M, sigma, offc, U, phi, esl):
        return gls_solve(xp, r, M, sigma, offc, U, phi, esl, npar,
                         threshold)

    def make_solve(esl):
        if jax.default_backend() == "cpu":
            @jax.jit
            def solve(r, M, sigma, offc, p):
                return _impl(jnp, r, M, sigma, offc,
                             model.noise_basis(p), model.noise_weights(p),
                             esl)

            from pint_tpu import aot

            # the ROADMAP item 2 leftover: on the CPU backend the GLS
            # solve is a jitted program (the wideband step rides this
            # same path through its combined assembly), so a warm store
            # serves it instead of re-tracing.  esl is structural (the
            # ECORR column range drives the Schur elimination shape);
            # the noise basis/weights enter via p's avals + model
            # structure, both already in the fingerprint.
            return aot.serve(
                "gls_solve", solve,
                aot.model_fingerprint(
                    model, batch, track_mode, "gls",
                    f"npar={npar}|thr={threshold}|esl={esl!r}"))

        cache: dict = {}

        def solve(r, M, sigma, offc, p, p_host=None):
            from pint_tpu.utils import host_eager

            r_h, M_h, s_h, offc_h = _fetch_host(r, M, sigma, offc)
            if p_host is not None:
                # The basis U from the HOST pytree (no device traffic),
                # re-extracted whenever the basis leaves are replaced by
                # a build_pdict.  Keyed on the leaf OBJECTS themselves
                # (strong references, identity-compared) so a recycled
                # allocation can never produce a false hit.  phi is NOT
                # cached: the prior variances depend on noise parameter
                # VALUES (which change across noise-fit alternations
                # while the basis arrays are reused).
                leaves = [p_host["const"].get(c.basis_pytree_name)
                          for c in model.correlated_noise_components]
                hit = ("leaves" in cache
                       and len(cache["leaves"]) == len(leaves)
                       and all(a is b for a, b in
                               zip(cache["leaves"], leaves)))
                if not hit:
                    cache["leaves"] = leaves
                    cache["U"] = _host_noise_basis(model, p_host)
                with host_eager():
                    phi = model.noise_weights(p_host)
                phi_h = None if phi is None else \
                    np.asarray(phi, np.float64)
            else:
                if "U" not in cache:  # static across steps of one fit
                    U = model.noise_basis(p)
                    cache["U"] = None if U is None else \
                        np.asarray(U, np.float64)
                phi = model.noise_weights(p)
                phi_h = None if phi is None else \
                    np.asarray(phi, np.float64)
            with profiling.stage("solve_host"):
                if not (np.all(np.isfinite(M_h))
                        and np.all(np.isfinite(r_h))
                        and np.all(np.isfinite(s_h))):
                    # same host hardening as wls_solve: LAPACK raises
                    # on NaN where the guards need a judgeable NaN dict
                    profiling.count("guard.solve_nonfinite_input")
                    return _nan_gls_out(r_h, npar)
                try:
                    return _impl(np, r_h, M_h, s_h, offc_h, cache["U"],
                                 phi_h, esl)
                except np.linalg.LinAlgError:
                    profiling.count("guard.solve_linalg_error")
                    return _nan_gls_out(r_h, npar)

        return solve

    _assemble_exact = _exact_assemble_factory(
        batch,
        assemble_builder if assemble_builder is not None else
        (lambda b: build_whitened_assembly(model, b, names, track_mode,
                                           include_offset,
                                           design_matrix=design_matrix)))

    def _host_step(x, p, exact, assemble_fn, solve_fn, p_host):
        out = _assemble_exact(x, p_host if p_host is not None else p) \
            if exact else None
        if out is None:
            out = assemble_fn(x, p)
        r, M, sigma, offc = out
        return solve_fn(r, M, sigma, offc, p, p_host)

    solve_cache: dict = {}

    def step(x, p, exact=False, p_host=None):
        esl = solve_cache.get("esl", ...)
        if esl is ...:
            esl = solve_cache["esl"] = model.ecorr_block(
                p_host if p_host is not None else p)
        solve = solve_cache.get(esl)
        if solve is None:
            solve = solve_cache[esl] = make_solve(esl)
        if jax.default_backend() == "cpu":
            r, M, sigma, offc = assemble(x, p)
            return solve(r, M, sigma, offc, p)
        return _host_step(x, p, exact, assemble, solve, p_host)

    return step


def _nan_gls_out(r, npar):
    """NaN GLS solve dict with the gls_solve key set (norms kept
    finite so host denormalization stays well-defined).  No
    "noise_ampls" key: _store_noise treats its absence as "drop stale
    realizations", which is exactly right for a failed solve."""
    return {"dx": np.full(npar, np.nan), "offset": np.nan,
            "chi2": np.nan, "Sigma_n": np.full((npar, npar), np.nan),
            "norms": np.ones(npar),
            "resid_sec": np.asarray(r), "n_bad": np.int64(0),
            "e_min": np.nan}


def gls_solve(xp, r, M, sigma, offc, U, phi, esl, npar,
              threshold=None):
    """The complete GLS linear solve + Woodbury chi2, xp-generic: runs
    as (part of) a jitted program on the (true-IEEE) CPU backend and in
    fused accelerator fit programs (where only the step ``dx`` is
    consumed — XLA dead-code-eliminates the rest), and as host numpy
    for the FINAL solve on accelerators — TPU's emulated-f64 dot
    products are only ~f32-grade at NANOGrav row counts, which destroys
    the small-eigenvalue structure parameter uncertainties are made of
    (measured on B1855+09: DMX uncertainties collapse ~200x if the Gram
    is formed on device).  With ``esl`` the ECORR block is eliminated
    through its exactly-diagonal Gram (Schur complement), so the
    eigendecomposition touches only timing+Fourier columns (~150
    instead of ~780 on B1855) and chi2 uses the matching per-epoch
    Sherman-Morrison (`woodbury_dot_split`).  The returned ``e_min``
    (smallest KEPT eigenvalue of the normalized, prior-augmented normal
    matrix) is the conditioning figure the fitters use to decide
    whether the device-assembled design matrix suffices for the final
    covariance (consulted by ``Fitter._final_step`` and the fused-fit
    finish against ``EXACT_COV_EMIN_FLOOR``)."""
    if U is not None and U.shape[0] != r.shape[0]:
        # wideband: the noise basis covers only the TOA rows; the DM
        # block is uncorrelated (reference pint_matrix.py:532 pads
        # the same way when combining design matrices)
        U = xp.concatenate(
            [U, xp.zeros((r.shape[0] - U.shape[0], U.shape[1]))],
            axis=0)
    if phi is not None:
        # zero prior variance (e.g. a disabled red-noise amplitude)
        # would make phiinv infinite; floor it so those columns are
        # pinned to ~zero amplitude instead of poisoning the solve
        # (1e-30 keeps 1/phi inside TPU's emulated-f64 range)
        phi = xp.where(phi > 0.0, phi, 1e-30)
    ntm = M.shape[1]
    Mfull = M if U is None else xp.concatenate([M, U], axis=1)
    P = Mfull.shape[1]
    Mn, rw, norms = _whiten_normalize(Mfull, r, sigma)
    phiinv = xp.zeros(P) if phi is None else \
        xp.concatenate([xp.zeros(ntm), 1.0 / phi])
    # (sqrt(phiinv)/norms)^2, NOT phiinv/norms^2: timing-column norms
    # can exceed 1e19 and norms**2 leaves the emulated-f64 exponent
    # range on TPU (the squared form stays bounded for every column)
    prior = (xp.sqrt(phiinv) / norms) ** 2
    thr = _machine_eps(xp) * P if threshold is None else threshold
    # ABSOLUTE threshold in the normalized coordinates (timing
    # columns have unit norm, so data-driven eigenvalues are O(ncols)
    # and true degeneracies sit at rounding level).  A threshold
    # relative to e[-1] breaks when a strong noise prior dominates:
    # 1/phi for a tightly-pinned basis mode inflates e[-1] by many
    # orders and the cutoff then swallows legitimately small timing
    # eigenvalues — seen on B1855+09, where the deep
    # (1 - rho^2 ~ 1e-10) OM-T0 degeneracy was dropped, collapsing
    # both uncertainties ~1e5x below tempo2's.
    if esl is None:
        A = Mn.T @ Mn + xp.diag(prior)
        e, V = _eigh_xp(xp, A)
        bad = e <= thr
        einv = xp.where(bad, 0.0, 1.0 / xp.where(bad, 1.0, e))
        sol = (V @ (einv * (V.T @ (Mn.T @ rw)))) / norms
        Sigma_n = (V * einv) @ V.T
    else:
        dlo, dhi = ntm + esl[0], ntm + esl[1]
        # dlo/dhi/P are trace-time Python ints (esl is a static tuple,
        # ntm/P come from shapes), so these np.* calls build CONSTANT
        # index arrays during tracing — no runtime value ever crosses
        # to the host (verified: the jitted CPU GLS step compiles and
        # the fused-program jaxpr carries them as literals)
        kidx = np.concatenate([np.arange(dlo), np.arange(dhi, P)])  # ddlint: disable=TRACE001 trace-time constant indices
        didx = np.arange(dlo, dhi)  # ddlint: disable=TRACE001 trace-time constant indices
        K = Mn[:, kidx]
        D = Mn[:, didx]
        b_K = K.T @ rw
        b_D = D.T @ rw
        # D's Gram block is exactly diagonal (disjoint supports);
        # unit column normalization makes the diagonal 1
        d_D = 1.0 + prior[didx]
        G_KD = K.T @ D
        S = K.T @ K + xp.diag(prior[kidx]) \
            - (G_KD / d_D[None, :]) @ G_KD.T
        e, V = _eigh_xp(xp, S)
        bad = e <= thr
        einv = xp.where(bad, 0.0, 1.0 / xp.where(bad, 1.0, e))
        sol_K = V @ (einv * (V.T @ (b_K - G_KD @ (b_D / d_D))))
        sol_D = (b_D - G_KD.T @ sol_K) / d_D
        if xp is np:
            sol = np.zeros(P)
            sol[kidx] = sol_K
            sol[didx] = sol_D
            sol = sol / norms
        else:
            sol = jnp.zeros(P).at[kidx].set(sol_K) \
                .at[didx].set(sol_D) / norms
        # (A^-1)_KK is exactly the Schur-complement inverse, and the
        # timing columns are the first npar entries of K
        Sigma_n = (V * einv) @ V.T
    # chi2 at x, offset profiled out in the C^-1 metric (over the
    # offc regressor — ones on TOA rows, zeros on wideband DM rows)
    off = xp.float64(0.0)
    if phi is None:
        if offc is not None:
            w = offc / sigma**2
            off = xp.sum(r * w) / xp.sum(w * offc)
        chi2 = xp.sum(((r - off * offc if offc is not None else r)
                       / sigma) ** 2)
    else:
        if esl is None:
            def cdot(a, b):
                return woodbury_dot(sigma**2, U, phi, a, b)[0]
        else:
            Ue = U[:, esl[0]:esl[1]]
            phie = phi[esl[0]:esl[1]]
            Uf = xp.concatenate([U[:, :esl[0]], U[:, esl[1]:]],
                                axis=1)
            phif = xp.concatenate([phi[:esl[0]], phi[esl[1]:]])

            def cdot(a, b):
                return woodbury_dot_split(sigma**2, Ue, phie,
                                          Uf, phif, a, b)[0]
        if offc is not None:
            off = cdot(offc, r) / cdot(offc, offc)
        r_off = r - off * offc if offc is not None else r
        chi2 = cdot(r_off, r_off)
    return {"dx": sol[:npar], "offset": off, "chi2": chi2,
            "Sigma_n": Sigma_n[:npar, :npar], "norms": norms[:npar],
            "noise_ampls": sol[ntm:], "resid_sec": r,
            "n_bad": xp.sum(bad),
            "e_min": xp.min(xp.where(bad, xp.inf, e))}


def build_gls_fullcov_step(model: TimingModel, batch: TOABatch,
                           fit_params: Sequence[str], track_mode: str,
                           threshold: Optional[float] = None,
                           include_offset: bool = True, assemble=None,
                           design_matrix: Optional[str] = None):
    """The dense-covariance GLS step (reference `GLSFitter.fit_toas`
    ``full_cov=True`` path + `get_gls_mtcm_mtcy_fullcov`,
    `/root/reference/src/pint/fitter.py:2601`): C = N + U Phi U^T is
    formed explicitly and Cholesky-factored, the normal equations are
    M^T C^-1 M dx = M^T C^-1 r.  O(N^2)-memory — the in-suite
    cross-check of the Woodbury basis path, exactly how the reference
    validates itself (its `tests/test_gls_fitter.py` runs both).
    """
    names = list(fit_params)
    npar = len(names)
    if assemble is None:
        assemble = build_whitened_assembly(model, batch, names, track_mode,
                                           include_offset,
                                           design_matrix=design_matrix)

    @jax.jit
    def solve(r, M, sigma, offc, p):
        from jax.scipy.linalg import solve_triangular

        U = model.noise_basis(p)
        phi = model.noise_weights(p)
        C = jnp.diag(sigma**2)
        if phi is not None:
            phi = jnp.where(phi > 0.0, phi, 0.0)
            if U.shape[0] != r.shape[0]:  # wideband zero-padding
                U2 = jnp.concatenate(
                    [U, jnp.zeros((r.shape[0] - U.shape[0], U.shape[1]))],
                    axis=0)
            else:
                U2 = U
            C = C + (U2 * phi) @ U2.T
        L = jnp.linalg.cholesky(C)

        def csolve(b):
            y = solve_triangular(L, b, lower=True)
            return solve_triangular(L.T, y, lower=False)

        # two-stage range-safe column normalization (see fit_wls_svd)
        Mw = M / sigma[:, None]
        cmax = jnp.max(jnp.abs(Mw), axis=0)
        cmax = jnp.where(cmax == 0.0, 1.0, cmax)
        _, nc = normalize_designmatrix(Mw / cmax)
        norms = cmax * nc
        Mn = M / norms
        CiM = csolve(Mn)
        A = Mn.T @ CiM
        y = CiM.T @ r
        e, V = jnp.linalg.eigh(A)
        thr = _machine_eps() * A.shape[0] if threshold is None \
            else threshold
        # ABSOLUTE cutoff in the normalized coordinates, matching
        # build_gls_step exactly so a user-supplied threshold means the
        # same thing on both paths (the cross-check must not diverge
        # because of threshold semantics)
        bad = e <= thr
        einv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, e))
        sol = (V @ (einv * (V.T @ y))) / norms
        Sigma_n = (V * einv) @ V.T
        off = jnp.float64(0.0)
        if offc is not None:
            Cio = csolve(offc)
            off = (Cio @ r) / (Cio @ offc)
        r_off = r - off * offc if offc is not None else r
        chi2 = r_off @ csolve(r_off)
        return {"dx": sol[:npar], "offset": off, "chi2": chi2,
                "Sigma_n": Sigma_n[:npar, :npar], "norms": norms[:npar],
                "resid_sec": r, "n_bad": jnp.sum(bad),
                "e_min": jnp.min(jnp.where(bad, jnp.inf, e))}

    def step(x, p, exact=False, p_host=None):
        # exact is accepted for interface parity but moot: the dense
        # full-cov path is CPU-only by construction (see docstring)
        r, M, sigma, offc = assemble(x, p)
        return solve(r, M, sigma, offc, p)

    return step


def _fetch_host(r, M, sigma, offc):
    """ONE batched device->host transfer of a whitened assembly (a
    per-array fetch pays a full tunnel round trip each).  Arrays that
    already live on the host or the CPU backend (the exact-assembly
    path) convert directly — no accelerator round trip."""
    plat = getattr(getattr(M, "device", None), "platform", None)
    if isinstance(M, np.ndarray) or plat == "cpu":
        return (np.asarray(r), np.asarray(M), np.asarray(sigma),
                None if offc is None else np.asarray(offc))
    profiling.count("fetch")
    with profiling.stage("fetch_host"):
        parts = [jnp.ravel(r), jnp.ravel(M), jnp.ravel(sigma)]
        if offc is not None:
            parts.append(jnp.ravel(offc))
        flat = np.asarray(jnp.concatenate(parts))
    n = r.shape[0]
    r_h = flat[:n]
    M_h = flat[n:n + M.size].reshape(M.shape)
    s_h = flat[n + M.size:n + M.size + n]
    offc_h = None if offc is None else flat[n + M.size + n:]
    return r_h, M_h, s_h, offc_h


def _exact_assemble_factory(batch, default_builder):
    """Final-covariance assembly on the in-process CPU backend: the
    accelerator-assembled design matrix carries ~1e-11 relative noise
    (emulated-f64 pipeline), ABOVE the deepest physical eigenvalues of
    NANOGrav normal matrices (~1e-13 normalized) — uncertainties of
    deeply-correlated pairs would be garbage.  Iteration steps stay on
    the accelerator (dx noise just iterates away); only the one final
    pass pays the CPU cost.  Everything — the captured batch AND the
    builder's own constants — must be created inside the CPU context:
    accelerator-committed captures silently override
    ``default_device(cpu)``."""
    cache: dict = {}

    def assemble_exact(x, p):
        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:  # JAX_PLATFORMS excludes cpu entirely
            if "warned" not in cache:
                cache["warned"] = True
                warnings.warn(
                    "no cpu backend available (JAX_PLATFORMS excludes "
                    "cpu): final covariance uses the accelerator-"
                    "assembled design matrix, whose ~1e-11 noise can "
                    "inflate/collapse deeply-correlated uncertainties; "
                    "run with JAX_PLATFORMS=<accel>,cpu for exact "
                    "covariances")
            return None
        with jax.default_device(cpu), profiling.stage("assemble_exact_cpu"):
            if "a" not in cache:
                batch_np = jax.tree_util.tree_map(np.asarray, batch)
                cache["a"] = default_builder(batch_np)
            x_np = np.asarray(x)
            p_np = jax.tree_util.tree_map(np.asarray, p)
            # memoize on the exact inputs: repeated fits of the SAME
            # problem land on the same converged x and p, and the
            # ~1 s/fit single-core jacfwd re-assembly is then identical
            # (grid scans and steady-state refits hit this constantly).
            # A fixed-size digest, not raw bytes: p carries multi-MB
            # basis arrays that must not be pinned per cached step.
            import hashlib

            h = hashlib.sha1(x_np.tobytes())
            for a in jax.tree_util.tree_leaves(p_np):
                h.update(a.tobytes() if hasattr(a, "tobytes")
                         else repr(a).encode())
            key = h.digest()
            hit = cache.get("memo")
            if hit is not None and hit[0] == key:
                return hit[1]
            out = cache["a"](x_np, p_np)
            if profiling.enabled():
                jax.block_until_ready(out)
            cache["memo"] = (key, out)
            return out

    return assemble_exact


@dispatch_contract("wls_step", max_compiles=40, max_dispatches=3,
                   max_transfers=3, warm_from_store=True)
# ddlint: disable=OBS001 returns bare jitted closures for the per-step hot path — a host span wrapper here would be per-iteration overhead; spanned by the fitter drivers
def build_wls_step(model: TimingModel, batch: TOABatch,
                   fit_params: Sequence[str], track_mode: str,
                   threshold: Optional[float] = None,
                   include_offset: bool = True, assemble=None,
                   kernel=None, host_finish=None,
                   design_matrix: Optional[str] = None):
    """The jitted Gauss-Newton step ``(x, p) -> dict`` for a frozen model
    structure.

    ``x`` is the free-parameter offset vector (device units, offsets from
    the pytree's reference values); ``p`` the params pytree.  The returned
    dict holds ``dx`` (the step, offset column already dropped), ``chi2``
    (at x, using the best-fit offset), ``Sigma`` (parameter covariance),
    ``resid_sec`` and ``n_bad``.

    An explicit phase-offset column is appended unless the model carries a
    free PHOFF (reference prepends an "Offset" column the same way,
    `/root/reference/src/pint/models/timing_model.py:2326`).

    ``kernel``: the linear WLS solve — :func:`fit_wls_svd` or
    :func:`fit_wls_eigh`; default backend-matched (`_default_wls_kernel`).
    """
    names = list(fit_params)
    if assemble is None:
        assemble = build_whitened_assembly(model, batch, names, track_mode,
                                           include_offset,
                                           design_matrix=design_matrix)
    if host_finish is None:
        host_finish = jax.default_backend() != "cpu"

    def _solve(xp, r, M, sigma, offc, kern):
        return wls_solve(xp, r, M, sigma, offc, kern, len(names),
                         threshold)

    if host_finish:
        # accelerator fit path: the device computes the physics
        # (residuals + jacfwd — the part TPU accelerates ~500x) and the
        # SOLVE runs on the host in true-IEEE f64 with the reference's
        # exact SVD recipe.  This is a PRECISION decision, not a
        # performance one: the TPU's emulated-f64 dot products are only
        # ~f32-grade (measured 4e-7..4e-4 absolute error on
        # unit-normalized Grams at NANOGrav row counts), which destroys
        # the small-eigenvalue structure that parameter uncertainties
        # are made of.  Grids/ensembles (vmapped, chi2-oriented) keep
        # the all-device kernels via host_finish=False.
        assemble_exact = _exact_assemble_factory(
            batch, lambda b: build_whitened_assembly(
                model, b, names, track_mode, include_offset,
                design_matrix=design_matrix))
        host_kernel = fit_wls_svd if kernel is None else kernel

        def step(x, p, exact=False, p_host=None):
            out = assemble_exact(
                x, p_host if p_host is not None else p) if exact else None
            if out is None:
                out = assemble(x, p)
            r, M, sigma, offc = out
            r_h, M_h, s_h, offc_h = _fetch_host(r, M, sigma, offc)
            with profiling.stage("solve_host"):
                return _solve(np, r_h, M_h, s_h, offc_h, host_kernel)

        return step

    kern = _default_wls_kernel() if kernel is None else kernel

    @jax.jit
    def solve(r, M, sigma, offc):
        return _solve(jnp, r, M, sigma, offc, kern)

    from pint_tpu import aot

    solve = aot.serve(
        "wls_solve", solve,
        f"npar={len(names)}|thr={threshold}"
        f"|kern={getattr(kern, '__name__', str(kern))}")

    def step(x, p, exact=False, p_host=None):
        r, M, sigma, offc = assemble(x, p)
        return solve(r, M, sigma, offc)

    return step


def _nan_solution(P):
    """The all-NaN stand-in for an impossible HOST linear solve (dpars,
    Sigma_n, norms, n_bad) — finite norms so denormalization stays
    well-defined.  Host-only by construction: called exclusively from
    the ``xp is np`` branch of wls_solve (the call-graph reachability
    of the linter cannot see through that guard, hence the inline
    suppression)."""
    return (np.full(P, np.nan), np.full((P, P), np.nan), np.ones(P),  # ddlint: disable=TRACE001 host-only (xp-is-np branch)
            np.int64(0))


def wls_solve(xp, r, M, sigma, offc, kern, npar, threshold=None):
    """One WLS solve + chi2 from a whitened assembly, xp-generic (the
    shared finish of the step and fused-fit paths).  chi2 is evaluated
    at x with the offset profiled out (the linear best fit of the offc
    regressor — ones on TOA rows, zeros on wideband DM rows — to the
    current residuals).  On the host (xp is np) the returned ``e_min``
    is the smallest kept eigenvalue of the normalized Gram (recovered
    as 1/||Sigma_n||_2 — exact for both kernels since Sigma_n's
    eigenvalues are the reciprocals of the kept ones), the conditioning
    figure `Fitter._final_step` tests against EXACT_COV_EMIN_FLOOR;
    device callers (grids) never
    consult it, so the extra decomposition is host-only.

    Host hardening: LAPACK RAISES on non-finite input where the jitted
    XLA kernels return NaN — a poisoned assembly must surface as a NaN
    result the fit guards can judge, not as a LinAlgError crash from
    inside the solve."""
    if xp is np:
        finite_in = bool(np.all(np.isfinite(M)) and np.all(np.isfinite(r))
                         and np.all(np.isfinite(sigma)))
        if not finite_in:
            profiling.count("guard.solve_nonfinite_input")
            dpars, Sigma_n, norms, n_bad = _nan_solution(M.shape[1])
        else:
            try:
                dpars, Sigma_n, norms, n_bad = kern(M, r, sigma,
                                                    threshold)
            except np.linalg.LinAlgError:
                # numerically impossible factorization (can also happen
                # on finite but pathological input)
                profiling.count("guard.solve_linalg_error")
                dpars, Sigma_n, norms, n_bad = _nan_solution(M.shape[1])
    else:
        dpars, Sigma_n, norms, n_bad = kern(M, r, sigma, threshold)
    if offc is not None:
        w = offc / sigma**2
        off = xp.sum(r * w) / xp.sum(w * offc)
        r_off = r - off * offc
    else:
        off = xp.float64(0.0)
        r_off = r
    chi2 = xp.sum((r_off / sigma) ** 2)
    if xp is np:
        if np.all(np.isfinite(Sigma_n)):
            smax = float(np.linalg.eigvalsh(Sigma_n)[-1])
            e_min = 1.0 / smax if smax > 0 else np.inf
        else:
            e_min = np.nan  # poisoned solve: compares False everywhere
    else:
        e_min = jnp.float64(jnp.inf)
    return {"dx": dpars[:npar], "offset": off, "chi2": chi2,
            "Sigma_n": Sigma_n[:npar, :npar], "norms": norms[:npar],
            "resid_sec": r, "n_bad": n_bad, "e_min": e_min}


#: fused-sentinel defaults (overridable per call via build_fused_fit):
#: DIVERGED after this many CONSECUTIVE chi2 increases (each beyond
#: tol_chi2) ...
FUSED_DIVERGE_STREAK = 3
#: ... or after this many consecutive iterations with no new best chi2
#: (improvement beyond tol_chi2) — the period-2 oscillation detector;
#: a healthy slow fit improves every iteration and never trips it
FUSED_STALL_ITERS = 6

#: Smallest kept normalized-Gram eigenvalue below which the final
#: covariance must come from a CPU-exact (true-IEEE) re-assembly of the
#: design matrix: the accelerator-assembled M carries ~1e-11 relative
#: noise, which perturbs the normalized Gram's eigenvalues by ~1e-8..1e-7
#: absolute at NANOGrav row counts; eigenvalues within ~100x of that get
#: noise-grade variances.  Above the floor the device assembly (host
#: true-f64 solve) is exact to well under quoted-uncertainty precision.
EXACT_COV_EMIN_FLOOR = 1e-5


def _host_noise_basis(model: TimingModel, p_host: dict):
    """The concatenated noise basis U as host numpy from a HOST params
    pytree — the blocks are host-built pytree leaves already, so this
    costs zero accelerator dispatches (the prior variances phi are NOT
    extracted here: they depend on noise parameter values and must be
    recomputed per solve)."""
    comps = [c for c in model.correlated_noise_components
             if c.basis_pytree_name in p_host["const"]]
    if not comps:
        return None
    return np.concatenate(
        [np.asarray(p_host["const"][c.basis_pytree_name], np.float64)
         for c in comps], axis=1)


@dispatch_contract("fused_fit", max_compiles=40, max_dispatches=1,
                   max_transfers=2, warm_from_store=True)
def build_fused_fit(model: TimingModel, batch: TOABatch,
                    fit_params: Sequence[str], track_mode: str, *,
                    threshold: Optional[float] = None,
                    include_offset: bool = True, maxiter: int = 2,
                    tol_chi2: float = 1e-8,
                    exact_floor: Optional[float] = None,
                    design_matrix: Optional[str] = None,
                    diverge_streak: Optional[int] = None,
                    stall_iters: Optional[int] = None):
    """An ENTIRE iterated WLS Gauss-Newton fit as one XLA program + one
    device->host transfer — the accelerator answer to VERDICT r3's
    single-fit latency finding (each eager step over a networked TPU
    pays ~100 ms/dispatch; a maxiter-step fit used to pay
    2*(maxiter+1) dispatches plus per-step fetches).

    The jitted program `lax.scan`s ``maxiter`` full Gauss-Newton steps
    (the device eigh kernel — only ``dx`` is consumed, so XLA dead-code
    eliminates each step's covariance/chi2 arithmetic), re-assembles
    the whitened system at the converged x, and returns everything in
    ONE flat f64 vector fetched in ONE transfer.  The FINAL solve then
    runs on the host in true-IEEE f64 with the reference's SVD recipe
    (accelerator Gram noise must not touch the reported covariance),
    and if it reports a kept eigenvalue within reach of the
    device-assembly noise (``e_min`` below ``exact_floor``), the
    design matrix is re-assembled once on the in-process CPU backend
    from the HOST params pytree (zero accelerator traffic) and the
    solve repeats — the exactness tiers of `_exact_assemble_factory`,
    now paid only when the conditioning actually demands it.

    WLS only: correlated-noise (GLS) normal matrices carry physical
    structure below the device Gram noise, so GLS iteration steps must
    be host-solved per step (see `GLSFitter._fused_ok`).

    **Convergence sentinel (ISSUE 3).**  The while_loop carries
    best-so-far ``(x, chi2)`` and computes an integer
    :class:`FitStatus` IN-GRAPH: non-finite chi2 exits immediately
    (NONFINITE — the bare ``|prev-chi2| < tol`` test can never trip on
    NaN, so an unguarded loop would silently burn ``maxiter`` NaN
    iterations); ``diverge_streak`` consecutive chi2 increases or
    ``stall_iters`` iterations without a new best exit as DIVERGED
    (the stall test catches the period-2 oscillation of e.g. the
    degenerate 3-frequency/free-DM FD block, which a pure
    consecutive-increase test misses because every rise is followed by
    a fall).  On DIVERGED/NONFINITE the returned ``x`` is the
    best-so-far iterate, not the last one.  The status and iteration
    count ride the same single flat transfer — the happy path stays
    1 jit_call + 1 fetch per fit.

    Returns ``fit(p, p_host=None) -> (x, out)`` with ``out`` the
    `wls_solve` host dict plus ``status`` (:class:`FitStatus`),
    ``iterations`` and ``best_chi2``.  ``p_host`` is the same pytree as
    ``p`` with host-numpy leaves (fitters pass ``resids.pdict``);
    without it the exact tier falls back to per-leaf device fetches.
    """
    names = list(fit_params)
    npar = len(names)
    if diverge_streak is None:
        diverge_streak = FUSED_DIVERGE_STREAK
    if stall_iters is None:
        stall_iters = FUSED_STALL_ITERS
    assemble = build_whitened_assembly(model, batch, names, track_mode,
                                       include_offset,
                                       design_matrix=design_matrix)
    inline = assemble.inline
    n_rows = batch.ntoas
    ncol = npar + (1 if include_offset else 0)
    host_offc = np.ones(n_rows) if include_offset else None
    floor = EXACT_COV_EMIN_FLOOR if exact_floor is None else exact_floor

    @jax.jit
    def run(p):
        # split design matrix: the linear-block columns are computed
        # ONCE here and reused by every loop iteration AND the final
        # re-assembly — in-graph, the pure-functional analogue of the
        # eager path's column cache (the while_loop body closes over
        # them as a loop constant)
        if assemble.split:
            cols = assemble.lin_cols(jnp.zeros(npar), p)

            def _asm(x):
                return assemble.inline_with_cols(x, p, cols)
        else:
            def _asm(x):
                return inline(x, p)

        # while_loop, not scan: honors the eager loop's tol_chi2
        # early-stop in-graph (a converged fit skips the remaining
        # iterations' device work; same break placement as the eager
        # loop — step applied, then consecutive-chi2 test).  The carry
        # holds the convergence sentinel's state: best-so-far (x, chi2),
        # the consecutive-increase and no-new-best streak counters, and
        # the integer FitStatus (_RUNNING while iterating).
        def cond(c):
            i, status = c[6], c[7]
            return jnp.logical_and(i < maxiter, status == _RUNNING)

        def body(c):
            x, prev, best_x, best_chi2, inc_streak, stall_streak, i, _ = c
            r, M, sigma, offc = _asm(x)
            dpars, _, _, _ = fit_wls_eigh(M, r, sigma, threshold)
            if offc is not None:
                w = offc / sigma**2
                off = jnp.sum(r * w) / jnp.sum(w * offc)
                chi2 = jnp.sum(((r - off * offc) / sigma) ** 2)
            else:
                chi2 = jnp.sum((r / sigma) ** 2)
            best_x, best_chi2, inc_streak, stall_streak, status = \
                sentinel_advance(x, chi2, prev, best_x, best_chi2,
                                 inc_streak, stall_streak, tol_chi2,
                                 diverge_streak, stall_iters)
            return (x + dpars[:npar], chi2, best_x, best_chi2,
                    inc_streak, stall_streak, i + 1, status)

        x, _, best_x, best_chi2, _, _, i, status = jax.lax.while_loop(
            cond, body,
            (jnp.zeros(npar), jnp.float64(jnp.inf), jnp.zeros(npar),
             jnp.float64(jnp.inf), jnp.int32(0), jnp.int32(0),
             jnp.int32(0), jnp.int32(_RUNNING)))
        status = jnp.where(status == _RUNNING,
                           jnp.int32(FitStatus.MAXITER), status)
        # failed runs hand back the best finite iterate, never the
        # poisoned/oscillating last one (best_x is the zeros start if
        # no iteration ever produced a finite chi2)
        ok = jnp.logical_or(status == FitStatus.CONVERGED,
                            status == FitStatus.MAXITER)
        x = jnp.where(ok, x, best_x)
        r, M, sigma, _ = _asm(x)
        tail = jnp.stack([status.astype(jnp.float64),
                          i.astype(jnp.float64), best_chi2])
        return jnp.concatenate([x, r, sigma, jnp.ravel(M), tail])

    # AOT store (ISSUE 7): the whole-fit program is the single most
    # expensive trace+compile in the package — a warm serving process
    # deserializes it from disk instead (the batch rides the closure,
    # so its data CRC is in the key)
    from pint_tpu import aot

    run = aot.serve(
        "fused_fit", run,
        aot.model_fingerprint(
            model, batch, track_mode, f"names={','.join(names)}",
            f"maxiter={maxiter}", f"tol={tol_chi2:g}",
            f"thr={threshold}", f"offc={include_offset}",
            f"dm={assemble.design_matrix}",
            f"streak={diverge_streak}", f"stall={stall_iters}"))

    assemble_exact = _exact_assemble_factory(
        batch, lambda b: build_whitened_assembly(
            model, b, names, track_mode, include_offset,
            design_matrix=design_matrix))

    def host_solve(r, M, sigma):
        return wls_solve(np, r, M, sigma, host_offc, fit_wls_svd, npar,
                         threshold)

    def fit(p, p_host=None):
        profiling.count("jit_call")
        with telemetry.span("fitter.fused_fit", n_par=npar,
                            n_toa=n_rows), \
                profiling.stage("fused_device_fit"):
            flat = run(p)
            if profiling.enabled():
                jax.block_until_ready(flat)
        profiling.count("fetch")
        with profiling.stage("fetch_host"):
            flat = np.asarray(flat)
        x = flat[:npar]
        r = flat[npar:npar + n_rows]
        sigma = flat[npar + n_rows:npar + 2 * n_rows]
        M = flat[npar + 2 * n_rows:-3].reshape(n_rows, ncol)
        status = FitStatus(int(flat[-3]))
        iterations = int(flat[-2])
        best_chi2 = float(flat[-1])
        if status in (FitStatus.DIVERGED, FitStatus.NONFINITE):
            profiling.count(f"guard.fused_{status.name.lower()}")
        with profiling.stage("solve_host"):
            out = host_solve(r, M, sigma)
        if float(out["e_min"]) < floor:
            profiling.count("exact_cov_pass")
            ex = assemble_exact(np.asarray(x),
                                p_host if p_host is not None else p)
            if ex is not None:
                r, M, sigma = (np.asarray(ex[0], np.float64),
                               np.asarray(ex[1], np.float64),
                               np.asarray(ex[2], np.float64))
                with profiling.stage("solve_host"):
                    out = host_solve(r, M, sigma)
        out = dict(out)
        if status in (FitStatus.CONVERGED, FitStatus.MAXITER) and \
                not np.isfinite(float(out["chi2"])):
            # belt check: the in-graph sentinel judged the DEVICE chi2;
            # if the host-exact final solve still went non-finite, the
            # fit is NONFINITE regardless of what the loop saw
            profiling.count("guard.fused_nonfinite")
            status = FitStatus.NONFINITE
        out["status"] = status
        out["iterations"] = iterations
        out["best_chi2"] = best_chi2
        # Apply the (already computed, true-IEEE) final Newton step:
        # the device-solved trajectory lands ~1e-3 sigma from the host
        # fixed point, and one exact GN step from there is quadratically
        # convergent — TPU and CPU fits then agree to well below quoted
        # precision.  Residuals/chi2 are updated by the linearization
        # the step itself is based on (dr = -M dx; exact to second
        # order at this displacement).  Skipped on DIVERGED/NONFINITE:
        # x is then the best-so-far iterate of a fit whose quadratic
        # model is known-broken, and the caller (degradation chain)
        # discards these numbers anyway — a finite diagnostic beats a
        # "corrected" one.
        dx = np.asarray(out["dx"], np.float64)
        if status in (FitStatus.CONVERGED, FitStatus.MAXITER) and \
                np.all(np.isfinite(dx)):
            x = x + dx
            r_new = out["resid_sec"] - M[:, :npar] @ dx
            if host_offc is not None:
                w = host_offc / sigma**2
                off = float(np.sum(r_new * w) / np.sum(w * host_offc))
                out["chi2"] = float(
                    np.sum(((r_new - off * host_offc) / sigma) ** 2))
                out["offset"] = off
            else:
                out["chi2"] = float(np.sum((r_new / sigma) ** 2))
            out["resid_sec"] = r_new
        return x, out

    # the served device program, reachable for the cost-card harvest
    # (pint_tpu.metrics): the fused fit's XLA cost lives in `run`, not
    # in the host finish
    fit.run = run
    return fit


def build_noise_lnlike(model: TimingModel, batch: TOABatch,
                       noise_names: Sequence[str], track_mode: str,
                       dm_index=None, dm_data=None, dm_error=None):
    """Jitted ``(x_noise, p) -> lnlikelihood`` over free *noise* parameters
    (EFAC/EQUAD/ECORR/red amplitudes...) at fixed timing parameters — the
    objective the reference's downhill fitters maximize numerically
    (`DownhillFitter._fit_noise`, `/root/reference/src/pint/fitter.py:1167`).
    Here it is one jitted expression, so the gradient comes from autodiff
    instead of finite differences.

    When ``dm_index/dm_data/dm_error`` are given, the wideband DM-residual
    Gaussian term is added, so DMEFAC/DMEQUAD-class parameters have a live
    gradient (reference `WidebandDownhillFitter` noise path)."""
    names = list(noise_names)
    calc = model.calc
    log2pi = float(np.log(2.0 * np.pi))
    wideband = dm_index is not None
    if wideband:
        idx = jnp.asarray(np.asarray(dm_index), dtype=jnp.int64)
        dmv = jnp.asarray(np.asarray(dm_data, np.float64))
        dme = jnp.asarray(np.asarray(dm_error, np.float64))

    @jax.jit
    def lnlike(x, p):
        p2 = model.with_x(p, x, names)
        r_cyc = raw_phase_resids(calc, p2, batch, track_mode,
                                 subtract_mean=False, use_weights=False)
        r = r_cyc / pv(p2, "F0")
        sigma = model.scaled_toa_uncertainty(p2, batch) * 1e-6
        w = 1.0 / sigma**2
        off = jnp.sum(r * w) / jnp.sum(w)
        r = r - off
        U = model.noise_basis(p2)
        phi = model.noise_weights(p2)
        if phi is not None:
            phi = jnp.where(phi > 0.0, phi, 1e-30)
            dot, logdet = woodbury_dot(sigma**2, U, phi, r, r)
        else:
            dot = jnp.sum((r / sigma) ** 2)
            logdet = 2.0 * jnp.sum(jnp.log(sigma))
        ll = -0.5 * (dot + logdet + r.shape[0] * log2pi)
        if wideband:
            r_dm = dmv - model.total_dm(p2, batch)[idx]
            sdm = model.scaled_dm_uncertainty(
                p2, batch, jnp.zeros(batch.ntoas).at[idx].set(dme))[idx]
            ll = ll - 0.5 * (jnp.sum((r_dm / sdm) ** 2)
                             + 2.0 * jnp.sum(jnp.log(sdm))
                             + r_dm.shape[0] * log2pi)
        return ll

    return lnlike


def denormalize_covariance(Sigma_n, norms) -> np.ndarray:
    """Host-side (true IEEE f64) covariance denormalization; see
    `fit_wls_svd` for why this cannot run on TPU."""
    norms = np.asarray(norms, np.float64)
    return np.asarray(Sigma_n, np.float64) / np.outer(norms, norms)


class FitSummary(NamedTuple):
    """Post-fit record.  The first four fields predate the guarded fit
    engine and keep their historical semantics (``converged`` is True
    for any non-failing finish, i.e. status CONVERGED or MAXITER);
    ``status``/``rung``/``guard_trips`` are the guarded engine's
    provenance: the terminal :class:`FitStatus`, which rung of the
    degradation chain produced the result ("fused"/"eager"/"lm", or
    the fitter's own tag), and a guard-name -> trip-count mapping."""

    chi2: float
    dof: int
    iterations: int
    converged: bool
    status: FitStatus = FitStatus.CONVERGED
    rung: str = ""
    guard_trips: Optional[Dict[str, int]] = None


class Fitter:
    """Base fitter (reference `Fitter`, `/root/reference/src/pint/fitter.py:116`).

    Holds (toas, model, resids); concrete subclasses implement
    ``fit_toas``.  After a fit, parameter values and uncertainties are
    written back into the model, ``parameter_covariance_matrix`` /
    ``parameter_correlation_matrix`` hold the scaled covariance, and
    ``resids`` reflects the post-fit model.
    """

    def __init__(self, toas, model: TimingModel,
                 track_mode: Optional[str] = None,
                 residuals: Optional[Residuals] = None,
                 design_matrix: Optional[str] = None,
                 policy: Optional[str] = None):
        self.toas = toas
        self.model = model
        #: TOA input-validation policy ("raise"|"mask"|"warn"), threaded
        #: to the batch export (pint_tpu.toabatch.make_batch)
        self.policy = policy
        self.resids = residuals if residuals is not None else \
            Residuals(toas, model, track_mode=track_mode, policy=policy)
        self.track_mode = self.resids.track_mode
        self.fitresult: Optional[FitSummary] = None
        self.parameter_covariance_matrix: Optional[np.ndarray] = None
        self.covariance_params: List[str] = []
        #: "split" (cache linear-parameter design-matrix columns,
        #: differentiate only the nonlinear core) or "full" (one jacfwd
        #: over every free parameter); default from PINT_TPU_DESIGN_MATRIX
        #: (-> "split")
        self.design_matrix = _resolve_design_matrix(design_matrix)

    #: True for fitters whose ``fit_toas`` maximizes the likelihood over
    #: free noise parameters (the downhill family)
    fits_noise = False

    # -- fittable parameters ---------------------------------------------
    @property
    def fit_params(self) -> List[str]:
        """Free parameters the linear step moves: all free device params
        except noise-component ones (those are fit by maximum likelihood
        in the downhill fitters, as in the reference `fitter.py:1040`)."""
        out = []
        skipped = []
        for n in self.model.free_params:
            if self.model.param_component(n) in self._noise_comp_names():
                skipped.append(n)
            else:
                out.append(n)
        if skipped and not self.fits_noise:
            warnings.warn(
                f"free noise parameters {skipped} are not fit by "
                f"{type(self).__name__}; freeze them or use a downhill "
                "fitter (which fits them by maximum likelihood)")
        return out

    def _noise_comp_names(self):
        return {type(c).__name__ for c in self.model.noise_components}

    @property
    def free_noise_params(self) -> List[str]:
        """Free parameters living on noise components (reference
        `_get_free_noise_params`, `fitter.py:1146`)."""
        noise_comps = self._noise_comp_names()
        return [n for n in self.model.free_params
                if self.model.param_component(n) in noise_comps]

    def get_designmatrix(self):
        """(M, names): the design matrix at the current parameter values,
        M[:,i] = -d(resid_sec)/d(param_i) in device units (reference
        `designmatrix`, `/root/reference/src/pint/models/timing_model.py:2326`,
        there computed from the hand-written derivative registry; here one
        `jax.jacfwd` of the residual function)."""
        names = self.fit_params
        rf = build_resid_sec_fn(self.model, self.resids.batch, names,
                                self.track_mode)
        p = self._device_pdict()
        x = self.model.x0(p, names)
        M = -np.asarray(jax.jit(jax.jacfwd(rf))(x, p))
        return M, names

    # -- reporting --------------------------------------------------------
    @property
    def parameter_correlation_matrix(self) -> Optional[np.ndarray]:
        C = self.parameter_covariance_matrix
        if C is None:
            return None
        s = np.sqrt(np.diag(C))
        return C / np.outer(s, s)

    def update_model(self):
        """Record fit provenance into the model (START/FINISH/NTOA/CHI2/
        TRES), as the reference does post-fit (`fitter.py:~640`)."""
        m, r = self.model, self.resids
        mjds = np.asarray(r.batch.tdbld)
        m.START.value = f"{mjds.min():.4f}"
        m.FINISH.value = f"{mjds.max():.4f}"
        m.NTOA.value = str(self.toas.ntoas)
        chi2 = r.calc_chi2()
        m.CHI2.value = f"{chi2:.4f}"
        m.CHI2R.value = f"{chi2 / r.dof:.6f}"
        m.TRES.value = f"{r.rms_weighted() * 1e6:.4f}"

    def get_summary(self) -> str:
        r = self.resids
        lines = [
            f"Fitted model using {type(self).__name__} with "
            f"{len(self.fit_params)} free parameters, {self.toas.ntoas} TOAs",
            f"Post-fit chi2 = {r.calc_chi2():.4f}  dof = {r.dof}  "
            f"reduced chi2 = {r.reduced_chi2:.4f}",
            f"Post-fit weighted RMS = {r.rms_weighted() * 1e6:.4f} us",
            "",
            f"{'PARAM':12s} {'VALUE':>25s} {'UNCERTAINTY':>15s}",
        ]
        for n in self.fit_params:
            par = self.model[n]
            unc = "" if par.uncertainty is None else \
                f"{par.uncertainty:.3g}"
            lines.append(f"{n:12s} {par.value_as_string():>25s} {unc:>15s}")
        return "\n".join(lines)

    def print_summary(self):  # pragma: no cover - console convenience
        print(self.get_summary())

    def fit_toas(self, maxiter: int = 2, **kw) -> float:
        raise NotImplementedError

    @staticmethod
    def auto(toas, model: TimingModel, downhill: bool = True,
             **kw) -> "Fitter":
        """Pick the appropriate fitter for the data/model combination
        (reference `Fitter.auto`, `/root/reference/src/pint/fitter.py:255`):
        wideband TOAs -> wideband fitter; correlated noise -> GLS;
        otherwise WLS; downhill variants by default.

        Every fitter chosen here runs under the guarded fit engine:
        integer `FitStatus` reporting, step-quality backtracking on the
        eager loops, the fused -> eager -> damped-LM degradation chain
        on accelerator fits, and the TOA validation ``policy`` knob
        (all keyword arguments, including ``policy=``, pass through to
        the chosen class)."""
        if toas.is_wideband:
            cls = WidebandDownhillFitter if downhill else WidebandTOAFitter
        elif model.has_correlated_errors:
            cls = DownhillGLSFitter if downhill else GLSFitter
        else:
            cls = DownhillWLSFitter if downhill else WLSFitter
        return cls(toas, model, **kw)

    def _make_step(self, names, threshold, include_offset):
        """The jitted Gauss-Newton step; WLS by default, overridden by the
        GLS fitters."""
        return build_wls_step(self.model, self.resids.batch, names,
                              self.track_mode, threshold=threshold,
                              include_offset=include_offset,
                              design_matrix=self.design_matrix)

    def _device_pdict(self):
        """The current params pytree, transferred to device ONCE per fit:
        it holds host numpy arrays (the noise basis alone is ~16 MB on
        real data) and would otherwise re-upload on every jitted step
        call — ruinous over a networked TPU tunnel."""
        profiling.count("device_put_pdict")
        with profiling.stage("device_put_pdict"):
            return jax.device_put(self.resids.pdict)

    def _cached_step(self, names, threshold, include_offset):
        """Reuse one jitted step across repeated timing fits (the
        noise-alternating loop calls _fit_timing several times; a fresh
        closure would recompile every time)."""
        key = (tuple(names), threshold, include_offset,
               self.design_matrix)
        if getattr(self, "_step_cache_key", None) != key:
            self._step_cache_key = key
            self._step_cache = self._make_step(names, threshold,
                                               include_offset)
        return self._step_cache

    def _final_step(self, step, x, p, p_host, e_min_hint=None,
                    precomputed=None):
        """Final solve at the converged x: device assembly + host-exact
        solve, escalating to a CPU-exact re-assembly ONLY when the
        conditioning demands it (a kept eigenvalue within reach of the
        ~1e-11 device-assembly noise).  On the CPU backend the assembly
        is already exact, so no second pass ever runs.

        ``e_min_hint``: the last iteration's ``e_min``.  Conditioning
        is a property of the design-matrix STRUCTURE, stable to ~1e-3
        relative across Gauss-Newton steps — so when the hint already
        sits below the floor, the device final (whose assembly+fetch,
        ~0.7 s over a tunneled TPU, would be thrown away) is skipped
        and the CPU-exact pass runs directly.

        ``precomputed``: a step output already evaluated AT ``x`` (the
        guarded eager loop's last accepted trial is exactly that),
        reused instead of a redundant re-dispatch; the exact-covariance
        escalation still applies on top of it."""
        from pint_tpu.utils import effective_platform

        accel = effective_platform() != "cpu"
        # x stays host numpy: the split-assembly column cache reads the
        # nonlinear offsets without a device round trip
        x = np.asarray(x)
        if accel and e_min_hint is not None and \
                e_min_hint < EXACT_COV_EMIN_FLOOR:
            profiling.count("exact_cov_pass")
            return step(x, p, exact=True, p_host=p_host)
        final = precomputed if precomputed is not None else \
            step(x, p, p_host=p_host)
        if accel and float(final["e_min"]) < EXACT_COV_EMIN_FLOOR:
            profiling.count("exact_cov_pass")
            final = step(x, p, exact=True, p_host=p_host)
        return final

    # -- fused whole-fit path (accelerators) ------------------------------
    def _fused_ok(self) -> bool:
        """Whether fit_toas should run as ONE fused device program + one
        transfer (build_fused_fit).  Default: on accelerators only — on
        XLA:CPU the fused whole-fit program is MIScompiled (the same
        scalar-rewrite corruption of the quad-single error-free
        transforms documented in PhaseCalc.phase; measured ~20 ns
        coherent residual shift under the 8-virtual-device test config),
        so the CPU backend keeps the eager step loop.  The decision
        follows the EFFECTIVE default device, not the process backend:
        under `jax.default_device(cpu)` in an accelerator process the
        fused program would compile for (and be corrupted by) XLA:CPU.
        PINT_TPU_FUSED=1 forces the fused path (structural tests only —
        CPU numbers are then approximate); =0 disables it."""
        import os

        flag = os.environ.get("PINT_TPU_FUSED", "")
        if flag == "0":
            return False
        if flag == "1":
            return True
        from pint_tpu.utils import effective_platform

        return effective_platform() != "cpu"

    def _make_fused(self, names, threshold, include_offset, maxiter,
                    tol_chi2):
        return build_fused_fit(self.model, self.resids.batch, names,
                               self.track_mode, threshold=threshold,
                               include_offset=include_offset,
                               maxiter=maxiter, tol_chi2=tol_chi2,
                               design_matrix=self.design_matrix)

    def _cached_fused(self, names, threshold, include_offset, maxiter,
                      tol_chi2):
        key = (tuple(names), threshold, include_offset, maxiter, tol_chi2,
               self.design_matrix)
        if getattr(self, "_fused_cache_key", None) != key:
            self._fused_cache_key = key
            self._fused_cache = self._make_fused(
                names, threshold, include_offset, maxiter, tol_chi2)
        return self._fused_cache

    def _fit_fused(self, maxiter, threshold, tol_chi2=1e-8) -> float:
        m = self.model
        names = self.fit_params
        p = self._device_pdict()
        p_host = self.resids.pdict
        include_offset = "PhaseOffset" not in m.components
        fit = self._cached_fused(names, threshold, include_offset, maxiter,
                                 tol_chi2)
        x, out = fit(p, p_host=p_host)
        status = out.get("status", FitStatus.CONVERGED)
        if status in (FitStatus.DIVERGED, FitStatus.NONFINITE):
            # graceful degradation (ISSUE 3 leg 3): the fused program's
            # sentinel tripped — nothing has been written back to the
            # model, so the eager rung restarts from the same state
            return self._degraded_fit(status, maxiter, threshold,
                                      tol_chi2)
        if int(out["n_bad"]):
            warnings.warn(
                f"{int(out['n_bad'])} degenerate parameter "
                "combination(s) dropped by SVD threshold",
                DegeneracyWarning)
        Sigma = denormalize_covariance(out["Sigma_n"], out["norms"])
        # host pdict everywhere below: basis reads and delta write-back
        # must not round-trip the accelerator
        self._store_noise(out, p_host)
        # seed only when the profiled-offset residuals match the
        # Residuals definition: weighted-mean subtraction (the default),
        # or no subtraction AND no offset actually profiled
        tr = getattr(self.resids, "toa", self.resids)
        seed_ok = (tr.subtract_mean and tr.use_weighted_mean) or \
            (not tr.subtract_mean and float(out["offset"]) == 0.0)
        seed = (out["resid_sec"], float(out["offset"])) if seed_ok \
            else None
        self._finalize(p_host, x, Sigma, names, resid_seed=seed)
        self.fitresult = FitSummary(
            float(out["chi2"]), self.resids.dof,
            out.get("iterations", maxiter), True, status=status,
            rung="fused", guard_trips={})
        self._record_provenance()
        return float(out["chi2"])

    #: the degradation-chain rungs tried after a fused DIVERGED/
    #: NONFINITE, in order; each gets ONE attempt
    DEGRADATION_RUNGS = ("eager", "lm")

    def _degraded_fit(self, fused_status, maxiter, threshold,
                      tol_chi2) -> float:
        """fused -> eager stepwise -> damped LM, one attempt each
        (ISSUE 3 leg 3).  A rung "succeeds" when it finishes with a
        finite chi2 and a status other than DIVERGED/NONFINITE; the
        winning rung is recorded in ``FitSummary.rung`` and the model
        provenance.  When every rung fails, raises
        :class:`~pint_tpu.exceptions.ConvergenceFailure` carrying the
        per-rung statuses — never a silent garbage chi2."""
        statuses = {"fused": fused_status}
        warnings.warn(
            f"fused fit ended {fused_status.name}; degrading to the "
            "eager stepwise fitter", FitDegradedWarning)
        for rung in self.DEGRADATION_RUNGS:
            profiling.count(f"guard.degrade_{rung}")
            try:
                with telemetry.span("fitter.degrade", rung=rung,
                                    fused_status=fused_status.name):
                    if rung == "eager":
                        chi2 = self._fit_eager(maxiter=max(maxiter, 8),
                                               threshold=threshold,
                                               tol_chi2=tol_chi2)
                    else:
                        chi2 = self._fit_lm_rescue(threshold=threshold,
                                                   tol_chi2=tol_chi2)
                st = self.fitresult.status
            except ConvergenceFailure as e:
                statuses[rung] = e.status if e.status is not None else \
                    FitStatus.NONFINITE
                warnings.warn(
                    f"{rung} rung failed "
                    f"({statuses[rung].name}); "
                    + ("degrading to damped LM" if rung != "lm"
                       else "degradation chain exhausted"),
                    FitDegradedWarning)
                continue
            statuses[rung] = st
            if np.isfinite(chi2) and st not in (FitStatus.DIVERGED,
                                                FitStatus.NONFINITE):
                self.fitresult = self.fitresult._replace(rung=rung)
                self._record_provenance(statuses)
                return chi2
            warnings.warn(
                f"{rung} rung ended {st.name}"
                + ("; degrading to damped LM" if rung != "lm" else
                   "; degradation chain exhausted"),
                FitDegradedWarning)
        telemetry.warn(
            "fitter.chain_exhausted",
            statuses={k: v.name for k, v in statuses.items()})
        telemetry.dump_on_failure("ConvergenceFailure")
        raise ConvergenceFailure(
            "fit failed through the whole degradation chain "
            f"(fused -> eager -> LM): { {k: v.name for k, v in statuses.items()} }",
            status=statuses.get("lm", fused_status),
            rung_statuses=statuses)

    def _fit_lm_rescue(self, threshold=None, tol_chi2=1e-8) -> float:
        """The chain's last rung: a damped Levenberg-Marquardt fit over
        the same (toas, model, residuals), independent of the WLS solve
        kernels (its damped normal-equations solve and trial-point chi2
        survive a poisoned `fit_wls_*`)."""
        lm = LMFitter(self.toas, self.model, residuals=self.resids,
                      design_matrix=self.design_matrix)
        chi2 = lm.fit_toas(threshold=threshold, tol_chi2=tol_chi2)
        self.fitresult = lm.fitresult
        self.parameter_covariance_matrix = lm.parameter_covariance_matrix
        self.covariance_params = lm.covariance_params
        return chi2

    def _record_provenance(self, rung_statuses=None):
        """Stamp the fit's provenance onto the model (alongside the
        START/FINISH/CHI2 bookkeeping of update_model): which rung of
        the degradation chain produced the accepted solution, its
        FitStatus, and — after a degraded fit — every attempted rung's
        status."""
        fr = self.fitresult
        self.model.fit_provenance = {
            "fitter": type(self).__name__,
            "rung": fr.rung,
            "status": fr.status.name,
            "rung_statuses": {k: v.name
                              for k, v in (rung_statuses or {}).items()},
        }

    def _store_noise(self, out, p):
        """Recover per-component noise realizations from the basis
        amplitudes (reference `fitter.py:1952-1968`)."""
        if "noise_ampls" not in out:
            # e.g. the full-covariance path: drop any stale realizations
            # from a previous basis-path fit rather than present them as
            # current
            self.noise_ampls = {}
            self.noise_resids = {}
            return
        ampls = np.asarray(out["noise_ampls"])
        self.noise_ampls = {}
        self.noise_resids = {}
        k = 0
        for c in self.model.correlated_noise_components:
            U = np.asarray(p["const"][c.basis_pytree_name])
            w = U.shape[1]
            a = ampls[k:k + w]
            self.noise_ampls[type(c).__name__] = a
            self.noise_resids[type(c).__name__] = U @ a
            k += w

    def _seed_resids(self, r_sec: np.ndarray, offset: float):
        """Prime the post-fit residual cache from the fused fit's final
        assembly (unsubtracted residuals [s] + profiled offset) instead
        of re-running the device pipeline: the offset-profiled residuals
        ARE the weighted-mean-subtracted residuals when the offc
        regressor is all-ones with 1/sigma^2 weights (the default
        Residuals definition), up to the (converged-fit-negligible)
        TZR-phase shift of the written-back parameters.  Callers guard
        on the residual configuration actually matching."""
        tr = getattr(self.resids, "toa", self.resids)
        nt = tr.batch.ntoas
        tr._phase_resids = np.asarray(
            (r_sec[:nt] - offset) * float(self.model.F0.value))
        tr._chi2_cache = None

    def _finalize(self, p: dict, x: np.ndarray, Sigma: np.ndarray,
                  names: List[str], resid_seed=None):
        """Write the solution back into host parameters + uncertainties.
        ``x`` stays host numpy throughout: with_x then stores numpy
        scalars in the delta leaves, so apply_deltas needs no
        device->host fetch at all.  ``resid_seed``: optional
        ``(r_sec, offset)`` from a fused fit's final assembly, applied
        after the model update so post-fit bookkeeping skips one device
        pipeline dispatch (see `_seed_resids`)."""
        m = self.model
        p2 = m.with_x(p, np.asarray(x), names)
        m.apply_deltas(p2)
        diag = np.diag(np.asarray(Sigma))
        if not np.all(np.isfinite(diag)):
            # covariance guard: a poisoned solve must not write NaN
            # uncertainties into the model as if they were measurements
            bad = [n for n, v in zip(names, diag) if not np.isfinite(v)]
            warnings.warn(
                f"non-finite parameter covariance for {bad}; their "
                "uncertainties are left unset", PintTpuWarning)
            profiling.count("guard.nonfinite_covariance")
        for i, n in enumerate(names):
            if np.isfinite(diag[i]):
                m[n].set_device_uncertainty(float(np.sqrt(max(
                    diag[i], 0.0))))
        self.parameter_covariance_matrix = np.asarray(Sigma)
        self.covariance_params = list(names)
        with profiling.stage("finalize_resid_update"):
            self.resids.update()
        if resid_seed is not None:
            self._seed_resids(*resid_seed)
        with profiling.stage("finalize_update_model"):
            self.update_model()


class WLSFitter(Fitter):
    """Iterated linear WLS (reference `WLSFitter`,
    `/root/reference/src/pint/fitter.py:1703`): each iteration solves the
    linearized problem by SVD and applies the full step — now with
    step-quality control (ISSUE 3 leg 2): a step that raises chi2 beyond
    ``max_chi2_increase`` is backtracked with bounded halving
    (lambda = 1, 1/2, 1/4, ... down to ``min_lambda``), the reference
    `DownhillFitter` lambda backoff generalized so the PLAIN fitters get
    it too.  A fit whose chi2 goes non-finite raises
    :class:`~pint_tpu.exceptions.ConvergenceFailure` instead of
    returning the poisoned number (see MIGRATION.md)."""

    def fit_toas(self, maxiter: int = 2, threshold: Optional[float] = None,
                 tol_chi2: float = 1e-8, min_lambda: float = 1e-3,
                 max_chi2_increase: float = 1e-2) -> float:
        if self._fused_ok():
            return self._fit_fused(maxiter, threshold, tol_chi2)
        return self._fit_eager(maxiter=maxiter, threshold=threshold,
                               tol_chi2=tol_chi2, min_lambda=min_lambda,
                               max_chi2_increase=max_chi2_increase)

    def _fit_eager(self, maxiter: int = 2,
                   threshold: Optional[float] = None,
                   tol_chi2: float = 1e-8, min_lambda: float = 1e-3,
                   max_chi2_increase: float = 1e-2) -> float:
        """The guarded eager step loop (also the degradation chain's
        second rung).  Each accepted trial's step output doubles as the
        next iteration's linearization AND, at the end, as the final
        solve — the guarded loop costs no extra dispatches over the
        unguarded one (1 initial + <= maxiter accepted trials)."""
        m = self.model
        names = self.fit_params
        p = self._device_pdict()
        include_offset = "PhaseOffset" not in m.components
        step = self._cached_step(names, threshold, include_offset)
        p_host = self.resids.pdict
        guard_trips: Dict[str, int] = {}

        def trip(name):
            guard_trips[name] = guard_trips.get(name, 0) + 1
            profiling.count(f"guard.{name}")

        x = np.zeros(len(names))
        out = step(x, p, p_host=p_host)
        chi2 = float(out["chi2"])
        if not np.isfinite(chi2):
            trip("eager_nonfinite")
            raise ConvergenceFailure(
                f"chi2 is non-finite ({chi2}) at the start point — "
                "poisoned uncertainties or residuals (check the TOA "
                "validation policy)", status=FitStatus.NONFINITE)
        status = FitStatus.MAXITER
        it = -1
        for it in range(maxiter):
            if int(out["n_bad"]):
                warnings.warn(
                    f"{int(out['n_bad'])} degenerate parameter "
                    "combination(s) dropped by SVD threshold",
                    DegeneracyWarning)
            dx = np.asarray(out["dx"])
            if not np.all(np.isfinite(dx)):
                # solver-output guard: a NaN/inf step cannot be walked
                trip("eager_nonfinite_step")
                status = FitStatus.NONFINITE
                break
            lam = 1.0
            trial = None
            while True:
                cand = step(x + lam * dx, p, p_host=p_host)
                t_chi2 = float(cand["chi2"])
                if np.isfinite(t_chi2) and \
                        t_chi2 <= chi2 + max_chi2_increase:
                    trial = cand
                    break
                trip("eager_backtrack")
                lam *= 0.5
                if lam < min_lambda:
                    break
            if trial is None:
                # no acceptable step length even at min lambda: stop at
                # the (finite) pre-step x instead of walking uphill
                trip("eager_step_rejected")
                status = FitStatus.DIVERGED
                break
            x = x + lam * dx
            improvement = chi2 - t_chi2
            chi2 = t_chi2
            out = trial
            if abs(improvement) < tol_chi2:
                status = FitStatus.CONVERGED
                break
        if status is FitStatus.NONFINITE:
            raise ConvergenceFailure(
                "WLS solve produced a non-finite step "
                f"(iteration {it}); chi2 at the last good point: "
                f"{chi2:.6g}", status=FitStatus.NONFINITE)
        # final solve at the converged x: `out` IS the step output at x
        # (the last accepted trial), so no re-dispatch unless the
        # exact-covariance escalation demands one
        final = self._final_step(step, x, p, p_host,
                                 e_min_hint=float(out["e_min"]),
                                 precomputed=out)
        Sigma = denormalize_covariance(final["Sigma_n"], final["norms"])
        self._store_noise(final, p_host)
        # seed post-fit residuals from the final assembly (same guard
        # as the fused path): skips the ~0.5 s device re-dispatch that
        # post-fit bookkeeping (calc_chi2/TRES) would otherwise pay.
        # GLS is EXCLUDED: its offset is profiled in the C^-1 (Woodbury)
        # metric, not the diagonal weighted mean the Residuals
        # definition subtracts — seeding there was measured to bias the
        # stored residuals by ~9 us constant on B1855.
        tr = getattr(self.resids, "toa", self.resids)
        seed_ok = not self.model.has_correlated_errors and (
            (tr.subtract_mean and tr.use_weighted_mean) or
            (not tr.subtract_mean
             and float(final.get("offset", 0.0)) == 0.0))
        seed = (np.asarray(final["resid_sec"]),
                float(final.get("offset", 0.0))) if seed_ok else None
        self._finalize(p_host, x, Sigma, names, resid_seed=seed)
        if status is FitStatus.DIVERGED:
            warnings.warn(
                "no acceptable step length found (chi2 rises even at "
                f"lambda={min_lambda:g}); returning the best point "
                "found", PintTpuWarning)
        self.fitresult = FitSummary(
            float(final["chi2"]), self.resids.dof, it + 1,
            status in (FitStatus.CONVERGED, FitStatus.MAXITER),
            status=status, rung="eager", guard_trips=guard_trips)
        self._record_provenance()
        return float(final["chi2"])


class GLSFitter(WLSFitter):
    """Generalized least squares over the augmented [timing | noise-basis]
    design matrix (reference `GLSFitter`,
    `/root/reference/src/pint/fitter.py:1821`); chi2 is the Woodbury
    r^T C^-1 r.  Also valid (and equal to WLS) with no correlated
    components.

    ``fit_toas(full_cov=True)`` switches to the dense-covariance solve
    (C = N + U Phi U^T formed and Cholesky-factored, reference
    ``full_cov=True`` path) — the O(N^2) cross-check of the basis path.
    """

    #: selected by fit_toas(full_cov=...); part of the step-cache key
    full_cov = False

    def fit_toas(self, maxiter: int = 2, *, full_cov: bool = False,
                 **kw) -> float:
        if full_cov != self.full_cov:
            self.full_cov = full_cov
            self._step_cache_key = None  # invalidate the cached step
        return super().fit_toas(maxiter=maxiter, **kw)

    def _make_step(self, names, threshold, include_offset):
        build = build_gls_fullcov_step if self.full_cov else build_gls_step
        return build(self.model, self.resids.batch, names,
                     self.track_mode, threshold=threshold,
                     include_offset=include_offset,
                     design_matrix=self.design_matrix)

    def _fused_ok(self) -> bool:
        # Never fused: a B1855-class GLS normal matrix has physical
        # structure below the accelerator's emulated-f64 Gram noise, and
        # a device-solved iteration step there is garbage (measured:
        # chi2 1e8 after one fused device step vs ~4200 host-solved).
        # The GLS step loop host-solves EVERY step from a
        # batched-fetched device assembly instead — with the host-pdict
        # exact pass and device-free finalize this is a ~2 s fit, not a
        # 75 s one.
        return False


class DownhillWLSFitter(Fitter):
    """Gauss-Newton with backtracking line search (reference
    `DownhillFitter`/`DownhillWLSFitter`,
    `/root/reference/src/pint/fitter.py:915,1268`): a proposed step is
    halved (lambda = 1, 1/2, 1/4, ...) until chi2 decreases; convergence
    when the step's predicted chi2 improvement is below tolerance.

    Free noise parameters (EFAC/EQUAD/ECORR/red amplitudes) are fit by
    numerically maximizing the log-likelihood, alternating with the
    timing fit (reference `DownhillFitter.fit_toas` noise path,
    `/root/reference/src/pint/fitter.py:1040,1167`) — here with autodiff
    gradient and Hessian of the jitted likelihood."""

    fits_noise = True

    def fit_toas(self, maxiter: int = 20, noise_fit_niter: int = 2,
                 threshold: Optional[float] = None,
                 min_lambda: float = 1e-3,
                 required_chi2_decrease: float = 1e-2,
                 max_chi2_increase: float = 1e-2) -> float:
        noise_names = self.free_noise_params
        if not noise_names:
            return self._fit_timing(
                maxiter=maxiter, threshold=threshold, min_lambda=min_lambda,
                required_chi2_decrease=required_chi2_decrease,
                max_chi2_increase=max_chi2_increase)
        for it in range(noise_fit_niter):
            self._fit_timing(
                maxiter=maxiter, threshold=threshold, min_lambda=min_lambda,
                required_chi2_decrease=required_chi2_decrease,
                max_chi2_increase=max_chi2_increase)
            self._fit_noise(noise_names,
                            uncertainty=(it == noise_fit_niter - 1))
        return self._fit_timing(
            maxiter=maxiter, threshold=threshold, min_lambda=min_lambda,
            required_chi2_decrease=required_chi2_decrease,
            max_chi2_increase=max_chi2_increase)

    def _fit_noise(self, noise_names: List[str],
                   uncertainty: bool = False) -> None:
        """Maximize the likelihood over the free noise parameters at the
        current timing solution (reference `_fit_noise`, `fitter.py:1167`);
        autodiff gradient, L-BFGS-B, Hessian-based uncertainties."""
        from scipy.optimize import minimize

        self.resids.update()
        p = self._device_pdict()
        m = self.model
        # cache the jitted likelihood/gradient pair across the alternating
        # iterations (same reason as _cached_step: a fresh closure would
        # recompile every time)
        key = tuple(noise_names)
        if getattr(self, "_noise_lnlike_key", None) != key:
            wb = getattr(self.resids, "dm_index", None)
            kw = {}
            if wb is not None:
                # wideband: include the DM-residual Gaussian term so
                # DMEFAC/DMEQUAD-class parameters have a live gradient
                kw = dict(dm_index=self.resids.dm_index,
                          dm_data=self.resids.dm_data,
                          dm_error=self.resids.dm_error)
            lnl = build_noise_lnlike(m, self.resids.batch, noise_names,
                                     self.track_mode, **kw)
            self._noise_lnlike_key = key
            self._noise_lnlike = lnl
            self._noise_grad = jax.jit(jax.grad(lnl))
        lnlike = self._noise_lnlike
        # faultinject failpoint: tests poison the gradient here to drive
        # the non-finite-Hessian fallback below (no cost when inactive)
        grad = faultinject.wrap("noise_grad", self._noise_grad)
        x0 = np.asarray(m.x0(p, noise_names))
        # an EQUAD-class parameter at exactly 0 is a stationary point of
        # the likelihood (it enters squared): the gradient there is
        # identically zero and a quasi-Newton iteration never leaves it.
        # Nudge zero starts off the saddle.
        x0 = np.where(x0 == 0.0, 0.05, x0)

        def nll(x):
            return -float(lnlike(jnp.asarray(x), p))

        def nll_grad(x):
            return -np.asarray(grad(jnp.asarray(x), p))

        res = minimize(nll, x0, jac=nll_grad, method="L-BFGS-B")
        x = res.x
        p2 = m.with_x(p, jnp.asarray(x), noise_names)
        m.apply_deltas(p2)
        if uncertainty:
            # observed information by central differences of the jitted
            # gradient: forward-over-reverse autodiff of the likelihood
            # NaNs on TPU's emulated f64, and 2n gradient calls are cheap
            h = 1e-3 * np.maximum(np.abs(x), 0.1)
            H = np.zeros((len(x), len(x)))
            for k in range(len(x)):
                xp = x.copy()
                xp[k] += h[k]
                xm = x.copy()
                xm[k] -= h[k]
                H[:, k] = (np.asarray(grad(jnp.asarray(xp), p))
                           - np.asarray(grad(jnp.asarray(xm), p))) \
                    / (2.0 * h[k])
            H = 0.5 * (H + H.T)
            # covariance = pseudo-inverse observed information (pinv:
            # flat directions at a boundary give 0 rather than blowing
            # up the whole matrix)
            if np.all(np.isfinite(H)):
                cov = np.linalg.pinv(-H)
                errs = np.sqrt(np.maximum(np.diag(cov), 0.0))
            else:
                # guard: a poisoned likelihood gradient must not write
                # NaN noise-parameter uncertainties into the model
                profiling.count("guard.noise_hessian_nonfinite")
                warnings.warn(
                    "noise-fit Hessian is non-finite; noise parameter "
                    f"uncertainties for {noise_names} are left unset",
                    PintTpuWarning)
                errs = np.full(len(noise_names), np.nan)
            for n, e in zip(noise_names, errs):
                if np.isfinite(e) and e > 0:
                    m[n].set_device_uncertainty(float(e))
        self.resids.update()

    def _fit_timing(self, maxiter: int = 20,
                    threshold: Optional[float] = None,
                    min_lambda: float = 1e-3,
                    required_chi2_decrease: float = 1e-2,
                    max_chi2_increase: float = 1e-2) -> float:
        m = self.model
        names = self.fit_params
        p = self._device_pdict()
        include_offset = "PhaseOffset" not in m.components
        step = self._cached_step(names, threshold, include_offset)
        p_host = self.resids.pdict
        x = np.zeros(len(names))
        out = step(x, p, p_host=p_host)
        chi2 = float(out["chi2"])
        converged = False
        exception = None
        it = -1
        for it in range(maxiter):
            dx = np.asarray(out["dx"])
            lam = 1.0
            while True:
                trial = step(x + lam * dx, p, p_host=p_host)
                trial_chi2 = float(trial["chi2"])
                if trial_chi2 <= chi2 + max_chi2_increase:
                    break
                lam *= 0.5
                if lam < min_lambda:
                    exception = ConvergenceFailure(
                        f"step rejected down to lambda={lam:.2g} "
                        f"(chi2 {chi2:.4f} -> {trial_chi2:.4f})")
                    break
            if exception is not None:
                break
            x = x + lam * dx
            improvement = chi2 - trial_chi2
            chi2 = trial_chi2
            out = trial
            if lam == 1.0 and improvement < required_chi2_decrease:
                converged = True
                break
        if not np.isfinite(chi2):
            raise ConvergenceFailure(
                f"downhill fit chi2 is non-finite ({chi2})",
                status=FitStatus.NONFINITE)
        # final covariance: device assembly + host solve, CPU-exact
        # re-assembly only when conditioning demands (_final_step)
        final = self._final_step(step, x, p, p_host,
                                 e_min_hint=float(out["e_min"]))
        self._store_noise(final, p_host)
        self._finalize(p_host, x,
                       denormalize_covariance(final["Sigma_n"],
                                              final["norms"]), names)
        if converged:
            status = FitStatus.CONVERGED
        elif exception is not None:
            status = FitStatus.DIVERGED
            profiling.count("guard.downhill_step_rejected")
        else:
            status = FitStatus.MAXITER
        self.fitresult = FitSummary(
            chi2, self.resids.dof, it + 1, converged, status=status,
            rung="downhill",
            guard_trips=({"downhill_step_rejected": 1}
                         if status is FitStatus.DIVERGED else {}))
        self._record_provenance()
        if exception is not None and not converged:
            warnings.warn(str(exception))
        return chi2


class DownhillGLSFitter(DownhillWLSFitter, GLSFitter):
    """Downhill line search over the GLS step (reference
    `DownhillGLSFitter`, `/root/reference/src/pint/fitter.py:1386`):
    fit_toas from the downhill base, _make_step from GLSFitter."""


class PowellFitter(Fitter):
    """Derivative-free Powell minimization of chi2 (reference
    `PowellFitter`, `/root/reference/src/pint/fitter.py:1659`, built on
    scipy fmin_powell).  Each chi2 evaluation is the jitted device
    pipeline; useful when the Gauss-Newton step misbehaves (strong
    nonlinearity, poor starting point)."""

    def fit_toas(self, maxiter: int = 2000, **kw) -> float:
        from scipy.optimize import minimize

        m = self.model
        names = self.fit_params
        p = self._device_pdict()
        include_offset = "PhaseOffset" not in m.components
        step = self._make_step(names, None, include_offset)
        # optimize in units of the parameter UNCERTAINTIES so Powell's
        # line searches see O(1) coordinates for every parameter (the
        # initial Gauss-Newton step can be ~0 for a parameter already at
        # its conditional optimum, which must not freeze it)
        out0 = step(np.zeros(len(names)), p)
        unc = np.sqrt(np.maximum(np.diag(denormalize_covariance(
            out0["Sigma_n"], out0["norms"])), 0.0))
        scale = np.maximum(unc, np.abs(np.asarray(out0["dx"])))
        scale = np.where(scale > 0, scale, 1.0)
        chi2_fn = build_chi2_fn(m, self.resids.batch, names,
                                self.track_mode, include_offset)

        def chi2(z):
            return float(chi2_fn(jnp.asarray(z * scale), p))

        res = minimize(chi2, np.zeros(len(names)), method="Powell",
                       options={"maxiter": maxiter, "xtol": 1e-10,
                                "ftol": 1e-12})
        x = res.x * scale
        p_host = self.resids.pdict
        final = self._final_step(step, x, p, p_host)
        Sigma = denormalize_covariance(final["Sigma_n"], final["norms"])
        self._store_noise(final, p_host)
        self._finalize(p_host, x, Sigma, names)
        self.fitresult = FitSummary(
            float(final["chi2"]), self.resids.dof, int(res.nit),
            bool(res.success),
            status=(FitStatus.CONVERGED if res.success
                    else FitStatus.MAXITER),
            rung="powell", guard_trips={})
        self._record_provenance()
        return float(final["chi2"])


class LMFitter(Fitter):
    """Levenberg-Marquardt: the Gauss-Newton normal matrix damped by
    ``lambda * diag`` with adaptive damping (reference `LMFitter`,
    `/root/reference/src/pint/fitter.py:2313`).  The damped solve runs on
    device from the same whitened assembly as WLS."""

    def _make_assembly(self, names, include_offset):
        return build_whitened_assembly(self.model, self.resids.batch,
                                       names, self.track_mode,
                                       include_offset,
                                       design_matrix=self.design_matrix)

    def _make_chi2_fn(self, names, include_offset):
        return build_chi2_fn(self.model, self.resids.batch, names,
                             self.track_mode, include_offset)

    def fit_toas(self, maxiter: int = 50, lam0: float = 1e-3,
                 lam_decrease: float = 3.0, lam_increase: float = 5.0,
                 tol_chi2: float = 1e-8, threshold=None) -> float:
        m = self.model
        names = self.fit_params
        p = self._device_pdict()
        include_offset = "PhaseOffset" not in m.components
        assemble = self._make_assembly(names, include_offset)

        @jax.jit
        def damped_solve(r, M, sigma, offc, lam):
            Mw = M / sigma[:, None]
            rw = r / sigma
            cmax = jnp.max(jnp.abs(Mw), axis=0)
            cmax = jnp.where(cmax == 0.0, 1.0, cmax)
            Mn, nc = normalize_designmatrix(Mw / cmax)
            norms = cmax * nc
            A = Mn.T @ Mn
            A = A + lam * jnp.diag(jnp.diag(A))
            # eigh, not LU: TPU's PJRT implements no f64 LuDecomposition
            # (A is symmetric positive-definite here)
            e, V = jnp.linalg.eigh(A)
            bad = e <= _machine_eps() * A.shape[0] * e[-1]
            einv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, e))
            dx = (V @ (einv * (V.T @ (Mn.T @ rw)))) / norms
            if offc is not None:
                w = offc / sigma**2
                off = jnp.sum(r * w) / jnp.sum(w * offc)
                chi2 = jnp.sum(((r - off * offc) / sigma) ** 2)
            else:
                chi2 = jnp.sum(rw**2)
            return dx[:len(names)], chi2

        def damped_step(x, lam):
            r, M, sigma, offc = assemble(x, p)
            return damped_solve(r, M, sigma, offc, lam)

        chi2_fn = self._make_chi2_fn(names, include_offset)
        guard_trips: Dict[str, int] = {}
        x = np.zeros(len(names))
        lam = lam0
        chi2 = float(chi2_fn(jnp.asarray(x), p))
        status = FitStatus.MAXITER
        it = 0
        for it in range(maxiter):
            dx, _ = damped_step(x, lam)
            x_try = x + np.asarray(dx)
            chi2_try = float(chi2_fn(jnp.asarray(x_try), p))
            if np.isfinite(chi2_try) and chi2_try < chi2:
                improvement = chi2 - chi2_try
                x, chi2 = x_try, chi2_try
                lam = max(lam / lam_decrease, 1e-12)
                if improvement < tol_chi2:
                    status = FitStatus.CONVERGED
                    break
            else:
                if np.isfinite(chi2_try) and \
                        abs(chi2_try - chi2) < tol_chi2:
                    # the rejected trial changed chi2 by less than the
                    # tolerance: we are at the minimum
                    status = FitStatus.CONVERGED
                    break
                lam *= lam_increase
                if lam > 1e12:
                    # the lambda-overflow bailout: no damping level
                    # yields an acceptable step (driven in tests via
                    # faultinject.nan_sigma)
                    guard_trips["lm_lambda_overflow"] = 1
                    profiling.count("guard.lm_lambda_overflow")
                    warnings.warn(
                        "LM damping diverged (lambda overflow); returning "
                        "the best point found")
                    status = FitStatus.DIVERGED
                    break
        if not np.isfinite(chi2):
            # never hand back a poisoned chi2: the start point itself
            # was non-finite and no trial ever improved on it
            raise ConvergenceFailure(
                f"LM fit chi2 is non-finite ({chi2}) after {it + 1} "
                "iteration(s)", status=FitStatus.NONFINITE)
        # covariance from the undamped step at the solution
        step = self._cached_step(names, threshold, include_offset)
        p_host = self.resids.pdict
        final = self._final_step(step, x, p, p_host)
        Sigma = denormalize_covariance(final["Sigma_n"], final["norms"])
        self._store_noise(final, p_host)
        self._finalize(p_host, x, Sigma, names)
        self.fitresult = FitSummary(
            chi2, self.resids.dof, it + 1,
            status in (FitStatus.CONVERGED, FitStatus.MAXITER),
            status=status, rung="lm", guard_trips=guard_trips)
        self._record_provenance()
        return chi2


class WidebandTOAFitter(GLSFitter):
    """Wideband fitter: simultaneous TOA + DM least squares (reference
    `WidebandTOAFitter`, `/root/reference/src/pint/fitter.py:1975`).

    The data vector stacks time residuals [s] and DM residuals [pc cm^-3]
    (the TOAs' ``-pp_dm``/``-pp_dme`` flags); one `jax.jacfwd` of the
    stacked residual function yields the combined design matrix, replacing
    the reference's `pint_matrix` block assembly (`pint_matrix.py:532`).
    GLS-based, so correlated noise (ECORR/red) on the TOA block is handled;
    without correlated components it reduces to wideband WLS.
    """

    def __init__(self, toas, model: TimingModel,
                 track_mode: Optional[str] = None,
                 design_matrix: Optional[str] = None,
                 policy: Optional[str] = None):
        from pint_tpu.residuals import WidebandTOAResiduals

        wb = WidebandTOAResiduals(toas, model, track_mode=track_mode,
                                  policy=policy)
        super().__init__(toas, model, residuals=wb,
                         design_matrix=design_matrix, policy=policy)

    def _make_step(self, names, threshold, include_offset):
        wb = self.resids

        def builder(batch):
            return build_wideband_assembly(
                self.model, batch, wb.dm_index, wb.dm_data, wb.dm_error,
                names, self.track_mode, include_offset,
                design_matrix=self.design_matrix)

        if self.full_cov:
            return build_gls_fullcov_step(
                self.model, wb.batch, names, self.track_mode,
                threshold=threshold, include_offset=include_offset,
                assemble=builder(wb.batch))
        return build_gls_step(self.model, wb.batch, names,
                              self.track_mode, threshold=threshold,
                              include_offset=include_offset,
                              assemble=builder(wb.batch),
                              assemble_builder=builder)

    def get_designmatrix(self):
        """(M, names): the *combined* TOA+DM design matrix — TOA rows in
        [s/unit], DM rows in [pc cm^-3/unit] (reference
        `WidebandTOAFitter.get_designmatrix`,
        `/root/reference/src/pint/fitter.py:2052`)."""
        names = self.fit_params
        wb = self.resids
        assemble = build_wideband_assembly(
            self.model, wb.batch, wb.dm_index, wb.dm_data, wb.dm_error,
            names, self.track_mode, include_offset=False)
        p = wb.pdict
        x = self.model.x0(p, names)
        _, M, _, _ = jax.jit(assemble)(x, p)
        return np.asarray(M), names


class WidebandLMFitter(LMFitter, WidebandTOAFitter):
    """Levenberg-Marquardt over the combined TOA+DM wideband assembly
    (reference `WidebandLMFitter`,
    `/root/reference/src/pint/fitter.py:2436`)."""

    def __init__(self, toas, model: TimingModel,
                 track_mode: Optional[str] = None,
                 policy: Optional[str] = None):
        WidebandTOAFitter.__init__(self, toas, model,
                                   track_mode=track_mode, policy=policy)

    def _make_assembly(self, names, include_offset):
        wb = self.resids
        return build_wideband_assembly(
            self.model, wb.batch, wb.dm_index, wb.dm_data, wb.dm_error,
            names, self.track_mode, include_offset,
            design_matrix=self.design_matrix)

    def _make_chi2_fn(self, names, include_offset):
        wb = self.resids
        return build_wideband_chi2_fn(
            self.model, wb.batch, wb.dm_index, wb.dm_data, wb.dm_error,
            names, self.track_mode, include_offset)


class WidebandDownhillFitter(DownhillWLSFitter, WidebandTOAFitter):
    """Downhill line search over the wideband GLS step (reference
    `WidebandDownhillFitter`, `/root/reference/src/pint/fitter.py:1558`)."""
