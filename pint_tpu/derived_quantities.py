"""Astrophysical quantities derived from timing parameters.

Reference: `derived_quantities.py`
(`/root/reference/src/pint/derived_quantities.py`) — the same formula set,
in plain SI/astronomer floats instead of astropy Quantities.  Unit
conventions (documented per function): periods [s], frequencies [Hz],
orbital periods [days], projected semi-major axes [light-s], masses
[Msun], angles [deg or rad as noted], magnetic fields [G].
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from pint_tpu import GMsun, Tsun, c as C

__all__ = [
    "p_to_f", "pferrs", "pulsar_age", "pulsar_edot", "pulsar_B",
    "pulsar_B_lightcyl", "mass_funct", "mass_funct2", "pulsar_mass",
    "companion_mass", "pbdot", "gamma", "omdot", "sini", "omdot_to_mtot",
    "a1sini", "shklovskii_factor", "dispersion_slope",
]

SECS_PER_DAY = 86400.0
SECS_PER_YEAR = 365.25 * SECS_PER_DAY
Tsun_s = Tsun                              # ~4.925490947e-6 s
I_NS = 1.0e45                              # canonical moment of inertia, g cm^2
PC_M = 3.0856775814913673e16


def p_to_f(p, pd, pdd: Optional[float] = None):
    """Period [s] (+derivatives) -> frequency [Hz] (+derivatives)
    (reference ibid:37)."""
    f = 1.0 / p
    fd = -pd / p**2
    if pdd is None:
        return f, fd
    fdd = 0.0 if pdd == 0.0 else 2.0 * pd**2 / p**3 - pdd / p**2
    return f, fd, fdd


def pferrs(porf, porferr, pdorfd=None, pdorfderr=None):
    """(value, error) propagation for the p<->f transformation
    (reference ibid:88)."""
    if pdorfd is None:
        return 1.0 / porf, porferr / porf**2
    forp = 1.0 / porf
    fdorpd = -pdorfd / porf**2
    fdorpderr = math.sqrt((4.0 * pdorfd**2 * porferr**2 / porf**6)
                          + pdorfderr**2 / porf**4)
    return forp, porferr / porf**2, fdorpd, fdorpderr


def pulsar_age(f: float, fdot: float, n: int = 3) -> float:
    """Characteristic age [yr], -f/((n-1) fdot) (reference ibid:148)."""
    return -f / ((n - 1) * fdot) / SECS_PER_YEAR


def pulsar_edot(f: float, fdot: float, I: float = I_NS) -> float:
    """Spin-down luminosity [erg/s] (reference ibid:193)."""
    return -4.0 * math.pi**2 * I * f * fdot


def pulsar_B(f: float, fdot: float) -> float:
    """Surface dipole field estimate [G], 3.2e19 sqrt(P Pdot)
    (reference ibid:231)."""
    return 3.2e19 * math.sqrt(max(-fdot / f**3, 0.0))


def pulsar_B_lightcyl(f: float, fdot: float) -> float:
    """Light-cylinder field [G] (reference ibid:273)."""
    p = 1.0 / f
    pd = -fdot / f**2
    return 2.9e8 * p ** (-5.0 / 2.0) * math.sqrt(pd)


def mass_funct(pb_days: float, x_ls: float) -> float:
    """Binary mass function [Msun], 4 pi^2 x^3 / (G Pb^2)
    (reference ibid:317)."""
    pb = pb_days * SECS_PER_DAY
    return 4.0 * math.pi**2 * (x_ls) ** 3 / (Tsun_s * pb**2)


def mass_funct2(mp: float, mc: float, i_deg: float) -> float:
    """Mass function [Msun] from component masses + inclination
    (reference ibid:357)."""
    return (mc * math.sin(math.radians(i_deg))) ** 3 / (mc + mp) ** 2


def pulsar_mass(pb_days: float, x_ls: float, mc: float,
                i_deg: float) -> float:
    """Pulsar mass [Msun] from the mass function with known companion
    mass and inclination (reference ibid:402)."""
    massfunct = mass_funct(pb_days, x_ls)
    sini_ = math.sin(math.radians(i_deg))
    ca = massfunct
    cb = 2 * massfunct * mc
    cc = massfunct * mc**2 - (mc * sini_) ** 3
    return (-cb + math.sqrt(cb**2 - 4 * ca * cc)) / (2 * ca)


def companion_mass(pb_days: float, x_ls: float, i_deg: float = 60.0,
                   mp: float = 1.4) -> float:
    """Companion mass [Msun] by solving the cubic mass function
    (reference ibid:469, same monic-cubic closed form)."""
    massfunct = mass_funct(pb_days, x_ls)
    sini_ = math.sin(math.radians(i_deg))
    # monic cubic: mc^3 - (mf/sini^3) mc^2 - (2 mp mf/sini^3) mc - mp^2 mf/sini^3
    a = -massfunct / sini_**3
    b = -2 * mp * massfunct / sini_**3
    c = -(mp**2) * massfunct / sini_**3
    # depressed-cubic real root (Cardano)
    p = b - a**2 / 3.0
    q = 2 * a**3 / 27.0 - a * b / 3.0 + c
    disc = (q / 2) ** 2 + (p / 3) ** 3
    if disc >= 0:
        s = math.sqrt(disc)
        u1 = np.cbrt(-q / 2 + s)
        u2 = np.cbrt(-q / 2 - s)
        t = u1 + u2
    else:
        r = math.sqrt(-(p**3) / 27.0)
        phi = math.acos(-q / (2 * r))
        t = 2 * np.cbrt(r) * math.cos(phi / 3.0)
    return float(t - a / 3.0)


def pbdot(mp: float, mc: float, pb_days: float, e: float) -> float:
    """GR orbital-decay rate [s/s] (Peters 1964; reference ibid:573)."""
    pb = pb_days * SECS_PER_DAY
    fe = (1.0 + 73.0 / 24 * e**2 + 37.0 / 96 * e**4) / (1 - e**2) ** 3.5
    return (-192.0 * math.pi / 5 *
            (2.0 * math.pi / pb) ** (5.0 / 3.0) *
            Tsun_s ** (5.0 / 3.0) * fe * mp * mc / (mp + mc) ** (1.0 / 3.0))


def gamma(mp: float, mc: float, pb_days: float, e: float) -> float:
    """Einstein-delay amplitude GAMMA [s] (reference ibid:638)."""
    pb = pb_days * SECS_PER_DAY
    return (e * (pb / (2.0 * math.pi)) ** (1.0 / 3.0) *
            Tsun_s ** (2.0 / 3.0) * (mp + mc) ** (-4.0 / 3.0) *
            mc * (mp + 2 * mc))


def omdot(mp: float, mc: float, pb_days: float, e: float) -> float:
    """GR periastron advance [deg/yr] (reference ibid:699)."""
    pb = pb_days * SECS_PER_DAY
    rad_per_s = (3.0 * (2.0 * math.pi / pb) ** (5.0 / 3.0) *
                 Tsun_s ** (2.0 / 3.0) * (mp + mc) ** (2.0 / 3.0) /
                 (1.0 - e**2))
    return math.degrees(rad_per_s) * SECS_PER_YEAR


def sini(mp: float, mc: float, pb_days: float, x_ls: float) -> float:
    """GR sin(i) from masses and Keplerian parameters (reference
    ibid:759)."""
    massfunct = mass_funct(pb_days, x_ls)
    return (massfunct * (mp + mc) ** 2 / mc**3) ** (1.0 / 3.0)


def omdot_to_mtot(omdot_deg_yr: float, pb_days: float, e: float) -> float:
    """Total mass [Msun] implied by a periastron advance (reference
    ibid:916)."""
    pb = pb_days * SECS_PER_DAY
    od = math.radians(omdot_deg_yr) / SECS_PER_YEAR
    return ((od / 3.0 * (1.0 - e**2) *
             (pb / (2.0 * math.pi)) ** (5.0 / 3.0)) ** (3.0 / 2.0)
            / Tsun_s)


def a1sini(mp: float, mc: float, pb_days: float) -> float:
    """Projected semi-major axis x = a1 sin i [light-s] for i=90 deg
    (reference ibid:980)."""
    pb = pb_days * SECS_PER_DAY
    return ((Tsun_s * mc**3 / (mp + mc) ** 2) ** (1.0 / 3.0) *
            (pb / (2.0 * math.pi)) ** (2.0 / 3.0))


def shklovskii_factor(pm_mas_yr: float, d_kpc: float) -> float:
    """Shklovskii correction factor a_s = mu^2 d / c [1/s]; multiply by a
    period to get the apparent Pdot contribution (reference ibid:1035)."""
    mu = math.radians(pm_mas_yr / 3600.0e3) / SECS_PER_YEAR  # rad/s
    return mu**2 * (d_kpc * 1e3 * PC_M) / C


def dispersion_slope(dm: float) -> float:
    """Dispersion slope [1/s] = DM * DMconst (reference ibid:1073)."""
    from pint_tpu import DMconst

    return DMconst * dm * 1e12  # DMconst is s MHz^2 / (pc cm^-3)
