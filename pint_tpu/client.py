"""pint_tpu.client — crash-survivable client for the network front door.

The other half of the ISSUE 19 boundary: a small, dependency-free
client for the :mod:`pint_tpu.gateway` HTTP API whose failure handling
is strong enough to extend the PR 18 kill-midflight conservation
invariant across the network.  Three disciplines:

* **Bounded retries with backoff + jitter under a caller deadline** —
  connection failures, 429 (honoring Retry-After) and 503 are retried
  up to ``retries`` times with exponential backoff and uniform jitter,
  never past the caller's ``timeout_s``; 400/404/409 are terminal (a
  malformed request does not become correct by repetition).
* **Idempotency by default** — every ``submit`` carries an
  ``X-Idempotency-Key`` (auto-generated when the caller has none), so
  a retry after a dropped connection maps back to the SAME job id
  server-side and can never double-fit.
* **Reconnect across restarts** — ``wait`` polls the job id and
  treats connection failures as "daemon restarting", probing
  ``/healthz`` until the supervised daemon is back; a resolved job's
  result replays from the gateway's dedup journal, so the answer
  survives the daemon that computed it.

IMPORTANT: this module imports ONLY the standard library at module
level and is runnable as a plain script (``python pint_tpu/client.py
load ...``) — the bench harness spawns client PROCESSES from it, and
importing the ``pint_tpu`` package would pay the full jax start-up tax
in every one of them.

Env knobs (all overridable per-call): ``PINT_TPU_CLIENT_RETRIES``
(default 4), ``PINT_TPU_CLIENT_BACKOFF_S`` (0.2),
``PINT_TPU_CLIENT_JITTER_S`` (0.1), ``PINT_TPU_CLIENT_BACKOFF_CAP_S``
(per-attempt sleep cap, 5.0), ``PINT_TPU_CLIENT_TIMEOUT_S``
(per-request socket timeout, 30).
"""

from __future__ import annotations

import http.client
import json
import math
import os
import random
import socket
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

__all__ = ["GatewayClient", "GatewayClientError", "GatewayUnavailable",
           "GatewayQuotaExceeded", "GatewayRequestFailed", "main"]


class GatewayClientError(Exception):
    """Base for client-side gateway failures; ``http_code`` is the
    terminal status code when one was received (else None)."""

    http_code: Optional[int] = None

    def __init__(self, msg="", http_code=None, doc=None):
        self.http_code = http_code
        self.doc = doc or {}
        super().__init__(msg)


class GatewayUnavailable(GatewayClientError):
    """The gateway could not be reached (or kept dropping the
    connection) within the retry budget — the daemon is down, still
    restarting, or the network is broken."""


class GatewayQuotaExceeded(GatewayClientError):
    """429 survived the retry budget: this tenant is over quota at
    this priority and the Retry-After horizon exceeds the caller's
    patience."""


class GatewayRequestFailed(GatewayClientError):
    """A terminal (non-retryable) HTTP error: 400 bad payload, 409
    idempotency conflict, 404 unknown job, or a 5xx that is not
    backpressure."""


#: connection-level failures worth retrying — includes the half-open
#: socket shapes a killed daemon leaves behind
_CONN_ERRORS = (ConnectionError, socket.timeout, socket.gaierror,
                http.client.HTTPException, TimeoutError)


def _pct(samples_ms: List[float], q: float) -> Optional[float]:
    xs = sorted(samples_ms)
    if not xs:
        return None
    i = min(len(xs) - 1, max(0, int(math.ceil(q * len(xs))) - 1))
    return round(xs[i], 4)


class GatewayClient:
    """One tenant's handle on a gateway base URL.

    ``stats`` accumulates across calls: ``retries`` (re-sent
    requests), ``reconnects`` (healthz probe cycles after a connection
    loss), ``dedup`` (submissions the server answered from its
    idempotency table/journal)."""

    def __init__(self, base_url: str, *, tenant: str = "default",
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 jitter_s: Optional[float] = None,
                 request_timeout_s: Optional[float] = None,
                 seed: Optional[int] = None):
        self.base_url = base_url.rstrip("/")
        self.tenant = tenant
        env = os.environ.get
        self.retries = int(env("PINT_TPU_CLIENT_RETRIES", "4") or 4) \
            if retries is None else int(retries)
        self.backoff_s = float(env("PINT_TPU_CLIENT_BACKOFF_S",
                                   "0.2") or 0.2) \
            if backoff_s is None else float(backoff_s)
        self.jitter_s = float(env("PINT_TPU_CLIENT_JITTER_S",
                                  "0.1") or 0.1) \
            if jitter_s is None else float(jitter_s)
        self.backoff_cap_s = float(env("PINT_TPU_CLIENT_BACKOFF_CAP_S",
                                       "5.0") or 5.0)
        self.request_timeout_s = float(env("PINT_TPU_CLIENT_TIMEOUT_S",
                                           "30") or 30) \
            if request_timeout_s is None else float(request_timeout_s)
        self._rng = random.Random(seed)
        self._keyseq = 0
        self.stats = {"retries": 0, "reconnects": 0, "dedup": 0}

    # -- low-level HTTP ----------------------------------------------------

    def _request(self, method: str, path: str,
                 body: Optional[bytes] = None,
                 headers: Optional[Dict[str, str]] = None):
        """-> ``(code, doc, headers)``; raises the ``_CONN_ERRORS``
        family on transport failure (retried by the callers)."""
        req = urllib.request.Request(
            self.base_url + path, data=body, method=method,
            headers=dict(headers or {}))
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                raw = resp.read()
                return resp.status, self._decode(raw), dict(
                    resp.headers)
        except urllib.error.HTTPError as e:
            raw = e.read()
            return e.code, self._decode(raw), dict(e.headers)
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, _CONN_ERRORS + (OSError,)):
                raise reason if isinstance(reason, Exception) \
                    else ConnectionError(str(reason))
            raise ConnectionError(str(reason))

    @staticmethod
    def _decode(raw: bytes) -> dict:
        try:
            doc = json.loads(raw.decode("utf-8"))
            return doc if isinstance(doc, dict) else {"body": doc}
        except (ValueError, UnicodeDecodeError):
            return {}

    def _sleep_budget(self, attempt: int, retry_after: Optional[float],
                      deadline_at: Optional[float]) -> bool:
        """Back off before retry ``attempt``; False when the caller's
        deadline cannot absorb the wait (stop retrying).  Exponential
        with a cap (the ``run_supervised`` idiom) so a large retry
        budget spans a slow daemon restart without the tail attempts
        sleeping for minutes."""
        delay = min(self.backoff_s * (2.0 ** attempt),
                    self.backoff_cap_s) \
            + self._rng.uniform(0.0, self.jitter_s)
        if retry_after is not None:
            delay = max(delay, float(retry_after))
        if deadline_at is not None \
                and time.monotonic() + delay >= deadline_at:
            return False
        time.sleep(delay)
        return True

    # -- probes ------------------------------------------------------------

    def healthz(self) -> Optional[dict]:
        """One /healthz probe; None when unreachable."""
        try:
            code, doc, _ = self._request("GET", "/healthz")
        except _CONN_ERRORS + (OSError,):
            return None
        return doc if code == 200 else None

    def wait_ready(self, timeout_s: float = 30.0,
                   poll_s: float = 0.2) -> bool:
        """Probe /healthz until the gateway answers — the reconnect
        loop a supervised restart is bridged by."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.healthz() is not None:
                return True
            time.sleep(poll_s)
        return self.healthz() is not None

    # -- submission --------------------------------------------------------

    def new_idem_key(self) -> str:
        self._keyseq += 1
        return f"c{os.getpid()}-{os.urandom(6).hex()}-{self._keyseq}"

    def submit(self, payload: dict, *, priority: str = "normal",
               tenant: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               idem_key: Optional[str] = None,
               trace_id: Optional[str] = None,
               timeout_s: Optional[float] = None) -> dict:
        """POST the job; returns ``{"job_id", "trace_id", "dedup"}``.

        The idempotency key (auto-generated if absent) makes every
        retry safe: a response lost to a dropped connection is
        recovered by re-sending, and the server maps the key back to
        the original admission.  ``deadline_ms`` is the JOB deadline —
        re-computed to the remaining budget on each retry so the
        propagated header never promises time that was already spent
        backing off."""
        idem_key = idem_key or self.new_idem_key()
        body = json.dumps(payload).encode("utf-8")
        deadline_at = None
        if timeout_s is not None:
            deadline_at = time.monotonic() + float(timeout_s)
        job_deadline_at = None
        if deadline_ms is not None:
            job_deadline_at = time.monotonic() + float(deadline_ms) / 1e3
        attempt = 0
        while True:
            headers = {"Content-Type": "application/json",
                       "X-Tenant": tenant or self.tenant,
                       "X-Priority": priority,
                       "X-Idempotency-Key": idem_key}
            if trace_id:
                headers["X-Trace-Id"] = trace_id
            if job_deadline_at is not None:
                remaining_ms = (job_deadline_at - time.monotonic()) \
                    * 1e3
                headers["X-Deadline-Ms"] = f"{remaining_ms:.1f}"
            retry_after = None
            try:
                code, doc, hdrs = self._request(
                    "POST", "/v1/jobs", body=body, headers=headers)
            except _CONN_ERRORS + (OSError,) as e:
                code, doc, hdrs = None, {"error": type(e).__name__,
                                         "message": str(e)}, {}
            if code == 202:
                if doc.get("dedup"):
                    self.stats["dedup"] += 1
                return doc
            if code in (400, 404, 409, 504):
                # terminal: a bad payload, a key conflict, or a
                # deadline that already expired cannot be fixed by
                # resending the same request
                raise GatewayRequestFailed(
                    f"gateway rejected the request "
                    f"({code}: {doc.get('message') or doc.get('error')})",
                    http_code=code, doc=doc)
            if code in (429, 503):
                ra = hdrs.get("Retry-After")
                try:
                    retry_after = float(ra) if ra else None
                except ValueError:
                    retry_after = None
            if attempt >= self.retries or not self._sleep_budget(
                    attempt, retry_after, deadline_at):
                if code == 429:
                    raise GatewayQuotaExceeded(
                        f"over quota after {attempt} retries "
                        f"({doc.get('message')})", http_code=429,
                        doc=doc)
                if code is None:
                    raise GatewayUnavailable(
                        f"gateway unreachable after {attempt} "
                        f"retries ({doc.get('message')})")
                raise GatewayRequestFailed(
                    f"gateway error {code} after {attempt} retries "
                    f"({doc.get('message') or doc.get('error')})",
                    http_code=code, doc=doc)
            attempt += 1
            self.stats["retries"] += 1

    # -- result polling ----------------------------------------------------

    def status(self, job_id: str) -> dict:
        code, doc, _ = self._request("GET", f"/v1/jobs/{job_id}")
        if code == 200:
            return doc
        raise GatewayRequestFailed(
            f"job {job_id!r}: gateway answered {code}",
            http_code=code, doc=doc)

    def wait(self, job_id: str, timeout_s: float = 120.0,
             poll_s: float = 0.1) -> dict:
        """Poll until the job resolves (state ``done`` or ``error``).
        A connection loss mid-wait is treated as a daemon restart:
        probe ``/healthz`` until it is back, then resume polling —
        a job resolved before the crash replays from the journal, an
        unresolved one was re-admitted under the same id."""
        deadline = time.monotonic() + float(timeout_s)
        delay = float(poll_s)
        while True:
            try:
                doc = self.status(job_id)
                if doc.get("state") in ("done", "error"):
                    return doc
            except GatewayRequestFailed as e:
                if e.http_code != 404:
                    raise
                # 404 right after a restart: the journal has the key
                # but the client may poll before re-admission settles
            except _CONN_ERRORS + (OSError,):
                # daemon restarting: probe /healthz until it is back,
                # bounded only by the CALLER's deadline — a supervised
                # cold restart can take the full jax start-up tax
                self.stats["reconnects"] += 1
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self.wait_ready(
                        timeout_s=remaining):
                    raise GatewayUnavailable(
                        f"gateway did not come back while waiting "
                        f"on {job_id!r}")
            if time.monotonic() >= deadline:
                raise GatewayUnavailable(
                    f"job {job_id!r} not resolved within "
                    f"{timeout_s} s")
            time.sleep(delay)
            delay = min(delay * 1.5, 1.0)

    def submit_and_wait(self, payload: dict, *,
                        priority: str = "normal",
                        tenant: Optional[str] = None,
                        deadline_ms: Optional[float] = None,
                        idem_key: Optional[str] = None,
                        trace_id: Optional[str] = None,
                        timeout_s: float = 120.0) -> dict:
        """Submit + wait under ONE deadline; the status doc gains a
        ``dedup`` echo so callers can count journal replays."""
        t0 = time.monotonic()
        out = self.submit(payload, priority=priority, tenant=tenant,
                          deadline_ms=deadline_ms, idem_key=idem_key,
                          trace_id=trace_id, timeout_s=timeout_s)
        remaining = max(float(timeout_s) - (time.monotonic() - t0),
                        0.5)
        doc = self.wait(out["job_id"], timeout_s=remaining)
        doc["dedup"] = bool(out.get("dedup"))
        return doc


# --- jax-free load CLI (the bench client process) -----------------------------

def _load_main(args) -> int:
    """``load``: submit every payload in a JSON file and wait for all
    of them — one bench client process.  Emits one JSON line:
    per-key chi2 bits (the conservation check), retry/dedup counts,
    and client-observed latency percentiles."""
    with open(args.payloads, encoding="utf-8") as fh:
        payloads = json.load(fh)
    if not isinstance(payloads, list) or not payloads:
        print(json.dumps({"error": "payloads file must be a "
                                   "non-empty JSON list"}))
        return 2
    cl = GatewayClient(args.url, tenant=args.tenant,
                       retries=args.retries, backoff_s=args.backoff_s,
                       jitter_s=args.jitter_s, seed=args.seed)
    if not cl.wait_ready(timeout_s=args.ready_timeout_s):
        print(json.dumps({"error": "gateway never became ready",
                          "url": args.url}))
        return 2
    results: Dict[str, Any] = {}
    lat_ms: List[float] = []
    errors: Dict[str, int] = {}
    completed = dedup = 0
    for i in range(args.jobs):
        payload = payloads[i % len(payloads)]
        key = f"{args.key_prefix}-{i}"
        t0 = time.monotonic()
        try:
            doc = cl.submit_and_wait(
                payload, priority=args.priority,
                deadline_ms=args.deadline_ms or None, idem_key=key,
                timeout_s=args.timeout_s)
        except Exception as e:
            errors[type(e).__name__] = errors.get(
                type(e).__name__, 0) + 1
            results[key] = {"error": type(e).__name__}
            continue
        lat_ms.append((time.monotonic() - t0) * 1e3)
        err = doc.get("error")
        if err:
            name = err.get("type") if isinstance(err, dict) else str(err)
            errors[name] = errors.get(name, 0) + 1
            results[key] = {"error": name}
            continue
        r = doc.get("result") or {}
        completed += 1
        dedup += 1 if doc.get("dedup") else 0
        results[key] = {"chi2_hex": r.get("chi2_hex"),
                        "name": r.get("name"),
                        "dedup": bool(doc.get("dedup"))}
        if args.think_ms:
            time.sleep(args.think_ms / 1e3)
    print(json.dumps({
        "mode": "client_load", "tenant": args.tenant,
        "priority": args.priority, "jobs": args.jobs,
        "completed": completed, "errors": errors,
        "retries": cl.stats["retries"],
        "reconnects": cl.stats["reconnects"], "dedup_hits": dedup,
        "p50_ms": _pct(lat_ms, 0.50), "p99_ms": _pct(lat_ms, 0.99),
        "results": results}))
    return 0 if completed == args.jobs else 1


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="pint_tpu/client.py",
        description="resilient gateway client (stdlib-only; safe to "
                    "run as a plain script — no jax import)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ld = sub.add_parser("load", help="submit a payload corpus and "
                                     "wait; one JSON summary line")
    ld.add_argument("--url", required=True)
    ld.add_argument("--payloads", required=True,
                    help="JSON file: list of wire payloads")
    ld.add_argument("--jobs", type=int, default=8)
    ld.add_argument("--tenant", default="default")
    ld.add_argument("--priority", default="normal",
                    choices=("high", "normal", "low"))
    ld.add_argument("--key-prefix", default=f"load{os.getpid()}")
    ld.add_argument("--deadline-ms", type=float, default=0.0)
    ld.add_argument("--think-ms", type=float, default=0.0)
    ld.add_argument("--retries", type=int, default=None)
    ld.add_argument("--backoff-s", type=float, default=None)
    ld.add_argument("--jitter-s", type=float, default=None)
    ld.add_argument("--seed", type=int, default=None)
    ld.add_argument("--timeout-s", type=float, default=240.0)
    ld.add_argument("--ready-timeout-s", type=float, default=60.0)
    args = ap.parse_args(argv)
    return _load_main(args)


if __name__ == "__main__":
    # NO canonical-module re-import here (the serve/gateway idiom):
    # that would import the pint_tpu package — and with it jax — in
    # every bench client process.  This module is self-contained.
    import sys

    sys.exit(main())
