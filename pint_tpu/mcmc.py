"""Device-resident MCMC samplers: affine-invariant ensemble and HMC.

Reference: `MCMCFitter` / `sampler.py`
(`/root/reference/src/pint/mcmc_fitter.py`, `sampler.py:60`), which wrap
the external `emcee` package — python loops, one likelihood call per
walker per step, no gradients.  Here both samplers run as single jitted
XLA programs (`lax.scan` over steps, walkers vectorized), and HMC uses
`jax.grad` of the posterior — only possible because the whole timing
model is differentiable.

* :func:`ensemble_sample` — the Goodman & Weare (2010) stretch move,
  emcee's algorithm, with the red/black half-ensemble update; affine
  invariance makes it robust to the wildly different parameter scales of
  timing models.
* :func:`hmc_sample` — Hamiltonian Monte Carlo with leapfrog
  integration, dual-averaging step-size adaptation (Hoffman & Gelman
  2014, Alg. 5) and covariance/diagonal whitening.

Backend guidance: the ensemble sampler is robust on TPU (its accept
ratio tolerates the emulated-f64 likelihood noise, and walker batches
vectorize beautifully).  HMC no longer collapses on TPU: warmup measures
the backend's energy-noise floor (O(0.1-1) on emulated f64, ~1e-12 on
CPU), lowers the dual-averaging acceptance target to what that floor
permits, and floors the whitened step at 1e-3 — measured on real TPU:
acceptance ~0.13 with valid-but-undermixed posteriors (shorter
trajectories, ``num_leapfrog~8``, help, since the surface roughness
accumulates per leapfrog step).  Metropolis remains exact for the
(emulated) posterior it evaluates.  CPU is still the recommended HMC
backend; the TPU path is for convenience, not throughput.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import telemetry
from pint_tpu.lint.contracts import dispatch_contract

__all__ = ["ensemble_sample", "hmc_sample", "MCMCFitter"]


class EnsembleResult(NamedTuple):
    chain: np.ndarray        # (nsteps, nwalkers, ndim)
    lnpost: np.ndarray       # (nsteps, nwalkers)
    acceptance: float


@dispatch_contract("mcmc_step", max_compiles=30, max_dispatches=4,
                   max_transfers=6)
def ensemble_sample(lnpost_fn, x0, nsteps: int, seed: int = 0,
                    a: float = 2.0, thin: int = 1,
                    checkpoint: str = None, checkpoint_every: int = 0,
                    resume: bool = False) -> EnsembleResult:
    """Goodman-Weare stretch-move ensemble sampler, fully on device.

    ``x0``: (nwalkers, ndim) start positions (nwalkers even, >= 2*ndim
    recommended).  Returns the chain INCLUDING burn-in; slice it yourself.

    Checkpoint/resume (reference `event_optimize --backend` HDF5 emcee
    backend, `/root/reference/src/pint/scripts/event_optimize.py`):
    with ``checkpoint`` set, the accumulated chain + sampler state is
    written to that ``.npz`` atomically every ``checkpoint_every`` steps
    (0 = only at the end); ``resume=True`` continues a matching
    checkpoint from where it stopped.  The RNG key sequence is derived
    from (seed, nsteps) and indexed by absolute step, so a killed and
    resumed run reproduces the uninterrupted chain EXACTLY (bitwise on
    a given backend) — asserted by tests/test_mcmc_resume.py.

    Checkpoints carry a CRC32 and are verified on load
    (:func:`pint_tpu.runtime.load_checkpoint`): a truncated or
    bit-flipped file raises a typed
    :class:`~pint_tpu.exceptions.CheckpointCorruptError` instead of
    propagating a numpy unpickling error (ISSUE 4 satellite).
    """
    import os

    x0 = jnp.asarray(x0, jnp.float64)
    nw, nd = x0.shape
    if nw % 2 or nw < 4:
        raise ValueError("need an even number of walkers >= 4")
    vln = jax.vmap(lnpost_fn)

    def half_step(key, movers, lnp_movers, others):
        """Stretch-move update of `movers` against `others`."""
        k1, k2, k3 = jax.random.split(key, 3)
        nm = movers.shape[0]
        # z ~ g(z) prop 1/sqrt(z) on [1/a, a]
        u = jax.random.uniform(k1, (nm,))
        z = ((a - 1.0) * u + 1.0) ** 2 / a
        j = jax.random.randint(k2, (nm,), 0, others.shape[0])
        prop = others[j] + z[:, None] * (movers - others[j])
        lnp_prop = vln(prop)
        lnr = jnp.log(jax.random.uniform(k3, (nm,)))
        lnq = (nd - 1.0) * jnp.log(z) + lnp_prop - lnp_movers
        acc = lnr < lnq
        new = jnp.where(acc[:, None], prop, movers)
        new_lnp = jnp.where(acc, lnp_prop, lnp_movers)
        return new, new_lnp, acc

    def step(carry, key):
        x, lnp = carry
        k1, k2 = jax.random.split(key)
        first, second = x[: nw // 2], x[nw // 2:]
        lp1, lp2 = lnp[: nw // 2], lnp[nw // 2:]
        first, lp1, acc1 = half_step(k1, first, lp1, second)
        second, lp2, acc2 = half_step(k2, second, lp2, first)
        x = jnp.concatenate([first, second])
        lnp = jnp.concatenate([lp1, lp2])
        nacc = jnp.sum(acc1) + jnp.sum(acc2)
        return (x, lnp), (x, lnp, nacc)

    # per-step keys indexed by ABSOLUTE step number (fold_in, not
    # split(key, nsteps): split hashes the total count into every key on
    # this jax version, so a 40-step and a 60-step run would draw
    # unrelated sequences and resume could not be bitwise).  Fetched to
    # host ONCE: the per-chunk loop below slices them with numpy —
    # device-array slicing (`keys[k:k2]`, `chain[-1]`) eagerly
    # dispatches several scalar index ops PER CHUNK (~15 extra tunnel
    # round trips each on a networked TPU; found by the dispatch-
    # contract audit, pint_tpu.lint.contracts "mcmc_step").
    _base_key = jax.random.PRNGKey(seed)
    keys = np.asarray(jax.vmap(
        lambda i: jax.random.fold_in(_base_key, i))(jnp.arange(nsteps)))

    @jax.jit
    def run(x0, lnp0, keys):
        # the final carry rides the same transfer as the chain so the
        # chunk loop never indexes device arrays eagerly
        (xf, lnpf), (chain, lnps, nacc) = jax.lax.scan(
            step, (x0, lnp0), keys)
        return xf, lnpf, chain, lnps, jnp.sum(nacc)

    chains, lnplist = [], []
    nacc_total = 0.0
    start = 0
    truncated = False
    x, lnp = x0, None
    if resume and checkpoint and os.path.exists(checkpoint):
        # CRC32-verified load: truncation/corruption raises a typed
        # CheckpointCorruptError, not a numpy/zipfile internal
        from pint_tpu.runtime import load_checkpoint

        f = load_checkpoint(checkpoint)
        if int(f["seed"]) != seed or f["chain"].shape[1:] != (nw, nd):
            raise ValueError(
                f"checkpoint {checkpoint} does not match this "
                "sampler configuration (seed/walkers/ndim)")
        start = min(int(f["steps_done"]), nsteps)
        truncated = int(f["steps_done"]) > nsteps
        chains = [f["chain"][:start]]
        lnplist = [f["lnpost"][:start]]
        nacc_total = float(f["nacc"])
        x = jnp.asarray(f["x_last"])
        lnp = jnp.asarray(f["lnp_last"])
    if lnp is None:
        lnp = vln(x0)   # lazily: a resumed run restores it instead

    def _save():
        if not checkpoint:
            return
        from pint_tpu.runtime import write_checkpoint

        write_checkpoint(checkpoint, {
            "chain": np.concatenate(chains) if chains else
            np.zeros((0, nw, nd)),
            "lnpost": np.concatenate(lnplist) if lnplist else
            np.zeros((0, nw)),
            "nacc": nacc_total, "steps_done": k, "seed": seed,
            "x_last": np.asarray(x), "lnp_last": np.asarray(lnp),
        }, compressed=True)

    k = start
    chunk = checkpoint_every if (checkpoint and checkpoint_every) \
        else nsteps
    while k < nsteps:
        k2 = min(nsteps, k + chunk)
        with telemetry.span("mcmc.chunk", lo=k, hi=k2,
                            nwalkers=nw, ndim=nd):
            x, lnp, c, lp, nacc = run(x, lnp, jnp.asarray(keys[k:k2]))
        # ONE fetch per checkpoint chunk (bounded by n_chunks, not
        # nsteps) — the chain must live on host to be checkpointable
        chains.append(np.asarray(c))           # ddlint: disable=TRACE002
        lnplist.append(np.asarray(lp))         # ddlint: disable=TRACE002
        nacc_total += float(nacc)              # ddlint: disable=TRACE002
        k = k2
        _save()
    chain = np.concatenate(chains)
    lnps = np.concatenate(lnplist)
    # a checkpoint truncated to fewer steps than it recorded cannot
    # attribute its acceptance count to the kept prefix
    acc = float("nan") if truncated else nacc_total / (nsteps * nw)
    return EnsembleResult(chain[::thin], lnps[::thin], acc)


class HMCResult(NamedTuple):
    samples: np.ndarray      # (num_samples, ndim)
    lnpost: np.ndarray       # (num_samples,)
    acceptance: float
    step_size: float
    mass_diag: np.ndarray


def hmc_sample(lnpost_fn, x0, num_warmup: int = 500,
               num_samples: int = 1000, num_leapfrog: int = 24,
               seed: int = 0, target_accept: float = 0.8,
               initial_step: Optional[float] = None,
               mass_diag: Optional[np.ndarray] = None,
               cov: Optional[np.ndarray] = None) -> HMCResult:
    """Gradient-based HMC over ``lnpost_fn`` (1-D input).

    The sampler runs in **whitened** coordinates: timing posteriors have
    parameter scales spanning ~15 decades and near-degenerate spin/
    astrometry correlations, and adapting a mass matrix in raw
    coordinates there is numerically doomed.  Pass ONE of:

    * ``cov`` — a dense covariance estimate (e.g. the WLS fitter's
      ``parameter_covariance_matrix`` converted to the sampler's units):
      coordinates are whitened by its Cholesky factor, which also undoes
      correlated near-degeneracies (the strongest preconditioner);
    * ``mass_diag`` (1/scale^2 per dim) — rough per-parameter scales,
      diagonal whitening only.

    Warmup brackets a starting step size, then adapts it by dual
    averaging (with a diagonal mass refinement pass when neither
    preconditioner was given); sampling runs with everything frozen.
    """
    x0 = jnp.asarray(x0, jnp.float64)
    nd = x0.shape[0]
    if cov is not None:
        # factor the CORRELATION matrix on the host (true-IEEE f64) and
        # rescale on device: covariance entries like var(F1) ~ 1e-37 and
        # their Cholesky intermediates underflow TPU's emulated f64 (f32
        # exponent range); the correlation factor is O(1) everywhere
        cov = np.asarray(cov, np.float64)
        s_np = np.sqrt(np.diag(cov))
        Lc = np.linalg.cholesky(cov / np.outer(s_np, s_np))
        L = jnp.asarray(Lc)
        s = jnp.asarray(s_np)

        def to_x(z):
            return s * (L @ z)

        def to_z(x):
            return jax.scipy.linalg.solve_triangular(L, x / s, lower=True)
    else:
        scale = jnp.ones(nd) if mass_diag is None else \
            1.0 / jnp.sqrt(jnp.asarray(mass_diag, jnp.float64))

        def to_x(z):
            return z * scale

        def to_z(x):
            return x / scale

    def lnpost_z(z):
        return lnpost_fn(to_x(z))

    grad_fn = jax.grad(lnpost_z)
    x0 = to_z(x0)            # z-space start
    minv0 = jnp.ones(nd)
    eps0 = 0.1 if initial_step is None else float(initial_step)

    def leapfrog(x, p, eps, minv):
        g = grad_fn(x)

        def body(_, state):
            x, p, g = state
            p = p + 0.5 * eps * g
            x = x + eps * minv * p
            g = grad_fn(x)
            p = p + 0.5 * eps * g
            return x, p, g

        return jax.lax.fori_loop(0, num_leapfrog, body, (x, p, g))[:2]

    def hmc_step(key, x, lnp, eps, minv):
        k1, k2 = jax.random.split(key)
        p = jax.random.normal(k1, (nd,)) / jnp.sqrt(minv)
        x_new, p_new = leapfrog(x, p, eps, minv)
        lnp_new = lnpost_z(x_new)
        h0 = lnp - 0.5 * jnp.sum(minv * p * p)
        h1 = lnp_new - 0.5 * jnp.sum(minv * p_new * p_new)
        # guard NaNs from divergent trajectories
        log_alpha = jnp.where(jnp.isfinite(h1), h1 - h0, -jnp.inf)
        alpha = jnp.minimum(1.0, jnp.exp(jnp.minimum(log_alpha, 0.0)))
        acc = jnp.log(jax.random.uniform(k2)) < log_alpha
        return (jnp.where(acc, x_new, x), jnp.where(acc, lnp_new, lnp),
                alpha)

    # -- warmup ------------------------------------------------------------
    # Stan-style: (a) bracket a sane initial step by doubling/halving,
    # (b) a dual-averaging window with unit mass, (c) re-estimate the
    # diagonal mass from that window's samples, (d) a FRESH dual-averaging
    # window under the new mass.  Restarting the averager is what recovers
    # from early -inf excursions outside a boxed prior (a single
    # never-reset averager can pin the step near zero for good).
    gamma, t0, kappa = 0.05, 10.0, 0.75

    def da_window(carry_key, x, lnp, minv, eps_init, n, da_target=None):
        if da_target is None:
            da_target = target_accept
        mu = jnp.log(10.0 * eps_init)

        def warm_step(carry, inp):
            i, key = inp
            x, lnp, logeps, logeps_bar, hbar, mean, m2 = carry
            x, lnp, alpha = hmc_step(key, x, lnp, jnp.exp(logeps), minv)
            it = i + 1.0
            hbar = (1.0 - 1.0 / (it + t0)) * hbar + \
                (da_target - alpha) / (it + t0)
            logeps = mu - jnp.sqrt(it) / gamma * hbar
            w = it ** (-kappa)
            logeps_bar = w * logeps + (1.0 - w) * logeps_bar
            # Welford running variance for the mass matrix
            d = x - mean
            mean = mean + d / it
            m2 = m2 + d * (x - mean)
            return (x, lnp, logeps, logeps_bar, hbar, mean, m2), alpha

        keys = jax.random.split(carry_key, n)
        idx = jnp.arange(n, dtype=jnp.float64)
        init = (x, lnp, jnp.log(eps_init), jnp.log(eps_init), 0.0,
                jnp.zeros(nd), jnp.zeros(nd))
        (x, lnp, _, logeps_bar, _, _, m2), alphas = jax.lax.scan(
            warm_step, init, (idx, keys))
        var = m2 / jnp.maximum(n - 1.0, 1.0)
        return x, lnp, jnp.exp(logeps_bar), var, jnp.mean(alphas)

    key = jax.random.PRNGKey(seed)
    kh, kw1, kw2, ks = jax.random.split(key, 4)

    @jax.jit
    def bracket_eps(x, lnp, key):
        """Double/halve toward ~50% acceptance (Hoffman & Gelman Alg. 4)."""
        _, _, alpha0 = hmc_step(key, x, lnp, eps0, minv0)
        direction = jnp.where(alpha0 > 0.5, 1.0, -1.0)

        def cond(state):
            logeps, alpha, k = state
            keep = jnp.where(direction > 0, alpha > 0.5, alpha < 0.5)
            return keep & (jnp.abs(logeps) < 30.0) & (k < 40)

        def body(state):
            logeps, _, k = state
            logeps = logeps + direction * jnp.log(2.0)
            _, _, alpha = hmc_step(key, x, lnp, jnp.exp(logeps), minv0)
            return logeps, alpha, k + 1

        logeps, _, _ = jax.lax.while_loop(
            cond, body, (jnp.log(eps0), alpha0, 0))
        return jnp.exp(logeps)

    # adapt the mass only when the caller gave no scales: a variance
    # estimated from a not-yet-mixed window is smaller than truth, which
    # shrinks trajectories and self-reinforces; with caller scales the
    # whitened metric is already near-unit and identity mass is safer
    adapt_mass = mass_diag is None and cov is None

    @jax.jit
    def energy_noise_floor(x, lnp, key):
        """Median |dH| of near-zero-length trajectories: on a true-IEEE
        backend this is ~1e-12; on TPU's emulated f64 the lnpost surface
        carries O(0.1-1) roughness that no step size can tunnel under.
        The achievable acceptance is capped near exp(-floor), so the
        dual-averaging target must be lowered to match or the step size
        collapses to zero chasing an impossible target (the previous
        behavior, which made HMC CPU-only)."""
        keys = jax.random.split(key, 8)

        def probe(k):
            k1, _ = jax.random.split(k)
            p = jax.random.normal(k1, (nd,))
            x_new, p_new = leapfrog(x, p, 1e-8, minv0)
            h0 = lnp - 0.5 * jnp.sum(p * p)
            h1 = lnpost_z(x_new) - 0.5 * jnp.sum(p_new * p_new)
            return jnp.abs(h1 - h0)

        return jnp.median(jax.vmap(probe)(keys))

    @jax.jit
    def warmup(x0):
        lnp0 = lnpost_z(x0)
        dh_floor = energy_noise_floor(x0, lnp0, kh)
        # acceptance achievable against the backend's energy-noise floor,
        # with 10% margin; never target below 0.25
        eff_target = jnp.clip(0.9 * jnp.exp(-dh_floor), 0.25,
                              target_accept)
        eps_i = bracket_eps(x0, lnp0, kh)
        n1 = num_warmup // 2
        x, lnp, eps1, var, _ = da_window(kw1, x0, lnp0, minv0, eps_i, n1,
                                         eff_target)
        minv = jnp.where(var > 0.0, var, minv0) if adapt_mass else minv0
        # eps2 is adapted under THIS minv — keep them paired for sampling
        x, lnp, eps2, _, _ = da_window(kw2, x, lnp, minv, eps1,
                                       num_warmup - n1, eff_target)
        # step floor, only when the measured energy noise says the
        # backend's surface is rough (emulated f64): on a true-IEEE
        # backend a sub-1e-3 whitened step can be the legitimately
        # adapted answer for a poorly whitened posterior
        eps2 = jnp.where(dh_floor > 1e-6, jnp.maximum(eps2, 1e-3), eps2)
        return x, lnp, eps2, minv

    x, lnp, eps, minv = warmup(x0)

    def samp_step(carry, key):
        x, lnp = carry
        x, lnp, alpha = hmc_step(key, x, lnp, eps, minv)
        return (x, lnp), (x, lnp, alpha)

    @jax.jit
    def run(x, lnp):
        keys = jax.random.split(ks, num_samples)
        (_, _), (xs, lnps, alphas) = jax.lax.scan(samp_step, (x, lnp), keys)
        return xs, lnps, jnp.mean(alphas)

    xs, lnps, acc = run(x, lnp)
    samples = np.asarray(jax.vmap(to_x)(xs))       # back to raw coordinates
    mass_out = np.asarray(1.0 / minv) if cov is not None else \
        np.asarray(1.0 / (minv * scale**2))
    return HMCResult(samples, np.asarray(lnps), float(acc),
                     float(eps), mass_out)


class MCMCFitter:
    """Posterior sampling "fitter" (reference `MCMCFitter`,
    `/root/reference/src/pint/mcmc_fitter.py:63`, there built on emcee).

    Runs the device ensemble sampler over a :class:`~pint_tpu.bayesian.
    BayesianTiming` posterior, stores posterior means/stds into the model
    parameters, and keeps the flat chain for inspection.
    """

    def __init__(self, toas, model, prior_info=None, nwalkers: int = 0,
                 use_pulse_numbers: bool = False):
        from pint_tpu.bayesian import BayesianTiming, default_prior_info

        if prior_info is None:
            prior_info = default_prior_info(model)
        self.bt = BayesianTiming(model, toas,
                                 use_pulse_numbers=use_pulse_numbers,
                                 prior_info=prior_info)
        self.model = model
        self.toas = toas
        self.nwalkers = nwalkers or max(4, 2 * self.bt.nparams + 2)
        if self.nwalkers % 2:
            self.nwalkers += 1
        self.chain: Optional[np.ndarray] = None

    def fit_toas(self, nsteps: int = 1000, burn: Optional[int] = None,
                 seed: int = 0) -> float:
        rng = np.random.default_rng(seed)
        # sample in offset space: walkers start near 0 with scale-sized
        # scatter, and no statistic ever subtracts two ~equal par values
        dx0 = rng.standard_normal((self.nwalkers, self.bt.nparams)) * \
            self.bt.scales()[None, :] * 0.1
        res = ensemble_sample(self.bt.lnposterior_offset_fn, dx0, nsteps,
                              seed=seed)
        burn = nsteps // 2 if burn is None else burn
        flat = res.chain[burn:].reshape(-1, self.bt.nparams)
        refs = self.bt.start_point()
        self.chain_offsets = flat
        self.chain = refs[None, :] + flat
        self.acceptance = res.acceptance
        self.lnpost = res.lnpost
        mean = refs + flat.mean(axis=0)
        std = flat.std(axis=0)
        imax = np.unravel_index(np.argmax(res.lnpost), res.lnpost.shape)
        self.maxpost_params = refs + res.chain[imax]
        for i, name in enumerate(self.bt.param_labels):
            par = self.model[name]
            if hasattr(par, "set_value"):      # MJD params take an MJD float
                par.set_value(float(mean[i]))
            else:
                par.value = float(mean[i])
            par.uncertainty = float(std[i])
        return float(np.max(res.lnpost))


class TemplateMCMCFitter(MCMCFitter):
    """MCMC timing fit against photon events through a pulse-profile
    template (reference `MCMCFitterAnalyticTemplate` /
    `MCMCFitterBinnedTemplate` + `lnlikelihood_basic`,
    `/root/reference/src/pint/mcmc_fitter.py:58,440,484`, there built on
    emcee): the likelihood of a parameter vector is

        sum_i ln( w_i f(phi_i(params)) + 1 - w_i )

    with ``f`` an :class:`~pint_tpu.templates.LCTemplate` and ``phi_i``
    the model pulse phases of the photons — here one jitted expression,
    so the ensemble sampler evaluates whole walker batches per step and
    the template gradient is available for free.
    """

    def __init__(self, toas, model, template, weights=None,
                 prior_info=None, nwalkers: int = 0):
        from pint_tpu import qs
        from pint_tpu.residuals import Residuals

        super().__init__(toas, model, prior_info=prior_info,
                         nwalkers=nwalkers)
        self.template = template
        r = Residuals(toas, model, subtract_mean=False)
        batch = r.batch
        p0 = r.pdict
        names = self.bt.param_labels
        units = jnp.asarray(np.asarray(model.fit_units(names)))
        calc = model.calc
        tfn = template._eval_fn()
        tx = jnp.asarray(template.get_parameters())
        if weights is None:
            weights = getattr(toas, "weights", None)
        w = None if weights is None else \
            jnp.asarray(np.asarray(weights, np.float64))

        def lnlike_off(dx):
            p = model.with_x(p0, dx * units, names)
            ph = calc.phase(p, batch)
            _, frac = qs.round_nearest(ph)
            phases = qs.to_f64(frac) % 1.0
            f = tfn(phases, tx)
            if w is None:
                return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
            return jnp.sum(jnp.log(jnp.maximum(
                w * f + (1.0 - w), 1e-300)))

        priors = list(self.bt.priors)
        refs = jnp.asarray(self.bt._ref)

        def lnpost_off(dx):
            params = refs + dx
            lp = jnp.sum(jnp.stack(
                [pr.logpdf(params[i]) for i, pr in enumerate(priors)]))
            ll = lnlike_off(dx)
            return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

        self.bt.lnposterior_offset_fn = jax.jit(lnpost_off)
