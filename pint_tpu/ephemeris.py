"""Solar-system ephemerides: body positions/velocities w.r.t. the SSB.

Equivalent of the reference's `src/pint/solar_system_ephemerides.py` (which
wraps astropy+jplephem and *downloads* JPL DE kernels).  This environment has
neither astropy nor jplephem nor network access, so this module provides:

* :class:`SPKEphemeris` — a from-scratch reader for JPL SPK/DAF binary kernels
  (``.bsp``; DAF file format per NAIF's SPK Required Reading; Chebyshev
  segment types 2 and 3).  Users drop ``de421.bsp``/``de440.bsp`` into
  ``$PINT_TPU_EPHEM_DIR`` (or CWD) and get full JPL precision — this replaces
  the reference's jplephem dependency with native code.
* :class:`BuiltinEphemeris` — an analytic fallback: truncated VSOP87D for
  the Earth (:mod:`pint_tpu.data.vsop87d_earth`) + an extended Meeus/ELP
  lunar series + heliocentric Keplerian mean elements (JPL "Approximate
  Positions of the Planets", Standish) for the other planets and the SSB
  offset.  Earth accuracy ~100-300 km (sub-ms light time).
* :class:`IntegratedEphemeris` — the default no-kernel path for real
  data: a 9-body numerical integration (+ solar 1PN term) whose EMB
  initial conditions are least-squares fit to the analytic theory over
  the data window, regenerating the full perturbation spectrum.  Earth
  accuracy ~100 km, zero phase wraps on the reference's B1855+09 golden
  data (tests/test_tempo2_parity.py).  Disk-cached per window.

All returns are ICRS-equatorial, SSB-centered, SI units (m, m/s).
Host-side numpy (load-time precompute; see SURVEY.md §7).  An on-device
Chebyshev pack for end-to-end jitted pipelines is provided by
:meth:`SPKEphemeris.chebyshev_pack`.
"""

from __future__ import annotations

import os
import struct
import warnings
from typing import Dict, Optional, Tuple

import numpy as np

from pint_tpu import GM_BODY
from pint_tpu.utils import PosVel

AU_KM = 149597870.700
DAY_S = 86400.0
#: seconds of TDB past J2000 (JD 2451545.0 TDB) per MJD(TDB) day
_J2000_MJD = 51544.5

# NAIF integer codes
NAIF = {
    "ssb": 0,
    "mercury_bary": 1,
    "venus_bary": 2,
    "emb": 3,
    "mars_bary": 4,
    "jupiter_bary": 5,
    "saturn_bary": 6,
    "uranus_bary": 7,
    "neptune_bary": 8,
    "pluto_bary": 9,
    "sun": 10,
    "moon": 301,
    "earth": 399,
    "mercury": 199,
    "venus": 299,
    "mars": 499,
    "jupiter": 599,
    "saturn": 699,
    "uranus": 799,
    "neptune": 899,
    "pluto": 999,
}

# For the giant planets the planet-barycenter offset is far below timing
# relevance (Shapiro-delay geometry), so barycenter codes substitute.
_BARY_FALLBACK = {499: 4, 599: 5, 699: 6, 799: 7, 899: 8, 999: 9, 199: 1, 299: 2}


def mjd_tdb_to_et(mjd_tdb):
    """MJD(TDB) -> ET seconds past J2000 TDB."""
    return (np.asarray(mjd_tdb, np.float64) - _J2000_MJD) * DAY_S


class _Segment:
    __slots__ = (
        "target",
        "center",
        "frame",
        "dtype",
        "et_beg",
        "et_end",
        "init",
        "intlen",
        "rsize",
        "n",
        "coeffs",
    )

    def __init__(self, target, center, frame, dtype, et_beg, et_end, init, intlen, rsize, n, coeffs):
        self.target = target
        self.center = center
        self.frame = frame
        self.dtype = dtype
        self.et_beg = et_beg
        self.et_end = et_end
        self.init = init
        self.intlen = intlen
        self.rsize = rsize
        self.n = n
        self.coeffs = coeffs  # (n, ncomp, ncoef) Chebyshev coefficients [km]

    def posvel_km(self, et):
        """Evaluate (pos[km], vel[km/s]) at ET seconds (vectorized)."""
        et = np.asarray(et, np.float64)
        idx = np.floor((et - self.init) / self.intlen).astype(np.int64)
        idx = np.clip(idx, 0, self.n - 1)
        mid = self.init + (idx + 0.5) * self.intlen
        radius = self.intlen / 2.0
        s = (et - mid) / radius  # in [-1, 1]
        c = self.coeffs[idx]  # (..., ncomp, ncoef)
        ncoef = c.shape[-1]
        # Chebyshev via Clenshaw recurrence, plus derivative recurrence
        b0 = np.zeros(s.shape + (c.shape[-2],))
        b1 = np.zeros_like(b0)
        d0 = np.zeros_like(b0)
        d1 = np.zeros_like(b0)
        s2 = (2.0 * s)[..., None]
        for k in range(ncoef - 1, 0, -1):
            d0, d1 = s2 * d0 - d1 + 2.0 * b0, d0
            b0, b1 = s2 * b0 - b1 + c[..., k], b0
        # p = c0 + s*b1 - b2  =>  p' = b1 + s*b1' - b2'
        dval = b0 + s[..., None] * d0 - d1
        val = s[..., None] * b0 - b1 + c[..., 0]
        if c.shape[-2] >= 6:  # type 3: velocity stored explicitly
            return val[..., 0:3], val[..., 3:6]
        return val, dval / radius


class SPKEphemeris:
    """JPL SPK (``.bsp``) kernel reader: DAF format, segment types 2 & 3.

    Format implemented from the public NAIF SPK/DAF specification (the
    reference instead imports ``jplephem``; cf.
    `src/pint/solar_system_ephemerides.py:18-45`).
    """

    def __init__(self, path: str):
        self.path = path
        self.segments: Dict[Tuple[int, int], list] = {}
        with open(path, "rb") as f:
            data = f.read()
        self._parse(data)
        self.name = os.path.splitext(os.path.basename(path))[0].lower()

    # -- DAF plumbing ----------------------------------------------------------

    def _parse(self, data: bytes):
        locidw = data[0:8].decode("ascii", "replace")
        if not (locidw.startswith("DAF/SPK") or locidw.startswith("NAIF/DAF")):
            raise ValueError(f"{self.path}: not an SPK kernel (ID word {locidw!r})")
        locfmt = data[88:96].decode("ascii", "replace")
        if "LTL" in locfmt:
            en = "<"
        elif "BIG" in locfmt:
            en = ">"
        else:
            # pre-FTP-validation files: guess from ND plausibility
            nd_l = struct.unpack("<i", data[8:12])[0]
            en = "<" if 0 < nd_l < 124 else ">"
        nd, ni = struct.unpack(en + "ii", data[8:16])
        fward, bward, free = struct.unpack(en + "iii", data[76:88])
        ss = nd + (ni + 1) // 2  # summary size in doubles
        f64 = np.dtype(en + "f8")
        i32 = np.dtype(en + "i4")
        words = np.frombuffer(data, dtype=f64)

        recno = fward
        while recno > 0:
            base = (recno - 1) * 128  # word index of record start
            nxt, _prev, nsum = words[base : base + 3]
            for k in range(int(nsum)):
                sbase = base + 3 + k * ss
                dbl = words[sbase : sbase + nd]
                ints = np.frombuffer(
                    words[sbase + nd : sbase + ss].tobytes(), dtype=i32
                )[:ni]
                self._load_segment(words, dbl, ints)
            recno = int(nxt)

    def _load_segment(self, words, dbl, ints):
        et_beg, et_end = float(dbl[0]), float(dbl[1])
        target, center, frame, dtype, begin, end = (int(x) for x in ints[:6])
        if dtype not in (2, 3):
            return  # only Chebyshev position(/velocity) segments are used by DE
        seg_words = words[begin - 1 : end]
        init, intlen, rsize, n = seg_words[-4:]
        rsize, n = int(rsize), int(n)
        recs = seg_words[: rsize * n].reshape(n, rsize)
        ncomp = 3 if dtype == 2 else 6
        ncoef = (rsize - 2) // ncomp
        coeffs = recs[:, 2:].reshape(n, ncomp, ncoef)
        self.segments.setdefault((target, center), []).append(
            _Segment(
                target, center, frame, dtype, et_beg, et_end, float(init), float(intlen), rsize, n, coeffs
            )
        )

    # -- public API ------------------------------------------------------------

    def _chain(self, code: int):
        """Chain of segment lists from SSB(0) to `code` (e.g. 399: 0->3->399)."""
        if code == 0:
            return []
        for (tgt, ctr), segs in self.segments.items():
            if tgt == code:
                return self._chain(ctr) + [segs]
        if code in _BARY_FALLBACK:
            return self._chain(_BARY_FALLBACK[code])
        raise KeyError(f"body {code} not reachable in {self.path}")

    @staticmethod
    def _pick(segs, et):
        """Segment covering all epochs in `et`, else EphemerisError."""
        from pint_tpu.exceptions import EphemerisError

        lo, hi = float(np.min(et)), float(np.max(et))
        for seg in segs:
            if seg.et_beg <= lo and hi <= seg.et_end:
                return seg
        spans = [(s.et_beg, s.et_end) for s in segs]
        raise EphemerisError(
            f"epochs ET [{lo}, {hi}] s outside kernel segment span(s) {spans} "
            f"(no extrapolation beyond the .bsp coverage)"
        )

    def posvel(self, body: str, mjd_tdb) -> PosVel:
        """(pos [m], vel [m/s]) of `body` w.r.t. SSB, ICRS axes."""
        code = NAIF[body.lower()]
        et = mjd_tdb_to_et(mjd_tdb)
        pos = 0.0
        vel = 0.0
        for segs in self._chain(code):
            p, v = self._pick(segs, et).posvel_km(et)
            pos = pos + p
            vel = vel + v
        return PosVel(np.asarray(pos) * 1e3, np.asarray(vel) * 1e3)

    def chebyshev_pack(self, body: str, mjd_start: float, mjd_end: float):
        """Extract (init, intlen, coeffs[m]) covering [mjd_start, mjd_end] for
        on-device evaluation (summed over the SSB chain after re-fitting is
        NOT done — each chain link is returned separately)."""
        code = NAIF[body.lower()]
        out = []
        e0, e1 = mjd_tdb_to_et(mjd_start), mjd_tdb_to_et(mjd_end)
        for segs in self._chain(code):
            seg = self._pick(segs, np.array([e0, e1]))
            i0 = max(0, int(np.floor((e0 - seg.init) / seg.intlen)))
            i1 = min(seg.n - 1, int(np.floor((e1 - seg.init) / seg.intlen)))
            out.append(
                (
                    seg.init + i0 * seg.intlen,
                    seg.intlen,
                    np.asarray(seg.coeffs[i0 : i1 + 1]) * 1e3,
                )
            )
        return out


# --- analytic fallback --------------------------------------------------------

# --- truncated VSOP87D Earth (see pint_tpu/data/vsop87d_earth.py) ----------


def _vsop_series(series, tau):
    """Evaluate sum_k tau^k * sum_i A cos(B + C tau) and its tau-derivative.

    tau: Julian millennia TDB from J2000 (array).  Returns (value, d/dtau).
    """
    tau = np.asarray(tau, np.float64)
    val = np.zeros_like(tau)
    dval = np.zeros_like(tau)
    for k, tab in enumerate(series):
        A, B, C = tab[:, 0], tab[:, 1], tab[:, 2]
        arg = B[None, :] + C[None, :] * tau[..., None]
        s_k = np.sum(A * np.cos(arg), axis=-1)
        ds_k = -np.sum(A * C * np.sin(arg), axis=-1)
        tk = tau**k
        val += tk * s_k
        dval += tk * ds_k
        if k > 0:
            dval += k * tau ** (k - 1) * s_k
    return val, dval


def _ecl_date_to_icrs_matrix(t_cy):
    """(N,3,3) rotation: dynamical-ecliptic-of-date -> ICRS/J2000 equator.

    Mean obliquity of date tilts ecliptic -> mean equator of date, then the
    (vectorized) Lieske precession matrix carries mean-of-date back to
    J2000.  The ~23 mas frame bias J2000->ICRS is far below the series
    truncation and is omitted, consistently with the ITRF chain in
    :mod:`pint_tpu.earth`.
    """
    from pint_tpu.earth import _r1, mean_obliquity, precession_matrix

    eps = mean_obliquity(np.asarray(t_cy, np.float64))
    return precession_matrix(np.asarray(t_cy, np.float64)) @ _r1(-eps)


def vsop87_earth_helio_icrs(mjd_tdb):
    """Heliocentric Earth (pos [m], vel [m/s]) in ICRS from the truncated
    VSOP87D series — the precision core of the analytic fallback, replacing
    Keplerian mean elements for the one body where accuracy matters most.

    The rotation matrix's own time-derivative (precession, ~1 m/s at 1 AU)
    is neglected in the velocity.
    """
    from pint_tpu.data import vsop87d_earth as v

    t = np.asarray(mjd_tdb, np.float64)
    scalar = t.ndim == 0
    t = np.atleast_1d(t)
    tau = (t - _J2000_MJD) / 365250.0
    L, dL = _vsop_series(v.L_SERIES, tau)
    B, dB = _vsop_series(v.B_SERIES, tau)
    R, dR = _vsop_series(v.R_SERIES, tau)
    cl, sl = np.cos(L), np.sin(L)
    cb, sb = np.cos(B), np.sin(B)
    pos = np.stack([R * cb * cl, R * cb * sl, R * sb], axis=-1)
    vel = np.stack(
        [
            dR * cb * cl - R * sb * dB * cl - R * cb * sl * dL,
            dR * cb * sl - R * sb * dB * sl + R * cb * cl * dL,
            dR * sb + R * cb * dB,
        ],
        axis=-1,
    )
    M = _ecl_date_to_icrs_matrix(tau * 10.0)  # millennia -> centuries
    pos = np.einsum("...ij,...j->...i", M, pos) * AU_KM * 1e3
    vel = np.einsum("...ij,...j->...i", M, vel) * AU_KM * 1e3 \
        / (365250.0 * DAY_S)
    if scalar:
        return pos[0], vel[0]
    return pos, vel


# JPL "Approximate Positions of the Planets" (E.M. Standish) Keplerian mean
# elements, J2000 ecliptic, valid 1800-2050.  Columns: a [au], e, I [deg],
# L [deg], long.peri [deg], long.node [deg]; then centennial rates of each.
_KEPLER_ELEMENTS = {
    "mercury": (0.38709927, 0.20563593, 7.00497902, 252.25032350, 77.45779628, 48.33076593,
                0.00000037, 0.00001906, -0.00594749, 149472.67411175, 0.16047689, -0.12534081),
    "venus": (0.72333566, 0.00677672, 3.39467605, 181.97909950, 131.60246718, 76.67984255,
              0.00000390, -0.00004107, -0.00078890, 58517.81538729, 0.00268329, -0.27769418),
    "emb": (1.00000261, 0.01671123, -0.00001531, 100.46457166, 102.93768193, 0.0,
            0.00000562, -0.00004392, -0.01294668, 35999.37244981, 0.32327364, 0.0),
    "mars": (1.52371034, 0.09339410, 1.84969142, -4.55343205, -23.94362959, 49.55953891,
             0.00001847, 0.00007882, -0.00813131, 19140.30268499, 0.44441088, -0.29257343),
    "jupiter": (5.20288700, 0.04838624, 1.30439695, 34.39644051, 14.72847983, 100.47390909,
                -0.00011607, -0.00013253, -0.00183714, 3034.74612775, 0.21252668, 0.20469106),
    "saturn": (9.53667594, 0.05386179, 2.48599187, 49.95424423, 92.59887831, 113.66242448,
               -0.00125060, -0.00050991, 0.00193609, 1222.49362201, -0.41897216, -0.28867794),
    "uranus": (19.18916464, 0.04725744, 0.77263783, 313.23810451, 170.95427630, 74.01692503,
               -0.00196176, -0.00004397, -0.00242939, 428.48202785, 0.40805281, 0.04240589),
    "neptune": (30.06992276, 0.00859048, 1.77004347, -55.12002969, 44.96476227, 131.78422574,
                0.00026291, 0.00005105, 0.00035372, 218.45945325, -0.32241464, -0.00508664),
    "pluto": (39.48211675, 0.24882730, 17.14001206, 238.92903833, 224.06891629, 110.30393684,
              -0.00031596, 0.00005170, 0.00004818, 145.20780515, -0.04062942, -0.01183482),
}

#: Earth/Moon mass ratio (DE421 convention)
EMRAT = 81.30056907419062
_MOON_FRAC = 1.0 / (1.0 + EMRAT)  # Moon's share of the E-M separation to EMB

#: obliquity used to rotate J2000 ecliptic -> ICRS equatorial [rad]
_EPS0 = np.deg2rad(84381.406 / 3600.0)

# Truncated ELP-2000/Meeus lunar series.  Args: multiples of (D, M, M', F);
# dL in 1e-6 deg, dR in 1e-3 km, dB in 1e-6 deg (separate table).
_MOON_LR = np.array(
    [
        # D  M  M'  F     dL        dR
        [0, 0, 1, 0, 6288774.0, -20905355.0],
        [2, 0, -1, 0, 1274027.0, -3699111.0],
        [2, 0, 0, 0, 658314.0, -2955968.0],
        [0, 0, 2, 0, 213618.0, -569925.0],
        [0, 1, 0, 0, -185116.0, 48888.0],
        [0, 0, 0, 2, -114332.0, -3149.0],
        [2, 0, -2, 0, 58793.0, 246158.0],
        [2, -1, -1, 0, 57066.0, -152138.0],
        [2, 0, 1, 0, 53322.0, -170733.0],
        [2, -1, 0, 0, 45758.0, -204586.0],
        [0, 1, -1, 0, -40923.0, -129620.0],
        [1, 0, 0, 0, -34720.0, 108743.0],
        [0, 1, 1, 0, -30383.0, 104755.0],
        [2, 0, 0, -2, 15327.0, 10321.0],
        [0, 0, 1, 2, -12528.0, 0.0],
        [0, 0, 1, -2, 10980.0, 79661.0],
        [4, 0, -1, 0, 10675.0, -34782.0],
        [0, 0, 3, 0, 10034.0, -23210.0],
        [4, 0, -2, 0, 8548.0, -21636.0],
        [2, 1, -1, 0, -7888.0, 24208.0],
        [2, 1, 0, 0, -6766.0, 30824.0],
        [1, 0, -1, 0, -5163.0, -8379.0],
        [1, 1, 0, 0, 4987.0, -16675.0],
        [2, -1, 1, 0, 4036.0, -12831.0],
        [2, 0, 2, 0, 3994.0, -10445.0],
        [4, 0, 0, 0, 3861.0, -11650.0],
        [2, 0, -3, 0, 3665.0, 14403.0],
        [0, 1, -2, 0, -2689.0, -7003.0],
        [2, 0, -1, 2, -2602.0, 0.0],
        [2, -1, -2, 0, 2390.0, 10056.0],
        [1, 0, 1, 0, -2348.0, 6322.0],
        [2, -2, 0, 0, 2236.0, -9884.0],
        [0, 1, 2, 0, -2120.0, 5751.0],
        [0, 2, 0, 0, -2069.0, 0.0],
        [2, -2, -1, 0, 2048.0, -4950.0],
        [2, 0, 1, -2, -1773.0, 4130.0],
        [2, 0, 0, 2, -1595.0, 0.0],
        [4, -1, -1, 0, 1215.0, -3958.0],
        [0, 0, 2, 2, -1110.0, 0.0],
        [3, 0, -1, 0, -892.0, 3258.0],
        [2, 1, 1, 0, -810.0, 2616.0],
        [4, -1, -2, 0, 759.0, -1897.0],
        [0, 2, -1, 0, -713.0, -2117.0],
        [2, 2, -1, 0, -700.0, 2354.0],
        [2, 1, -2, 0, 691.0, 0.0],
        [2, -1, 0, -2, 596.0, 0.0],
        [4, 0, 1, 0, 549.0, -1423.0],
        [0, 0, 4, 0, 537.0, -1117.0],
        [4, -1, 0, 0, 520.0, -1571.0],
        [1, 0, -2, 0, -487.0, -1739.0],
        [2, 1, 0, -2, -399.0, 0.0],
        [0, 0, 2, -2, -381.0, -4421.0],
        [1, 1, 1, 0, 351.0, 0.0],
        [3, 0, -2, 0, -340.0, 0.0],
        [4, 0, -3, 0, 330.0, 0.0],
        [2, -1, 2, 0, 327.0, 0.0],
        [0, 2, 1, 0, -323.0, 1165.0],
        [1, 1, -1, 0, 299.0, 0.0],
        [2, 0, 3, 0, 294.0, 0.0],
        [2, 0, -1, -2, 0.0, 8752.0],
    ]
)
_MOON_B = np.array(
    [
        # D  M  M'  F     dB
        [0, 0, 0, 1, 5128122.0],
        [0, 0, 1, 1, 280602.0],
        [0, 0, 1, -1, 277693.0],
        [2, 0, 0, -1, 173237.0],
        [2, 0, -1, 1, 55413.0],
        [2, 0, -1, -1, 46271.0],
        [2, 0, 0, 1, 32573.0],
        [0, 0, 2, 1, 17198.0],
        [2, 0, 1, -1, 9266.0],
        [0, 0, 2, -1, 8822.0],
        [2, -1, 0, -1, 8216.0],
        [2, 0, -2, -1, 4324.0],
        [2, 0, 1, 1, 4200.0],
        [2, 1, 0, -1, -3359.0],
        [2, -1, -1, 1, 2463.0],
        [2, -1, 0, 1, 2211.0],
        [2, -1, -1, -1, 2065.0],
        [0, 1, -1, -1, -1870.0],
        [4, 0, -1, -1, 1828.0],
        [0, 1, 0, 1, -1794.0],
        [0, 0, 0, 3, -1749.0],
        [0, 1, -1, 1, -1565.0],
        [1, 0, 0, 1, -1491.0],
        [0, 1, 1, 1, -1475.0],
        [0, 1, 1, -1, -1410.0],
        [0, 1, 0, -1, -1344.0],
        [1, 0, 0, -1, -1335.0],
        [0, 0, 3, 1, 1107.0],
        [4, 0, 0, -1, 1021.0],
        [4, 0, -1, 1, 833.0],
        [0, 0, 1, -3, 777.0],
        [4, 0, -2, 1, 671.0],
        [2, 0, 0, -3, 607.0],
        [2, 0, 2, -1, 596.0],
        [2, -1, 1, -1, 491.0],
        [2, 0, -2, 1, -451.0],
        [0, 0, 3, -1, 439.0],
        [2, 0, 2, 1, 422.0],
        [2, 0, -3, -1, 421.0],
        [2, 1, -1, 1, -366.0],
        [2, 1, 0, 1, -351.0],
        [4, 0, 0, 1, 331.0],
        [2, -1, 1, 1, 315.0],
        [2, -2, 0, -1, 302.0],
        [0, 0, 1, 3, -283.0],
        [2, 1, 1, -1, -229.0],
        [1, 1, 0, -1, 223.0],
        [1, 1, 0, 1, 223.0],
        [0, 1, -2, -1, -220.0],
        [2, 1, -1, -1, -220.0],
        [1, 0, 1, 1, -185.0],
        [2, -1, -2, -1, 181.0],
        [0, 1, 2, 1, -177.0],
        [4, 0, -2, -1, 176.0],
        [4, -1, -1, -1, 166.0],
        [1, 0, 1, -1, -164.0],
        [4, 0, 1, -1, 132.0],
        [1, 0, -1, -1, -119.0],
        [4, -1, 0, -1, 115.0],
        [2, -2, 0, 1, 107.0],
    ]
)


def _ecl_to_icrs(v):
    """Rotate J2000-ecliptic vectors to ICRS equatorial."""
    ce, se = np.cos(_EPS0), np.sin(_EPS0)
    x, y, z = v[..., 0], v[..., 1], v[..., 2]
    return np.stack([x, ce * y - se * z, se * y + ce * z], axis=-1)


def _kepler_posvel_au(name, t_cy, dL_rad=0.0, da_frac=0.0):
    """Heliocentric J2000-ecliptic (pos [au], vel [au/day]) from mean
    elements.  ``dL_rad``/``da_frac``: corrections to the mean longitude
    [rad] and semi-major axis [fractional] — the giant-planet parameters
    the DE405-anchored IC fit solves for (see
    `IntegratedEphemeris._integrate_window`)."""
    a0, e0, i0, L0, w0, O0, da, de, di, dL, dw, dO = _KEPLER_ELEMENTS[name]
    a = (a0 + da * t_cy) * (1.0 + da_frac)
    e = e0 + de * t_cy
    inc = np.deg2rad(i0 + di * t_cy)
    L = np.deg2rad(L0 + dL * t_cy) + dL_rad
    wbar = np.deg2rad(w0 + dw * t_cy)
    Om = np.deg2rad(O0 + dO * t_cy)
    w = wbar - Om  # argument of perihelion
    M = np.remainder(L - wbar + np.pi, 2 * np.pi) - np.pi
    # Kepler equation, Newton iteration (fixed count; e < 0.25 for all bodies)
    E = M + e * np.sin(M)
    for _ in range(6):
        E = E - (E - e * np.sin(E) - M) / (1.0 - e * np.cos(E))
    cosE, sinE = np.cos(E), np.sin(E)
    # perifocal coordinates
    xp = a * (cosE - e)
    yp = a * np.sqrt(1 - e * e) * sinE
    # mean motion [rad/day]
    n = np.deg2rad(dL) / 36525.0
    rdot_f = a * n / (1.0 - e * cosE)
    vxp = -rdot_f * sinE
    vyp = rdot_f * np.sqrt(1 - e * e) * cosE
    cw, sw = np.cos(w), np.sin(w)
    cO, sO = np.cos(Om), np.sin(Om)
    ci, si = np.cos(inc), np.sin(inc)
    r11 = cO * cw - sO * sw * ci
    r12 = -cO * sw - sO * cw * ci
    r21 = sO * cw + cO * sw * ci
    r22 = -sO * sw + cO * cw * ci
    r31 = sw * si
    r32 = cw * si
    pos = np.stack([r11 * xp + r12 * yp, r21 * xp + r22 * yp, r31 * xp + r32 * yp], -1)
    vel = np.stack([r11 * vxp + r12 * vyp, r21 * vxp + r22 * vyp, r31 * vxp + r32 * vyp], -1)
    return pos, vel


def _moon_pos_km(t_cy):
    """Geocentric Moon position only, ecliptic frame [km]."""
    t = np.asarray(t_cy, np.float64)
    deg = np.pi / 180.0
    Lp = (218.3164477 + 481267.88123421 * t - 0.0015786 * t**2) * deg
    D = (297.8501921 + 445267.1114034 * t - 0.0018819 * t**2) * deg
    M = (357.5291092 + 35999.0502909 * t - 0.0001536 * t**2) * deg
    Mp = (134.9633964 + 477198.8675055 * t + 0.0087414 * t**2) * deg
    F = (93.2720950 + 483202.0175233 * t - 0.0036539 * t**2) * deg
    E = 1.0 - 0.002516 * t - 0.0000074 * t**2

    def series(table, trig):
        args = (
            table[:, 0] * D[..., None]
            + table[:, 1] * M[..., None]
            + table[:, 2] * Mp[..., None]
            + table[:, 3] * F[..., None]
        )
        ecorr = np.where(np.abs(table[:, 1]) > 0, E[..., None] ** np.abs(table[:, 1]), 1.0)
        return args, ecorr

    argsLR, eLR = series(_MOON_LR, np.sin)
    dL = np.sum(_MOON_LR[:, 4] * eLR * np.sin(argsLR), axis=-1) * 1e-6 * deg
    dR = np.sum(_MOON_LR[:, 5] * eLR * np.cos(argsLR), axis=-1) * 1e-3
    argsB, eB = series(_MOON_B, np.sin)
    dB = np.sum(_MOON_B[:, 4] * eB * np.sin(argsB), axis=-1) * 1e-6 * deg
    # additive planetary/flattening corrections (Meeus ch. 47: the Venus
    # term A1, Jupiter term A2, and Earth-flattening term A3); ~26 km in
    # longitude, ~15 km in latitude — above the extended series floor
    A1 = (119.75 + 131.849 * t) * deg
    A2 = (53.09 + 479264.290 * t) * deg
    A3 = (313.45 + 481266.484 * t) * deg
    dL = dL + (3958.0 * np.sin(A1) + 1962.0 * np.sin(Lp - F)
               + 318.0 * np.sin(A2)) * 1e-6 * deg
    dB = dB + (-2235.0 * np.sin(Lp) + 382.0 * np.sin(A3)
               + 175.0 * np.sin(A1 - F) + 175.0 * np.sin(A1 + F)
               + 127.0 * np.sin(Lp - Mp) - 115.0 * np.sin(Lp + Mp)) \
        * 1e-6 * deg

    lon = Lp + dL
    lat = dB
    r = 385000.56 + dR  # km
    cl, sl = np.cos(lon), np.sin(lon)
    cb, sb = np.cos(lat), np.sin(lat)
    return np.stack([r * cb * cl, r * cb * sl, r * sb], -1)


def _moon_geocentric_km(t_cy):
    """Geocentric Moon, **ecliptic of date** (pos [km], vel [km/day]).

    Extended Meeus/ELP series.  Callers must precess the output to ICRS
    with :func:`_ecl_date_to_icrs_matrix` (both ephemeris classes do) —
    treating it as J2000 would introduce a ~1.4 deg/cy frame error
    (~100+ km).  Velocity by central difference of the series (smooth
    analytic function).
    """
    t = np.asarray(t_cy, np.float64)
    pos = _moon_pos_km(t)
    dt = 1e-7  # centuries ≈ 5.3 min
    vel = (_moon_pos_km(t + dt) - _moon_pos_km(t - dt)) / (2 * dt * 36525.0)
    return pos, vel


class BuiltinEphemeris:
    """Analytic fallback ephemeris (see module docstring for accuracy).

    The Earth is computed from the truncated VSOP87D series
    (:func:`vsop87_earth_helio_icrs`) + the extended Meeus/ELP lunar series
    (ecliptic of date, precessed to ICRS) — ~50-150 km, i.e. sub-ms in
    light time, measured against the reference's tempo2 golden residuals
    (tests/test_tempo2_parity.py).  The Sun/SSB offset and the outer
    planets still use Keplerian mean elements (their error enters timing
    only through the GM-weighted SSB sum and Shapiro geometry, suppressed
    by 3-6 orders of magnitude).
    """

    name = "builtin_analytic"

    def __init__(self, warn=True):
        if warn:
            warnings.warn(
                "Using the builtin analytic ephemeris (no JPL .bsp kernel "
                "found).  Earth position errors are ~1e2 km (sub-ms light "
                "time): fine for simulation and differential fitting, NOT "
                "for absolute ns-level timing of real data.  Supply a DE "
                "kernel via $PINT_TPU_EPHEM_DIR for full accuracy.",
                stacklevel=2,
            )

    def _helio_all(self, t_cy):
        out = {}
        for name in _KEPLER_ELEMENTS:
            p, v = _kepler_posvel_au(name, t_cy)
            out[name] = (p, v)
        return out

    @staticmethod
    def _ssb_offset(helio_si):
        """Sun w.r.t. SSB from a dict of heliocentric SI (pos, vel):
        the GM-weighted barycentre sum."""
        gm_tot = GM_BODY["sun"]
        psum = 0.0
        vsum = 0.0
        for name, (p, v) in helio_si.items():
            key = "earth" if name == "emb" else name
            gm = GM_BODY[key] + (GM_BODY["moon"] if name == "emb" else 0.0)
            gm_tot = gm_tot + gm
            psum = psum + gm * p
            vsum = vsum + gm * v
        return -psum / gm_tot, -vsum / gm_tot

    def _earth_moon_helio_si(self, mjd_tdb, t_cy):
        """(earth, moon_geo, emb) heliocentric/geocentric ICRS [m, m/s]."""
        ep, ev = vsop87_earth_helio_icrs(mjd_tdb)
        mp_km, mv_kmd = _moon_geocentric_km(t_cy)
        M = _ecl_date_to_icrs_matrix(t_cy)
        mp = np.einsum("...ij,...j->...i", M, mp_km) * 1e3
        mv = np.einsum("...ij,...j->...i", M, mv_kmd) * 1e3 / DAY_S
        emb_p = ep + _MOON_FRAC * mp
        emb_v = ev + _MOON_FRAC * mv
        return (ep, ev), (mp, mv), (emb_p, emb_v)

    def posvel(self, body: str, mjd_tdb) -> PosVel:
        body = body.lower()
        mjd_tdb = np.asarray(mjd_tdb, np.float64)
        if body == "ssb":
            z = np.zeros(np.shape(mjd_tdb) + (3,))
            return PosVel(z, z.copy())
        t = (mjd_tdb - _J2000_MJD) / 36525.0
        helio = self._helio_all(t)
        (ep, ev), (mp, mv), (emb_p, emb_v) = \
            self._earth_moon_helio_si(mjd_tdb, t)

        def kepler_si(name):
            p, v = helio[name]
            return (_ecl_to_icrs(np.asarray(p)) * AU_KM * 1e3,
                    _ecl_to_icrs(np.asarray(v)) * AU_KM * 1e3 / DAY_S)

        # Sun w.r.t. SSB: GM-weighted sum of heliocentric positions, with
        # the VSOP87-grade EMB replacing its Keplerian mean elements
        helio_si = {name: ((emb_p, emb_v) if name == "emb"
                           else kepler_si(name)) for name in helio}
        sun_p, sun_v = self._ssb_offset(helio_si)

        if body == "sun":
            p, v = sun_p, sun_v
        elif body == "earth":
            p, v = ep + sun_p, ev + sun_v
        elif body == "moon":
            p, v = ep + mp + sun_p, ev + mv + sun_v
        elif body == "emb":
            p, v = emb_p + sun_p, emb_v + sun_v
        else:
            key = body[:-5] if body.endswith("_bary") else body
            kp, kv = kepler_si(key)
            p, v = kp + sun_p, kv + sun_v
        return PosVel(np.asarray(p), np.asarray(v))


# --- integrated ephemeris -----------------------------------------------------

#: bodies carried by the N-body integration, in state-vector order
_NBODY_NAMES = ("sun", "mercury", "venus", "emb", "mars", "jupiter",
                "saturn", "uranus", "neptune")
_NBODY_VERSION = 4  # bump to invalidate on-disk caches
C_M_S = 299792458.0


def _nbody_gm():
    from pint_tpu import GM_BODY

    return np.array([
        GM_BODY["sun"], GM_BODY["mercury"], GM_BODY["venus"],
        GM_BODY["earth"] + GM_BODY["moon"], GM_BODY["mars"],
        GM_BODY["jupiter"], GM_BODY["saturn"], GM_BODY["uranus"],
        GM_BODY["neptune"],
    ])


def _nbody_rhs_factory(gm):
    n = len(gm)
    gm_sun = gm[0]

    def rhs(t, y):
        r = y[:3 * n].reshape(n, 3)
        d = r[None, :, :] - r[:, None, :]
        dist2 = np.einsum("ijk,ijk->ij", d, d)
        np.fill_diagonal(dist2, 1.0)
        inv3 = dist2**-1.5
        np.fill_diagonal(inv3, 0.0)
        a = np.einsum("ij,ijk->ik", gm[None, :] * inv3, d)
        # 1PN Schwarzschild term of the Sun (EIH, Sun-field only): moves
        # the Earth ~5 km over a decade (perihelion advance), above the
        # fitted-IC noise floor
        v = y[3 * n:].reshape(n, 3)
        rs = r[1:] - r[0]
        vs = v[1:] - v[0]
        r2 = np.einsum("ij,ij->i", rs, rs)
        rnorm = np.sqrt(r2)
        rv = np.einsum("ij,ij->i", rs, vs)
        v2 = np.einsum("ij,ij->i", vs, vs)
        coef = gm_sun / (C_M_S**2 * r2 * rnorm)
        a_gr = coef[:, None] * (
            (4.0 * gm_sun / rnorm - v2)[:, None] * rs
            + 4.0 * rv[:, None] * vs)
        a[1:] += a_gr
        return np.concatenate([y[3 * n:], a.ravel()])

    return rhs


class IntegratedEphemeris(BuiltinEphemeris):
    """Numerically integrated solar system, seeded by the analytic theory.

    The 9-body system (Sun + planets, Earth+Moon as EMB) is integrated
    (DOP853, rtol 1e-12, + the Sun's 1PN Schwarzschild term) over a window
    covering the requested epochs.  The EMB initial conditions are then
    *fit* to the truncated-VSOP87 analytic trajectory over the whole
    window (3-iteration Gauss-Newton with a frozen sensitivity matrix):
    the dynamics regenerates the full perturbation spectrum that any
    truncated analytic series lacks, while the least-squares seed averages
    the series' periodic truncation noise down to its systematic floor.

    On top of the integration, queries inside the CANONICAL window
    (~2000-2018) are served with the baked Earth-position correction
    field (:mod:`pint_tpu.data.ephem_correction`, fit by
    :mod:`pint_tpu.ephemcal` against the DE-ephemeris truth published
    in the reference's golden artifacts) applied to the geocenter —
    default ON, disabled by ``PINT_TPU_NO_EPH_CORR=1``.

    Measured against the reference's tempo2 golden residuals on B1855+09
    (tests/test_tempo2_parity.py): median light-time gap ~8 us with
    zero phase wraps (cross-validated holdout prediction ~11-15 us),
    vs ~190 us for the uncorrected integration, ~320 us/141 wraps for
    the pure analytic series and ~1.3 ms for Keplerian mean elements.
    Windows are cached on disk (``$PINT_TPU_CACHE`` or
    ``~/.cache/pint_tpu``).

    This replaces nothing in the reference (which downloads JPL kernels,
    `solar_system_ephemerides.py`); it is the zero-download path to
    ~10-us-grade real-data timing.
    """

    name = "builtin_integrated"

    #: sampling step of the stored trajectory [days]
    _STEP = 4.0
    #: window quantum + padding [days]
    _QUANTUM = 512.0
    _PAD = 700.0
    #: the CANONICAL window [MJD], quantum-aligned: one fixed span
    #: covering the reference-era pulsar datasets (~2000-2018).  Any
    #: query fitting inside it is served from this single build rather
    #: than its own quantized window, so (a) every dataset in the era
    #: sees the SAME trajectory (no per-window IC-fit scatter), and
    #: (b) the baked Earth-position correction table
    #: (:mod:`pint_tpu.data.ephem_correction`, fit against exactly this
    #: build) applies exactly.  Queries outside fall back to the
    #: quantized-window scheme unchanged.
    _CANONICAL = (51712.0, 58368.0)

    def __init__(self, warn=False):
        super().__init__(warn=False)
        if warn:
            warnings.warn(
                "No JPL .bsp kernel found: using the built-in integrated "
                "ephemeris (N-body fit to the analytic theory; ~km-grade "
                "Earth inside the 2000-2018 calibrated span, ~100 km "
                "outside it).  Supply a DE kernel via $PINT_TPU_EPHEM_DIR "
                "for full accuracy.", stacklevel=2)
        #: (wlo, whi) -> {body: CubicSpline}; every quantized window ever
        #: built in this process
        self._windows = {}

    # -- window management -------------------------------------------------
    @staticmethod
    def _cache_dir():
        d = os.environ.get("PINT_TPU_CACHE")
        if not d:
            d = os.path.join(os.path.expanduser("~"), ".cache", "pint_tpu")
        return d

    #: widest window the anchor extension may create [days] — beyond
    #: this the query epoch is too far from the DE405 table for the
    #: anchored fit to help, and the analytic-anchored build is used
    _ANCHOR_EXTEND_MAX = 20000.0

    @staticmethod
    def _anchor_range():
        """(lo, hi) MJD of the DE405 anchor table, or None when absent
        or not enabled.

        The anchor is OPT-IN (``PINT_TPU_DE_ANCHOR=1``) and now LEGACY:
        fitting the initial conditions to the 2-year DE405 table nails
        the in-window trajectory (measured 1366 km -> 7 km vs the
        table; tests/test_de_anchor.py) but EXTRAPOLATES worse than
        the analytic-anchored fit on multi-year datasets, because a
        2-year anchor cannot constrain the giant-planet mean-element
        errors.  The DEFAULT path supersedes it: the baked correction
        field (:mod:`pint_tpu.data.ephem_correction`, fit from the
        same table PLUS the multi-pulsar golden projections over
        2002-2017) reaches anchor-table grade in-window without the
        extrapolation pathology (B1855 tempo2-gap median ~8 us)."""
        if os.environ.get("PINT_TPU_DE_ANCHOR") != "1":
            return None
        try:
            from pint_tpu.data import de_anchor
        except ImportError:
            return None
        return (float(de_anchor.MJD_TDB[0]), float(de_anchor.MJD_TDB[-1]))

    def _window_key(self, mjd):
        """The quantized window covering this query, a pure function of
        the query epochs ALONE.  Earlier designs extended one global
        window as new epochs arrived; because the EMB initial-condition
        fit runs over the whole window, extension changed the served
        Earth positions for epochs already answered — results then
        depended on query *history* (test-order-dependent parity
        failures).  Deterministic quantization means the same dataset
        always gets the same integration no matter what else the process
        touched; distinct datasets may use overlapping windows (disk
        cache makes rebuilds cheap).

        When the DE405 anchor table is available and the union stays
        under _ANCHOR_EXTEND_MAX days, the window is extended to cover
        the table so the build can fit its initial conditions to real
        JPL-ephemeris positions (still a pure function of the query)."""
        mjd = np.atleast_1d(np.asarray(mjd, np.float64))
        lo, hi = float(np.min(mjd)), float(np.max(mjd))
        ar = self._anchor_range()
        if ar is not None:
            ulo, uhi = min(lo, ar[0] - 50.0), max(hi, ar[1] + 50.0)
            if uhi - ulo <= self._ANCHOR_EXTEND_MAX:
                lo, hi = ulo, uhi
        # canonical preference only on the default path: the legacy
        # opt-in anchored mode (ar set) keeps its smaller quantized
        # windows — anchored builds never serve the correction, and
        # canonicalizing them would force a needless full-era anchored
        # integration
        if ar is None:
            clo, chi = self._CANONICAL
            if clo + self._STEP <= lo and hi <= chi - self._STEP:
                return clo, chi
        q = self._QUANTUM
        wlo = float(np.floor((lo - self._PAD) / q) * q)
        whi = float(np.ceil((hi + self._PAD) / q) * q)
        return wlo, whi

    def _splines_for(self, mjd, key=None):
        if key is not None:
            # pinned path: never serve silent CubicSpline extrapolation —
            # a query outside the pinned window falls back to its own
            # quantized window (still deterministic, still correct)
            m = np.atleast_1d(np.asarray(mjd, np.float64))
            if not (key[0] <= float(np.min(m))
                    and float(np.max(m)) <= key[1]):
                key = None
        if key is None:
            key = self._window_key(mjd)
        sp = self._windows.get(key)
        if sp is None:
            sp = self._windows[key] = self._build(*key)
        else:
            self._windows[key] = self._windows.pop(key)  # LRU touch
        # bounded LRU: a long-lived process touching many datasets must
        # not accumulate spline sets forever (the disk cache makes a
        # rebuild cheap)
        while len(self._windows) > 8:
            self._windows.pop(next(iter(self._windows)))
        return sp

    def pinned_to(self, mjd_span):
        """A view of this ephemeris whose every query is served from the
        single window quantized from ``mjd_span`` — so a multi-observatory
        dataset (whose posvels are computed in per-site groups with
        different time ranges) sees ONE consistent integration throughout.
        The span must cover the later queries (the window pad leaves
        ~700 days of slack)."""
        return _PinnedEphemeris(self, self._window_key(mjd_span))

    def _build(self, wlo, whi):
        ar = self._anchor_range()
        anch = "a" if (ar is not None and wlo <= ar[0]
                       and ar[1] <= whi) else ""
        gc = self._stored_gcorr()
        if gc:
            import hashlib
            h = hashlib.sha1(repr(sorted(gc.items())).encode()) \
                .hexdigest()[:8]
            anch += f"c{h}"
        tag = f"nbody_{int(wlo)}_{int(whi)}_v{_NBODY_VERSION}{anch}.npz"
        path = os.path.join(self._cache_dir(), tag)
        grid = None
        states = None
        if os.path.isfile(path):
            try:
                with np.load(path) as f:
                    grid, states = f["grid"], f["states"]
            except Exception:
                grid = None
        if grid is None:
            grid, states = self._integrate_window(wlo, whi)
            try:
                os.makedirs(self._cache_dir(), exist_ok=True)
                # the tmp name must END in .npz: np.savez appends the
                # suffix otherwise and the atomic rename then targets a
                # file that does not exist (the disk cache silently
                # never persisted — found as hundreds of orphaned
                # *.tmpPID.npz files)
                tmp = path + f".tmp{os.getpid()}.npz"
                try:
                    np.savez_compressed(tmp, grid=grid, states=states)
                    os.replace(tmp, path)
                finally:
                    # a killed/failed write must not orphan its tmp
                    # (the driver's 600 s budget DOES kill mid-write)
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                # sweep tmp orphans from writers that died before the
                # finally could run (SIGKILL) — anything older than 1 h
                # is dead, its PID notwithstanding
                import glob
                import time
                for stale in glob.glob(
                        os.path.join(self._cache_dir(), "*.tmp*.npz")):
                    try:
                        if time.time() - os.path.getmtime(stale) > 3600:
                            os.unlink(stale)
                    except OSError:
                        pass
            except OSError:
                pass
        # QUINTIC interpolation of the stored 4-day samples: a cubic
        # spline's interpolation error on the annual orbit at h=4 d is
        # (2*pi*h/T)^4/384 * 1 AU ~ 9 km — a 4-day-period wiggle in
        # every served Earth position (~30 us of light time, found as
        # the dominant term of the DE405-anchor fit residual spectrum).
        # k=5 drops it to ~30 m; the integrator's rtol=1e-12 samples
        # are smooth enough that the higher order is free accuracy.
        from scipy.interpolate import make_interp_spline
        sp = {
            nm: make_interp_spline(grid, states[:, 3 * i:3 * i + 3],
                                   k=5)
            for i, nm in enumerate(_NBODY_NAMES)
        }
        if not anch:
            corr = self._correction_spline(wlo, whi)
            if corr is not None:
                sp["_earth_corr"] = corr
        return sp

    @classmethod
    def _correction_spline(cls, wlo, whi):
        """The baked Earth-SSB position-correction spline
        (:mod:`pint_tpu.data.ephem_correction` — fit against the
        CANONICAL unanchored build from the reference's published
        DE-ephemeris truth: the DE405 daily table, the `testtimes`
        3-D golden rows, the J1744-1134 golden Roemer column, and the
        multi-pulsar tempo2 residual-gap curves), or None when absent,
        disabled (``PINT_TPU_NO_EPH_CORR=1``), or not applicable to
        this window.  The table's knots span the full canonical window
        (data-free edges are tapered at bake time), so evaluation
        never extrapolates."""
        if os.environ.get("PINT_TPU_NO_EPH_CORR") == "1":
            return None
        if (wlo, whi) != cls._CANONICAL:
            return None
        try:
            from pint_tpu.data import ephem_correction as ec
        except ImportError:
            return None
        from scipy.interpolate import CubicSpline
        return CubicSpline(np.asarray(ec.KNOT_MJD, np.float64),
                           np.asarray(ec.CORR_M, np.float64))

    # -- the integration itself --------------------------------------------
    def _analytic_emb_helio(self, mjd):
        mjd = np.atleast_1d(np.asarray(mjd, np.float64))
        _, _, (emb_p, _v) = self._earth_moon_helio_si(
            mjd, (mjd - _J2000_MJD) / 36525.0)
        return emb_p

    def _base_ic(self, mjd0, gcorr=None):
        """Initial state from the analytic theory; ``gcorr`` maps a
        planet name to its (dL_rad, da_frac) mean-element correction
        (the giant-planet fit parameters of the anchored build)."""
        t = (mjd0 - _J2000_MJD) / 36525.0
        gcorr = gcorr or {}
        pos = [np.zeros(3)]
        vel = [np.zeros(3)]
        for nm in _NBODY_NAMES[1:]:
            if nm == "emb":
                p = self._analytic_emb_helio([mjd0])
                pp = self._analytic_emb_helio([mjd0 + 0.01])
                pm = self._analytic_emb_helio([mjd0 - 0.01])
                pos.append(p[0])
                vel.append((pp[0] - pm[0]) / (0.02 * DAY_S))
            else:
                dl, dafr = gcorr.get(nm, (0.0, 0.0))
                p, v = _kepler_posvel_au(nm, np.array([t]), dl, dafr)
                pos.append(_ecl_to_icrs(p)[0] * AU_KM * 1e3)
                vel.append(_ecl_to_icrs(v)[0] * AU_KM * 1e3 / DAY_S)
        return np.array(pos), np.array(vel)

    #: giant-planet mean-element corrections the anchored fit solves
    #: for, as (planet, which) with which in {"dL" [rad], "da" [frac]}
    _GIANT_PARAMS = (("jupiter", "dL"), ("jupiter", "da"),
                     ("saturn", "dL"), ("saturn", "da"),
                     ("uranus", "dL"))
    #: finite-difference steps for the frozen sensitivity matrix:
    #: EMB pos [m], EMB vel [m/s], then per _GIANT_PARAMS entry
    _FIT_STEPS = [1e4] * 3 + [1e-3] * 3 + [1e-5, 1e-7, 1e-5, 1e-7, 1e-4]

    def _anchor_emb_bary(self):
        """(mjd_tdb, emb_pos_m) of the DE405 anchor table, converted
        geocenter->EMB with the lunar series (mu*moon_geo ~ 4671 km, so
        the series' ~50-100 km Moon error enters at only ~1 km)."""
        from pint_tpu.data import de_anchor

        mjd = np.asarray(de_anchor.MJD_TDB, np.float64)
        t_cy = (mjd - _J2000_MJD) / 36525.0
        mp_km, _ = _moon_geocentric_km(t_cy)
        M = _ecl_date_to_icrs_matrix(t_cy)
        mp = np.einsum("...ij,...j->...i", M, mp_km) * 1e3
        return mjd, np.asarray(de_anchor.EARTH_POS_M, np.float64) \
            + _MOON_FRAC * mp

    @staticmethod
    def _stored_gcorr():
        """Giant-planet mean-element corrections from the baked-in
        multi-dataset calibration (see :mod:`pint_tpu.ephemcal`), as a
        {planet: (dL_rad, da_frac)} dict; empty when the calibration
        data is absent or disabled (PINT_TPU_NO_EPHEMCAL=1)."""
        if os.environ.get("PINT_TPU_NO_EPHEMCAL") == "1":
            return {}
        try:
            from pint_tpu.data import ephem_calibration
        except ImportError:
            return {}
        return dict(ephem_calibration.GIANT_CORRECTIONS)

    def _integrate_window(self, wlo, whi, gcorr_base=None,
                          free_giants=None):
        """Integrate the window and fit the initial conditions.

        Two regimes:

        * **DE405-anchored** (the default whenever the window covers the
          anchor table): the fit target is the table's 730 daily
          BARYCENTRIC EMB positions — true JPL-ephemeris information.
          Free parameters: the EMB state (6), optionally
          mean-longitude/semi-major corrections for the giant planets
          (``free_giants`` — these move the Sun-vs-SSB term, the
          dominant error of the mean-element theory: measured ~1400 km
          Earth-SSB error unanchored), and a constant frame offset (3,
          absorbing bodies outside the 9-body system — Pluto alone
          shifts the DE SSB by ~40 km).  When the baked-in calibration
          supplies giant corrections (``gcorr_base``, default
          `_stored_gcorr`), the giants are FIXED there — the
          calibration fit them against multi-year sky-projected truth,
          which a 2-year anchor cannot constrain in extrapolation.
        * **analytic-anchored** (table absent/disabled/too far): the
          fit target is the truncated-VSOP87 heliocentric EMB over the
          whole window, EMB state only — the zero-data fallback.
        """
        from scipy.integrate import solve_ivp

        gm = _nbody_gm()
        rhs = _nbody_rhs_factory(gm)
        mjd0 = 0.5 * (wlo + whi)
        grid = np.arange(wlo, whi + self._STEP / 2, self._STEP)
        ts = grid - mjd0

        anchor = None
        ar = self._anchor_range()
        if ar is not None and wlo <= ar[0] and ar[1] <= whi:
            anchor = self._anchor_emb_bary()

        base = self._stored_gcorr() if gcorr_base is None else gcorr_base
        if free_giants is None:
            # The giants float ONLY in (opt-in) anchored builds: their
            # Sun-vs-SSB error is quasi-static-but-rotating, which the
            # 6 EMB dofs + offset cannot represent (measured in-window
            # floor 218 km without them, 7 km with).  Anchored mode is
            # an IN-WINDOW tool — a 2-year anchor cannot determine the
            # giants' slow terms, so the fitted values must not be
            # trusted in extrapolation (see _anchor_range).
            free_giants = self._GIANT_PARAMS if anchor is not None \
                else ()
        if anchor is None:
            free_giants = ()
        ngiant = len(free_giants)
        npar = 6 + ngiant
        _giant_steps = dict(zip(self._GIANT_PARAMS,
                                self._FIT_STEPS[6:]))
        steps = list(self._FIT_STEPS[:6]) + \
            [_giant_steps[g] for g in free_giants]

        def run(theta):
            gcorr = {nm: tuple(v) for nm, v in base.items()}
            for (nm, which), v in zip(free_giants, theta[6:]):
                dl, dafr = gcorr.get(nm, (0.0, 0.0))
                gcorr[nm] = (dl + v, dafr) if which == "dL" else \
                    (dl, dafr + v)
            pos, vel = self._base_ic(mjd0, gcorr)
            pos, vel = pos.copy(), vel.copy()
            pos[3] += theta[:3]
            vel[3] += theta[3:6]
            mtot = gm.sum()
            pos -= (gm[:, None] * pos).sum(0) / mtot
            vel -= (gm[:, None] * vel).sum(0) / mtot
            y0 = np.concatenate([pos.ravel(), vel.ravel()])
            kw = dict(rtol=1e-12, atol=1e-2, method="DOP853")
            fw = solve_ivp(rhs, (0, ts[-1] * DAY_S), y0,
                           t_eval=ts[ts >= 0] * DAY_S, **kw)
            bw = solve_ivp(rhs, (0, ts[0] * DAY_S), y0,
                           t_eval=ts[ts < 0][::-1] * DAY_S, **kw)
            return np.concatenate([bw.y[:, ::-1], fw.y], axis=1).T

        if anchor is not None:
            from scipy.interpolate import CubicSpline

            amjd, aemb = anchor

            def predict(Y):
                # barycentric EMB of the integration at anchor epochs
                return CubicSpline(grid, Y[:, 9:12])(amjd)

            # Hybrid fit target:
            # * the anchor rows (sigma ~10 m), with the constant frame
            #   offset profiled out EXACTLY (per-axis demean) — offset
            #   and IC columns are near-degenerate for quasi-static
            #   residuals, and an unscaled min-norm lstsq would split a
            #   static shift across orbital dofs, matching it in-window
            #   while swinging ~20x harder outside (measured: 74 km
            #   static perturbation -> 1400 km 1.5 yr past the anchor);
            # * the truncated-VSOP87 heliocentric EMB over the WHOLE
            #   window at its own ~40 km truncation grade — a weak
            #   tether that bounds extrapolation drift far from the
            #   anchor (a 2-year perfect anchor alone EXTRAPOLATES
            #   worse than fitting mediocre data everywhere: measured
            #   190 -> 768 us median on the B1855 holdout).
            # Anchor-dominant weights: anchored mode is OPT-IN for
            # in-window DE-grade accuracy (see _anchor_range), so the
            # anchor rows must win outright wherever they constrain;
            # the VSOP tether only keeps the far field from running
            # away (the two targets disagree systematically by
            # ~1400 km — one trajectory cannot satisfy both, and
            # balanced weights were measured to give the worst of both:
            # 727 us in-window AND 272 us on the B1855 holdout).
            ana = self._analytic_emb_helio(grid)
            wa, wv = 1.0 / 10.0, 1.0 / 40e3     # [1/m]

            def resid_vec(Y):
                ra = predict(Y) - aemb
                ra = ra - ra.mean(axis=0)
                rv = (Y[:, 9:12] - Y[:, 0:3]) - ana
                return np.concatenate([wa * ra.ravel(),
                                       wv * rv.ravel()])

            theta = np.zeros(npar)
            J = None
            for _ in range(3):
                Y = run(theta)
                r0 = resid_vec(Y)
                if J is None:  # frozen sensitivity (near-linear)
                    cols = []
                    for k in range(npar):
                        th2 = theta.copy()
                        th2[k] += steps[k]
                        cols.append((resid_vec(run(th2)) - r0)
                                    / steps[k])
                    J = np.column_stack(cols)
                upd, *_ = np.linalg.lstsq(J, -r0, rcond=None)
                theta = theta + upd
            Y = run(theta)
            # the frame offset is whatever constant remains vs DE405
            off = -(predict(Y) - aemb).mean(axis=0)
            nstate = 3 * len(_NBODY_NAMES)
            states = Y[:, :nstate].copy()
            # translate every body into the DE405 SSB frame
            states += np.tile(off, len(_NBODY_NAMES))
            return grid, states

        ana = self._analytic_emb_helio(grid)
        dic = np.zeros(6)
        J = None
        for _ in range(3):
            Y = run(np.concatenate([dic, np.zeros(ngiant)]))
            emb = Y[:, 9:12] - Y[:, 0:3]
            res = (emb - ana).ravel()
            if J is None:  # frozen sensitivity (the problem is near-linear)
                J = np.zeros((res.size, 6))
                steps = self._FIT_STEPS[:6]
                for k in range(6):
                    d2 = dic.copy()
                    d2[k] += steps[k]
                    Yk = run(np.concatenate([d2, np.zeros(ngiant)]))
                    J[:, k] = ((Yk[:, 9:12] - Yk[:, 0:3]) - emb).ravel() \
                        / steps[k]
            upd, *_ = np.linalg.lstsq(J, -res, rcond=None)
            dic = dic + upd
        Y = run(np.concatenate([dic, np.zeros(ngiant)]))
        nstate = 3 * len(_NBODY_NAMES)
        return grid, Y[:, :nstate]

    # -- posvel ------------------------------------------------------------
    def posvel(self, body: str, mjd_tdb, _window_key=None) -> PosVel:
        body = body.lower()
        mjd = np.asarray(mjd_tdb, np.float64)
        if body == "ssb":
            z = np.zeros(np.shape(mjd) + (3,))
            return PosVel(z, z.copy())
        splines = self._splines_for(mjd, key=_window_key)
        t_cy = (mjd - _J2000_MJD) / 36525.0
        if body in ("earth", "moon", "emb"):
            emb_p = splines["emb"](mjd)
            emb_v = splines["emb"](mjd, 1) / DAY_S
            if body == "emb":
                return PosVel(emb_p, emb_v)
            mp_km, mv_kmd = _moon_geocentric_km(t_cy)
            M = _ecl_date_to_icrs_matrix(t_cy)
            mp = np.einsum("...ij,...j->...i", M, mp_km) * 1e3
            mv = np.einsum("...ij,...j->...i", M, mv_kmd) * 1e3 / DAY_S
            if body == "earth":
                p_e = emb_p - _MOON_FRAC * mp
                v_e = emb_v - _MOON_FRAC * mv
                # baked truth correction applies to the GEOCENTER (it
                # was fit against geocenter truth — Roemer projections
                # and the DE405 daily table — so no lunar-series error
                # enters); 'emb'/'moon' stay on the raw integration
                corr = splines.get("_earth_corr")
                if corr is not None:
                    p_e = p_e + corr(mjd)
                    v_e = v_e + corr(mjd, 1) / DAY_S
                return PosVel(p_e, v_e)
            return PosVel(emb_p + (1.0 - _MOON_FRAC) * mp,
                          emb_v + (1.0 - _MOON_FRAC) * mv)
        key = body[:-5] if body.endswith("_bary") else body
        if key in splines:
            return PosVel(splines[key](mjd),
                          splines[key](mjd, 1) / DAY_S)
        return super().posvel(body, mjd_tdb)


# --- SPK writer ---------------------------------------------------------------

#: (body, center) pairs written by write_spk, with their NAIF codes and
#: Chebyshev record length [days] (the real DE kernels use the same
#: chain topology: SSB->EMB->{Earth,Moon}, SSB->Sun, SSB->planet
#: barys).  Records are 4 days for EVERY body, aligned to 4-day MJD
#: boundaries: the integrated ephemeris serves cubic splines with
#: 4-day knots (IntegratedEphemeris._STEP) on a 4-day-aligned grid, so
#: knot-aligned records see an exactly-cubic source and the Chebyshev
#: fit is exact — longer records straddle knots, where the source is
#: only C^2 and high-order convergence collapses (measured: 74 km
#: Mercury error with 8-day records vs sub-mm aligned).
_WRITE_PAIRS = (
    (("emb", "ssb"), (3, 0), 4.0),
    (("earth", "emb"), (399, 3), 4.0),
    (("moon", "emb"), (301, 3), 4.0),
    (("sun", "ssb"), (10, 0), 4.0),
    (("mercury", "ssb"), (1, 0), 4.0),
    (("venus", "ssb"), (2, 0), 4.0),
    (("mars", "ssb"), (4, 0), 4.0),
    (("jupiter", "ssb"), (5, 0), 4.0),
    (("saturn", "ssb"), (6, 0), 4.0),
    (("uranus", "ssb"), (7, 0), 4.0),
    (("neptune", "ssb"), (8, 0), 4.0),
)


def write_spk(path: str, eph, mjd_lo: float, mjd_hi: float,
              ncoef: int = 13) -> str:
    """Write a JPL-format SPK (``.bsp``) kernel from any ephemeris
    object with a ``posvel(body, mjd_tdb)`` method — the inverse of
    :class:`SPKEphemeris` (DAF + type-2 Chebyshev position segments,
    little-endian).

    This is how the builtin integrated ephemeris's "drop in a .bsp for
    full precision" claim becomes testable without network access: a
    kernel written from the integrator and read back through the SPK
    path must reproduce the direct path exactly
    (tests/test_spk_writer.py), so when a REAL ``de421.bsp`` is placed
    in ``$PINT_TPU_EPHEM_DIR`` the same plumbing serves full JPL
    precision.  Reference counterpart: the kernel files consumed via
    jplephem in `solar_system_ephemerides.py:18-45`.
    """
    import struct

    from numpy.polynomial import chebyshev as _cheb

    # 4-day-aligned start (see _WRITE_PAIRS: knot alignment)
    et_lo = mjd_tdb_to_et(4.0 * np.floor(mjd_lo / 4.0))
    et_hi = mjd_tdb_to_et(mjd_hi)

    segments = []  # (target, center, init, intlen, records)
    for (body, center), (tcode, ccode), days in _WRITE_PAIRS:
        intlen = days * DAY_S
        n = int(np.ceil((et_hi - et_lo) / intlen))
        init = et_lo
        # Chebyshev-Gauss nodes per record; one batched posvel call
        k = np.arange(ncoef)
        nodes = np.cos(np.pi * (k + 0.5) / ncoef)[::-1]  # (-1, 1)
        mids = init + (np.arange(n) + 0.5) * intlen
        radius = intlen / 2.0
        et = (mids[:, None] + nodes[None, :] * radius).ravel()
        mjd = et / DAY_S + _J2000_MJD
        p = eph.posvel(body, mjd).pos
        if center != "ssb":
            p = p - eph.posvel(center, mjd).pos
        p_km = (p / 1e3).reshape(n, ncoef, 3)
        recs = np.empty((n, 2 + 3 * ncoef))
        recs[:, 0] = mids
        recs[:, 1] = radius
        for i in range(n):
            # interpolation through the Gauss nodes (exact fit)
            c = _cheb.chebfit(nodes, p_km[i], ncoef - 1)  # (ncoef, 3)
            recs[i, 2:] = c.T.ravel()
        segments.append((tcode, ccode, init, intlen, recs))

    # --- DAF assembly (record = 1024 bytes = 128 f64 words) ------------
    nd, ni = 2, 6
    data_word = 3 * 128 + 1          # 1-based word address of record 4
    seg_meta = []
    blobs = []
    w = data_word
    for tcode, ccode, init, intlen, recs in segments:
        n, rsize = recs.shape
        words = np.concatenate(
            [recs.ravel(), [init, intlen, float(rsize), float(n)]])
        seg_meta.append((tcode, ccode, w, w + words.size - 1,
                         init, init + n * intlen))
        blobs.append(words)
        w += words.size
    free = w

    fr = bytearray(1024)
    fr[0:8] = b"DAF/SPK "
    struct.pack_into("<ii", fr, 8, nd, ni)
    fr[16:76] = b"pint_tpu write_spk".ljust(60)
    struct.pack_into("<iii", fr, 76, 2, 2, free)
    fr[88:96] = b"LTL-IEEE"

    sr = bytearray(1024)
    struct.pack_into("<ddd", sr, 0, 0.0, 0.0, float(len(seg_meta)))
    ss = nd + (ni + 1) // 2          # summary size [words]
    for k, (tc, cc, beg, end, e0, e1) in enumerate(seg_meta):
        off = (3 + k * ss) * 8
        struct.pack_into("<dd", sr, off, e0, e1)
        struct.pack_into("<iiiiii", sr, off + 16, tc, cc, 1, 2, beg, end)
    nr = bytearray(1024)

    with open(path, "wb") as f:
        f.write(bytes(fr) + bytes(sr) + bytes(nr))
        for words in blobs:
            f.write(np.asarray(words, "<f8").tobytes())
    return path


# --- loader -------------------------------------------------------------------

_EPHEM_CACHE: Dict[str, object] = {}


def _search_dirs():
    dirs = []
    env = os.environ.get("PINT_TPU_EPHEM_DIR")
    if env:
        dirs.append(env)
    dirs += [os.getcwd(), os.path.join(os.path.dirname(__file__), "data", "ephem")]
    return dirs


def load_ephemeris(name: Optional[str] = "DE421"):
    """Resolve an ephemeris by name ('DE421'), path, or fallback to builtin.

    Mirrors the reference's resolution order (`solar_system_ephemerides.py`)
    minus the network download (zero-egress environment).
    """
    key = (name or "builtin").lower()
    # the mode override is part of the cache identity: changing
    # PINT_TPU_EPHEM_MODE between calls must not serve stale instances
    mode = os.environ.get("PINT_TPU_EPHEM_MODE", "").lower()
    cache_key = (key, mode)
    if cache_key in _EPHEM_CACHE:
        return _EPHEM_CACHE[cache_key]
    eph = None
    analytic_names = ("builtin", "builtin_analytic")
    builtin_names = analytic_names + ("builtin_integrated",)
    if key == "builtin_integrated":
        eph = _shared_integrated()
    elif key not in analytic_names:
        if os.path.isfile(key) or os.path.isfile(str(name)):
            eph = SPKEphemeris(str(name) if os.path.isfile(str(name)) else key)
        else:
            fname = key if key.endswith(".bsp") else key + ".bsp"
            for d in _search_dirs():
                p = os.path.join(d, fname)
                if os.path.isfile(p):
                    eph = SPKEphemeris(p)
                    break
    if eph is None:
        # fallback resolution: a missing *named kernel* always warns; the
        # substitute is the integrated ephemeris (best offline accuracy)
        # unless PINT_TPU_EPHEM_MODE=analytic.  Explicit "builtin" stays
        # the cheap analytic series unless the mode forces integrated.
        if key not in builtin_names:
            warnings.warn(
                f"ephemeris kernel {name!r} not found on disk; falling "
                "back to the builtin "
                + ("analytic" if mode == "analytic" else "integrated")
                + " ephemeris (~100-300 km Earth; sub-ms light time). "
                "Supply the .bsp via $PINT_TPU_EPHEM_DIR for full "
                "accuracy.", stacklevel=2)
        if mode == "analytic":
            eph = BuiltinEphemeris(warn=False)
        elif key in analytic_names and mode != "integrated":
            eph = BuiltinEphemeris(warn=False)
        else:
            eph = _shared_integrated()
    _EPHEM_CACHE[cache_key] = eph
    return eph


class _PinnedEphemeris:
    """Window-pinned view of an :class:`IntegratedEphemeris` (see
    `IntegratedEphemeris.pinned_to`)."""

    def __init__(self, eph: "IntegratedEphemeris", key):
        self._eph = eph
        self._key = key
        self.name = eph.name

    def posvel(self, body: str, mjd_tdb) -> PosVel:
        return self._eph.posvel(body, mjd_tdb, _window_key=self._key)


_INTEGRATED_SINGLETON: Optional["IntegratedEphemeris"] = None


def _shared_integrated() -> "IntegratedEphemeris":
    """One IntegratedEphemeris instance for every kernel-name fallback, so
    each quantized window is integrated once per process and shared (the
    instance keeps a dict of windows; results are a pure function of each
    query's own span — see `IntegratedEphemeris._window_key`)."""
    global _INTEGRATED_SINGLETON
    if _INTEGRATED_SINGLETON is None:
        _INTEGRATED_SINGLETON = IntegratedEphemeris(warn=False)
    return _INTEGRATED_SINGLETON


def objPosVel_wrt_SSB(objname: str, mjd_tdb, ephem="DE421") -> PosVel:
    """Drop-in analogue of the reference's `objPosVel_wrt_SSB`
    (`src/pint/solar_system_ephemerides.py`): SI units, ICRS, SSB-centered."""
    eph = ephem if hasattr(ephem, "posvel") else load_ephemeris(ephem)
    return eph.posvel(objname, mjd_tdb)
