"""Minimal from-scratch FITS reader: headers + binary tables.

The reference reads photon-event files through `astropy.io.fits`
(`/root/reference/src/pint/event_toas.py:195`); this environment has no
astropy, and event files only need a small subset of FITS: 2880-byte
blocks of 80-character header cards, then big-endian binary-table (or
image) data.  Supports the TFORM codes mission event files use
(L/B/I/J/K/E/D, with repeat counts) plus header-only access for the
timing keywords (MJDREF*, TIMESYS, TIMEZERO, TELESCOP, ...).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["read_fits", "FITSHDU"]

BLOCK = 2880
CARD = 80

#: TFORM letter -> (numpy big-endian dtype, bytes)
_TFORM = {
    "L": (">u1", 1), "B": (">u1", 1), "I": (">i2", 2), "J": (">i4", 4),
    "K": (">i8", 8), "E": (">f4", 4), "D": (">f8", 8),
}

#: element widths of codes we can skip over but not decode
_SKIP_WIDTH = {"X": None, "C": 8, "M": 16, "P": 8, "Q": 16}


def _column_bytes(repeat: int, code: str) -> int:
    if code == "A":
        return repeat
    if code == "X":                  # bit array: ceil(repeat/8) bytes
        return (repeat + 7) // 8
    if code in _SKIP_WIDTH:
        return repeat * _SKIP_WIDTH[code]
    if code in _TFORM:
        return repeat * _TFORM[code][1]
    raise ValueError(f"unsupported FITS TFORM code {code!r}")


class FITSHDU:
    """One header-data unit: header dict + (for BINTABLE) column arrays."""

    def __init__(self, header: Dict[str, object],
                 data: Optional[Dict[str, np.ndarray]] = None):
        self.header = header
        self.data = data or {}

    @property
    def name(self) -> str:
        return str(self.header.get("EXTNAME", "")).strip()

    def __getitem__(self, col: str) -> np.ndarray:
        return self.data[col.upper()]

    def __contains__(self, col: str) -> bool:
        return col.upper() in self.data


def _parse_card(card: bytes):
    """One 80-byte header card -> (key, value) or None."""
    s = card.decode("ascii", errors="replace")
    key = s[:8].strip()
    if not key or key in ("COMMENT", "HISTORY", "END"):
        return None
    if s[8:10] != "= ":
        return None
    body = s[10:]
    # strip inline comment (outside quoted strings)
    if body.lstrip().startswith("'"):
        start = body.index("'")
        end = body.index("'", start + 1)
        # FITS doubles quotes inside strings; rare in practice
        while end + 1 < len(body) and body[end + 1] == "'":
            end = body.index("'", end + 2)
        val: object = body[start + 1:end].rstrip()
    else:
        body = body.split("/")[0].strip()
        if body in ("T", "F"):
            val = body == "T"
        else:
            try:
                val = int(body)
            except ValueError:
                try:
                    val = float(body)
                except ValueError:
                    val = body
    return key, val


def _read_header(f) -> Optional[Dict[str, object]]:
    header: Dict[str, object] = {}
    while True:
        block = f.read(BLOCK)
        if len(block) < BLOCK:
            return None if not header else header
        for i in range(0, BLOCK, CARD):
            card = block[i:i + CARD]
            if card.startswith(b"END"):
                return header
            kv = _parse_card(card)
            if kv:
                header[kv[0]] = kv[1]


def _data_size(header) -> int:
    naxis = int(header.get("NAXIS", 0))
    if naxis == 0:
        return 0
    size = abs(int(header.get("BITPIX", 8))) // 8
    for i in range(1, naxis + 1):
        size *= int(header.get(f"NAXIS{i}", 0))
    size *= int(header.get("GCOUNT", 1))
    size += int(header.get("PCOUNT", 0))
    return size


def _parse_tform(tform: str) -> Tuple[int, str]:
    tform = str(tform).strip()
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    return repeat, tform[i] if i < len(tform) else tform[0]


def _read_bintable(header, raw: bytes) -> Dict[str, np.ndarray]:
    nrow = int(header["NAXIS2"])
    rowbytes = int(header["NAXIS1"])
    nfields = int(header["TFIELDS"])
    cols: List[Tuple[str, int, str, int]] = []   # (name, repeat, code, off)
    off = 0
    for i in range(1, nfields + 1):
        name = str(header.get(f"TTYPE{i}", f"COL{i}")).strip().upper()
        repeat, code = _parse_tform(header[f"TFORM{i}"])
        cols.append((name, repeat, code, off))
        off += _column_bytes(repeat, code)
    if off != rowbytes:
        raise ValueError(
            f"binary table row size mismatch: {off} != NAXIS1={rowbytes}")
    table = np.frombuffer(raw[:nrow * rowbytes], dtype=np.uint8)
    table = table.reshape(nrow, rowbytes)
    out = {}
    for name, repeat, code, off in cols:
        if code == "A":
            chunk = table[:, off:off + repeat]
            out[name] = np.array(
                [bytes(r).decode("ascii", "replace").rstrip()
                 for r in chunk])
            continue
        if code in _SKIP_WIDTH:
            # bit arrays / complex / variable-length descriptors: skipped
            # (row layout stays intact so the other columns still parse)
            continue
        dtype, width = _TFORM[code]
        chunk = table[:, off:off + repeat * width].copy()
        arr = chunk.view(dtype).reshape(nrow, repeat)
        if code == "L":              # FITS logicals are ASCII 'T'/'F'
            arr = arr == ord("T")
        out[name] = arr[:, 0] if repeat == 1 else arr
    return out


def read_fits(path: str) -> List[FITSHDU]:
    """Read all HDUs; binary-table extensions get parsed column data."""
    hdus = []
    with open(path, "rb") as f:
        while True:
            header = _read_header(f)
            if header is None:
                break
            size = _data_size(header)
            padded = ((size + BLOCK - 1) // BLOCK) * BLOCK
            raw = f.read(padded)
            if len(raw) < padded and size > 0:
                raise ValueError("truncated FITS data unit")
            xt = str(header.get("XTENSION", "")).strip()
            if xt == "BINTABLE":
                hdus.append(FITSHDU(header, _read_bintable(header, raw)))
            else:
                hdus.append(FITSHDU(header))
    if not hdus:
        raise ValueError(f"{path} is not a FITS file (no HDUs)")
    return hdus
