"""Precision-flow auditor: prove the dd chain survives without native f64.

The package's numerical story rests on one claim: every phase-critical
value travels the device program either in native f64 or inside a
compensated multi-word representation (the QS quad-single words of
:mod:`pint_tpu.qs`, the DD pairs of :mod:`pint_tpu.dd`), and never
passes through *bare* float32 arithmetic.  On hardware with true f64
the claim is cheap; on TPUs — where f64 is slow emulation or absent —
it is the whole ballgame, and :func:`pint_tpu.precision.policy`
("dd32") exists precisely so programs can be built with x64 disabled.
Until this module the claim was enforced only locally (AST rules over
source text, JAXPR001 over narrowing conversions); nothing *proved*
end-to-end that a traced entrypoint keeps its critical dataflow out of
bare f32.

This module is an abstract interpreter over closed jaxprs.  Every
intermediate value is assigned a class from a small precision lattice:

* ``EXACT_INT`` — integers, and integer-valued floats below 2^24
  (day counts): exact in any float width.
* ``F64`` — native float64: fine wherever it exists.
* ``DD_PAIR`` — one word of a (hi, lo) pair created by the
  ``pint_tpu_eft_guard`` primitive; the partner word is tracked through
  a shared *pair group* so breaking the pair is detectable.
* ``COMPENSATED_F32`` — an f32 word participating in a compensated
  representation (QS words, exact-split words, outputs of sanctioned
  dd/qs kernels).
* ``BARE_F32`` — plain f32 arithmetic: precision is gone.
* ``BOTTOM`` — unreached (join identity).

Alongside the class, each value carries a *taint set* (which critical
inputs feed it — ``F0__qs`` words, ``tdb_frac_w``, the TZR phase
words…) and a bounded *provenance* chain of the source locations that
produced it, so a finding names not just the offending equation but
the path from the feeding input.

**Sanctioned kernels.**  dd.py and qs.py internally do f32 arithmetic
on purpose — that is what an error-free transformation *is*.  For each
equation the auditor walks the jax user-frame stack and finds the
OUTERMOST frame inside dd.py/qs.py.  If that frame's function is a
declared pair-preserving kernel (``dd.PAIR_KERNELS`` /
``qs.PAIR_KERNELS``) or a private helper, the equation is
pair-preserving: f32 outputs are ``COMPENSATED_F32``, never findings.
If it is a declared collapse kernel (``to_f64``, ``to_float``…) the
output class follows its dtype — ``F64`` when x64 is on, ``BARE_F32``
(a PREC002 on tainted data) when it is not.  An *unknown public* dd/qs
function is treated as a collapse: new kernels must be declared, they
do not ride in sanctioned.

**Rules.**

* **PREC002** — a tainted value TRANSITIONS into ``BARE_F32``: the
  equation where phase-critical precision is destroyed (reported once
  per collapse site, with the provenance chain back to the feeding
  input).
* **PREC003** — a tainted ``DD_PAIR`` member is consumed by a
  non-sanctioned, non-structural equation without its partner among
  the inputs: the pair is broken even though no individual op narrowed
  anything.

Structural primitives (broadcast/reshape/transpose/slice/…) propagate
class, taint and pair membership instead of breaking them;
``pjit``/``scan``/``while``/``cond``/``custom_*`` sub-jaxprs are
entered with the caller's states (loop carries are re-run once after
joining, branch outputs are joined).

**Driving it.**  Entrypoints declare themselves with
``@precision_contract(name, chain="phase_critical")``
(:mod:`pint_tpu.lint.contracts`); :func:`audit_precision` traces each
declared entrypoint TWICE on a small barycentric fixture — once with
native x64, once rebuilt entirely under
``jax.experimental.disable_x64()`` with ``precision.policy("dd32")`` —
and both legs must come back clean.  Run it:
``python -m pint_tpu.lint --precflow`` (subset:
``--precflow=name1,name2``; list: ``--list-precision-contracts``).
The seeded regression proving the auditor catches a real break is
``faultinject.collapse_dd_pair``, which recombines the residual DD
pair with a raw f32 add — PREC002 fires at the faultinject site with
provenance back to ``tdb_frac_w``.

Suppression uses the shared syntax at the reported call site::

    x = qs.to_f64(frac)  # ddlint: disable=PREC002 <why this is fine>
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

from pint_tpu.lint.findings import Finding, scan_suppressions

__all__ = [
    "BOTTOM", "EXACT_INT", "F64", "DD_PAIR", "COMPENSATED_F32", "BARE_F32",
    "VarState", "join", "join_states", "ChainSpec", "CHAINS",
    "analyze_closed_jaxpr", "analyze_fn", "audit_precision",
]

# --- the lattice --------------------------------------------------------------

BOTTOM = "bottom"
EXACT_INT = "exact_int"
F64 = "f64"
DD_PAIR = "dd_pair"
COMPENSATED_F32 = "compensated_f32"
BARE_F32 = "bare_f32"

#: every class, in no particular order (the lattice is not a chain)
CLASSES = (BOTTOM, EXACT_INT, F64, DD_PAIR, COMPENSATED_F32, BARE_F32)


def join(a: str, b: str) -> str:
    """Least-upper-bound of two precision classes (used where control
    flow merges: cond branches, loop carries).  ``BARE_F32`` absorbs —
    a value that is bare on ANY path is bare; ``EXACT_INT`` is neutral
    (exact in every representation); mixing distinct wide
    representations degrades conservatively to ``COMPENSATED_F32``
    (still not a finding — only ``BARE_F32`` is)."""
    if a == b:
        return a
    if a == BOTTOM:
        return b
    if b == BOTTOM:
        return a
    if BARE_F32 in (a, b):
        return BARE_F32
    if a == EXACT_INT:
        return b
    if b == EXACT_INT:
        return a
    # distinct members of {F64, DD_PAIR, COMPENSATED_F32}
    return COMPENSATED_F32


@dataclass(frozen=True)
class VarState:
    """Abstract state of one jaxpr value."""

    cls: str = BOTTOM
    taint: frozenset = frozenset()      #: critical-input labels feeding it
    group: Optional[int] = None         #: pair-group id (DD_PAIR partners)
    prov: tuple = ()                    #: bounded provenance (loc strings)


_UNTRACKED = VarState(BARE_F32)         # untainted fallback

_PROV_CAP = 8


def join_states(a: VarState, b: VarState) -> VarState:
    return VarState(
        join(a.cls, b.cls), a.taint | b.taint,
        a.group if a.group == b.group else None,
        a.prov if len(a.prov) >= len(b.prov) else b.prov)


# --- chains: which inputs are precision-critical ------------------------------


class ChainSpec(NamedTuple):
    """What "critical" means for one declared chain: program inputs
    whose pytree path matches ``param_pattern``, plus the named TOA
    batch columns (matched against jaxpr constants by identity, or by
    bitwise equality for staged copies)."""

    param_pattern: str
    batch_fields: Tuple[str, ...]


#: chain name (the ``chain=`` of ``@precision_contract``) -> spec
CHAINS: Dict[str, ChainSpec] = {
    "phase_critical": ChainSpec(
        param_pattern=r"__qs|__fracqs|__tzrphase__",
        batch_fields=("tdb_day", "tdb_frac", "tdb_frac_w", "pulse_number"),
    ),
}


# --- jaxpr plumbing -----------------------------------------------------------

_SANCTIONED_FILES = {"dd.py", "qs.py"}

#: primitives that move values without doing arithmetic on them —
#: class/taint/pair membership passes straight through
_STRUCTURAL = {
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "dynamic_update_slice", "rev", "copy",
    "stop_gradient", "gather", "pad", "reduce_precision", "select_n",
    "concatenate",
}

#: primitives under which integer-valued-exact floats stay exact
_INT_EXACT = {"add", "sub", "neg", "mul", "max", "min", "round", "floor",
              "ceil", "abs", "convert_element_type", "reduce_sum",
              "reduce_max", "reduce_min"}

_GUARD_PRIM = "pint_tpu_eft_guard"


def _float_bits(dtype) -> Optional[int]:
    name = getattr(dtype, "name", str(dtype))
    return {"float16": 16, "bfloat16": 16,
            "float32": 32, "float64": 64}.get(name)


def _dtype_kind(dtype) -> str:
    name = getattr(dtype, "name", str(dtype))
    if name.startswith(("int", "uint", "bool")):
        return "int"
    if name == "float64":
        return "f64"
    return "f32"


def _user_frames(eqn) -> List[Tuple[str, str, str, int]]:
    """(basename, function, path, line) per user frame, innermost
    first."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return []
    frames = []
    try:
        from jax._src import source_info_util as siu

        frames = list(siu.user_frames(si))
    except Exception:
        tb = getattr(si, "traceback", None)
        if tb is not None and hasattr(tb, "frames"):
            frames = list(tb.frames)
    out = []
    for fr in frames:
        path = getattr(fr, "file_name", None) or \
            getattr(fr, "filename", None) or ""
        line = getattr(fr, "start_line", None) or \
            getattr(fr, "line_num", None) or getattr(fr, "lineno", 0) or 0
        func = getattr(fr, "function_name", None) or \
            getattr(fr, "name", "") or ""
        if path:
            out.append((os.path.basename(path), func, path, int(line)))
    return out


_SUPPRESS_CACHE: dict = {}
_SRC_CACHE: dict = {}


def _suppressed(path: Optional[str], line: Optional[int], code: str) -> bool:
    if not path or not line or not os.path.isfile(path):
        return False
    sup = _SUPPRESS_CACHE.get(path)
    if sup is None:
        try:
            with open(path, encoding="utf-8") as fh:
                sup = scan_suppressions(fh.read())
        except OSError:
            sup = scan_suppressions("")
        _SUPPRESS_CACHE[path] = sup
    return sup.is_suppressed(code, line)


def _src_line(path: Optional[str], line: Optional[int]) -> str:
    if not path or not line or not os.path.isfile(path):
        return ""
    lines = _SRC_CACHE.get(path)
    if lines is None:
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            lines = []
        _SRC_CACHE[path] = lines
    return lines[line - 1] if 0 < line <= len(lines) else ""


def _as_closed(val):
    """(open jaxpr, consts) from whatever an eqn param holds."""
    if hasattr(val, "jaxpr"):                       # ClosedJaxpr
        return val.jaxpr, list(val.consts)
    if hasattr(val, "eqns"):                        # open Jaxpr
        return val, []
    return None, []


# --- the interpreter ----------------------------------------------------------


class _Ctx:
    """Shared analysis state: finding sink, dedup, const classifier,
    pair-group allocator."""

    def __init__(self, name: str,
                 classify_const: Callable[[object], Optional[str]]):
        self.name = name
        self.classify_const = classify_const
        self.findings: List[Finding] = []
        self._emitted: set = set()
        self._next_group = 0

    def new_group(self) -> int:
        self._next_group += 1
        return self._next_group

    def emit(self, code: str, path: Optional[str], line: Optional[int],
             message: str) -> None:
        key = (code, path or "", line or 0)
        if key in self._emitted:
            return
        self._emitted.add(key)
        if _suppressed(path, line, code):
            return
        self.findings.append(Finding(
            code, path or f"<traced {self.name}>", line or 0, 0, message,
            source=_src_line(path, line), origin="precflow"))


def _init_state(aval, label: Optional[str]) -> VarState:
    """Initial class of a program input/constant from its dtype; a
    critical f32 input is a compensated word (the exact splits), never
    bare."""
    kind = _dtype_kind(getattr(aval, "dtype", None))
    taint = frozenset([label]) if label else frozenset()
    if kind == "int":
        return VarState(EXACT_INT, taint)
    if kind == "f64":
        return VarState(F64, taint)
    return VarState(COMPENSATED_F32 if label else BARE_F32, taint)


def _literal_state(var) -> VarState:
    kind = _dtype_kind(getattr(getattr(var, "aval", None), "dtype", None))
    if kind == "int":
        return VarState(EXACT_INT)
    val = getattr(var, "val", None)
    try:
        if val is not None and float(val) == float(int(val)) and \
                abs(float(val)) < 2 ** 24:
            return VarState(EXACT_INT)
    except (TypeError, ValueError, OverflowError):
        pass
    return VarState(F64 if kind == "f64" else BARE_F32)


def _is_literal(var) -> bool:
    return not hasattr(var, "count") and hasattr(var, "val")


def _literal_is_zero(var) -> bool:
    try:
        import numpy as np

        return _is_literal(var) and np.all(np.asarray(var.val) == 0)
    except Exception:
        return False


def _loc_tag(frames, prim: str) -> str:
    if frames:
        base, _fn, _path, line = frames[0]
        return f"{base}:{line}({prim})"
    return f"<nowhere>({prim})"


def _extend_prov(states: Sequence[VarState], tag: str) -> tuple:
    best: tuple = ()
    for s in states:
        if s.taint and len(s.prov) > len(best):
            best = s.prov
    if best and best[-1] == tag:
        return best
    return (best + (tag,))[-_PROV_CAP:]


def _sanction(frames) -> Tuple[Optional[str], Optional[str], tuple]:
    """Outermost dd.py/qs.py frame classification.

    Returns ``(verdict, kernel, call_site)`` where verdict is ``None``
    (not inside dd/qs), ``"pair"`` or ``"collapse"``; call_site is the
    first frame OUTSIDE the sanctioned region (where the module-boundary
    call happened — the actionable location for a collapse finding).
    """
    idx = None
    for i, (base, _fn, _path, _line) in enumerate(frames):
        if base in _SANCTIONED_FILES:
            idx = i
    if idx is None:
        return None, None, ()
    base, fn, _path, _line = frames[idx]
    call_site = frames[idx + 1] if idx + 1 < len(frames) else frames[idx]
    from pint_tpu import dd as _dd
    from pint_tpu import qs as _qs

    mod = _dd if base == "dd.py" else _qs
    if fn in mod.PAIR_KERNELS or fn.startswith("_"):
        return "pair", fn, call_site
    # declared collapse kernels AND unknown public names both collapse:
    # a new kernel must be declared in PAIR_KERNELS to ride sanctioned
    return "collapse", fn, call_site


def _taint_msg(taint: frozenset) -> str:
    return ", ".join(sorted(taint)) or "<untainted>"


def _prov_msg(prov: tuple) -> str:
    return " -> ".join(prov) if prov else "<no provenance>"


def _run_jaxpr(jaxpr, in_states: Sequence[VarState],
               const_states: Sequence[VarState], ctx: _Ctx) -> List[VarState]:
    env: Dict[object, VarState] = {}
    for v, s in zip(jaxpr.constvars, const_states):
        env[v] = s
    for v, s in zip(jaxpr.invars, in_states):
        env[v] = s

    def state_of(var) -> VarState:
        if _is_literal(var):
            return _literal_state(var)
        return env.get(var, _UNTRACKED)

    for eqn in jaxpr.eqns:
        outs = _eval_eqn(eqn, [state_of(v) for v in eqn.invars], ctx,
                         jaxpr.eqns)
        for v, s in zip(eqn.outvars, outs):
            env[v] = s
    return [state_of(v) for v in jaxpr.outvars]


def _consts_states(consts, ctx: _Ctx) -> List[VarState]:
    out = []
    for c in consts:
        out.append(_init_state(
            type("A", (), {"dtype": getattr(c, "dtype", None)})(),
            ctx.classify_const(c)))
    return out


def _eval_sub(eqn, states: Sequence[VarState], ctx: _Ctx
              ) -> Optional[List[VarState]]:
    """Interprocedural step: run the eqn's sub-jaxpr(s) with the
    caller's states.  Returns out states, or None if this eqn has no
    sub-jaxpr (caller falls through to the local transfer functions)."""
    prim = eqn.primitive.name
    params = eqn.params
    if prim == "scan":
        sub, consts = _as_closed(params["jaxpr"])
        nc, ncarry = params["num_consts"], params["num_carry"]
        body_consts = list(states[:nc])
        carry = list(states[nc:nc + ncarry])
        xs = list(states[nc + ncarry:])
        cstates = _consts_states(consts, ctx)
        outs = _run_jaxpr(sub, body_consts + carry + xs, cstates, ctx)
        # re-run once with joined carries (bounded fixpoint: one widening
        # round is enough for a monotone join over a finite lattice of
        # this depth in practice)
        carry2 = [join_states(a, b) for a, b in zip(carry, outs[:ncarry])]
        outs = _run_jaxpr(sub, body_consts + carry2 + xs, cstates, ctx)
        return outs[:ncarry] + outs[ncarry:]
    if prim == "while":
        csub, cconsts = _as_closed(params["cond_jaxpr"])
        bsub, bconsts = _as_closed(params["body_jaxpr"])
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cond_consts = list(states[:cn])
        body_consts = list(states[cn:cn + bn])
        carry = list(states[cn + bn:])
        outs = _run_jaxpr(bsub, body_consts + carry,
                          _consts_states(bconsts, ctx), ctx)
        carry2 = [join_states(a, b) for a, b in zip(carry, outs)]
        _run_jaxpr(csub, cond_consts + carry2,
                   _consts_states(cconsts, ctx), ctx)
        return _run_jaxpr(bsub, body_consts + carry2,
                          _consts_states(bconsts, ctx), ctx)
    if prim in ("cond", "switch"):
        branches = params["branches"]
        ops = list(states[1:])
        merged: Optional[List[VarState]] = None
        for br in branches:
            sub, consts = _as_closed(br)
            outs = _run_jaxpr(sub, ops, _consts_states(consts, ctx), ctx)
            merged = outs if merged is None else [
                join_states(a, b) for a, b in zip(merged, outs)]
        return merged
    # single-jaxpr wrappers: pjit / remat / custom_jvp / custom_vjp / …
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in params:
            sub, consts = _as_closed(params[key])
            if sub is not None:
                return _run_jaxpr(sub, list(states),
                                  _consts_states(consts, ctx), ctx)
    return None


def _eval_eqn(eqn, states: Sequence[VarState], ctx: _Ctx,
              sibling_eqns: Sequence = ()) -> List[VarState]:
    prim = eqn.primitive.name
    frames = _user_frames(eqn)
    tag = _loc_tag(frames, prim)
    taint = frozenset().union(*[s.taint for s in states]) if states \
        else frozenset()
    prov = _extend_prov(states, tag)

    sub_out = _eval_sub(eqn, states, ctx)
    if sub_out is not None:
        return sub_out

    # the EFT guard: its (>=2) outputs are a freshly minted dd pair
    if prim == _GUARD_PRIM:
        group = ctx.new_group()
        return [VarState(DD_PAIR, taint, group, prov) for _ in eqn.outvars]

    # x * 0 (literal) is a constant, not a flow of x's precision
    if prim == "mul" and any(_literal_is_zero(v) for v in eqn.invars):
        return [VarState(EXACT_INT) for _ in eqn.outvars]

    verdict, kernel, call_site = _sanction(frames)
    if verdict == "pair":
        groups = {s.group for s in states if s.group is not None}
        group = groups.pop() if len(groups) == 1 else None
        out = []
        for v in eqn.outvars:
            kind = _dtype_kind(getattr(getattr(v, "aval", None), "dtype",
                                       None))
            cls = {"int": EXACT_INT, "f64": F64}.get(kind, COMPENSATED_F32)
            out.append(VarState(cls, taint,
                                group if cls == COMPENSATED_F32 else None,
                                prov))
        return out
    if verdict == "collapse":
        out = []
        for v in eqn.outvars:
            kind = _dtype_kind(getattr(getattr(v, "aval", None), "dtype",
                                       None))
            if kind == "int":
                out.append(VarState(EXACT_INT, taint, None, prov))
            elif kind == "f64":
                out.append(VarState(F64, taint, None, prov))
            else:
                if taint:
                    _base, _fn, path, line = call_site
                    ctx.emit(
                        "PREC002", path, line,
                        f"phase-critical value collapses to bare f32 in "
                        f"'{kernel}' (traced '{ctx.name}'): fed by "
                        f"{_taint_msg(taint)}; chain {_prov_msg(prov)} — "
                        "the program does not survive without native f64")
                out.append(VarState(BARE_F32, taint, None, prov))
        return out

    # structural data movement: pass class/taint/pair membership through
    if prim in _STRUCTURAL:
        data = [s for s in states if s.cls != BOTTOM] or [_UNTRACKED]
        merged = data[0]
        for s in data[1:]:
            merged = join_states(merged, s)
        return [VarState(merged.cls, taint, merged.group, prov)
                for _ in eqn.outvars]

    if prim == "convert_element_type":
        src = states[0] if states else _UNTRACKED
        new = eqn.params.get("new_dtype")
        kind = _dtype_kind(new)
        if kind == "int" or src.cls == EXACT_INT:
            return [VarState(EXACT_INT, taint, None, prov)]
        if kind == "f64":
            return [VarState(F64, taint, None, prov)]
        old_bits = _float_bits(getattr(getattr(eqn.invars[0], "aval", None),
                                       "dtype", None))
        if old_bits == 64:        # narrowing f64 -> f32
            # an exact split (sibling upcast + error-capturing subtract)
            # starts a compensated representation; anything else is a
            # plain demotion
            from pint_tpu.lint.jaxpr_audit import _is_exact_split

            if _is_exact_split(eqn, sibling_eqns):
                return [VarState(COMPENSATED_F32, taint, None, prov)]
            if taint and src.cls != BARE_F32:
                _emit_collapse(ctx, eqn, frames, prim, taint, prov)
            return [VarState(BARE_F32, taint, None, prov)]
        return [VarState(src.cls if src.cls != BOTTOM else BARE_F32,
                         taint, src.group, prov)]

    # generic numeric equation outside the sanctioned kernels
    out: List[VarState] = []
    fired_003 = False
    for s in states:
        if s.group is None or not s.taint or \
                s.cls not in (DD_PAIR, COMPENSATED_F32):
            continue
        partner = any(o is not s and o.group == s.group for o in states)
        if not partner:
            _base, _fn, path, line = frames[0] if frames else ("", "", None,
                                                               None)
            ctx.emit(
                "PREC003", path, line,
                f"dd pair broken in '{prim}' (traced '{ctx.name}'): the "
                f"hi/lo word is consumed without its partner outside the "
                f"sanctioned dd/qs kernels; fed by {_taint_msg(s.taint)}; "
                f"chain {_prov_msg(s.prov)}")
            fired_003 = True
            break
    all_exact = all(s.cls in (EXACT_INT, BOTTOM) for s in states) \
        if states else False
    for v in eqn.outvars:
        kind = _dtype_kind(getattr(getattr(v, "aval", None), "dtype", None))
        if kind == "int":
            out.append(VarState(EXACT_INT, taint, None, prov))
        elif kind == "f64":
            out.append(VarState(F64, taint, None, prov))
        elif all_exact and prim in _INT_EXACT:
            out.append(VarState(EXACT_INT, taint, None, prov))
        else:
            if taint and not fired_003 and any(
                    s.cls not in (BARE_F32, BOTTOM) for s in states):
                _emit_collapse(ctx, eqn, frames, prim, taint, prov)
            out.append(VarState(BARE_F32, taint, None, prov))
    return out


def _emit_collapse(ctx: _Ctx, eqn, frames, prim: str, taint: frozenset,
                   prov: tuple) -> None:
    _base, _fn, path, line = frames[0] if frames else ("", "", None, None)
    ctx.emit(
        "PREC002", path, line,
        f"phase-critical value collapses to bare f32 in '{prim}' "
        f"(traced '{ctx.name}'): fed by {_taint_msg(taint)}; chain "
        f"{_prov_msg(prov)} — the program does not survive without "
        "native f64")


# --- entry points -------------------------------------------------------------


def analyze_closed_jaxpr(closed, invar_labels: Sequence[Optional[str]],
                         classify_const: Callable[[object], Optional[str]]
                         = lambda c: None,
                         name: str = "<traced fn>") -> List[Finding]:
    """Run the abstract interpreter over a closed jaxpr.

    ``invar_labels`` marks the critical program inputs (parallel to
    ``closed.jaxpr.invars``; ``None`` = not critical);
    ``classify_const`` maps closure constants (at any sub-jaxpr depth)
    to a critical label or ``None``.
    """
    ctx = _Ctx(name, classify_const)
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    consts = list(getattr(closed, "consts", []) or [])
    in_states = [
        _init_state(getattr(v, "aval", None), lab)
        for v, lab in zip(jaxpr.invars, invar_labels)]
    _run_jaxpr(jaxpr, in_states, _consts_states(consts, ctx), ctx)
    return ctx.findings


def analyze_fn(fn, *args, pattern: str = "", invar_labels=None,
               critical_consts: Optional[Dict[str, object]] = None,
               name: Optional[str] = None) -> List[Finding]:
    """Trace ``fn(*args)`` and analyze it.

    Critical inputs are named either explicitly (``invar_labels``,
    parallel to the flattened args) or by regex over the argument
    pytree paths (``pattern``); ``critical_consts`` maps labels to
    arrays matched against closure constants by identity or bitwise
    equality.
    """
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    if invar_labels is None:
        leaves = jax.tree_util.tree_flatten_with_path(args)[0]
        rx = re.compile(pattern) if pattern else None
        invar_labels = []
        for path, _leaf in leaves:
            key = jax.tree_util.keystr(path)
            if rx and rx.search(key):
                parts = re.findall(r"\[['\"]?([^'\"\]]+)['\"]?\]", key)
                invar_labels.append(".".join(parts[1:] or parts) or key)
            else:
                invar_labels.append(None)
    crit = dict(critical_consts or {})

    def classify(c):
        import numpy as np

        for label, arr in crit.items():
            if c is arr:
                return label
            try:
                if getattr(c, "shape", None) == getattr(arr, "shape", ()) \
                        and getattr(c, "dtype", None) == \
                        getattr(arr, "dtype", None) \
                        and np.array_equal(np.asarray(c), np.asarray(arr)):
                    return label
            except Exception:
                continue
        return None

    return analyze_closed_jaxpr(
        closed, invar_labels, classify,
        name=name or getattr(fn, "__name__", "<traced fn>"))


# --- the audit driver ---------------------------------------------------------

# Spindown-only barycentric fixture: delays are identically zero, so
# the whole phase-critical chain is the QS/DD time axis — exactly what
# the dd32 policy must carry.  (Validated: the dd32 residuals of this
# fixture agree with the f64 path to <0.1 ns.)
_PREC_PAR = """
PSR PRECFLOW
F0 300.0 1
F1 -1.0e-15 1
PEPOCH 55000
TZRMJD 55000.05
TZRFRQ 0
TZRSITE bary
"""


def _fixture(ntoas: int = 12):
    """(model, toas) under the CURRENT x64/policy context — legs must
    build their own so staged dtypes match the regime under test."""
    import warnings

    import numpy as np

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.models import get_model
        from pint_tpu.toa import get_TOAs_array

        model = get_model(_PREC_PAR.strip().splitlines())
        t = 55000.0 + np.linspace(0.0, 10.0, ntoas)
        toas = get_TOAs_array(t, obs="bary", freqs_mhz=np.inf)
    return model, toas


def _drv_residuals(ntoas: int):
    """(fn, args, batch) for the 'residuals' precision contract."""
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.residuals import Residuals

        model, toas = _fixture(ntoas)
        resid = Residuals(toas, model)
    return resid._fn, (resid.pdict,), resid.batch


#: contract name -> fixture driver (a registered contract with no
#: driver is itself a finding — audits cannot silently rot)
_DRIVERS: Dict[str, Callable] = {
    "residuals": _drv_residuals,
}


def _audit_leg(name: str, chain: ChainSpec, leg: str,
               ntoas: int) -> List[Finding]:
    fn, args, batch = _DRIVERS[name](ntoas)
    crit = {}
    for f in chain.batch_fields:
        arr = getattr(batch, f, None)
        if arr is not None:
            crit[f"batch.{f}"] = arr
    findings = analyze_fn(fn, *args, pattern=chain.param_pattern,
                          critical_consts=crit, name=f"{name}[{leg}]")
    return findings


def audit_precision(names: Optional[Sequence[str]] = None,
                    ntoas: int = 12) -> List[Finding]:
    """Audit every ``@precision_contract`` entrypoint (or the named
    subset), each traced twice: native x64, and rebuilt under
    ``jax.experimental.disable_x64()`` + ``precision.policy("dd32")``.

    Raises ``KeyError`` for an unknown name (the CLI maps it to exit
    2, matching ``--contracts``).  ``PINT_TPU_SKIP_PRECFLOW=1`` skips
    the audit entirely (returns no findings).
    """
    if os.environ.get("PINT_TPU_SKIP_PRECFLOW") == "1":
        return []
    import jax

    from pint_tpu import precision
    from pint_tpu.lint.contracts import PRECISION_REGISTRY, \
        _ensure_registered

    _ensure_registered()
    selected = sorted(PRECISION_REGISTRY)
    if names:
        unknown = sorted(set(names) - set(selected))
        if unknown:
            raise KeyError(
                f"unknown precision contract(s): {', '.join(unknown)} "
                f"(declared: {', '.join(selected) or '<none>'})")
        selected = sorted(names)
    findings: List[Finding] = []
    for name in selected:
        pc = PRECISION_REGISTRY[name]
        if name not in _DRIVERS:
            findings.append(Finding(
                "PREC002", pc.path, pc.line, 0,
                f"precision contract '{name}' has no audit driver in "
                "pint_tpu.lint.precflow._DRIVERS — the declared chain "
                "is not being proven", origin="precflow"))
            continue
        if pc.chain not in CHAINS:
            findings.append(Finding(
                "PREC002", pc.path, pc.line, 0,
                f"precision contract '{name}' names unknown chain "
                f"'{pc.chain}' (known: {', '.join(sorted(CHAINS))})",
                origin="precflow"))
            continue
        chain = CHAINS[pc.chain]
        # leg 1: native x64, default policy — f64 collapses are real f64
        findings += _audit_leg(name, chain, "x64", ntoas)
        # leg 2: the TPU-realistic regime — no wide dtype exists, the
        # dd32 policy must carry the chain in compensated pairs
        with jax.experimental.disable_x64():
            with precision.policy("dd32"):
                findings += _audit_leg(name, chain, "x64_off+dd32", ntoas)
    return findings
