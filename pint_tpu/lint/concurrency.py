"""lint v5 — the concurrency & signal-safety auditor (AST layer).

The serve plane is genuinely concurrent: ``serve.py``, ``gateway.py``,
``telemetry.py``, ``metrics.py`` and ``runtime.py`` share mutable state
across a ThreadingHTTPServer, a dispatcher daemon, hook callbacks and
signal handlers — and PR 19's races (the idempotency double-admit, the
unlocked ``requests_total`` bump) were found by hand, after the fact.
This module is the gate that catches the next one mechanically.

Rules (all pure-AST, jax-free — safe to run anywhere):

* **LOCK001** — lock-guard inference.  For each class that owns a lock
  (``self._lock = threading.Lock()`` / ``RLock`` / ``Condition``), every
  ``self._x`` attribute is mapped to its guarding lock by observing
  which ``with self._lock:`` block dominates its write sites (strict
  majority, ``__init__`` exempt — construction happens before
  publication).  A write / read-modify-write / container mutation of a
  guarded field on a *thread-reachable* path without that lock held is
  a finding.  Thread roots: ``Thread(target=...)`` / ``Timer`` bodies,
  ``do_*`` methods of HTTPRequestHandler subclasses, and callbacks
  registered via ``add_count_hook`` / ``add_span_end_hook`` /
  ``profiling._count_hook``.
* **LOCK002** — static lock-acquisition-order graph.  Nested ``with``
  blocks contribute direct edges; edges propagate through the
  module-local call graph (calling ``f()`` while holding A, where ``f``
  transitively acquires B, adds A -> B).  Any cycle is a potential
  deadlock, reported once per cycle naming both edges with their
  acquisition sites.
* **SIG001** — signal-handler safety.  Code reachable from a registered
  signal handler (``signal.signal(sig, h)`` sites — the ``SignalFlush``
  pattern) may not acquire a non-reentrant lock that the main path also
  takes (the signal can land *while the main thread holds it* — classic
  self-deadlock), nor make an unbounded blocking call (``.join()`` /
  ``.wait()`` / ``.acquire()`` with no timeout).
* **HOOK001** — hook re-entry / registry-lock discipline.  Codifies the
  PR 11 invariant "hooks are called OUTSIDE the lock": a callback
  reachable from ``profiling.count`` / ``telemetry.span`` exit must not
  re-enter ``profiling.count`` (infinite hook recursion), and the
  emitting side must not invoke a registered hook while holding a
  lock (``for hook in _count_hooks: hook(...)`` inside ``with _lock:``).

What the AST cannot see — the *observed* acquisition order of real
threads under a live serving run — is covered by the dynamic layer in
:mod:`pint_tpu.lint.lockhooks` (CONTRACT005).

Suppression and baseline ride the shared machinery: ``# ddlint:
disable=LOCK001 <why>`` sanctions a site, and findings participate in
the checked-in baseline exactly like the other AST rules.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from pint_tpu.lint.findings import Finding, scan_suppressions

__all__ = ["RULES_CONCURRENCY", "lint_concurrency_source",
           "lint_concurrency_file", "lint_concurrency_paths",
           "audit_concurrency"]

#: rule code -> one-line description (merged into ``--list-rules``)
RULES_CONCURRENCY = {
    "LOCK001": "guarded-field write without its inferred lock on a "
               "thread-reachable path (guard = the lock whose with-block "
               "dominates the attribute's write sites)",
    "LOCK002": "lock-acquisition-order cycle in the static nested-with "
               "graph propagated through the module-local call graph "
               "(potential deadlock; both edges named)",
    "SIG001": "signal-handler-reachable code acquires a non-reentrant "
              "lock also taken on the main path, or makes an unbounded "
              "blocking call (join/wait/acquire with no timeout)",
    "HOOK001": "count/span hook re-enters profiling.count, or a "
               "registered hook is invoked while a registry lock is "
               "held (the 'hooks called OUTSIDE the lock' invariant)",
}

#: methods whose writes happen before the object is published to other
#: threads — exempt from guard inference AND from firing
_CONSTRUCTION = {"__init__", "__new__", "__init_subclass__"}

#: container-mutation method names counted as write sites
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "move_to_end", "sort", "reverse",
}

#: lock factory names (trailing attribute) -> reentrant?
_LOCK_FACTORIES = {"Lock": False, "RLock": True, "Condition": False,
                   "Semaphore": False, "BoundedSemaphore": False}

#: unbounded blocking primitives when called with no timeout (SIG001)
_BLOCKING = {"join", "wait", "acquire"}


def _dotted(node) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _trailing(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _Func:
    """One function/method/closure and its concurrency-relevant facts."""

    __slots__ = ("name", "qualname", "node", "cls", "parent", "calls",
                 "thread_reachable", "thread_via",
                 "sig_reachable", "sig_via",
                 "hook_reachable", "hook_via",
                 "acquires", "trans_acquires")

    def __init__(self, name, qualname, node, cls, parent):
        self.name = name
        self.qualname = qualname
        self.node = node
        self.cls = cls                      # _Cls or None
        self.parent = parent                # enclosing _Func or None
        self.calls: Set[Tuple[str, str]] = set()   # ("name", x) | ("self", x)
        self.thread_reachable = False
        self.thread_via: Optional[str] = None
        self.sig_reachable = False
        self.sig_via: Optional[str] = None
        self.hook_reachable = False
        self.hook_via: Optional[str] = None
        self.acquires: Set[Tuple[str, ...]] = set()       # direct lock ids
        self.trans_acquires: Set[Tuple[str, ...]] = set()


class _Cls:
    __slots__ = ("name", "node", "bases", "methods", "locks")

    def __init__(self, name, node, bases):
        self.name = name
        self.node = node
        self.bases = bases                  # dotted base-name strings
        self.methods: Dict[str, _Func] = {}
        self.locks: Dict[str, str] = {}     # attr -> factory kind


class _Index(ast.NodeVisitor):
    """Pass 1: functions, classes, lock attributes, root registrations."""

    def __init__(self, modname: str):
        self.modname = modname
        self.functions: List[_Func] = []
        self.classes: Dict[str, _Cls] = {}
        self.module_funcs: Dict[str, _Func] = {}
        self.module_locks: Dict[str, str] = {}   # name -> factory kind
        #: (kind, ref-node, cls-at-site, func-at-site, via) to resolve
        #: in pass 2; kind in {"thread", "hook", "sig"}
        self.root_refs: List[tuple] = []
        self._cls_stack: List[_Cls] = []
        self._fn_stack: List[_Func] = []

    # -- structure -------------------------------------------------------------

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = [_dotted(b) or "" for b in node.bases]
        rec = _Cls(node.name, node, bases)
        self.classes[node.name] = rec
        self._cls_stack.append(rec)
        self.generic_visit(node)
        self._cls_stack.pop()
        if any("RequestHandler" in b for b in rec.bases):
            # every do_* verb of an HTTP(S) request handler runs on a
            # server worker thread
            for mname, m in rec.methods.items():
                if mname.startswith("do_") or mname == "handle":
                    self.root_refs.append(
                        ("thread", m, None, None,
                         f"{rec.name}.{mname} HTTP handler"))

    def _enter_function(self, node) -> None:
        cls = self._cls_stack[-1] if self._cls_stack and \
            not self._fn_stack else None
        parent = self._fn_stack[-1] if self._fn_stack else None
        if parent is not None:
            qual = f"{parent.qualname}.{node.name}"
        elif cls is not None:
            qual = f"{cls.name}.{node.name}"
        else:
            qual = node.name
        rec = _Func(node.name, qual, node, cls, parent)
        self.functions.append(rec)
        if cls is not None:
            cls.methods[node.name] = rec
        elif parent is None:
            self.module_funcs[node.name] = rec
        self._fn_stack.append(rec)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _enter_function
    visit_AsyncFunctionDef = _enter_function

    # -- lock attributes & hook-singleton assignment ---------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        kind = self._lock_factory(node.value)
        for tgt in node.targets:
            if kind is not None:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self" and self._fn_stack and \
                        self._fn_stack[-1].cls is not None:
                    self._fn_stack[-1].cls.locks[tgt.attr] = kind
                elif isinstance(tgt, ast.Name) and not self._fn_stack:
                    self.module_locks[tgt.id] = kind
            # ``profiling._count_hook = fn`` — the singleton count hook
            if _trailing(tgt) == "_count_hook":
                self.root_refs.append(
                    ("hook", node.value,
                     self._cls_stack[-1] if self._cls_stack else None,
                     self._fn_stack[-1] if self._fn_stack else None,
                     "_count_hook singleton"))
        self.generic_visit(node)

    def _lock_factory(self, value) -> Optional[str]:
        if isinstance(value, ast.Call):
            name = _trailing(value.func)
            if name in _LOCK_FACTORIES:
                return name
        return None

    # -- root registrations ----------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = _trailing(node.func)
        cls = self._cls_stack[-1] if self._cls_stack else None
        fn = self._fn_stack[-1] if self._fn_stack else None
        if name in ("Thread", "Timer"):
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    self.root_refs.append(
                        ("thread", kw.value, cls, fn,
                         f"threading.{name}(target=...)"))
            if name == "Timer" and len(node.args) >= 2:
                self.root_refs.append(
                    ("thread", node.args[1], cls, fn,
                     "threading.Timer body"))
        elif name in ("add_count_hook", "add_span_end_hook") and node.args:
            self.root_refs.append(
                ("hook", node.args[0], cls, fn, f"{name}(...)"))
        elif name == "signal" and len(node.args) >= 2 and \
                _dotted(node.func) in ("signal.signal", "signal"):
            self.root_refs.append(
                ("sig", node.args[1], cls, fn, "signal.signal(...)"))
        self.generic_visit(node)


def _collect_calls(fn: _Func) -> None:
    """Call edges: bare names and ``self.x(...)`` — module-local only."""
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                fn.calls.add(("name", f.id))
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                fn.calls.add(("self", f.attr))


def _resolve_ref(index: _Index, ref, cls: Optional[_Cls],
                 fn: Optional[_Func]) -> Optional[_Func]:
    """A function-valued expression (``self._loop``, a bare name, a
    ``Cls.method`` attribute) -> its _Func record, or None."""
    if isinstance(ref, _Func):
        return ref
    if isinstance(ref, ast.Attribute) and \
            isinstance(ref.value, ast.Name):
        if ref.value.id == "self" and cls is not None:
            return cls.methods.get(ref.attr)
        owner = index.classes.get(ref.value.id)
        if owner is not None:
            return owner.methods.get(ref.attr)
        return index.module_funcs.get(ref.attr)
    if isinstance(ref, ast.Name):
        cur = fn
        while cur is not None:    # closures shadow module scope
            for cand in index.functions:
                if cand.parent is cur and cand.name == ref.id:
                    return cand
            cur = cur.parent
        return index.module_funcs.get(ref.id)
    return None


def _resolve_call(index: _Index, fn: _Func,
                  edge: Tuple[str, str]) -> Optional[_Func]:
    kind, name = edge
    if kind == "self":
        return fn.cls.methods.get(name) if fn.cls is not None else None
    cur = fn.parent
    while cur is not None:
        for cand in index.functions:
            if cand.parent is cur and cand.name == name:
                return cand
        cur = cur.parent
    return index.module_funcs.get(name)


def _propagate(index: _Index) -> None:
    """Fixed point: thread/sig/hook reachability through call edges and
    into closures (a nested def runs on its parent's thread)."""
    for kind, ref, cls, fn, via in index.root_refs:
        target = _resolve_ref(index, ref, cls, fn)
        if target is None:
            continue
        if kind == "thread" and not target.thread_reachable:
            target.thread_reachable, target.thread_via = True, via
        elif kind == "hook" and not target.hook_reachable:
            target.hook_reachable, target.hook_via = True, via
            # hooks fire on whichever thread hits the count/span site
            if not target.thread_reachable:
                target.thread_reachable, target.thread_via = True, via
        elif kind == "sig" and not target.sig_reachable:
            target.sig_reachable, target.sig_via = True, via
    changed = True
    while changed:
        changed = False
        for fn in index.functions:
            flow = [fn.parent] if fn.parent is not None else []
            for edge in fn.calls:
                callee = _resolve_call(index, fn, edge)
                if callee is not None:
                    flow.append(None)   # marker: fn -> callee direction
                    for flag, via in (("thread_reachable", "thread_via"),
                                      ("sig_reachable", "sig_via"),
                                      ("hook_reachable", "hook_via")):
                        if getattr(fn, flag) and not getattr(callee, flag):
                            setattr(callee, flag, True)
                            setattr(callee, via,
                                    getattr(fn, via) or fn.qualname)
                            changed = True
            for src in flow:
                if src is None:
                    continue
                for flag, via in (("thread_reachable", "thread_via"),
                                  ("sig_reachable", "sig_via"),
                                  ("hook_reachable", "hook_via")):
                    if getattr(src, flag) and not getattr(fn, flag):
                        setattr(fn, flag, True)
                        setattr(fn, via, getattr(src, via) or src.qualname)
                        changed = True


# --- per-function lock-aware event walk --------------------------------------

class _Events:
    """Lock-aware walk of one function body: write sites, call sites and
    acquisitions, each annotated with the lexically-held lock set."""

    def __init__(self, index: _Index, fn: _Func,
                 entry_held: tuple = ()):
        self.index = index
        self.fn = fn
        self.writes: List[tuple] = []     # (attr, kind, node, held)
        self.calls: List[tuple] = []      # (call-node, edge|None, held)
        self.acquires: List[tuple] = []   # (lock-id, node, held-before)
        self.hook_vars: Set[str] = set()  # for-targets iterating *_hooks
        self.guard_reads: List[tuple] = []   # (attr, if-stmt, held)
        body = fn.node.body
        self._walk(body, entry_held)

    # lock identity: ("C", ClassName, attr) | ("M", name)
    def _lock_of(self, expr) -> Optional[tuple]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.fn.cls is not None and \
                expr.attr in self.fn.cls.locks:
            return ("C", self.fn.cls.name, expr.attr)
        if isinstance(expr, ast.Name) and \
                expr.id in self.index.module_locks:
            return ("M", expr.id)
        return None

    def lock_kind(self, lock_id: tuple) -> str:
        if lock_id[0] == "C":
            return self.index.classes[lock_id[1]].locks[lock_id[2]]
        return self.index.module_locks[lock_id[1]]

    def _walk(self, stmts, held: tuple) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    lock = self._lock_of(item.context_expr)
                    if lock is not None:
                        self.acquires.append((lock, item.context_expr,
                                              inner))
                        if lock not in inner:
                            inner = inner + (lock,)
                    else:
                        self._scan_expr(item.context_expr, held)
                self._walk(stmt.body, inner)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue    # separate scope; analysed on its own
            elif isinstance(stmt, (ast.If, ast.While)):
                # reads of self._x in a branch test, for the unlocked
                # check-then-act half of LOCK001
                for sub in ast.walk(stmt.test):
                    attr = self._self_attr(sub)
                    if attr is not None and self.fn.cls is not None \
                            and attr not in self.fn.cls.locks:
                        self.guard_reads.append((attr, stmt, held))
                self._scan_expr(stmt.test, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            elif isinstance(stmt, ast.For):
                # remember hook-list iteration targets for HOOK001
                it = _trailing(stmt.iter)
                if it is None and isinstance(stmt.iter, ast.Call):
                    for a in stmt.iter.args:   # tuple(_hooks) wrapper
                        it = it or _trailing(a)
                if it is not None and it.endswith("_hooks") and \
                        isinstance(stmt.target, ast.Name):
                    self.hook_vars.add(stmt.target.id)
                self._scan_expr(stmt.iter, held)
                self._walk(stmt.body, held)
                self._walk(stmt.orelse, held)
            else:
                for expr in ast.iter_child_nodes(stmt):
                    if isinstance(expr, ast.expr):
                        self._scan_expr(expr, held)
                for block in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, block, None)
                    if sub:
                        if block == "handlers":
                            for h in sub:
                                self._walk(h.body, held)
                        else:
                            self._walk(sub, held)
                self._scan_stmt_writes(stmt, held)

    def _scan_stmt_writes(self, stmt, held: tuple) -> None:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self._write_target(tgt, "write", held)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._write_target(stmt.target, "write", held)
        elif isinstance(stmt, ast.AugAssign):
            self._write_target(stmt.target, "read-modify-write", held)
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self._write_target(tgt, "delete", held)

    def _self_attr(self, node) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr.startswith("_"):
            return node.attr
        return None

    def _write_target(self, tgt, kind: str, held: tuple) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._write_target(el, kind, held)
            return
        base = tgt
        if isinstance(tgt, ast.Subscript):
            base = tgt.value
            kind = "item-" + kind
        attr = self._self_attr(base)
        if attr is not None and self.fn.cls is not None and \
                attr not in self.fn.cls.locks:
            self.writes.append((attr, kind, tgt, held))

    def _scan_expr(self, expr, held: tuple) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda,)):
                continue
            if isinstance(node, ast.Call):
                f = node.func
                edge = None
                if isinstance(f, ast.Name):
                    edge = ("name", f.id)
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self":
                    edge = ("self", f.attr)
                self.calls.append((node, edge, held))
                # ``.append()`` & friends on self._x are write sites
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    attr = self._self_attr(f.value)
                    if attr is not None and self.fn.cls is not None and \
                            attr not in self.fn.cls.locks:
                        self.writes.append(
                            (attr, f"mutation (.{f.attr}())", node, held))
                # ``lock.acquire()`` outside a with-block still orders
                lock = self._lock_of(f.value) \
                    if isinstance(f, ast.Attribute) and \
                    f.attr == "acquire" else None
                if lock is not None:
                    self.acquires.append((lock, node, held))


def _lock_label(lock_id: tuple, modname: str) -> str:
    if lock_id[0] == "C":
        return f"{lock_id[1]}.self.{lock_id[2]}"
    return f"{modname}.{lock_id[1]}"


def _held_class_locks(held: tuple, cls: _Cls) -> Set[str]:
    return {lid[2] for lid in held
            if lid[0] == "C" and lid[1] == cls.name}


def _build_events(index: _Index) -> Dict[_Func, "_Events"]:
    """Lock-aware events with call-site held-set propagation.

    The codebase's ``*_locked`` convention — private helpers that are
    only ever called with the lock already held — would otherwise drown
    the rules in false positives.  Rather than trusting the *name*, the
    walk computes each private function's entry held-set as the
    INTERSECTION of the locks held at all of its resolved call sites
    (public functions and thread roots are entered bare: an external
    caller holds nothing).  Entry sets only grow, so the fixed point
    terminates."""
    entry: Dict[_Func, tuple] = {fn: () for fn in index.functions}
    events: Dict[_Func, _Events] = {}
    for _ in range(20):
        events = {fn: _Events(index, fn, entry[fn])
                  for fn in index.functions}
        sites: Dict[_Func, List[frozenset]] = {}
        for fn, ev in events.items():
            for _, edge, held in ev.calls:
                if edge is None:
                    continue
                callee = _resolve_call(index, fn, edge)
                if callee is not None:
                    sites.setdefault(callee, []).append(frozenset(held))
        changed = False
        for fn in index.functions:
            if not fn.name.startswith("_") or \
                    fn.name.startswith("__") or fn not in sites:
                continue    # public / dunder / never called locally
            common = frozenset.intersection(*sites[fn])
            new = tuple(sorted(common, key=repr))
            if new != entry[fn]:
                entry[fn] = new
                changed = True
        if not changed:
            break
    return events


# --- rules -------------------------------------------------------------------

def _rule_lock001(index: _Index, events: Dict[_Func, _Events],
                  report) -> None:
    """Guard inference + unguarded-write detection, per class."""
    for cls in index.classes.values():
        if not cls.locks:
            continue
        # attr -> [(func, node, held-class-locks, kind)]
        sites: Dict[str, List[tuple]] = {}
        # attr -> [(func, if-stmt, held-class-locks)] branch-test reads
        reads: Dict[str, List[tuple]] = {}
        for mname, fn in cls.methods.items():
            if mname in _CONSTRUCTION or fn not in events:
                continue
            for attr, kind, node, held in events[fn].writes:
                sites.setdefault(attr, []).append(
                    (fn, node, _held_class_locks(held, cls), kind))
            for attr, stmt, held in events[fn].guard_reads:
                reads.setdefault(attr, []).append(
                    (fn, stmt, _held_class_locks(held, cls)))
            # closures inside methods write through the method's self
            for sub in index.functions:
                cur = sub.parent
                while cur is not None and cur is not fn:
                    cur = cur.parent
                if cur is fn and sub in events and sub.cls is None:
                    for attr, kind, node, held in events[sub].writes:
                        sites.setdefault(attr, []).append(
                            (sub, node, _held_class_locks(held, cls),
                             kind))
        guarded: Set[str] = set()
        for attr, lst in sorted(sites.items()):
            counts: Dict[str, int] = {}
            for _, _, held, _ in lst:
                for lock in held:
                    counts[lock] = counts.get(lock, 0) + 1
            if not counts:
                continue
            guard = max(sorted(counts), key=lambda k: counts[k])
            locked = counts[guard]
            if locked <= len(lst) - locked:
                continue    # no strict majority -> no inferred guard
            guarded.add(attr)
            for fn, node, held, kind in lst:
                if guard in held:
                    continue
                if not (fn.thread_reachable or fn.hook_reachable):
                    continue
                via = fn.thread_via or fn.hook_via or fn.qualname
                report("LOCK001", node,
                       f"{kind} of self.{attr} without self.{guard} — "
                       f"inferred guard (held at {locked}/{len(lst)} "
                       f"write sites) — on a thread-reachable path "
                       f"({fn.qualname}, via {via})")
        # unlocked check-then-act: a branch test reads self._x and the
        # taken branch writes it back, no lock held at either site, in
        # thread-reachable code of a lock-owning class.  The window
        # between the read and the write is a race even when no guard
        # could be inferred (the `_last_stats_write` /
        # PR 19 double-admit shape)
        for attr, rlist in sorted(reads.items()):
            if attr in guarded:
                continue    # the guard-based pass already judged it
            for fn, stmt, rheld in rlist:
                if rheld or not (fn.thread_reachable or
                                 fn.hook_reachable):
                    continue
                for wfn, wnode, wheld, kind in sites.get(attr, ()):
                    if wfn is not fn or wheld:
                        continue
                    if wnode.lineno < stmt.lineno:
                        continue    # the act must follow the check
                    via = fn.thread_via or fn.hook_via or fn.qualname
                    report("LOCK001", wnode,
                           f"unlocked check-then-act on self.{attr}: "
                           f"tested at line {stmt.lineno} and "
                           f"{kind.replace('item-', '')} here with no "
                           f"{'/'.join(sorted('self.' + k for k in cls.locks))} "
                           f"held — two threads can both pass the "
                           f"check ({fn.qualname}, via {via})")
                    break


def _rule_lock002(index: _Index, events: Dict[_Func, _Events],
                  modname: str, report) -> None:
    """Lock-order graph: direct nesting + propagation through calls."""
    # transitive acquire sets (fixed point)
    for fn, ev in events.items():
        fn.acquires = {lock for lock, _, _ in ev.acquires}
        fn.trans_acquires = set(fn.acquires)
    changed = True
    while changed:
        changed = False
        for fn, ev in events.items():
            for _, edge, _ in ev.calls:
                if edge is None:
                    continue
                callee = _resolve_call(index, fn, edge)
                if callee is not None and callee in events:
                    add = callee.trans_acquires - fn.trans_acquires
                    if add:
                        fn.trans_acquires |= add
                        changed = True
    # edges with provenance: (A, B) -> (node, description)
    edges: Dict[Tuple[tuple, tuple], tuple] = {}
    for fn, ev in events.items():
        for lock, node, held in ev.acquires:
            for h in held:
                if h != lock and (h, lock) not in edges:
                    edges[(h, lock)] = (node, f"nested with in "
                                              f"{fn.qualname}")
        for call, edge, held in ev.calls:
            if edge is None or not held:
                continue
            callee = _resolve_call(index, fn, edge)
            if callee is None or callee not in events:
                continue
            for m in callee.trans_acquires:
                for h in held:
                    if h != m and (h, m) not in edges:
                        edges[(h, m)] = (
                            call, f"{fn.qualname} calls "
                                  f"{callee.qualname} holding "
                                  f"{_lock_label(h, modname)}")
    # cycle detection (DFS, report each cycle once)
    adj: Dict[tuple, List[tuple]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    seen_cycles: Set[frozenset] = set()
    state: Dict[tuple, int] = {}
    stack: List[tuple] = []

    def dfs(v: tuple) -> None:
        state[v] = 1
        stack.append(v)
        for w in adj.get(v, ()):
            if state.get(w, 0) == 0:
                dfs(w)
            elif state.get(w) == 1:
                cyc = stack[stack.index(w):] + [w]
                key = frozenset(cyc)
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                parts = []
                for a, b in zip(cyc, cyc[1:]):
                    node, why = edges[(a, b)]
                    parts.append(
                        f"{_lock_label(a, modname)} -> "
                        f"{_lock_label(b, modname)} "
                        f"(line {node.lineno}: {why})")
                first_node = edges[(cyc[0], cyc[1])][0]
                report("LOCK002", first_node,
                       "lock-acquisition-order cycle (potential "
                       "deadlock): " + "; ".join(parts))
        stack.pop()
        state[v] = 2

    for v in sorted(adj):
        if state.get(v, 0) == 0:
            dfs(v)


def _rule_sig001(index: _Index, events: Dict[_Func, _Events],
                 modname: str, report) -> None:
    main_locks: Set[tuple] = set()
    for fn, ev in events.items():
        if not fn.sig_reachable:
            for lock, _, _ in ev.acquires:
                main_locks.add(lock)
    for fn, ev in events.items():
        if not fn.sig_reachable:
            continue
        for lock, node, _ in ev.acquires:
            if ev.lock_kind(lock) != "RLock" and lock in main_locks:
                report("SIG001", node,
                       f"signal-handler path ({fn.qualname}, via "
                       f"{fn.sig_via}) acquires non-reentrant "
                       f"{_lock_label(lock, modname)} also taken on "
                       f"the main path — self-deadlock if the signal "
                       f"lands while it is held")
        for call, _, _ in ev.calls:
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr in _BLOCKING \
                    and not call.args and not call.keywords:
                report("SIG001", call,
                       f"unbounded blocking .{f.attr}() with no "
                       f"timeout in signal-handler-reachable code "
                       f"({fn.qualname}, via {fn.sig_via})")


def _rule_hook001(index: _Index, events: Dict[_Func, _Events],
                  report) -> None:
    for fn, ev in events.items():
        # (a) a registered hook must not re-enter profiling.count
        if fn.hook_reachable:
            for call, _, _ in ev.calls:
                d = _dotted(call.func)
                if d in ("profiling.count", "count") and \
                        (d != "count" or
                         index.modname == "profiling"):
                    report("HOOK001", call,
                           f"hook-reachable {fn.qualname} (via "
                           f"{fn.hook_via}) re-enters profiling.count "
                           f"— infinite hook recursion hazard")
        # (b) the emitting side: never invoke a hook under a lock
        for call, _, held in ev.calls:
            if not held:
                continue
            t = _trailing(call.func)
            if t is None:
                continue
            if t in ev.hook_vars or t.endswith("_hook") and \
                    not t.startswith(("add_", "remove_")):
                locks = ", ".join(
                    _lock_label(h, index.modname) for h in held)
                report("HOOK001", call,
                       f"hook invoked while holding {locks} in "
                       f"{fn.qualname} — hooks must be called OUTSIDE "
                       f"the registry lock (PR 11 invariant)")


# --- orchestration -----------------------------------------------------------

def lint_concurrency_source(source: str, filename: str) -> List[Finding]:
    """Run the concurrency rules over one file; suppressions applied."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as exc:
        return [Finding("SYNTAX", filename, exc.lineno or 0,
                        exc.offset or 0, f"syntax error: {exc.msg}",
                        origin="concurrency")]
    sup = scan_suppressions(source)
    src_lines = source.splitlines()
    findings: List[Finding] = []
    modname = os.path.splitext(os.path.basename(filename))[0]

    def report(code: str, node, message: str):
        line = getattr(node, "lineno", 0)
        end = getattr(node, "end_lineno", None)
        if sup.is_suppressed(code, line, end):
            return
        text = src_lines[line - 1] if 0 < line <= len(src_lines) else ""
        findings.append(Finding(code, filename, line,
                                getattr(node, "col_offset", 0) + 1,
                                message, source=text,
                                origin="concurrency"))

    index = _Index(modname)
    index.visit(tree)
    if not index.module_locks and \
            not any(c.locks for c in index.classes.values()) and \
            not index.root_refs:
        return []    # no threading surface at all — skip the walks
    for fn in index.functions:
        _collect_calls(fn)
    _propagate(index)
    events = _build_events(index)

    _rule_lock001(index, events, report)
    _rule_lock002(index, events, modname, report)
    _rule_sig001(index, events, modname, report)
    _rule_hook001(index, events, report)

    findings.sort(key=lambda f: (f.line, f.col, f.code))
    return findings


def lint_concurrency_file(path: str) -> List[Finding]:
    with open(path, encoding="utf-8") as fh:
        return lint_concurrency_source(fh.read(), path)


def lint_concurrency_paths(paths) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        findings.extend(lint_concurrency_file(
                            os.path.join(dirpath, fn)))
        elif path.endswith(".py"):
            findings.extend(lint_concurrency_file(path))
    return findings


def audit_concurrency(modules=None) -> List[Finding]:
    """The bench/CLI entry: concurrency rules over the installed
    package (or the named ``pint_tpu`` modules, e.g. ``["serve",
    "gateway"]``).  Raises KeyError on an unknown module name."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if modules:
        paths = []
        for m in modules:
            p = os.path.join(pkg, *m.strip().split(".")) + ".py"
            if not os.path.isfile(p):
                raise KeyError(f"unknown module {m!r} (no {p})")
            paths.append(p)
    else:
        paths = [pkg]
    return lint_concurrency_paths(paths)
