"""pint_tpu.lint — precision & trace-safety static analyzer.

The paper's ~10 ns Tempo2-agreement claim rests on invariants the code
cannot express in types: error-free transforms survive only if their
word pairs are never recombined with raw ``+`` and never demoted below
the working dtype, and jit-compiled hot paths must never host-sync
(``pint_tpu/dd.py`` documents the measured hardware reality behind
both).  This package makes those conventions *checked properties*:

* AST rules (:mod:`pint_tpu.lint.astrules`):
  **DD001** raw ``+/-`` on DD/QS words outside ``dd.py``/``qs.py``;
  **PREC001** dtype demotion in precision-critical modules;
  **TRACE001** host syncs inside jit-reachable code;
  **JIT001** retrace hazards on jit-wrapped functions;
  **SHARD001/SHARD002** sharding hygiene in mesh-reachable code
  (bare ``device_put`` without a sharding; batch-sharded
  ``shard_map``/``pjit`` without declared output specs).
* Dispatch-contract audit (:mod:`pint_tpu.lint.contracts` +
  :mod:`pint_tpu.lint.hlo_audit`): **CONTRACT001-003** compile/
  dispatch/transfer budgets and warm-start behaviour; **CONTRACT004**
  SPMD collective-communication budgets — each mesh entrypoint is
  lowered under the emulated 8-device mesh, its compiled HLO parsed
  for collectives, and op counts / moved bytes / device peak / output
  shardings judged against the contract's declared budgets.
* Runtime jaxpr audit (:mod:`pint_tpu.lint.jaxpr_audit`): **JAXPR001**
  — traces the residual/fitter entry points and rejects narrowing
  ``convert_element_type`` equations that are not exact error-free
  splits.
* Precision-flow audit (:mod:`pint_tpu.lint.precflow`): **PREC002/
  PREC003** — an abstract interpreter over the traced jaxpr assigns
  every intermediate a precision-lattice class and proves each
  ``@precision_contract`` entrypoint keeps its phase-critical chain
  out of bare f32, both with native x64 and rebuilt under
  ``disable_x64()`` + ``precision.policy("dd32")``
  (``--precflow`` / ``--list-precision-contracts``).
* Concurrency & signal-safety audit (:mod:`pint_tpu.lint.concurrency`,
  ``--concurrency[=modules]``): **LOCK001** writes to a lock-guarded
  attribute (guard inferred from which lock dominates its write
  sites) on thread-reachable paths without the lock, plus unlocked
  check-then-act; **LOCK002** cycles in the static lock-acquisition-
  order graph; **SIG001** signal-handler lock/blocking hazards;
  **HOOK001** hook re-entrancy and hooks-under-registry-lock.  The
  dynamic companion (:mod:`pint_tpu.lint.lockhooks`) traces real lock
  acquisitions during ``serve check`` / ``gateway check``
  (``PINT_TPU_LOCKAUDIT=1`` or the ``racy_schedule`` /
  ``lock_order_invert`` failpoints) and judges observed cycles and
  dispatch-under-lock as **CONTRACT005**.

Run it::

    python -m pint_tpu.lint                 # whole package, text output
    pint-tpu-lint --format=json pint_tpu/   # console entry point, CI form
    python -m pint_tpu.lint --list-rules

Suppression: ``# ddlint: disable=CODE <justification>`` on (or directly
above) the offending line; grandfathered findings live in the checked-in
``pint_tpu/lint/baseline.txt`` (see its header for the burn-down count).
The pytest gate is ``tests/test_lint.py`` (skippable for WIP branches
via ``PINT_TPU_SKIP_LINT=1``).
"""

from pint_tpu.lint.astrules import (  # noqa: F401
    PRECISION_MODULES,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)
from pint_tpu.lint.concurrency import (  # noqa: F401
    RULES_CONCURRENCY,
    audit_concurrency,
    lint_concurrency_file,
    lint_concurrency_paths,
    lint_concurrency_source,
)
from pint_tpu.lint.baseline import (  # noqa: F401
    apply_baseline,
    default_baseline_path,
    load_baseline,
    write_baseline,
)
from pint_tpu.lint.findings import Finding, scan_suppressions  # noqa: F401

__all__ = [
    "Finding", "RULES", "PRECISION_MODULES", "lint_source", "lint_file",
    "lint_paths", "scan_suppressions", "load_baseline", "write_baseline",
    "apply_baseline", "default_baseline_path", "RULES_CONCURRENCY",
    "audit_concurrency", "lint_concurrency_source",
    "lint_concurrency_file", "lint_concurrency_paths",
]

# NOTE: pint_tpu.lint.precflow (audit_precision, analyze_fn, the
# precision lattice) and pint_tpu.lint.contracts (precision_contract,
# PRECISION_REGISTRY) import jax at audit time and are deliberately
# not re-exported here — `import pint_tpu.lint` stays jax-free for the
# AST-only fast path.  pint_tpu.lint.lockhooks (the dynamic
# CONTRACT005 lock audit) pulls in pint_tpu.faultinject/profiling and
# is likewise left to its call sites (serve/gateway `_check`).
