"""Compiled-HLO communication audit: what XLA *actually emits* for the
mesh entrypoints (ISSUE 10).

The dispatch contracts (PR 5) count work at the runtime boundary —
compiles, dispatches, host transfers.  None of that sees INSIDE a
compiled program, and for the SPMD paths the inside is where scaling
lives or dies: an accidental replication or an implicit ``all-gather``
in a sharded solve silently turns the scaling curve flat, and the
dispatch counters stay green.  This module closes that hole by lowering
each mesh-using entrypoint to compiled HLO under the emulated
8-virtual-device CPU mesh (the same MULTICHIP trick conftest.py uses,
so the audit runs in tier-1 with no accelerator) and reading three
things off the compiled artifact:

* **collective ops** — every ``all-gather`` / ``all-reduce`` /
  ``reduce-scatter`` / ``all-to-all`` / ``collective-permute`` in the
  HLO module text, with op-count AND byte accounting per category
  (bytes from the op's result shape; tuple shapes sum their
  components);
* **per-device memory** — ``compiled.memory_analysis()`` argument /
  output / temp / generated-code sizes, combined into a peak bound
  (this jax exposes no single peak field);
* **output shardings** — the compiled program's actual output
  ``PartitionSpec`` s, normalized (size-1 mesh axes and unsharded dims
  dropped) and compared against what the entrypoint declares.  XLA is
  free to resolve an unconstrained output replicated; the comparison
  makes that resolution a contract, not an accident.

Judgment lives in :mod:`pint_tpu.lint.contracts` (CONTRACT004): each
comm-budgeted ``@dispatch_contract`` declares ``max_collectives={...}``
per category, ``max_comm_bytes`` and ``max_device_peak_bytes``; a
collective category present in the HLO but absent from the budget is
ALWAYS a failure — exactly mirroring the always-fail steady-state
retrace rule — so new communication cannot ride in unbudgeted.  The
seeded regression proving the auditor catches real failures is
``faultinject.chatty_collective`` (an extra per-chunk cross-batch
all-reduce; value-preserving, so only this audit can see it).

Drivers here mirror the dispatch-contract drivers: they build the real
entrypoint program on the shared :class:`ContractFixture` and lower it
exactly as the entrypoint would run it — the fast-path whole-grid
shard_map program for ``sharded_chunk``, the (1, n)-mesh variant the
multihost wrapper compiles for ``multihost_chunk``, and the fleet
bucket program lowered on batch-mesh ``NamedSharding`` avals for
``fleet_fit``.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

__all__ = ["COLLECTIVE_CATEGORIES", "CollectiveOp", "CommProfile",
           "HloProgram", "HLO_DRIVERS", "analyze_compiled",
           "memory_profile", "normalize_spec", "sharding_mismatches",
           "comm_report", "shape_bytes"]

#: the steady-state collective vocabulary the audit accounts for; a
#: category outside a contract's ``max_collectives`` is always-fail
COLLECTIVE_CATEGORIES = ("all-gather", "all-reduce", "reduce-scatter",
                         "all-to-all", "collective-permute")

# one collective instruction per line in HLO text:
#   %name = shape CATEGORY(operands), replica_groups=...
# async pairs lower as CATEGORY-start/-done; counting the -start leg
# only would miss sync ops, so both spellings fold into the category
# and the -done leg is skipped below (its operand is the -start tuple).
_COLL_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>" + "|".join(COLLECTIVE_CATEGORIES) + r")"
    r"(?P<suffix>-start|-done)?\(", re.M)

_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2,
                "s64": 8, "s32": 4, "s16": 2, "s8": 1,
                "u64": 8, "u32": 4, "u16": 2, "u8": 1,
                "pred": 1, "c64": 8, "c128": 16}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Bytes in an HLO shape string; tuple shapes sum components,
    unknown dtypes count zero (conservative, never crashes the audit)."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_str):
        nb = _DTYPE_BYTES.get(m.group(1))
        if nb is None:
            continue
        n = 1
        for d in m.group(2).split(","):
            if d:
                n *= int(d)
        total += n * nb
    return total


class CollectiveOp(NamedTuple):
    """One collective instruction in the compiled HLO."""

    name: str       #: the HLO op name (CONTRACT004 attribution)
    category: str   #: one of :data:`COLLECTIVE_CATEGORIES`
    nbytes: int     #: result-shape bytes moved by this op


class CommProfile(NamedTuple):
    """The communication profile of one compiled mesh program."""

    counts: Dict[str, int]             #: per-category op counts
    bytes_by_category: Dict[str, int]  #: per-category byte totals
    ops: Tuple[CollectiveOp, ...]      #: every collective, in HLO order
    comm_bytes: int                    #: total collective bytes
    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    peak_bytes: int                    #: arg+out+temp+generated-code
    #: normalized actual output specs (None when the compiled artifact
    #: exposes no inspectable sharding)
    output_specs: Optional[Tuple[Tuple[str, ...], ...]]


class HloProgram(NamedTuple):
    """What an HLO driver returns: the compiled program, the mesh it
    was lowered for, and the normalized output specs the entrypoint
    declares (None disables the sharding comparison — used where the
    replication choice is itself sanctioned by the collective budget)."""

    compiled: object
    mesh: object
    expected_out_specs: Optional[Tuple[Tuple[str, ...], ...]]


def normalize_spec(spec, mesh) -> Tuple[str, ...]:
    """Flatten a ``PartitionSpec`` to the mesh axis names that actually
    shard data.  Unsharded dims (None) carry no axis; a size-1 mesh
    axis shards nothing (sharded over it is replication — the (1, n)
    multihost mesh resolves ``P('batch')`` to ``P()``), so both sides
    of the comparison drop it."""
    sizes = dict(zip(mesh.axis_names,
                     getattr(mesh.devices, "shape", ())))
    out: List[str] = []
    for dim in tuple(spec):
        if dim is None:
            continue
        for ax in (dim if isinstance(dim, tuple) else (dim,)):
            if sizes.get(ax, 1) > 1:
                out.append(ax)
    return tuple(out)


def _output_specs(compiled, mesh):
    """Normalized actual output specs, handling both the bare
    NamedSharding a single-output program exposes and the sequence a
    multi-output program does; None when uninspectable."""
    try:
        sh = compiled.output_shardings
    except Exception:
        return None
    if not isinstance(sh, (list, tuple)):
        sh = [sh]
    specs = []
    for s in sh:
        spec = getattr(s, "spec", None)
        if spec is None:
            return None
        specs.append(normalize_spec(spec, mesh))
    return tuple(specs)


def memory_profile(compiled) -> Dict[str, int]:
    """``compiled.memory_analysis()`` flattened to plain ints — the
    argument / output / temp / generated-code sizes plus the combined
    peak bound (this jax exposes no single peak field).  All-zero when
    the artifact exposes no memory analysis (never raises): the cost
    cards in :mod:`pint_tpu.metrics` and the CONTRACT004 leg both ride
    this one extraction."""
    arg = out = temp = gen = 0
    try:
        ma = compiled.memory_analysis()
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        temp = int(ma.temp_size_in_bytes)
        gen = int(ma.generated_code_size_in_bytes)
    except Exception:
        pass
    return {"argument_bytes": arg, "output_bytes": out,
            "temp_bytes": temp, "generated_code_bytes": gen,
            "peak_bytes": arg + out + temp + gen}


def analyze_compiled(compiled, mesh=None) -> CommProfile:
    """Parse one compiled program's HLO text + memory analysis into a
    :class:`CommProfile`.  ``mesh`` enables the output-sharding read."""
    txt = compiled.as_text()
    counts: Dict[str, int] = {}
    byts: Dict[str, int] = {}
    ops: List[CollectiveOp] = []
    for m in _COLL_RE.finditer(txt):
        if m.group("suffix") == "-done":
            continue  # the async completion leg of an op already counted
        cat = m.group("op")
        nb = shape_bytes(m.group("shape"))
        counts[cat] = counts.get(cat, 0) + 1
        byts[cat] = byts.get(cat, 0) + nb
        ops.append(CollectiveOp(m.group("name"), cat, nb))
    mem = memory_profile(compiled)
    specs = _output_specs(compiled, mesh) if mesh is not None else None
    return CommProfile(counts, byts, tuple(ops), sum(byts.values()),
                       mem["argument_bytes"], mem["output_bytes"],
                       mem["temp_bytes"], mem["peak_bytes"], specs)


def sharding_mismatches(profile: CommProfile,
                        expected: Optional[Tuple[Tuple[str, ...], ...]]
                        ) -> List[Tuple[int, tuple, tuple]]:
    """(index, actual, declared) for every output whose compiled
    sharding disagrees with the declared spec (both normalized)."""
    if expected is None or profile.output_specs is None:
        return []
    out = []
    for i, (got, want) in enumerate(zip(profile.output_specs, expected)):
        if got != want:
            out.append((i, got, want))
    return out


# --- per-entrypoint HLO drivers ----------------------------------------------
# Each driver builds the REAL entrypoint program on the shared
# ContractFixture and lowers it exactly as the entrypoint dispatches it.
# Drivers adapt to the available device count (tier-1 runs on the
# 8-virtual-device CPU mesh conftest.py forces; a 1-device session
# degrades to collective-free programs, which every budget admits).

_AUDIT_GRID = (14.9, 14.95, 15.0, 15.05)


def _hlo_sharded_chunk(fix) -> HloProgram:
    """The fast-path whole-grid shard_map program on the default
    ("batch", "toa") mesh — declared out_specs (P("batch"),
    P("batch", None))."""
    import numpy as np

    from pint_tpu.parallel import make_mesh, prep_sharded_grid

    f = fix.grid_fitter()
    mesh = make_mesh()
    grid = {"DM": np.asarray(_AUDIT_GRID)}
    fit, stacked, batch, _ = prep_sharded_grid(
        f, grid, mesh, mesh.devices.shape[0], 1, "sharded")
    compiled = fit.lower(stacked, batch).compile()
    expected = tuple(normalize_spec(s, mesh)
                     for s in (("batch",), ("batch", None)))
    return HloProgram(compiled, mesh, expected)


def _hlo_multihost_chunk(fix) -> HloProgram:
    """The per-process (1, n_local) variant the multihost wrapper
    compiles: batch stays host-level, TOAs shard over every local
    device.  The size-1 batch axis normalizes away on both sides."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from pint_tpu.parallel import prep_sharded_grid

    f = fix.grid_fitter()
    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(1, len(devs)), ("batch", "toa"))
    grid = {"DM": np.asarray(_AUDIT_GRID)}
    fit, stacked, batch, _ = prep_sharded_grid(
        f, grid, mesh, 1, 1, "multihost")
    compiled = fit.lower(stacked, batch).compile()
    expected = tuple(normalize_spec(s, mesh)
                     for s in (("batch",), ("batch", None)))
    return HloProgram(compiled, mesh, expected)


def _hlo_fleet_fit(fix) -> HloProgram:
    """The fleet bucket program lowered on batch-mesh NamedSharding
    avals (what FleetFitter dispatches when built with a sharding).
    XLA replicates the unconstrained vmap output via the two budgeted
    all-gathers — that replication choice is sanctioned by the
    collective budget, so the spec comparison is disabled here."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pint_tpu.fitter import _default_wls_kernel
    from pint_tpu.fleet import _build_bucket_fit
    from pint_tpu.parallel import make_batch_mesh

    ff = fix.fleet_fitter()
    plan = ff._ensure_plan()
    b = plan["buckets"][0]
    rep = plan["rep"][b.skey_idx]
    kern = ff.kernel if ff.kernel is not None else _default_wls_kernel()
    prog = _build_bucket_fit(
        rep.model, rep.resid.track_mode, plan["delta_keys"][b.skey_idx],
        b.n_param, "PhaseOffset" not in rep.model.components,
        ff.maxiter, ff.tol_chi2, kern, ff.threshold,
        ff.diverge_streak, ff.stall_iters)
    args = ff._chunk_args(0)
    mesh = make_batch_mesh(2 if len(jax.devices()) >= 2 else 1)
    sh = NamedSharding(mesh, P(mesh.axis_names[0]))
    avals = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype,
                                       sharding=sh), args)
    compiled = prog.lower(*avals).compile()
    return HloProgram(compiled, mesh, None)


def _hlo_pta_simulate(fix) -> HloProgram:
    """The pta noise-synthesis program lowered on batch-mesh
    NamedSharding avals: per-pulsar chunk rows shard over the batch
    axis, the shared frequency grids and common-process spectrum stay
    replicated.  Like the fleet bucket program, the unconstrained vmap
    output replicates via budgeted all-gathers."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pint_tpu.parallel import make_batch_mesh

    run = fix.pta_run()
    args = run._chunk_args(0, 0)
    sc = run.scenario
    w_rows = np.zeros((sc.chunk_size, 2 * sc.n_gwb_modes))
    gwb_ag = np.zeros(2)
    mesh = make_batch_mesh(2 if len(jax.devices()) >= 2 else 1)
    sh_b = NamedSharding(mesh, P(mesh.axis_names[0]))
    sh_r = NamedSharding(mesh, P())

    def aval(x, sh):
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

    avals = ([aval(a, sh_b) for a in args]
             + [aval(w_rows, sh_b), aval(gwb_ag, sh_r),
                aval(run.f_red, sh_r), aval(run.f_gwb, sh_r)])
    compiled = run._prog.fn.lower(*avals).compile()
    return HloProgram(compiled, mesh, None)


#: contract name -> HLO driver; consulted by the CONTRACT004 leg in
#: :mod:`pint_tpu.lint.contracts` (a comm budget without a driver here
#: is itself a finding, mirroring the dispatch-driver rule)
HLO_DRIVERS: Dict[str, Callable] = {
    "sharded_chunk": _hlo_sharded_chunk,
    "multihost_chunk": _hlo_multihost_chunk,
    "fleet_fit": _hlo_fleet_fit,
    "pta_simulate": _hlo_pta_simulate,
}


def comm_report(name: str, fixture):
    """(profile, mismatches) for one comm-budgeted entrypoint — the
    measurement half of CONTRACT004, exposed for tests and bench."""
    prog = HLO_DRIVERS[name](fixture)
    profile = analyze_compiled(prog.compiled, prog.mesh)
    return profile, sharding_mismatches(profile, prog.expected_out_specs)
