"""XLA-boundary instrumentation: compiles, dispatches, transfers, retraces.

The AST rules and the jaxpr audit see *programs*; what they cannot see
is what the runtime actually DOES — how many times XLA compiled, how
many executables were dispatched, how many device<->host crossings
happened, and whether a jit cache key quietly changed between two calls
that "should" have been identical.  Those are exactly the quantities
the package's performance claims are made of (fused fit = 1 dispatch +
1 fetch; checkpointed scans compile ONE chunk shape), so this module
gives the linter eyes at that boundary:

* **compiles** — :func:`jax._src.compiler.backend_compile` wrapped (the
  single funnel every XLA compilation goes through, cached or not).
* **dispatches** — ``pxla.ExecuteReplicated.__call__`` wrapped.  The
  C++ pjit fastpath normally bypasses Python dispatch entirely, so for
  the duration of the instrumentation ``pjit._get_fastpath_data`` is
  forced to ``None`` and the two C++ ``PjitFunctionCache``\\ s are
  cleared on entry: already-compiled programs then route through the
  Python dispatch path (their tracing/executable caches stay warm — no
  recompilation is induced; each call just pays Python-call overhead,
  which is why this is an audit harness and not an always-on profiler).
* **transfers** — device->host materializations via the
  ``ArrayImpl._value`` property (``float()``/``.item()``/``.tolist()``/
  ``jax.device_get``/``__array__``) with byte accounting, and
  host->device staging via ``jax.device_put``.  NOTE on the CPU
  backend ``np.asarray(arr)`` is a zero-copy buffer-protocol view and
  does not materialize — the counted transfers are therefore a
  conservative floor (on a real accelerator every one of these is a
  tunnel round trip).
* **block_until_ready** — explicit synchronization points.
* **cache_hits / aot_hits** — persistent-compilation-cache executable
  loads (the ``jax._src.compiler._cache_read`` funnel) and AOT
  program-store loads (:mod:`pint_tpu.aot` reports via
  :func:`note_aot_hit`).  Before these, a cache-served warm start was
  indistinguishable from "nothing needed compiling"; entering
  :func:`instrument` also SUSPENDS AOT store writes (like the
  persistent-cache write suspension) so marginal-mode measurements
  cannot load what their own base run traced.
* **retraces** — ``jax_explain_cache_misses`` is enabled and the
  explanation log (``jax._src.pjit``) captured; each record is parsed
  into a :class:`RetraceEvent` naming the traced function and the
  unstable cache-key component (shapes / dtypes / weak_type / pytree
  structure / function identity / tracing context).

Patching follows the same save-patch-restore discipline as
:mod:`pint_tpu.faultinject`; only one :func:`instrument` context may be
active at a time, and counter updates are lock-guarded so concurrently
dispatching threads cannot lose events.
"""

from __future__ import annotations

import contextlib
import logging
import re
import threading
from typing import Iterator, List, NamedTuple, Optional

__all__ = ["TraceCounters", "RetraceEvent", "Instrumentation",
           "instrument", "is_active"]


class RetraceEvent(NamedTuple):
    """One steady-state-relevant tracing-cache miss."""

    fn_name: str      #: the traced function ("f", "run", ...)
    component: str    #: unstable cache-key component ("weak_type", ...)
    message: str      #: the full explanation text (jax's own words)


class TraceCounters(NamedTuple):
    """A snapshot (or delta) of the instrumented quantities.

    ``cache_hits`` counts persistent-compilation-cache executable
    loads and ``aot_hits`` AOT-store program loads — before these
    existed, a cache-served program was indistinguishable from "no
    compile happened", so a warm start could not be *attributed* (did
    the store serve, or did nothing need compiling?)."""

    compiles: int = 0
    dispatches: int = 0
    transfers_d2h: int = 0
    transfers_h2d: int = 0
    host_bytes: int = 0
    block_until_ready: int = 0
    cache_hits: int = 0           #: persistent compilation cache loads
    aot_hits: int = 0             #: AOT program-store loads
    retraces: tuple = ()          # tuple[RetraceEvent, ...]

    def __sub__(self, other: "TraceCounters") -> "TraceCounters":
        """Componentwise difference (marginal-cost measurements); the
        retrace tuple keeps the events beyond ``other``'s count."""
        return TraceCounters(
            self.compiles - other.compiles,
            self.dispatches - other.dispatches,
            self.transfers_d2h - other.transfers_d2h,
            self.transfers_h2d - other.transfers_h2d,
            self.host_bytes - other.host_bytes,
            self.block_until_ready - other.block_until_ready,
            self.cache_hits - other.cache_hits,
            self.aot_hits - other.aot_hits,
            self.retraces[len(other.retraces):])

    @property
    def transfers(self) -> int:
        return self.transfers_d2h + self.transfers_h2d

    def as_dict(self) -> dict:
        return {"compiles": self.compiles, "dispatches": self.dispatches,
                "transfers": self.transfers,
                "host_bytes": self.host_bytes,
                "block_until_ready": self.block_until_ready,
                "cache_hits": self.cache_hits,
                "aot_hits": self.aot_hits,
                "retraces": len(self.retraces)}


# --- retrace-explanation parsing ---------------------------------------------

_FN_FOR_RE = re.compile(r"^\s*for (\S+?)(?: defined at| id=|$)", re.M)
_FN_NEVER_RE = re.compile(r"never seen function:\s*\n\s*(\S+?) id=")
_TYPEPAIR_RE = re.compile(
    r"seen ([a-z_]+[0-9]*)\[([0-9,]*)\][^,]*, but now given "
    r"([a-z_]+[0-9]*)\[([0-9,]*)\]")


def classify_retrace(message: str) -> RetraceEvent:
    """Parse one ``TRACING CACHE MISS`` explanation into (fn, unstable
    cache-key component).  The component vocabulary is what the contract
    findings report: ``weak_type`` / ``dtypes`` / ``shapes`` /
    ``input pytree structure`` / ``function identity`` /
    ``tracing context`` / ``args-kwargs signature`` / ``cache key``."""
    fn = "<unknown>"
    m = _FN_NEVER_RE.search(message)
    if m:
        return RetraceEvent(m.group(1),
                            "function identity (new function object per "
                            "call — jit wrapper re-created instead of "
                            "cached)", message)
    m = _FN_FOR_RE.search(message)
    if m:
        fn = m.group(1)
    if "weak_type=" in message:
        return RetraceEvent(fn, "weak_type (Python scalar vs jax.Array "
                                "spelling of the same value)", message)
    if "never seen input type signature" in message:
        pairs = _TYPEPAIR_RE.findall(message)
        if any(a != b for a, _, b, _ in pairs):
            return RetraceEvent(fn, "dtypes", message)
        if any(sa != sb for _, sa, _, sb in pairs):
            return RetraceEvent(fn, "shapes", message)
        return RetraceEvent(fn, "input types", message)
    if "never seen input pytree" in message:
        return RetraceEvent(fn, "input pytree structure", message)
    if "tracing context" in message:
        return RetraceEvent(fn, "tracing context (config/manager state)",
                            message)
    if "never seen passing" in message:
        return RetraceEvent(fn, "args/kwargs signature", message)
    return RetraceEvent(fn, "cache key (unclassified)", message)


class _RetraceHandler(logging.Handler):
    def __init__(self, inst: "Instrumentation"):
        super().__init__(level=logging.WARNING)
        self._inst = inst

    def emit(self, record):
        msg = record.getMessage()
        if "TRACING CACHE MISS" not in msg:
            return
        ev = classify_retrace(msg)
        with self._inst._lock:
            self._inst._retraces.append(ev)


# --- the instrumentation context ---------------------------------------------

_ACTIVE: Optional["Instrumentation"] = None


def is_active() -> bool:
    return _ACTIVE is not None


def note_aot_hit() -> None:
    """Called by :mod:`pint_tpu.aot` when a store load succeeds, so an
    active instrumentation can attribute a zero-compile warm start to
    the store rather than to "nothing needed compiling"."""
    inst = _ACTIVE
    if inst is not None:
        with inst._lock:
            inst._aot_hits += 1


class Instrumentation:
    """Live counters for one :func:`instrument` context.

    ``mark()`` returns an opaque snapshot; ``since(mark)`` the
    :class:`TraceCounters` delta from that snapshot to now — the
    warmup/steady phase arithmetic the contract harness is built on.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._compiles = 0
        self._dispatches = 0
        self._d2h = 0
        self._h2d = 0
        self._host_bytes = 0
        self._block = 0
        self._cache_hits = 0
        self._aot_hits = 0
        self._retraces: List[RetraceEvent] = []

    # -- reading -----------------------------------------------------------
    def counters(self) -> TraceCounters:
        with self._lock:
            return TraceCounters(self._compiles, self._dispatches,
                                 self._d2h, self._h2d, self._host_bytes,
                                 self._block, self._cache_hits,
                                 self._aot_hits, tuple(self._retraces))

    def mark(self) -> TraceCounters:
        return self.counters()

    def since(self, mark: TraceCounters) -> TraceCounters:
        return self.counters() - mark


@contextlib.contextmanager
def instrument() -> Iterator[Instrumentation]:
    """Install the XLA-boundary hooks; restores everything on exit.

    Not reentrant (one audit at a time — the patched functions are
    process-global, so nesting would double-count)."""
    global _ACTIVE

    if _ACTIVE is not None:
        raise RuntimeError("tracehooks.instrument() is already active")

    import jax
    from jax._src import array as _array
    from jax._src import compiler as _compiler
    from jax._src import pjit as _pjit
    from jax._src.interpreters import pxla as _pxla

    inst = Instrumentation()

    orig_backend_compile = _compiler.backend_compile
    orig_exec_call = _pxla.ExecuteReplicated.__call__
    orig_fastpath = _pjit._get_fastpath_data
    orig_value = _array.ArrayImpl.__dict__["_value"]
    orig_block = _array.ArrayImpl.__dict__.get("block_until_ready")
    orig_device_put = jax.device_put
    orig_cache_read = _compiler._cache_read
    orig_explain = jax.config.jax_explain_cache_misses
    orig_cache_min = jax.config.jax_persistent_cache_min_compile_time_secs

    def backend_compile(*a, **k):
        with inst._lock:
            inst._compiles += 1
        return orig_backend_compile(*a, **k)

    def cache_read(*a, **k):
        # the persistent-compilation-cache read funnel: a non-None
        # executable is a cache HIT (the load that replaces a compile —
        # distinguishable, now, from "no compile happened")
        out = orig_cache_read(*a, **k)
        if out and out[0] is not None:
            with inst._lock:
                inst._cache_hits += 1
        return out

    def exec_call(self, *args):
        with inst._lock:
            inst._dispatches += 1
        return orig_exec_call(self, *args)

    def value_getter(self):
        out = orig_value.fget(self)
        with inst._lock:
            inst._d2h += 1
            inst._host_bytes += int(getattr(out, "nbytes", 0))
        return out

    def block_until_ready(self, *a, **k):
        with inst._lock:
            inst._block += 1
        return orig_block(self, *a, **k)

    def device_put(x, *a, **k):
        with inst._lock:
            inst._h2d += 1
            inst._host_bytes += sum(
                int(getattr(leaf, "nbytes", 0))
                for leaf in jax.tree_util.tree_leaves(x))
        return orig_device_put(x, *a, **k)

    handler = _RetraceHandler(inst)
    pjit_logger = logging.getLogger("jax._src.pjit")
    # explanations must reach OUR handler but not spam the user's
    # stderr (explain_cache_misses also makes the persistent-cache
    # layer chatty at WARNING); both restored on exit
    orig_propagate = pjit_logger.propagate
    compiler_logger = logging.getLogger("jax._src.compiler")
    orig_compiler_level = compiler_logger.level
    cache_logger = logging.getLogger("jax._src.compilation_cache")
    orig_cache_level = cache_logger.level

    _compiler.backend_compile = backend_compile
    _compiler._cache_read = cache_read
    _pxla.ExecuteReplicated.__call__ = exec_call
    _pjit._get_fastpath_data = lambda *a, **k: None
    _array.ArrayImpl._value = property(value_getter)
    if callable(orig_block):
        _array.ArrayImpl.block_until_ready = block_until_ready
    jax.device_put = device_put
    pjit_logger.addHandler(handler)
    pjit_logger.propagate = False
    compiler_logger.setLevel(logging.ERROR)
    cache_logger.setLevel(logging.ERROR)
    jax.config.update("jax_explain_cache_misses", True)
    # suspend persistent-compilation-cache WRITES while measuring:
    # a borderline >min-compile-time program persisted between two
    # measured calls makes the second call LOAD what the first
    # COMPILED, skewing marginal-mode counters negative (loads are
    # still served — measurement must observe the cache, not mutate it)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      1e9)
    # ... and the same discipline for the AOT program store: a blob
    # written between a marginal-mode base and extended run would make
    # the extended run LOAD what the base run TRACED (reads stay
    # served, so warm-start measurement still sees hits)
    from pint_tpu import aot as _aot

    aot_suspension = _aot.suspend_writes()
    aot_suspension.__enter__()
    # evict the C++ fastpath entries of ALREADY-warm programs so their
    # dispatches route through the (counted) Python path; tracing and
    # executable caches are untouched — no recompilation is induced
    try:
        _pjit._cpp_pjit_cache_fun_only.clear()
        _pjit._cpp_pjit_cache_explicit_attributes.clear()
    except Exception:   # cache layout differs on some jax versions
        pass

    _ACTIVE = inst
    try:
        yield inst
    finally:
        _ACTIVE = None
        aot_suspension.__exit__(None, None, None)
        _compiler.backend_compile = orig_backend_compile
        _compiler._cache_read = orig_cache_read
        _pxla.ExecuteReplicated.__call__ = orig_exec_call
        _pjit._get_fastpath_data = orig_fastpath
        _array.ArrayImpl._value = orig_value
        if callable(orig_block):
            _array.ArrayImpl.block_until_ready = orig_block
        jax.device_put = orig_device_put
        pjit_logger.removeHandler(handler)
        pjit_logger.propagate = orig_propagate
        compiler_logger.setLevel(orig_compiler_level)
        cache_logger.setLevel(orig_cache_level)
        jax.config.update("jax_explain_cache_misses", orig_explain)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          orig_cache_min)
