"""Dynamic lock-order auditor (CONTRACT005) — the runtime half of lint v5.

The static rules in :mod:`pint_tpu.lint.concurrency` see what the AST
can prove; this module sees what actually happened.  During a real
``serve check`` / ``gateway check`` leg, :func:`instrument` patches the
``threading.Lock`` / ``threading.RLock`` *factories* (the lock types are
C-level and cannot be instance-patched) so every lock allocated inside
the window is a :class:`_TracedLock` proxy that records, per thread:

* the **acquisition-order graph**: an edge ``A -> B`` whenever a thread
  *attempts* to take ``B`` while holding ``A``.  Edges are recorded at
  the attempt, not the grant — a timed-out acquire in an inverted pair
  still contributes its half of the cycle, so the audit catches the
  deadlock shape without having to actually deadlock.
* **held-lock-across-dispatch**: a ``profiling`` count hook watches the
  dispatch counters (``serve.dispatch``, ``jit_call``, ...) and flags
  any emitted while the emitting thread holds a traced lock — a device
  dispatch under a service lock serializes the plane (the PR 11 "hooks
  and dispatch OUTSIDE the lock" invariant, observed rather than
  inferred).

:func:`LockAudit.judge` turns both into **CONTRACT005**
:class:`~pint_tpu.lint.findings.Finding` records with thread names and
allocation-site attribution (``file.py:line`` of each lock's creation),
so the sweep's inverted-order negative control exits 1 naming both
locks.

Activation follows the tracehooks save-patch-restore idiom: a singleton
context manager, originals restored in ``finally``, ``RuntimeError`` on
nesting.  :func:`maybe_instrument` is the cheap front door serve/gateway
``check`` call unconditionally: it returns a null context unless
``PINT_TPU_LOCKAUDIT=1`` or a concurrency failpoint
(``racy_schedule`` / ``lock_order_invert``) is active, so the untraced
hot path never pays for the machinery.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from typing import Iterator, List, Optional

from pint_tpu.lint.findings import Finding

__all__ = ["LockAudit", "instrument", "maybe_instrument", "judge_active"]

#: profiling count names that mark a device/daemon dispatch — emitting
#: one while holding a traced lock is a plane-serializing hazard
_DISPATCH_COUNTS = ("serve.dispatch", "jit_call", "fleet.chunk_dispatch",
                    "pta.chunk_dispatch")


def _alloc_site() -> str:
    """``file.py:line`` of the frame that called the lock factory,
    skipping lockhooks/threading internals — the lock's identity in
    every finding."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not (fn.endswith("lockhooks.py") or fn.endswith("threading.py")):
            import os

            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>:0"


class _TracedLock:
    """Proxy over a real ``threading.Lock``/``RLock`` that reports
    acquire attempts and releases to the active :class:`LockAudit`.

    Implements the full lock protocol *plus* the private
    ``_is_owned``/``_acquire_restore``/``_release_save`` trio so a
    ``threading.Condition`` built while instrumented (its internal
    ``RLock()`` call returns a proxy) keeps working.
    """

    __slots__ = ("_inner", "_site", "_audit", "__weakref__")

    def __init__(self, inner, site: str, audit: "LockAudit"):
        self._inner = inner
        self._site = site
        self._audit = audit

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._audit._attempt(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._audit._acquired(self)
        else:
            self._audit._abandoned(self)
        return got

    def release(self):
        self._inner.release()
        self._audit._released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # Condition-compatibility: delegate the private protocol, falling
    # back to CPython's own plain-Lock shims (Condition binds these at
    # construction; a bare ``_thread.lock`` has none of them), and keep
    # the audit's held stack accurate across ``Condition.wait()``
    def _is_owned(self):
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):   # the Condition probe fallback
            inner.release()
            return False
        return True

    def _acquire_restore(self, state):
        self._audit._attempt(self)
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._audit._acquired(self)

    def _release_save(self):
        inner = self._inner
        if hasattr(inner, "_release_save"):
            state = inner._release_save()
        else:
            inner.release()
            state = None
        self._audit._released(self)
        return state

    def __repr__(self):   # pragma: no cover - debugging aid
        return f"<_TracedLock {self._site} over {self._inner!r}>"


class LockAudit:
    """Observed lock-order graph + held-across-dispatch records for one
    instrumented window."""

    def __init__(self):
        self._tls = threading.local()
        self._mu = threading.Lock()   # guards the aggregate dicts below
        # (site_a, site_b) -> (thread_name, "f1:l1 -> f2:l2" stack note)
        self.edges: dict = {}
        # [(count_name, thread_name, held-site tuple)]
        self.dispatches_under_lock: list = []

    # -- per-thread bookkeeping (proxy callbacks) --------------------------

    def _held(self) -> list:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    def _attempt(self, lock: _TracedLock) -> None:
        held = self._held()
        if held:
            # racy_schedule widens the window between "decided to take
            # the lock" and "took it" — poor-man's TSan
            from pint_tpu import faultinject

            faultinject.wrap("racy_schedule", lambda: None)()
            edge = (held[-1]._site, lock._site)
            if edge[0] != edge[1]:
                t = threading.current_thread().name
                note = " -> ".join(x._site for x in held) \
                    + f" -> {lock._site}"
                with self._mu:
                    self.edges.setdefault(edge, (t, note))

    def _acquired(self, lock: _TracedLock) -> None:
        self._held().append(lock)

    def _abandoned(self, lock: _TracedLock) -> None:
        # non-blocking / timed-out acquire: the edge (attempt) stands,
        # the hold does not
        pass

    def _released(self, lock: _TracedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                break

    def _on_count(self, name: str, n: int = 1) -> None:
        if name not in _DISPATCH_COUNTS:
            return
        held = getattr(self._tls, "held", None)
        if held:
            t = threading.current_thread().name
            sites = tuple(x._site for x in held)
            with self._mu:
                self.dispatches_under_lock.append((name, t, sites))

    # -- judgement ---------------------------------------------------------

    @staticmethod
    def _site_loc(site: str):
        path, _, line = site.rpartition(":")
        try:
            return path or site, int(line)
        except ValueError:
            return site, 0

    def cycles(self) -> List[tuple]:
        """Elementary cycles in the observed site-level order graph,
        deduped by vertex set."""
        adj: dict = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        seen, out = set(), []
        for start in sorted(adj):
            stack = [(start, (start,))]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start and len(path) > 1:
                        key = frozenset(path)
                        if key not in seen:
                            seen.add(key)
                            out.append(path)
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + (nxt,)))
        return out

    def judge(self) -> List[Finding]:
        """CONTRACT005 findings: observed lock-order cycles (each edge
        attributed to the thread and acquisition chain that recorded
        it) and dispatches emitted while holding a traced lock."""
        findings = []
        for cyc in self.cycles():
            edges = [(cyc[i], cyc[(i + 1) % len(cyc)])
                     for i in range(len(cyc))]
            attribution = "; ".join(
                f"{a} -> {b} [thread {self.edges[(a, b)][0]}: "
                f"{self.edges[(a, b)][1]}]"
                for a, b in edges if (a, b) in self.edges)
            path, line = self._site_loc(cyc[0])
            findings.append(Finding(
                code="CONTRACT005", path=path, line=line, col=0,
                message=(f"observed lock-order cycle between "
                         f"{' and '.join(sorted(set(cyc)))}: "
                         f"{attribution}"),
                source=f"lock-order cycle {' -> '.join(cyc)}",
                origin="lockhooks"))
        for name, thread, sites in self.dispatches_under_lock:
            path, line = self._site_loc(sites[-1])
            findings.append(Finding(
                code="CONTRACT005", path=path, line=line, col=0,
                message=(f"dispatch counter {name!r} emitted on thread "
                         f"{thread!r} while holding traced lock(s) "
                         f"{', '.join(sites)} — device dispatch under a "
                         f"service lock serializes the plane"),
                source=f"dispatch-under-lock {name} {sites[-1]}",
                origin="lockhooks"))
        findings.sort(key=lambda f: (f.path, f.line, f.message))
        return findings


#: the active audit window, if any (tracehooks-style singleton)
_ACTIVE: Optional[LockAudit] = None


def judge_active() -> List[Finding]:
    """Findings from the currently-open window (empty when inactive) —
    for in-process probes that want to look before the window closes."""
    return _ACTIVE.judge() if _ACTIVE is not None else []


@contextlib.contextmanager
def instrument() -> Iterator[LockAudit]:
    """Patch the ``threading.Lock``/``RLock`` factories so locks
    allocated inside the window are traced; register the dispatch count
    hook; fire the ``lock_order_invert`` failpoint (which, when active,
    spawns the seeded two-lock inversion the sweep's negative control
    judges).  Originals restored on exit; nesting is an error."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError("lockhooks.instrument() already active")
    from pint_tpu import faultinject, profiling

    audit = LockAudit()
    orig_lock, orig_rlock = threading.Lock, threading.RLock

    def traced_lock():
        return _TracedLock(orig_lock(), _alloc_site(), audit)

    def traced_rlock():
        return _TracedLock(orig_rlock(), _alloc_site(), audit)

    threading.Lock = traced_lock
    threading.RLock = traced_rlock
    profiling.add_count_hook(audit._on_count)
    _ACTIVE = audit
    try:
        # seeded inversion driver: a no-op unless the lock_order_invert
        # failpoint is active, in which case the factory runs the
        # two-thread inverted-acquire scenario against freshly-traced
        # locks (timed acquires — the cycle is RECORDED, never entered)
        faultinject.wrap("lock_order_invert", lambda: None)()
        yield audit
    finally:
        _ACTIVE = None
        threading.Lock = orig_lock
        threading.RLock = orig_rlock
        profiling.remove_count_hook(audit._on_count)


def _wanted() -> bool:
    import os

    if os.environ.get("PINT_TPU_LOCKAUDIT") == "1":
        return True
    from pint_tpu import faultinject

    return (faultinject.is_active("racy_schedule")
            or faultinject.is_active("lock_order_invert"))


@contextlib.contextmanager
def maybe_instrument() -> Iterator[Optional[LockAudit]]:
    """:func:`instrument` when the audit is requested
    (``PINT_TPU_LOCKAUDIT=1`` or a concurrency failpoint is active),
    else a null context yielding ``None`` — the zero-cost default path
    for ``serve check`` / ``gateway check``."""
    if not _wanted():
        yield None
        return
    with instrument() as audit:
        yield audit
