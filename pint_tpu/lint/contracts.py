"""Compiled-program dispatch contracts: declared budgets, audited runs.

The framework's performance architecture is a set of *counting*
invariants: the fused fit is one jitted call plus one fetch
(:func:`pint_tpu.fitter.build_fused_fit`), a split-assembly step is one
device program (:func:`pint_tpu.fitter._make_assembly`), a checkpointed
scan compiles ONE chunk shape no matter how many chunks run
(:func:`pint_tpu.runtime.run_checkpointed_scan`).  Until this module,
those invariants lived in scattered ad-hoc test assertions over
self-reported counters; nothing audited the package itself, so a stray
``float()`` or an unstable jit cache key could silently reintroduce
per-step recompiles — the exact failure mode that separates a
TPU-native rebuild from eager NumPy timing (PINT, arxiv 2012.00074) and
that Vela.jl's compiled-kernel design names as the cost to guard
(arxiv 2412.15858).

**Declaring a contract.**  Every hot public entrypoint carries a
:func:`dispatch_contract` decorator naming its budgets::

    @dispatch_contract("fused_fit", max_compiles=40, max_dispatches=2,
                       max_transfers=2)
    def build_fused_fit(model, batch, ...): ...

The decorator is zero-cost at call time (it only records the contract
in :data:`REGISTRY` and returns the function unchanged).  Budgets bound
the STEADY-STATE call (dispatches / transfers / host bytes) and the
one-time warmup (compiles); steady-state compiles and retraces are
always-fail — there is no legitimate steady-state retrace.

**Auditing.**  :func:`audit_contracts` drives each registered
entrypoint on a small synthetic fixture under
:mod:`pint_tpu.lint.tracehooks` — warmup call(s), then a steady-state
call — and emits findings through the shared
:mod:`pint_tpu.lint.findings` machinery:

* **CONTRACT001** — a declared budget was exceeded (the finding names
  the axis, the measured value and the budget).
* **CONTRACT002** — the steady-state call retraced or recompiled; the
  finding carries jax's own cache-miss attribution naming the unstable
  cache-key component (shapes / dtypes / weak_type / pytree structure /
  function identity / tracing context).
* **CONTRACT003** — the cold-start axis (ISSUE 7): a
  ``warm_from_store=True`` entrypoint, rebuilt against an AOT program
  store its first build populated (:func:`check_warm`), compiled or
  missed the store — the finding carries the ProgramKey-miss
  attribution (which entry, which key digest, why it missed).
* **CONTRACT004** — the SPMD communication axis (ISSUE 10): a
  comm-budgeted entrypoint's compiled HLO (lowered by
  :mod:`pint_tpu.lint.hlo_audit` under the emulated CPU mesh) exceeded
  a per-category collective budget, moved more collective bytes than
  ``max_comm_bytes``, peaked above ``max_device_peak_bytes``, resolved
  an output sharding differently than declared — or contains a
  collective category with NO declared budget, which is always-fail
  (the SPMD mirror of the always-fail steady-state retrace rule).  The
  finding names the entrypoint, the collective category and the HLO op.

Scan-shaped entrypoints whose programs are rebuilt per call
(``mcmc_step``) are measured in *marginal* mode: a short run and a
longer run of the same call, with steady state defined as the
difference — the "one compiled chunk shape" property then reads as
``marginal compiles == 0``.

Sanctioning a breach uses the shared suppression syntax on (or next
to) the decorator line::

    @dispatch_contract("name", ...)  # ddlint: disable=CONTRACT001 <why>

Run it: ``python -m pint_tpu.lint --contracts`` (or
``--contracts=name1,name2`` for a subset); the pytest gate is
``tests/test_contracts.py`` (marker ``contracts``, opt out with
``PINT_TPU_SKIP_CONTRACTS=1``).  The seeded regressions proving the
auditor catches real failures are ``faultinject.retrace_storm``,
``faultinject.chatty_transfer`` and (for the comm axis)
``faultinject.chatty_collective``.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

from pint_tpu.lint.findings import Finding, scan_suppressions
from pint_tpu.lint.tracehooks import TraceCounters, instrument

__all__ = ["Contract", "ContractReport", "REGISTRY", "dispatch_contract",
           "PrecisionContract", "PRECISION_REGISTRY", "precision_contract",
           "check", "audit_contracts", "steady_state_counters",
           "ContractFixture", "harvest_cost_cards"]


class Contract(NamedTuple):
    """One entrypoint's declared dispatch budget."""

    name: str
    max_compiles: int        #: warmup ceiling (one-time cost)
    max_dispatches: int      #: steady-state ceiling
    max_transfers: int       #: steady-state ceiling (d2h + h2d)
    max_host_bytes: int      #: steady-state ceiling
    warmup: int              #: warmup calls before the measured call
    qualname: str            #: decorated function, for attribution
    path: str                #: decoration site (suppression lookup)
    line: int
    #: cold-start axis (ISSUE 7): the entrypoint consults the AOT
    #: program store, and a warm-store rebuild of it must show ZERO
    #: compiles (CONTRACT003 with ProgramKey-miss attribution)
    warm_from_store: bool = False
    #: SPMD communication axis (ISSUE 10): per-category collective-op
    #: budget over the compiled HLO, e.g. ``{"all-reduce": 6}``.  A
    #: category present in the HLO but absent here is ALWAYS a failure
    #: (CONTRACT004) — new communication cannot ride in unbudgeted.
    #: None means the entrypoint has no comm contract (no HLO leg runs).
    max_collectives: Optional[Dict[str, int]] = None
    #: total collective bytes over the compiled program (CONTRACT004)
    max_comm_bytes: Optional[int] = None
    #: per-device arg+output+temp+code peak bound (CONTRACT004)
    max_device_peak_bytes: Optional[int] = None


#: contract name -> Contract, populated at decoration (import) time
REGISTRY: Dict[str, Contract] = {}


def dispatch_contract(name: str, *, max_compiles: int,
                      max_dispatches: int, max_transfers: int = 8,
                      max_host_bytes: int = 1 << 22, warmup: int = 1,
                      warm_from_store: bool = False,
                      max_collectives: Optional[Dict[str, int]] = None,
                      max_comm_bytes: Optional[int] = None,
                      max_device_peak_bytes: Optional[int] = None):
    """Register a dispatch budget for a hot public entrypoint.

    Returns the function unchanged — zero call-time cost.  The audit
    drives the entrypoint through its driver in this module (a contract
    without a driver is itself reported, so budgets cannot silently rot).

    ``warm_from_store=True`` adds the cold-start axis: the entrypoint's
    programs are served by the AOT store (:mod:`pint_tpu.aot`), and
    the audit's warm leg — rebuild the entrypoint against a store its
    first build just populated — must show ZERO compiles (CONTRACT003,
    attributed to the ProgramKey misses when it fails).

    ``max_collectives`` adds the SPMD communication axis (ISSUE 10):
    the entrypoint's compiled HLO is audited per collective category by
    :mod:`pint_tpu.lint.hlo_audit` (CONTRACT004); a category in the HLO
    with no entry in the dict always fails, and ``max_comm_bytes`` /
    ``max_device_peak_bytes`` bound total collective traffic and the
    per-device memory footprint.
    """
    def deco(fn):
        import inspect

        try:
            path = inspect.getsourcefile(fn) or "<unknown>"
        except TypeError:
            path = "<unknown>"
        line = getattr(getattr(fn, "__code__", None), "co_firstlineno", 0)
        REGISTRY[name] = Contract(
            name, int(max_compiles), int(max_dispatches),
            int(max_transfers), int(max_host_bytes), int(warmup),
            getattr(fn, "__qualname__", str(fn)), path, line,
            bool(warm_from_store),
            dict(max_collectives) if max_collectives is not None
            else None,
            None if max_comm_bytes is None else int(max_comm_bytes),
            None if max_device_peak_bytes is None
            else int(max_device_peak_bytes))
        fn.__dispatch_contract__ = name
        return fn

    return deco


class PrecisionContract(NamedTuple):
    """One entrypoint's declared precision-critical chain.

    Declares that the values named by ``chain`` (a key of
    :data:`pint_tpu.lint.precflow.CHAINS`, selecting which program
    inputs are precision-critical) must never collapse to bare f32
    (PREC002) or lose a dd pair word outside a sanctioned kernel
    (PREC003), even when the program is traced under
    ``jax.experimental.disable_x64()``.  Audited by
    :func:`pint_tpu.lint.precflow.audit_precision`.
    """

    name: str
    chain: str               #: critical-input chain spec (precflow.CHAINS)
    qualname: str            #: decorated function, for attribution
    path: str                #: decoration site (suppression lookup)
    line: int


#: precision-contract name -> PrecisionContract, populated at import time
PRECISION_REGISTRY: Dict[str, PrecisionContract] = {}


def precision_contract(name: str, *, chain: str = "phase_critical"):
    """Register a precision-flow contract for an entrypoint.

    Returns the function unchanged — zero call-time cost, exactly like
    :func:`dispatch_contract` (the two stack freely).  The precision
    auditor (:mod:`pint_tpu.lint.precflow`) traces each registered
    entrypoint twice — native x64 on, and under
    ``jax.experimental.disable_x64()`` with ``policy("dd32")`` — and
    proves the declared critical chain survives both regimes.
    """
    def deco(fn):
        import inspect

        try:
            path = inspect.getsourcefile(fn) or "<unknown>"
        except TypeError:
            path = "<unknown>"
        line = getattr(getattr(fn, "__code__", None), "co_firstlineno", 0)
        PRECISION_REGISTRY[name] = PrecisionContract(
            name, str(chain), getattr(fn, "__qualname__", str(fn)),
            path, line)
        fn.__precision_contract__ = name
        return fn

    return deco


class ContractReport(NamedTuple):
    """Measured warmup/steady counters + findings for one contract."""

    name: str
    warmup: TraceCounters
    steady: TraceCounters
    findings: tuple          # tuple[Finding, ...] (before suppression)

    @property
    def ok(self) -> bool:
        return not self.findings


def _ensure_registered() -> None:
    """Import every module that declares contracts (registration is a
    decoration side effect)."""
    import pint_tpu.fitter        # noqa: F401
    import pint_tpu.fleet         # noqa: F401
    import pint_tpu.gridutils     # noqa: F401
    import pint_tpu.mcmc          # noqa: F401
    import pint_tpu.multihost     # noqa: F401
    import pint_tpu.parallel      # noqa: F401
    import pint_tpu.residuals     # noqa: F401
    import pint_tpu.pta           # noqa: F401
    import pint_tpu.runtime       # noqa: F401
    import pint_tpu.serve         # noqa: F401


# --- the synthetic fixture ----------------------------------------------------

# Isolated pulsar with an FD block so the linear/nonlinear design-matrix
# partition is non-trivial (FD1/FD2 are declared-linear columns); two
# observing frequencies make them determinable.  Small enough that the
# whole 10-entrypoint audit compiles in seconds on XLA:CPU.
_CONTRACT_PAR = """
PSR CONTRACTAUDIT
RAJ 05:00:00.0 1
DECJ 20:00:00.0 1
F0 300.0 1
F1 -1.0e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 15.0 1
FD1 1e-5 1
FD2 -2e-6 1
TZRMJD 55000.1
TZRFRQ 1400
TZRSITE gbt
EPHEM DE421
"""

_NTOAS = 12


class ContractFixture:
    """Lazily-built shared fixture: one tiny narrowband set, a wideband
    variant, and a frozen-DM grid variant.  Build it OUTSIDE the
    instrumented region (fixture construction is not part of any
    budget)."""

    def __init__(self, ntoas: int = _NTOAS):
        import warnings

        import numpy as np

        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals
        from pint_tpu.toa import get_TOAs_array

        self.np = np
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            self.model = get_model(_CONTRACT_PAR.strip().splitlines())
            t = 55000.0 + np.linspace(0.0, 30.0, ntoas)
            freqs = np.tile([1400.0, 800.0], (ntoas + 1) // 2)[:ntoas]
            self.toas = get_TOAs_array(
                t, obs="gbt", errors_us=1.0, freqs_mhz=freqs,
                ephem="DE421")
            self.resid = Residuals(self.toas, self.model)
        self.batch = self.resid.batch
        self.pdict = self.resid.pdict
        self.names = list(self.model.free_params)
        self._cache: dict = {}
        import tempfile

        self._tmp = tempfile.TemporaryDirectory(prefix="pint_tpu_contract_")

    def tmpfile(self, name: str) -> str:
        return os.path.join(self._tmp.name, name)

    def wideband(self):
        """(model, toas, fitter) for the wideband contract."""
        if "wideband" not in self._cache:
            import copy
            import warnings

            from pint_tpu.fitter import WidebandTOAFitter
            from pint_tpu.simulation import add_wideband_dm_data

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                model = copy.deepcopy(self.model)
                toas = add_wideband_dm_data(
                    copy.deepcopy(self.toas), model, dm_error=2e-4)
                f = WidebandTOAFitter(toas, model)
            self._cache["wideband"] = (model, toas, f)
        return self._cache["wideband"]

    def fleet_fitter(self):
        """A tiny 4-pulsar / 2-bucket FleetFitter for the fleet_fit
        contract: ragged TOA counts (8, 8, 16, 16) -> two padded shapes,
        chunk width 2 -> 2 chunks, so steady state must be 2 dispatches
        + 2 fetches.  TOAs are simulated FROM each model and the
        ill-conditioned directions are frozen (RAJ/DECJ on a 30-day
        span, DM vs the FD block) so every in-bucket fit ends
        CONVERGED/MAXITER — a sentinel failure would requeue onto the
        eager path mid-audit and blow the budget for the wrong
        reason."""
        if "fleet" not in self._cache:
            import copy
            import warnings

            import numpy as np

            from pint_tpu.fleet import FleetFitter
            from pint_tpu.simulation import make_fake_toas_uniform

            pulsars = []
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                for i, n in enumerate((8, 8, 16, 16)):
                    model = copy.deepcopy(self.model)
                    model.RAJ.frozen = True
                    model.DECJ.frozen = True
                    model.DM.frozen = True
                    toas = make_fake_toas_uniform(
                        55000.0, 55030.0, n, model, obs="gbt",
                        error_us=300.0,
                        freq_mhz=np.tile([1400.0, 800.0],
                                         (n + 1) // 2)[:n],
                        add_noise=True, seed=100 + i)
                    pulsars.append((f"AUDIT{i}", model, toas))
                self._cache["fleet"] = FleetFitter(
                    pulsars, maxiter=3, chunk_size=2)
        return self._cache["fleet"]

    def pta_run(self):
        """A tiny built PTA scenario (4 pulsars, chunk width 2 -> 2
        chunks) for the pta_simulate contract: steady state must be 2
        dispatches + 2 fetches, with only the common-process rows
        crossing host->device."""
        if "pta" not in self._cache:
            from pint_tpu import pta

            sc = pta.Scenario(
                n_pulsars=4, seed=0, chunk_size=2,
                cadence=pta.Cadence(span_days=360.0,
                                    cadence_days=15.0))
            self._cache["pta"] = pta.build(sc)
        return self._cache["pta"]

    def grid_fitter(self):
        """A WLSFitter with DM frozen, for the grid contracts."""
        key = "grid_fitter"
        if key not in self._cache:
            import copy
            import warnings

            from pint_tpu.fitter import WLSFitter

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                model = copy.deepcopy(self.model)
                model.DM.frozen = True
                self._cache[key] = WLSFitter(self.toas, model)
        return self._cache[key]


# --- per-contract drivers -----------------------------------------------------
# A driver builds (outside the instrumented region) and returns either
#   {"call": fn}                      — warmup = fn()*warmup; steady = fn()
#   {"base": fnA, "extended": fnB}    — marginal mode: steady = B - A
# All array allocation is hoisted out of the returned callables so the
# measured counts are the entrypoint's own.

def _drv_residuals(fix: ContractFixture):
    from pint_tpu.residuals import build_resid_fn

    fn = build_resid_fn(fix.model, fix.batch, fix.resid.track_mode,
                        True, True)
    p = fix.pdict
    return {"call": lambda: fn(p)}


def _drv_split_assembly(fix: ContractFixture):
    from pint_tpu.fitter import build_whitened_assembly

    a = build_whitened_assembly(fix.model, fix.batch, fix.names,
                                fix.resid.track_mode,
                                include_offset=True,
                                design_matrix="split")
    x0 = fix.np.zeros(len(fix.names))
    p = fix.pdict
    return {"call": lambda: a(x0, p)}


def _drv_wls_step(fix: ContractFixture):
    from pint_tpu.fitter import build_wls_step

    step = build_wls_step(fix.model, fix.batch, fix.names,
                          fix.resid.track_mode)
    x0 = fix.np.zeros(len(fix.names))
    p = fix.pdict
    return {"call": lambda: step(x0, p)}


def _drv_gls_step(fix: ContractFixture):
    from pint_tpu.fitter import build_gls_step

    step = build_gls_step(fix.model, fix.batch, fix.names,
                          fix.resid.track_mode)
    x0 = fix.np.zeros(len(fix.names))
    p = fix.pdict
    return {"call": lambda: step(x0, p)}


def _drv_wideband_step(fix: ContractFixture):
    _, _, f = fix.wideband()
    names = f.fit_params
    step = f._cached_step(names, None, True)
    x0 = fix.np.zeros(len(names))
    p = f.resids.pdict
    return {"call": lambda: step(x0, p)}


def _drv_fused_fit(fix: ContractFixture):
    from pint_tpu.fitter import build_fused_fit

    fit = build_fused_fit(fix.model, fix.batch, fix.names,
                          fix.resid.track_mode, maxiter=3,
                          exact_floor=0.0)
    p = fix.pdict
    return {"call": lambda: fit(p, p)}


def _drv_grid_chunk(fix: ContractFixture):
    from pint_tpu.gridutils import grid_chisq_flat

    f = fix.grid_fitter()
    grid = {"DM": fix.np.asarray([14.9, 14.95, 15.0, 15.05])}
    return {"call": lambda: grid_chisq_flat(f, grid, maxiter=1,
                                            chunk_size=2)}


def _drv_sharded_chunk(fix: ContractFixture):
    from pint_tpu.parallel import make_mesh, sharded_grid_chisq

    f = fix.grid_fitter()
    mesh = make_mesh()
    nb = mesh.devices.shape[0]
    grid = {"DM": fix.np.asarray([14.9, 14.95, 15.0, 15.05])}
    return {"call": lambda: sharded_grid_chisq(
        f, grid, mesh=mesh, maxiter=1, chunk_size=2 * nb)}


def _drv_multihost_chunk(fix: ContractFixture):
    import jax
    from jax.sharding import Mesh

    from pint_tpu.multihost import multihost_grid_chisq

    f = fix.grid_fitter()
    # the per-process view of the multihost mesh: batch stays at the
    # host level (size 1 here — single process), TOAs shard over every
    # local device (the 8-virtual-device CPU mesh in tier-1)
    devs = fix.np.array(jax.devices())
    mesh = Mesh(devs.reshape(1, len(devs)), ("batch", "toa"))
    grid = {"DM": fix.np.asarray([14.9, 14.95, 15.0, 15.05])}
    return {"call": lambda: multihost_grid_chisq(f, grid, mesh=mesh,
                                                 maxiter=1)}


def _drv_checkpointed_chunk(fix: ContractFixture):
    from pint_tpu.gridutils import grid_chisq_flat

    f = fix.grid_fitter()
    # 5 points / chunks of 2: the ragged last chunk exercises the
    # pad-to-one-compiled-shape path run_checkpointed_scan promises
    grid = {"DM": fix.np.asarray([14.9, 14.95, 15.0, 15.05, 15.1])}
    ck = fix.tmpfile("contract_scan.npz")
    return {"call": lambda: grid_chisq_flat(
        f, grid, maxiter=1, chunk_size=2, checkpoint=ck)}


def _drv_mcmc_step(fix: ContractFixture):
    import jax.numpy as jnp

    from pint_tpu.mcmc import ensemble_sample

    def lnpost(x):
        return -0.5 * jnp.sum(x * x)

    x0 = fix.np.asarray([[0.1, 0.0], [0.0, 0.1],
                         [-0.1, 0.0], [0.0, -0.1]])
    ck1, ck2 = fix.tmpfile("mcmc_a.npz"), fix.tmpfile("mcmc_b.npz")
    # warm BOTH run shapes OUTSIDE the measured window: on a cold
    # process the base run pays one-time compiles the extended run then
    # reuses, driving the marginal compile count negative (the
    # subtraction only cancels work both runs repeat) — and warming
    # only one shape would leave the other side's one-time retraces
    # uncancelled, so each measured shape gets its own warmup
    ensemble_sample(lnpost, x0, nsteps=2, seed=1,
                    checkpoint=fix.tmpfile("mcmc_warm_a.npz"),
                    checkpoint_every=2)
    ensemble_sample(lnpost, x0, nsteps=6, seed=1,
                    checkpoint=fix.tmpfile("mcmc_warm_b.npz"),
                    checkpoint_every=2)
    # marginal mode: the 6-step run re-dispatches the SAME compiled
    # 2-step chunk two extra times — per-chunk marginal compiles must
    # be zero (the one-compiled-chunk-shape property)
    return {
        "base": lambda: ensemble_sample(lnpost, x0, nsteps=2, seed=1,
                                        checkpoint=ck1,
                                        checkpoint_every=2),
        "extended": lambda: ensemble_sample(lnpost, x0, nsteps=6, seed=1,
                                            checkpoint=ck2,
                                            checkpoint_every=2),
    }


def _drv_fleet_fit(fix: ContractFixture):
    ff = fix.fleet_fitter()
    return {"call": lambda: ff.fit()}


def _drv_pta_simulate(fix: ContractFixture):
    """Steady-state pta simulation: re-synthesizing the SAME
    realization (the idempotent-replay idiom serve_request uses) must
    hit the staged chunk cache — 1 dispatch + 1 fetch per chunk, only
    the per-realization common-process rows cross host->device."""
    run = fix.pta_run()
    return {"call": lambda: run.simulate(realization=0)}


def _drv_serve_request(fix: ContractFixture):
    """The serve daemon's steady-state request path: resubmit two
    prepared 8-TOA jobs (one structure/shape bucket -> ONE coalesced
    batch) and flush inline.  A FRESH TimingService per builder call —
    check_warm's leg B must rebuild programs against the warm store —
    while the jobs reuse the fleet fixture's models/TOAs (preparation
    is host-side staging, outside the instrumented window)."""
    from pint_tpu.serve import TimingService

    ff = fix.fleet_fitter()
    svc = TimingService(batch_size=2, maxiter=3)
    jobs = [svc.prepare(pu.model, pu.toas, name=pu.name)
            for pu in ff._pulsars[:2]]

    def call():
        futs = [svc.submit_prepared(j) for j in jobs]
        svc.flush()
        return [f.result(timeout=600.0).chi2 for f in futs]

    return {"call": call}


_DRIVERS: Dict[str, Callable[[ContractFixture], dict]] = {
    "residuals": _drv_residuals,
    "split_assembly": _drv_split_assembly,
    "wls_step": _drv_wls_step,
    "gls_step": _drv_gls_step,
    "wideband_step": _drv_wideband_step,
    "fused_fit": _drv_fused_fit,
    "grid_chunk": _drv_grid_chunk,
    "sharded_chunk": _drv_sharded_chunk,
    "multihost_chunk": _drv_multihost_chunk,
    "checkpointed_chunk": _drv_checkpointed_chunk,
    "mcmc_step": _drv_mcmc_step,
    "fleet_fit": _drv_fleet_fit,
    "pta_simulate": _drv_pta_simulate,
    "serve_request": _drv_serve_request,
}


# --- measurement + judgment ---------------------------------------------------

def steady_state_counters(call: Callable[[], object], *,
                          warmup: int = 1):
    """(warmup, steady) :class:`TraceCounters` for ``call`` — the shared
    measurement primitive tests use directly (single source of truth for
    "N dispatches per step" style assertions)."""
    with instrument() as th:
        m0 = th.mark()
        for _ in range(max(1, warmup)):
            call()
        m1 = th.mark()
        call()
        m2 = th.mark()
    return (m1 - m0), (m2 - m1)


def _measure(driver: dict, warmup: int):
    if "call" in driver:
        return steady_state_counters(driver["call"], warmup=warmup)
    with instrument() as th:
        m0 = th.mark()
        driver["base"]()
        m1 = th.mark()
        driver["extended"]()
        m2 = th.mark()
    base, ext = (m1 - m0), (m2 - m1)
    # marginal steady state: what the extra chunks cost beyond the base
    # run (both runs rebuild their programs, so identical one-time work
    # cancels; only per-chunk costs survive the subtraction)
    return base, ext - base


def _judge(c: Contract, warm: TraceCounters,
           steady: TraceCounters) -> List[Finding]:
    findings: List[Finding] = []

    def f(code: str, msg: str):
        findings.append(Finding(
            code, c.path, c.line, 1,
            f"contract '{c.name}' ({c.qualname}): {msg}",
            source=f"@dispatch_contract('{c.name}')", origin="contract"))

    n_re = len(steady.retraces)
    if n_re or steady.compiles > 0:
        parts = []
        for ev in steady.retraces[:3]:
            parts.append(f"{ev.fn_name}: {ev.component}")
        attribution = "; ".join(parts) if parts else \
            "recompile without a visible tracing-cache miss " \
            "(executable-cache key changed)"
        f("CONTRACT002",
          f"steady-state retrace/recompile ({n_re} retrace(s), "
          f"{steady.compiles} compile(s)) — unstable cache-key "
          f"component: {attribution}")
    for axis, got, limit in (
            ("dispatches", steady.dispatches, c.max_dispatches),
            ("transfers", steady.transfers, c.max_transfers),
            ("host_bytes", steady.host_bytes, c.max_host_bytes)):
        if got > limit:
            f("CONTRACT001",
              f"steady-state {axis} = {got} exceeds budget {limit}")
    if warm.compiles > c.max_compiles:
        f("CONTRACT001",
          f"warmup compiles = {warm.compiles} exceeds budget "
          f"{c.max_compiles}")
    return findings


def _has_comm_contract(c: Contract) -> bool:
    return (c.max_collectives is not None or c.max_comm_bytes is not None
            or c.max_device_peak_bytes is not None)


def _judge_comm(c: Contract, profile, mismatches) -> List[Finding]:
    """CONTRACT004: the compiled HLO against the declared comm budget.
    Attribution names the entrypoint, the collective category and the
    HLO op; an unbudgeted category present in the program is always a
    failure (the SPMD mirror of the always-fail retrace rule)."""
    findings: List[Finding] = []

    def f(msg: str):
        findings.append(Finding(
            "CONTRACT004", c.path, c.line, 1,
            f"contract '{c.name}' ({c.qualname}): {msg}",
            source=f"@dispatch_contract('{c.name}')", origin="contract"))

    budget = c.max_collectives or {}
    for cat in sorted(profile.counts):
        n = profile.counts[cat]
        nb = profile.bytes_by_category.get(cat, 0)
        first = next(op.name for op in profile.ops if op.category == cat)
        if cat not in budget:
            f(f"unbudgeted collective category '{cat}' in the compiled "
              f"HLO ({n} op(s), {nb} B; HLO op '{first}') — a collective "
              "with no declared budget always fails: add it to "
              "max_collectives or eliminate it")
        elif n > budget[cat]:
            f(f"collective '{cat}' count {n} exceeds budget "
              f"{budget[cat]} (HLO op '{first}'; {nb} B in category)")
    if c.max_comm_bytes is not None and \
            profile.comm_bytes > c.max_comm_bytes:
        f(f"collective traffic {profile.comm_bytes} B exceeds "
          f"max_comm_bytes {c.max_comm_bytes}")
    if c.max_device_peak_bytes is not None and \
            profile.peak_bytes > c.max_device_peak_bytes:
        f(f"per-device peak {profile.peak_bytes} B exceeds "
          f"max_device_peak_bytes {c.max_device_peak_bytes}")
    for idx, got, want in mismatches:
        f(f"output {idx} compiled sharding {got or '(replicated)'} "
          f"does not match the declared PartitionSpec axes "
          f"{want or '(replicated)'} — XLA resolved the output "
          "differently than the contract declares")
    return findings


def _comm_leg(c: Contract, fix: ContractFixture) -> List[Finding]:
    """Lower the entrypoint's compiled HLO and judge CONTRACT004.

    Runs OUTSIDE :func:`instrument` (lowering compiles; none of it is
    steady-state work).  The (profile, mismatches) pair is cached on
    the fixture so repeated checks in one audit pass lower each program
    once — failpoint runs (``chatty_collective``) therefore need a
    FRESH fixture, which they need anyway for the program caches the
    entrypoints keep on their fitters."""
    from pint_tpu.lint import hlo_audit

    builder = hlo_audit.HLO_DRIVERS.get(c.name)
    if builder is None:
        return [Finding(
            "CONTRACT004", c.path, c.line, 1,
            f"contract '{c.name}' declares a comm budget but has no HLO "
            "audit driver — add one to pint_tpu/lint/hlo_audit.py so "
            "the budget is enforced",
            source=f"@dispatch_contract('{c.name}')", origin="contract")]
    cache = getattr(fix, "_cache", None)
    key = ("comm", c.name)
    cached = cache.get(key) if isinstance(cache, dict) else None
    if cached is None:
        prog = builder(fix)
        profile = hlo_audit.analyze_compiled(prog.compiled, prog.mesh)
        # the audit already owns a real Compiled — feed the metrics
        # cost-card registry for free (ISSUE 13; best-effort, never
        # fails the audit)
        from pint_tpu import metrics

        metrics.harvest_compiled(c.name, prog.compiled,
                                 source="contract_audit")
        cached = (profile,
                  hlo_audit.sharding_mismatches(profile,
                                                prog.expected_out_specs))
        if isinstance(cache, dict):
            cache[key] = cached
    return _judge_comm(c, *cached)


def _unwrap_program(fn):
    """Peel ``faultinject.wrap`` / ``aot._ServedProgram`` layers down to
    the lowerable jitted program (``_ServedProgram.fn`` is the jit; an
    active failpoint closure has neither attribute, in which case the
    caller's per-leg guard skips the card)."""
    while not hasattr(fn, "lower") and hasattr(fn, "fn"):
        fn = fn.fn
    return fn


def harvest_cost_cards(fixture: Optional[ContractFixture] = None
                       ) -> Dict[str, Dict[str, object]]:
    """Build + compile the headline entrypoint programs on the audit
    fixture and record their full cost cards (FLOPs, bytes accessed,
    per-device peak) in :mod:`pint_tpu.metrics` — the bench cost-card
    leg (ISSUE 13).

    Covers ``residuals``, ``fused_fit``, ``fleet_bucket`` (the fleet
    bucket program on batch-mesh avals, reusing the CONTRACT004 HLO
    driver) and ``serve_bucket`` (the daemon's coalesced batch
    program).  Runs OUTSIDE any instrumented window — lowering and
    compiling here is measurement, not steady-state work.  Each leg is
    independent: a failure drops that entry from the result rather
    than taking the others down."""
    from pint_tpu import metrics

    import time

    fix = fixture if fixture is not None else ContractFixture()
    cards: Dict[str, Dict[str, object]] = {}

    def leg(entry: str, build: Callable[[], tuple]) -> None:
        try:
            compiled, call_args = build()
            card = metrics.harvest_compiled(entry, compiled,
                                            source="cost_cards")
            if card is None:
                return
            if call_args is not None:
                # achieved-vs-peak: time the compiled program itself
                # (min-of-2 after one warm call) so the card carries a
                # FLOP/s the flops estimate can be divided against
                import jax

                jax.block_until_ready(compiled(*call_args))
                walls = []
                for _ in range(2):
                    t0 = time.perf_counter()
                    jax.block_until_ready(compiled(*call_args))
                    walls.append(time.perf_counter() - t0)
                wall = max(min(walls), 1e-9)
                extra = {"digest": card.get("digest", ""),
                         "exec_wall_s": wall}
                if card.get("flops"):
                    extra["achieved_flops_per_sec"] = \
                        float(card["flops"]) / wall
                metrics.record_cost_card(entry, extra)
                card.update(extra)
            cards[entry] = card
        except Exception:
            pass

    def _residuals():
        from pint_tpu.residuals import build_resid_fn

        fn = _unwrap_program(build_resid_fn(
            fix.model, fix.batch, fix.resid.track_mode, True, True))
        return fn.lower(fix.pdict).compile(), (fix.pdict,)

    def _fused_fit():
        from pint_tpu.fitter import build_fused_fit

        fit = build_fused_fit(fix.model, fix.batch, fix.names,
                              fix.resid.track_mode, maxiter=3,
                              exact_floor=0.0)
        run = _unwrap_program(fit.run)
        return run.lower(fix.pdict).compile(), (fix.pdict,)

    def _fleet_bucket():
        from pint_tpu.lint import hlo_audit

        # sharded ShapeDtypeStruct avals — inspectable, not callable
        return hlo_audit.HLO_DRIVERS["fleet_fit"](fix).compiled, None

    def _serve_bucket():
        from pint_tpu.serve import TimingService

        ff = fix.fleet_fitter()
        svc = TimingService(batch_size=2, maxiter=3)
        jobs = [svc.prepare(pu.model, pu.toas, name=pu.name)
                for pu in ff._pulsars[:2]]
        bucket = svc._bucket_for(jobs[0])
        assert svc._bucket_for(jobs[1]) is bucket
        prog = _unwrap_program(svc._bucket_program(bucket))
        args = svc._batch_args(bucket, jobs)
        return prog.lower(*args).compile(), args

    leg("residuals", _residuals)
    leg("fused_fit", _fused_fit)
    leg("fleet_bucket", _fleet_bucket)
    leg("serve_bucket", _serve_bucket)
    return cards


def check(name: str,
          fixture: Optional[ContractFixture] = None) -> ContractReport:
    """Measure one contract and judge it against its declared budget."""
    _ensure_registered()
    c = REGISTRY.get(name)
    if c is None:
        raise KeyError(f"no dispatch contract named {name!r} "
                       f"(registered: {sorted(REGISTRY)})")
    builder = _DRIVERS.get(name)
    if builder is None:
        return ContractReport(name, TraceCounters(), TraceCounters(), (
            Finding("CONTRACT001", c.path, c.line, 1,
                    f"contract '{name}' has no audit driver — add one to "
                    "pint_tpu/lint/contracts.py so the budget is "
                    "enforced", source=f"@dispatch_contract('{name}')",
                    origin="contract"),))
    fix = fixture if fixture is not None else ContractFixture()
    driver = builder(fix)
    warm, steady = _measure(driver, c.warmup)
    findings = _judge(c, warm, steady)
    if _has_comm_contract(c) and \
            os.environ.get("PINT_TPU_CONTRACT_COMM", "1") != "0":
        findings.extend(_comm_leg(c, fix))
    return ContractReport(name, warm, steady, tuple(findings))


def check_warm(name: str,
               fixture: Optional[ContractFixture] = None
               ) -> ContractReport:
    """The cold-start axis (ISSUE 7) for a ``warm_from_store=True``
    contract: build the entrypoint against a FRESH AOT store (leg A —
    populates the store and, via the round-trip verify call, lands the
    thin exported-call wrapper in the persistent compilation cache),
    then REBUILD it (leg B: new function objects, empty tracing cache)
    and measure the rebuilt call under instrumentation.  The warm leg
    must show ZERO compiles — CONTRACT003 otherwise, attributed to the
    ProgramKey misses the store recorded (or to a cold persistent
    cache when the store itself hit)."""
    import tempfile

    import jax

    from pint_tpu import aot

    _ensure_registered()
    c = REGISTRY.get(name)
    if c is None:
        raise KeyError(f"no dispatch contract named {name!r} "
                       f"(registered: {sorted(REGISTRY)})")
    if not c.warm_from_store:
        raise ValueError(f"contract {name!r} is not warm_from_store")
    builder = _DRIVERS.get(name)
    if builder is None or not callable(builder):
        return ContractReport(name, TraceCounters(), TraceCounters(), ())
    fix = fixture if fixture is not None else ContractFixture()

    findings: List[Finding] = []

    def f(msg: str):
        findings.append(Finding(
            "CONTRACT003", c.path, c.line, 1,
            f"contract '{c.name}' ({c.qualname}): {msg}",
            source=f"@dispatch_contract('{c.name}')", origin="contract"))

    # the warm leg needs a live persistent compilation cache for the
    # exported-call wrappers; point one at the scratch dir if the
    # process runs cacheless (PINT_TPU_XLA_CACHE=0)
    with tempfile.TemporaryDirectory(prefix="pint_tpu_warm_") as td:
        prev_cc = jax.config.jax_compilation_cache_dir
        if prev_cc is None:
            from jax._src import compilation_cache as _cc

            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(td, "cc"))
            _cc.reset_cache()
        try:
            with aot.temporary_store(os.path.join(td, "store")):
                driver = builder(fix)
                if "call" not in driver:
                    raise ValueError(
                        f"warm_from_store contract {name!r} needs a "
                        "'call'-mode driver")
                driver["call"]()          # leg A: populate the store
                driver2 = builder(fix)    # leg B: fresh programs
                mmark = aot.miss_mark()
                cmark = aot.counters()
                with instrument() as th:
                    m0 = th.mark()
                    driver2["call"]()     # the cold-start call
                    m1 = th.mark()
                    driver2["call"]()     # steady state on the warm path
                    m2 = th.mark()
                warm = m1 - m0
                steady = m2 - m1
                misses = aot.misses_since(mmark)
                delta = aot.counters_since(cmark)
        finally:
            if prev_cc is None:
                from jax._src import compilation_cache as _cc

                jax.config.update("jax_compilation_cache_dir", prev_cc)
                _cc.reset_cache()
    n_compiles = warm.compiles + steady.compiles
    # a ProgramKey miss on the warm leg means the store fell back to
    # LIVE TRACING — the cost the store exists to kill — even when a
    # warm persistent compilation cache absorbs the recompile itself
    if n_compiles > 0 or steady.retraces or misses:
        if misses:
            attribution = "; ".join(
                f"ProgramKey miss: entry '{m.entry}' key {m.digest} "
                f"({m.reason})" for m in misses[:4])
        elif delta.get("hits", 0) > 0 and n_compiles:
            attribution = (
                f"store HIT ({delta['hits']} program(s) served) but the "
                "exported-call wrapper recompiled — persistent "
                "compilation cache cold or lowering nondeterministic")
        elif n_compiles or steady.retraces:
            attribution = "no store traffic (serve() wrapper dropped?)"
        else:
            attribution = "unattributed"
        f(f"warm-from-store leg failed the zero-compile start "
          f"({n_compiles} compile(s), {len(steady.retraces)} steady "
          f"retrace(s), {len(misses)} ProgramKey miss(es)) — "
          f"{attribution}")
    return ContractReport(name, warm, steady, tuple(findings))


_SUPPRESS_CACHE: dict = {}


def _suppressed(c: Contract, code: str) -> bool:
    """Shared ``# ddlint: disable=`` suppression at (or within 2 lines
    of) the decoration site sanctions a breach."""
    sup = _SUPPRESS_CACHE.get(c.path)
    if sup is None:
        try:
            with open(c.path, encoding="utf-8") as fh:
                sup = scan_suppressions(fh.read())
        except OSError:
            sup = scan_suppressions("")
        _SUPPRESS_CACHE[c.path] = sup
    return any(sup.is_suppressed(code, ln)
               for ln in range(max(1, c.line - 2), c.line + 3))


def audit_contracts(names: Optional[Sequence[str]] = None,
                    fixture: Optional[ContractFixture] = None,
                    warm_legs: Optional[bool] = None) -> List[Finding]:
    """Drive every registered contract (or the named subset) and return
    the unsanctioned findings — the ``--contracts`` CLI mode and the
    tier-1 gate (tests/test_contracts.py).

    ``warm_legs`` (default on; ``PINT_TPU_CONTRACT_WARM=0`` opts out)
    adds the cold-start axis: every audited ``warm_from_store=True``
    contract also runs :func:`check_warm` and must show zero compiles
    against a store its own first build populated (CONTRACT003)."""
    _ensure_registered()
    targets = sorted(REGISTRY) if names is None else list(names)
    unknown = [n for n in targets if n not in REGISTRY]
    if unknown:
        raise KeyError(f"unknown contract(s) {unknown}; registered: "
                       f"{sorted(REGISTRY)}")
    if warm_legs is None:
        warm_legs = os.environ.get("PINT_TPU_CONTRACT_WARM", "1") != "0"
    fix = fixture if fixture is not None else ContractFixture()
    findings: List[Finding] = []
    for name in targets:
        rep = check(name, fixture=fix)
        reps = [rep]
        if warm_legs and REGISTRY[name].warm_from_store and \
                name in _DRIVERS:
            reps.append(check_warm(name, fixture=fix))
        for r in reps:
            for f in r.findings:
                if not _suppressed(REGISTRY[name], f.code):
                    findings.append(f)
    return findings
