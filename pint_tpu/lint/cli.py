"""Command-line front end: ``python -m pint_tpu.lint`` / ``pint-tpu-lint``.

Exit codes: 0 = clean (modulo baseline), 1 = new findings, 2 = usage
error.  ``--format=json`` emits a machine-readable document for CI and
editor integrations.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from pint_tpu.lint import astrules, baseline as bl
from pint_tpu.lint.findings import Finding, format_json, format_text

__all__ = ["main"]


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="pint-tpu-lint",
        description="Precision & trace-safety static analyzer for pint_tpu "
                    "(AST rules DD001/PREC001/TRACE001/JIT001 plus the "
                    "JAXPR001 runtime jaxpr audit).")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the installed "
                         "pint_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    dest="fmt", help="output format (default: text)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: the checked-in "
                         "pint_tpu/lint/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings "
                         "(preserves the recorded first-run count)")
    ap.add_argument("--no-jaxpr-audit", action="store_true",
                    help="skip the runtime jaxpr audit (AST rules only; "
                         "no jax import, much faster)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for code, desc in astrules.RULES.items():
            print(f"{code}  {desc}")
        return 0

    paths = args.paths or [_package_dir()]
    for p in paths:
        if not os.path.exists(p):
            print(f"pint-tpu-lint: no such path: {p}", file=sys.stderr)
            return 2

    findings: List[Finding] = astrules.lint_paths(paths)

    if not args.no_jaxpr_audit:
        # the audit traces the *installed* package's entry points; it is
        # meaningful whenever the package itself is under lint
        pkg = _package_dir()
        in_scope = any(
            os.path.commonpath([os.path.abspath(p), pkg]) == pkg or
            os.path.abspath(p) == os.path.dirname(pkg)
            for p in paths)
        if in_scope:
            from pint_tpu.lint.jaxpr_audit import audit_entry_points

            findings = findings + audit_entry_points()

    meta = {"total": len(findings), "baselined": 0, "stale_baseline": 0}

    if args.update_baseline:
        import datetime

        path = args.baseline or bl.default_baseline_path()
        n = bl.write_baseline(path, findings,
                              date=datetime.date.today().isoformat())
        print(f"pint-tpu-lint: wrote {n} baseline entries to {path}")
        return 0

    new = findings
    if not args.no_baseline:
        path = args.baseline or bl.default_baseline_path()
        base = bl.load_baseline(path)
        new, n_baselined, stale = bl.apply_baseline(findings, base)
        meta["baselined"] = n_baselined
        meta["stale_baseline"] = sum(stale.values())
        if stale and args.fmt == "text":
            print(f"pint-tpu-lint: note: {sum(stale.values())} stale "
                  "baseline entr(y/ies) no longer match — consider "
                  "--update-baseline to shrink the file", file=sys.stderr)

    meta["new"] = len(new)
    if args.fmt == "json":
        print(format_json(new, meta))
    else:
        if new:
            print(format_text(new))
        print(f"pint-tpu-lint: {len(new)} new finding(s), "
              f"{meta['baselined']} baselined, "
              f"{meta['stale_baseline']} stale baseline entr(y/ies)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
