"""Command-line front end: ``python -m pint_tpu.lint`` / ``pint-tpu-lint``.

Exit codes: **0** = clean (modulo baseline) — and ALWAYS 0 under
``--update-baseline``, whose job is recording findings, not judging
them; **1** = new findings; **2** = usage error (bad path, unknown rule
code, unknown contract name).  ``--format=json`` emits a
machine-readable document for CI and editor integrations in every
mode, including ``--update-baseline``.

Modes:

* default — AST rules + the runtime jaxpr audit over the given paths
  (or the installed package).
* ``--contracts[=NAME[,NAME]]`` — the dispatch-contract audit
  (:mod:`pint_tpu.lint.contracts`): drive every registered entrypoint
  (or the named subset) on the synthetic fixture and report budget
  breaches (CONTRACT001) and steady-state retraces (CONTRACT002).
* ``--precflow[=NAME[,NAME]]`` — the precision-flow audit
  (:mod:`pint_tpu.lint.precflow`): trace every
  ``@precision_contract`` entrypoint (or the named subset) with
  native x64 AND under ``disable_x64()`` + ``policy("dd32")``, and
  report phase-critical bare-f32 collapses (PREC002) and broken dd
  pairs (PREC003).
* ``--concurrency[=MODULE[,MODULE]]`` — the concurrency & signal-
  safety audit (:mod:`pint_tpu.lint.concurrency`): lock-guard
  inference (LOCK001), static lock-order cycles (LOCK002), signal-
  handler lock/blocking hazards (SIG001), and hook re-entrancy
  (HOOK001) over the whole package or the named modules.

Rule filtering: ``--select CODE[,CODE]`` keeps only those codes,
``--ignore CODE[,CODE]`` drops them (select wins when both name a
code).  Codes are validated against ``--list-rules``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from pint_tpu.lint import astrules, baseline as bl
from pint_tpu.lint.findings import (
    Finding, format_github, format_json, format_text,
)

__all__ = ["main"]


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="pint-tpu-lint",
        description="Precision & trace-safety static analyzer for pint_tpu "
                    "(AST rules DD001/PREC001/TRACE001/TRACE002/JIT001/"
                    "JIT002/SHARD001/SHARD002, the JAXPR001 runtime jaxpr "
                    "audit, the CONTRACT001-CONTRACT004 dispatch-"
                    "contract audit incl. the warm-from-store cold-start "
                    "axis and the SPMD collective-communication budgets, "
                    "the PREC002/PREC003 precision-flow audit, and the "
                    "LOCK001/LOCK002/SIG001/HOOK001 concurrency & "
                    "signal-safety audit with its CONTRACT005 dynamic "
                    "lock-order companion). "
                    "Exit codes: 0 clean (always 0 with "
                    "--update-baseline), 1 new findings, 2 usage error.")
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the installed "
                         "pint_tpu package)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text", dest="fmt",
                    help="output format (default: text; 'github' emits "
                         "::error workflow-command annotations for CI)")
    ap.add_argument("--select", default=None, metavar="CODE[,CODE]",
                    help="only report findings with these rule codes "
                         "(see --list-rules)")
    ap.add_argument("--ignore", default=None, metavar="CODE[,CODE]",
                    help="drop findings with these rule codes; --select "
                         "wins when both name a code")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: the checked-in "
                         "pint_tpu/lint/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from this run's findings "
                         "(preserves the recorded first-run count) and "
                         "exit 0 EVEN IF findings exist — recording, not "
                         "judging")
    ap.add_argument("--no-jaxpr-audit", action="store_true",
                    help="skip the runtime jaxpr audit (AST rules only; "
                         "no jax import, much faster)")
    ap.add_argument("--contracts", nargs="?", const="all", default=None,
                    metavar="NAME[,NAME]",
                    help="run the dispatch-contract audit instead of the "
                         "AST rules: drive every registered entrypoint "
                         "(or the named subset) on the synthetic fixture "
                         "and report budget breaches / steady-state "
                         "retraces")
    ap.add_argument("--precflow", nargs="?", const="all", default=None,
                    metavar="NAME[,NAME]",
                    help="run the precision-flow audit instead of the "
                         "AST rules: trace every @precision_contract "
                         "entrypoint (or the named subset) with native "
                         "x64 and under disable_x64()+policy('dd32'), "
                         "and report PREC002/PREC003 findings")
    ap.add_argument("--concurrency", nargs="?", const="all",
                    default=None, metavar="MODULE[,MODULE]",
                    help="run the concurrency & signal-safety audit "
                         "instead of the AST precision rules: lock-"
                         "guard inference, lock-order cycles, signal-"
                         "handler hazards and hook re-entrancy over "
                         "the package (or the named modules, e.g. "
                         "serve,gateway)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--list-contracts", action="store_true",
                    help="print the registered dispatch contracts "
                         "(name, budgets, entrypoint) and exit")
    ap.add_argument("--list-precision-contracts", action="store_true",
                    help="print the registered precision contracts "
                         "(name, chain, entrypoint) and exit")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        for code, desc in astrules.RULES.items():
            print(f"{code}  {desc}")
        return 0

    if args.list_contracts:
        from pint_tpu.lint import contracts as con

        con._ensure_registered()
        for name in sorted(con.REGISTRY):
            c = con.REGISTRY[name]
            extras = []
            if c.warm_from_store:
                extras.append("warm-from-store")
            if c.max_collectives is not None:
                budget = ",".join(f"{k}<={v}" for k, v in
                                  sorted(c.max_collectives.items()))
                extras.append(f"collectives[{budget or 'none'}]")
            if c.max_comm_bytes is not None:
                extras.append(f"comm-bytes<={c.max_comm_bytes}")
            if c.max_device_peak_bytes is not None:
                extras.append(f"peak-bytes<={c.max_device_peak_bytes}")
            print(f"{name:20s} {c.qualname:30s} "
                  f"compiles<={c.max_compiles} "
                  f"dispatches<={c.max_dispatches} "
                  f"transfers<={c.max_transfers}"
                  + "".join(" " + e for e in extras))
        return 0

    if args.list_precision_contracts:
        from pint_tpu.lint import contracts as con

        con._ensure_registered()
        for name in sorted(con.PRECISION_REGISTRY):
            pc = con.PRECISION_REGISTRY[name]
            print(f"{name:20s} {pc.qualname:30s} chain={pc.chain}")
        return 0

    select = ignore = None
    if args.select is not None:
        select = {c.strip().upper() for c in args.select.split(",")
                  if c.strip()}
    if args.ignore is not None:
        ignore = {c.strip().upper() for c in args.ignore.split(",")
                  if c.strip()}
    for flag, codes in (("--select", select), ("--ignore", ignore)):
        unknown = (codes or set()) - set(astrules.RULES)
        if unknown:
            print(f"pint-tpu-lint: {flag}: unknown rule code(s) "
                  f"{sorted(unknown)} (see --list-rules)",
                  file=sys.stderr)
            return 2

    findings: List[Finding]
    if args.contracts is not None:
        from pint_tpu.lint import contracts as con

        names = None if args.contracts == "all" else [
            n.strip() for n in args.contracts.split(",") if n.strip()]
        try:
            findings = con.audit_contracts(names)
        except KeyError as exc:
            print(f"pint-tpu-lint: {exc}", file=sys.stderr)
            return 2
    elif args.precflow is not None:
        from pint_tpu.lint.precflow import audit_precision

        names = None if args.precflow == "all" else [
            n.strip() for n in args.precflow.split(",") if n.strip()]
        try:
            findings = audit_precision(names)
        except KeyError as exc:
            print(f"pint-tpu-lint: {exc}", file=sys.stderr)
            return 2
    elif args.concurrency is not None:
        from pint_tpu.lint.concurrency import (
            audit_concurrency, lint_concurrency_paths,
        )

        if args.paths:
            # explicit paths win over the module list: lint those files
            # with the concurrency rules (the seeded-fixture CI leg)
            for p in args.paths:
                if not os.path.exists(p):
                    print(f"pint-tpu-lint: no such path: {p}",
                          file=sys.stderr)
                    return 2
            findings = lint_concurrency_paths(args.paths)
        else:
            names = None if args.concurrency == "all" else [
                n.strip() for n in args.concurrency.split(",")
                if n.strip()]
            try:
                findings = audit_concurrency(names)
            except KeyError as exc:
                print(f"pint-tpu-lint: {exc}", file=sys.stderr)
                return 2
    else:
        paths = args.paths or [_package_dir()]
        for p in paths:
            if not os.path.exists(p):
                print(f"pint-tpu-lint: no such path: {p}",
                      file=sys.stderr)
                return 2

        findings = astrules.lint_paths(paths)

        want_jaxpr = not args.no_jaxpr_audit and \
            (select is None or "JAXPR001" in select) and \
            not (ignore and "JAXPR001" in ignore)
        if want_jaxpr:
            # the audit traces the *installed* package's entry points;
            # it is meaningful whenever the package itself is under lint
            pkg = _package_dir()
            in_scope = any(
                os.path.commonpath([os.path.abspath(p), pkg]) == pkg or
                os.path.abspath(p) == os.path.dirname(pkg)
                for p in paths)
            if in_scope:
                from pint_tpu.lint.jaxpr_audit import audit_entry_points

                findings = findings + audit_entry_points()

    if select is not None:
        findings = [f for f in findings if f.code in select]
    if ignore is not None:
        findings = [f for f in findings
                    if f.code not in ignore or
                    (select is not None and f.code in select)]

    meta = {"total": len(findings), "baselined": 0, "stale_baseline": 0}

    if args.update_baseline:
        import datetime

        path = args.baseline or bl.default_baseline_path()
        n = bl.write_baseline(path, findings,
                              date=datetime.date.today().isoformat())
        if args.fmt == "json":
            meta["baseline_entries_written"] = n
            meta["baseline_path"] = path
            meta["new"] = 0
            print(format_json([], meta))
        else:
            print(f"pint-tpu-lint: wrote {n} baseline entries to {path}")
        return 0    # recording, not judging: findings never fail this mode

    new = findings
    if not args.no_baseline:
        path = args.baseline or bl.default_baseline_path()
        base = bl.load_baseline(path)
        new, n_baselined, stale = bl.apply_baseline(findings, base)
        meta["baselined"] = n_baselined
        meta["stale_baseline"] = sum(stale.values())
        if stale and args.fmt == "text":
            print(f"pint-tpu-lint: note: {sum(stale.values())} stale "
                  "baseline entr(y/ies) no longer match — consider "
                  "--update-baseline to shrink the file", file=sys.stderr)

    meta["new"] = len(new)
    if args.fmt == "json":
        print(format_json(new, meta))
    elif args.fmt == "github":
        out = format_github(new, meta)
        if out:
            print(out)
    else:
        if new:
            print(format_text(new))
        print(f"pint-tpu-lint: {len(new)} new finding(s), "
              f"{meta['baselined']} baselined, "
              f"{meta['stale_baseline']} stale baseline entr(y/ies)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
