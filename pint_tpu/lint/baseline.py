"""Checked-in baseline of grandfathered findings.

The baseline lets the lint gate be strict for *new* code while the
legacy findings are burned down over time.  Entries are keyed by
``(code, repo-relative path, stripped source line)`` — not line numbers —
so edits elsewhere in a file do not invalidate them.  Identical lines
are matched with multiplicity.

The header records the first-run finding count (the pre-cleanup state of
the tree when the linter was introduced) next to the current count, so
the burn-down is visible in the diff of every baseline regeneration.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Iterable, List, Tuple

from pint_tpu.lint.findings import Finding

__all__ = ["default_baseline_path", "load_baseline", "write_baseline",
           "apply_baseline", "parse_header"]

_SEP = "\t"


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


def parse_header(path: str) -> dict:
    """{'first-run': int|None, 'current': int|None} from header comments."""
    meta = {"first-run": None, "current": None}
    if not os.path.isfile(path):
        return meta
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if not line.startswith("#"):
                break
            for key in meta:
                tag = f"# {key}:"
                if line.startswith(tag):
                    try:
                        meta[key] = int(line[len(tag):].split()[0])
                    except (ValueError, IndexError):
                        pass
    return meta


def load_baseline(path: str) -> Counter:
    """Multiset of baseline keys (code, path, source)."""
    entries: Counter = Counter()
    if not os.path.isfile(path):
        return entries
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line or line.startswith("#"):
                continue
            parts = line.split(_SEP, 2)
            if len(parts) == 3:
                entries[(parts[0], parts[1], parts[2])] += 1
    return entries


def apply_baseline(findings: Iterable[Finding], baseline: Counter
                   ) -> Tuple[List[Finding], int, Counter]:
    """Split findings into (new, n_baselined, stale_entries)."""
    budget = Counter(baseline)
    new: List[Finding] = []
    n_baselined = 0
    for f in findings:
        if budget[f.key] > 0:
            budget[f.key] -= 1
            n_baselined += 1
        else:
            new.append(f)
    stale = Counter({k: v for k, v in budget.items() if v > 0})
    return new, n_baselined, stale


def write_baseline(path: str, findings: Iterable[Finding],
                   date: str = "") -> int:
    """Write the baseline for the given findings; preserves the first-run
    count from an existing file (or seeds it from this run)."""
    findings = sorted(findings, key=lambda f: f.key + (f.line,))
    prev = parse_header(path)
    n = len(findings)
    first_run = prev["first-run"] if prev["first-run"] is not None else n
    when = f" ({date})" if date else ""
    lines = [
        "# pint_tpu.lint baseline — grandfathered findings.",
        "# Matched by (code, path, stripped source line); identical lines",
        "# count with multiplicity.  Shrink me, don't grow me: fix the",
        "# hazard or add an inline `# ddlint: disable=CODE <why>` instead.",
        "# regenerate: python -m pint_tpu.lint --update-baseline",
        f"# first-run: {first_run} findings (pre-cleanup tree)",
        f"# current: {n} findings{when}",
    ]
    for f in findings:
        code, relpath, src = f.key
        lines.append(_SEP.join((code, relpath, src)))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    return n
