"""Runtime jaxpr audit: narrowing dtype conversions the AST cannot see.

The AST rules in :mod:`pint_tpu.lint.astrules` only see literal spellings
(``.astype(jnp.float32)``).  A demotion can also arise structurally — a
weak-typed Python scalar pulling an f64 chain down to f32, a library call
converting internally, an implicit promotion rule change — and those only
become visible in the traced program.  This module traces the public
residual/fitter entry points and walks the resulting jaxpr (recursing
through ``pjit``/``scan``/``cond`` sub-jaxprs) for ``convert_element_type``
equations whose output float dtype is narrower than their input.

Not every narrowing is a bug: the package's quad-single arithmetic
(:mod:`pint_tpu.qs`) is *built* from exact f64→f32 word splits.  Three
sanctioning mechanisms keep the audit quiet on legitimate code:

1. **Exact-split detection** (structural): a conversion ``w = f32(x)``
   is sanctioned when the same jaxpr also computes ``x - f64(w)`` — the
   Dekker/Veltkamp split signature, which captures the rounding error
   rather than discarding it.
2. **Sanctioned modules**: equations whose source location lies in
   ``dd.py``/``qs.py`` (the audited EFT kernels themselves).
3. **Inline suppressions**: the shared ``# ddlint: disable=JAXPR001``
   (or ``PREC001``) comment on the originating source line, for
   intentional non-split demotions that are exact by a range argument
   (e.g. casting a <2^24 day count to f32).

Everything else is reported as **JAXPR001**.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from pint_tpu.lint.findings import Finding, scan_suppressions

__all__ = ["audit_fn", "audit_closed_jaxpr", "audit_entry_points",
           "narrowing_conversions"]

_SANCTIONED_FILES = {"dd.py", "qs.py"}
_FLOAT_BITS = {"float16": 16, "bfloat16": 16, "float32": 32, "float64": 64}


def _float_bits(dtype) -> Optional[int]:
    return _FLOAT_BITS.get(getattr(dtype, "name", str(dtype)))


def _iter_jaxprs(jaxpr):
    """Yield this jaxpr and every sub-jaxpr reachable through eqn params
    (pjit/scan/while/cond/custom_* all stash jaxprs differently)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for val in eqn.params.values():
            for sub in _as_jaxprs(val):
                yield from _iter_jaxprs(sub)


def _as_jaxprs(val):
    if hasattr(val, "eqns"):                      # Jaxpr
        return [val]
    if hasattr(val, "jaxpr"):                     # ClosedJaxpr
        return [val.jaxpr]
    if isinstance(val, (tuple, list)):
        out = []
        for v in val:
            out.extend(_as_jaxprs(v))
        return out
    return []


def _source_location(eqn) -> Tuple[Optional[str], Optional[int]]:
    """(file, line) of the user frame that emitted this equation."""
    si = getattr(eqn, "source_info", None)
    if si is None:
        return None, None
    frames = []
    try:
        from jax._src import source_info_util as siu

        frames = list(siu.user_frames(si))
    except Exception:
        tb = getattr(si, "traceback", None)
        if tb is not None and hasattr(tb, "frames"):
            frames = list(tb.frames)
    for fr in frames:
        fname = getattr(fr, "file_name", None) or getattr(fr, "filename", None)
        line = getattr(fr, "start_line", None) or \
            getattr(fr, "line_num", None) or getattr(fr, "lineno", None)
        if fname:
            return fname, line
    return None, None


_SUPPRESS_CACHE: dict = {}


def _line_suppressed(path: str, line: Optional[int]) -> bool:
    if not path or not line or not os.path.isfile(path):
        return False
    sup = _SUPPRESS_CACHE.get(path)
    if sup is None:
        try:
            with open(path, encoding="utf-8") as fh:
                sup = scan_suppressions(fh.read())
        except OSError:
            sup = scan_suppressions("")
        _SUPPRESS_CACHE[path] = sup
    return sup.is_suppressed("JAXPR001", line) or \
        sup.is_suppressed("PREC001", line)


def _is_exact_split(eqn, eqns) -> bool:
    """Does this narrowing conversion participate in an error-free split?

    Pattern: ``w = convert[f32](x)`` is exact-split when a sibling
    equation upcasts ``w`` back to x's dtype and another subtracts that
    from ``x`` (capturing, not discarding, the rounding error).
    """
    x = eqn.invars[0]
    w = eqn.outvars[0]
    wide = getattr(getattr(x, "aval", None), "dtype", None)
    if wide is None:
        return False
    upcasts = []
    for e2 in eqns:
        if e2.primitive.name == "convert_element_type" and e2.invars and \
                e2.invars[0] is w and \
                _float_bits(e2.params.get("new_dtype")) == _float_bits(wide):
            upcasts.append(e2.outvars[0])
    if not upcasts:
        return False
    for e3 in eqns:
        if e3.primitive.name == "sub" and len(e3.invars) == 2:
            a, b = e3.invars
            for wb in upcasts:
                if (a is x and b is wb) or (a is wb and b is x):
                    return True
    return False


def narrowing_conversions(jaxpr) -> List[tuple]:
    """All float-narrowing convert_element_type eqns in a (closed) jaxpr,
    as (eqn, sibling_eqns, in_dtype, out_dtype) tuples."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    out = []
    for jx in _iter_jaxprs(jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name != "convert_element_type":
                continue
            new = eqn.params.get("new_dtype")
            aval = getattr(eqn.invars[0], "aval", None)
            old = getattr(aval, "dtype", None)
            ob, nb = _float_bits(old), _float_bits(new)
            if ob is not None and nb is not None and nb < ob:
                out.append((eqn, jx.eqns, old, new))
    return out


def audit_closed_jaxpr(jaxpr, name: str = "<traced fn>") -> List[Finding]:
    """Unsanctioned narrowing conversions in a traced program."""
    findings: List[Finding] = []
    for eqn, eqns, old, new in narrowing_conversions(jaxpr):
        if _is_exact_split(eqn, eqns):
            continue
        path, line = _source_location(eqn)
        if path and os.path.basename(path) in _SANCTIONED_FILES:
            continue
        if _line_suppressed(path, line):
            continue
        src = ""
        if path and line and os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
                if 0 < line <= len(lines):
                    src = lines[line - 1]
            except OSError:
                pass
        findings.append(Finding(
            "JAXPR001", path or name, line or 0, 0,
            f"narrowing convert_element_type {old} -> "
            f"{getattr(new, 'name', new)} in traced '{name}' is not an "
            "exact split and not suppressed — precision silently destroyed "
            "on the device path", source=src, origin="jaxpr"))
    return findings


def audit_fn(fn, *args, name: Optional[str] = None, **kwargs) -> List[Finding]:
    """Trace ``fn(*args, **kwargs)`` and audit the resulting jaxpr."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return audit_closed_jaxpr(closed, name=name or getattr(
        fn, "__name__", "<traced fn>"))


# A minimal isolated-pulsar fixture: enough to trace the full
# phase -> residual -> chi2 pipeline (spindown + astrometry + dispersion
# + barycentering + TZR) without binary models.
_AUDIT_PAR = """
PSR LINTAUDIT
RAJ 05:00:00.0 1
DECJ 20:00:00.0 1
F0 300.0 1
F1 -1.0e-15 1
PEPOCH 55000
POSEPOCH 55000
DM 15.0 1
TZRMJD 55000.1
TZRFRQ 1400
TZRSITE gbt
EPHEM DE421
"""


def audit_entry_points(ntoas: int = 8) -> List[Finding]:
    """Trace the public residual and fitter chi2 entry points over a small
    synthetic dataset and audit their jaxprs.

    This is the tier-1 gate's runtime leg: any PR that introduces an
    unsanctioned demotion anywhere in the dd-critical call tree (model
    phase, residuals, chi2 assembly) fires here even if the AST rules
    cannot see it.
    """
    import warnings

    import numpy as np

    findings: List[Finding] = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        from pint_tpu.fitter import build_chi2_fn
        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals
        from pint_tpu.toa import get_TOAs_array

        model = get_model(_AUDIT_PAR.strip().splitlines())
        t = 55000.0 + np.linspace(0.0, 10.0, ntoas)
        toas = get_TOAs_array(
            t, obs="gbt", errors_us=1.0,
            freqs_mhz=np.full(ntoas, 1400.0), ephem="DE421")
        resid = Residuals(toas, model)
        findings += audit_fn(resid._fn, resid.pdict, name="residuals")

        names = list(model.free_params)
        chi2 = build_chi2_fn(model, resid.batch, names,
                             track_mode=resid.track_mode,
                             include_offset=True)
        x0 = model.x0(resid.pdict, names)
        findings += audit_fn(chi2, x0, resid.pdict, name="fitter.chi2")
    return findings
