"""``python -m pint_tpu.lint`` entry point."""

import sys

from pint_tpu.lint.cli import main

sys.exit(main())
